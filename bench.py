"""Benchmark: timing-fit throughput on the flagship model.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Current benchmark (round 1): full WLS fit step (residuals + jacfwd
design matrix + column-normalized SVD solve) on 1e5 simulated TOAs of
the spindown+dispersion+astrometry MSP model, on the default JAX backend
(TPU under the driver).  value = TOAs/sec for one fit step; vs_baseline
= speedup of the accelerator step over the identical computation pinned
to host CPU (the reference implementation class is single-process CPU
NumPy — SURVEY.md §6 records no published throughput, so the measured
CPU denominator stands in per BASELINE.md protocol).

This will graduate to the north-star GLS red-noise benchmark (1e5 TOAs,
Woodbury covariance) when the GLS fitter lands.
"""

import json
import time

import numpy as np


def _fit_step_fn(cm, w):
    import jax
    import jax.numpy as jnp

    from pint_tpu.fitting.wls import _wls_step

    def fit_step(x):
        r = cm.time_residuals(x, subtract_mean=False)
        M = cm.design_matrix(x)
        ones = jnp.ones((cm.bundle.ntoa, 1))
        M2 = jnp.concatenate([ones, M], axis=1)
        dx, _, _ = _wls_step(r, M2, w)
        return x + dx[1:], jnp.sum(w * r * r)

    return jax.jit(fit_step)


def _time_step(step, x0, nrep=5):
    # warmup/compile
    x, c = step(x0)
    x.block_until_ready()
    ts = []
    for _ in range(nrep):
        t0 = time.perf_counter()
        x, c = step(x0)
        x.block_until_ready()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def main():
    import jax

    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    from __graft_entry__ import _build

    ntoa = 100_000
    _, toas, cm = _build(ntoa)
    w = jnp.asarray(1.0 / (toas.error_us * 1e-6) ** 2)

    # accelerator (default backend) timing
    step = _fit_step_fn(cm, w)
    t_dev = _time_step(step, cm.x0())

    # CPU baseline: identical computation pinned to host
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        cpu_bundle = jax.device_put(cm.bundle, cpu)
        cm_cpu = type(cm)(cm.model, cpu_bundle, subtract_mean=True)
        step_cpu = _fit_step_fn(cm_cpu, jax.device_put(w, cpu))
        t_cpu = _time_step(step_cpu, jax.device_put(cm.x0(), cpu), nrep=3)

    toas_per_sec = ntoa / t_dev
    print(
        json.dumps(
            {
                "metric": "WLS fit-step throughput (1e5 TOAs, "
                "spindown+DM+astrometry, jacfwd design + SVD solve)",
                "value": round(toas_per_sec, 1),
                "unit": "TOAs/sec",
                "vs_baseline": round(t_cpu / t_dev, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
