"""Benchmark: the north-star metric (BASELINE.md / BASELINE.json).

GLS fit-step throughput on 1e5 TOAs with a red-noise covariance:
residuals + jacfwd design matrix + EFAC/EQUAD white rescaling +
power-law red-noise Fourier basis (TNREDC 30 -> k=60), solved by the
Woodbury reduced-rank path — the §3.3 hot loop.  (No ECORR here: with
every TOA its own observing epoch the quantization basis is dense
(n, n/2) — hundreds of GB at 1e5 TOAs — and ECORR degenerates to EQUAD;
config-2-style epoched data exercises ECORR in the tests instead.)

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
value = TOAs/sec for one full fit step on the default backend (TPU
under the driver) using the framework's production TPU path — the
general-basis mixed-precision MXU Woodbury that GLSFitter auto-selects
on accelerators (fused='auto'; compile-time precomputed Fourier basis,
validated bounds in fitting/gls.py::_woodbury_mixed_tail /
tests/test_ffgram.py; faster AND more accurate than the opt-in Pallas
streaming path — see gls_step_woodbury_fourier's accuracy note).
vs_baseline = speedup over the all-f64 XLA computation pinned to host
CPU, which stands in for the reference implementation class
(single-process CPU; SURVEY.md §6 records no published throughput, so
the measured CPU denominator applies per BASELINE.md protocol).  The
ratio therefore measures framework-on-TPU vs reference-class-on-CPU —
hardware AND algorithm together, which is the BASELINE.md north-star
definition.

Methodology note (changed alongside the mixed-precision work, so
cross-round bench numbers spanning that change are not like-for-like):
per-step time is the steady-state cost inside ONE device program — a
256-step lax.scan chain, matching how GLSFitter._make_fit_loop runs
production fits (one dispatch per fit, and PTA batches vmap many
pulsars per dispatch).  profiling/profile_step_parts.py decomposes the
per-step cost; the one ~85-130 ms tunnel round-trip per dispatch is a
property of the axon tunnel, not of the TPU path being scored, and at
chain=256 contributes < 0.5 ms/step to the measurement (a single
isolated maxiter-4 fit would instead pay ~1/4 of it per step).
"""

import json
import time

import numpy as np


def _build(ntoa):
    import jax

    jax.config.update("jax_enable_x64", True)
    from pint_tpu.simulation import make_test_pulsar

    par = """
PSR              J1744-1134
F0               245.4261196898081  1
F1               -5.38e-16          1
PEPOCH           55000
DM               3.1380             1
RAJ              17:44:29.403209    1
DECJ             -11:34:54.68067    1
EFAC             -f L-wide 1.1
EQUAD            -f L-wide 0.5
TNREDAMP         -13.5
TNREDGAM         3.7
TNREDC           30
"""
    model, toas = make_test_pulsar(
        par, ntoa=ntoa, start_mjd=53000.0, end_mjd=57500.0, seed=0,
        iterations=1,
    )
    # synthetic 1-AU orbit so astrometry has leverage (real ephemeris
    # ingest replaces this on-sky; the FLOP count is identical)
    from pint_tpu.constants import AU, SECS_PER_DAY

    ph = 2 * np.pi * (
        toas.t.mjd_int + toas.t.sec.to_float() / SECS_PER_DAY - 53000.0
    ) / 365.25
    toas.ssb_obs_pos = np.stack(
        [AU * np.cos(ph), AU * np.sin(ph), np.zeros_like(ph)], axis=-1
    )
    cm = model.compile(toas)
    return model, toas, cm


def _fit_step_fn(cm, mode: str = "f64"):
    """One GLS Gauss-Newton step.  mode='mixed' is the production
    accelerator path GLSFitter auto-selects (f32 MXU Grams over the
    precomputed f64 basis; validated in tests/test_ffgram.py);
    mode='f64' is the all-f64 XLA path that also serves as the CPU
    reference-class computation."""
    import jax
    import jax.numpy as jnp

    from pint_tpu.fitting.base import design_with_offset, noffset
    from pint_tpu.fitting.gls import (
        gls_step_woodbury,
        gls_step_woodbury_mixed,
    )

    no = noffset(cm)
    step = (
        gls_step_woodbury_mixed if mode == "mixed" else gls_step_woodbury
    )

    def fit_step(x):
        r = cm.time_residuals(x, subtract_mean=False)
        M = design_with_offset(cm, x)
        Ndiag = jnp.square(cm.scaled_sigma(x))
        T, phi = cm.noise_basis_or_empty(x)
        dx, cov, chi2, _ = step(r, M, Ndiag, T, phi)
        return x + dx[no:], chi2

    # CompiledModel.jit: baked-constant lowering at this (1e5) size,
    # argument-fed above 2e5 TOAs (docs/parallelism.md 'Compile
    # discipline' — the threshold trade-off is measured there)
    return cm.jit(fit_step)


def _time_step(step, x0, nrep=5, chain=16, data_args=(), jit_wrap=None):
    """Median time per fit step, measured as ONE device program of
    `chain` DEPENDENT steps (lax.scan, x feeding forward — exactly how
    GLSFitter._make_fit_loop runs a production fit), so the whole
    chain costs a single dispatch: the ~85 ms axon-tunnel round-trip,
    irrelevant to TPU throughput, is amortized 1/chain.  data_args:
    extra runtime arguments prepended to each step call (the CPU
    baseline passes the bundle this way to defeat constant folding).

    Sync is a host copy of the carry (np.asarray), NOT
    block_until_ready — the axon tunnel can report ready before the
    value exists, silently shrinking measured times."""
    import jax

    def _run(x, *data):
        def body(c, _):
            x2, chi2 = step(*data, c) if data else step(c)
            return x2, chi2

        return jax.lax.scan(body, x, None, length=chain)

    # jit_wrap=cm.jit threads the bundle through the whole chained
    # program as a runtime argument (an inner cm.jit under a plain
    # outer jit would re-bake the bundle as constants)
    run_chain = (jit_wrap or jax.jit)(_run)

    x, c = run_chain(x0, *data_args)  # warmup/compile
    _ = np.asarray(x)
    # refuse to publish a timing of garbage: NaN chains time exactly
    # like correct ones on TPU.  This is the SHARED validator
    # (runtime/guard.py; promoted from run_benchmarks.py's r4 gate) —
    # it raises a diagnosed PintTpuNumericsError naming the
    # emulated-f64 hazard class instead of a bare refusal.
    from pint_tpu.runtime.guard import validate_finite

    validate_finite(
        {"state": np.asarray(x), "chi2": np.asarray(c)[-1:]},
        site="bench:chain", what="bench step chain",
    )
    ts = []
    for _ in range(nrep):
        t0 = time.perf_counter()
        x, c = run_chain(x0, *data_args)
        _ = np.asarray(x)
        ts.append((time.perf_counter() - t0) / chain)
    return float(np.median(ts))


def _guard_block(cm, step, mode, t_dev):
    """Robustness telemetry for BENCH_*.json, tracked alongside
    throughput: one laddered dispatch records which degradation rung
    (runtime/fallback.py) serves the north-star step, the guard
    counters capture retries/timeouts/fallbacks, and the overhead
    probe measures the guard's per-dispatch cost DIRECTLY (watchdog
    thread spawn+join around a host no-op — the only work the guard
    adds per dispatch; validation runs once per fit, not per step).
    overhead_pct relates that cost to the north-star chain dispatch
    (256 steps, how production fits and the headline metric run) and
    must stay <2% — measured deterministically rather than as the
    difference of two tunnel-noisy chain timings (the ~85-130 ms
    round-trip scatter would dwarf a 2% band)."""
    import jax

    from pint_tpu.exceptions import PintTpuError
    from pint_tpu.runtime import guard as rguard
    from pint_tpu.runtime.fallback import run_ladder
    from pint_tpu.runtime.guard import validate_finite

    backend = jax.default_backend()
    step_f64 = step if mode == "f64" else _fit_step_fn(cm, mode="f64")
    rungs = [(f"{backend}-{mode}", lambda s: step(cm.x0()))]
    if mode != "f64":
        rungs.append((f"{backend}-f64", lambda s: step_f64(cm.x0())))
    with rguard.configured(compile_timeout=3600.0,
                           dispatch_timeout=900.0):
        out, report = run_ladder(
            rungs, site="bench:northstar",
            validate=lambda o, s: validate_finite(
                {"x": o[0], "chi2": o[1]}, site=s,
                what="bench warm step",
            ),
        )
        _ = np.asarray(out[0])
        ts = []
        for _ in range(20):
            t0 = time.perf_counter()
            rguard.guarded_call(lambda: None, (), site="bench:probe",
                                timeout=60.0)
            ts.append(time.perf_counter() - t0)
    per_dispatch = float(np.median(ts))
    overhead_pct = per_dispatch / (256 * t_dev) * 100.0
    if overhead_pct >= 2.0:
        raise PintTpuError(
            f"guard overhead {overhead_pct:.2f}% of the north-star "
            "chain dispatch exceeds the 2% robustness budget "
            f"({per_dispatch * 1e3:.3f} ms/dispatch vs "
            f"{256 * t_dev * 1e3:.1f} ms/chain)"
        )
    snap = rguard.STATS.snapshot()
    return {
        "rung": report.rung,
        "fallbacks": snap["fallbacks"],
        "retries": snap["retries"],
        "timeouts": snap["timeouts"],
        "numerics_errors": snap["numerics_errors"],
        "watchdog_margin_s": (
            None if snap["watchdog_margin_s"] is None
            else round(snap["watchdog_margin_s"], 3)
        ),
        "guard_cost_per_dispatch_ms": round(per_dispatch * 1e3, 4),
        "overhead_pct": round(overhead_pct, 4),
    }


def _obs_block(serve_rps=None):
    """Flight-recorder telemetry for BENCH_*.json (PR 2), tracked next
    to the guard block: a small traced GLS fit+refit probe (1) gates
    the r5 "refits are one dispatch" invariant — commit() must
    invalidate NO compiled code, so the XLA trace counter
    (obs.metrics 'compile.traces', counted exactly at the cm.jit
    chokepoint) must not move across the refit — and (2) folds the
    metrics snapshot (recompiles, bytes to device, max span) into the
    single JSON line.  The probe runs with tracing ENABLED in a scoped
    block; the timed sections above ran with it off, so the <2%
    guard-overhead gate still measures the production (tracing-off)
    path.

    Attribution overhead gate (ISSUE 17): stage-clock attribution is
    ALWAYS ON — every served request pays the monotonic stamps, the
    per-stage window-histogram observes, and one exemplar offer.  The
    probe micro-benches that full per-request cost and amortizes it
    against the serve block's measured steady rps (``serve_rps``);
    the product must stay under 2% of wall time."""
    from pint_tpu.exceptions import PintTpuError
    from pint_tpu.fitting.gls import GLSFitter
    from pint_tpu.obs import export as obs_export
    from pint_tpu.obs import metrics as obs_metrics
    from pint_tpu.obs import trace as obs_trace
    from pint_tpu.simulation import make_test_pulsar

    par = (
        "PSR J0000+0000\nF0 100.0 1\nF1 -1e-15 1\nPEPOCH 55000\n"
        "DM 10.0 1\n"
    )
    with obs_trace.tracing(clear=True):
        model, toas = make_test_pulsar(
            par, ntoa=1000, start_mjd=55000.0, end_mjd=56000.0,
            seed=3, iterations=1,
        )
        fitter = GLSFitter(toas, model)
        fitter.fit_toas(maxiter=3)
        traces0 = obs_metrics.counter("compile.traces").value
        fitter.fit_toas(maxiter=3)  # refit after commit
        refit_retraces = (
            obs_metrics.counter("compile.traces").value - traces0
        )
    if refit_retraces:
        raise PintTpuError(
            f"{refit_retraces} XLA retrace(s) across the refit loop — "
            "the r5 'refits are one dispatch' invariant is broken "
            "(commit() must not invalidate compiled code; see "
            "cm.jit's runtime-argument references)"
        )
    out = obs_export.summary()
    out["refit_retraces"] = refit_retraces
    # tracing-ON span cost, measured (the off path is covered by the
    # guard overhead gate above, which runs with the recorder off):
    # one open+close of an enabled span, amortized over 2000 reps —
    # AFTER summary() so the probe spans don't pollute the span stats
    with obs_trace.tracing():
        t0 = time.perf_counter()
        for _ in range(2000):
            with obs_trace.TRACER.span("probe", "host"):
                pass
        out["span_cost_on_us"] = round(
            (time.perf_counter() - t0) / 2000 * 1e6, 3
        )
    # per-request attribution cost: the FULL stage-clock path one
    # served request pays — 9 stamps, the total + per-stage window
    # -histogram observes, one exemplar offer — on scratch instances
    # (never the live serve.latency.* registrations)
    wh_total = obs_metrics.WindowHistogram("bench.attr.total")
    wh_stage = {
        s: obs_metrics.WindowHistogram(f"bench.attr.{s}")
        for s in obs_metrics.STAGES[1:]
    }
    ex = obs_metrics.ExemplarReservoir("bench.attr.ex")
    nrep = 2000
    t0 = time.perf_counter()
    for i in range(nrep):
        stages = {}
        for s in obs_metrics.STAGES:
            stages[s] = time.monotonic()
        t = stages["finish"]
        wh_total.observe((t - stages["submit"]) * 1e3, now=t)
        prev = stages["submit"]
        for s in obs_metrics.STAGES[1:]:
            wh_stage[s].observe((stages[s] - prev) * 1e3, now=t)
            prev = stages[s]
        ex.offer((t - stages["submit"]) * 1e3, f"req-{i}", stages,
                 now=t)
    attr_cost_us = (time.perf_counter() - t0) / nrep * 1e6
    out["attr_cost_per_request_us"] = round(attr_cost_us, 3)
    if serve_rps:
        overhead_pct = attr_cost_us * 1e-6 * serve_rps * 100.0
        out["attr_overhead_pct"] = round(overhead_pct, 4)
        if overhead_pct >= 2.0:
            raise PintTpuError(
                f"stage-clock attribution costs {overhead_pct:.2f}% "
                f"of wall at {serve_rps:.0f} rps (>= 2% budget) — "
                "the always-on stamps/window-histogram path must stay "
                "cheap (docs/observability.md 'request flows')"
            )
    return out


def _fit_traj_block(t_dev=None):
    """Fused-trajectory telemetry for BENCH_*.json (ISSUE 9): a small
    downhill probe gates the tentpole invariant — ONE complete steady
    -state downhill fit (GN proposal + lambda ladder + noise-floor
    measurement + stop/freeze control, all maxiter legs) costs exactly
    ONE guarded dispatch (fitting/downhill.py::_fused_loop).  Reported
    next to it: the host-loop rung on the SAME fitter
    (PINT_TPU_DOWNHILL_FUSED=0 — per-leg dispatches plus per-call
    re-jit, what every downhill fit paid before the fusion), so the
    driver tracks the dispatch amortization per round
    (profiling/dispatch_floor.py has the full ladder)."""
    import os

    from pint_tpu.exceptions import PintTpuError
    from pint_tpu.fitting.downhill import DownhillWLSFitter
    from pint_tpu.obs import metrics as obs_metrics
    from pint_tpu.simulation import make_test_pulsar

    par = (
        "PSR TRAJ\nF0 61.485 1\nF1 -1.2e-15 1\nPEPOCH 53750\n"
        "DM 224.1 1\n"
    )
    m, toas = make_test_pulsar(
        par, ntoa=62, start_mjd=53000.0, end_mjd=57000.0, iterations=1
    )
    f = DownhillWLSFitter(toas, m)
    g = obs_metrics.counter("dispatch.guarded")
    f.fit_toas()  # warm: compile + fault-ladder probes
    nrep = 3
    g0 = g.value
    t0 = time.perf_counter()
    for _ in range(nrep):
        f.fit_toas()
    fused_wall = (time.perf_counter() - t0) / nrep
    per_fit = (g.value - g0) / nrep
    if not f.converged:
        raise PintTpuError("fit_traj probe did not converge")
    if per_fit != 1.0:
        raise PintTpuError(
            f"{per_fit:g} guarded dispatch(es) per steady-state "
            "downhill fit — the fused-trajectory invariant is exactly "
            "ONE (fitting/downhill.py::_fused_loop; "
            "docs/performance.md)"
        )
    saved = os.environ.get("PINT_TPU_DOWNHILL_FUSED")
    os.environ["PINT_TPU_DOWNHILL_FUSED"] = "0"
    try:
        f.fit_toas()  # the host rung re-jits per call; still "warm"
        h0 = g.value
        t0 = time.perf_counter()
        f.fit_toas()
        host_wall = time.perf_counter() - t0
        host_dispatches = g.value - h0
    finally:
        if saved is None:
            os.environ.pop("PINT_TPU_DOWNHILL_FUSED", None)
        else:
            os.environ["PINT_TPU_DOWNHILL_FUSED"] = saved
    return {
        "dispatches_per_fit": per_fit,
        # the north-star per-step device cost next to the trajectory
        # figures (ISSUE 12): fused_wall_ms / dev_step_ms ~ the
        # host-side overhead share the fusion + donation path leaves
        "dev_step_ms": (
            None if t_dev is None else round(t_dev * 1e3, 4)
        ),
        "fused_wall_ms": round(fused_wall * 1e3, 2),
        "host_wall_ms": round(host_wall * 1e3, 2),
        "host_dispatches_per_fit": host_dispatches,
        "dispatch_amortization_x": round(
            host_dispatches / per_fit, 1
        ),
        "wall_speedup_x": round(
            host_wall / max(fused_wall, 1e-9), 1
        ),
    }


#: bf16 MXU peak of the bench TPU generation (shared accounting with
#: profiling/run_benchmarks.py and profiling/mfu.py — model MFU is
#: model-FLOPs / wall / this peak, a LOWER bound on true utilization)
PEAK_BF16_FLOPS = 197e12


def _mfu_time_op(fn, arg, nrep=3, chain=16):
    """Chained dependent timing (>=16 rule: the ~85 ms tunnel
    round-trip amortizes 1/chain; scalar feedback keeps steps
    dependent, scalar output keeps the host copy off the clock)."""
    import jax

    @jax.jit
    def run(A):
        def body(c, _):
            L = fn(c)
            return (c + 1e-30 * L[0, 0]), L[0, 0]

        _, ls = jax.lax.scan(body, A, None, length=chain)
        return ls[-1]

    _ = float(np.asarray(run(arg)))
    ts = []
    for _ in range(nrep):
        t0 = time.perf_counter()
        _ = float(np.asarray(run(arg)))
        ts.append((time.perf_counter() - t0) / chain)
    return float(np.median(ts))


def _mfu_block(cm):
    """ISSUE 13 `mfu` block: arithmetic utilization of the two solve
    paths every serve fit funnels through, plus the solve-policy
    parity gate.

    dense rung — blocked_cholesky(precision='highest', the 6-pass
    accuracy-bearing factorization) vs fast_cholesky32 (bf16x3 'high'
    trailing GEMMs, the IR preconditioner) on an equilibrated operand;
    GF/s and model MFU (n^3/3 model FLOPs over the bf16 peak).  GATE
    on accelerators: the bf16x3 recipe must hold >= 1.3x over the
    6-pass rung — the multipass win ISSUE 13 banks.

    woodbury rung — per-solve latency of the k x k Sigma IR solve
    (ops/ffgram.py::chol_solve_ir) on the bench model's real basis.

    parity gate (ALL backends) — one mixed GLS step with the policy
    FORCED vs OFF must agree within the _woodbury_mixed_tail contract
    (dx 2e-3 of the largest component, chi2 1e-3 relative, normalized
    covariance 5e-3).  A violation raises PintTpuError: the policy
    may never trade correctness for MFU silently."""
    import os

    import jax
    import jax.numpy as jnp

    from pint_tpu.exceptions import PintTpuError
    from pint_tpu.parallel.dense import blocked_cholesky, fast_cholesky32

    accel = jax.default_backend() != "cpu"
    n = 8192 if accel else 1024

    rng = np.random.default_rng(0)
    W = rng.normal(size=(n, 64)).astype(np.float32)
    C = W @ W.T + n * np.eye(n, dtype=np.float32)
    d = np.sqrt(np.diag(C))
    Ceq = jnp.asarray((C / np.outer(d, d)).astype(np.float32))
    flops = n**3 / 3

    t_highest = _mfu_time_op(
        lambda A: blocked_cholesky(A, block=512, precision="highest",
                                   diag_bump=3e-5),
        Ceq,
    )
    t_fast = _mfu_time_op(fast_cholesky32, Ceq)
    speedup = t_highest / t_fast
    if accel and speedup < 1.3:
        raise PintTpuError(
            f"mfu gate: bf16x3 fast_cholesky32 at n={n} is only "
            f"{speedup:.2f}x over the 6-pass HIGHEST factorization "
            "(gate >= 1.3x) — the multipass trailing GEMM lost its "
            "advantage (driver regression gate, ISSUE 13)"
        )

    # woodbury rung: the real bench-model Sigma solve
    from pint_tpu.fitting.base import design_with_offset
    from pint_tpu.ops.ffgram import chol_solve_ir, gram32_joint

    x = cm.x0()
    r = cm.time_residuals(x, subtract_mean=False)
    M = design_with_offset(cm, x)
    Ninv = 1.0 / jnp.square(cm.scaled_sigma(x))
    T, phi = cm.noise_basis_or_empty(x)
    from pint_tpu.fitting.gls import _column_norms

    norm = _column_norms(M)
    X = jnp.concatenate([M / norm[None, :], r[:, None]], axis=1)
    sig_tt, twx, _ = gram32_joint(T.astype(jnp.float32), X, Ninv)
    Sigma = jnp.diag(1.0 / phi) + sig_tt
    k = int(Sigma.shape[0])
    t_wood = _mfu_time_op(
        lambda S: chol_solve_ir(S, twx, check_rtol=1e-5), Sigma
    )

    # parity gate: the policy forced vs off, fresh traces each (the
    # env is read at trace time — ops/solve_policy.py)
    from pint_tpu.fitting.gls import gls_step_woodbury_mixed

    def _step_under(setting):
        saved = os.environ.get("PINT_TPU_SOLVE_IR")
        os.environ["PINT_TPU_SOLVE_IR"] = setting

        @jax.jit
        def stepfn(xx):
            rr = cm.time_residuals(xx, subtract_mean=False)
            MM = design_with_offset(cm, xx)
            Nd = jnp.square(cm.scaled_sigma(xx))
            TT, pp = cm.noise_basis_or_empty(xx)
            return gls_step_woodbury_mixed(
                rr, MM, Nd, TT, pp, normalized_cov=True
            )

        try:
            dx, (covn, nm), chi2, _ = stepfn(x)
            return (np.asarray(dx), np.asarray(covn),
                    float(chi2))
        finally:
            if saved is None:
                os.environ.pop("PINT_TPU_SOLVE_IR", None)
            else:
                os.environ["PINT_TPU_SOLVE_IR"] = saved

    dx_off, cov_off, chi_off = _step_under("0")
    dx_on, cov_on, chi_on = _step_under("force")
    dx_rel = float(np.max(np.abs(dx_on - dx_off))
                   / np.max(np.abs(dx_off)))
    chi_rel = abs(chi_on - chi_off) / abs(chi_off)
    cov_rel = float(np.max(np.abs(cov_on - cov_off))
                    / np.max(np.abs(cov_off)))
    # inverted comparisons: a NaN (poisoned or diverged IR step) must
    # FAIL the gate, and `nan > tol` is False
    if not (dx_rel <= 2e-3 and chi_rel <= 1e-3 and cov_rel <= 5e-3):
        raise PintTpuError(
            "mfu gate: IR'd mixed step diverged from the exact-policy "
            f"step (dx_rel={dx_rel:.2e} gate 2e-3, chi2_rel="
            f"{chi_rel:.2e} gate 1e-3, cov_rel={cov_rel:.2e} gate "
            "5e-3; nan = poisoned solve) — the solve policy broke the "
            "_woodbury_mixed_tail contract (ISSUE 13)"
        )

    return {
        "dense_n": n,
        "dense_highest_ms": round(t_highest * 1e3, 2),
        "dense_bf16x3_ms": round(t_fast * 1e3, 2),
        "dense_highest_gflops": round(flops / t_highest / 1e9, 1),
        "dense_bf16x3_gflops": round(flops / t_fast / 1e9, 1),
        "dense_bf16x3_mfu_vs_bf16_peak": round(
            flops / t_fast / PEAK_BF16_FLOPS, 4
        ),
        "dense_speedup_x": round(speedup, 2),
        "dense_speedup_gate": ">=1.3x on accelerators",
        "woodbury_k": k,
        "woodbury_solve_ms": round(t_wood * 1e3, 3),
        "parity": {
            "dx_rel": round(dx_rel, 9),
            "chi2_rel": round(chi_rel, 9),
            "cov_rel": round(cov_rel, 9),
            "gates": "dx<=2e-3 chi2<=1e-3 cov<=5e-3 (all backends)",
        },
    }


def _fused_interior_block(cm, mode, t_dev):
    """ISSUE 18 `fused_interior` block: the VMEM-resident joint-Gram
    pipeline (ops/pallas_fit.py, routed from fitting/gls.py behind
    ops/solve_policy.py) scored against the PINT_TPU_FUSED_INTERIOR=0
    hatch on the SAME north-star step.

    perf gate (accelerators) — the fused interior is the production
    default there, so the main `dev_step_ms` already measures it; this
    block re-times the identical chained program under the hatch
    (fresh trace: the env is read at TRACE time) and GATES the ratio
    >= 1.3x — the HBM-round-trip toll the fusion banks.  On CPU the
    fused default is OFF and `force` runs the Pallas interpreter (a
    correctness probe, not a perf number — profiling/dispatch_floor.py
    carries the forced ladder), so the timing legs are skipped.

    parity gate (ALL backends) — one mixed GLS step FORCED vs hatched
    must agree within the _woodbury_mixed_tail contract (dx 2e-3 of
    the largest component, chi2 1e-3 relative, normalized covariance
    5e-3), with inverted comparisons so a NaN fails the gate.

    retrace gate — extra warmed executions of the forced step leave
    its pjit cache at ONE entry (zero steady retraces; the
    serve-bucket-ladder version is pinned in
    tests/test_fused_interior.py)."""
    import os

    import jax
    import jax.numpy as jnp

    from pint_tpu.exceptions import PintTpuError
    from pint_tpu.fitting.base import design_with_offset
    from pint_tpu.fitting.gls import gls_step_woodbury_mixed
    from pint_tpu.ops.pallas_fit import fused_block_table

    accel = jax.default_backend() != "cpu"
    x = cm.x0()
    T, _phi = cm.noise_basis_or_empty(x)
    n, k = int(T.shape[0]), int(T.shape[1])
    p1 = int(design_with_offset(cm, x).shape[1]) + 1  # + residual col
    tab = fused_block_table(n, k, p1)

    def _env_under(setting):
        saved = os.environ.get("PINT_TPU_FUSED_INTERIOR")
        if setting is None:
            os.environ.pop("PINT_TPU_FUSED_INTERIOR", None)
        else:
            os.environ["PINT_TPU_FUSED_INTERIOR"] = setting
        return saved

    def _env_restore(saved):
        if saved is None:
            os.environ.pop("PINT_TPU_FUSED_INTERIOR", None)
        else:
            os.environ["PINT_TPU_FUSED_INTERIOR"] = saved

    # timing legs (accelerators): fused is the default — t_dev IS the
    # fused number.  Hatch leg re-runs the exact _time_step recipe
    # (chain=256, cm.jit threads the bundle as a runtime argument).
    t_hatch = None
    speedup = None
    if accel and tab is not None:
        saved = _env_under("0")
        try:
            step_hatch = _fit_step_fn(cm, mode=mode)
            t_hatch = _time_step(step_hatch, x, chain=256,
                                 jit_wrap=cm.jit)
        finally:
            _env_restore(saved)
        speedup = t_hatch / t_dev
        if speedup < 1.3:
            raise PintTpuError(
                f"fused_interior gate: the fused interior is only "
                f"{speedup:.2f}x over the PINT_TPU_FUSED_INTERIOR=0 "
                "hatch on the north-star step (gate >= 1.3x on "
                "accelerators) — the VMEM-resident Gram pipeline lost "
                "its HBM-traffic advantage (ISSUE 18)"
            )

    # parity gate (all backends): forced vs hatched, fresh jit per
    # setting — pjit's global cache keys on function identity, so one
    # reused wrapper would silently replay the first setting's trace
    def _step_under(setting, extra_calls=0):
        saved = _env_under(setting)

        @jax.jit
        def stepfn(xx):
            rr = cm.time_residuals(xx, subtract_mean=False)
            MM = design_with_offset(cm, xx)
            Nd = jnp.square(cm.scaled_sigma(xx))
            TT, pp = cm.noise_basis_or_empty(xx)
            return gls_step_woodbury_mixed(
                rr, MM, Nd, TT, pp, normalized_cov=True
            )

        try:
            dx, (covn, nm), chi2, _ = stepfn(x)
            out = (np.asarray(dx), np.asarray(covn), float(chi2))
            for _ in range(extra_calls):
                stepfn(x)
            return out, int(stepfn._cache_size())
        finally:
            _env_restore(saved)

    (dx_off, cov_off, chi_off), _ = _step_under("0")
    (dx_on, cov_on, chi_on), cache_n = _step_under("force",
                                                   extra_calls=3)
    dx_rel = float(np.max(np.abs(dx_on - dx_off))
                   / np.max(np.abs(dx_off)))
    chi_rel = abs(chi_on - chi_off) / abs(chi_off)
    cov_rel = float(np.max(np.abs(cov_on - cov_off))
                    / np.max(np.abs(cov_off)))
    # inverted comparisons: a NaN (poisoned IR solve, or a fused Gram
    # that overflowed) must FAIL the gate, and `nan > tol` is False
    if not (dx_rel <= 2e-3 and chi_rel <= 1e-3 and cov_rel <= 5e-3):
        raise PintTpuError(
            "fused_interior gate: the fused-interior mixed step "
            f"diverged from the hatched step (dx_rel={dx_rel:.2e} "
            f"gate 2e-3, chi2_rel={chi_rel:.2e} gate 1e-3, cov_rel="
            f"{cov_rel:.2e} gate 5e-3; nan = poisoned solve) — "
            "ops/pallas_fit.py broke the _woodbury_mixed_tail "
            "contract (ISSUE 18)"
        )
    if cache_n != 1:
        raise PintTpuError(
            f"fused_interior gate: {cache_n} executables for one "
            "warmed fused step (gate: exactly 1) — the fused interior "
            "retraced at steady state (ISSUE 18)"
        )

    return {
        "active_default": bool(accel and tab is not None),
        "block_table": (
            None if tab is None
            else {"block_n": tab[0], "k_pad": tab[1], "p1_pad": tab[2]}
        ),
        "n": n, "k": k, "p1": p1,
        "fused_step_ms": (
            round(t_dev * 1e3, 4) if speedup is not None else None
        ),
        "hatch_step_ms": (
            round(t_hatch * 1e3, 4) if t_hatch is not None else None
        ),
        "speedup_x": (
            round(speedup, 3) if speedup is not None else None
        ),
        "speedup_gate": ">=1.3x on accelerators (CPU: interpret-mode"
                        " correctness probe only)",
        "steady_executables": cache_n,
        "parity": {
            "dx_rel": round(dx_rel, 9),
            "chi2_rel": round(chi_rel, 9),
            "cov_rel": round(cov_rel, 9),
            "gates": "dx<=2e-3 chi2<=1e-3 cov<=5e-3 (all backends)",
        },
    }


def _serve_block():
    """Serving telemetry for BENCH_*.json (ISSUE 4 — pint_tpu/serve):
    a mixed-size fleet of same-composition pulsars served as fits,
    scored two ways.  SERIAL is one-request-at-a-time dispatch
    (max_batch=1, one in flight) — what every pre-serve caller did by
    hand; ASYNC is the production engine (dynamic batching + >=4
    batches in flight hiding the ~85 ms tunnel round-trip).  Gates:
    steady-state traffic must cause ZERO XLA retraces (mixed TOA
    counts all land in one power-of-two bucket), and on accelerators
    the async engine must sustain >= 3x the serial throughput — both
    are ISSUE 4 acceptance criteria, enforced here so the driver
    tracks them per round like the guard/obs invariants.

    ISSUE 5 adds the FABRIC figures: the per-replica occupancy
    breakdown of the async engine, and a replica-scaling probe (the
    same offered load through a 1-replica and a 4-replica fabric,
    inflight=1 so the router's spill policy replicates the hot
    session group across the pool).  Gates: zero steady-state
    RECOMPILES per replica in both rungs (each replica's session
    compiles at most once per (composition, bucket, capacity) — a
    spill's first compile is a fresh wrapper, not a retrace), and on
    accelerators the 4-replica aggregate throughput must reach >= 2x
    the single-replica rung.

    ISSUE 6 adds the POPULATION figures (_population_probe): 1000
    distinct pars of one composition served through composition-keyed
    sessions.  Gates (all backends): zero XLA compiles while serving
    the full distinct population after the capacity-ladder warm
    (exactly one compile per (bucket, capacity), never per par), zero
    steady-state retraces, and distinct-par steady throughput >= 0.8x
    the single-par figure.

    ISSUE 9 adds the COALESCING figure: in-replica batch coalescing
    (serve/fabric/replica.py::Replica._coalesce) runs at its default
    (ON) throughout this block, so the zero-steady-retrace gates above
    ALSO certify that merged dispatches only ever land on warmed
    kernel capacities; coalesced_batches reports how many queued
    batches were absorbed into stacked dispatches.

    ISSUE 10 adds the GANG figures (_gang_probe): a mixed pool (one
    gang over half the devices + singles) serving an above-threshold
    1024-bucket fit load.  Gates (all backends, >= 2 devices): the
    big work is served by gang-tagged executors through normal
    submit(), and the steady window adds ZERO recompiles on every
    executor (the per-gang mode-keyed kernel caches); on accelerators
    with a real gang the sharded big-fit throughput must reach
    >= 1.5x the single-replica rung.

    ISSUE 11 adds the RESTART and SLO figures (_restart_probe /
    _slo_probe): kill-and-restart through the warm ledger must
    recover >= 0.9x the pre-kill steady rps (accelerators) with zero
    fresh XLA compiles (persistent-cache hits only) and zero steady
    retraces; near-deadline requests must close their batch early
    (serve.slo.early_close), and the per-composition admission quota
    must shed a hot composition's surplus typed while keeping an
    interactive composition's p99 bounded.

    ISSUE 12 adds the XKEY figure (_xkey_probe): co-resident
    DISTINCT-key small batches on one replica served as one fused
    device call (serve/fabric/replica.py::_fuse) — >= 2x fewer
    guarded dispatches than the PINT_TPU_SERVE_XKEY_FUSE=0 hatch,
    zero steady retraces either mode, bitwise-identical responses.
    Buffer donation (PINT_TPU_DONATE) and transfer overlap
    (PINT_TPU_SERVE_OVERLAP) run at their defaults (ON) throughout
    this block, so every gate above also certifies the donation
    snapshot/fence contract and the double-buffered dispatcher."""
    import jax

    from pint_tpu.exceptions import PintTpuError
    from pint_tpu.obs import metrics as obs_metrics
    from pint_tpu.serve import FitRequest, TimingEngine
    from pint_tpu.simulation import make_test_pulsar

    npsr, rounds = 8, 4
    pulsars = []
    for i in range(npsr):
        par = (
            f"PSR S{i}\nF0 {120 + 11 * i}.25 1\nF1 -2e-15 1\n"
            f"PEPOCH 55000\nDM {4 + 1.7 * i:.2f} 1\n"
        )
        m, toas = make_test_pulsar(
            par, ntoa=160 + 12 * i,  # mixed sizes, one 256 bucket
            start_mjd=54000.0, end_mjd=56000.0, seed=i, iterations=1,
        )
        pulsars.append((m.as_parfile(), toas))
    total_toas = sum(len(t) for _, t in pulsars)

    def requests():
        return [
            FitRequest(par=p, toas=t, maxiter=2) for p, t in pulsars
        ]

    # serial baseline: submit -> wait -> next, one dispatch each
    with TimingEngine(max_batch=1, max_wait_ms=0.0, inflight=1) as eng:
        eng.submit(requests()[0]).result(timeout=3600)  # warm cap-1
        t0 = time.perf_counter()
        for r in requests():
            eng.submit(r).result(timeout=3600)
        serial_rps = npsr / (time.perf_counter() - t0)

    eng = TimingEngine(max_batch=npsr, max_wait_ms=5.0, inflight=4)
    try:
        for f in eng.submit_many(requests()):  # warm the cap-8 kernel
            f.result(timeout=3600)
        eng.reset_stats()  # scope stats to the steady-state window
        traces0 = obs_metrics.counter("compile.traces").value
        t0 = time.perf_counter()
        futs = []
        for _ in range(rounds):
            futs += eng.submit_many(requests())
        for f in futs:
            f.result(timeout=3600)
        wall = time.perf_counter() - t0
        retraces = (
            obs_metrics.counter("compile.traces").value - traces0
        )
        st = eng.stats()
    finally:
        eng.close()
    rps = npsr * rounds / wall
    speedup = rps / serial_rps

    # replica-scaling probe (ISSUE 5): same offered load, 1 vs 4
    # replicas; inflight=1 saturates the routed replica so the hot
    # group spills across the pool during the warm bursts
    def _replica_rung(nrep):
        reng = TimingEngine(
            max_batch=4, max_wait_ms=2.0, inflight=1, replicas=nrep,
            affinity=nrep, max_queue=256,
        )
        try:
            for _ in range(2):  # warm + spill + per-replica compiles
                for f in reng.submit_many(requests() * rounds):
                    f.result(timeout=3600)
            reng.reset_stats()
            rec0 = obs_metrics.counter("compile.recompiles").value
            t0 = time.perf_counter()
            futs = []
            for _ in range(rounds):
                futs += reng.submit_many(requests())
            for f in futs:
                f.result(timeout=3600)
            rung_wall = time.perf_counter() - t0
            recompiles = (
                obs_metrics.counter("compile.recompiles").value - rec0
            )
            fab = reng.stats()["fabric"]
            occ = {
                tag: rs["batches"]
                for tag, rs in fab["per_replica"].items()
                if rs["batches"]
            }
            return npsr * rounds / rung_wall, recompiles, occ, fab
        finally:
            reng.close()

    # population probe (ISSUE 6): 1000 distinct pars of ONE
    # composition — after the batch-capacity ladder is warm, serving
    # the whole population must add ZERO XLA compiles (sessions are
    # composition-keyed; per-par state rides the stacked pulsar axis
    # as runtime arguments), sustain zero steady-state retraces, and
    # hold >= 0.8x the single-par steady throughput.  Cold-record
    # admission (host par parses) is reported unGated as
    # cold_admit_rps — it is pure host work by construction (the
    # compile gate is what pins that down).
    def _population_probe():
        from pint_tpu.simulation import make_population

        npop = 1000
        ppars, ptoas = make_population(
            "PSR POPB\nF0 169.5 1\nF1 -1.8e-15 1\nPEPOCH 55000\n"
            "DM 6.17 1\n",
            npop, ntoa=48, seed=23, start_mjd=54000.0,
            end_mjd=56000.0, iterations=1,
        )

        def preqs(distinct):
            return [
                FitRequest(
                    par=ppars[j] if distinct else ppars[0],
                    toas=ptoas, maxiter=2,
                )
                for j in range(npop)
            ]

        # replicas=1: a saturated-burst SPILL compiles legitimately on
        # the spilled-to replica (PR 5 semantics, covered by the
        # replica probe below) and would read as a spurious per-par
        # compile here — one replica isolates the composition-keying
        # claim
        peng = TimingEngine(
            max_batch=16, max_wait_ms=5.0, inflight=4,
            max_queue=2 * npop, replicas=1,
        )
        try:
            wave = 1
            while wave <= 16:  # the one compile per (bucket, capacity)
                for f in peng.submit_many([
                    FitRequest(par=ppars[0], toas=ptoas, maxiter=2)
                    for _ in range(wave)
                ]):
                    f.result(timeout=3600)
                wave <<= 1
            def timed(distinct):
                t0 = time.perf_counter()
                for f in peng.submit_many(preqs(distinct)):
                    f.result(timeout=3600)
                return npop / (time.perf_counter() - t0)

            # single-par steady figure (best of 2: each phase is a
            # ~2.5 s window and the ratio gate below must not trip on
            # transient host noise)
            single_rps = max(timed(False), timed(False))
            # cold-record admission of the whole distinct population:
            # host parses only — the compile counter must not move
            traces0 = obs_metrics.counter("compile.traces").value
            admit_rps = timed(True)
            pop_compiles = (
                obs_metrics.counter("compile.traces").value - traces0
            )
            # steady distinct-par figure (records warm, every request
            # a DIFFERENT par stacked on the pulsar axis)
            peng.reset_stats()
            rec0 = obs_metrics.counter("compile.recompiles").value
            pop_rps = max(timed(True), timed(True))
            pop_retraces = (
                obs_metrics.counter("compile.recompiles").value - rec0
            )
            pst = peng.stats()
        finally:
            peng.close()
        if pop_compiles:
            raise PintTpuError(
                f"{pop_compiles} XLA compile(s) while serving {npop} "
                "distinct pars of one warmed composition — sessions "
                "must be composition-keyed (exactly one compile per "
                "(bucket, capacity), never per par; docs/serving.md)"
            )
        if pop_retraces:
            raise PintTpuError(
                f"{pop_retraces} steady-state XLA recompile(s) across "
                f"{npop} distinct-par serving — the population "
                "zero-retrace invariant is broken (docs/serving.md)"
            )
        ratio = pop_rps / single_rps
        if ratio < 0.8:
            raise PintTpuError(
                f"{npop} distinct-par serving sustained only "
                f"{ratio:.2f}x the single-par steady throughput "
                "(>= 0.8x required: per-par state must ride the "
                "stacked dispatch as runtime arguments, not rebuild "
                "host/compile state per request; docs/serving.md)"
            )
        return {
            "distinct_pars": npop,
            "requests_per_s": round(pop_rps, 2),
            "single_par_requests_per_s": round(single_rps, 2),
            "throughput_ratio": round(ratio, 3),
            "cold_admit_rps": round(admit_rps, 2),
            "compiles_after_warm": pop_compiles,
            "steady_retraces": pop_retraces,
            "stack_distinct_mean": (
                pst["population"]["stack_distinct_mean"]
            ),
            "pars_live": pst["population"]["pars"],
            "compositions": pst["population"]["compositions"],
        }

    # gang probe (ISSUE 10): big-bucket work through a mixed pool —
    # the router must keep it on the gang, the gang must shard it
    # with zero steady recompiles, and on accelerators the sharded
    # compute must beat one chip
    def _gang_probe():
        from pint_tpu.parallel.mesh import serving_devices

        ndev = len(serving_devices())
        if ndev < 2:
            return {
                "skipped": f"needs >= 2 devices, have {ndev}",
            }
        gsize = max(2, ndev // 2)
        bm, btoas = make_test_pulsar(
            "PSR BIGG\nF0 171.5 1\nF1 -1.5e-15 1\nPEPOCH 55000\n"
            "DM 7.7 1\n",
            ntoa=600,  # 1024 bucket: above the probe's gang threshold
            start_mjd=54000.0, end_mjd=56000.0, seed=41,
            iterations=1,
        )
        bpar = bm.as_parfile()
        nreq = 6

        def big_reqs():
            return [
                FitRequest(par=bpar, toas=btoas, maxiter=2)
                for _ in range(nreq)
            ]

        def rung(**kw):
            geng = TimingEngine(
                max_batch=2, max_wait_ms=2.0, inflight=2,
                max_queue=256, **kw,
            )
            try:
                for _ in range(2):  # warm the (bucket, cap) kernels
                    for f in geng.submit_many(big_reqs()):
                        f.result(timeout=3600)
                geng.reset_stats()
                rec0 = obs_metrics.counter("compile.recompiles").value
                t0 = time.perf_counter()
                futs = []
                for _ in range(rounds):
                    futs += geng.submit_many(big_reqs())
                tags = {f.result(timeout=3600).replica for f in futs}
                rung_wall = time.perf_counter() - t0
                rec = (
                    obs_metrics.counter("compile.recompiles").value
                    - rec0
                )
                return (
                    nreq * rounds / rung_wall, rec, tags,
                    geng.stats()["fabric"],
                )
            finally:
                geng.close()

        s_rps, s_rec, _s_tags, _ = rung(replicas=1)
        g_rps, g_rec, g_tags, g_fab = rung(
            replicas=0, gangs=1, gang_size=gsize,
            gang_threshold=512, affinity=1,
        )
        if s_rec or g_rec:
            raise PintTpuError(
                f"{s_rec}+{g_rec} steady-state XLA recompile(s) "
                "across the gang-probe rungs — an executor retraced "
                "a warmed kernel (per-gang caches key (group, "
                "capacity, gang shape, placement mode); "
                "docs/serving.md)"
            )
        if g_fab["gangs"] >= 1 and not all(
            t.startswith("g") for t in g_tags
        ):
            raise PintTpuError(
                f"above-threshold 1024-bucket fits served by {sorted(g_tags)} "
                "— the router must place big session groups on gang "
                "executors (docs/serving.md)"
            )
        gang_scaling = g_rps / s_rps
        if (jax.default_backend() != "cpu"
                and g_fab["gangs"] >= 1 and gang_scaling < 1.5):
            raise PintTpuError(
                f"gang-of-{gsize} sharded big-fit throughput reached "
                f"only {gang_scaling:.2f}x the single-replica rung "
                "(>= 1.5x required on accelerators: the gang must "
                "shard the TOA axis across its members; "
                "docs/serving.md)"
            )
        return {
            "devices": ndev,
            "gang_size": gsize,
            "gangs": g_fab["gangs"],
            "big_bucket": 1024,
            "gang_threshold": g_fab["gang_threshold"],
            "single_replica_rps": round(s_rps, 2),
            "gang_rps": round(g_rps, 2),
            "gang_scaling_x": round(gang_scaling, 2),
            "big_served_by": sorted(g_tags),
            "steady_recompiles": s_rec + g_rec,
        }

    # restart probe (ISSUE 11): kill-and-restart through the warm
    # ledger (serve/warm_ledger.py).  Generation 1 warms the fit
    # capacity ladder and records the ledger; generation 2's boot
    # replay must recover the full kernel set with ZERO fresh XLA
    # compiles (persistent-compile-cache hits only), then sustain the
    # prior traffic mix with zero live traces, zero steady retraces,
    # and >= 0.9x the pre-kill steady throughput (accelerators).
    def _restart_probe():
        import os as _os
        import tempfile

        from pint_tpu.runtime import compile_cache

        lpath = _os.path.join(
            tempfile.mkdtemp(prefix="pint-tpu-bench-restart-"),
            "warm-ledger.json",
        )
        kw = dict(
            max_batch=4, max_wait_ms=2.0, inflight=2, replicas=1,
            warm_ledger=lpath,
        )

        def _steady(eng):
            t0 = time.perf_counter()
            futs = []
            for _ in range(rounds):
                futs += eng.submit_many(requests())
            for f in futs:
                f.result(timeout=3600)
            return npsr * rounds / (time.perf_counter() - t0)

        eng = TimingEngine(**kw)
        try:
            wave = 1
            while wave <= 4:  # warm + record caps 1, 2, 4
                for f in eng.submit_many([
                    FitRequest(
                        par=pulsars[i % npsr][0],
                        toas=pulsars[i % npsr][1], maxiter=2,
                    )
                    for i in range(wave)
                ]):
                    f.result(timeout=3600)
                wave <<= 1
            rps_before = _steady(eng)
        finally:
            eng.close()

        xla0 = compile_cache.entry_count()
        tr = obs_metrics.counter("compile.traces")
        tr0 = tr.value
        rep0 = obs_metrics.counter("serve.warm.replayed").value
        eng2 = TimingEngine(**kw)  # boot replays the ledger
        try:
            replay_traces = tr.value - tr0
            replayed = (
                obs_metrics.counter("serve.warm.replayed").value - rep0
            )
            tr1 = tr.value
            rec0 = obs_metrics.counter("compile.recompiles").value
            rps_after = _steady(eng2)
            fresh_traces = tr.value - tr1
            steady_retraces = (
                obs_metrics.counter("compile.recompiles").value - rec0
            )
        finally:
            eng2.close()
        xla_new = compile_cache.entry_count() - xla0
        if replayed < 1:
            raise PintTpuError(
                "warm-restart replay re-warmed no kernels — the "
                "ledger write-through or the boot pre-warmer is "
                "broken (serve/warm_ledger.py; docs/robustness.md)"
            )
        if fresh_traces or steady_retraces:
            raise PintTpuError(
                f"{fresh_traces} fresh trace(s) + {steady_retraces} "
                "retrace(s) under the prior traffic mix after a "
                "warm restart — replay must recover the FULL "
                "(bucket, capacity, op) kernel set "
                "(serve/warm_ledger.py; docs/robustness.md)"
            )
        if compile_cache.cache_dir() is not None and xla_new > 0:
            raise PintTpuError(
                f"{xla_new} fresh persistent-cache executable(s) "
                "written during the warm-restart replay — generation "
                "2 must be served entirely by compile-cache HITS "
                "(runtime/compile_cache.py; docs/robustness.md)"
            )
        ratio = rps_after / rps_before
        if jax.default_backend() != "cpu" and ratio < 0.9:
            raise PintTpuError(
                f"post-restart steady throughput is {ratio:.2f}x the "
                "pre-kill figure (>= 0.9x required on accelerators: "
                "a warm restart must recover serving capacity, not "
                "re-pay the cold start; docs/robustness.md)"
            )
        return {
            "rps_before": round(rps_before, 2),
            "rps_after": round(rps_after, 2),
            "throughput_ratio": round(ratio, 3),
            "replayed_kernels": replayed,
            "replay_traces": replay_traces,
            "fresh_traces": fresh_traces,
            "steady_retraces": steady_retraces,
            "xla_new_entries": xla_new,
            "compile_cache_enabled": (
                compile_cache.cache_dir() is not None
            ),
        }

    # elastic probe (ISSUE 16): online gang/single repartition on a
    # LIVE engine.  Each flip runs ReplicaPool.repartition with a wave
    # of requests in flight: the incoming partition pre-warms from the
    # warm ledger, the outgoing one retires through the DRAINING fence
    # (queued work re-routes, nothing drops), and steady traffic on
    # the new partition must run trace-free.  Gated: zero lost
    # futures, zero steady traces after each flip, zero fresh
    # persistent-cache executables across the measured cycle.
    def _elastic_probe():
        import os as _os
        import tempfile

        from pint_tpu.parallel.mesh import serving_devices
        from pint_tpu.runtime import compile_cache
        from pint_tpu.serve import ResidualsRequest

        ndev = len(serving_devices())
        if ndev < 3:  # a gang of 2 + at least one single
            return {"skipped": f"needs >= 3 devices, have {ndev}"}

        bm, btoas = make_test_pulsar(
            "PSR EBIG\nF0 312.5 1\nF1 -2.1e-15 1\nPEPOCH 55000\n"
            "DM 17.3 1\n", ntoa=600,  # 1024 bucket: gang-classified
            start_mjd=53000.0, end_mjd=57000.0, seed=61,
            iterations=1,
        )
        bpar = bm.as_parfile()
        spar, stoas = pulsars[0]
        lpath = _os.path.join(
            tempfile.mkdtemp(prefix="pint-tpu-bench-elastic-"),
            "warm-ledger.json",
        )

        def smalls(n):
            return [ResidualsRequest(par=spar, toas=stoas)
                    for _ in range(n)]

        def bigs(n):
            return [ResidualsRequest(par=bpar, toas=btoas)
                    for _ in range(n)]

        offered = completed = 0

        def resolve(futs):
            nonlocal offered, completed
            offered += len(futs)
            for f in futs:
                try:
                    f.result(timeout=3600)
                    completed += 1
                except Exception:
                    pass

        tr = obs_metrics.counter("compile.traces")
        # max_batch=1 pins every kernel at capacity 1 and the steady
        # windows submit one key class at a time: no batching or
        # fusion freedom — the probe measures reshape mechanics only
        eng = TimingEngine(
            max_batch=1, max_wait_ms=1.0, inflight=1, max_queue=256,
            replicas=min(4, ndev), gangs=1, gang_size=2,
            gang_threshold=512, warm_ledger=lpath,
        )
        # deterministic persistent-cache writes: with the default
        # 0.2 s floor, whether a borderline compile is WRITTEN is
        # timing-dependent, and the measured cycle's zero-new-entries
        # gate needs the warm flips' writes to be complete
        min_s_prior = jax.config.jax_persistent_cache_min_compile_time_secs
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.0
        )
        try:
            for _ in range(2):  # warm both classes via the router
                resolve([*map(eng.submit, smalls(2) + bigs(2))])
            # warm FLIP cycle: the persistent cache keys per
            # (program, device assignment) — the first time a
            # partition shape exists, its executors' ledger prewarm
            # legitimately compiles first-ever pairs.  One full
            # dissolve+reform populates every pair BOTH shapes use;
            # the measured cycle repeats identical pairs, all hits.
            eng.pool.repartition(gangs=0)
            resolve([*map(eng.submit, smalls(2) + bigs(1))])
            eng.pool.repartition(gangs=1, gang_size=2)
            resolve([*map(eng.submit, smalls(2) + bigs(1))])
            if offered != completed:
                raise PintTpuError(
                    f"{offered - completed} request(s) lost during "
                    "the elastic warm-up flips — a reshape dropped "
                    "in-flight work (serve/fabric/pool.py::"
                    "repartition; docs/robustness.md)"
                )

            xla0 = compile_cache.entry_count()
            # dissolve with a small-key wave in flight
            futs = [*map(eng.submit, smalls(4))]
            dissolve_s = eng.pool.repartition(gangs=0)
            resolve(futs)
            t0 = tr.value
            resolve([*map(eng.submit, smalls(2))])
            resolve([*map(eng.submit, bigs(1))])
            dissolve_traces = tr.value - t0
            # re-form with a big-key wave in flight
            futs = [*map(eng.submit, bigs(2))]
            reform_s = eng.pool.repartition(gangs=1, gang_size=2)
            resolve(futs)
            t0 = tr.value
            resolve([*map(eng.submit, bigs(1))])
            resolve([*map(eng.submit, smalls(2))])
            reform_traces = tr.value - t0
            xla1 = compile_cache.entry_count()
            est = eng.stats()["elastic"]
        finally:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                min_s_prior,
            )
            eng.close()
        lost = offered - completed
        if lost:
            raise PintTpuError(
                f"{lost} request(s) lost across the elastic reshape "
                "cycle — every future in flight during a repartition "
                "must resolve (serve/fabric/pool.py::repartition; "
                "docs/robustness.md)"
            )
        if dissolve_traces or reform_traces:
            raise PintTpuError(
                f"{dissolve_traces} (post-dissolve) + {reform_traces} "
                "(post-reform) steady trace(s) — a reshape must hand "
                "traffic to a fully pre-warmed partition "
                "(warm-ledger replay in pool.repartition; "
                "docs/robustness.md 'elastic fleet')"
            )
        xla_new = (
            None if xla0 is None or xla1 is None else xla1 - xla0
        )
        if xla_new not in (None, 0):
            raise PintTpuError(
                f"{xla_new} fresh persistent-cache executable(s) "
                "written during the measured elastic cycle — after "
                "one warm flip cycle every (program, device "
                "assignment) pair must be a compile-cache HIT "
                "(runtime/compile_cache.py; docs/robustness.md)"
            )
        return {
            "devices": ndev,
            "dissolve_s": round(dissolve_s, 3),
            "reform_s": round(reform_s, 3),
            "reshape_s": round(max(dissolve_s, reform_s), 3),
            "lost": lost,
            "steady_traces": dissolve_traces + reform_traces,
            "xla_new_entries": xla_new,
            "reshapes": est["reshapes"],
            "partition": est["partition"],
        }

    # SLO probe (ISSUE 11): deadline-aware batch close + the per
    # -composition admission quota.  Leg 1: a near-deadline request in
    # an otherwise-idle engine with a LONG max-wait must be flushed at
    # (deadline - margin), not at max-wait — serve.slo.early_close
    # moves and the observed latency stays well under max_wait.
    # Leg 2: a hot composition floods the pipeline; with quota on, the
    # surplus sheds typed RequestRejected('quota') and an interactive
    # composition's p99 stays bounded instead of queueing behind the
    # flood (gated vs the quota-off p99 on accelerators).
    def _slo_probe():
        hot_par, hot_toas = pulsars[0]
        im, itoas = make_test_pulsar(
            "PSR INTR\nF0 88.0 1\nPEPOCH 55000\nDM 12.0 1\n",
            ntoa=48, start_mjd=54000.0, end_mjd=56000.0, seed=77,
            iterations=1,
        )
        ipar = im.as_parfile()

        # leg 1: deadline-aware early close
        deng = TimingEngine(
            max_batch=8, max_wait_ms=500.0, inflight=2, replicas=1,
            slo_close_ms=400.0,
        )
        try:
            deng.submit(FitRequest(
                par=hot_par, toas=hot_toas, maxiter=2,
            )).result(timeout=3600)  # warm cap 1
            ec0 = obs_metrics.counter("serve.slo.early_close").value
            t0 = time.perf_counter()
            deng.submit(FitRequest(
                par=hot_par, toas=hot_toas, maxiter=2,
                deadline_s=0.45,
            )).result(timeout=3600)
            near_deadline_ms = (time.perf_counter() - t0) * 1e3
            early_closes = (
                obs_metrics.counter("serve.slo.early_close").value
                - ec0
            )
        finally:
            deng.close()
        if early_closes < 1 or near_deadline_ms >= 450.0:
            raise PintTpuError(
                f"near-deadline request took {near_deadline_ms:.0f} ms "
                f"with {early_closes} early close(s) — the collector "
                "must flush a batch at (deadline - margin), not at "
                "max_wait (serve/batcher.py; docs/serving.md)"
            )

        # leg 2: quota fairness under a hot-composition flood
        from pint_tpu.exceptions import RequestRejected

        def _quota_rung(quota):
            # warm with admission unthrottled (the capacity-ladder
            # waves would themselves trip the quota), then arm it for
            # the measured flood window only
            qeng = TimingEngine(
                max_batch=8, max_wait_ms=4.0, inflight=2, replicas=1,
                max_queue=512, quota=0,
            )
            try:
                wave = 1
                while wave <= 8:  # warm the hot capacity ladder
                    for f in qeng.submit_many([
                        FitRequest(
                            par=hot_par, toas=hot_toas, maxiter=2,
                        )
                        for _ in range(wave)
                    ]):
                        f.result(timeout=3600)
                    wave <<= 1
                for f in qeng.submit_many([  # warm interactive caps
                    FitRequest(par=ipar, toas=itoas, maxiter=2)
                    for _ in range(2)
                ]):
                    f.result(timeout=3600)
                qeng.quota = quota
                flood = [
                    qeng.submit(FitRequest(
                        par=hot_par, toas=hot_toas, maxiter=2,
                    ))
                    for _ in range(160)
                ]
                # interactive requests one at a time (a real
                # interactive caller awaits each answer): with the
                # quota off the first one queues behind the whole
                # flood; with it on the flood surplus is already shed
                lats = []
                for _ in range(10):
                    ti = time.perf_counter()
                    qeng.submit(FitRequest(
                        par=ipar, toas=itoas, maxiter=2,
                    )).result(timeout=3600)
                    lats.append(time.perf_counter() - ti)
                shed = 0
                for f in flood:
                    try:
                        f.result(timeout=3600)
                    except RequestRejected as e:
                        assert e.reason == "quota", e.reason
                        shed += 1
                p99 = float(np.percentile(
                    np.asarray(lats) * 1e3, 99,
                ))
                return p99, shed
            finally:
                qeng.close()

        p99_off, shed_off = _quota_rung(0)
        p99_on, shed_on = _quota_rung(6)
        if shed_on < 1 or shed_off != 0:
            raise PintTpuError(
                f"quota rung shed {shed_on} (on) / {shed_off} (off) — "
                "a hot-composition flood over quota must shed typed "
                "RequestRejected('quota') exactly when the quota is "
                "enabled (serve/engine.py::_check_quota; "
                "docs/serving.md)"
            )
        if jax.default_backend() != "cpu" and p99_on > 0.8 * p99_off:
            raise PintTpuError(
                f"interactive p99 {p99_on:.0f} ms with the quota on "
                f"vs {p99_off:.0f} ms without (accelerator bound: "
                "<= 0.8x — the per-composition quota must keep the "
                "hot flood from monopolizing the pipeline; "
                "docs/serving.md)"
            )
        return {
            "near_deadline_ms": round(near_deadline_ms, 1),
            "early_closes": early_closes,
            "interactive_p99_ms_quota_on": round(p99_on, 1),
            "interactive_p99_ms_quota_off": round(p99_off, 1),
            "hot_shed_quota_on": shed_on,
        }

    def _xkey_probe():
        """Cross-key fused dispatches (ISSUE 12): three distinct
        small (key, capacity) identities made co-resident on ONE
        replica must serve with >= 2x fewer guarded dispatches than
        the unfused hatch (PINT_TPU_SERVE_XKEY_FUSE=0) at steady
        state, ZERO steady retraces in both modes, and bitwise
        -identical responses (the fused wrapper runs the members'
        exact solo programs and de-multiplexes).

        Co-residency is made DETERMINISTIC (the driver gate cannot
        tolerate a scheduler race): each round submits a full PLUG
        batch first — it pops with an empty queue, so it always
        dispatches solo — and a one-shot hang fault stalls the
        dispatcher inside that plug dispatch while the three small
        -key batches close behind it.  The fuser then sees all three
        at once, so the only combo that can ever form is the full
        sorted 3-set: the warm rounds trace exactly the solos then
        exactly that one combo wrapper, and steady rounds trace
        nothing.  Both modes run the identical stall, and only
        dispatch COUNTS are gated, so the fault never touches the
        measured figure."""
        import os

        import numpy as np

        from pint_tpu.runtime import faults
        from pint_tpu.serve import ResidualsRequest
        from pint_tpu.simulation import make_test_pulsar

        pa, ta = pulsars[0]          # plug: residuals @ bucket 256
        pb, tb = pulsars[1]          # small key 1: fit @ bucket 256
        mc, tc = make_test_pulsar(   # small keys 2+3 @ bucket 128
            "PSR X9\nF0 97.31 1\nF1 -1.4e-15 1\nPEPOCH 55000\n"
            "DM 12.4 1\n", ntoa=100, start_mjd=54000.0,
            end_mjd=56000.0, seed=77, iterations=1,
        )
        pc = mc.as_parfile()
        nrounds = 3

        def burst(e):
            with faults.inject(
                "hang:1@serve:residuals", hang_seconds=0.5
            ):
                fs = [
                    e.submit(ResidualsRequest(par=pa, toas=ta))
                    for _ in range(8)
                ]
                for _ in range(8):
                    fs.append(e.submit(
                        FitRequest(par=pb, toas=tb, maxiter=2)
                    ))
                    fs.append(e.submit(
                        ResidualsRequest(par=pc, toas=tc)
                    ))
                    fs.append(e.submit(
                        FitRequest(par=pc, toas=tc, maxiter=2)
                    ))
                return [f.result(timeout=3600) for f in fs]

        g = obs_metrics.counter("dispatch.guarded")
        tr = obs_metrics.counter("compile.traces")
        out = {}
        for mmode in ("on", "off"):
            saved = os.environ.get("PINT_TPU_SERVE_XKEY_FUSE")
            try:
                if mmode == "off":
                    os.environ["PINT_TPU_SERVE_XKEY_FUSE"] = "0"
                else:
                    os.environ.pop("PINT_TPU_SERVE_XKEY_FUSE", None)
                e = TimingEngine(
                    replicas=1, max_batch=8, max_wait_ms=5.0,
                    inflight=8, max_queue=256,
                )
                try:
                    # two warm rounds: solos trace first, then (fused
                    # mode) the one combo wrapper the solo-warm gate
                    # admits
                    for _ in range(2):
                        burst(e)
                    disp, traces, rounds_d = 0, 0, []
                    results = []
                    for _ in range(nrounds):
                        g0, tr0 = g.value, tr.value
                        results = burst(e)
                        rounds_d.append(g.value - g0)
                        disp += g.value - g0
                        traces += tr.value - tr0
                    out[mmode] = (disp, traces, rounds_d, results)
                finally:
                    e.close()
            finally:
                if saved is None:
                    os.environ.pop(
                        "PINT_TPU_SERVE_XKEY_FUSE", None
                    )
                else:
                    os.environ["PINT_TPU_SERVE_XKEY_FUSE"] = saved
        disp_on, tr_on, rounds_on, res_on = out["on"]
        disp_off, tr_off, _, res_off = out["off"]
        if tr_on or tr_off:
            raise PintTpuError(
                f"{tr_on} (fused) / {tr_off} (solo) steady-state "
                "trace(s) in the mixed-key probe — cross-key fusion "
                "must only dispatch warmed combo wrappers "
                "(serve/fabric/replica.py::_fuse; docs/serving.md)"
            )
        # the plug is exactly one known solo dispatch per round —
        # subtract it so the ratio measures the fusible small keys
        best_x = max(
            (disp_off / nrounds - 1) / max(d - 1, 1)
            for d in rounds_on
        )
        if best_x < 2.0:
            raise PintTpuError(
                f"mixed-key fusion reached only {best_x:.2f}x fewer "
                "dispatches than the unfused hatch (>= 2.0x "
                "required: N co-resident distinct-key batches must "
                "serve as one fused device call; "
                "serve/fabric/replica.py::_fuse, docs/serving.md)"
            )
        for a, b in zip(res_on, res_off):
            if hasattr(a, "residuals_s"):
                same = (np.array_equal(a.residuals_s, b.residuals_s)
                        and a.chi2 == b.chi2)
            else:
                same = (np.array_equal(a.deltas, b.deltas)
                        and a.chi2 == b.chi2)
            if not same:
                raise PintTpuError(
                    "fused-mode response differs from the unfused "
                    "hatch — cross-key fusion must de-multiplex "
                    "bitwise-identically (the members' exact solo "
                    "programs; serve/session.py::build_fused_kernel)"
                )
        return {
            "fused_dispatches_per_round": round(
                disp_on / nrounds, 2
            ),
            "solo_dispatches_per_round": round(
                disp_off / nrounds, 2
            ),
            "dispatch_reduction_x": round(best_x, 2),
            "steady_retraces": tr_on + tr_off,
        }

    population = _population_probe()
    gang = _gang_probe()
    restart = _restart_probe()
    slo = _slo_probe()
    xkey = _xkey_probe()
    elastic = _elastic_probe()

    r1_rps, r1_rec, _r1_occ, _ = _replica_rung(1)
    r4_rps, r4_rec, r4_occ, r4_fab = _replica_rung(4)
    scaling = r4_rps / r1_rps
    if r1_rec or r4_rec:
        raise PintTpuError(
            f"{r1_rec}+{r4_rec} steady-state XLA recompile(s) across "
            "the replica-scaling rungs — a fabric replica retraced an "
            "existing kernel (each replica must compile at most once "
            "per (composition, bucket, capacity); docs/serving.md)"
        )
    # the scaling gate needs real devices to scale across: a 1-device
    # host clamps the "4-replica" pool to one replica (serving_devices)
    # and the criterion is unmeasurable there
    if (jax.default_backend() != "cpu"
            and r4_fab["replicas"] >= 2 and scaling < 2.0):
        raise PintTpuError(
            f"{r4_fab['replicas']}-replica fabric sustained only "
            f"{scaling:.2f}x the single-replica throughput at the "
            "same offered load (>= 2x required on accelerators: the "
            "router must spread a saturated session group across the "
            "pool; docs/serving.md)"
        )
    if retraces:
        raise PintTpuError(
            f"{retraces} XLA retrace(s) across steady-state serving of "
            "mixed-size requests within one bucket — the serve "
            "zero-retrace invariant is broken (shape buckets / batch "
            "capacities must make every steady-state dispatch "
            "shape-stable; docs/serving.md)"
        )
    if jax.default_backend() != "cpu" and speedup < 3.0:
        raise PintTpuError(
            f"async serving sustained only {speedup:.2f}x the serial "
            "one-request-at-a-time throughput (>= 3x required on "
            "accelerators: batching + pipelining must amortize the "
            "~85 ms tunnel round-trip; docs/serving.md)"
        )
    return {
        "requests_per_s": round(rps, 2),
        "toas_per_s": round(rps * total_toas / npsr, 1),
        "p50_ms": st["p50_ms"],
        "p99_ms": st["p99_ms"],
        # per-stage p99 dwell (ISSUE 17): where the latency actually
        # lives across the admit->finish pipeline
        "stage_p99_ms": {
            s: v["p99_ms"]
            for s, v in st["latency"]["stages"].items()
        },
        "batch_occupancy": st["batch_occupancy_mean"],
        "sheds": st["shed"] + st["rejected"],
        "serial_requests_per_s": round(serial_rps, 2),
        "speedup_vs_serial": round(speedup, 2),
        "steady_retraces": retraces,
        "coalesced_batches": st["fabric"]["coalesced"],
        "population": population,
        "gang": gang,
        "restart": restart,
        "slo": slo,
        "xkey": xkey,
        "elastic": elastic,
        "replicas": st["fabric"]["replicas"],
        "replica_occupancy": {
            tag: rs["batches"]
            for tag, rs in st["fabric"]["per_replica"].items()
            if rs["batches"]
        },
        "replica_scaling": {
            "replicas_1_rps": round(r1_rps, 2),
            "replicas_4_rps": round(r4_rps, 2),
            "scaling_x": round(scaling, 2),
            "r4_occupancy": r4_occ,
            "r4_spills": r4_fab["spills"],
            "steady_recompiles": r1_rec + r4_rec,
        },
    }


def _stream_block():
    """Streaming-session telemetry (ISSUE 14 — serve/stream.py): one
    long-lived ObserveSession over a large absorbed base, fed k=16
    appends at steady state.  Each append rides the rank-update
    O(append) path (fitting/gls.py stream_state_*) through the warmed
    per-tail-bucket kernel; the reference is the full-refit cost of
    the same merged set through the same warmed engine — what every
    append paid before the incremental path existed.

    Gates: ZERO XLA traces across the steady append window (all
    backends — the zero-steady-retrace convention), and on
    accelerators the steady k=16 append must land >= 10x faster than
    the full refit on a 1e6-TOA session.  The CPU mesh measures the
    same probe honestly at a bounded base (the O(n) anchor fit and
    full-refit references at 1e6 are minutes of host time, not
    signal); p99 append latency is reported either way."""
    import jax

    from pint_tpu.exceptions import PintTpuError
    from pint_tpu.obs import metrics as obs_metrics
    from pint_tpu.serve import FitRequest, TimingEngine
    from pint_tpu.simulation import make_test_pulsar

    accel = jax.default_backend() != "cpu"
    base_n = 1_000_000 if accel else 20_000
    k, nwarm, nsteady = 16, 2, 12
    par_txt = (
        "PSR STRB\nF0 218.81 1\nF1 -2.2e-15 1\nPEPOCH 55000\n"
        "DM 12.4 1\nTNREDAMP -13.2\nTNREDGAM 3.2\nTNREDC 10\n"
    )
    reserve = k * (nwarm + nsteady)
    model, toas = make_test_pulsar(
        par_txt, ntoa=base_n + reserve, start_mjd=53000.0,
        end_mjd=57500.0, seed=14, iterations=1,
    )
    par = model.as_parfile()
    engine = TimingEngine(max_batch=4, max_wait_ms=1.0, inflight=2)
    try:
        t0 = time.perf_counter()
        stream = engine.open_stream(par, toas[:base_n], maxiter=4)
        open_s = time.perf_counter() - t0
        used = base_n
        for _ in range(nwarm):  # warm the k=16 tail-bucket kernel
            stream.append(toas[used:used + k]).result(timeout=3600)
            used += k
        traces0 = obs_metrics.counter("compile.traces").value
        lat = []
        for _ in range(nsteady):
            t0 = time.perf_counter()
            stream.append(toas[used:used + k]).result(timeout=3600)
            lat.append(time.perf_counter() - t0)
            used += k
        steady_traces = (
            obs_metrics.counter("compile.traces").value - traces0
        )
        # full-refit reference on the merged set (1 untimed + 3 timed
        # — same warmed engine, same fit bucket as the anchor fit)
        merged = toas[:used]
        full = []
        for i in range(4):
            t0 = time.perf_counter()
            engine.submit(FitRequest(
                par=par, toas=merged, maxiter=4,
            )).result(timeout=3600)
            if i:
                full.append(time.perf_counter() - t0)
        stream_stats = engine.stats()["stream"]
    finally:
        engine.close()
    lat.sort()
    full.sort()
    incr_ms = lat[len(lat) // 2] * 1e3
    p99_ms = lat[min(len(lat) - 1, int(0.99 * len(lat)))] * 1e3
    full_ms = full[len(full) // 2] * 1e3
    speedup = full_ms / incr_ms
    if steady_traces:
        raise PintTpuError(
            f"{steady_traces} XLA trace(s) across the steady append "
            "window — appends must ride the warmed per-tail-bucket "
            "kernel after bucket warm (the serve zero-steady-retrace "
            "convention; docs/serving.md 'streaming sessions')"
        )
    if accel and speedup < 10.0:
        raise PintTpuError(
            f"steady k={k} append on a {base_n}-TOA session is only "
            f"{speedup:.1f}x faster than the full refit (>= 10x "
            "required on accelerators: the rank-update path must "
            "keep append cost O(k), not O(n); docs/performance.md "
            "'O(append) streaming')"
        )
    return {
        "base_ntoa": base_n,
        "append_k": k,
        "open_s": round(open_s, 2),
        "append_ms": round(incr_ms, 3),
        "append_p99_ms": round(p99_ms, 3),
        "full_refit_ms": round(full_ms, 3),
        "speedup_vs_full_refit": round(speedup, 2),
        "speedup_gate": ">=10x on accelerators",
        "steady_traces": steady_traces,
        "incremental": stream_stats["incremental"],
        "fallbacks": (
            stream_stats["warm_refits"] + stream_stats["cold_refits"]
        ),
    }


def _jobs_block():
    """Background compute class (ISSUE 20 — serve/jobs/): grid and
    MCMC jobs end-to-end through ``TimingEngine.submit`` as the second
    traffic class, on the same fleet as interactive serving.

    Gates (all backends unless noted): ZERO XLA traces across a
    steady repeat of a warmed job (power-of-two quanta on warmed
    per-executor kernels — the serve convention); the deterministic
    preempt/resume round-trip — a deadline shed (the r13 pressure
    signal) must preempt a long in-flight grid job and the resumed
    surface must be BITWISE the unpressured run's; and on
    accelerators interactive p99 must hold (< 3x the idle p99) while
    a background job owns the spare capacity."""
    import jax
    import numpy as np

    from pint_tpu.exceptions import PintTpuError
    from pint_tpu.obs import metrics as obs_metrics
    from pint_tpu.serve import ResidualsRequest, TimingEngine
    from pint_tpu.serve.api import JobRequest
    from pint_tpu.simulation import make_test_pulsar

    accel = jax.default_backend() != "cpu"
    mc = obs_metrics.counter
    model, toas = make_test_pulsar(
        "PSR BJOB\nF0 211.44 1\nF1 -1.9e-15 1\nPEPOCH 55000\n"
        "DM 9.3 1\n",
        ntoa=256, start_mjd=54000.0, end_mjd=56500.0, seed=20,
        iterations=1,
    )
    par = model.as_parfile()

    def axis(center, half, n):
        return list(center + half * np.linspace(-1.0, 1.0, n))

    small = {
        "F0": axis(211.44, 2e-9, 16), "F1": axis(-1.9e-15, 2e-17, 16),
    }
    big = {
        "F0": axis(211.44, 2e-9, 64), "F1": axis(-1.9e-15, 2e-17, 64),
    }

    def grid_job(engine, grid):
        return engine.submit(JobRequest(
            kind="grid_chisq", par=par, toas=toas, grid=grid,
        ))

    def timed_wave(engine, n=12):
        lat = []
        for _ in range(n):
            t0 = time.perf_counter()
            engine.submit(ResidualsRequest(
                par=par, toas=toas,
            )).result(timeout=3600)
            lat.append(time.perf_counter() - t0)
        lat.sort()
        return lat

    engine = TimingEngine(max_batch=4, max_wait_ms=1.0, inflight=2)
    try:
        # interactive baseline (idle fleet, warmed kernel)
        engine.submit(ResidualsRequest(
            par=par, toas=toas,
        )).result(timeout=3600)
        idle_lat = timed_wave(engine)

        # grid end-to-end: warm run, then the steady repeat gate
        npts = 16 * 16
        t0 = time.perf_counter()
        ref = grid_job(engine, small).result(timeout=3600)
        grid_s = time.perf_counter() - t0
        traces0 = mc("compile.traces").value
        again = grid_job(engine, small).result(timeout=3600)
        steady_s = time.perf_counter() - t0 - grid_s
        steady_traces = mc("compile.traces").value - traces0
        steady_bitwise = bool(np.array_equal(
            ref.result["chi2"], again.result["chi2"]
        ))

        # MCMC end-to-end (fixed-quantum lax.scan interior)
        nsteps, nwalkers = 256, 16
        t0 = time.perf_counter()
        engine.submit(JobRequest(
            kind="mcmc", par=par, toas=toas, nsteps=nsteps,
            nwalkers=nwalkers, seed=20,
        )).result(timeout=3600)
        mcmc_s = time.perf_counter() - t0

        # the unpressured long-grid surface (same (key, cap) as the
        # pressured run below — no fresh kernel)
        big_ref = grid_job(engine, big).result(timeout=3600)

        # preempt/resume round-trip + interactive latency under a
        # live background job: a deliberately-expired deadline is the
        # deterministic r13 shed signal (engine._expired), the timed
        # wave rides the fleet while the job yields and resumes
        p0 = mc("serve.jobs.preempted").value
        r0 = mc("serve.jobs.resumed").value
        q0 = mc("serve.jobs.quanta").value
        jfut = grid_job(engine, big)
        deadline = time.monotonic() + 60.0
        while (mc("serve.jobs.quanta").value == q0
               and time.monotonic() < deadline):
            time.sleep(0.002)
        try:
            engine.submit(ResidualsRequest(
                par=par, toas=toas, deadline_s=1e-4,
            )).result(timeout=3600)
        except Exception:
            pass  # the deadline shed IS the probe
        jobs_lat = timed_wave(engine)
        pressured = jfut.result(timeout=3600)
        preempted = mc("serve.jobs.preempted").value - p0
        resumed = mc("serve.jobs.resumed").value - r0
        preempt_bitwise = bool(np.array_equal(
            big_ref.result["chi2"], pressured.result["chi2"]
        ))
        jobs_stats = engine.stats()["jobs"]
    finally:
        engine.close()

    def p99(lat):
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))] * 1e3

    p99_idle = p99(idle_lat)
    p99_jobs = p99(jobs_lat)
    ratio = p99_jobs / max(p99_idle, 1e-9)
    if steady_traces:
        raise PintTpuError(
            f"{steady_traces} XLA trace(s) across a steady job repeat "
            "— quanta must ride warmed per-executor kernels after the "
            "first run (the serve zero-steady-retrace convention; "
            "docs/serving.md 'background jobs')"
        )
    if not (preempted >= 1 and resumed >= 1 and preempt_bitwise):
        raise PintTpuError(
            f"preempt/resume round-trip failed: preempted={preempted} "
            f"resumed={resumed} bitwise={preempt_bitwise} — a deadline "
            "shed must yield the fleet within one quantum and the "
            "resumed job must continue from its exact carry "
            "(docs/robustness.md 'preemption ladder')"
        )
    if accel and ratio > 3.0:
        raise PintTpuError(
            f"interactive p99 degraded {ratio:.1f}x while a background "
            "job ran (>= 3x: jobs must yield on pressure and stay off "
            "busy executors; docs/serving.md 'background jobs')"
        )
    return {
        "grid_pts_per_s": round(npts / grid_s, 1),
        "grid_steady_pts_per_s": round(npts / steady_s, 1),
        "mcmc_samples_per_s": round(nsteps * nwalkers / mcmc_s, 1),
        "steady_traces": steady_traces,
        "steady_bitwise": steady_bitwise,
        "preempted": preempted,
        "resumed": resumed,
        "preempt_bitwise": preempt_bitwise,
        "interactive_p99_idle_ms": round(p99_idle, 3),
        "interactive_p99_jobs_ms": round(p99_jobs, 3),
        "p99_ratio": round(ratio, 2),
        "p99_gate": "< 3x on accelerators",
        "quantum_p50_ms": jobs_stats["quantum_p50_ms"],
    }


def main():
    import jax

    jax.config.update("jax_enable_x64", True)

    ntoa = 100_000
    # cold-path telemetry (r6): the driver-tracked bench line now
    # carries the build/ingest wall next to the warm-step metric, plus
    # the persistent-compile-cache state, so cold-start regressions
    # are guarded like throughput ones (the full phase breakdown —
    # swap refits, time-to-first-fit — lives in
    # profiling/profile_fit_wall.py's cold_path JSON block).
    from pint_tpu.runtime import compile_cache

    _cache_entries0 = compile_cache.entry_count()
    _t0 = time.perf_counter()
    model, toas, cm = _build(ntoa)
    cold_block = {
        "build_ingest_s": round(time.perf_counter() - _t0, 2),
        "ingest_toas_per_s": round(
            ntoa / (time.perf_counter() - _t0), 1
        ),
        "compile_cache_dir": compile_cache.cache_dir(),
    }

    # device path: the production accelerator mode (GLSFitter 'auto')
    from pint_tpu.fitting.gls import default_accel_mode

    mode = default_accel_mode(cm)
    step = _fit_step_fn(cm, mode=mode)
    # chain=256 on device: the steady-state per-step cost (production
    # fits amortize the one-dispatch cost over GN iterations and over
    # vmapped PTA batches; the tunnel round-trip is not TPU work and
    # still contributes < 0.5 ms/step at this chain length)
    t_dev = _time_step(step, cm.x0(), chain=256, jit_wrap=cm.jit)

    guard_block = _guard_block(cm, step, mode, t_dev)
    fit_traj_block = _fit_traj_block(t_dev)
    # serve first: the obs block's attribution-overhead gate amortizes
    # the measured per-request stage-clock cost against the serve
    # block's steady request rate (ISSUE 17)
    serve_block = _serve_block()
    obs_block = _obs_block(serve_rps=serve_block["requests_per_s"])
    stream_block = _stream_block()
    jobs_block = _jobs_block()
    mfu_block = _mfu_block(cm)
    fused_block = _fused_interior_block(cm, mode, t_dev)

    # CPU baseline: the all-f64 reference-class computation on host
    # (dispatch-free, so a short chain measures the same steady state).
    # Faithfulness guards — the reference (src/pint/fitter.py GLS loop)
    # recomputes the noise design matrix and refactorizes every
    # iteration, so the stand-in must too: (a) strip the compile-time
    # precomputed Fourier-basis masks (a framework feature the
    # reference class lacks) so the basis sin/cos are recomputed per
    # step; (b) pass the TOA bundle as a RUNTIME argument so XLA
    # cannot constant-fold the x-independent noise factorization out
    # of the loop (folding it would credit the reference class with
    # our trace-time specialization).
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        cpu_bundle = jax.device_put(cm.bundle, cpu)
        cpu_bundle = cpu_bundle._replace(masks={
            k: v for k, v in cpu_bundle.masks.items()
            if not k.endswith(":F")
        })
        cm_cpu = type(cm)(cm.model, cpu_bundle, subtract_mean=True)
        cm_cpu.track_mode = cm.track_mode
        step_cpu_x = _fit_step_fn(cm_cpu)

        def step_cpu(bundle, x):
            saved = cm_cpu.bundle
            cm_cpu.bundle = bundle
            try:
                return step_cpu_x(x)
            finally:
                cm_cpu.bundle = saved

        # denominator robustness (VERDICT r2 weak 1: the r2 builder and
        # driver runs disagreed ~2x because chain=4/nrep=3 was load-
        # sensitive): chain=16 amortizes per-dispatch overhead to <1%,
        # nrep=5 medians reject transient host load, and the host state
        # is logged (stderr) so an anomalous denominator is explicable
        t_cpu = _time_step(
            step_cpu, jax.device_put(cm.x0(), cpu), nrep=5, chain=16,
            data_args=(cpu_bundle,),
        )

    import os
    import sys

    print(
        json.dumps({
            "cpu_step_ms": round(t_cpu * 1e3, 2),
            "dev_step_ms": round(t_dev * 1e3, 4),
            "loadavg": os.getloadavg(),
            "ncpu": os.cpu_count(),
            "cpu_chain": 16, "cpu_nrep": 5, "dev_chain": 256,
        }),
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": "GLS red-noise fit-step throughput (1e5 TOAs,"
                " EFAC/EQUAD + 30-harmonic PL red noise, Woodbury"
                " solve + jacfwd design)",
                "value": round(ntoa / t_dev, 1),
                "unit": "TOAs/sec",
                "vs_baseline": round(t_cpu / t_dev, 3),
                "guard": guard_block,
                "obs": obs_block,
                "fit_traj": fit_traj_block,
                "serve": serve_block,
                "stream": stream_block,
                "jobs": jobs_block,
                "mfu": mfu_block,
                "fused_interior": fused_block,
                "cold": {
                    **cold_block,
                    # executables persisted by THIS run: >0 on a cold
                    # disk, 0 on a fully warm one (every compile
                    # served from the cache)
                    "compile_cache_new_entries": (
                        compile_cache.entry_count() - _cache_entries0
                    ),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
