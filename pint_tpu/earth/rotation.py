"""ITRF -> GCRS rotation: IAU1976 precession + truncated IAU1980
nutation + frame bias + GAST spin + polar motion.

Reference parity: src/pint/erfautils.py::gcrs_posvel_from_itrf, which
wraps ERFA's full IAU2000A machinery via astropy.  Here the classical
equinox-based chain is implemented directly:

  r_GCRS = B^T P^T(t) N^T(t) R3(-GAST) W^T(t) r_ITRF

with the nutation series truncated to the 54 largest IAU1980 terms
(every term with |dpsi| >= 0.4 mas or |deps| >= 0.2 mas).  The ~52
omitted terms are each <= 0.3 mas with RSS < ~0.7 mas, so the series
is ~1 mas-class vs full IAU1980 — the same class as IAU2000B vs
IAU2000A (the reference's full machinery).  1 mas of orientation is
~3 cm of observatory position ~ 0.1 ns of timing; IAU1980 itself
differs from IAU2000A by a further ~3 mas (updated amplitudes +
planetary nutation), which the frame-bias + EOP corrections absorb in
practice.  GAST includes the two largest complementary terms of the
equation of the equinoxes (IAU 2000 definition).

All functions are vectorized numpy over the TOA axis and run host-side
at ingest (SURVEY.md §3.1: load-time work); the products ship to device
as TOABundle geometry columns.
"""

from __future__ import annotations

import numpy as np

ARCSEC = np.pi / (180.0 * 3600.0)
TWOPI = 2.0 * np.pi
# IERS conventional mean angular velocity of the Earth (rad/s)
OMEGA_EARTH = 7.292115855306589e-5  # derived from the ERA rate below
# ERA rate: revolutions per UT1 day
_ERA_RATE = 1.00273781191135448


def _r1(angle):
    c, s = np.cos(angle), np.sin(angle)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([
        np.stack([o, z, z], -1),
        np.stack([z, c, s], -1),
        np.stack([z, -s, c], -1),
    ], -2)


def _r2(angle):
    c, s = np.cos(angle), np.sin(angle)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([
        np.stack([c, z, -s], -1),
        np.stack([z, o, z], -1),
        np.stack([s, z, c], -1),
    ], -2)


def _r3(angle):
    c, s = np.cos(angle), np.sin(angle)
    z, o = np.zeros_like(c), np.ones_like(c)
    return np.stack([
        np.stack([c, s, z], -1),
        np.stack([-s, c, z], -1),
        np.stack([z, z, o], -1),
    ], -2)


# -- frame bias (GCRS -> mean J2000), IAU 2000 ---------------------------
_XI0 = -0.0166170 * ARCSEC
_ETA0 = -0.0068192 * ARCSEC
_DA0 = -0.01460 * ARCSEC


def bias_matrix():
    """B such that r_J2000mean = B r_GCRS."""
    return (_r1(np.float64(-_ETA0)) @ _r2(np.float64(_XI0))
            @ _r3(np.float64(_DA0)))


# -- IAU1976 precession ---------------------------------------------------
def precession_matrix(t_tt_cent):
    """P such that r_mean-of-date = P r_J2000 (IAU 1976)."""
    T = np.asarray(t_tt_cent, dtype=np.float64)
    zeta = (2306.2181 * T + 0.30188 * T**2 + 0.017998 * T**3) * ARCSEC
    z = (2306.2181 * T + 1.09468 * T**2 + 0.018203 * T**3) * ARCSEC
    theta = (2004.3109 * T - 0.42665 * T**2 - 0.041833 * T**3) * ARCSEC
    return _r3(-z) @ _r2(theta) @ _r3(-zeta)


def mean_obliquity(t_tt_cent):
    """IAU1980 mean obliquity of the ecliptic (rad)."""
    T = np.asarray(t_tt_cent, dtype=np.float64)
    return (
        84381.448 - 46.8150 * T - 0.00059 * T**2 + 0.001813 * T**3
    ) * ARCSEC


# -- IAU1980 nutation, largest 54 terms ----------------------------------
# rows: (l, l', F, D, Om multipliers, psi_0.1mas, psi_t, eps_0.1mas, eps_t)
_NUT_TERMS = np.array([
    [0, 0, 0, 0, 1, -171996.0, -174.2, 92025.0, 8.9],
    [0, 0, 2, -2, 2, -13187.0, -1.6, 5736.0, -3.1],
    [0, 0, 2, 0, 2, -2274.0, -0.2, 977.0, -0.5],
    [0, 0, 0, 0, 2, 2062.0, 0.2, -895.0, 0.5],
    [0, 1, 0, 0, 0, 1426.0, -3.4, 54.0, -0.1],
    [1, 0, 0, 0, 0, 712.0, 0.1, -7.0, 0.0],
    [0, 1, 2, -2, 2, -517.0, 1.2, 224.0, -0.6],
    [0, 0, 2, 0, 1, -386.0, -0.4, 200.0, 0.0],
    [1, 0, 2, 0, 2, -301.0, 0.0, 129.0, -0.1],
    [0, -1, 2, -2, 2, 217.0, -0.5, -95.0, 0.3],
    [1, 0, 0, -2, 0, -158.0, 0.0, -1.0, 0.0],
    [0, 0, 2, -2, 1, 129.0, 0.1, -70.0, 0.0],
    [-1, 0, 2, 0, 2, 123.0, 0.0, -53.0, 0.0],
    [1, 0, 0, 0, 1, 63.0, 0.1, -33.0, 0.0],
    [0, 0, 0, 2, 0, 63.0, 0.0, -2.0, 0.0],
    [-1, 0, 2, 2, 2, -59.0, 0.0, 26.0, 0.0],
    [-1, 0, 0, 0, 1, -58.0, -0.1, 32.0, 0.0],
    [1, 0, 2, 0, 1, -51.0, 0.0, 27.0, 0.0],
    [-2, 0, 2, 0, 1, 46.0, 0.0, -24.0, 0.0],
    [0, 0, 2, 2, 2, -38.0, 0.0, 16.0, 0.0],
    [2, 0, 2, 0, 2, -31.0, 0.0, 13.0, 0.0],
    [2, 0, 0, 0, 0, 29.0, 0.0, -1.0, 0.0],
    [1, 0, 2, -2, 2, 29.0, 0.0, -12.0, 0.0],
    [0, 0, 2, 0, 0, 26.0, 0.0, -1.0, 0.0],
    [0, 0, 2, -2, 0, -22.0, 0.0, 0.0, 0.0],
    [-1, 0, 2, 0, 1, 21.0, 0.0, -10.0, 0.0],
    [0, 2, 0, 0, 0, 17.0, -0.1, 0.0, 0.0],
    [0, 2, 2, -2, 2, -16.0, 0.1, 7.0, 0.0],
    [-1, 0, 0, 2, 1, 16.0, 0.0, -8.0, 0.0],
    [0, 1, 0, 0, 1, -15.0, 0.0, 9.0, 0.0],
    [1, 0, 0, -2, 1, -13.0, 0.0, 7.0, 0.0],
    [0, -1, 0, 0, 1, -12.0, 0.0, 6.0, 0.0],
    [2, 0, -2, 0, 0, 11.0, 0.0, 0.0, 0.0],
    [-1, 0, 2, 2, 1, -10.0, 0.0, 5.0, 0.0],
    [1, 0, 2, 2, 2, -8.0, 0.0, 3.0, 0.0],
    [0, -1, 2, 0, 2, -7.0, 0.0, 3.0, 0.0],
    [0, 0, 2, 2, 1, -7.0, 0.0, 3.0, 0.0],
    [1, 1, 0, -2, 0, -7.0, 0.0, 0.0, 0.0],
    [0, 1, 2, 0, 2, 7.0, 0.0, -3.0, 0.0],
    [-2, 0, 0, 2, 1, -6.0, 0.0, 3.0, 0.0],
    [0, 0, 0, 2, 1, -6.0, 0.0, 3.0, 0.0],
    [2, 0, 2, -2, 2, 6.0, 0.0, -3.0, 0.0],
    [1, 0, 0, 2, 0, 6.0, 0.0, 0.0, 0.0],
    [1, 0, 2, -2, 1, 6.0, 0.0, -3.0, 0.0],
    [0, 0, 0, -2, 1, -5.0, 0.0, 3.0, 0.0],
    [0, -1, 2, -2, 1, -5.0, 0.0, 3.0, 0.0],
    [2, 0, 2, 0, 1, -5.0, 0.0, 3.0, 0.0],
    [1, -1, 0, 0, 0, 5.0, 0.0, 0.0, 0.0],
    [1, 0, 0, -1, 0, -4.0, 0.0, 0.0, 0.0],
    [0, 0, 0, 1, 0, -4.0, 0.0, 0.0, 0.0],
    [0, 1, 0, -2, 0, -4.0, 0.0, 0.0, 0.0],
    [1, 0, -2, 0, 0, 4.0, 0.0, 0.0, 0.0],
    [2, 0, 0, -2, 1, 4.0, 0.0, -2.0, 0.0],
    [0, 1, 2, -2, 1, 4.0, 0.0, -2.0, 0.0],
])


def fundamental_args(t_tt_cent):
    """Delaunay arguments l, l', F, D, Om (rad; IERS 2003 polynomials)."""
    T = np.asarray(t_tt_cent, dtype=np.float64)

    def poly(deg0, c1, c2, c3):
        return np.deg2rad(
            deg0 + (c1 * T + c2 * T**2 + c3 * T**3) / 3600.0
        )

    l = poly(134.96340251, 1717915923.2178, 31.8792, 0.051635)
    lp = poly(357.52910918, 129596581.0481, -0.5532, 0.000136)
    F = poly(93.27209062, 1739527262.8478, -12.7512, -0.001037)
    D = poly(297.85019547, 1602961601.2090, -6.3706, 0.006593)
    Om = poly(125.04455501, -6962890.5431, 7.4722, 0.007702)
    return l, lp, F, D, Om


def nutation_angles(t_tt_cent):
    """(dpsi, deps) in radians; truncated IAU1980 (54 largest terms,
    omitted-term RSS < 0.7 mas — see module docstring)."""
    T = np.asarray(t_tt_cent, dtype=np.float64)
    l, lp, F, D, Om = fundamental_args(T)
    args = np.stack([l, lp, F, D, Om], axis=-1)  # (..., 5)
    mult = _NUT_TERMS[:, :5]  # (k, 5)
    phase = np.tensordot(args, mult.T, axes=([-1], [0]))  # (..., k)
    psi_amp = (_NUT_TERMS[:, 5] + _NUT_TERMS[:, 6] * T[..., None])
    eps_amp = (_NUT_TERMS[:, 7] + _NUT_TERMS[:, 8] * T[..., None])
    dpsi = np.sum(psi_amp * np.sin(phase), axis=-1) * 1e-4 * ARCSEC
    deps = np.sum(eps_amp * np.cos(phase), axis=-1) * 1e-4 * ARCSEC
    return dpsi, deps


def nutation_matrix(t_tt_cent):
    """N such that r_true-of-date = N r_mean-of-date."""
    eps0 = mean_obliquity(t_tt_cent)
    dpsi, deps = nutation_angles(t_tt_cent)
    return _r1(-(eps0 + deps)) @ _r3(-dpsi) @ _r1(eps0)


# -- Earth rotation angle / sidereal time --------------------------------
def era(mjd_ut1):
    """Earth rotation angle (rad; IAU 2000 definition).

    Tu = JD(UT1) - 2451545.0 = MJD(UT1) - 51544.5; splitting Tu into
    day + fraction keeps the fast term at full f64 resolution.
    """
    mjd = np.asarray(mjd_ut1, dtype=np.float64)
    tu_day = np.floor(mjd) - 51544.0
    tu_frac = mjd - np.floor(mjd) - 0.5
    # ERA/2pi = 0.779... + 1.00273781191135448 Tu; the integer-day part
    # of 1.0*Tu drops out mod 1, leaving full resolution on the fraction
    turns = (
        0.7790572732640
        + 0.00273781191135448 * (tu_day + tu_frac)
        + tu_frac
    )
    return np.mod(turns, 1.0) * TWOPI


def gmst82(mjd_ut1):
    """Greenwich mean sidereal time, IAU1982 model (rad)."""
    mjd = np.asarray(mjd_ut1, dtype=np.float64)
    Tu = (mjd - 51544.5) / 36525.0
    gmst_s = (
        67310.54841
        + (876600.0 * 3600.0 + 8640184.812866) * Tu
        + 0.093104 * Tu**2
        - 6.2e-6 * Tu**3
    )
    return np.mod(gmst_s * TWOPI / 86400.0, TWOPI)


def gast(mjd_ut1, t_tt_cent):
    """Greenwich apparent sidereal time = GMST + equation of the
    equinoxes (dpsi cos(eps) + the two largest complementary terms of
    the IAU 2000 definition, ~0.9 mas total)."""
    eps0 = mean_obliquity(t_tt_cent)
    dpsi, deps = nutation_angles(t_tt_cent)
    _, _, _, _, Om = fundamental_args(t_tt_cent)
    ee_ct = (0.00264 * np.sin(Om) + 0.000063 * np.sin(2.0 * Om)) * ARCSEC
    return gmst82(mjd_ut1) + dpsi * np.cos(eps0 + deps) + ee_ct


# -- full chain -----------------------------------------------------------
def itrf_to_gcrs_matrix(mjd_ut1, t_tt_cent, xp_rad=0.0, yp_rad=0.0):
    """(..., 3, 3) matrix M with r_GCRS = M r_ITRF."""
    B = bias_matrix()
    P = precession_matrix(t_tt_cent)
    N = nutation_matrix(t_tt_cent)
    theta = gast(mjd_ut1, t_tt_cent)
    spin = _r3(-theta)
    W = _r1(-np.asarray(yp_rad, dtype=np.float64)) @ _r2(
        -np.asarray(xp_rad, dtype=np.float64)
    )
    # r_ITRF = W R3(GAST) N P B r_GCRS  ->  invert (all orthonormal)
    M_c2t = W @ _r3(theta) @ N @ P @ B
    return np.swapaxes(M_c2t, -1, -2)


def gcrs_posvel_from_itrf(
    itrf_m, mjd_ut1, t_tt_cent, xp_rad=0.0, yp_rad=0.0
):
    """Observatory GCRS position (m) and velocity (m/s).

    itrf_m: (3,) or (n, 3); mjd_ut1/t_tt_cent: scalar or (n,).
    Velocity = omega x r in the true-of-date frame (precession/nutation
    rates are ~1e-12 rad/s, 7 orders below Earth spin — neglected, as
    does the reference's velocity via finite differencing).
    """
    itrf = np.asarray(itrf_m, dtype=np.float64)
    M = itrf_to_gcrs_matrix(mjd_ut1, t_tt_cent, xp_rad, yp_rad)
    pos = (M @ itrf[..., None])[..., 0]
    omega = np.array([0.0, 0.0, OMEGA_EARTH])
    # v_GCRS = M (omega x r_ITRF) in the rotating-frame sense
    v_itrf = np.cross(np.broadcast_to(omega, itrf.shape), itrf)
    vel = (M @ v_itrf[..., None])[..., 0]
    return pos, vel


def itrf_to_geodetic(itrf_m):
    """WGS84 geodetic latitude (rad), longitude (rad), height (m)."""
    x, y, z = np.asarray(itrf_m, dtype=np.float64).T
    a, f = 6378137.0, 1.0 / 298.257223563
    b = a * (1 - f)
    e2 = f * (2 - f)
    p = np.hypot(x, y)
    lon = np.arctan2(y, x)
    # Bowring's method, one iteration (sub-mm for Earth surface)
    u = np.arctan2(z * a, p * b)
    ep2 = e2 / (1 - e2)
    lat = np.arctan2(
        z + ep2 * b * np.sin(u) ** 3, p - e2 * a * np.cos(u) ** 3
    )
    N = a / np.sqrt(1 - e2 * np.sin(lat) ** 2)
    h = p / np.cos(lat) - N
    return lat, lon, h
