"""Earth-orientation parameters (UT1-UTC, polar motion).

Reference parity: the reference gets EOP from astropy's IERS-A/B
machinery (auto-downloaded).  Offline-first design here: a parser for
the standard IERS ``finals2000A.all`` fixed-width format, loaded from
``$PINT_TPU_EOP`` or an explicit path; with no table, DUT1 = xp = yp = 0
with a one-time warning (absolute timing error bounded by |DUT1| <= 0.9 s
x 465 m/s / c ~= 1.4 us of Roemer; polar motion < 15 m ~= 50 ns).
"""

from __future__ import annotations

import os
import warnings

import numpy as np

ARCSEC = np.pi / (180.0 * 3600.0)

_warned = False


class EOPTable:
    def __init__(self, mjd, dut1_s, xp_rad, yp_rad, name="eop"):
        order = np.argsort(mjd)
        self.mjd = np.asarray(mjd, float)[order]
        self.dut1_s = np.asarray(dut1_s, float)[order]
        self.xp_rad = np.asarray(xp_rad, float)[order]
        self.yp_rad = np.asarray(yp_rad, float)[order]
        self.name = name

    def at(self, mjd_utc):
        """(dut1_s, xp_rad, yp_rad) linearly interpolated; clamped at the
        table ends."""
        m = np.asarray(mjd_utc, float)
        return (
            np.interp(m, self.mjd, self.dut1_s),
            np.interp(m, self.mjd, self.xp_rad),
            np.interp(m, self.mjd, self.yp_rad),
        )


def parse_finals2000a(path) -> EOPTable:
    """Parse the IERS finals2000A.all fixed-width format.

    Columns (1-indexed): MJD 8-15, PM-x 19-27 ("), PM-y 38-46 ("),
    UT1-UTC 59-68 (s).  Rows without a UT1 value are skipped.
    """
    mjds, duts, xps, yps = [], [], [], []
    with open(path) as f:
        for line in f:
            if len(line) < 68:
                continue
            try:
                mjd = float(line[7:15])
                xp = float(line[18:27])
                yp = float(line[37:46])
                dut1 = float(line[58:68])
            except ValueError:
                continue
            mjds.append(mjd)
            duts.append(dut1)
            xps.append(xp * ARCSEC)
            yps.append(yp * ARCSEC)
    if not mjds:
        from pint_tpu.exceptions import DataFileError

        raise DataFileError(f"no EOP rows parsed from {path}")
    return EOPTable(mjds, duts, xps, yps, name=os.path.basename(str(path)))


_table: EOPTable | None = None
_loaded_from_env = False


def set_eop_table(table: EOPTable | None):
    global _table
    _table = table


def reset_eop():
    """Forget the loaded table AND the env-load/warn memos (tests;
    $PINT_TPU_EOP changes)."""
    global _table, _loaded_from_env, _warned
    _table = None
    _loaded_from_env = False
    _warned = False


def get_eop(mjd_utc):
    """(dut1_s, xp_rad, yp_rad) at mjd_utc, from the loaded table or the
    zero default."""
    global _table, _loaded_from_env, _warned
    if _table is None and not _loaded_from_env:
        _loaded_from_env = True
        path = os.environ.get("PINT_TPU_EOP")
        if path and os.path.exists(path):
            _table = parse_finals2000a(path)
    if _table is not None:
        return _table.at(mjd_utc)
    if not _warned:
        _warned = True
        warnings.warn(
            "no Earth-orientation table loaded (set $PINT_TPU_EOP to an "
            "IERS finals2000A.all file); using UT1=UTC and zero polar "
            "motion (~us-level absolute timing error)"
        )
    m = np.asarray(mjd_utc, float)
    z = np.zeros_like(m)
    return z, z, z
