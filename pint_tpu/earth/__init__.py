"""Earth orientation: precession, nutation, rotation, polar motion, EOP.

TPU-native replacement for the pyerfa (C) capabilities the reference
consumes via astropy (SURVEY.md §2 native-capability table, row 1).
"""

from pint_tpu.earth.rotation import (  # noqa: F401
    gcrs_posvel_from_itrf,
    itrf_to_gcrs_matrix,
)
