"""pint_tpu.obs — the dispatch flight recorder (PR 2).

Three layers (each its own module) plus the chokepoint helpers below:

- :mod:`pint_tpu.obs.trace` — nested thread-safe spans with monotonic
  clocks and explicit device fencing (off by default; ~free when off).
- :mod:`pint_tpu.obs.metrics` — always-on counters/gauges/histograms;
  ``snapshot()`` subsumes the old ``GuardStats.snapshot()``.
- :mod:`pint_tpu.obs.export` — Perfetto/Chrome-trace JSON, bench.py's
  one-line summary, and the human ``flight_report``.

The helpers here are the accounting hooks the compile chokepoint
(models/timing_model.py::CompiledModel.jit) calls: they record XLA
(re)traces, baked-module transport pressure, and operand bytes.  They
live in obs so the chokepoint stays one import away from the recorder
and pintlint (rules obs1-obs5) can statically verify the wiring.
"""

from __future__ import annotations

import os

from pint_tpu.obs import metrics, trace
from pint_tpu.obs.trace import TRACER

__all__ = [
    "metrics",
    "trace",
    "TRACER",
    "note_trace",
    "note_baked_module",
    "note_transfer",
]

# pre-register the canonical metrics so every snapshot() carries the
# full key set (a counter that never fired reads 0, not KeyError —
# bench JSON and dashboards need stable schemas)
for _name, _unit in (
    ("dispatch.count", ""),
    ("dispatch.guarded", ""),
    ("compile.traces", ""),
    ("compile.recompiles", ""),
    ("transfer.bytes_to_device", "bytes"),
    ("transport.near_413", ""),
    ("fit.count", ""),
    ("ingest.count", ""),
    ("ingest.toas", "TOAs"),
    # serving engine (pint_tpu/serve — PR 4); histograms/gauges below
    ("serve.requests", ""),
    ("serve.completed", ""),
    ("serve.shed", ""),
    ("serve.rejected", ""),
    ("serve.batches", ""),
    ("serve.session.hits", ""),
    ("serve.session.misses", ""),
    ("serve.session.evictions", ""),
    ("serve.polyco.hits", ""),
    ("serve.polyco.misses", ""),
    # serving fabric (pint_tpu/serve/fabric — PR 5): routing,
    # placement spills, replica health transitions, canary probes
    ("serve.fabric.routes", ""),
    ("serve.fabric.reroutes", ""),
    ("serve.fabric.spills", ""),
    ("serve.fabric.failures", ""),
    ("serve.fabric.degraded", ""),
    ("serve.fabric.quarantines", ""),
    ("serve.fabric.readmits", ""),
    ("serve.fabric.probes", ""),
    ("serve.fabric.no_replica", ""),
    # fleet operability (pint_tpu/serve — ISSUE 11): dispatch-boundary
    # late sheds, SLO-aware early batch closes, per-composition quota
    # rejections, and the warm-restart ledger's replay accounting
    ("serve.shed.late", ""),
    ("serve.slo.early_close", ""),
    ("serve.quota_rejected", ""),
    ("serve.warm.recorded", ""),
    ("serve.warm.replayed", ""),
    ("serve.warm.failed", ""),
    ("serve.warm.stale", ""),
):
    metrics.counter(_name, unit=_unit)
del _name, _unit
metrics.histogram("serve.batch_occupancy")
metrics.histogram("serve.latency_ms", unit="ms")
metrics.gauge("serve.queue_depth")

#: the axon remote-compile transport rejects requests around this size
#: (HTTP 413 measured at ~256 MB, r5); a baked module whose literal
#: estimate crosses NEAR_413_FRACTION of it bumps transport.near_413.
TRANSPORT_LIMIT_BYTES = int(
    os.environ.get("PINT_TPU_TRANSPORT_LIMIT_BYTES", str(256 * 2**20))
)
NEAR_413_FRACTION = 0.25

#: measured floor for baked-literal HLO text per TOA (CLAUDE.md /
#: docs/parallelism.md: ~240 bytes/TOA at bench configs; the n=32768
#: dense step measured ~488) — the estimate below takes the max of
#: this floor and the bundle's actual numeric bytes.
HLO_BYTES_PER_TOA = 240.0


def note_trace(site: str, retrace: bool):
    """Called from INSIDE a jitted function's Python body, which jax
    executes exactly once per XLA (re)trace — so this host side effect
    is an exact compile counter.  ``retrace=True`` marks a trace
    beyond the wrapper's first: a RECOMPILE (bundle swap, ladder
    device pin, shape change).  Recompiles must stay 0 across a refit
    loop (the r5 "refits are one dispatch" invariant; bench.py and
    tests/test_obs.py gate on it)."""
    metrics.counter("compile.traces", help="XLA (re)traces").inc()
    if retrace:
        metrics.counter(
            "compile.recompiles",
            help="re-traces of an existing wrapper",
        ).inc()
        TRACER.event("recompile", "compile", site=site)


def note_baked_module(site: str, ntoa: int, bundle=None):
    """Record transport pressure of a baked-constant lowering: the
    bundle columns become HLO literals, and the remote-compile
    transport 413s near TRANSPORT_LIMIT_BYTES (r5).  The default
    bake/argue cutover (2e5 TOAs) keeps baked modules far from the
    limit; a raised $PINT_TPU_BAKE_THRESHOLD is how one sneaks up on
    it — this near-miss counter is the early warning."""
    est = HLO_BYTES_PER_TOA * max(int(ntoa), 0)
    if bundle is not None:
        est = max(est, float(trace.nbytes_of(bundle)))
    metrics.gauge(
        "transport.baked_bytes_est", unit="bytes",
        help="estimated baked-literal HLO bytes of the last module",
    ).set(est)
    if est >= NEAR_413_FRACTION * TRANSPORT_LIMIT_BYTES:
        metrics.counter(
            "transport.near_413",
            help="baked modules near the 413 transport limit",
        ).inc()
        TRACER.event(
            "near-413", "transport", site=site, ntoa=int(ntoa),
            est_bytes=est, limit_bytes=TRANSPORT_LIMIT_BYTES,
        )


def note_transfer(site: str, const_bytes: int, args) -> None:
    """Account operand bytes riding a dispatch as runtime arguments
    (argument-fed lowerings ship the whole bundle per call; baked ones
    only the delta vector).  ``const_bytes`` is the precomputed size
    of per-wrapper-constant operands (bundle + reference pytree) so
    the per-dispatch cost is one small tree walk over ``args``."""
    import jax

    try:
        # inlined under an outer trace (vmap/jit): no host dispatch
        # happens here, so counting operand bytes would double-book
        # the outer dispatch's transfer
        if not jax.core.trace_state_clean():
            return
    except Exception:
        pass
    nb = const_bytes + trace.nbytes_of(args)
    metrics.counter(
        "transfer.bytes_to_device", unit="bytes",
        help="operand bytes shipped as runtime arguments",
    ).inc(nb)
    TRACER.annotate(bytes_to_device=nb, site=site)
