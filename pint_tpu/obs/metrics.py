"""Metrics registry: counters / gauges / histograms for the axon path.

Reference parity: none — the reference framework has no metrics
surface; this is TPU-service infrastructure (ROADMAP north-star:
"fast as the hardware allows" requires knowing where time and bytes
go).  Unlike the tracer (pint_tpu.obs.trace), metrics are ALWAYS on:
each is a lock-guarded scalar whose update costs are the same order as
the pre-obs ``GuardStats`` counters they subsume — the per-dispatch
cost stays inside the <2% guard budget bench.py asserts.

``snapshot()`` is the canonical telemetry read; it subsumes and
deprecates the bespoke ``runtime/guard.py::GuardStats.snapshot()``
(which is now a thin adapter over this registry, kept for its existing
consumers).  Canonical metric names and units are documented in
docs/observability.md:

==============================  =======  ==============================
name                            kind     meaning
==============================  =======  ==============================
dispatch.count                  counter  host calls through a dispatch
                                         chokepoint (cm.jit wrappers,
                                         guarded sharded steps)
dispatch.guarded                counter  ...of which ran under the
                                         guard supervisor
compile.traces                  counter  XLA (re)traces observed at the
                                         cm.jit chokepoint
compile.recompiles              counter  traces beyond the first per
                                         wrapper — MUST stay 0 across a
                                         refit loop (the r5 "refits are
                                         one dispatch" invariant;
                                         bench.py gates on it)
transfer.bytes_to_device        counter  operand bytes shipped as
                                         runtime arguments per dispatch
transport.baked_bytes_est       gauge    estimated baked-literal HLO
                                         bytes of the last
                                         baked-lowering module
transport.near_413              counter  baked modules whose estimate
                                         crossed the near-miss fraction
                                         of the transport's ~256 MB
                                         413 limit (a raised
                                         $PINT_TPU_BAKE_THRESHOLD is
                                         how you get here)
guard.retries                   counter  transient-failure retries
guard.timeouts                  counter  watchdog expirations
guard.transport_rejections      counter  deterministic 413-class
                                         refusals
guard.numerics_errors           counter  diagnosed non-finite refusals
guard.fallbacks                 counter  ladder rung drops
guard.watchdog_margin_s         gauge    last margin before timeout
guard.watchdog_margin_frac_min  gauge    min margin/timeout fraction
fallback.rung                   gauge    rung index that served the
                                         last laddered computation
fit.count                       counter  fit_toas invocations
ingest.count / ingest.toas      counter  ingest calls / TOAs ingested
serve.requests                  counter  submissions to the serving
                                         engine (pint_tpu/serve)
serve.completed                 counter  ...resolved successfully
serve.shed                      counter  deadline sheds (typed
                                         RequestRejected)
serve.rejected                  counter  bounded-queue rejections
serve.batches                   counter  dispatched micro-batches
serve.batch_occupancy           histo    live requests per batch
serve.latency_ms                histo    submit->result wall time
serve.queue_depth               gauge    admission-queue depth
serve.session.hits/misses/      counter  composition-session LRU
  evictions                              traffic (compiled layer)
serve.session.par_hits/         counter  per-par record LRU traffic
  par_misses/par_evictions               (lightweight host layer)
serve.session.pars_served       counter  distinct pars ever admitted
serve.session.pars              gauge    live par records
serve.session.compositions      gauge    live distinct compositions
serve.stack.distinct_pars       histo    DISTINCT pars vmapped per
                                         dispatched batch (stack
                                         occupancy, ISSUE 6)
serve.composition.C.pars/       counter  per-composition ledger (C =
  batches/compiles                       short composition id): pars
                                         joined, batches dispatched,
                                         XLA traces — compiles must
                                         stay at one per (bucket,
                                         capacity) per replica no
                                         matter how many pars join
serve.polyco.hits/misses        counter  per-par-record polyco spans
serve.fabric.routes/reroutes    counter  routing decisions / failed
                                         -batch re-routes
serve.fabric.spills             counter  affinity-set growth under
                                         saturation
serve.fabric.failures           counter  guard-class batch failures
serve.fabric.degraded/          counter  replica health transitions
  quarantines/readmits
serve.fabric.probes             counter  canary dispatches
serve.fabric.no_replica         counter  typed sheds with no live
                                         replica to route to
serve.replica.N.batches         counter  batches served by replica N
serve.replica.N.outstanding     gauge    queued+inflight batches
serve.replica.N.state           gauge    health-state string
==============================  =======  ==============================
"""

from __future__ import annotations

import math
import threading


class Counter:
    """Monotonic (between resets) thread-safe counter."""

    def __init__(self, name: str, unit: str = "", help: str = ""):
        self.name = name
        self.unit = unit
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1):
        with self._lock:
            self._value += n

    def reset(self):
        with self._lock:
            self._value = 0

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-value gauge; ``None`` means never set since reset."""

    def __init__(self, name: str, unit: str = "", help: str = ""):
        self.name = name
        self.unit = unit
        self.help = help
        self._lock = threading.Lock()
        self._value = None

    def set(self, v):
        with self._lock:
            self._value = v

    def set_min(self, v):
        """Keep the minimum of the current value and ``v``."""
        with self._lock:
            if self._value is None or v < self._value:
                self._value = v

    def reset(self):
        with self._lock:
            self._value = None

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Streaming summary (count/sum/min/max + log2 buckets) — enough
    to spot a bimodal dispatch-latency distribution (warm ~85 ms
    tunnel round-trips vs multi-second compiles) without keeping
    samples."""

    def __init__(self, name: str, unit: str = "", help: str = ""):
        self.name = name
        self.unit = unit
        self.help = help
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None
            self._buckets: dict[int, int] = {}

    def observe(self, v: float):
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            b = (
                -1074  # subnormal floor bucket
                if v <= 0.0
                else int(math.floor(math.log2(v)))
            )
            self._buckets[b] = self._buckets.get(b, 0) + 1

    @property
    def value(self) -> dict:
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "mean": (
                    self._sum / self._count if self._count else None
                ),
                "buckets_log2": dict(sorted(self._buckets.items())),
            }


class MetricsRegistry:
    """Get-or-create registry; one flat namespace of dotted names."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, cls, name: str, unit: str, help: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, unit, help)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str, unit: str = "", help: str = "") -> Counter:
        return self._get(Counter, name, unit, help)

    def gauge(self, name: str, unit: str = "", help: str = "") -> Gauge:
        return self._get(Gauge, name, unit, help)

    def histogram(self, name: str, unit: str = "",
                  help: str = "") -> Histogram:
        return self._get(Histogram, name, unit, help)

    def snapshot(self) -> dict:
        """All metric values keyed by canonical name — the telemetry
        read that subsumes GuardStats.snapshot() (bench.py's obs block
        and Fitter.flight_report consume this)."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.value for name, m in items}

    def reset(self, prefix: str = ""):
        """Reset metrics whose name starts with ``prefix`` (all, by
        default) — between bench phases / test cases."""
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            if name.startswith(prefix):
                m.reset()


#: the process-wide registry every chokepoint bumps
REGISTRY = MetricsRegistry()


def counter(name: str, unit: str = "", help: str = "") -> Counter:
    return REGISTRY.counter(name, unit, help)


def gauge(name: str, unit: str = "", help: str = "") -> Gauge:
    return REGISTRY.gauge(name, unit, help)


def histogram(name: str, unit: str = "", help: str = "") -> Histogram:
    return REGISTRY.histogram(name, unit, help)


def snapshot() -> dict:
    return REGISTRY.snapshot()


def reset(prefix: str = ""):
    REGISTRY.reset(prefix)
