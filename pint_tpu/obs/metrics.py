"""Metrics registry: counters / gauges / histograms for the axon path.

Reference parity: none — the reference framework has no metrics
surface; this is TPU-service infrastructure (ROADMAP north-star:
"fast as the hardware allows" requires knowing where time and bytes
go).  Unlike the tracer (pint_tpu.obs.trace), metrics are ALWAYS on:
each is a lock-guarded scalar whose update costs are the same order as
the pre-obs ``GuardStats`` counters they subsume — the per-dispatch
cost stays inside the <2% guard budget bench.py asserts.

``snapshot()`` is the canonical telemetry read; it subsumes and
deprecates the bespoke ``runtime/guard.py::GuardStats.snapshot()``
(which is now a thin adapter over this registry, kept for its existing
consumers).  Canonical metric names and units are documented in
docs/observability.md:

==============================  =======  ==============================
name                            kind     meaning
==============================  =======  ==============================
dispatch.count                  counter  host calls through a dispatch
                                         chokepoint (cm.jit wrappers,
                                         guarded sharded steps)
dispatch.guarded                counter  ...of which ran under the
                                         guard supervisor
compile.traces                  counter  XLA (re)traces observed at the
                                         cm.jit chokepoint
compile.recompiles              counter  traces beyond the first per
                                         wrapper — MUST stay 0 across a
                                         refit loop (the r5 "refits are
                                         one dispatch" invariant;
                                         bench.py gates on it)
transfer.bytes_to_device        counter  operand bytes shipped as
                                         runtime arguments per dispatch
transport.baked_bytes_est       gauge    estimated baked-literal HLO
                                         bytes of the last
                                         baked-lowering module
transport.near_413              counter  baked modules whose estimate
                                         crossed the near-miss fraction
                                         of the transport's ~256 MB
                                         413 limit (a raised
                                         $PINT_TPU_BAKE_THRESHOLD is
                                         how you get here)
guard.retries                   counter  transient-failure retries
guard.timeouts                  counter  watchdog expirations
guard.transport_rejections      counter  deterministic 413-class
                                         refusals
guard.numerics_errors           counter  diagnosed non-finite refusals
guard.fallbacks                 counter  ladder rung drops
guard.watchdog_margin_s         gauge    last margin before timeout
guard.watchdog_margin_frac_min  gauge    min margin/timeout fraction
fallback.rung                   gauge    rung index that served the
                                         last laddered computation
fit.count                       counter  fit_toas invocations
ingest.count / ingest.toas      counter  ingest calls / TOAs ingested
serve.requests                  counter  submissions to the serving
                                         engine (pint_tpu/serve)
serve.completed                 counter  ...resolved successfully
serve.shed                      counter  deadline sheds (typed
                                         RequestRejected)
serve.rejected                  counter  bounded-queue rejections
serve.batches                   counter  dispatched micro-batches
serve.batch_occupancy           histo    live requests per batch
serve.latency_ms                histo    submit->result wall time
serve.queue_depth               gauge    admission-queue depth
serve.session.hits/misses/      counter  composition-session LRU
  evictions                              traffic (compiled layer)
serve.session.par_hits/         counter  per-par record LRU traffic
  par_misses/par_evictions               (lightweight host layer)
serve.session.pars_served       counter  distinct pars ever admitted
serve.session.pars              gauge    live par records
serve.session.compositions      gauge    live distinct compositions
serve.stack.distinct_pars       histo    DISTINCT pars vmapped per
                                         dispatched batch (stack
                                         occupancy, ISSUE 6)
serve.composition.C.pars/       counter  per-composition ledger (C =
  batches/compiles                       short composition id): pars
                                         joined, batches dispatched,
                                         XLA traces — compiles must
                                         stay at one per (bucket,
                                         capacity) per replica no
                                         matter how many pars join
serve.polyco.hits/misses        counter  per-par-record polyco spans
serve.fabric.routes/reroutes    counter  routing decisions / failed
                                         -batch re-routes
serve.fabric.spills             counter  affinity-set growth under
                                         saturation
serve.fabric.failures           counter  guard-class batch failures
serve.fabric.degraded/          counter  replica health transitions
  quarantines/readmits
serve.fabric.probes             counter  canary dispatches
serve.fabric.no_replica         counter  typed sheds with no live
                                         replica to route to
serve.replica.N.batches         counter  batches served by replica N
serve.replica.N.outstanding     gauge    queued+inflight batches
serve.replica.N.state           gauge    health-state string
serve.latency.total             whisto   end-to-end submit->finish ms
                                         (sliding window; feeds
                                         stats()['p50_ms'/'p99_ms'])
serve.latency.stage.S           whisto   per-stage dwell ms, S one of
                                         :data:`STAGES` (ISSUE 17 —
                                         consecutive-stamp deltas)
serve.latency.exemplars         worst-k  slow-request reservoir: full
                                         stage vectors + flow ids
serve.shed_stage.R.S            counter  sheds of reason R whose LAST
                                         stamped stage was S (the
                                         shed-reason x stage table)
==============================  =======  ==============================
"""

from __future__ import annotations

import collections
import math
import threading
import time


class Counter:
    """Monotonic (between resets) thread-safe counter."""

    def __init__(self, name: str, unit: str = "", help: str = ""):
        self.name = name
        self.unit = unit
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1):
        with self._lock:
            self._value += n

    def reset(self):
        with self._lock:
            self._value = 0

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge:
    """Last-value gauge; ``None`` means never set since reset."""

    def __init__(self, name: str, unit: str = "", help: str = ""):
        self.name = name
        self.unit = unit
        self.help = help
        self._lock = threading.Lock()
        self._value = None

    def set(self, v):
        with self._lock:
            self._value = v

    def set_min(self, v):
        """Keep the minimum of the current value and ``v``."""
        with self._lock:
            if self._value is None or v < self._value:
                self._value = v

    def reset(self):
        with self._lock:
            self._value = None

    @property
    def value(self):
        with self._lock:
            return self._value


class Histogram:
    """Streaming summary (count/sum/min/max + log2 buckets) — enough
    to spot a bimodal dispatch-latency distribution (warm ~85 ms
    tunnel round-trips vs multi-second compiles) without keeping
    samples."""

    def __init__(self, name: str, unit: str = "", help: str = ""):
        self.name = name
        self.unit = unit
        self.help = help
        self._lock = threading.Lock()
        self.reset()

    def reset(self):
        with self._lock:
            self._count = 0
            self._sum = 0.0
            self._min = None
            self._max = None
            self._buckets: dict[int, int] = {}

    def observe(self, v: float):
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            b = (
                -1074  # subnormal floor bucket
                if v <= 0.0
                else int(math.floor(math.log2(v)))
            )
            self._buckets[b] = self._buckets.get(b, 0) + 1

    @property
    def value(self) -> dict:
        with self._lock:
            return {
                "count": self._count,
                "sum": self._sum,
                "min": self._min,
                "max": self._max,
                "mean": (
                    self._sum / self._count if self._count else None
                ),
                "buckets_log2": dict(sorted(self._buckets.items())),
            }


#: canonical serving-pipeline stage order (ISSUE 17).  Every stamp a
#: request or batch record carries is keyed by one of these; the order
#: IS the monotonicity contract (tools/chaos.py asserts it per leg).
#: ``submit``..``close`` live on the engine's per-request ``_Pending``,
#: ``route``..``fence`` on the fabric's ``BatchWork``, ``finish`` is
#: stamped at response resolution.  Host-only ops (predict) legally
#: skip the fabric stages — completeness is per-path, monotonicity is
#: universal.
STAGES = (
    "submit", "admit", "close", "route", "queue",
    "place", "dispatch", "fence", "finish",
)


def last_stage(stages: dict | None) -> str:
    """The latest canonical stage a record reached (its last stamp in
    :data:`STAGES` order); ``"none"`` for an empty/missing vector."""
    out = "none"
    if stages:
        for s in STAGES:
            if s in stages:
                out = s
    return out


def note_shed_stage(reason: str, stages: dict | None):
    """Bump the shed-reason x stage cell — called at every typed-shed
    site (queue-full, quota, deadline, deadline-late, no-replica,
    shutdown, streams) so ``stats()['latency']['shed_stages']`` shows
    WHERE in the pipeline each rejection class strikes."""
    REGISTRY.counter(
        f"serve.shed_stage.{reason}.{last_stage(stages)}"
    ).inc()


class WindowHistogram:
    """Sliding-window percentile estimator with bounded memory.

    Replaces the flat 4096-deque in ``TimingEngine.stats()`` (ISSUE
    17): that deque conflated warmup and steady state across long
    runs — a sample observed hours ago weighed the same as one from
    the last second.  This keeps ``(monotonic_t, value)`` pairs in a
    deque bounded BOTH ways: ``maxlen`` caps memory, ``window_s``
    expires old samples at observe/read time.  ``percentile`` uses the
    same sorted-index formula the deque-era ``stats()`` used
    (``sorted[min(n-1, int(q*n))]``), so offered-load sweeps that
    pinned those semantics read identical numbers over a fresh window;
    ``reset()`` empties the window exactly like clearing the deque
    (``TimingEngine.reset_stats()`` reaches it through the registry's
    ``serve.`` prefix reset)."""

    def __init__(self, name: str, unit: str = "", help: str = "", *,
                 window_s: float = 300.0, maxlen: int = 4096):
        self.name = name
        self.unit = unit
        self.help = help
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._samples: collections.deque = collections.deque(
            maxlen=maxlen
        )

    def _prune(self, now: float):
        # lock held by caller
        horizon = now - self.window_s
        q = self._samples
        while q and q[0][0] < horizon:
            q.popleft()

    def observe(self, v: float, now: float | None = None):
        t = time.monotonic() if now is None else now
        with self._lock:
            self._prune(t)
            self._samples.append((t, float(v)))

    def reset(self):
        with self._lock:
            self._samples.clear()

    def _window(self) -> list:
        with self._lock:
            self._prune(time.monotonic())
            return [v for _, v in self._samples]

    def percentile(self, q: float):
        """The deque-era quantile: sorted in-window samples indexed at
        ``min(n-1, int(q*n))``; ``None`` on an empty window."""
        vals = sorted(self._window())
        if not vals:
            return None
        return vals[min(len(vals) - 1, int(q * len(vals)))]

    @property
    def count(self) -> int:
        return len(self._window())

    @property
    def value(self) -> dict:
        vals = sorted(self._window())
        n = len(vals)
        return {
            "count": n,
            "p50": vals[min(n - 1, int(0.50 * n))] if n else None,
            "p99": vals[min(n - 1, int(0.99 * n))] if n else None,
            "max": vals[-1] if n else None,
        }


class ExemplarReservoir:
    """Bounded worst-k slow-request reservoir (ISSUE 17).

    Keeps the ``k`` slowest requests of the sliding window, each with
    its full stage vector and flow id, so "why was p99 slow" has named
    exemplars (flight_report prints them) instead of one anonymous
    percentile.  ``offer`` is O(k) under one lock — k is small (8) and
    the call sits on the finish path next to the existing histogram
    observes, inside the <2% attribution budget bench.py gates."""

    def __init__(self, name: str, unit: str = "", help: str = "", *,
                 k: int = 8, window_s: float = 300.0):
        self.name = name
        self.unit = unit
        self.help = help
        self.k = int(k)
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._worst: list[dict] = []  # sorted ascending by latency

    def offer(self, lat_ms: float, flow: str,
              stages: dict | None = None,
              now: float | None = None):
        t = time.monotonic() if now is None else now
        with self._lock:
            horizon = t - self.window_s
            keep = [e for e in self._worst if e["t"] >= horizon]
            if len(keep) >= self.k and lat_ms <= keep[0]["lat_ms"]:
                self._worst = keep
                return
            keep.append({
                "t": t, "lat_ms": float(lat_ms), "flow": flow,
                "stages": dict(stages) if stages else {},
            })
            keep.sort(key=lambda e: e["lat_ms"])
            self._worst = keep[-self.k:]

    def reset(self):
        with self._lock:
            self._worst.clear()

    @property
    def value(self) -> list[dict]:
        """Worst-first exemplars still inside the window (each without
        the internal ``t`` key — latency, flow id, stage vector)."""
        with self._lock:
            horizon = time.monotonic() - self.window_s
            self._worst = [
                e for e in self._worst if e["t"] >= horizon
            ]
            return [
                {k: v for k, v in e.items() if k != "t"}
                for e in reversed(self._worst)
            ]


class MetricsRegistry:
    """Get-or-create registry; one flat namespace of dotted names."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get(self, cls, name: str, unit: str, help: str):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, unit, help)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str, unit: str = "", help: str = "") -> Counter:
        return self._get(Counter, name, unit, help)

    def gauge(self, name: str, unit: str = "", help: str = "") -> Gauge:
        return self._get(Gauge, name, unit, help)

    def histogram(self, name: str, unit: str = "",
                  help: str = "") -> Histogram:
        return self._get(Histogram, name, unit, help)

    def window_histogram(self, name: str, unit: str = "",
                         help: str = "") -> WindowHistogram:
        return self._get(WindowHistogram, name, unit, help)

    def exemplars(self, name: str, unit: str = "",
                  help: str = "") -> ExemplarReservoir:
        return self._get(ExemplarReservoir, name, unit, help)

    def snapshot(self) -> dict:
        """All metric values keyed by canonical name — the telemetry
        read that subsumes GuardStats.snapshot() (bench.py's obs block
        and Fitter.flight_report consume this)."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.value for name, m in items}

    def reset(self, prefix: str = ""):
        """Reset metrics whose name starts with ``prefix`` (all, by
        default) — between bench phases / test cases."""
        with self._lock:
            items = list(self._metrics.items())
        for name, m in items:
            if name.startswith(prefix):
                m.reset()


#: the process-wide registry every chokepoint bumps
REGISTRY = MetricsRegistry()


def counter(name: str, unit: str = "", help: str = "") -> Counter:
    return REGISTRY.counter(name, unit, help)


def gauge(name: str, unit: str = "", help: str = "") -> Gauge:
    return REGISTRY.gauge(name, unit, help)


def histogram(name: str, unit: str = "", help: str = "") -> Histogram:
    return REGISTRY.histogram(name, unit, help)


def window_histogram(name: str, unit: str = "",
                     help: str = "") -> WindowHistogram:
    return REGISTRY.window_histogram(name, unit, help)


def exemplars(name: str, unit: str = "",
              help: str = "") -> ExemplarReservoir:
    return REGISTRY.exemplars(name, unit, help)


def snapshot() -> dict:
    return REGISTRY.snapshot()


def reset(prefix: str = ""):
    REGISTRY.reset(prefix)
