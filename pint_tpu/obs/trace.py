"""Dispatch flight recorder: nested, thread-safe wall-clock spans.

Reference parity: the reference framework (src/pint/logging.py plus
ad-hoc cProfile scripts) has no structured tracing; this module is the
TPU-first replacement.  Every hard-won axon fact in CLAUDE.md — the
~85 ms tunnel round-trip, silent recompiles on bundle swaps, HTTP 413
rejections near 256 MB, fallback-ladder rungs — was discovered by
one-off timing scripts after something went wrong.  The tracer makes
those signals first-class: the dispatch chokepoints
(models/timing_model.py::CompiledModel.jit via
runtime/guard.py::dispatch_guard), the guard supervisor
(guarded_call), the fallback ladder, every fitter's ``fit_toas`` and
the TOA ingest pipeline all record spans here, so *where the time and
bytes go* across compile -> transfer -> dispatch -> fence is a
recorded artifact (export via pint_tpu.obs.export, CLI summary via
tools/traceview.py) instead of archaeology.

Design constraints:

- **off by default, ~free when off**: ``Tracer.span`` returns a shared
  no-op handle after ONE attribute check when ``enabled`` is False —
  no allocation, no lock, no clock read.  The chokepoints sit on the
  per-dispatch hot path whose total guard budget is <2% of the
  north-star chain dispatch (bench.py asserts it); tracing must not
  move that needle when off.  Enable with :func:`enable`, the scoped
  :func:`tracing` context manager, or ``$PINT_TPU_TRACE=1``.
- **monotonic clocks**: all timestamps are ``time.perf_counter()`` —
  never wall-clock, which steps under NTP.
- **explicit device fencing**: jax dispatch is ASYNC — a span closed
  without fencing records dispatch latency, not compute.
  :meth:`Tracer.fence` block_until_ready's every array leaf of an
  arbitrary pytree (the shared :func:`fence_pytree`, which also fixes
  profiler.py::PhaseTimer's fence for nested containers) inside a
  ``fence``-category span, so the time the host spent *waiting on the
  device* is itself visible in the trace.
- **thread-safe**: the guard's watchdog runs attempts in worker
  threads (runtime/guard.py::_attempt); the span stack is thread-local
  and :meth:`Tracer.under` re-parents a worker thread's spans beneath
  the caller's attempt span.

Span taxonomy (category strings; full table in docs/observability.md):
``fit`` > ``rung`` > ``compile``/``dispatch`` > ``attempt`` >
``fence``/``validate``, plus ``ingest``, ``transfer``, ``phase``
(profiler.py::PhaseTimer) and instant events ``recompile``, ``retry``,
``watchdog-timeout``, ``transport-rejection``, ``fallback``,
``numerics-error``, ``near-413``.

Flow stitching (ISSUE 17): spans and events carry an optional
``flow`` id — the serving ``request_id`` or a batch id — that survives
cross-thread handoffs.  A span opened without an explicit ``flow=``
INHERITS the enclosing span's flow (including a worker-thread span
re-parented via :meth:`Tracer.under`), so one request's path submit ->
collector -> router -> replica dispatcher -> fencer -> done-callback
is one connected arc.  obs/export.py turns same-flow spans into
Chrome-trace flow events (``s``/``t``/``f``) that Perfetto renders as
arrows across thread tracks; :meth:`Tracer.name_thread` labels the
tracks (``M`` metadata records).
"""

from __future__ import annotations

import contextlib
import itertools
import os
import threading
import time
from dataclasses import dataclass, field


@dataclass
class Span:
    """One completed (or open) wall-clock interval."""

    name: str
    cat: str  # taxonomy category (module docstring)
    t0: float  # perf_counter seconds
    span_id: int
    parent_id: int | None
    thread: int
    attrs: dict = field(default_factory=dict)
    t1: float | None = None
    flow: str | None = None  # request/batch id stitching thread handoffs

    @property
    def dur_s(self) -> float:
        return (self.t1 if self.t1 is not None else self.t0) - self.t0


@dataclass
class Event:
    """An instant (zero-duration) marker: recompile, retry, fallback."""

    name: str
    cat: str
    t: float
    parent_id: int | None
    thread: int
    attrs: dict = field(default_factory=dict)
    flow: str | None = None


def nbytes_of(value) -> int:
    """Total device/host bytes of every array leaf of a pytree (leaves
    without ``.nbytes`` — scalars, strings — count zero)."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(value):
        nb = getattr(leaf, "nbytes", None)
        if nb is not None:
            total += int(nb)
    return total


def fence_pytree(value):
    """block_until_ready EVERY device-array leaf of an arbitrary
    pytree (nested dicts/tuples/namedtuples/registered nodes).

    The shared fence used by :meth:`Tracer.fence` and
    profiler.py::PhaseTimer (whose pre-obs ``_Phase.fence`` only
    fenced leaves it could reach by hand).  ``jax.block_until_ready``
    tree-maps over the whole structure; the manual fallback covers jax
    versions without it and non-pytree objects carrying arrays in
    attributes is out of scope (register them as pytrees instead)."""
    import jax

    try:
        jax.block_until_ready(value)
    except Exception:
        for leaf in jax.tree_util.tree_leaves(value):
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()
    return value


class _NoopHandle:
    """The shared disabled-path span handle: every method a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NOOP = _NoopHandle()


class _SpanHandle:
    """Context manager closing one span; ``set(**attrs)`` annotates."""

    __slots__ = ("_tracer", "sp")

    def __init__(self, tracer: "Tracer", sp: Span):
        self._tracer = tracer
        self.sp = sp

    def __enter__(self):
        return self

    def set(self, **attrs):
        self.sp.attrs.update(attrs)
        return self

    def __exit__(self, etype, evalue, tb):
        sp = self.sp
        sp.t1 = time.perf_counter()
        if etype is not None:
            sp.attrs.setdefault(
                "error", f"{etype.__name__}: {evalue}"
            )
        tr = self._tracer
        stack = tr._stack()
        # pop by identity: robust to mispaired exits across re-entrant
        # guard retries
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is sp:
                del stack[i]
                break
        tr._record_span(sp)
        return False


class Tracer:
    """Thread-safe span/event recorder with a bounded buffer.

    ``capacity`` bounds the finished-span and event buffers; past it,
    new records are counted in ``dropped`` instead of silently growing
    (a week-long service run must not OOM on its own telemetry)."""

    def __init__(self, capacity: int = 200_000):
        self.enabled = False
        self.capacity = capacity
        self.dropped = 0
        self._lock = threading.Lock()
        self._spans: list[Span] = []
        self._events: list[Event] = []
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._thread_names: dict[int, str] = {}

    # -- span stack (thread-local) ---------------------------------------
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_span(self) -> Span | None:
        st = getattr(self._tls, "stack", None)
        return st[-1] if st else None

    def current_span_id(self) -> int | None:
        sp = self.current_span()
        return None if sp is None else sp.span_id

    @contextlib.contextmanager
    def under(self, span: "Span | _SpanHandle | None"):
        """Re-parent this THREAD's spans beneath ``span`` for the
        with-block — used by the guard's watchdog worker so attempt
        internals nest under the caller thread's attempt span."""
        if not self.enabled or span is None:
            yield
            return
        if isinstance(span, _SpanHandle):
            span = span.sp
        if not isinstance(span, Span):
            # a no-op handle (tracing toggled between span() and here):
            # never seed the stack with something lacking span_id
            yield
            return
        stack = self._stack()
        stack.append(span)
        try:
            yield
        finally:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i] is span:
                    del stack[i]
                    break

    # -- recording -------------------------------------------------------
    def _record_span(self, sp: Span):
        with self._lock:
            if len(self._spans) < self.capacity:
                self._spans.append(sp)
            else:
                self.dropped += 1

    def name_thread(self, name: str):
        """Label the CALLING thread's track in exports (one write per
        thread ident; Perfetto ``M``/thread_name metadata).  Safe to
        call unconditionally — a dict store, no lock."""
        self._thread_names[threading.get_ident()] = name

    def thread_names(self) -> dict[int, str]:
        return dict(self._thread_names)

    def span(self, name: str, cat: str = "host",
             flow: str | None = None, **attrs):
        """Open a span; use as a context manager.  The disabled path is
        ONE attribute check returning a shared no-op handle.  ``flow``
        stitches the span into a cross-thread request arc; omitted, it
        inherits the enclosing span's flow (so :meth:`under` carries
        the id onto worker threads)."""
        if not self.enabled:
            return _NOOP
        stack = self._stack()
        parent = stack[-1] if stack else None
        sp = Span(
            name=name,
            cat=cat,
            t0=time.perf_counter(),
            span_id=next(self._ids),
            parent_id=parent.span_id if parent else None,
            thread=threading.get_ident(),
            attrs=dict(attrs),
            flow=flow if flow is not None
            else (parent.flow if parent else None),
        )
        stack.append(sp)
        return _SpanHandle(self, sp)

    def event(self, name: str, cat: str = "event",
              flow: str | None = None, **attrs):
        """Record an instant event under the current span (no-op when
        disabled — counters for always-on accounting live in
        pint_tpu.obs.metrics, not here)."""
        if not self.enabled:
            return
        sp = self.current_span()
        ev = Event(
            name=name,
            cat=cat,
            t=time.perf_counter(),
            parent_id=None if sp is None else sp.span_id,
            thread=threading.get_ident(),
            attrs=dict(attrs),
            flow=flow if flow is not None
            else (sp.flow if sp is not None else None),
        )
        with self._lock:
            if len(self._events) < self.capacity:
                self._events.append(ev)
            else:
                self.dropped += 1

    def annotate(self, **attrs):
        """Attach attributes to the current span, if any."""
        if not self.enabled:
            return
        sp = self.current_span()
        if sp is not None:
            sp.attrs.update(attrs)

    def attach_log(self, level: str, message: str, fields=None):
        """Attach a structured log record to the current span (called
        by pint_tpu.logging's dedup filter on every record it passes,
        so a span carries the warnings emitted while it was open)."""
        if not self.enabled:
            return
        sp = self.current_span()
        if sp is not None:
            entry = {"level": level, "message": message}
            if fields:
                entry["fields"] = dict(fields)
            sp.attrs.setdefault("logs", []).append(entry)

    def fence(self, value, name: str = "fence", **attrs):
        """block_until_ready every array leaf of ``value`` inside a
        ``fence`` span (async dispatch must never be timed as complete
        without this); fences even when tracing is disabled so callers
        can rely on the synchronization semantics."""
        if not self.enabled:
            return fence_pytree(value)
        with self.span(name, "fence", bytes=nbytes_of(value), **attrs):
            return fence_pytree(value)

    # -- introspection / lifecycle ---------------------------------------
    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def events(self) -> list[Event]:
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._spans.clear()
            self._events.clear()
            self.dropped = 0


#: the process-wide tracer every chokepoint records into
TRACER = Tracer()

if os.environ.get("PINT_TPU_TRACE", "") not in ("", "0", "off"):
    TRACER.enabled = True


def enable():
    TRACER.enabled = True


def disable():
    TRACER.enabled = False


def enabled() -> bool:
    return TRACER.enabled


def current_span_id() -> int | None:
    return TRACER.current_span_id()


@contextlib.contextmanager
def tracing(clear: bool = False):
    """Scoped enablement: ``with tracing(): fitter.fit_toas()`` records
    the fit; ``clear=True`` starts from an empty buffer."""
    if clear:
        TRACER.clear()
    prev = TRACER.enabled
    TRACER.enabled = True
    try:
        yield TRACER
    finally:
        TRACER.enabled = prev
