"""Exporters for the dispatch flight recorder.

Three consumers, three shapes (reference parity: none — the reference
framework has no trace surface; this complements
``profiler.device_trace``'s XLA-internal profile with the framework's
own host-side span view, which survives the axon tunnel where the
on-chip profiler often cannot run):

- :func:`to_chrome_trace` / :func:`write_chrome_trace` — Chrome Trace
  Event Format JSON, loadable in Perfetto (https://ui.perfetto.dev)
  and ``chrome://tracing``; :func:`load_chrome_trace` round-trips it
  back into Span/Event objects (tools/traceview.py and the exporter
  tests build on this).
- :func:`summary` — a small flat dict (recompiles, bytes to device,
  max span) merged into bench.py's single JSON line.
- :func:`flight_report` — the human post-mortem attached to every
  fitter (``Fitter.flight_report()``, sibling of PR 1's
  ``guard_report``): top spans, recompiles, bytes, rung history.
"""

from __future__ import annotations

import json
import os
from collections import defaultdict

from pint_tpu.obs import metrics as _metrics
from pint_tpu.obs import trace as _trace
from pint_tpu.obs.trace import Event, Span


def to_chrome_trace(spans=None, events=None, tracer=None) -> dict:
    """Chrome Trace Event Format dict (Perfetto-loadable).

    Spans become complete ('X') events with microsecond timestamps on
    the perf_counter timebase; instant events become 'i' markers; the
    full metrics snapshot rides in ``otherData`` so one file carries
    both signals.

    Flow stitching (ISSUE 17): spans carrying a ``flow`` id
    additionally emit Chrome-trace flow events — 's' (start) on the
    earliest span of the flow, 't' (step) on each middle span, 'f'
    (end, ``bp:"e"``) on the last — all sharing the flow id, each
    timestamped INSIDE its enclosing 'X' slice (midpoint) so
    Perfetto binds it to that slice and renders one request as a
    connected arc across thread tracks.  Threads named via
    ``Tracer.name_thread`` emit 'M' thread_name metadata so the
    tracks read ``serve-collector`` / ``replica r0 fence`` instead of
    bare tids."""
    tracer = tracer or _trace.TRACER
    spans = tracer.spans() if spans is None else spans
    events = tracer.events() if events is None else events
    pid = os.getpid()
    out = []
    flows: dict = {}
    for sp in spans:
        t1 = sp.t1 if sp.t1 is not None else sp.t0
        args = {
            "span_id": sp.span_id,
            "parent_id": sp.parent_id,
            **sp.attrs,
        }
        if sp.flow is not None:
            args["flow"] = sp.flow
            flows.setdefault(sp.flow, []).append(sp)
        out.append({
            "ph": "X",
            "name": sp.name,
            "cat": sp.cat,
            "ts": sp.t0 * 1e6,
            "dur": (t1 - sp.t0) * 1e6,
            "pid": pid,
            "tid": sp.thread,
            "args": args,
        })
    for ev in events:
        args = {"parent_id": ev.parent_id, **ev.attrs}
        if ev.flow is not None:
            args["flow"] = ev.flow
        out.append({
            "ph": "i",
            "s": "t",  # thread-scoped instant
            "name": ev.name,
            "cat": ev.cat,
            "ts": ev.t * 1e6,
            "pid": pid,
            "tid": ev.thread,
            "args": args,
        })
    # derived flow arcs: NOT round-tripped by load_chrome_trace (the
    # span 'flow' arg is the source of truth; these exist for the
    # Perfetto renderer)
    for fid, group in flows.items():
        group.sort(key=lambda sp: sp.t0)
        for i, sp in enumerate(group):
            t1 = sp.t1 if sp.t1 is not None else sp.t0
            rec = {
                "ph": "s" if i == 0 else
                      "f" if i == len(group) - 1 else "t",
                "id": fid,
                "name": f"flow:{fid}",
                "cat": "flow",
                "ts": (sp.t0 + t1) / 2 * 1e6,
                "pid": pid,
                "tid": sp.thread,
            }
            if rec["ph"] == "f":
                rec["bp"] = "e"  # bind to the enclosing slice
            out.append(rec)
    for tid, tname in tracer.thread_names().items():
        out.append({
            "ph": "M",
            "name": "thread_name",
            "pid": pid,
            "tid": tid,
            "args": {"name": tname},
        })
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "metrics": _metrics.snapshot(),
            "dropped": tracer.dropped,
        },
    }


def write_chrome_trace(path: str, spans=None, events=None,
                       tracer=None) -> str:
    """Serialize the trace to ``path``; returns the path."""
    doc = to_chrome_trace(spans=spans, events=events, tracer=tracer)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def load_chrome_trace(source) -> tuple[list, list]:
    """Round-trip a Chrome-trace dict / JSON file path back into
    ``(spans, events)`` — the reconstruction tools/traceview.py and
    the exporter tests run on."""
    if isinstance(source, str):
        with open(source) as f:
            doc = json.load(f)
    else:
        doc = source
    spans, events = [], []
    for rec in doc.get("traceEvents", []):
        args = dict(rec.get("args", {}))
        if rec.get("ph") == "X":
            t0 = rec["ts"] / 1e6
            spans.append(Span(
                name=rec["name"],
                cat=rec.get("cat", "host"),
                t0=t0,
                t1=t0 + rec.get("dur", 0.0) / 1e6,
                span_id=args.pop("span_id", None),
                parent_id=args.pop("parent_id", None),
                thread=rec.get("tid", 0),
                flow=args.pop("flow", None),
                attrs=args,
            ))
        elif rec.get("ph") == "i":
            events.append(Event(
                name=rec["name"],
                cat=rec.get("cat", "event"),
                t=rec["ts"] / 1e6,
                parent_id=args.pop("parent_id", None),
                thread=rec.get("tid", 0),
                flow=args.pop("flow", None),
                attrs=args,
            ))
        # 's'/'t'/'f' flow arcs and 'M' metadata are DERIVED from the
        # span/event records above — skipped on load (the 'flow' arg
        # restores Span.flow/Event.flow losslessly)
    return spans, events


def _by_name(spans):
    """Aggregate spans by name: (total_s, count, max_s), descending."""
    agg = defaultdict(lambda: [0.0, 0, 0.0])
    for sp in spans:
        a = agg[f"{sp.cat}:{sp.name}"]
        a[0] += sp.dur_s
        a[1] += 1
        a[2] = max(a[2], sp.dur_s)
    return sorted(agg.items(), key=lambda kv: kv[1][0], reverse=True)


def summary(tracer=None) -> dict:
    """The one-line telemetry dict bench.py folds into its JSON output
    next to the guard block: dispatch/recompile counts, bytes to
    device, and the largest recorded span."""
    tracer = tracer or _trace.TRACER
    snap = _metrics.snapshot()
    spans = tracer.spans()
    max_span = max(spans, key=lambda sp: sp.dur_s, default=None)
    return {
        "dispatches": snap.get("dispatch.count", 0),
        "recompiles": snap.get("compile.recompiles", 0),
        "traces": snap.get("compile.traces", 0),
        "bytes_to_device": snap.get("transfer.bytes_to_device", 0),
        "near_413": snap.get("transport.near_413", 0),
        "spans": len(spans),
        "max_span_ms": (
            None if max_span is None
            else round(max_span.dur_s * 1e3, 3)
        ),
        "max_span": None if max_span is None else max_span.name,
        # cold-path counters (r6): ingest-cache outcomes + parallel
        # -ingest degradations, so the driver-tracked line shows the
        # cache/pool behaving (zeros when the run never ingested from
        # files — e.g. the synthetic bench)
        "ingest_cache_hits": snap.get("ingest.cache.hits", 0),
        "ingest_cache_incremental": snap.get(
            "ingest.cache.incremental", 0
        ),
        "ingest_parallel_degrades": snap.get(
            "ingest.parallel.degrades", 0
        ),
    }


def flight_report(tracer=None, guard_report=None, top: int = 12) -> str:
    """Human-readable post-mortem of the recorded flight.

    Works with tracing disabled too (metrics are always on): the span
    section then just points at how to enable the recorder."""
    tracer = tracer or _trace.TRACER
    snap = _metrics.snapshot()
    spans = tracer.spans()
    events = tracer.events()
    lines = ["== flight report =="]

    if guard_report is not None:
        lines.append(
            f"served by rung {guard_report.rung!r} "
            f"(index {guard_report.rung_index}) at {guard_report.site}"
        )
        for rung, err in guard_report.history:
            lines.append(f"  tripped {rung!r}: {err}")

    lines.append(
        "dispatches={d} (guarded {g})  traces={t}  recompiles={r}  "
        "bytes_to_device={b}".format(
            d=snap.get("dispatch.count", 0),
            g=snap.get("dispatch.guarded", 0),
            t=snap.get("compile.traces", 0),
            r=snap.get("compile.recompiles", 0),
            b=snap.get("transfer.bytes_to_device", 0),
        )
    )
    guard_bits = {
        k.split(".", 1)[1]: v
        for k, v in snap.items()
        if k.startswith("guard.") and v not in (0, None)
    }
    if guard_bits:
        lines.append(
            "guard: " + "  ".join(
                f"{k}={v}" for k, v in sorted(guard_bits.items())
            )
        )
    if snap.get("transport.near_413", 0):
        lines.append(
            f"transport: {snap['transport.near_413']} baked module(s) "
            "neared the ~256 MB 413 limit (lower "
            "$PINT_TPU_BAKE_THRESHOLD; docs/observability.md)"
        )
    fabric_bits = {
        k.split(".", 2)[2]: v
        for k, v in snap.items()
        if k.startswith("serve.fabric.") and v not in (0, None)
    }
    if fabric_bits:
        lines.append(
            "fabric: " + "  ".join(
                f"{k}={v}" for k, v in sorted(fabric_bits.items())
            )
        )
    # streaming sessions (ISSUE 17 satellite): append ladder counts,
    # drift rollbacks (drift_fallback IS the rollback signal), alerts
    stream_bits = {
        k.split(".", 2)[2]: v
        for k, v in snap.items()
        if k.startswith("serve.stream.") and v not in (0, None)
    }
    if stream_bits:
        lines.append(
            "stream: " + "  ".join(
                f"{k}={v}" for k, v in sorted(stream_bits.items())
            )
        )
    # elastic fleet: reshape count + last-reshape duration + mid-drain
    # queue flushes (serve.fabric.drain_flushes reported above)
    elastic_bits = {
        k.split(".", 2)[2]: v
        for k, v in snap.items()
        if k.startswith("serve.elastic.")
        and not isinstance(v, dict) and v not in (0, None)
    }
    if elastic_bits:
        lines.append(
            "elastic: " + "  ".join(
                f"{k}={v}" for k, v in sorted(elastic_bits.items())
            )
        )
    # background jobs (ISSUE 20): the preemptible class's lifecycle
    # counters — quanta served, yields to interactive pressure,
    # checkpoint/restore traffic, quantum faults
    job_bits = {
        k.split(".", 2)[2]: v
        for k, v in snap.items()
        if k.startswith("serve.jobs.")
        and not isinstance(v, dict) and v not in (0, None)
    }
    if job_bits:
        lines.append(
            "background jobs: " + "  ".join(
                f"{k}={v}" for k, v in sorted(job_bits.items())
            )
        )
    # slow-request exemplars: the window's worst-k flights with their
    # last completed stage (full stage vectors in engine stats())
    exemplars = snap.get("serve.latency.exemplars") or []
    if exemplars:
        lines.append("slowest requests (window):")
        for ex in exemplars[:top]:
            stages = ex.get("stages") or {}
            last = _metrics.last_stage(stages)
            lines.append(
                f"  {ex.get('lat_ms', 0.0):>9.2f} ms  "
                f"flow={ex.get('flow', '?')}  last={last}"
            )
    # per-composition population breakdown (ISSUE 6): pars joined,
    # batches dispatched, XLA compiles — per composition id
    comp_bits = sorted(
        (k.split(".")[2], k.split(".", 3)[3], v)
        for k, v in snap.items()
        if k.startswith("serve.composition.") and v not in (None, 0)
    )
    if comp_bits:
        pop = {
            k: snap.get(f"serve.session.{k}")
            for k in ("pars_served", "pars", "compositions")
        }
        pop_txt = "  ".join(
            f"{k}={v}" for k, v in pop.items() if v not in (None, 0)
        )
        lines.append(f"population: {pop_txt}".rstrip())
        per = defaultdict(list)
        for cid, field, v in comp_bits:
            per[cid].append(f"{field}={v}")
        lines.append(
            "compositions: " + "  ".join(
                f"{cid}[{' '.join(sorted(fields))}]"
                for cid, fields in sorted(per.items())
            )
        )
    replica_bits = sorted(
        (k.split(".")[2], k.split(".", 3)[3], v)
        for k, v in snap.items()
        if k.startswith("serve.replica.") and v not in (None, 0)
    )
    if replica_bits:
        per = defaultdict(list)
        for rid, field, v in replica_bits:
            per[rid].append(f"{field}={v}")
        lines.append(
            "replicas: " + "  ".join(
                f"r{rid}[{' '.join(fields)}]"
                for rid, fields in sorted(
                    # rids are numeric in production but test doubles
                    # register arbitrary strings — sort those after
                    per.items(),
                    key=lambda kv: (
                        (0, int(kv[0]), "") if kv[0].isdigit()
                        else (1, 0, kv[0])
                    ),
                )
            )
        )

    if not spans:
        lines.append(
            "no spans recorded — enable the recorder with "
            "pint_tpu.obs.trace.enable() or PINT_TPU_TRACE=1"
        )
    else:
        lines.append(
            f"{len(spans)} spans"
            + (f" ({tracer.dropped} dropped)" if tracer.dropped else "")
        )
        lines.append(
            f"  {'span':<40}{'calls':>7}{'total s':>10}{'max ms':>10}"
        )
        for name, (tot, n, mx) in _by_name(spans)[:top]:
            lines.append(
                f"  {name:<40}{n:>7}{tot:>10.3f}{mx * 1e3:>10.2f}"
            )

    interesting = [
        ev for ev in events
        if ev.cat in ("compile", "guard", "transport", "fabric")
        or ev.name in ("recompile", "fallback", "near-413")
    ]
    if interesting:
        lines.append("events:")
        for ev in interesting[-top:]:
            attrs = " ".join(
                f"{k}={v}" for k, v in ev.attrs.items()
            )
            lines.append(f"  {ev.name} [{ev.cat}] {attrs}".rstrip())
    return "\n".join(lines)
