"""pint_tpu — a TPU-native pulsar-timing framework.

Re-implements the capabilities of PINT (reference: mhvk/PINT, surveyed in
``SURVEY.md``) as a unit-free, pure-functional JAX core: timing-model
components compile to ``(params, toa_bundle) -> phase`` kernels that jit,
vmap over pulsars, and shard over the TOA axis of a ``jax.sharding.Mesh``;
fitters run XLA Cholesky / SVD on device; absolute time is carried as
two-part values (double-double) so pulse phase is tracked to sub-ns over
decades without float128 (which TPUs do not have).

Layering (cf. SURVEY.md §1): ops (numerics kernels) → timebase (host exact
time) → io / observatories / ephemeris → toas → models → residuals →
fitting → parallel.
"""

from pint_tpu._version import __version__

# x64 must be on before any jnp array is created: absolute-time arithmetic
# relies on f64 pairs (see pint_tpu.ops.dd).
import jax as _jax

_jax.config.update("jax_enable_x64", True)

# persistent XLA compilation cache (runtime/compile_cache.py): a warm
# process start reuses the previous run's compiled executables instead
# of paying the 32-43 s remote first-fit compile again.  Best-effort:
# opt out with PINT_TPU_COMPILE_CACHE=0; failures downgrade to jax's
# normal in-memory-only behavior.
from pint_tpu.runtime import compile_cache as _compile_cache

_compile_cache.enable()

__all__ = ["__version__"]
