// Native kernels for host-side ingest hot paths.
//
// TPU-native replacement for the C-extension capabilities the reference
// leans on (astropy's C time parsers; SURVEY.md §2 native-capability
// table row 4): the per-TOA exact decimal MJD parse is the dominant
// cost of loading large tim files in pure Python (one decimal.Decimal
// round-trip per TOA).  Here: batched parse of decimal MJD strings into
// (int day, double-double seconds-of-day), using error-free transforms
// (two_sum / fma two_prod) so the result matches the Python
// Decimal-exact path to ~1e-32 relative (far below the 1e-28 s
// resolution the timebase claims).
//
// Build: g++ -O3 -shared -fPIC (driven by pint_tpu/native/__init__.py).

#include <cmath>
#include <cstdint>

namespace {

struct dd {
  double hi, lo;
};

inline dd two_sum(double a, double b) {
  double s = a + b;
  double bb = s - a;
  double err = (a - (s - bb)) + (b - bb);
  return {s, err};
}

inline dd quick_two_sum(double a, double b) {
  double s = a + b;
  return {s, b - (s - a)};
}

inline dd two_prod(double a, double b) {
  double p = a * b;
  return {p, std::fma(a, b, -p)};
}

inline dd dd_add_d(dd a, double b) {
  dd s = two_sum(a.hi, b);
  double lo = s.lo + a.lo;
  return quick_two_sum(s.hi, lo);
}

inline dd dd_mul_d(dd a, double b) {
  dd p = two_prod(a.hi, b);
  double lo = p.lo + a.lo * b;
  return quick_two_sum(p.hi, lo);
}

inline dd dd_div_d(dd a, double b) {
  double q1 = a.hi / b;
  dd p = two_prod(q1, b);
  double r = ((a.hi - p.hi) - p.lo) + a.lo;
  return quick_two_sum(q1, r / b);
}

// exact powers of ten as doubles (10^k is exact for k <= 22)
double pow10_exact(int k) {
  double v = 1.0;
  for (int i = 0; i < k; ++i) v *= 10.0;
  return v;
}

}  // namespace

extern "C" {

// Parse n decimal MJD strings (pulsar_mjd convention: fraction of an
// 86400 s day).  buf holds the concatenated strings; offsets/lengths
// index it.  Outputs: integer day, seconds-of-day as (hi, lo).
// Returns 0 on success, or 1-based index of the first bad string.
int64_t parse_mjd_strings(const char* buf, const int64_t* offsets,
                          const int64_t* lengths, int64_t n,
                          int64_t* day_out, double* hi_out,
                          double* lo_out) {
  for (int64_t i = 0; i < n; ++i) {
    const char* s = buf + offsets[i];
    int64_t len = lengths[i];
    int64_t pos = 0;
    while (pos < len && (s[pos] == ' ' || s[pos] == '\t')) ++pos;
    if (pos < len && s[pos] == '+') ++pos;
    if (pos >= len || s[pos] == '-') return i + 1;  // negative: no
    // integer part (<= 18 digits: no int64 overflow possible)
    int64_t day = 0;
    int idigits = 0;
    while (pos < len && s[pos] >= '0' && s[pos] <= '9') {
      if (++idigits > 18) return i + 1;
      day = day * 10 + (s[pos] - '0');
      ++pos;
    }
    if (idigits == 0) return i + 1;
    // fraction
    dd frac = {0.0, 0.0};
    int ndigits = 0;
    if (pos < len && s[pos] == '.') {
      ++pos;
      // accumulate in chunks of 15 digits (10^15 < 2^53: every chunk
      // value is exactly representable in a double)
      while (pos < len && s[pos] >= '0' && s[pos] <= '9') {
        uint64_t chunk = 0;
        int c = 0;
        while (pos < len && s[pos] >= '0' && s[pos] <= '9' && c < 15) {
          chunk = chunk * 10 + uint64_t(s[pos] - '0');
          ++pos;
          ++c;
        }
        frac = dd_mul_d(frac, pow10_exact(c));
        frac = dd_add_d(frac, double(chunk));
        ndigits += c;
      }
      // divide by 10^ndigits (in exact <=22-power steps)
      int k = ndigits;
      while (k > 0) {
        int step = k > 22 ? 22 : k;
        frac = dd_div_d(frac, pow10_exact(step));
        k -= step;
      }
    }
    while (pos < len && (s[pos] == ' ' || s[pos] == '\t')) ++pos;
    if (pos != len) return i + 1;  // trailing junk
    dd sec = dd_mul_d(frac, 86400.0);
    day_out[i] = day;
    hi_out[i] = sec.hi;
    lo_out[i] = sec.lo;
  }
  return 0;
}

// Self-test hook: dd arithmetic sanity (returns 0 when healthy).
int64_t native_self_test() {
  dd a = {1.0, 0.0};
  a = dd_div_d(a, 3.0);
  a = dd_mul_d(a, 3.0);
  // 1/3*3 in dd must be 1 to ~1e-32
  double err = std::fabs((a.hi - 1.0) + a.lo);
  return err < 1e-30 ? 0 : 1;
}

}  // extern "C"
