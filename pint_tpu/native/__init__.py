"""Native (C++) host kernels: build-on-first-use + ctypes bindings.

The shared library is compiled from pint_native.cpp with the system g++
on first import (cached next to the source, keyed on source mtime) and
loaded via ctypes — no pybind11/build-isolation dependency.  Every
entry point has a pure-Python fallback; ``available()`` reports whether
the native path is active.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import warnings
from pathlib import Path

import numpy as np

_SRC = Path(__file__).with_name("pint_native.cpp")
_LIB = Path(__file__).with_name("_pint_native.so")

_lib = None
_tried = False


def _build() -> bool:
    cxx = os.environ.get("CXX", "g++")
    cmd = [
        cxx, "-O3", "-shared", "-fPIC", "-std=c++17",
        str(_SRC), "-o", str(_LIB),
    ]
    try:
        subprocess.run(
            cmd, check=True, capture_output=True, timeout=120
        )
        return True
    except (OSError, subprocess.SubprocessError) as e:
        warnings.warn(
            f"building pint_native failed ({e}); using the pure-Python "
            "ingest paths"
        )
        return False


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if os.environ.get("PINT_TPU_NO_NATIVE"):
        return None
    try:
        if (
            not _LIB.exists()
            or _LIB.stat().st_mtime < _SRC.stat().st_mtime
        ):
            if not _build():
                return None
        lib = ctypes.CDLL(str(_LIB))
    except OSError as e:
        warnings.warn(f"loading pint_native failed ({e})")
        return None
    lib.parse_mjd_strings.restype = ctypes.c_int64
    lib.parse_mjd_strings.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        ctypes.c_int64,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.float64, flags="C"),
        np.ctypeslib.ndpointer(np.float64, flags="C"),
    ]
    lib.native_self_test.restype = ctypes.c_int64
    lib.native_self_test.argtypes = []
    if lib.native_self_test() != 0:
        warnings.warn(
            "pint_native self-test failed; using pure-Python paths"
        )
        return None
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def parse_mjd_strings(strings):
    """Batched exact decimal MJD parse (pulsar_mjd convention):
    -> (day int64 (n,), sec_hi (n,), sec_lo (n,)) or None when the
    native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    try:
        enc = [s.strip().encode("ascii") for s in strings]
    except UnicodeEncodeError as e:
        raise ValueError(f"non-ASCII character in MJD string: {e}") from e
    n = len(enc)
    buf = b"".join(enc)
    lengths = np.array([len(e) for e in enc], dtype=np.int64)
    offsets = np.concatenate([[0], np.cumsum(lengths)[:-1]]).astype(
        np.int64
    )
    day = np.empty(n, dtype=np.int64)
    hi = np.empty(n, dtype=np.float64)
    lo = np.empty(n, dtype=np.float64)
    rc = lib.parse_mjd_strings(buf, offsets, lengths, n, day, hi, lo)
    if rc != 0:
        raise ValueError(
            f"bad MJD string at index {rc - 1}: {strings[rc - 1]!r}"
        )
    return day, hi, lo
