"""Minimal FITS reader/writer for event binary tables.

TPU-native replacement for the astropy.io.fits capability the reference
uses in src/pint/event_toas.py / fermi_toas.py — only what the photon
path needs: header parsing, BINTABLE column decode (logical/byte/short/
int/long/float/double/string TFORMs), and adding a column (the
photonphase script writes PULSE_PHASE back).

FITS structure: 2880-byte blocks; headers are 80-char cards; binary
tables are big-endian packed rows described by TFORMn codes.
"""

from __future__ import annotations

import numpy as np

BLOCK = 2880
CARD = 80

_TFORM_DTYPES = {
    "L": ("S1", 1), "B": (">u1", 1), "I": (">i2", 2), "J": (">i4", 4),
    "K": (">i8", 8), "E": (">f4", 4), "D": (">f8", 8), "A": ("S", 1),
}


def _parse_header_block(data, off):
    """Parse cards until END; returns (dict, new offset, card list)."""
    cards = []
    hdr: dict = {}
    while True:
        block = data[off:off + BLOCK]
        if len(block) < BLOCK:
            raise ValueError("truncated FITS header")
        off += BLOCK
        done = False
        for i in range(0, BLOCK, CARD):
            card = block[i:i + CARD].decode("ascii", "replace")
            cards.append(card)
            key = card[:8].strip()
            if key == "END":
                done = True
                break
            if not key or key in ("COMMENT", "HISTORY") or card[8] != "=":
                continue
            val = card[10:].split("/")[0].strip()
            if val.startswith("'"):
                hdr[key] = val.strip("'").strip()
            elif val in ("T", "F"):
                hdr[key] = val == "T"
            else:
                try:
                    hdr[key] = int(val)
                except ValueError:
                    try:
                        hdr[key] = float(val)
                    except ValueError:
                        hdr[key] = val
        if done:
            break
    return hdr, off, cards


def _data_size(hdr):
    """FITS standard: |BITPIX|/8 * GCOUNT * (PCOUNT + prod(NAXISi))."""
    bitpix = abs(int(hdr.get("BITPIX", 8)))
    naxis = int(hdr.get("NAXIS", 0))
    if naxis == 0:
        return 0
    n = 1
    for i in range(1, naxis + 1):
        n *= int(hdr.get(f"NAXIS{i}", 0))
    gcount = int(hdr.get("GCOUNT", 1))
    pcount = int(hdr.get("PCOUNT", 0))
    return bitpix // 8 * gcount * (pcount + n)


def _parse_tform(tform: str):
    tform = tform.strip()
    i = 0
    while i < len(tform) and tform[i].isdigit():
        i += 1
    repeat = int(tform[:i]) if i else 1
    code = tform[i]
    return repeat, code


class HDU:
    def __init__(self, header, cards, data_bytes):
        self.header = header
        self.cards = cards
        self._data = data_bytes

    @property
    def name(self):
        return str(self.header.get("EXTNAME", "")).strip()

    def is_bintable(self):
        return self.header.get("XTENSION", "").strip() == "BINTABLE"

    def columns(self):
        n = int(self.header.get("TFIELDS", 0))
        return [
            str(self.header.get(f"TTYPE{i}", f"col{i}")).strip()
            for i in range(1, n + 1)
        ]

    def _layout(self):
        nfields = int(self.header["TFIELDS"])
        offs, dtypes, names = [], [], []
        off = 0
        for i in range(1, nfields + 1):
            repeat, code = _parse_tform(str(self.header[f"TFORM{i}"]))
            base, size = _TFORM_DTYPES[code]
            offs.append(off)
            if code == "A":
                dtypes.append((f"S{repeat}", 1))
            else:
                dtypes.append((base, repeat))
            names.append(str(self.header.get(f"TTYPE{i}", f"col{i}")).strip())
            off += repeat * size
        rowlen = int(self.header["NAXIS1"])
        if off > rowlen:
            raise ValueError("TFORM row length exceeds NAXIS1")
        return names, offs, dtypes, rowlen

    def column(self, name):
        """Column data as a numpy array (nrows,) or (nrows, repeat)."""
        names, offs, dtypes, rowlen = self._layout()
        nrows = int(self.header["NAXIS2"])
        try:
            i = [n.upper() for n in names].index(str(name).upper())
        except ValueError:
            raise KeyError(
                f"no column {name!r} in {self.name}; have {names}"
            )
        raw = np.frombuffer(
            self._data[: nrows * rowlen], dtype=np.uint8
        ).reshape(nrows, rowlen)
        dt, repeat = dtypes[i]
        itemsize = np.dtype(dt).itemsize
        chunk = raw[:, offs[i]: offs[i] + itemsize * repeat]
        out = chunk.reshape(-1).view(dt).reshape(nrows, repeat)
        if dt.startswith("S"):
            return np.char.strip(out[:, 0].astype(str))
        out = out.astype(out.dtype.newbyteorder("="))
        return out[:, 0] if repeat == 1 else out


def read_fits(path) -> list[HDU]:
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(b"SIMPLE"):
        raise ValueError(f"{path}: not a FITS file")
    hdus = []
    off = 0
    while off < len(data):
        hdr, off, cards = _parse_header_block(data, off)
        size = _data_size(hdr)
        padded = (size + BLOCK - 1) // BLOCK * BLOCK
        hdus.append(HDU(hdr, cards, data[off:off + size]))
        off += padded
    return hdus


def get_bintable(path, extname=None) -> HDU:
    """First BINTABLE HDU (or the named one)."""
    for h in read_fits(path):
        if not h.is_bintable():
            continue
        if extname is None or h.name.upper() == str(extname).upper():
            return h
    raise ValueError(f"no BINTABLE {extname or ''} in {path}")


# -- writing (event files for tests + PULSE_PHASE output) -----------------
def _card(key, value, comment=""):
    if isinstance(value, bool):
        v = "T" if value else "F"
        s = f"{key:<8}= {v:>20}"
    elif isinstance(value, (int, np.integer)):
        s = f"{key:<8}= {value:>20d}"
    elif isinstance(value, float):
        s = f"{key:<8}= {value:>20.13E}"
    else:
        s = f"{key:<8}= '{value}'"
    if comment:
        s += f" / {comment}"
    return s[:CARD].ljust(CARD)


def _pad_block(b: bytes, fill=b"\x00") -> bytes:
    rem = len(b) % BLOCK
    return b if rem == 0 else b + fill * (BLOCK - rem)


def write_event_fits(path, columns: dict, header_extra: dict = None,
                     extname: str = "EVENTS"):
    """Write a minimal FITS file: empty primary HDU + one BINTABLE with
    float64 (D), float32 (E), int32 (J) or string (A) columns inferred
    from the arrays."""
    cards = [
        _card("SIMPLE", True), _card("BITPIX", 8), _card("NAXIS", 0),
        _card("EXTEND", True), "END".ljust(CARD),
    ]
    primary = _pad_block("".join(cards).encode("ascii"), b" ")

    names = list(columns)
    arrays = []
    tforms = []
    for n in names:
        a = np.asarray(columns[n])
        if a.dtype.kind == "f" and a.dtype.itemsize == 4:
            arrays.append(a.astype(">f4"))
            tforms.append("1E")
        elif a.dtype.kind == "f":
            arrays.append(a.astype(">f8"))
            tforms.append("1D")
        elif a.dtype.kind in "iu":
            arrays.append(a.astype(">i4"))
            tforms.append("1J")
        else:
            width = max(1, max((len(str(s)) for s in a), default=1))
            arrays.append(np.asarray(
                [str(s).ljust(width).encode() for s in a], dtype=f"S{width}"
            ))
            tforms.append(f"{width}A")
    nrows = len(arrays[0])
    rowlen = sum(a.dtype.itemsize for a in arrays)
    tcards = [
        _card("XTENSION", "BINTABLE"), _card("BITPIX", 8),
        _card("NAXIS", 2), _card("NAXIS1", rowlen),
        _card("NAXIS2", nrows), _card("PCOUNT", 0), _card("GCOUNT", 1),
        _card("TFIELDS", len(names)), _card("EXTNAME", extname),
    ]
    for i, (n, tf) in enumerate(zip(names, tforms), start=1):
        tcards.append(_card(f"TTYPE{i}", n))
        tcards.append(_card(f"TFORM{i}", tf))
    for k, v in (header_extra or {}).items():
        tcards.append(_card(k, v))
    tcards.append("END".ljust(CARD))
    theader = _pad_block("".join(tcards).encode("ascii"), b" ")

    rows = np.empty((nrows, rowlen), dtype=np.uint8)
    off = 0
    for a in arrays:
        size = a.dtype.itemsize
        rows[:, off:off + size] = a.reshape(nrows, 1).view(np.uint8).reshape(
            nrows, size
        )
        off += size
    tdata = _pad_block(rows.tobytes())

    with open(path, "wb") as f:
        f.write(primary)
        f.write(theader)
        f.write(tdata)


def add_column(path, out_path, name, values, extname=None):
    """Copy the file with an extra column appended to the (first or
    named) BINTABLE (reference behavior: photonphase writes PULSE_PHASE
    back into the event file)."""
    hdu = get_bintable(path, extname)
    cols = {n: hdu.column(n) for n in hdu.columns()}
    cols[name] = np.asarray(values)
    extra = {
        k: hdu.header[k]
        for k in hdu.header
        if k in (
            "MJDREFI", "MJDREFF", "MJDREF", "TIMEZERO", "TIMESYS",
            "TELESCOP", "INSTRUME", "OBS_ID",
        )
    }
    write_event_fits(
        out_path, cols, header_extra=extra, extname=hdu.name or "EVENTS"
    )
