"""Observatory clock-correction files.

Reference parity: src/pint/observatory/clock_file.py::ClockFile — piecewise
-linear MJD -> correction curves, read from tempo2 ``.clk`` files
(``# UTC(gbt) UTC`` header; ``mjd offset_seconds`` rows) or tempo
``time.dat`` files (``mjd offset_microseconds`` rows, site-coded).
Out-of-range policy mirrors the reference: warn (default), error, or
extrapolate-zero.
"""

from __future__ import annotations

import warnings
from pathlib import Path

import numpy as np

from pint_tpu.exceptions import ClockCorrectionOutOfRange, PintTpuError


class ClockFile:
    """Piecewise-linear clock correction: corr(mjd) seconds."""

    def __init__(
        self,
        mjd: np.ndarray,
        corr_s: np.ndarray,
        name: str = "",
        valid_beyond_ends: bool = False,
    ):
        order = np.argsort(mjd, kind="stable")
        self.mjd = np.asarray(mjd, dtype=np.float64)[order]
        self.corr_s = np.asarray(corr_s, dtype=np.float64)[order]
        self.name = name
        self.valid_beyond_ends = valid_beyond_ends

    @staticmethod
    def from_tempo2(path, name: str = "") -> "ClockFile":
        """Tempo2 .clk: '# FROM TO' header line, then 'mjd offset_s'."""
        mjds, corrs = [], []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                parts = line.split()
                try:
                    mjds.append(float(parts[0]))
                    corrs.append(float(parts[1]))
                except (ValueError, IndexError):
                    continue
        if not mjds:
            raise PintTpuError(f"no clock data in {path}")
        return ClockFile(
            np.array(mjds), np.array(corrs), name=name or Path(path).stem
        )

    @staticmethod
    def from_tempo(path, site: str = "", name: str = "") -> "ClockFile":
        """Tempo time.dat-style: 'mjd offset_us [offset2_us] [site]'.

        Offsets are microseconds; when a site column is present, rows are
        filtered to the requested one-letter code.
        """
        mjds, corrs = [], []
        with open(path) as f:
            for line in f:
                ls = line.strip()
                if not ls or ls.startswith(("#", "C", "c", "MJD")):
                    continue
                parts = ls.split()
                try:
                    mjd = float(parts[0])
                    off_us = float(parts[1])
                except (ValueError, IndexError):
                    continue
                if site and len(parts) >= 4 and parts[3] != site:
                    continue
                mjds.append(mjd)
                corrs.append(off_us * 1e-6)
        if not mjds:
            raise PintTpuError(f"no clock data for site {site!r} in {path}")
        return ClockFile(
            np.array(mjds), np.array(corrs), name=name or Path(path).stem
        )

    def evaluate(self, mjd, limits: str = "warn") -> np.ndarray:
        """Interpolate corrections (seconds) at mjd (float array).

        limits: 'warn' (clamp + warn), 'error', or 'none' (clamp silently).
        """
        mjd = np.asarray(mjd, dtype=np.float64)
        out_of_range = (mjd < self.mjd[0]) | (mjd > self.mjd[-1])
        if np.any(out_of_range) and not self.valid_beyond_ends:
            msg = (
                f"clock file {self.name}: {int(out_of_range.sum())} MJDs "
                f"outside [{self.mjd[0]:.1f}, {self.mjd[-1]:.1f}]"
            )
            if limits == "error":
                raise ClockCorrectionOutOfRange(msg)
            if limits == "warn":
                warnings.warn(msg)
        out = np.interp(mjd, self.mjd, self.corr_s)
        if not self.valid_beyond_ends:
            # extrapolate-zero beyond the tabulated span (module policy)
            out = np.where(out_of_range, 0.0, out)
        return out

    @property
    def first_mjd(self):
        return self.mjd[0]

    @property
    def last_mjd(self):
        return self.mjd[-1]

    def __add__(self, other: "ClockFile") -> "ClockFile":
        """Compose two corrections on the union grid (chain links)."""
        grid = np.union1d(self.mjd, other.mjd)
        total = self.evaluate(grid, limits="none") + other.evaluate(
            grid, limits="none"
        )
        return ClockFile(
            grid, total, name=f"{self.name}+{other.name}"
        )

    def write_tempo2(self, path, hdrline: str = ""):
        with open(path, "w") as f:
            f.write((hdrline or f"# {self.name}") + "\n")
            for m, c in zip(self.mjd, self.corr_s):
                f.write(f"{m:.6f} {c:.12e}\n")
