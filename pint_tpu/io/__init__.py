"""Host IO: par files, tim files, clock files, EOP, SPK ephemerides."""

from pint_tpu.io.par import parse_parfile  # noqa: F401
from pint_tpu.io.tim import read_tim_file  # noqa: F401
