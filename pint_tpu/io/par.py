"""Par-file (pulsar ephemeris) parsing.

Reference parity: src/pint/models/model_builder.py::parse_parfile — a par
file is ``NAME value [fit] [uncertainty]`` lines; repeated names are legal
(JUMP families); '#' and 'C '-style comments; Fortran 'D' exponents.
Component selection from the parsed dict happens in
pint_tpu.models.builder, mirroring ModelBuilder.
"""

from __future__ import annotations

import io
import os
from collections import OrderedDict
from typing import Union

__all__ = ["parse_parfile"]


def parse_parfile(path_or_str: Union[str, os.PathLike]) -> "OrderedDict[str, list[list[str]]]":
    """Parse a par file into {UPPER_NAME: [token-list, ...]}.

    Accepts a filesystem path or the par-file text itself (any string
    containing a newline is treated as content — matching the reference's
    get_model(StringIO) convenience).
    """
    if hasattr(path_or_str, "read"):
        text = path_or_str.read()
    else:
        s = os.fspath(path_or_str)
        if "\n" in s:
            text = s
        else:
            with open(s) as f:
                text = f.read()
    out: OrderedDict[str, list[list[str]]] = OrderedDict()
    for raw in io.StringIO(text):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.upper().startswith("C ") or line.upper().startswith("CC "):
            continue
        # strip trailing comments
        for mark in ("#",):
            if mark in line:
                line = line.split(mark, 1)[0].strip()
        tokens = line.split()
        if not tokens:
            continue
        name = tokens[0].upper()
        out.setdefault(name, []).append(tokens[1:])
    return out


def parfile_dict_to_text(d) -> str:
    lines = []
    for name, entries in d.items():
        for tokens in entries:
            lines.append(" ".join([name, *tokens]))
    return "\n".join(lines) + "\n"
