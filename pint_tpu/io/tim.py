"""Tim-file (TOA) parsing and writing.

Reference parity: src/pint/toa.py tim parsing — Tempo2 ("FORMAT 1") and
Princeton formats, tim commands (FORMAT, MODE, INCLUDE, TIME, EFAC,
EQUAD, EMIN, SKIP/NOSKIP, END, PHASE, JUMP), per-TOA flags (-key value).

Princeton fixed columns (tempo convention):
  col 0     observatory one-character code
  col 1-:   free text name
  cols 15+  freq (MHz), MJD (cols 24-44), uncertainty (us)
We parse Princeton leniently by whitespace after extracting the site code,
which covers the files produced by tempo/PINT writers; ITOA/Parkes formats
raise a clear error (rare in modern datasets).
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np

from pint_tpu.exceptions import PintTpuError
from pint_tpu.timebase.times import TimeArray
from pint_tpu.toas.toas import TOAs

__all__ = ["read_tim_file", "write_tim_file"]

_COMMANDS = {
    "FORMAT", "MODE", "INCLUDE", "TIME", "EFAC", "EQUAD", "EMIN", "EMAX",
    "SKIP", "NOSKIP", "END", "PHASE", "JUMP", "TRACK", "INFO", "FMIN",
    "FMAX", "SIGMA",
}


def _is_flag_key(tok: str) -> bool:
    """'-f', '-be' are flag keys; '-1', '-0.5' are (negative) values."""
    return len(tok) >= 2 and tok[0] == "-" and not tok[1].isdigit() \
        and tok[1] != "."


class _ParseState:
    def __init__(self):
        self.fmt = "Princeton"
        self.time_offset_s = 0.0
        self.efac = 1.0
        self.equad_us = 0.0
        self.emin_us = None
        self.emax_us = None
        self.fmin_mhz = None
        self.fmax_mhz = None
        self.phase = 0.0
        self.skip = False
        self.jump_counter = 0
        self.in_jump = False
        self.ended = False


def read_tim_file(path, include_depth: int = 0, state: "_ParseState" = None):
    """-> raw row dict (pre-TOAs).  ``state`` is shared across INCLUDE
    (tempo2 semantics: FORMAT/EFAC/TIME... in force carry into included
    files and mutations inside them persist after return)."""
    if include_depth > 10:
        raise PintTpuError("INCLUDE nesting too deep")
    rows = {
        "mjd": [], "freq": [], "err": [], "obs": [], "flags": [],
        "time_offset": [],
    }
    state = state or _ParseState()
    if hasattr(path, "read"):  # file-like (timedit buffers); INCLUDE
        # inside an anonymous buffer raises in _parse_line (no base
        # directory to resolve against)
        f = path
        path = Path("<buffer>")
        for lineno, raw in enumerate(f, 1):
            _parse_line(raw, state, rows, path, lineno, include_depth)
            if state.ended:
                break
        return rows
    path = Path(path)
    with open(path) as f:
        for lineno, raw in enumerate(f, 1):
            _parse_line(raw, state, rows, path, lineno, include_depth)
            if state.ended:
                break
    return rows


def build_toas_from_rows(rows) -> TOAs:
    t = TimeArray.from_mjd_strings(rows["mjd"], scale="utc")
    # Apply TIME-command offsets to the arrival times now (design note:
    # the reference defers them to the clock-correction stage via a 'to'
    # flag; baking them in at parse time is equivalent — the shifted time
    # IS the arrival time — and keeps ingest stateless).  Offsets travel
    # in a dedicated row array so a user's ordinary '-to' flag cannot
    # shift times.
    offsets = np.asarray(rows["time_offset"], dtype=np.float64)
    if np.any(offsets != 0.0):
        t = t.add_seconds(offsets)
    toas = TOAs(
        t,
        np.array(rows["freq"], dtype=np.float64),
        np.array(rows["err"], dtype=np.float64),
        rows["obs"],
        rows["flags"],
    )
    return toas


def _parse_line(raw, state, rows, path, lineno, depth):
    line = raw.strip()
    if not line:
        return
    if line.startswith(("#", "C ", "c ", "%")):
        return
    tokens = line.split()
    head = tokens[0].upper()

    if head in _COMMANDS:
        _apply_command(head, tokens, state, rows, path, depth)
        return
    if state.skip:
        return
    if state.fmt == "Tempo2":
        _parse_tempo2_toa(tokens, state, rows, path, lineno)
    else:
        _parse_princeton_toa(raw.rstrip("\n"), tokens, state, rows, path, lineno)


def _apply_command(head, tokens, state, rows, path, depth):
    if head == "FORMAT":
        state.fmt = "Tempo2" if tokens[1] == "1" else "Princeton"
    elif head == "MODE":
        pass  # fit-mode hint, ignored (reference logs and ignores too)
    elif head == "INCLUDE":
        if str(path) == "<buffer>":
            raise PintTpuError(
                "INCLUDE inside an anonymous tim buffer has no base "
                "directory to resolve against"
            )
        inc = Path(path).parent / tokens[1]
        sub = read_tim_file(inc, depth + 1, state=state)
        for k in rows:
            rows[k].extend(sub[k])
    elif head == "TIME":
        state.time_offset_s += float(tokens[1])
    elif head == "EFAC":
        state.efac = float(tokens[1])
    elif head == "EQUAD":
        state.equad_us = float(tokens[1])
    elif head == "EMIN":
        state.emin_us = float(tokens[1])
    elif head == "EMAX":
        state.emax_us = float(tokens[1])
    elif head == "FMIN":
        state.fmin_mhz = float(tokens[1])
    elif head == "FMAX":
        state.fmax_mhz = float(tokens[1])
    elif head in ("SIGMA", "TRACK", "INFO"):
        import warnings

        warnings.warn(f"tim command {head} not supported; ignored")
    elif head == "PHASE":
        state.phase += float(tokens[1])
    elif head == "SKIP":
        state.skip = True
    elif head == "NOSKIP":
        state.skip = False
    elif head == "END":
        state.ended = True
    elif head == "JUMP":
        # toggle; tag subsequent TOAs with -tim_jump N (reference: JUMP
        # blocks become maskParameter selections via flags)
        if state.in_jump:
            state.in_jump = False
        else:
            state.jump_counter += 1
            state.in_jump = True


def _common_flags(state, extra):
    flags = dict(extra)
    if state.in_jump:
        flags["tim_jump"] = str(state.jump_counter)
    if state.phase != 0.0:
        flags["padd"] = repr(state.phase)
    return flags


def _apply_err_model(err_us, state):
    return state.efac * np.hypot(err_us, state.equad_us)


def _parse_tempo2_toa(tokens, state, rows, path, lineno):
    # name freq sat err site [-flag value ...]
    if len(tokens) < 5:
        raise PintTpuError(f"{path}:{lineno}: bad Tempo2 TOA line")
    name, freq, sat, err, site = tokens[:5]
    flags = {}
    rest = tokens[5:]
    i = 0
    while i < len(rest):
        if not _is_flag_key(rest[i]):
            raise PintTpuError(
                f"{path}:{lineno}: expected -flag, got {rest[i]!r}"
            )
        key = rest[i][1:]
        # next token is this flag's value unless it is itself a flag key
        # (valueless/boolean flags; note values like '-1' are NOT keys)
        if i + 1 < len(rest) and not _is_flag_key(rest[i + 1]):
            flags[key] = rest[i + 1]
            i += 2
        else:
            flags[key] = ""
            i += 1
    flags.setdefault("name", name)
    _append_toa(rows, sat, freq, err, site, flags, state)


def _parse_princeton_toa(raw, tokens, state, rows, path, lineno):
    # Site code is column 0; remaining fields whitespace-separated:
    # name... freq mjd err [dm-correction]
    site = raw[0]
    if site.isspace():
        raise PintTpuError(
            f"{path}:{lineno}: bad Princeton TOA line (no site code)"
        )
    # find numeric fields from the right: err, mjd, freq
    if len(tokens) < 3:
        raise PintTpuError(f"{path}:{lineno}: bad Princeton TOA line")
    # tokens[0] starts with the site char; strip it
    toks = list(tokens)
    toks[0] = toks[0][1:]
    if toks[0] == "":
        toks = toks[1:]
    numeric = []
    for j, t in enumerate(toks):
        try:
            float(t)
            numeric.append(j)
        except ValueError:
            pass
    # Heuristic: the last three (or four, with DM corr) numeric tokens are
    # freq, mjd, err(, ddm).  MJD is the token containing '.', > 20000.
    mjd_idx = None
    for j in numeric:
        try:
            v = float(toks[j])
        except ValueError:
            continue
        if 20000 < v < 1000000 and "." in toks[j]:
            mjd_idx = j
    if mjd_idx is None or mjd_idx == 0 or mjd_idx + 1 >= len(toks):
        raise PintTpuError(f"{path}:{lineno}: cannot locate MJD field")
    freq = toks[mjd_idx - 1]
    sat = toks[mjd_idx]
    err = toks[mjd_idx + 1]
    flags = {}
    if toks[:mjd_idx - 1]:
        flags["name"] = toks[0]
    _append_toa(rows, sat, freq, err, site, flags, state)


def _append_toa(rows, sat, freq, err, site, flags, state):
    err_us = _apply_err_model(float(err), state)
    freq_mhz = float(freq) if float(freq) != 0.0 else np.inf
    # EMIN/EMAX/FMIN/FMAX selection commands (tempo semantics: exclude
    # TOAs outside the accepted ranges)
    if state.emin_us is not None and err_us < state.emin_us:
        return
    if state.emax_us is not None and err_us > state.emax_us:
        return
    if state.fmin_mhz is not None and freq_mhz < state.fmin_mhz:
        return
    if state.fmax_mhz is not None and freq_mhz > state.fmax_mhz:
        return
    rows["mjd"].append(sat)
    rows["freq"].append(freq_mhz)
    rows["err"].append(err_us)
    rows["obs"].append(site)
    rows["flags"].append(_common_flags(state, flags))
    rows["time_offset"].append(state.time_offset_s)


def get_TOAs_from_tim(path) -> TOAs:
    """Parse a tim file into a TOAs container (no ingest computations).

    Recorded as an ``ingest:parse`` cold-path span (r6): the per-line
    loop is the one ingest stage that CANNOT chunk across workers —
    tim commands are stateful in row order (EFAC/TIME/SKIP carry into
    later rows, INCLUDE splices files) — so it shows up separately in
    a trace next to the parallelizable column stages."""
    from pint_tpu.obs.trace import TRACER

    with TRACER.span("ingest:parse", "ingest"):
        rows = read_tim_file(path)
        toas = build_toas_from_rows(rows)
        TRACER.annotate(ntoa=len(toas))
    return toas


def write_tim_file(path, toas: TOAs, name: str = "pint_tpu"):
    """Write Tempo2-format tim file (reference: TOAs.write_TOA_file);
    ``path`` may be a path or a writable file object (timedit)."""
    from pint_tpu.utils.misc import open_or_use

    with open_or_use(path, "w") as f:
        f.write("FORMAT 1\n")
        mjds = toas.t.to_mjd_strings(ndigits=16)
        for i in range(len(toas)):
            flags = dict(toas.flags[i])
            nm = flags.pop("name", name)
            freq = toas.freq[i]
            freq_s = "0.000000" if not np.isfinite(freq) else f"{freq:.6f}"
            line = (
                f"{nm} {freq_s} {mjds[i]} "
                f"{toas.error_us[i]:.3f} {toas.obs[i]}"
            )
            for k, v in flags.items():
                line += f" -{k} {v}" if v != "" else f" -{k}"
            f.write(line + "\n")
