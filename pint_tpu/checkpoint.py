"""Fit-state checkpoints (save / resume).

Reference parity: SURVEY.md §5 checkpoint/resume — the reference's
story is (a) the TOA pickle cache (ours: toas/cache.py), (b) parfile
round-trip as the model checkpoint (ours: TimingModel.as_parfile), and
(c) nothing for long runs.  The TPU framework adds (c): an
orbax-style-but-dependency-free .npz checkpoint of fitter state
(parameters, covariance, chi2) and MCMC sampler state (chain tail, rng
seed), so PTA-scale batch fits and long samplers resume across
preemptions.
"""

from __future__ import annotations

import numpy as np

_VERSION = 1


def _npz_path(path) -> str:
    """np.savez appends .npz when missing; normalize so save/load pairs
    round-trip with the same path string."""
    s = str(path)
    return s if s.endswith(".npz") else s + ".npz"


def save_fit(path, fitter):
    """Checkpoint a fitted fitter: par snapshot + covariance + chi2."""
    if fitter.parameter_covariance_matrix is None:
        raise ValueError("fit before checkpointing")
    np.savez_compressed(
        _npz_path(path),
        version=_VERSION,
        kind="fit",
        parfile=np.array(fitter.model.as_parfile()),
        free_names=np.array(list(fitter.cm.free_names)),
        cov=fitter.parameter_covariance_matrix,
        chi2=np.float64(fitter.chi2 if fitter.chi2 is not None else np.nan),
        converged=np.bool_(fitter.converged),
    )


def load_fit(path):
    """-> dict(model, free_names, cov, chi2, converged); the model is
    rebuilt from the par snapshot (the framework's canonical model
    serialization)."""
    from pint_tpu.models.builder import get_model

    z = np.load(_npz_path(path), allow_pickle=False)
    if int(z["version"]) > _VERSION:
        raise ValueError(
            f"checkpoint version {int(z['version'])} is newer than "
            f"this build ({_VERSION})"
        )
    return {
        "model": get_model(str(z["parfile"])),
        "free_names": [str(n) for n in z["free_names"]],
        "cov": z["cov"],
        "chi2": float(z["chi2"]),
        "converged": bool(z["converged"]),
    }


def save_mcmc(path, mcmc_fitter, keep_last: int = 200):
    """Checkpoint an MCMCFitter: par snapshot + the chain tail (enough
    to re-seed walkers) + diagnostics."""
    if mcmc_fitter.chain is None:
        raise ValueError("sample before checkpointing")
    tail = mcmc_fitter.chain[-keep_last:]
    np.savez_compressed(
        _npz_path(path),
        version=_VERSION,
        kind="mcmc",
        parfile=np.array(mcmc_fitter.model.as_parfile()),
        param_names=np.array(list(mcmc_fitter.bt.param_names)),
        chain_tail=tail,
        lnp_tail=mcmc_fitter.lnp[-keep_last:],
        acceptance=np.float64(mcmc_fitter.acceptance),
    )


def resume_mcmc(path, toas, nsteps: int = 1000, seed: int = 1):
    """Rebuild the model from a checkpoint and continue sampling from
    the saved walker positions.  Returns the resumed MCMCFitter."""
    from pint_tpu.models.builder import get_model
    from pint_tpu.sampler import MCMCFitter, run_ensemble

    z = np.load(_npz_path(path), allow_pickle=False)
    if str(z["kind"]) != "mcmc":
        raise ValueError("not an MCMC checkpoint")
    model = get_model(str(z["parfile"]))
    mf = MCMCFitter(toas, model)
    last = z["chain_tail"][-1]  # (nwalkers, ndim)
    # TRUE resume: the equilibrated ensemble continues from its exact
    # positions (multimodality preserved) — no re-initialization ball
    chain, lnp, acc = run_ensemble(
        mf.bt.lnposterior, last.mean(axis=0),
        nsteps=nsteps, seed=seed, init_walkers=last,
    )
    mf.chain, mf.lnp, mf.acceptance = chain, lnp, acc
    return mf
