"""Fit-state checkpoints (save / resume).

Reference parity: SURVEY.md §5 checkpoint/resume — the reference's
story is (a) the TOA pickle cache (ours: toas/cache.py), (b) parfile
round-trip as the model checkpoint (ours: TimingModel.as_parfile), and
(c) nothing for long runs.  The TPU framework adds (c): an
orbax-style-but-dependency-free .npz checkpoint of fitter state
(parameters, covariance, chi2), MCMC sampler state (chain tail, RNG
seed + schedule cursor), and background-job state (serve/jobs/), so
PTA-scale batch fits and long samplers resume across preemptions.

Durability contract (ISSUE 20 satellite): every write is ATOMIC — the
payload lands in a same-directory temp file and os.replace()s into
place, so a kill mid-checkpoint leaves the previous checkpoint intact,
never a torn npz.  Every load is EAGER and TYPED — a truncated,
corrupt, wrong-kind, or newer-version file raises
exceptions.CheckpointError (never a bare zipfile/KeyError crash), which
is what lets the background-job resume ladder degrade to a cold start
explicitly instead of resuming from garbage.
"""

from __future__ import annotations

import os

import numpy as np

from pint_tpu.exceptions import CheckpointError

_VERSION = 1


def _npz_path(path) -> str:
    """np.savez appends .npz when missing; normalize so save/load pairs
    round-trip with the same path string."""
    s = str(path)
    return s if s.endswith(".npz") else s + ".npz"


def _atomic_savez(path, **payload) -> str:
    """Write an npz atomically: temp file in the TARGET directory (a
    cross-filesystem tmp would make os.replace non-atomic), fsync'd,
    then os.replace into place.  A kill at any point leaves either the
    old checkpoint or the new one — never a torn file."""
    p = _npz_path(path)
    tmp = f"{p}.{os.getpid()}.tmp"
    try:
        with open(tmp, "wb") as f:
            np.savez_compressed(f, **payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return p


def _load_checkpoint(path, kind=None, allow_pickle=False) -> dict:
    """Eager-load an npz checkpoint into a plain dict.  Eager matters:
    np.load is lazy and a truncated member would otherwise only blow up
    at first access, deep in caller code — here every failure mode
    (missing zip directory, truncated member, bad header) surfaces as
    one typed CheckpointError at the load site."""
    p = _npz_path(path)
    try:
        with np.load(p, allow_pickle=allow_pickle) as z:
            data = {k: np.asarray(z[k]) for k in z.files}
    except FileNotFoundError:
        raise
    except Exception as exc:
        raise CheckpointError(
            f"unreadable checkpoint {p!r} (truncated or corrupt): {exc}"
        ) from exc
    if "version" not in data or "kind" not in data:
        raise CheckpointError(f"{p!r} is not a pint_tpu checkpoint")
    if int(data["version"]) > _VERSION:
        raise CheckpointError(
            f"checkpoint version {int(data['version'])} is newer than "
            f"this build ({_VERSION})"
        )
    if kind is not None and str(data["kind"]) != kind:
        raise CheckpointError(
            f"{p!r} is a {str(data['kind'])!r} checkpoint, not {kind!r}"
        )
    return data


def save_fit(path, fitter):
    """Checkpoint a fitted fitter: par snapshot + covariance + chi2."""
    if fitter.parameter_covariance_matrix is None:
        raise ValueError("fit before checkpointing")
    _atomic_savez(
        path,
        version=_VERSION,
        kind="fit",
        parfile=np.array(fitter.model.as_parfile()),
        free_names=np.array(list(fitter.cm.free_names)),
        cov=fitter.parameter_covariance_matrix,
        chi2=np.float64(fitter.chi2 if fitter.chi2 is not None else np.nan),
        converged=np.bool_(fitter.converged),
    )


def load_fit(path):
    """-> dict(model, free_names, cov, chi2, converged); the model is
    rebuilt from the par snapshot (the framework's canonical model
    serialization)."""
    from pint_tpu.models.builder import get_model

    z = _load_checkpoint(path, kind="fit")
    return {
        "model": get_model(str(z["parfile"])),
        "free_names": [str(n) for n in z["free_names"]],
        "cov": z["cov"],
        "chi2": float(z["chi2"]),
        "converged": bool(z["converged"]),
    }


def save_mcmc(path, mcmc_fitter, keep_last: int = 200):
    """Checkpoint an MCMCFitter: par snapshot + the chain tail (enough
    to re-seed walkers) + diagnostics + the RNG-cursor record (seed,
    steps done, planned schedule length, exact final walkers and their
    log-posteriors) that makes resume_mcmc continue the chain on the
    planned key schedule (sampler.ensemble_keys contract: in-plan
    segments bitwise, past-plan extension deterministic)."""
    if mcmc_fitter.chain is None:
        raise ValueError("sample before checkpointing")
    tail = mcmc_fitter.chain[-keep_last:]
    payload = dict(
        version=_VERSION,
        kind="mcmc",
        parfile=np.array(mcmc_fitter.model.as_parfile()),
        param_names=np.array(list(mcmc_fitter.bt.param_names)),
        chain_tail=tail,
        lnp_tail=mcmc_fitter.lnp[-keep_last:],
        acceptance=np.float64(mcmc_fitter.acceptance),
    )
    meta = getattr(mcmc_fitter, "run_meta", None)
    if meta:
        payload.update(
            seed=np.int64(meta["seed"]),
            nsteps_done=np.int64(meta["nsteps_done"]),
            nsteps_total=np.int64(meta["nsteps_total"]),
            walkers=np.asarray(mcmc_fitter.chain[-1]),
            lp_last=np.asarray(mcmc_fitter.lnp[-1]),
        )
    _atomic_savez(path, **payload)


def resume_mcmc(path, toas, nsteps: int = 1000, seed: int = 1):
    """Rebuild the model from a checkpoint and continue sampling from
    the saved walker positions.  Returns the resumed MCMCFitter.

    Checkpoints carrying the RNG-cursor record (save_mcmc of this
    build) continue on the SAVED seed's key schedule — in-plan
    segments are bitwise-identical to the uninterrupted run, and runs
    continued past their plan extend it deterministically; the
    ``seed`` argument applies only to legacy cursor-less files."""
    from pint_tpu.models.builder import get_model
    from pint_tpu.sampler import MCMCFitter, run_ensemble

    z = _load_checkpoint(path, kind="mcmc")
    model = get_model(str(z["parfile"]))
    mf = MCMCFitter(toas, model)
    last = z["chain_tail"][-1]  # (nwalkers, ndim)
    # TRUE resume: the equilibrated ensemble continues from its exact
    # positions (multimodality preserved) — no re-initialization ball
    if "seed" in z:
        done = int(z["nsteps_done"])
        total = max(int(z["nsteps_total"]), done + nsteps)
        chain, lnp, acc = run_ensemble(
            mf.bt.lnposterior, np.asarray(last).mean(axis=0),
            nsteps=nsteps, seed=int(z["seed"]),
            init_walkers=z["walkers"], init_lp=z["lp_last"],
            nsteps_total=total, start=done,
        )
        mf.run_meta = dict(
            seed=int(z["seed"]), nsteps_done=done + nsteps,
            nsteps_total=total,
        )
    else:
        chain, lnp, acc = run_ensemble(
            mf.bt.lnposterior, last.mean(axis=0),
            nsteps=nsteps, seed=seed, init_walkers=last,
        )
    mf.chain, mf.lnp, mf.acceptance = chain, lnp, acc
    return mf


def save_job(path, payload: dict) -> str:
    """Atomic background-job checkpoint (serve/jobs/scheduler.py):
    state arrays, RNG key material, and the cursor.  Non-array values
    (the nested sampler's host Generator state dict) ride as 0-d
    object arrays — load_job unwraps them."""
    arrays = {}
    for k, v in payload.items():
        if k in ("version", "kind"):
            raise ValueError(f"reserved checkpoint field {k!r}")
        arrays[k] = np.asarray(v)
    return _atomic_savez(path, version=_VERSION, kind="job", **arrays)


def load_job(path) -> dict:
    """-> the save_job payload (typed CheckpointError on any damage;
    the job resume ladder catches it and reports, never resumes from
    a torn file)."""
    data = _load_checkpoint(path, kind="job", allow_pickle=True)
    out = {}
    for k, v in data.items():
        if k in ("version", "kind"):
            continue
        out[k] = v.item() if (v.dtype == object and v.ndim == 0) else v
    return out
