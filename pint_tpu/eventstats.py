"""Photon-phase periodicity statistics: Z^2_m, H-test.

Reference parity: src/pint/eventstats.py::z2m, hm, sf_z2m, sf_hm
(heritage: de Jager, Raubenheimer & Swanepoel 1989; de Jager &
Busching 2010 for the H-test tail probability).  Vectorized numpy;
the trig sums are trivially jax-able if photon sets grow large.
"""

from __future__ import annotations

import numpy as np
from scipy.stats import chi2 as _chi2


def z2m(phases, m: int = 2, weights=None):
    """Z^2_m statistics for harmonics 1..m; returns array of the
    cumulative Z^2_k for k = 1..m."""
    ph = 2.0 * np.pi * np.asarray(phases, dtype=np.float64)
    if weights is None:
        w = np.ones_like(ph)
    else:
        w = np.asarray(weights, dtype=np.float64)
    # weighted form (Kerr 2011): Z^2_k = 2/sum(w^2) * |sum w e^{ik phi}|^2
    norm = np.sum(w * w)
    ks = np.arange(1, m + 1)
    arg = ks[:, None] * ph[None, :]
    c = np.sum(w[None, :] * np.cos(arg), axis=1)
    s = np.sum(w[None, :] * np.sin(arg), axis=1)
    return np.cumsum(2.0 / norm * (c * c + s * s))


def sf_z2m(z2, m: int = 2):
    """Survival function of Z^2_m (chi^2 with 2m dof)."""
    return float(_chi2.sf(z2, 2 * m))


def hm(phases, m: int = 20, weights=None):
    """H-test statistic: max_k (Z^2_k - 4k + 4) over k = 1..m."""
    z = z2m(phases, m=m, weights=weights)
    ks = np.arange(1, m + 1)
    return float(np.max(z - 4.0 * ks + 4.0))


def h2sig(h):
    """H-test significance in sigma (de Jager & Busching 2010:
    p = exp(-0.4 H))."""
    from scipy.stats import norm

    logp = -0.4 * h
    return float(norm.isf(np.exp(logp))) if logp > -700 else float(
        norm.isf(0.0)
    )


def sf_hm(h):
    """H-test tail probability exp(-0.4 H) (de Jager & Busching 2010)."""
    return float(np.exp(-0.4 * h))
