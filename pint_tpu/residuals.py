"""Residuals: the host-facing wrapper over compiled residual kernels.

Reference parity: src/pint/residuals.py::Residuals (calc_phase_resids,
calc_time_resids, chi2, track_mode, weighted-mean subtraction).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.models.timing_model import CompiledModel, TimingModel
from pint_tpu.toas.toas import TOAs


class Residuals:
    def __init__(
        self,
        toas: TOAs,
        model: TimingModel,
        subtract_mean: bool = True,
        track_mode: Optional[str] = None,
        compiled: Optional[CompiledModel] = None,
    ):
        self.toas = toas
        self.model = model
        self.cm = compiled or model.compile(toas, subtract_mean=subtract_mean)
        if track_mode is not None:
            self.cm.track_mode = track_mode
        self._x = self.cm.x0()

    @property
    def phase_resids(self) -> np.ndarray:
        return np.asarray(self.cm.phase_residuals(self._x))

    @property
    def time_resids(self) -> np.ndarray:
        """Seconds (weighted-mean-subtracted if subtract_mean)."""
        return np.asarray(self.cm.time_residuals_jit(self._x))

    @property
    def chi2(self) -> float:
        return float(self.cm.chi2_jit(self._x))

    @property
    def dof(self) -> int:
        # the implicit offset costs a dof unless PHOFF (already counted
        # among free params) replaces it
        offset = 0 if "PHOFF" in self.cm.free_names else 1
        return len(self.toas) - len(self.cm.free_names) - offset

    @property
    def reduced_chi2(self) -> float:
        return self.chi2 / self.dof

    def rms_weighted(self) -> float:
        """Weighted RMS of time residuals (scaled errors), seconds."""
        r = self.time_resids
        w = 1.0 / np.asarray(self.cm.scaled_sigma(self._x)) ** 2
        return float(np.sqrt(np.sum(w * r * r) / np.sum(w)))


class CombinedResiduals:
    """Concatenation of residual objects from independent data sets
    (reference: residuals.py::CombinedResiduals — the chi2s add; the
    unit-heterogeneous residual lists stay per-member)."""

    def __init__(self, residual_list):
        self.residual_objs = list(residual_list)

    @property
    def chi2(self) -> float:
        return float(sum(r.chi2 for r in self.residual_objs))

    @property
    def dof(self) -> int:
        # AttributeError surfaces for members without a dof notion
        # (e.g. wideband pairs) rather than silently summing zeros
        return int(sum(r.dof for r in self.residual_objs))

    @property
    def reduced_chi2(self) -> float:
        return self.chi2 / self.dof

    def __len__(self):
        return sum(
            len(getattr(r, "toas", [])) for r in self.residual_objs
        )
