"""Host (numpy) double-double arithmetic.

Mirror of ``pint_tpu.ops.dd`` for load-time host code (TOA ingest, clock
chains, TDB conversion).  Kept separate because host numpy guarantees IEEE
f64 semantics on every machine, whereas the device path may run on TPUs
whose f64 is emulated (non-IEEE) — ingest must not silently lose precision
by being traced onto such a device.  The two implementations share
algorithms and are cross-checked in tests/test_timebase.py.
"""

from __future__ import annotations

import numpy as np

_SPLITTER = 134217729.0  # 2**27 + 1


def _two_sum(a, b):
    s = a + b
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def _quick_two_sum(a, b):
    s = a + b
    err = b - (s - a)
    return s, err


def _two_prod(a, b):
    p = a * b
    t = _SPLITTER * a
    ahi = t - (t - a)
    alo = a - ahi
    t = _SPLITTER * b
    bhi = t - (t - b)
    blo = b - bhi
    err = ((ahi * bhi - p) + ahi * blo + alo * bhi) + alo * blo
    return p, err


class HostDD:
    """value = hi + lo, numpy arrays (or scalars)."""

    __slots__ = ("hi", "lo")
    __array_priority__ = 100  # beat ndarray in mixed binary ops

    def __init__(self, hi, lo=None):
        self.hi = np.asarray(hi, dtype=np.float64)
        self.lo = (
            np.zeros_like(self.hi)
            if lo is None
            else np.asarray(lo, dtype=np.float64)
        )

    # -- construction ----------------------------------------------------
    @staticmethod
    def from_sum(a, b) -> "HostDD":
        a = np.asarray(a, dtype=np.float64)
        b = np.asarray(b, dtype=np.float64)
        return HostDD(*_two_sum(a, b))

    @staticmethod
    def from_prod(a, b) -> "HostDD":
        a = np.asarray(a, dtype=np.float64)
        b = np.asarray(b, dtype=np.float64)
        return HostDD(*_two_prod(a, b))

    @staticmethod
    def from_string(s) -> "HostDD":
        """Exact decimal-string parse; s may be a str or sequence of str."""
        from decimal import Decimal, localcontext

        def one(x):
            with localcontext() as ctx:
                ctx.prec = 50
                d = Decimal(x)
                hi = float(d)
                lo = float(d - Decimal(hi))
            return hi, lo

        if isinstance(s, str):
            hi, lo = one(s)
            return HostDD(hi, lo)
        pairs = [one(x) for x in s]
        return HostDD(
            np.array([p[0] for p in pairs]), np.array([p[1] for p in pairs])
        )

    def normalize(self) -> "HostDD":
        return HostDD(*_quick_two_sum(self.hi, self.lo))

    # -- arithmetic ------------------------------------------------------
    def _coerce(self, other) -> "HostDD":
        return other if isinstance(other, HostDD) else HostDD(other)

    def __add__(self, other):
        other = self._coerce(other)
        s, e = _two_sum(self.hi, other.hi)
        e = e + (self.lo + other.lo)
        return HostDD(*_quick_two_sum(s, e))

    __radd__ = __add__

    def __neg__(self):
        return HostDD(-self.hi, -self.lo)

    def __sub__(self, other):
        return self + (-self._coerce(other))

    def __rsub__(self, other):
        return (-self) + other

    def __mul__(self, other):
        other = self._coerce(other)
        p, e = _two_prod(self.hi, other.hi)
        e = e + (self.hi * other.lo + self.lo * other.hi)
        return HostDD(*_quick_two_sum(p, e))

    __rmul__ = __mul__

    def __truediv__(self, other):
        other = self._coerce(other)
        q1 = self.hi / other.hi
        r = self - other * q1
        q2 = r.hi / other.hi
        r = r - other * q2
        q3 = r.hi / other.hi
        s, e = _quick_two_sum(q1, q2)
        return HostDD(*_quick_two_sum(s, e + q3))

    def __rtruediv__(self, other):
        return self._coerce(other) / self

    # -- comparisons -----------------------------------------------------
    def __lt__(self, other):
        d = (self - other).normalize()
        return (d.hi < 0) | ((d.hi == 0) & (d.lo < 0))

    def __gt__(self, other):
        d = (self - other).normalize()
        return (d.hi > 0) | ((d.hi == 0) & (d.lo > 0))

    def __le__(self, other):
        return ~(self > other)

    def __ge__(self, other):
        return ~(self < other)

    def __eq__(self, other):
        d = (self - other).normalize()
        return (d.hi == 0) & (d.lo == 0)

    def __ne__(self, other):
        return ~(self == other)

    __hash__ = None

    # -- conversions -----------------------------------------------------
    def to_float(self):
        return self.hi + self.lo

    def __float__(self):
        # scalar only (numpy raises on arrays, matching ndarray rules)
        return float(self.hi + self.lo)

    def split_int_frac(self):
        ihi = np.floor(self.hi + 0.5)
        rem = HostDD(self.hi - ihi, self.lo).normalize()
        ilo = np.floor(rem.hi + 0.5)
        frac = HostDD(rem.hi - ilo, rem.lo).normalize()
        carry = np.floor(frac.hi + frac.lo + 0.5)
        return ihi + ilo + carry, (frac - carry).to_float()

    # -- shape utilities -------------------------------------------------
    @property
    def shape(self):
        return self.hi.shape

    def __len__(self):
        return len(self.hi)

    def __getitem__(self, idx):
        return HostDD(self.hi[idx], self.lo[idx])

    def __repr__(self):
        return f"HostDD(hi={self.hi!r}, lo={self.lo!r})"

    def to_device(self):
        """Convert to the JAX-side DD pytree (pint_tpu.ops.dd.DD)."""
        import jax.numpy as jnp

        from pint_tpu.ops.dd import DD

        return DD(jnp.asarray(self.hi), jnp.asarray(self.lo))
