"""TimeArray: integer MJD + double-double seconds-of-day, scale-tagged.

Reference parity: replaces astropy ``Time`` + the custom "pulsar_mjd"
format (src/pint/pulsar_mjd.py) and the longdouble ``tdbld`` TOA column.
Design: the day number is exact (int64); time-of-day is HostDD seconds
(~1e-28 s resolution); conversions between UTC/TAI/TT/TDB/TCB/TCG keep
everything in exact + DD arithmetic, so round-trips hold to ~1e-20 s.

MJD string parsing supports both conventions:
- ``format="pulsar_mjd"`` (Tempo/Princeton convention, the reference's
  default for tim files): fractional day * 86400 s even on leap-second
  days — i.e. the label is interpreted as if every UTC day had 86400 s.
- ``format="mjd"``: true elapsed-seconds interpretation (a leap-second
  day has 86401 s, so frac .99999 can land inside the leap second).
Both agree except during/after a leap second within a day.
"""

from __future__ import annotations

from typing import Iterable, Union

import numpy as np

from pint_tpu.constants import (
    L_B,
    L_G,
    MJD_J2000,
    SECS_PER_DAY,
    TDB0,
    TT_MINUS_TAI,
)
from pint_tpu.exceptions import PintTpuError
from pint_tpu.ops.tdb import tdb_minus_tt
from pint_tpu.timebase.hostdd import HostDD
from pint_tpu.timebase.leapseconds import is_leap_second_day, tai_minus_utc

SCALES = ("utc", "tai", "tt", "tdb", "tcg", "tcb", "ut1")

# MJD(TT) of 1977-01-01T00:00:32.184 TT == JD 2443144.5003725, the shared
# origin epoch of TT/TCG/TCB rate transforms (IAU 1991/2000/2006).
_T77_MJD = 43144.0
_T77_SEC = 32.184


# The conversion graph is a chain (tcg hangs off tt):
#   utc -- tai -- tt -- tdb -- tcb        tt -- tcg
_CHAIN = ["utc", "tai", "tt", "tdb", "tcb"]

# first MJD of the leap-second era (1972-01-01, TAI-UTC = 10 s)
_LEAP_GUARD = 41317


def _route(src: str, dst: str) -> list[str]:
    """Sequence of intermediate scales (excluding src) from src to dst."""
    for s in (src, dst):
        if s == "ut1":
            raise PintTpuError(
                "ut1 conversions require an EOP table "
                "(pint_tpu.io.eop); not available on this TimeArray"
            )

    def chain_pos(s):
        return _CHAIN.index(s if s != "tcg" else "tt")

    route = []
    if src == "tcg":
        route.append("tt")
        src = "tt"
    i, j = chain_pos(src), _CHAIN.index(dst if dst != "tcg" else "tt")
    if i != j:
        step = 1 if j > i else -1
        stop = j + step if j + step >= 0 else None
        route += _CHAIN[i + step : stop : step]
    if dst == "tcg":
        route.append("tcg")
    return route


def _norm(mjd_int: np.ndarray, sec: HostDD, day_len=SECS_PER_DAY):
    """Carry seconds into days so 0 <= sec < day_len (uniform-day scales)."""
    carry = np.floor(sec.hi / day_len)
    sec = sec - carry * day_len
    # fix boundary cases from the f64 floor
    neg = (sec.hi < 0)
    sec = HostDD(
        np.where(neg, sec.hi + day_len, sec.hi), sec.lo
    ).normalize()
    carry = carry - neg
    over = sec.hi >= day_len
    sec = HostDD(np.where(over, sec.hi - day_len, sec.hi), sec.lo).normalize()
    carry = carry + over
    return mjd_int + carry.astype(np.int64), sec


class TimeArray:
    """An array of epochs: ``mjd_int`` (int64 days) + ``sec`` (HostDD
    seconds-of-day) in time scale ``scale``."""

    __slots__ = ("mjd_int", "sec", "scale")

    def __init__(self, mjd_int, sec: HostDD, scale: str = "utc"):
        if scale not in SCALES:
            raise PintTpuError(f"unknown time scale {scale!r}")
        self.mjd_int = np.atleast_1d(np.asarray(mjd_int, dtype=np.int64))
        sec = sec if isinstance(sec, HostDD) else HostDD(sec)
        self.sec = HostDD(
            np.broadcast_to(np.atleast_1d(sec.hi), self.mjd_int.shape).copy(),
            np.broadcast_to(np.atleast_1d(sec.lo), self.mjd_int.shape).copy(),
        )
        self.scale = scale

    # ------------------------------------------------------------------ #
    @staticmethod
    def from_mjd_strings(
        strings: Union[str, Iterable[str]],
        scale: str = "utc",
        format: str = "pulsar_mjd",
    ) -> "TimeArray":
        """Exact parse of decimal MJD strings (tim-file convention)."""
        if isinstance(strings, str):
            strings = [strings]
        strings = list(strings)
        if format not in ("pulsar_mjd", "mjd"):
            raise PintTpuError(f"unknown MJD format {format!r}")
        if format == "pulsar_mjd" or scale != "utc":
            # native batched parse (C++ double-double); identical
            # convention: fraction of an 86400 s day.  Errors are
            # normalized to PintTpuError so callers see the same
            # exception type with or without the native library.
            from pint_tpu import native

            try:
                parsed = native.parse_mjd_strings(strings)
            except ValueError as e:
                raise PintTpuError(str(e)) from e
            if parsed is not None:
                day, hi, lo = parsed
                return TimeArray(day, HostDD(hi, lo), scale)
        ints, fracs = [], []
        for s in strings:
            s = s.strip()
            neg = s.startswith("-")
            if neg:
                raise PintTpuError(f"negative MJD not supported: {s}")
            ipart, _, fpart = s.partition(".")
            ints.append(int(ipart))
            fracs.append("0." + (fpart or "0"))
        mjd_int = np.array(ints, dtype=np.int64)
        frac = HostDD.from_string(fracs)
        if format == "mjd" and scale == "utc":
            day_len = np.where(
                is_leap_second_day(mjd_int), SECS_PER_DAY + 1, SECS_PER_DAY
            )
            sec = frac * day_len
        else:  # pulsar_mjd convention, or uniform-day (non-UTC) scales
            sec = frac * SECS_PER_DAY
        return TimeArray(mjd_int, sec, scale)

    @staticmethod
    def from_mjd_float(mjd, scale: str = "tdb") -> "TimeArray":
        """From float64 MJD (sub-µs resolution only — for tests/sim)."""
        mjd = np.atleast_1d(np.asarray(mjd, dtype=np.float64))
        mjd_int = np.floor(mjd).astype(np.int64)
        sec = HostDD(mjd - mjd_int) * SECS_PER_DAY
        return TimeArray(mjd_int, sec, scale)

    @staticmethod
    def from_mjd_two_part(day: int, sec_of_day, scale: str = "tdb"):
        return TimeArray(day, HostDD(sec_of_day), scale)

    # ------------------------------------------------------------------ #
    def to_mjd_strings(self, ndigits: int = 19) -> list[str]:
        """Decimal MJD strings (pulsar_mjd convention), round-trip safe.

        Limitation inherited from the pulsar_mjd format itself: an
        instant *inside* a leap second (sec-of-day >= 86400) has no
        representation; such values raise rather than silently shifting
        into the next day.
        """
        from decimal import Decimal, localcontext

        if self.scale == "utc" and np.any(self.sec.hi >= SECS_PER_DAY):
            raise PintTpuError(
                "cannot serialize an instant inside a leap second in "
                "pulsar_mjd format; convert to a uniform scale first"
            )
        out = []
        for i in range(len(self.mjd_int)):
            with localcontext() as ctx:
                ctx.prec = 40
                frac = (
                    Decimal(float(self.sec.hi[i])) + Decimal(float(self.sec.lo[i]))
                ) / Decimal(86400)
                total = Decimal(int(self.mjd_int[i])) + frac
                out.append(f"{total:.{ndigits}f}")
        return out

    def mjd_float(self) -> np.ndarray:
        """Approximate float64 MJD (for plotting/selection, ~µs)."""
        return self.mjd_int + self.sec.to_float() / SECS_PER_DAY

    def mjd_dd(self) -> HostDD:
        """MJD as HostDD days."""
        return HostDD(self.mjd_int.astype(np.float64)) + self.sec / SECS_PER_DAY

    def seconds_since(self, epoch_mjd_int, epoch_sec=0.0) -> HostDD:
        """(self - epoch) in DD seconds; exact day-difference arithmetic."""
        ddays = (self.mjd_int - np.int64(epoch_mjd_int)).astype(np.float64)
        return HostDD.from_prod(ddays, SECS_PER_DAY) + (self.sec - epoch_sec)

    # ------------------------------------------------------------------ #
    # scale conversions
    def to_scale(self, target: str) -> "TimeArray":
        if target == self.scale:
            return self
        t = self
        for hop in _route(self.scale, target):
            t = t._one_hop(hop)
        return t

    def _one_hop(self, target: str) -> "TimeArray":
        key = (self.scale, target)
        if key == ("utc", "tai"):
            return self._utc_to_tai()
        if key == ("tai", "utc"):
            return self._tai_to_utc()
        if key == ("tai", "tt"):
            return self._shift_const(TT_MINUS_TAI, "tt")
        if key == ("tt", "tai"):
            return self._shift_const(-TT_MINUS_TAI, "tai")
        if key == ("tt", "tdb"):
            return self._tt_to_tdb()
        if key == ("tdb", "tt"):
            return self._tdb_to_tt()
        if key == ("tt", "tcg"):
            return self._tt_to_tcg()
        if key == ("tcg", "tt"):
            return self._tcg_to_tt()
        if key == ("tdb", "tcb"):
            return self._tdb_to_tcb()
        if key == ("tcb", "tdb"):
            return self._tcb_to_tdb()
        raise PintTpuError(f"no conversion {key}")

    def _shift_const(self, dt_sec: float, scale: str) -> "TimeArray":
        mjd, sec = _norm(self.mjd_int, self.sec + dt_sec)
        return TimeArray(mjd, sec, scale)

    def _utc_to_tai(self) -> "TimeArray":
        off = tai_minus_utc(self.mjd_int).astype(np.float64)
        mjd, sec = _norm(self.mjd_int, self.sec + off)
        return TimeArray(mjd, sec, "tai")

    def _tai_to_utc(self) -> "TimeArray":
        # UTC day D starts at TAI-elapsed T_start(D) = (D-E)*86400+off(D)
        # (E = 41317, where TAI-UTC = 10 s).  Find the largest D with
        # T_start(D) <= T; then sec = T - T_start(D), which lands in
        # [86400, 86401) during a leap second — round-tripping exactly
        # through _utc_to_tai.
        E = _LEAP_GUARD
        T = self.seconds_since(E)  # DD TAI-elapsed
        q = np.floor(T.hi / SECS_PER_DAY).astype(np.int64)
        d0 = E + q
        off0 = tai_minus_utc(d0).astype(np.float64)
        # sec-of-day candidate for D = d0
        s0 = T - HostDD.from_prod(q.astype(np.float64), SECS_PER_DAY)
        in_prev = s0.hi < off0  # T before d0's start: belongs to d0-1
        d = np.where(in_prev, d0 - 1, d0)
        off = tai_minus_utc(d).astype(np.float64)
        sec = (
            s0
            + np.where(in_prev, SECS_PER_DAY, 0.0)
            - off
        ).normalize()
        return TimeArray(d, sec, "utc")

    def _tt_centuries(self) -> np.ndarray:
        return (
            (self.mjd_int - MJD_J2000) + self.sec.to_float() / SECS_PER_DAY
        ) / 36525.0

    def _tt_to_tdb(self) -> "TimeArray":
        d = tdb_minus_tt(self._tt_centuries(), xp=np)
        mjd, sec = _norm(self.mjd_int, self.sec + d)
        return TimeArray(mjd, sec, "tdb")

    def _tdb_to_tt(self) -> "TimeArray":
        # TDB-TT argument uses TT; one fixed-point pass is plenty (the
        # series slope is ~2e-8 s/s)
        d = tdb_minus_tt(self._tt_centuries(), xp=np)
        mjd, sec = _norm(self.mjd_int, self.sec - d)
        t1 = TimeArray(mjd, sec, "tt")
        d2 = tdb_minus_tt(t1._tt_centuries(), xp=np)
        mjd, sec = _norm(self.mjd_int, self.sec - d2)
        return TimeArray(mjd, sec, "tt")

    def _elapsed_since_t77(self) -> HostDD:
        return self.seconds_since(int(_T77_MJD), _T77_SEC)

    def _tt_to_tcg(self) -> "TimeArray":
        # TCG - TT = LG/(1-LG) * (TT - T77)
        rate = L_G / (1.0 - L_G)
        d = self._elapsed_since_t77() * rate
        mjd, sec = _norm(self.mjd_int, self.sec + d)
        return TimeArray(mjd, sec, "tcg")

    def _tcg_to_tt(self) -> "TimeArray":
        d = self._elapsed_since_t77() * L_G
        mjd, sec = _norm(self.mjd_int, self.sec - d)
        return TimeArray(mjd, sec, "tt")

    def _tdb_to_tcb(self) -> "TimeArray":
        # TDB = TCB - LB*(TCB - T77) + TDB0  =>  invert
        d = (self._elapsed_since_t77() - TDB0) * (L_B / (1.0 - L_B))
        mjd, sec = _norm(self.mjd_int, self.sec + d - TDB0)
        return TimeArray(mjd, sec, "tcb")

    def _tcb_to_tdb(self) -> "TimeArray":
        d = self._elapsed_since_t77() * L_B
        mjd, sec = _norm(self.mjd_int, self.sec - d + TDB0)
        return TimeArray(mjd, sec, "tdb")

    # ------------------------------------------------------------------ #
    @property
    def shape(self):
        return self.mjd_int.shape

    def __len__(self):
        return len(self.mjd_int)

    def __getitem__(self, idx) -> "TimeArray":
        return TimeArray(self.mjd_int[idx], self.sec[idx], self.scale)

    def __repr__(self):
        n = len(self.mjd_int)
        head = ", ".join(self[: min(n, 3)].to_mjd_strings(10))
        return f"TimeArray<{self.scale}>[{n}]({head}{'...' if n > 3 else ''})"

    def add_seconds(self, s) -> "TimeArray":
        """Shift by s seconds (float/array/HostDD), carrying days."""
        mjd, sec = _norm(self.mjd_int, self.sec + s)
        return TimeArray(mjd, sec, self.scale)

    def sort_index(self) -> np.ndarray:
        # lexsort: primary key last; exact ordering even at sub-ns spacing
        return np.lexsort((self.sec.lo, self.sec.hi, self.mjd_int))
