"""Host-side exact time substrate.

Reference parity: ``src/pint/pulsar_mjd.py`` (the "pulsar_mjd" Astropy Time
format) and the astropy/ERFA time-scale machinery PINT leans on.  Here the
host representation is ``TimeArray``: integer MJD + double-double
seconds-of-day, in a tagged time scale, backed by numpy (host numpy is
always IEEE f64, unlike the axon TPU device — see docs/precision.md).
"""

from pint_tpu.timebase.hostdd import HostDD  # noqa: F401
from pint_tpu.timebase.times import TimeArray  # noqa: F401
from pint_tpu.timebase.leapseconds import tai_minus_utc  # noqa: F401
