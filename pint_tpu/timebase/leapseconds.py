"""Leap-second (TAI-UTC) table, 1972+.

Reference parity: the reference relies on astropy's bundled ERFA leap-second
table (used implicitly by ``toa.py::TOAs.compute_TDBs``).  We embed the
public IERS announcements as (calendar date, TAI-UTC) and derive MJDs from
``datetime`` (no hand-typed day numbers).  The table is complete through
2017-01-01 (TAI-UTC = 37 s); no leap second has been announced since.  An
updated table can be loaded from a standard ``leap-seconds.list`` file via
:func:`load_leap_seconds_list`.

Pre-1972 ("rubber second") epochs are out of scope, matching the practical
domain of pulsar-timing data; conversions before MJD 41317 raise.
"""

from __future__ import annotations

import bisect
from datetime import date

import numpy as np

from pint_tpu.exceptions import PintTpuError

_MJD_EPOCH_ORDINAL = date(1858, 11, 17).toordinal()


def calendar_to_mjd(year: int, month: int, day: int) -> int:
    return date(year, month, day).toordinal() - _MJD_EPOCH_ORDINAL


# (effective date, TAI-UTC seconds) — IERS Bulletin C history.
_LEAP_HISTORY = [
    ((1972, 1, 1), 10),
    ((1972, 7, 1), 11),
    ((1973, 1, 1), 12),
    ((1974, 1, 1), 13),
    ((1975, 1, 1), 14),
    ((1976, 1, 1), 15),
    ((1977, 1, 1), 16),
    ((1978, 1, 1), 17),
    ((1979, 1, 1), 18),
    ((1980, 1, 1), 19),
    ((1981, 7, 1), 20),
    ((1982, 7, 1), 21),
    ((1983, 7, 1), 22),
    ((1985, 7, 1), 23),
    ((1988, 1, 1), 24),
    ((1990, 1, 1), 25),
    ((1991, 1, 1), 26),
    ((1992, 7, 1), 27),
    ((1993, 7, 1), 28),
    ((1994, 7, 1), 29),
    ((1996, 1, 1), 30),
    ((1997, 7, 1), 31),
    ((1999, 1, 1), 32),
    ((2006, 1, 1), 33),
    ((2009, 1, 1), 34),
    ((2012, 7, 1), 35),
    ((2015, 7, 1), 36),
    ((2017, 1, 1), 37),
]

_LEAP_MJDS = [calendar_to_mjd(*d) for d, _ in _LEAP_HISTORY]
_LEAP_OFFSETS = [off for _, off in _LEAP_HISTORY]


def load_leap_seconds_list(path) -> None:
    """Extend/replace the table from an NTP ``leap-seconds.list`` file
    (lines: NTP-epoch-seconds TAI-UTC).  NTP epoch 1900-01-01 = MJD 15020."""
    global _LEAP_MJDS, _LEAP_OFFSETS
    entries = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            ntp_sec, off = int(parts[0]), int(parts[1])
            mjd = 15020 + ntp_sec // 86400
            entries.append((mjd, off))
    entries.sort()
    if entries:
        _LEAP_MJDS = [e[0] for e in entries]
        _LEAP_OFFSETS = [e[1] for e in entries]


def tai_minus_utc(mjd_utc) -> np.ndarray:
    """TAI-UTC in integer seconds at the given UTC MJD(s) (1972+)."""
    mjd = np.atleast_1d(np.asarray(mjd_utc, dtype=np.int64))
    if np.any(mjd < _LEAP_MJDS[0]):
        raise PintTpuError(
            f"UTC before MJD {_LEAP_MJDS[0]} (1972-01-01) unsupported"
        )
    idx = np.searchsorted(_LEAP_MJDS, mjd, side="right") - 1
    out = np.asarray(_LEAP_OFFSETS, dtype=np.int64)[idx]
    return out if np.ndim(mjd_utc) else out[0]


def is_leap_second_day(mjd_utc) -> np.ndarray:
    """True where UTC day mjd has 86401 seconds (day before a step)."""
    mjd = np.atleast_1d(np.asarray(mjd_utc, dtype=np.int64))
    out = np.isin(mjd + 1, np.asarray(_LEAP_MJDS))
    return out if np.ndim(mjd_utc) else out[0]


def leap_second_table():
    """(mjd array, TAI-UTC array) — for inspection/serialization."""
    return np.asarray(_LEAP_MJDS), np.asarray(_LEAP_OFFSETS)
