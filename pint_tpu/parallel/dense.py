"""Sharded dense-covariance GLS: blocked Cholesky over the mesh.

SURVEY.md §7 step 6: the reference's full_cov=True path is an O(n^3)
n x n factorization (src/pint/fitter.py::GLSFitter.fit_toas with
full_cov) that walls at ~1e4 TOAs on one core.  Here the factorization
is a right-looking BLOCKED Cholesky whose trailing-submatrix update —
where all the O(n^3) FLOPs live — is a full-width (n, b) @ (b, n)
GEMM that XLA partitions over the mesh ('toa'-axis row sharding, the
same axis the Woodbury paths shard).  The O(n^2) panel solves and the
O(b^3) diagonal factorizations stay replicated: at n/b >= 8 blocks the
GEMM dominates, so wall-clock scales with devices while the sequential
critical path (n/b small factorizations) stays negligible.

Two precision modes mirroring fitting/gls.py::gls_step_full_cov:
  f64    — blocked Cholesky in f64 (CPU / validation);
  mixed  — Jacobi equilibration + blocked f32 Cholesky on the MXU +
           f64 iterative refinement (the chol_solve_ir recipe,
           ops/ffgram.py, with the factorization sharded).

The IR residual products are O(n^2 p) — two orders below the
factorization — and stay replicated (split-f32 matmul).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from pint_tpu.fitting.gls import _column_norms, _finish_normal_eqs


def _constrain(mesh, x, spec):
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec)
    )


def blocked_cholesky(C, block: int = 1024, mesh=None, axis: str = "toa"):
    """Lower Cholesky factor of SPD C (n, n), any n.

    Right-looking blocked algorithm with a PYTHON-UNROLLED outer loop:
    every iteration slices the true trailing submatrix with static
    shapes, so the trailing-update GEMM — where all the O(n^3) FLOPs
    live — does exactly sum_j (n-j-b)^2 b ~ n^3/3 MACs.  (The r3
    fori_loop version carried the full (n, n) matrix and updated
    full-height zero-masked panels: ~3x the FLOPs, the measured 6.6 vs
    19.2 TF/s gap to XLA's native factorization — VERDICT r3 weak 2.)
    The O(b^3) diagonal factorizations use XLA's native Cholesky and
    stay replicated; with `mesh`, the trailing matrix is row-sharded
    over `axis` and the update GEMM runs partitioned.  dtype follows C
    (f32 for the mixed path).

    n that is not a block multiple is zero-padded with a unit diagonal
    (the padded factor is block-diagonal [L, I], so slicing back to
    (n, n) is exact) — arbitrary real TOA counts work without a
    caller-side padding recipe (ADVICE r2; VERDICT r2 weak 5)."""
    n = C.shape[0]
    pad = (-n) % block
    if pad:
        C = jnp.pad(C, ((0, pad), (0, pad)))
        C = C.at[
            jnp.arange(n, n + pad), jnp.arange(n, n + pad)
        ].set(jnp.asarray(1.0, dtype=C.dtype))
    npad = n + pad
    A = C
    col_blocks = []
    for j in range(0, npad, block):
        A = _constrain(mesh, A, P(axis, None))
        Ld = jnp.linalg.cholesky(A[:block, :block])  # replicated
        pan = jax.scipy.linalg.solve_triangular(
            Ld, A[block:, :block].T, lower=True
        ).T
        col_blocks.append((Ld, pan))
        if j + block < npad:
            pan = _constrain(mesh, pan, P(axis, None))
            # the O((n-j)^2 b) trailing GEMM — sharded, static shapes.
            # precision=HIGHEST is load-bearing: the TPU default matmul
            # (bf16 passes) loses ~1e-3 relative in pan@pan.T, and the
            # Schur cancellation 1 - ||pan_row||^2 then goes NEGATIVE
            # on real red-noise covariances (unit-diagonal + rank-k
            # with ||W||_F^2 ~ 1e4) — sqrt(neg) NaNs the next diagonal
            # block.  XLA's native Cholesky pins its internal GEMMs the
            # same way (r4: zero-phi test matrices never exposed this).
            A = A[block:, block:] - jnp.matmul(
                pan, pan.T, precision=jax.lax.Precision.HIGHEST
            )
            A = _constrain(mesh, A, P(axis, None))
    L = jnp.zeros((npad, npad), C.dtype)
    for k, (Ld, pan) in enumerate(col_blocks):
        j = k * block
        L = L.at[j:j + block, j:j + block].set(Ld)
        if pan.shape[0]:
            L = L.at[j + block:, j:j + block].set(pan)
    return L[:n, :n]


def sharded_chol_solve_ir(C, B, block: int = 512, mesh=None,
                          axis: str = "toa", refine: int = 2):
    """chol_solve_ir (ops/ffgram.py — the single equilibration+IR
    recipe and accuracy contract) with the f32 factorization swapped
    for the mesh-sharded blocked Cholesky."""
    from pint_tpu.ops.ffgram import chol_solve_ir

    return chol_solve_ir(
        C, B, refine=refine,
        cholesky=lambda A32: blocked_cholesky(
            A32, block=block, mesh=mesh, axis=axis
        ),
    )


def sharded_gls_step_full_cov(mesh, r, M, Ndiag, T, phi,
                              method: str = "mixed",
                              axis: str = "toa", block: int = 512,
                              normalized_cov=False):
    """Dense-covariance GLS step with the n x n factorization sharded
    over the mesh — the multi-chip form of fitting/gls.py::
    gls_step_full_cov (same normal-equation assembly, same precision
    modes).  Any n: the factorization pads to the block size
    internally (unit-diagonal padding; see blocked_cholesky)."""
    from pint_tpu.models.noise import dense_noise_cov

    C = dense_noise_cov(Ndiag, T, phi)
    C = _constrain(mesh, C, P(axis, None))
    norm = _column_norms(M)
    Mn = M / norm[None, :]
    X = jnp.concatenate([Mn, r[:, None]], axis=1)
    if method == "mixed":
        from pint_tpu.ops.ffgram import matmul_split32

        CiX = sharded_chol_solve_ir(
            C, X, block=block, mesh=mesh, axis=axis
        )
        G = matmul_split32(X.T, CiX)
        return _finish_normal_eqs(
            G[:-1, :-1], -G[:-1, -1], G[-1, -1], norm, normalized_cov
        )
    if method != "f64":
        raise ValueError(f"unknown method {method!r}")
    L = blocked_cholesky(C, block=block, mesh=mesh, axis=axis)
    Y = jax.scipy.linalg.solve_triangular(L, X, lower=True)
    CiX = jax.scipy.linalg.solve_triangular(L.T, Y, lower=False)
    G = X.T @ CiX
    return _finish_normal_eqs(
        G[:-1, :-1], -G[:-1, -1], G[-1, -1], norm, normalized_cov
    )
