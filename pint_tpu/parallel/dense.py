"""Sharded dense-covariance GLS: blocked Cholesky over the mesh.

SURVEY.md §7 step 6: the reference's full_cov=True path is an O(n^3)
n x n factorization (src/pint/fitter.py::GLSFitter.fit_toas with
full_cov) that walls at ~1e4 TOAs on one core.  Here the factorization
is a right-looking BLOCKED Cholesky whose trailing-submatrix update —
where all the O(n^3) FLOPs live — is a full-width (n, b) @ (b, n)
GEMM that XLA partitions over the mesh ('toa'-axis row sharding, the
same axis the Woodbury paths shard).  The O(n^2) panel solves and the
O(b^3) diagonal factorizations stay replicated: at n/b >= 8 blocks the
GEMM dominates, so wall-clock scales with devices while the sequential
critical path (n/b small factorizations) stays negligible.

Two precision modes mirroring fitting/gls.py::gls_step_full_cov:
  f64    — blocked Cholesky in f64 (CPU / validation);
  mixed  — Jacobi equilibration + blocked f32 Cholesky on the MXU +
           f64 iterative refinement (the chol_solve_ir recipe,
           ops/ffgram.py, with the factorization sharded).

The IR residual products are O(n^2 p) — two orders below the
factorization — and stay replicated (split-f32 matmul).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from pint_tpu.fitting.gls import _column_norms, _finish_normal_eqs

# lint: module(matmul-highest) — every matmul here carries an explicit
# precision: a single default bf16 pass NaNs the Schur cancellation
# (see blocked_cholesky's precision note; tools/lint rule f64-emu)
# lint: module(ir-refined) — the 'high' (bf16x3) trailing GEMMs here
# are preconditioner-grade by contract: f64 iterative refinement with
# the TRUE operator sits on top (fast_cholesky32 / chol_solve_ir;
# tools/lint rule f64-emu check 5)


def _constrain(mesh, x, spec):
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec)
    )


def _panel_factor(Cc, block, prec, panel, bump, eye):
    """Factor one panel from its fully-updated block column Cc
    ((m, b): diagonal block on top, sub-column below) — the shared
    diagonal-factorization + panel-solve step of both schedules."""
    D = Cc[:block, :block]
    if bump is not None:
        D = D + bump
    Ld = jnp.linalg.cholesky(D)  # replicated
    if panel == "inv":
        Ldinv = jax.scipy.linalg.solve_triangular(Ld, eye, lower=True)
        pan = jnp.matmul(Cc[block:], Ldinv.T, precision=prec)
    else:
        pan = jax.scipy.linalg.solve_triangular(
            Ld, Cc[block:].T, lower=True
        ).T
    return Ld, pan


def _lookahead_factor(A, npad, block, mesh, axis, prec, panel, bump,
                      eye, update_chunks):
    """Depth-1 lookahead schedule for blocked_cholesky (ISSUE 13).

    Loop invariant: A is the trailing matrix whose FIRST block column
    already carries every earlier panel's Schur update, and (Ld, pan)
    — the last col_blocks entry — is that column's factorization,
    computed BEFORE the previous iteration's remainder GEMM was
    needed.  Each iteration then (a) forms ONLY the next block
    column's update, a thin (m, b) GEMM, and factors panel j+1 from
    it immediately; (b) applies the remainder of panel j's trailing
    update — the big sharded GEMM — in update_chunks independent
    column groups.  (b) has no data dependency on panel j+1's serial
    O(b^3) factorization/panel-solve, so the compiler can run them
    concurrently, and each chunk's inter-shard pan gather can overlap
    a neighboring chunk's local GEMM."""
    if update_chunks <= 0:
        update_chunks = 2 if mesh is not None else 1
    nblk = npad // block
    # remote-compile budget: the sequential schedule emits ~nblk
    # trailing GEMMs; cap the chunked count at ~2x that (CLAUDE.md's
    # n=32768 transport limit)
    while update_chunks > 1 and nblk * (update_chunks + 1) > 96:
        update_chunks -= 1
    A = _constrain(mesh, A, P(axis, None))
    col_blocks = [_panel_factor(A[:, :block], block, prec, panel,
                                bump, eye)]
    for _ in range(nblk - 1):
        _, pan = col_blocks[-1]
        pan = _constrain(mesh, pan, P(axis, None))
        m = A.shape[0] - block
        # (a) next block column, fully updated — panel j+1 factors
        # from it without waiting on the remainder GEMM below
        Cnext = A[block:, block:2 * block] - jnp.matmul(
            pan, pan[:block].T, precision=prec
        )
        col_blocks.append(_panel_factor(Cnext, block, prec, panel,
                                        bump, eye))
        # (b) remainder trailing update in independent column groups
        pieces = [Cnext]
        rest = m - block
        if rest > 0:
            nch = min(update_chunks, max(1, rest // block))
            bounds = [rest * i // nch for i in range(nch + 1)]
            for c0, c1 in zip(bounds[:-1], bounds[1:]):
                piece = A[block:, 2 * block + c0:2 * block + c1]
                piece = piece - jnp.matmul(
                    pan, pan[block + c0:block + c1].T, precision=prec
                )
                pieces.append(_constrain(mesh, piece, P(axis, None)))
            A = jnp.concatenate(pieces, axis=1)
        else:
            A = Cnext
        A = _constrain(mesh, A, P(axis, None))
    return col_blocks


def blocked_cholesky(C, block: int = 1024, mesh=None, axis: str = "toa",
                     precision: str = "highest", panel: str = "solve",
                     diag_bump: float = 0.0, lookahead=None,
                     update_chunks: int = 0):
    """Lower Cholesky factor of SPD C (n, n), any n.

    Right-looking blocked algorithm with a PYTHON-UNROLLED outer loop:
    every iteration slices the true trailing submatrix with static
    shapes, so the trailing-update GEMM — where all the O(n^3) FLOPs
    live — does exactly sum_j (n-j-b)^2 b ~ n^3/3 MACs.  (The r3
    fori_loop version carried the full (n, n) matrix and updated
    full-height zero-masked panels: ~3x the FLOPs, the measured 6.6 vs
    19.2 TF/s gap to XLA's native factorization — VERDICT r3 weak 2.)
    The O(b^3) diagonal factorizations use XLA's native Cholesky and
    stay replicated; with `mesh`, the trailing matrix is row-sharded
    over `axis` and the update GEMM runs partitioned.  dtype follows C
    (f32 for the mixed path).

    precision ('highest'|'high') sets the trailing-GEMM matmul passes
    on TPU.  'highest' (6-pass bf16 emulation) is the safe default:
    a single bf16 pass loses ~1e-3 relative in pan@pan.T and the Schur
    cancellation 1 - ||pan_row||^2 then goes NEGATIVE on real
    red-noise covariances (unit-diagonal + rank-k with ||W||_F^2 ~
    1e4) — sqrt(neg) NaNs the next diagonal block; XLA's native
    Cholesky pins its internal GEMMs the same way.  'high' (3-pass
    bf16x3, ~f32 fidelity: measured factor residual 7e-6 vs 2e-7 rel
    on the red-noise operand, profiling/cholesky_variants.py) is for
    PRECONDITIONER use where f64 iterative refinement with the true
    operator sits on top — see fast_cholesky32.

    panel ('solve'|'inv') picks the panel computation: XLA's
    sequential triangular solve, or a GEMM against the explicitly
    inverted b x b diagonal block (O(n b^2) at MXU rate instead of the
    solve's serial critical path; the inverse of a well-conditioned
    equilibrated diagonal block is stable at f32).

    diag_bump adds a ridge to every diagonal entry, applied PER
    DIAGONAL BLOCK at factor time — algebraically identical to
    factorizing C + bump*I (earlier Schur updates never touch a later
    block's ridge) but O(b) per block instead of an O(n^2) full-matrix
    scatter, which XLA materializes as a copy of the whole operand
    (~11 ms of pure HBM traffic at n=16384 — measured r5).

    n that is not a block multiple is zero-padded with a unit diagonal
    (the padded factor is block-diagonal [L, I], so slicing back to
    (n, n) is exact) — arbitrary real TOA counts work without a
    caller-side padding recipe (ADVICE r2; VERDICT r2 weak 5).

    lookahead (None = $PINT_TPU_DENSE_LOOKAHEAD, default on; ISSUE 13)
    selects the depth-1 lookahead/double-buffered schedule: panel j's
    trailing update is SPLIT into (a) the next block-column's update —
    a small (m, b, b) GEMM from which panel j+1 factors IMMEDIATELY —
    and (b) the remainder update, the big sharded GEMM, which carries
    no data dependency into panel j+1's factorization, so the compiler
    is free to run the serial O(b^3) factorization and panel solve
    while the shard-parallel GEMM (and its inter-shard collective) is
    in flight.  update_chunks (0 = auto: 2 when sharded, 1 otherwise)
    additionally splits the remainder update into independent
    block-column groups so each chunk's collective (the pan gather)
    can overlap the neighboring chunk's local GEMM — psum/gather
    splitting on the ('toa',) mesh.  The chunk count is capped so the
    python-unrolled HLO stays inside the remote-compile budget
    (CLAUDE.md's n=32768 transport limit).  Element-wise the schedule
    computes the same contractions (each output element is the same
    dot over b terms), but fusion boundaries differ, so exact bitwise
    equality with the sequential schedule is not guaranteed —
    PINT_TPU_DENSE_LOOKAHEAD=0 (or lookahead=False) restores the
    sequential schedule bitwise.  Overlap is MEASURED, not asserted:
    profiling/cholesky_sweep.py and sharded_dense_scaling.py emit the
    per-rung lookahead times and estimated overlap fraction."""
    prec = {
        "highest": jax.lax.Precision.HIGHEST,
        "high": jax.lax.Precision.HIGH,
    }[precision]
    n = C.shape[0]
    pad = (-n) % block
    if pad:
        C = jnp.pad(C, ((0, pad), (0, pad)))
        C = C.at[
            jnp.arange(n, n + pad), jnp.arange(n, n + pad)
        ].set(jnp.asarray(1.0, dtype=C.dtype))
    npad = n + pad
    A = C
    col_blocks = []
    eye = jnp.eye(block, dtype=C.dtype)
    bump = (
        jnp.asarray(diag_bump, C.dtype) * jnp.eye(block, dtype=C.dtype)
        if diag_bump else None
    )
    if lookahead is None:
        from pint_tpu.ops.solve_policy import dense_lookahead

        lookahead = dense_lookahead()
    if lookahead:
        col_blocks = _lookahead_factor(
            A, npad, block, mesh, axis, prec, panel, bump, eye,
            update_chunks,
        )
    else:
        for j in range(0, npad, block):
            A = _constrain(mesh, A, P(axis, None))
            D = A[:block, :block]
            if bump is not None:
                D = D + bump
            Ld = jnp.linalg.cholesky(D)  # replicated
            if panel == "inv":
                Ldinv = jax.scipy.linalg.solve_triangular(
                    Ld, eye, lower=True
                )
                pan = jnp.matmul(
                    A[block:, :block], Ldinv.T, precision=prec
                )
            else:
                pan = jax.scipy.linalg.solve_triangular(
                    Ld, A[block:, :block].T, lower=True
                ).T
            col_blocks.append((Ld, pan))
            if j + block < npad:
                pan = _constrain(mesh, pan, P(axis, None))
                # the O((n-j)^2 b) trailing GEMM — sharded, static
                # shapes
                A = A[block:, block:] - jnp.matmul(
                    pan, pan.T, precision=prec
                )
                A = _constrain(mesh, A, P(axis, None))
    L = jnp.zeros((npad, npad), C.dtype)
    for k, (Ld, pan) in enumerate(col_blocks):
        j = k * block
        L = L.at[j:j + block, j:j + block].set(Ld)
        if pan.shape[0]:
            L = L.at[j + block:, j:j + block].set(pan)
    return L[:n, :n]


def fast_cholesky32(Aeq32, block: int = 512, ridge: float = 3e-5):
    """MXU-rate f32 Cholesky of an EQUILIBRATED (unit-diagonal) SPD
    operand, for preconditioner use only — the r5 answer to VERDICT r4
    weak 2.

    Measured on-chip at n=16384 on the real red-noise-conditioned
    operand, with the 85 ms tunnel round-trip amortized over a 16-deep
    dependent chain (the r3/r4 sweeps' chain=4 left ~21 ms of tunnel
    latency in EVERY per-step number, uniformly deflating them —
    profiling/cholesky_sweep.py): this configuration factorizes at
    22.6 TF/s vs XLA's native custom call at 19.6 — the trailing GEMM
    (where all n^3/3 FLOPs live) runs 3-pass bf16x3 ('high') instead
    of 6-pass, and block=512 keeps the O(n^2 b) panel solves small.
    Variants measured and rejected on the same operand (r5): panel-by
    -inverse at HIGH NaNs (Ldinv's large entries amplify the 3-pass
    error into the Schur cancellation, from the last diagonal block
    outward); 1-pass DEFAULT NaNs outright; blocks 256 (17.2 TF/s)
    and 1024 (22.2) bracket the 512 optimum; panel-by-inverse with a
    HIGHEST pan-GEMM ties (22.5) with more failure surface.  The cost
    is factor accuracy (~7e-6 vs ~2e-7 relative residual), IRRELEVANT
    for the chol_solve_ir/woodbury_chol_solve_ir preconditioner role: the
    refinement residual applies the TRUE f64 operator, so the refined
    solution converges to the exact solve.  At the production refine=2
    the refined step was probed INDISTINGUISHABLE from the native
    factor's (on-chip n=8192 red-noise operands; the on-chip accuracy
    net pins the full 8192-16384 selection window) — an extra pass is
    available headroom at O(n^2 p), two orders below the
    factorization, should a future operand class need it.

    `ridge` bumps the unit diagonal before factorizing (applied per
    diagonal block inside the kernel — a full-matrix diagonal scatter
    would copy the whole n^2 operand): the 3-pass Schur error (~1e-5
    absolute on an equilibrated operand) could drive a genuinely tiny
    trailing pivot negative and NaN the factor; a few-x-error ridge
    removes that failure class entirely and is, again, only a
    preconditioner perturbation.  Do NOT use this for a direct
    (non-refined) factorization — blocked_cholesky(precision=
    'highest') or the native call are the accuracy-bearing routes.

    The outer loop is python-unrolled, so n/block is compile-time HLO
    size: past 32 blocks the remote-compile cost explodes (n=32768 at
    b=512 = 64 unrolled trailing updates).  block grows to keep the
    unroll depth <= 32; the b=1024 rate (22.2 TF/s) is within 2% of
    the b=512 optimum anyway."""
    while Aeq32.shape[0] > 32 * block:
        block *= 2
    return blocked_cholesky(Aeq32, block=block, precision="high",
                            panel="solve", diag_bump=ridge)


def sharded_chol_solve_ir(C, B, block: int = 512, mesh=None,
                          axis: str = "toa", refine: int = 2,
                          check_rtol=None):
    """chol_solve_ir (ops/ffgram.py — the single equilibration+IR
    recipe and accuracy contract) with the f32 factorization swapped
    for the mesh-sharded blocked Cholesky.  check_rtol passes through
    to the post-refinement residual check (ops/solve_policy.py)."""
    from pint_tpu.ops.ffgram import chol_solve_ir

    return chol_solve_ir(
        C, B, refine=refine,
        cholesky=lambda A32: blocked_cholesky(
            A32, block=block, mesh=mesh, axis=axis
        ),
        check_rtol=check_rtol,
    )


def sharded_gls_step_full_cov(mesh, r, M, Ndiag, T, phi,
                              method: str = "mixed",
                              axis: str = "toa", block: int = 512,
                              normalized_cov=False):
    """Dense-covariance GLS step with the n x n factorization sharded
    over the mesh — the multi-chip form of fitting/gls.py::
    gls_step_full_cov (same normal-equation assembly, same precision
    modes).  Any n: the factorization pads to the block size
    internally (unit-diagonal padding; see blocked_cholesky)."""
    from pint_tpu.models.noise import dense_noise_cov

    C = dense_noise_cov(Ndiag, T, phi)
    C = _constrain(mesh, C, P(axis, None))
    norm = _column_norms(M)
    Mn = M / norm[None, :]
    X = jnp.concatenate([Mn, r[:, None]], axis=1)
    if method == "mixed":
        from pint_tpu.ops.ffgram import matmul_split32
        from pint_tpu.ops import solve_policy

        CiX = sharded_chol_solve_ir(
            C, X, block=block, mesh=mesh, axis=axis,
            check_rtol=solve_policy.check_rtol(),
        )
        G = matmul_split32(X.T, CiX)
        return _finish_normal_eqs(
            G[:-1, :-1], -G[:-1, -1], G[-1, -1], norm, normalized_cov,
            ir=True,
        )
    if method != "f64":
        raise ValueError(f"unknown method {method!r}")
    L = blocked_cholesky(C, block=block, mesh=mesh, axis=axis)
    Y = jax.scipy.linalg.solve_triangular(L, X, lower=True)
    CiX = jax.scipy.linalg.solve_triangular(L.T, Y, lower=False)
    # HIGHEST: this f64 rung also runs on accelerators (the fallback
    # ladder), where the default bf16-pass matmul would quietly
    # degrade the normal-equation Gram it feeds _finish_normal_eqs
    G = jnp.matmul(X.T, CiX, precision=jax.lax.Precision.HIGHEST)
    return _finish_normal_eqs(
        G[:-1, :-1], -G[:-1, -1], G[-1, -1], norm, normalized_cov
    )
