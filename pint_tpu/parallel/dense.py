"""Sharded dense-covariance GLS: blocked Cholesky over the mesh.

SURVEY.md §7 step 6: the reference's full_cov=True path is an O(n^3)
n x n factorization (src/pint/fitter.py::GLSFitter.fit_toas with
full_cov) that walls at ~1e4 TOAs on one core.  Here the factorization
is a right-looking BLOCKED Cholesky whose trailing-submatrix update —
where all the O(n^3) FLOPs live — is a full-width (n, b) @ (b, n)
GEMM that XLA partitions over the mesh ('toa'-axis row sharding, the
same axis the Woodbury paths shard).  The O(n^2) panel solves and the
O(b^3) diagonal factorizations stay replicated: at n/b >= 8 blocks the
GEMM dominates, so wall-clock scales with devices while the sequential
critical path (n/b small factorizations) stays negligible.

Two precision modes mirroring fitting/gls.py::gls_step_full_cov:
  f64    — blocked Cholesky in f64 (CPU / validation);
  mixed  — Jacobi equilibration + blocked f32 Cholesky on the MXU +
           f64 iterative refinement (the chol_solve_ir recipe,
           ops/ffgram.py, with the factorization sharded).

The IR residual products are O(n^2 p) — two orders below the
factorization — and stay replicated (split-f32 matmul).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from pint_tpu.fitting.gls import _column_norms, _finish_normal_eqs


def _constrain(mesh, x, spec):
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec)
    )


def blocked_cholesky(C, block: int = 1024, mesh=None, axis: str = "toa"):
    """Lower Cholesky factor of SPD C (n, n), any n.

    Right-looking blocked algorithm; with `mesh`, the working matrix is
    row-sharded over `axis` and the trailing update GEMM runs
    partitioned.  dtype follows C (f32 for the mixed path).

    n that is not a block multiple is zero-padded with a unit diagonal
    (the padded factor is block-diagonal [L, I], so slicing back to
    (n, n) is exact) — arbitrary real TOA counts work without a
    caller-side padding recipe (ADVICE r2; VERDICT r2 weak 5).

    Default block 1024: measured fastest on the bench chip (n=16384
    f32: 223 ms vs 357 ms at block 512).  Single-device callers should
    prefer jnp.linalg.cholesky (XLA's native factorization measured
    3x faster — 19.2 vs 6.6 TF/s at n=16384 f32); this kernel's value
    is the mesh-sharded trailing update."""
    n = C.shape[0]
    pad = (-n) % block
    if pad:
        C = jnp.pad(C, ((0, pad), (0, pad)))
        C = C.at[
            jnp.arange(n, n + pad), jnp.arange(n, n + pad)
        ].set(jnp.asarray(1.0, dtype=C.dtype))
    npad = n + pad
    nblocks = npad // block
    row = jnp.arange(npad)

    def body(i, C):
        j = i * block
        C = _constrain(mesh, C, P(axis, None))
        D = jax.lax.dynamic_slice(C, (j, j), (block, block))
        Ld = jnp.linalg.cholesky(D)  # (b, b), replicated
        cols = jax.lax.dynamic_slice(C, (0, j), (npad, block))
        # panel = C[:, j:j+b] @ Ld^-T; rows j..j+b come out as Ld
        panel = jax.scipy.linalg.solve_triangular(
            Ld, cols.T, lower=True
        ).T
        in_panel = (row >= j)[:, None]
        C = jax.lax.dynamic_update_slice(
            C, jnp.where(in_panel, panel, cols), (0, j)
        )
        # trailing update: only rows/cols >= j+b have nonzero product
        below = (row >= j + block)[:, None]
        Lb = jnp.where(below, panel, jnp.zeros_like(panel))
        Lb = _constrain(mesh, Lb, P(axis, None))
        C = C - Lb @ Lb.T  # the O(n^2 b) GEMM — sharded
        return _constrain(mesh, C, P(axis, None))

    C = jax.lax.fori_loop(0, nblocks, body, C)
    return jnp.tril(C)[:n, :n]


def sharded_chol_solve_ir(C, B, block: int = 512, mesh=None,
                          axis: str = "toa", refine: int = 2):
    """chol_solve_ir (ops/ffgram.py — the single equilibration+IR
    recipe and accuracy contract) with the f32 factorization swapped
    for the mesh-sharded blocked Cholesky."""
    from pint_tpu.ops.ffgram import chol_solve_ir

    return chol_solve_ir(
        C, B, refine=refine,
        cholesky=lambda A32: blocked_cholesky(
            A32, block=block, mesh=mesh, axis=axis
        ),
    )


def sharded_gls_step_full_cov(mesh, r, M, Ndiag, T, phi,
                              method: str = "mixed",
                              axis: str = "toa", block: int = 512,
                              normalized_cov=False):
    """Dense-covariance GLS step with the n x n factorization sharded
    over the mesh — the multi-chip form of fitting/gls.py::
    gls_step_full_cov (same normal-equation assembly, same precision
    modes).  Any n: the factorization pads to the block size
    internally (unit-diagonal padding; see blocked_cholesky)."""
    from pint_tpu.models.noise import dense_noise_cov

    C = dense_noise_cov(Ndiag, T, phi)
    C = _constrain(mesh, C, P(axis, None))
    norm = _column_norms(M)
    Mn = M / norm[None, :]
    X = jnp.concatenate([Mn, r[:, None]], axis=1)
    if method == "mixed":
        from pint_tpu.ops.ffgram import matmul_split32

        CiX = sharded_chol_solve_ir(
            C, X, block=block, mesh=mesh, axis=axis
        )
        G = matmul_split32(X.T, CiX)
        return _finish_normal_eqs(
            G[:-1, :-1], -G[:-1, -1], G[-1, -1], norm, normalized_cov
        )
    if method != "f64":
        raise ValueError(f"unknown method {method!r}")
    L = blocked_cholesky(C, block=block, mesh=mesh, axis=axis)
    Y = jax.scipy.linalg.solve_triangular(L, X, lower=True)
    CiX = jax.scipy.linalg.solve_triangular(L.T, Y, lower=False)
    G = X.T @ CiX
    return _finish_normal_eqs(
        G[:-1, :-1], -G[:-1, -1], G[-1, -1], norm, normalized_cov
    )
