"""Mesh construction + bundle sharding.

The TOA axis is the framework's data axis: every per-TOA kernel
(residuals, design matrix, noise scaling) is embarrassingly parallel
over it, and the GLS normal equations reduce over it (psum inserted by
XLA).  ``shard_bundle`` places a TOABundle's leading axis across the
'toa' mesh axis; everything else (parameters, bases) is replicated or
model-sharded by the fitters.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pint_tpu.ops.dd import DD
from pint_tpu.toas.bundle import TOABundle


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Join a multi-host run (the framework's distributed communication
    backend is XLA collectives over ICI within a slice and DCN across
    hosts — docs/parallelism.md; the reference has no distributed
    backend at all, SURVEY.md §5).

    Call once per process before any jax computation; with no arguments
    on Cloud TPU the coordinator is auto-discovered from the cluster
    environment.  After this, jax.devices() is the GLOBAL device list,
    so make_mesh() spans all hosts and the same fit/PTA programs run
    unchanged — the Gram psums are the only cross-host traffic
    (k-sized blocks, a few hundred KB per step).  Returns the process
    index.  Explicit no-op when already initialized, or when neither an
    address nor a detectable cluster environment exists (single-process
    dev boxes); anything else propagates — a silently-degraded
    "multi-host" job that actually runs single-host must not happen.
    """
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None and is_init():
        return jax.process_index()
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except ValueError as e:
        # With an explicit coordinator (or explicit process topology)
        # a ValueError is a real configuration error and must
        # propagate.  Otherwise jax's cluster auto-detection found no
        # usable environment: stay single-process, but say so — a
        # misconfigured auto-detected cluster (e.g. inconsistent SLURM
        # env) lands here too, and the sibling ranks would hang at the
        # coordinator while this rank silently ran alone.
        if (
            coordinator_address is not None
            or num_processes is not None
            or process_id is not None
        ):
            raise
        import warnings

        warnings.warn(
            "initialize_distributed(): no cluster environment joined "
            f"({e}); staying single-process",
            RuntimeWarning,
        )
        return 0
    except RuntimeError:
        # "must be called before any JAX calls": too late to join.
        # With an EXPLICIT coordinator this must fail loudly (a
        # silently single-host "multi-host" job is the worst outcome);
        # without one, the caller was only opportunistically probing —
        # warn and stay single-process.
        if coordinator_address is not None:
            raise
        import warnings

        warnings.warn(
            "initialize_distributed() called after the JAX backend "
            "initialized; staying single-process (call it first to "
            "join a cluster)",
            RuntimeWarning,
        )
        return jax.process_index()
    return jax.process_index()


def serving_devices(n: Optional[int] = None) -> list:
    """Devices backing the serving fabric's replica pool
    (serve/fabric/pool.py): the default backend's local devices — the
    tests' virtual 8-device CPU mesh (conftest's XLA_FLAGS) and the
    axon TPU slice both surface here, so the fabric exercises real
    multi-device placement without hardware.  ``n`` clamps the pool
    width to the first n devices (never below 1, never above what
    exists); None/0 = all."""
    devs = list(jax.local_devices())
    if n:
        devs = devs[: max(1, min(int(n), len(devs)))]
    return devs


def gang_mesh(devices) -> Mesh:
    """1-D ('toa',) mesh over a gang's device subset.

    The serving fabric's gang replicas (serve/fabric/gang.py) carve
    contiguous subsets out of :func:`serving_devices` and shard their
    big-bucket session dispatches over this mesh — same axis name and
    layout convention as the batch shard_map kernels
    (parallel/gls.py::sharded_gls_step, parallel/dense.py), so the
    collectives GSPMD inserts match the ones those kernels spell
    explicitly (docs/parallelism.md)."""
    devs = list(devices)
    if not devs:
        raise ValueError("gang_mesh: empty device set")
    return Mesh(np.asarray(devs), axis_names=("toa",))


def make_mesh(
    n_toa_shards: Optional[int] = None,
    n_pulsar_shards: int = 1,
    devices=None,
) -> Mesh:
    """Mesh with axes ('pulsar', 'toa').

    Defaults to all local devices on the toa axis — the right layout for
    single-pulsar fits; PTA batches trade devices onto the pulsar axis.
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if n_toa_shards is None:
        n_toa_shards = n // n_pulsar_shards
    if n_toa_shards * n_pulsar_shards != n:
        raise ValueError(
            f"{n_pulsar_shards} x {n_toa_shards} != {n} devices"
        )
    dev = np.asarray(devices).reshape(n_pulsar_shards, n_toa_shards)
    return Mesh(dev, axis_names=("pulsar", "toa"))


def _pad_to(n: int, k: int) -> int:
    return (n + k - 1) // k * k


def pad_axis0(tree, cur: int, pad: int):
    """Pad every leaf whose leading axis is `cur` by repeating its last
    row `pad` times (shared by TOA-axis sharding and PTA batching)."""

    def padleaf(x):
        if isinstance(x, jnp.ndarray) and x.ndim >= 1 and x.shape[0] == cur:
            return jnp.concatenate(
                [x, jnp.repeat(x[-1:], pad, axis=0)], axis=0
            )
        return x

    return jax.tree_util.tree_map(padleaf, tree)


def pad_bundle(bundle: TOABundle, multiple: int) -> tuple[TOABundle, np.ndarray]:
    """Pad the TOA axis to a multiple of the shard count.

    Padded entries get zero weight via the returned validity mask (f64
    0/1); zero-weight TOAs contribute nothing to fits (weights multiply
    every reduction).  Padding duplicates the last TOA so kernels stay
    NaN-free.
    """
    n = bundle.ntoa
    m = _pad_to(n, multiple)
    if m == n:
        return bundle, np.ones(n)
    pad = m - n
    new = pad_axis0(bundle, n, pad)
    valid = np.concatenate([np.ones(n), np.zeros(pad)])
    return new, valid


def shard_bundle(bundle: TOABundle, mesh: Mesh) -> TOABundle:
    """Place every per-TOA leaf across the 'toa' mesh axis."""
    n = bundle.ntoa
    sharding = NamedSharding(mesh, P("toa"))

    def place(x):
        if isinstance(x, jnp.ndarray) and x.ndim >= 1 and x.shape[0] == n:
            spec = ("toa",) + (None,) * (x.ndim - 1)
            return jax.device_put(x, NamedSharding(mesh, P(*spec)))
        return x

    del sharding
    return jax.tree_util.tree_map(place, bundle)


def replicate(x, mesh: Mesh):
    return jax.device_put(x, NamedSharding(mesh, P()))
