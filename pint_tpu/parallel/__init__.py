"""Distributed layer: mesh definition, sharding specs, collectives.

Reference parity: the reference has NO distributed backend (SURVEY.md
§2 parallelism checklist) — its axes of scale are NumPy vectorization
over TOAs and BLAS threads.  Here the same axes become first-class mesh
axes:
  'toa'    — data parallelism over the TOA axis (residual/design kernels)
  'pulsar' — batch parallelism over pulsars (PTA-scale vmap)
  'model'  — model parallelism for dense covariance factorizations
XLA collectives (psum for normal-equation reduction, collective-permute
inside sharded Cholesky) ride ICI within a slice / DCN across slices.
"""

from pint_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    shard_bundle,
    replicate,
)
