"""Explicitly-sharded GLS: shard_map + psum over the TOA axis.

SURVEY.md §5 "distributed communication backend": the framework's
collective layer is XLA collectives over the mesh — here made explicit
with shard_map so the communication pattern is auditable and portable
to multi-host slices (ICI within a slice, DCN across; the same psum
works over both).

The GLS normal equations decompose exactly over TOA shards:

  M^T N^-1 M   = sum_s  M_s^T N_s^-1 M_s          (psum, (p, p))
  T^T N^-1 T   = sum_s  T_s^T N_s^-1 T_s          (psum, (k, k))
  T^T N^-1 M/r = sum_s  ...                       (psum, (k, p+1))
  r^T N^-1 r   = sum_s  ...                       (psum, scalar)

so each device touches only its TOA shard; the only communication is
the psum of small (p, p)/(k, k)/(k, p) blocks — O(k^2) bytes per step,
independent of n.  The k x k and p x p solves then run replicated.
This is the pjit-autosharding path's explicit twin: results match
gls_step_woodbury exactly (tests/test_sharded_gls.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from pint_tpu.fitting.gls import _chol_solve, _finish_normal_eqs


def sharded_gls_step(mesh, r, M, Ndiag, T, phi, axis: str = "toa",
                     normalized_cov=False):
    """One Woodbury GLS solve with the TOA axis sharded over `axis`.

    r (n,), M (n, p), Ndiag (n,), T (n, k) must have n divisible by the
    mesh axis size (pad with ~infinite-error TOAs via parallel.mesh /
    parallel.pta helpers).  phi (k,) is replicated.
    Returns (dx (p,), cov (p, p), chi2, n_degenerate) — identical to
    gls_step_woodbury.  On backends whose emulated f64 keeps only the
    f32 exponent range (axon TPU), pass normalized_cov=True and
    unnormalize cov = covn/outer(norm, norm) on the HOST — stiff-column
    variances underflow on device (fitting/gls.py::_finish_normal_eqs).
    """
    try:
        from jax import shard_map
    except ImportError:  # pre-0.4.x-alias jax: experimental home
        from jax.experimental.shard_map import shard_map

    def local_blocks(r_s, M_s, Nd_s, T_s):
        """Per-shard partial sums; psum makes them global."""
        Ninv = 1.0 / Nd_s
        NM = M_s * Ninv[:, None]
        blocks = (
            M_s.T @ NM,                 # (p, p)
            T_s.T @ (T_s * Ninv[:, None]),  # (k, k)
            T_s.T @ NM,                 # (k, p)
            M_s.T @ (Ninv * r_s),       # (p,)
            T_s.T @ (Ninv * r_s),       # (k,)
            jnp.dot(r_s, Ninv * r_s),   # ()
        )
        return jax.tree_util.tree_map(
            lambda b: jax.lax.psum(b, axis), blocks
        )

    sm = shard_map(
        local_blocks,
        mesh=mesh,
        in_specs=(P(axis), P(axis, None), P(axis), P(axis, None)),
        out_specs=(P(), P(), P(), P(), P(), P()),
    )

    # column normalization must be global (shared helper keeps this
    # path numerically identical to the unsharded one)
    from pint_tpu.fitting.gls import _column_norms

    norm = _column_norms(M)
    Mn = M / norm[None, :]

    MNM, TNT, TNM, MNr, TNr, rNr = sm(r, Mn, Ndiag, T)

    # replicated small solves (Woodbury assembly)
    Sigma = jnp.diag(1.0 / phi) + TNT
    corrM = _chol_solve(Sigma, TNM)       # Sigma^-1 T^T N^-1 Mn
    corrR = _chol_solve(Sigma, TNr[:, None])[:, 0]
    A = MNM - TNM.T @ corrM
    b = -(MNr - TNM.T @ corrR)
    r_cinv_r = rNr - jnp.dot(TNr, corrR)
    return _finish_normal_eqs(A, b, r_cinv_r, norm, normalized_cov)


def sharded_gls_step_mixed(mesh, r, M, Ndiag, T, phi, axis: str = "toa",
                           normalized_cov=False):
    """The PRODUCTION accelerator path (mixed precision, f32 MXU
    Grams with f64 accumulation — fitting/gls.py::
    gls_step_woodbury_mixed) with the TOA axis sharded over `axis`.

    The chunked f32 Grams decompose over TOA shards exactly like the
    f64 ones: each device runs gram32_joint on its shard and the psum
    of the small (k+p+1)^2 blocks makes them global — identical
    collective pattern and O(k^2) bytes per step as sharded_gls_step,
    same precision contract as the single-device mixed path
    (_woodbury_mixed_tail; chunk-level f64 accumulation happens within
    each shard, and the cross-shard psum is f64).

    Under solve_policy.fused_interior_active each shard's local Gram
    runs the fused Pallas pass instead (shard_map is MANUAL
    partitioning — the kernel sees a per-device static shape, so the
    GSPMD auto-partitioning hazard that makes gang shard mode bypass
    the fusion does not apply here); the psum pattern is unchanged.
    """
    try:
        from jax import shard_map
    except ImportError:  # pre-0.4.x-alias jax: experimental home
        from jax.experimental.shard_map import shard_map

    from pint_tpu.fitting.gls import _column_norms
    from pint_tpu.fitting.gls import _woodbury_mixed_tail
    from pint_tpu.ops import solve_policy
    from pint_tpu.ops.ffgram import gram32_joint

    # fused-interior decision OUTSIDE shard_map, on the PER-SHARD
    # static shape (shard_map splits the TOA axis evenly): the fused
    # branch needs check_rep=False (pallas_call has no replication
    # rule), so the choice of gram and the shard_map flags must agree
    use_fused = False
    if solve_policy.fused_interior_active():
        from pint_tpu.ops.pallas_fit import fused_block_table

        n_s = -(-r.shape[0] // mesh.size)
        use_fused = (
            fused_block_table(n_s, T.shape[-1], M.shape[-1] + 1)
            is not None
        )

    norm = _column_norms(M)
    Mn = M / norm[None, :]

    def local_grams(r_s, Mn_s, Nd_s, T_s):
        Ninv = 1.0 / Nd_s
        X = jnp.concatenate([Mn_s, r_s[:, None]], axis=1)
        if use_fused:
            from pint_tpu.ops.pallas_fit import fused_gram_joint

            sig_tt, twx, G_XX = fused_gram_joint(
                T_s.astype(jnp.float32), X, Ninv
            )
        else:
            sig_tt, twx, G_XX = gram32_joint(
                T_s.astype(jnp.float32), X, Ninv
            )
        return jax.tree_util.tree_map(
            lambda b: jax.lax.psum(b, axis), (sig_tt, twx, G_XX)
        )

    sm = shard_map(
        local_grams,
        mesh=mesh,
        in_specs=(P(axis), P(axis, None), P(axis), P(axis, None)),
        out_specs=(P(), P(), P()),
        # the unfused path keeps replication checking exactly as
        # before (check_rep=True is bitwise the pre-fusion program)
        check_rep=not use_fused,
    )
    sig_tt, twx, G_XX = sm(r, Mn, Ndiag, T)
    return _woodbury_mixed_tail(G_XX, sig_tt, twx, phi, norm,
                                normalized_cov)


def guarded_sharded_gls_step(mesh, r, M, Ndiag, T, phi,
                             axis: str = "toa", normalized_cov=False):
    """One sharded GLS step dispatched under the device-execution
    guard (runtime/): watchdog + transient-retry at the dispatch
    (runtime/guard.py::dispatch_guard), post-step finite validation,
    and a two-rung degradation ladder mixed -> f64 on accelerator
    meshes (a sharded step cannot leave its mesh, so there is no CPU
    rung here; on CPU meshes the second rung is a clean re-dispatch of
    the f64 collective path).  Returns ((dx, cov, chi2, nbad),
    GuardReport)."""
    from pint_tpu.runtime.fallback import run_ladder
    from pint_tpu.runtime.guard import dispatch_guard, validate_finite

    def make_thunk(step_fn, name):
        fn = dispatch_guard(
            jax.jit(
                lambda *ops: step_fn(
                    mesh, *ops, axis=axis, normalized_cov=normalized_cov
                )
            ),
            site=f"parallel.gls:{name}",
        )

        def thunk(rung_site):
            return fn(r, M, Ndiag, T, phi)

        return thunk

    if jax.default_backend() != "cpu":
        rungs = [
            ("tpu-mixed", make_thunk(sharded_gls_step_mixed, "mixed")),
            ("tpu-f64", make_thunk(sharded_gls_step, "f64")),
        ]
    else:
        rungs = [
            ("cpu-f64", make_thunk(sharded_gls_step, "f64")),
            ("cpu-f64-retry", make_thunk(sharded_gls_step, "f64b")),
        ]

    def validate(out, rung_site):
        dx, _cov, chi2, _nbad = out
        validate_finite({"dx": dx, "chi2": chi2}, site=rung_site,
                        what="sharded GLS step")

    return run_ladder(rungs, site="parallel.gls.step",
                      validate=validate)


def place_gls_operands(mesh, r, M, Ndiag, T, phi, axis: str = "toa"):
    """Device-put the operands with the sharding sharded_gls_step
    expects (TOA axis across `axis`, phi replicated)."""
    shard1 = NamedSharding(mesh, P(axis))
    shard2 = NamedSharding(mesh, P(axis, None))
    repl = NamedSharding(mesh, P())
    return (
        jax.device_put(r, shard1),
        jax.device_put(M, shard2),
        jax.device_put(Ndiag, shard1),
        jax.device_put(T, shard2),
        jax.device_put(phi, repl),
    )
