"""PTA-scale batching: vmap/shard the pulsar axis.

SURVEY.md §7 step 8 / BASELINE.json config 5: fit tens of pulsars as one
batched device computation.  The reference has no batch axis at all
(one Python process per pulsar); here the pulsar axis is a leading vmap
axis over the same compiled kernels, sharded across the mesh's
'pulsar' axis while each pulsar's TOA axis rides 'toa'
(parallel.mesh.make_mesh).

Requirements for stacking: the pulsars must share a model composition
(same free-parameter layout, same mask keys, same noise-basis column
count — the common case for survey-uniform PTA data); TOA counts may
differ (padding with ~infinite-error TOAs that carry zero weight).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import PintTpuError
from pint_tpu.fitting.base import noffset
from pint_tpu.fitting.gls import default_accel_mode, gauss_newton_step
from pint_tpu.toas.bundle import TOABundle

#: Uncertainty assigned to padded TOAs (microseconds).  The value must
#: thread the emulated-f64 hazard window of docs/precision.md on BOTH
#: sides (regression-tested in tests/test_pta_batch.py::
#: test_pad_error_emulated_f64_headroom; axon's f32-pair f64 keeps the
#: f32 EXPONENT range):
#:
#: * big enough that pad rows are statistically invisible: the pad
#:   weight 1/(1e18 us)^2 = 1e-24 s^-2 is ~1e-36 of a 1-us real TOA's
#:   1e12 s^-2 — fit perturbations land ~25 decades below f64
#:   roundoff;
#: * small enough that nothing overflows or flushes on device:
#:   - Ndiag entry sigma^2 = (1e12 s)^2 = 1e24 stays ~14 decades under
#:     the f32-range ceiling 3.4e38 (sigma itself under the ~1.8e19
#:     square ceiling of runtime/guard.py::F32_SQUARE_CEILING);
#:   - the Woodbury whitening forms 1/sigma^2 = 1e-24, ~14 decades
#:     above the ~1.2e-38 flush-to-zero floor (and safely above the
#:     1/x-overflow floor ~1e-38 — cf. noise_basis_or_empty's 1e-30
#:     degenerate weight, chosen against the same hazard);
#:   - padded weighted design columns |M·sqrt(w)|: pad rows repeat the
#:     last real TOA, so |M| <= ~1e17 (the F4+ spindown-column scale of
#:     the weighted-design assembly ceiling) times sqrt(w)=1e-12 is
#:     ~1e5 — far under the |M·sqrt(w)| ~3.4e38 assembly ceiling.
#:
#: Raising this past ~1e19 starts eating the sigma^2 headroom on
#: device; lowering it below ~1e9 starts giving pad rows measurable
#: (>1e-18 relative) statistical weight.  1e18 sits mid-window.
PAD_ERROR_US = 1e18


def pad_bundle_to(bundle: TOABundle, n: int) -> TOABundle:
    """Pad the TOA axis to length n by repeating the last TOA with
    ~infinite error (zero statistical weight)."""
    from pint_tpu.parallel.mesh import pad_axis0

    cur = bundle.ntoa
    if cur == n:
        return bundle
    if cur > n:
        raise PintTpuError(f"cannot pad {cur} TOAs down to {n}")
    pad = n - cur
    out = pad_axis0(bundle, cur, pad)
    return out._replace(
        error_us=jnp.concatenate(
            [bundle.error_us, jnp.full(pad, PAD_ERROR_US)]
        )
    )


def _device_ref(cm):
    """Split a CompiledModel's host reference values into (numeric
    device pytree, static host dict).  The numeric part is what differs
    per pulsar and gets stacked/vmapped; strings/bools stay static.
    One splitter serves this, the single-model runtime-ref arguments,
    AND the serving engine's per-par records (serve/session.py::
    ParRecord uses the ``device=False`` host variant so population
    admission never touches the device) — see
    models/timing_model.py::split_ref_runtime.  This shared trace
    surface is why a fresh par can join an existing stacked serving
    kernel without re-tracing: the kernels have always traced with
    these leaves as (vmapped) runtime values."""
    from pint_tpu.models.timing_model import split_ref_runtime

    return split_ref_runtime(cm.ref)


class PTABatch:
    """A pulsar-axis batch over per-pulsar CompiledModels."""

    def __init__(self, cms: list):
        if not cms:
            raise PintTpuError("empty PTA batch")
        names = cms[0].free_names
        for cm in cms[1:]:
            if cm.free_names != names:
                raise PintTpuError(
                    "PTA batch needs identical free-parameter layouts: "
                    f"{names} vs {cm.free_names}"
                )
            if set(cm.bundle.masks) != set(cms[0].bundle.masks):
                raise PintTpuError("PTA batch needs identical mask keys")
            for k, v0 in cms[0].bundle.masks.items():
                v = cm.bundle.masks[k]
                if v.shape[1:] != v0.shape[1:]:
                    # e.g. precomputed noise-basis matrices with
                    # different harmonic counts (mismatched TNREDC)
                    raise PintTpuError(
                        "PTA batch needs identical noise-basis/mask "
                        f"structure: mask {k!r} is {v0.shape} vs "
                        f"{v.shape} — match TNREDC / ECORR epoch "
                        "structures across pulsars"
                    )
        self.cms = cms
        self.free_names = names
        self.npulsars = len(cms)
        self._fit_loops: dict = {}  # compiled scan loops by (mode, maxiter)
        nmax = max(cm.bundle.ntoa for cm in cms)
        padded = [pad_bundle_to(cm.bundle, nmax) for cm in cms]
        self.bundle = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *padded
        )
        self.ntoa = nmax
        self._proto = cms[0]
        # per-pulsar reference parameter values become batched data
        # (each pulsar's x is a delta from ITS OWN par-file values)
        refs = [_device_ref(cm) for cm in cms]
        num_keys = set(refs[0][0])
        for num, static in refs[1:]:
            if set(num) != num_keys:
                raise PintTpuError(
                    "PTA batch needs identical numeric parameter sets"
                )
            if static != refs[0][1]:
                raise PintTpuError(
                    "PTA batch needs identical static (string/bool) "
                    f"parameters: {static} vs {refs[0][1]}"
                )
        self._static_ref = refs[0][1]
        self.ref = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *[num for num, _ in refs]
        )
        # kernels read structural knobs (harmonic counts, epoch
        # quantization) off the HOST model at trace time; the basis
        # COLUMN structure must therefore agree across pulsars or the
        # prototype's structure would silently replace each pulsar's
        # own (the TOA axis differs pre-padding and is fine)
        def basis_cols(cm):
            T, phi = jax.eval_shape(
                cm.noise_basis_or_empty, jnp.zeros(len(names))
            )
            return T.shape[1:], phi.shape
        k0 = basis_cols(cms[0])
        for i, cm in enumerate(cms[1:], start=1):
            ki = basis_cols(cm)
            if ki != k0:
                raise PintTpuError(
                    "PTA batch needs identical noise-basis structure "
                    f"(pulsar 0: {k0}, pulsar {i}: {ki}) — match TNREDC"
                    " / ECORR epoch structures across pulsars"
                )

    # -- batched kernels --------------------------------------------------
    def _with_state(self, fn):
        """Run a CompiledModel method with a per-pulsar bundle + ref
        swapped into the prototype (the kernels read both off the
        instance; the swap happens at trace time under vmap)."""
        proto = self._proto

        def call(bundle, ref, *args):
            saved_b, saved_r = proto.bundle, proto.ref
            proto.bundle = bundle
            proto.ref = {**self._static_ref, **ref}
            try:
                return fn(proto, *args)
            finally:
                proto.bundle = saved_b
                proto.ref = saved_r

        return call

    def x0(self):
        return jnp.zeros(
            (self.npulsars, len(self.free_names)), dtype=jnp.float64
        )

    def residuals(self, xs):
        """(P, n) time residuals."""
        call = self._with_state(
            lambda cm, x: cm.time_residuals(x, subtract_mean=False)
        )
        return jax.vmap(call)(self.bundle, self.ref, xs)

    def chi2(self, xs):
        call = self._with_state(lambda cm, x: cm.chi2(x))
        return jax.vmap(call)(self.bundle, self.ref, xs)

    def _step_mode(self) -> str:
        """GLSFitter's production precision policy (shared helper:
        fitting/gls.py::default_accel_mode — mixed-precision MXU on
        accelerators with a correlated basis, exact f64 otherwise)."""
        return default_accel_mode(self._proto)

    def fit_step(self, xs, mode: str | None = None):
        """One batched GLS Gauss-Newton step for every pulsar:
        -> (new xs (P, p), chi2 (P,), (covn (P, p, p), norm (P, p))).

        mode: 'mixed' | 'f64' | None (None = _step_mode policy)."""
        no = noffset(self._proto)
        mode = mode or self._step_mode()
        if mode not in ("mixed", "f64"):
            raise PintTpuError(
                f"unknown PTA fit mode {mode!r}: expected 'mixed' or "
                "'f64'"
            )

        def single(cm, x):
            # the shared step assembly (fitting/gls.py::
            # gauss_newton_step — also the serving engine's batched
            # kernel body); covariance stays NORMALIZED on device
            # ((covn, norm) — raw variances of stiff columns underflow
            # f32-range emulated f64, see gls.py::_finish_normal_eqs);
            # fit() unnormalizes on the host
            xn, (covn, nrm), chi2, _ = gauss_newton_step(cm, x, mode)
            return xn, chi2, (covn[no:, no:], nrm[no:])

        call = self._with_state(single)
        return jax.vmap(call)(self.bundle, self.ref, xs)

    def fit(self, maxiter: int = 3, mode: str | None = None):
        """Iterated batched fit; returns (xs, chi2 (P,)).

        The whole iteration runs as ONE device program (lax.scan over
        the Gauss-Newton steps), so a PTA-batch fit costs a single
        dispatch regardless of maxiter — the batched sibling of
        GLSFitter._make_fit_loop."""
        if maxiter < 1:
            raise PintTpuError("PTABatch.fit needs maxiter >= 1")
        mode = mode or self._step_mode()
        key = (mode, maxiter)
        if key not in self._fit_loops:
            self._fit_loops[key] = self._make_fit_loop(mode, maxiter)
        xs, chi2, (covn, nrm) = self._fit_loops[key](self.x0())
        # unnormalize in HOST IEEE f64 (see fit_step)
        covn, nrm = np.asarray(covn), np.asarray(nrm)
        self.cov = covn / (nrm[:, :, None] * nrm[:, None, :])
        return xs, chi2

    def _make_fit_loop(self, mode: str, maxiter: int):
        p = len(self.free_names)

        # PTA batch loops predate the cm.jit chokepoint (per-pulsar
        # refs already ride as vmapped runtime args here); guard/span
        # coverage for this path is ROADMAP work
        @jax.jit  # lint: obs-ok (PTABatch pre-chokepoint path)
        def run(xs0):
            def body(carry, _):
                xs, _, _ = carry
                return self.fit_step(xs, mode=mode), None

            init = (
                xs0,
                jnp.zeros((self.npulsars,)),
                (
                    jnp.zeros((self.npulsars, p, p)),
                    jnp.ones((self.npulsars, p)),
                ),
            )
            (xs, chi2, _stale_cov), _ = jax.lax.scan(
                body, init, None, length=maxiter
            )
            # fit_step's chi2 is the linearized POST-step value of its
            # proposal (gls.py::_finish_normal_eqs: r_cinv_r - dx.b),
            # so the scan's last carry already belongs to the returned
            # xs — keep it (the GLSFitter convention).  The covariance,
            # however, was linearized at the PRE-step state; re-evaluate
            # at xs so committed uncertainties are not one step stale.
            _xs_next, _chi2_next, cov = self.fit_step(xs, mode=mode)
            return xs, chi2, cov

        return run

    def commit(self, xs, covs=None):
        """Fold fitted deltas back into each pulsar's host model, with
        per-parameter uncertainties from covs (P, p, p) — defaults to
        the last fit()'s covariance."""
        if covs is None:
            covs = getattr(self, "cov", None)
        for i, (cm, x) in enumerate(zip(self.cms, np.asarray(xs))):
            unc = None
            if covs is not None:
                unc = np.sqrt(np.diag(np.asarray(covs)[i]))
            cm.commit(x, uncertainties=unc)

    def shard(self, mesh):
        """Place the batch across the mesh: pulsar axis on 'pulsar',
        TOA axis on 'toa'."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        def place(x):
            if isinstance(x, jnp.ndarray) and x.ndim >= 2 and \
                    x.shape[0] == self.npulsars:
                spec = ("pulsar", "toa") + (None,) * (x.ndim - 2)
                return jax.device_put(x, NamedSharding(mesh, P(*spec)))
            return x

        self.bundle = jax.tree_util.tree_map(place, self.bundle)
        # compiled loops baked the previous (unsharded) arrays as
        # closure constants — they must not be reused
        self._fit_loops.clear()
        return self
