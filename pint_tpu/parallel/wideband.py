"""Mesh-sharded wideband GLS: the stacked [TOA; DM] system over the
TOA-sharding axis (VERDICT r4 missing 3).

The wideband fitter (fitting/wideband.py::WidebandTOAFitter, reference
src/pint/fitter.py::WidebandTOAFitter + pint_matrix.py combination)
solves a Woodbury system whose rows are the 2n stacked [TOA residual;
DM residual] equations: diagonal white part [sigma_toa^2;
sigma_dm^2], correlated bases acting on the TOA block only (zero DM
rows), one design matrix from jacfwd of the combined residual kernel.

Structurally that IS the system parallel/gls.py already shards — the
per-shard Gram partial sums decompose over ANY row partition, so the
DM block simply rides the same axis: stack, pad the row count to the
mesh divisor with ~infinite-variance rows (weight ~0: they drop out of
every N^-1-weighted sum), and delegate.  The f64 and mixed paths both
come along for free, with the same collective pattern (O((k+p)^2)
bytes per step, n-independent) and the same precision contracts as
narrowband.
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.parallel.gls import (
    place_gls_operands, sharded_gls_step, sharded_gls_step_mixed,
)

#: variance of a padding row: large enough that its weight vanishes in
#: every sum, small enough that 1/x stays far from the emulated-f64
#: overflow cliff (CLAUDE.md: f32 exponent range on axon)
_PAD_VAR = 1e30


def stack_wideband_operands(r_t, r_dm, M_t, M_dm, Nd_t, Nd_dm, T, phi,
                            multiple: int = 1):
    """Stack per-block operands into the (2n[+pad], ...) system the
    sharded steps consume.  T gets zero DM rows (correlated noise acts
    on TOAs only — fitting/wideband.py::_combined_basis).  Pad rows
    (to make the row count divisible by the mesh axis) carry zero
    residual/design and ~infinite variance."""
    r = jnp.concatenate([r_t, r_dm])
    M = jnp.concatenate([M_t, M_dm], axis=0)
    Nd = jnp.concatenate([Nd_t, Nd_dm])
    k = T.shape[1]
    T2 = jnp.concatenate(
        [T, jnp.zeros((r_dm.shape[0], k), T.dtype)], axis=0
    )
    n2 = r.shape[0]
    pad = (-n2) % multiple
    if pad:
        r = jnp.concatenate([r, jnp.zeros(pad, r.dtype)])
        M = jnp.concatenate(
            [M, jnp.zeros((pad, M.shape[1]), M.dtype)], axis=0
        )
        Nd = jnp.concatenate(
            [Nd, jnp.full(pad, _PAD_VAR, Nd.dtype)]
        )
        T2 = jnp.concatenate(
            [T2, jnp.zeros((pad, k), T2.dtype)], axis=0
        )
    return r, M, Nd, T2, phi


def sharded_wideband_step(mesh, r, M, Ndiag, T, phi,
                          axis: str = "toa", method: str = "f64",
                          normalized_cov=False):
    """One sharded wideband GLS step on pre-stacked operands (see
    stack_wideband_operands; row count must divide the mesh axis).
    method 'f64' | 'mixed' — the same two production paths as
    narrowband, byte-identical collective structure."""
    step = {"f64": sharded_gls_step, "mixed": sharded_gls_step_mixed}[
        method
    ]
    return step(mesh, r, M, Ndiag, T, phi, axis=axis,
                normalized_cov=normalized_cov)


def place_wideband_operands(mesh, r, M, Ndiag, T, phi,
                            axis: str = "toa"):
    """Device-put pre-stacked wideband operands with the row axis
    sharded — identical placement contract to the narrowband helper."""
    return place_gls_operands(mesh, r, M, Ndiag, T, phi, axis=axis)
