"""Generalized-least-squares fitter (correlated noise).

Reference parity: src/pint/fitter.py::GLSFitter.fit_toas — the hot fit
loop of SURVEY.md §3.3.  The noise covariance is C = N + T phi T^T with
diagonal N (scaled white errors) and a reduced-rank basis T (n,k),
k << n (ECORR epochs + red-noise harmonics).  Normal equations solve via
the Woodbury identity:

  C^-1 = N^-1 - N^-1 T (phi^-1 + T^T N^-1 T)^-1 T^T N^-1

so only k x k and p x p Cholesky factorizations run — all on device
(XLA Cholesky / triangular solves on the MXU).  full_cov=True takes the
explicit n x n dense path (the O(n^3) wall the TPU build attacks; used
for cross-validation and benchmarking).
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import ConvergenceFailure
from pint_tpu.fitting.base import Fitter
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.toas.toas import TOAs


def _chol_solve(A, B, jitter: float = 0.0):
    """Solve A X = B with A symmetric positive-definite via Cholesky."""
    if jitter:
        A = A + jitter * jnp.eye(A.shape[0])
    L = jnp.linalg.cholesky(A)
    Y = jax.scipy.linalg.solve_triangular(L, B, lower=True)
    return jax.scipy.linalg.solve_triangular(L.T, Y, lower=False)


def _solve_spd_threshold(A, B, threshold=None):
    """Solve A X = B (A symmetric PSD) zeroing near-degenerate
    eigendirections, mirroring the WLS SVD-threshold behavior so that
    degenerate models (e.g. a JUMP selecting all TOAs) produce a
    min-norm answer + DegeneracyWarning count instead of NaNs."""
    w, V = jnp.linalg.eigh(A)
    if threshold is None:
        threshold = jnp.finfo(jnp.float64).eps * A.shape[0]
    bad = w < threshold * jnp.max(w)
    winv = jnp.where(bad, 0.0, 1.0 / jnp.where(bad, 1.0, w))
    return (V * winv[None, :]) @ (V.T @ B), jnp.sum(bad)


def _column_norms(M):
    norm = jnp.sqrt(jnp.sum(M * M, axis=0))
    return jnp.where(norm == 0, 1.0, norm)


def _finish_normal_eqs(A, b, r_cinv_r, norm):
    """Shared normal-equation tail for every GLS flavor: SPD-threshold
    solve, covariance, chi2 = r^T C^-1 r minus the fitted decrement
    dx^T b (removes the offset-column power, matching the reference),
    column un-normalization."""
    dxn, nbad = _solve_spd_threshold(A, b[:, None])
    dxn = dxn[:, 0]
    covn, _ = _solve_spd_threshold(A, jnp.eye(A.shape[0]))
    chi2 = r_cinv_r - jnp.dot(dxn, b)
    return dxn / norm, covn / jnp.outer(norm, norm), chi2, nbad


def _solve_normal_eqs(cinv_mult, r, M):
    """Shared GLS tail: column-normalize, form/solve normal equations
    via an explicit C^-1-apply."""
    norm = _column_norms(M)
    Mn = M / norm[None, :]
    CiM = cinv_mult(Mn)
    Cir = cinv_mult(r[:, None])[:, 0]
    A = Mn.T @ CiM
    b = -(Mn.T @ Cir)
    return _finish_normal_eqs(A, b, jnp.dot(r, Cir), norm)


def make_cinv_mult(Ndiag, T, phi):
    """Build X -> C^-1 X for C = diag(Ndiag) + T diag(phi) T^T via the
    Woodbury identity.  The single shared implementation: the GLS
    proposal, the downhill acceptance objective, and wideband all use
    this builder so the factorization can never diverge between them."""
    Ninv = 1.0 / Ndiag
    # Sigma = phi^-1 + T^T N^-1 T  (k x k)
    TN = T * Ninv[:, None]  # N^-1 T  (n,k)
    Sigma = jnp.diag(1.0 / phi) + T.T @ TN

    def cinv_mult(X):
        NX = X * Ninv[:, None]
        return NX - TN @ _chol_solve(Sigma, TN.T @ X)

    return cinv_mult


def gls_step_woodbury(r, M, Ndiag, T, phi):
    """One GLS normal-equation solve, reduced-rank path.

    r (n,), M (n,p), Ndiag (n,), T (n,k), phi (k,) ->
    (dx (p,), cov (p,p), chi2, n_degenerate).
    """
    return _solve_normal_eqs(make_cinv_mult(Ndiag, T, phi), r, M)


def gls_step_woodbury_fourier(r, M, Ndiag, t_sec, freqs, phi):
    """Woodbury GLS with the Pallas fused-Gram kernels: the red-noise
    basis T = [sin, cos](2 pi f t) is never materialized — its Gram
    pieces stream through VMEM in f32 (ops/pallas_kernels.py).

    Mixed precision by design: residuals, white-noise weighting, and
    M^T N^-1 M stay f64; only the reduced-rank CORRECTION term (the
    noise covariance's low-rank part) runs f32.  Tested agreement vs
    the f64 path (tests/test_pallas_kernels.py): step directions to
    <2e-3 of the largest component, chi2 to <1e-3 relative,
    uncertainties to <5e-3 — i.e. well under a per-iteration Gauss-
    Newton tolerance, and iterated fits land within ~1e-2 sigma of the
    f64 solution.  Requires a pure-Fourier basis
    (CompiledModel.noise_fourier_spec).
    """
    from pint_tpu.ops.pallas_kernels import fourier_gram

    Ninv = 1.0 / Ndiag
    norm = _column_norms(M)
    Mn = M / norm[None, :]
    # f64 white-noise block (cheap: p is small)
    A_white = Mn.T @ (Mn * Ninv[:, None])
    b_white = Mn.T @ (Ninv * r)
    r_Nr = jnp.dot(r, Ninv * r)
    # f32 fused Gram of the basis against [Mn | r]
    X = jnp.concatenate([Mn, r[:, None]], axis=1)
    sig_tt, twx = fourier_gram(t_sec, freqs, Ninv, X)
    sig_tt = sig_tt.astype(jnp.float64)
    twx = twx.astype(jnp.float64)
    Sigma = jnp.diag(1.0 / phi) + sig_tt
    corr = _chol_solve(Sigma, twx)  # Sigma^-1 T^T N^-1 [Mn | r]
    A = A_white - twx[:, :-1].T @ corr[:, :-1]
    b = -(b_white - twx[:, :-1].T @ corr[:, -1])
    r_cinv_r = r_Nr - jnp.dot(twx[:, -1], corr[:, -1])
    return _finish_normal_eqs(A, b, r_cinv_r, norm)


def gls_step_full_cov(r, M, Ndiag, T, phi):
    """Dense-covariance path: C = diag(N) + T phi T^T, explicit n x n
    Cholesky (reference full_cov=True)."""
    C = jnp.diag(Ndiag)
    if T is not None:
        C = C + (T * phi[None, :]) @ T.T
    L = jnp.linalg.cholesky(C)

    def cinv_mult(X):
        Y = jax.scipy.linalg.solve_triangular(L, X, lower=True)
        return jax.scipy.linalg.solve_triangular(L.T, Y, lower=False)

    return _solve_normal_eqs(cinv_mult, r, M)


class GLSFitter(Fitter):
    """Iterated GLS fit; also correct (equals WLS) with no correlated
    noise in the model.

    fused='auto' (default) uses the Pallas mixed-precision fused-Gram
    Woodbury on accelerators when the correlated noise is a pure
    Fourier basis (see gls_step_woodbury_fourier for the validated
    accuracy bounds); fused=False forces the all-f64 path, fused=True
    forces the fused path (errors if the noise structure disallows it).
    """

    def __init__(self, toas: TOAs, model: TimingModel,
                 full_cov: bool = False, fused="auto"):
        super().__init__(toas, model)
        self.full_cov = full_cov
        self.fused = fused

    def _use_fused(self) -> bool:
        if self.fused is True and self.full_cov:
            from pint_tpu.exceptions import PintTpuError

            raise PintTpuError(
                "fused=True and full_cov=True are mutually exclusive "
                "(the fused path is reduced-rank by construction)"
            )
        if self.full_cov or self.fused is False:
            return False
        has_spec = self.cm.noise_fourier_spec(self.cm.x0()) is not None
        if self.fused is True:
            if not has_spec:
                from pint_tpu.exceptions import PintTpuError

                raise PintTpuError(
                    "fused=True needs a single pure-Fourier correlated-"
                    "noise basis (PL red noise)"
                )
            return True
        # 'auto': accelerators only (interpret-mode Pallas on CPU is
        # correct but slow)
        return has_spec and jax.default_backend() != "cpu"

    def fit_toas(self, maxiter: int = 4, tol_chi2: float = 1e-10) -> float:
        full_cov = self.full_cov
        use_fused = self._use_fused()

        @jax.jit
        def step(x):
            r = self.cm.time_residuals(x, subtract_mean=False)
            M = self._design_with_offset(x)
            Ndiag = jnp.square(self.cm.scaled_sigma(x))
            if use_fused:
                t_sec, freqs, phi = self.cm.noise_fourier_spec(x)
                return gls_step_woodbury_fourier(
                    r, M, Ndiag, t_sec, freqs, phi
                )
            # pure white: Woodbury with the empty basis degenerates to
            # WLS normal equations
            T, phi = self.cm.noise_basis_or_empty(x)
            if full_cov:
                return gls_step_full_cov(r, M, Ndiag, T, phi)
            return gls_step_woodbury(r, M, Ndiag, T, phi)

        x = self.cm.x0()
        chi2 = None
        cov = None
        for it in range(maxiter):
            dx, cov, chi2_new, nbad = step(x)
            if int(nbad):
                from pint_tpu.exceptions import DegeneracyWarning

                warnings.warn(
                    f"{int(nbad)} degenerate normal-equation directions "
                    "zeroed in GLS solve",
                    DegeneracyWarning,
                )
            chi2_new = float(chi2_new)
            if not np.isfinite(chi2_new):
                raise ConvergenceFailure("non-finite chi2 during GLS fit")
            x = x + dx[self._noffset:]  # dx[0] is the offset column
            if chi2 is not None and abs(chi2 - chi2_new) < tol_chi2 * max(
                chi2_new, 1.0
            ):
                chi2 = chi2_new
                self.converged = True
                break
            chi2 = chi2_new

        return self._finalize(x, cov, float(chi2))
