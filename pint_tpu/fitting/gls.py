"""Generalized-least-squares fitter (correlated noise).

Reference parity: src/pint/fitter.py::GLSFitter.fit_toas — the hot fit
loop of SURVEY.md §3.3.  The noise covariance is C = N + T phi T^T with
diagonal N (scaled white errors) and a reduced-rank basis T (n,k),
k << n (ECORR epochs + red-noise harmonics).  Normal equations solve via
the Woodbury identity:

  C^-1 = N^-1 - N^-1 T (phi^-1 + T^T N^-1 T)^-1 T^T N^-1

so only k x k and p x p Cholesky factorizations run — all on device
(XLA Cholesky / triangular solves on the MXU).  full_cov=True takes the
explicit n x n dense path (the O(n^3) wall the TPU build attacks; used
for cross-validation and benchmarking).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pint_tpu.fitting.base import (
    Fitter,
    design_with_offset,
    make_scan_fit_loop,
    noffset,
    record_fit,
)
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.toas.toas import TOAs


def _chol_solve(A, B, jitter: float = 0.0):
    """Solve A X = B with A symmetric positive-definite via Cholesky."""
    if jitter:
        A = A + jitter * jnp.eye(A.shape[0])
    L = jnp.linalg.cholesky(A)
    Y = jax.scipy.linalg.solve_triangular(L, B, lower=True)
    return jax.scipy.linalg.solve_triangular(L.T, Y, lower=False)


def _column_norms(M):
    """Column norms with |max| pre-scaling: design columns reach ~1e17
    (the F1 column is dt^2/2), and on backends with f32-pair emulated
    f64 (axon TPU — f32 EXPONENT range) the squares overflow to inf
    for multi-decade spans; dividing by the column max first keeps
    every squared intermediate <= n."""
    mx = jnp.max(jnp.abs(M), axis=0)
    mx = jnp.where(mx == 0, 1.0, mx)
    norm = jnp.sqrt(jnp.sum(jnp.square(M / mx[None, :]), axis=0)) * mx
    return jnp.where(norm == 0, 1.0, norm)


def _eigh_threshold_solve(A, b, threshold=None):
    """Min-norm solve of SPD A x = b with near-degenerate
    eigendirections zeroed (so degenerate models — e.g. a JUMP
    selecting all TOAs — produce a min-norm answer + DegeneracyWarning
    count instead of NaNs).  One eigendecomposition serves both the
    solve and the pseudo-inverse (a p x p eigh is emulated-f64 work on
    TPU — paying it twice showed up in profiling/profile_solve_parts).
    The default eigenvalue cut eps*p*lam_max is the Gram's own
    roundoff floor.  Returns (x, pinv(A), n_zeroed).  Shared by the
    GLS normal-equation tail and the WLS 'gram' method."""
    w, V = jnp.linalg.eigh(A)
    if threshold is None:
        threshold = jnp.finfo(jnp.float64).eps * A.shape[0]
    bad = w < threshold * jnp.max(w)
    winv = jnp.where(bad, 0.0, 1.0 / jnp.where(bad, 1.0, w))
    Vw = V * winv[None, :]
    return Vw @ (V.T @ b), Vw @ V.T, jnp.sum(bad)


def _finish_normal_eqs(A, b, r_cinv_r, norm, normalized_cov=False,
                       ir=False):
    """Shared normal-equation tail for every GLS flavor: thresholded
    solve, covariance, chi2 = r^T C^-1 r minus the fitted decrement
    dx^T b (removes the offset-column power, matching the reference),
    column un-normalization.

    normalized_cov=True returns the covariance as (covn, norm) — O(1)
    device magnitudes — instead of covn/outer(norm, norm): the
    unnormalized variance of a stiff column (F1 ~ 1e-40 s^-4) sits
    BELOW the f32 exponent range that axon's emulated f64 inherits and
    flushes to zero on device; fitters unnormalize on the host in IEEE
    f64 (Fitter._unnorm_cov).

    ir=True opts this solve into the per-solve precision policy
    (ops/solve_policy.py): when the policy is active (accelerator
    backends, PINT_TPU_SOLVE_IR!=0) the p x p system solves as an
    equilibrated f32 Cholesky + f64 iterative refinement with the
    residual check, replacing an emulated-f64 eigh that is both slow
    AND only ~f32-accurate on chip (docs/precision.md).  The trade is
    degeneracy semantics: the eigh shim zeroes near-degenerate
    directions (min-norm + DegeneracyWarning count); the IR path has
    no spectral view, so a degenerate system NaNs the Cholesky, fails
    the residual check, and the fallback ladder re-serves the fit from
    the f64 rung — where the eigh semantics still live.  The mixed
    paths pass ir=True; the f64 paths never do, keeping the ladder's
    landing spot strict."""
    from pint_tpu.ops import solve_policy

    if ir and solve_policy.ir_active():
        from pint_tpu.ops.ffgram import chol_solve_ir

        p = A.shape[0]
        X = chol_solve_ir(
            A, jnp.concatenate([b[:, None], jnp.eye(p)], axis=1),
            cholesky=solve_policy.ir_cholesky(p),
            check_rtol=solve_policy.check_rtol(),
        )
        dxn = X[:, 0]
        covn = 0.5 * (X[:, 1:] + X[:, 1:].T)  # A^-1, symmetrized
        nbad = jnp.zeros((), jnp.int32)  # degeneracy -> NaN -> ladder
    else:
        dxn, covn, nbad = _eigh_threshold_solve(A, b)
    chi2 = r_cinv_r - jnp.dot(dxn, b)
    if normalized_cov:
        return dxn / norm, (covn, norm), chi2, nbad
    return dxn / norm, covn / jnp.outer(norm, norm), chi2, nbad


def _solve_normal_eqs(cinv_mult, r, M, normalized_cov=False):
    """Shared GLS tail: column-normalize, form/solve normal equations
    via an explicit C^-1-apply."""
    norm = _column_norms(M)
    Mn = M / norm[None, :]
    CiM = cinv_mult(Mn)
    Cir = cinv_mult(r[:, None])[:, 0]
    A = Mn.T @ CiM
    b = -(Mn.T @ Cir)
    return _finish_normal_eqs(A, b, jnp.dot(r, Cir), norm,
                              normalized_cov)


def woodbury_sigma(Ndiag, T, phi):
    """(Ninv, TN = N^-1 T, Sigma = phi^-1 + T^T N^-1 T) — the ONE
    assembly of the Woodbury inner system, shared by make_cinv_mult
    and the Bayesian marginalized likelihood (which also needs Sigma's
    Cholesky for ln det C)."""
    Ninv = 1.0 / Ndiag
    TN = T * Ninv[:, None]  # (n, k)
    Sigma = jnp.diag(1.0 / phi) + T.T @ TN
    return Ninv, TN, Sigma


def make_cinv_mult(Ndiag, T, phi):
    """Build X -> C^-1 X for C = diag(Ndiag) + T diag(phi) T^T via the
    Woodbury identity.  The single shared implementation: the GLS
    proposal, the downhill acceptance objective, wideband, and the
    Bayesian likelihood all build on woodbury_sigma so the
    factorization can never diverge between them."""
    Ninv, TN, Sigma = woodbury_sigma(Ndiag, T, phi)

    def cinv_mult(X):
        NX = X * Ninv[:, None]
        return NX - TN @ _chol_solve(Sigma, TN.T @ X)

    return cinv_mult


def gls_step_woodbury(r, M, Ndiag, T, phi, normalized_cov=False):
    """One GLS normal-equation solve, reduced-rank path.

    r (n,), M (n,p), Ndiag (n,), T (n,k), phi (k,) ->
    (dx (p,), cov (p,p), chi2, n_degenerate); normalized_cov=True
    returns cov as (covn, norm) — see _finish_normal_eqs.
    """
    return _solve_normal_eqs(make_cinv_mult(Ndiag, T, phi), r, M,
                             normalized_cov)


def _woodbury_mixed_tail(G_XX, sig_tt, twx, phi, norm,
                         normalized_cov=False):
    """Shared mixed-precision Woodbury assembly: given the f32-grade
    Grams G_XX = X^T N^-1 X for X = [Mn | r], sig_tt = T^T N^-1 T, and
    twx = T^T N^-1 X, build and solve the normal equations.

    Precision contract (validated in tests/test_pallas_kernels.py,
    tests/test_ffgram.py): every Gram — including the gradient
    b_white = Mn^T N^-1 r and r^T N^-1 r — runs as a chunked f32 MXU
    Gram with f64 chunk accumulation (~3e-8 relative to summed-term
    magnitudes; ops/ffgram.py); the k x k factorization is an
    equilibrated f32 Cholesky + f64 iterative refinement.  The
    gradient's f32-grade error scales with the CURRENT residual norm,
    so Gauss-Newton stays contracting from far-off starts, and at the
    fixed point (residuals at the noise floor) the converged
    parameters land within ~2e-4 sigma of the all-f64 solution
    (measured, 2e4-TOA red-noise config; the earlier exact-f64
    gradient bought ~100x tighter agreement at ~1.4 ms/step of
    emulated-f64 reductions — profiling/profile_solve_parts.py).
    Net agreement vs the all-f64 path: step directions <2e-3 of the
    largest component, chi2 <1e-3 relative, uncertainties <5e-3;
    iterated fits within ~1e-2 sigma.
    """
    from pint_tpu.ops import solve_policy
    from pint_tpu.ops.ffgram import chol_solve_ir

    A_white = G_XX[:-1, :-1]
    b_white = G_XX[:-1, -1]
    r_Nr = G_XX[-1, -1]
    Sigma = jnp.diag(1.0 / phi) + sig_tt
    # Sigma^-1 T^T N^-1 [Mn | r]: under the solve policy (accelerator
    # backends) the k x k factorization takes the bf16x3 blocked
    # kernel at large k and arms the residual check; with
    # PINT_TPU_SOLVE_IR=0 both kwargs are None — bitwise the
    # pre-policy call (ops/solve_policy.py)
    corr = chol_solve_ir(
        Sigma, twx,
        cholesky=solve_policy.ir_cholesky(Sigma.shape[0]),
        check_rtol=solve_policy.check_rtol(),
    )
    A = A_white - twx[:, :-1].T @ corr[:, :-1]
    b = -(b_white - twx[:, :-1].T @ corr[:, -1])
    r_cinv_r = r_Nr - jnp.dot(twx[:, -1], corr[:, -1])
    return _finish_normal_eqs(A, b, r_cinv_r, norm, normalized_cov,
                              ir=True)


def gls_step_woodbury_fourier(r, M, Ndiag, t_sec, freqs, phi,
                              normalized_cov=False):
    """Woodbury GLS with the Pallas fused-Gram kernels: the red-noise
    basis T = [sin, cos](2 pi f t) is never materialized — its Gram
    pieces stream through VMEM in f32 (ops/pallas_kernels.py), then the
    shared mixed-precision assembly (_woodbury_mixed_tail) finishes the
    solve.  Requires a pure-Fourier basis
    (CompiledModel.noise_fourier_spec).

    ACCURACY NOTE (why this is opt-in, not 'auto'): the in-kernel f32
    phase arguments 2 pi f t carry ~1e-5 rad error at multi-year
    spans, a SYSTEMATIC basis perturbation that moves red-noise-
    degenerate parameters (F1) by ~0.2 sigma at PTA scale (measured vs
    the dense and general-mixed paths, which agree with each other to
    ~2e-3 sigma).  Use for quick-look fits or when n*2k is too large
    to materialize; the 'mixed' path with the compile-time precomputed
    basis is both faster and f64-basis accurate at bench scale.
    """
    from pint_tpu.ops.ffgram import gram32
    from pint_tpu.ops.pallas_kernels import fourier_gram

    Ninv = 1.0 / Ndiag
    norm = _column_norms(M)
    Mn = M / norm[None, :]
    X = jnp.concatenate([Mn, r[:, None]], axis=1)
    sig_tt, twx = fourier_gram(t_sec, freqs, Ninv, X)
    return _woodbury_mixed_tail(
        gram32(X, Ninv),
        sig_tt.astype(jnp.float64), twx.astype(jnp.float64), phi, norm,
        normalized_cov,
    )


def gls_step_woodbury_mixed(r, M, Ndiag, T, phi, normalized_cov=False):
    """Woodbury GLS for an arbitrary reduced-rank basis (ECORR
    quantization blocks, combined ECORR+Fourier stacks) with the noise
    side in f32 on the MXU — the general-basis sibling of the Pallas
    fourier path, same validated tolerance class.

    The basis columns T only carry f32 information (0/1 quantization
    entries are exact; Fourier columns are smooth O(1) values), so
    T^T N^-1 T and T^T N^-1 [M | r] run as one chunked f32 MXU Gram
    (ops/ffgram.py); the shared mixed-precision assembly
    (_woodbury_mixed_tail, which documents the precision contract)
    finishes the solve.

    Interior fusion (ISSUE 18): under solve_policy.fused_interior_active
    the joint Gram runs as ONE VMEM-resident Pallas grid pass
    (ops/pallas_fit.py::fused_gram_joint) instead of the chunked XLA
    pipeline — same |max|-prescale, weights, and chunk-128 f32
    accumulation class, with the per-chunk partials never leaving
    VMEM.  Shapes outside the VMEM block table, and traces under
    solve_policy.fused_interior_bypass (gang shard mode), keep the
    unfused gram32_joint; PINT_TPU_FUSED_INTERIOR=0 restores it
    bitwise everywhere.  The route is decided at TRACE time from
    static shapes — steady serve traffic never retraces on it.
    """
    Ninv = 1.0 / Ndiag
    norm = _column_norms(M)
    Mn = M / norm[None, :]
    X = jnp.concatenate([Mn, r[:, None]], axis=1)
    sig_tt, twx, G_XX = _joint_gram(T, X, Ninv)
    return _woodbury_mixed_tail(G_XX, sig_tt, twx, phi, norm,
                                normalized_cov)


def _joint_gram(T, X, Ninv):
    """The fused-or-unfused joint Gram dispatch for the mixed Woodbury
    interior — the ONE chokepoint solve_policy gates (pintlint obs12
    pins it): fused Pallas pass when the policy is active and the
    static shape fits the VMEM block table, the chunked XLA
    gram32_joint otherwise (bitwise the pre-fusion path)."""
    from pint_tpu.ops import solve_policy
    from pint_tpu.ops.ffgram import gram32_joint

    n, p1 = X.shape
    k = T.shape[-1]
    if solve_policy.fused_interior_active():
        from pint_tpu.ops.pallas_fit import (
            fused_block_table,
            fused_gram_joint,
        )

        if fused_block_table(n, k, p1) is not None:
            return fused_gram_joint(T.astype(jnp.float32), X, Ninv)
    return gram32_joint(T.astype(jnp.float32), X, Ninv)


def default_accel_mode(cm) -> str:
    """The production precision policy shared by GLSFitter ('auto') and
    PTABatch: mixed precision (f32 MXU) on accelerators when a
    correlated basis exists, exact f64 on CPU backends and for
    pure-white models (noise_basis_or_empty's dummy column is not a
    real basis)."""
    if jax.default_backend() == "cpu":
        return "f64"
    return "mixed" if cm.has_correlated_errors else "f64"


def gauss_newton_step(cm, x, mode: str):
    """One reduced-rank GLS Gauss-Newton step evaluated on a
    CompiledModel's CURRENT bundle/reference state:
    ``-> (x_new, (covn, norm), chi2, nbad)`` with the covariance kept
    NORMALIZED (including the implicit-offset row — callers slice and
    unnormalize; see _finish_normal_eqs on why raw variances must not
    form on device).

    The single shared step assembly for every consumer that swaps
    per-pulsar bundles/refs into a prototype model before calling —
    the PTA batch (parallel/pta.py::PTABatch.fit_step) and the serving
    engine's batched fit kernels (serve/session.py::build_fit_kernel)
    — so the residual/design/whitening recipe can never diverge from
    GLSFitter's own ``_step_inputs``.

    mode: 'mixed' (f32 MXU Woodbury Grams — the accelerator policy of
    default_accel_mode) or 'f64' (exact; the CPU/white-noise policy).
    """
    from pint_tpu.exceptions import PintTpuError

    if mode not in ("mixed", "f64"):
        raise PintTpuError(
            f"unknown GLS step mode {mode!r}: expected 'mixed' or 'f64'"
        )
    step = (
        gls_step_woodbury_mixed if mode == "mixed" else gls_step_woodbury
    )
    no = noffset(cm)
    r = cm.time_residuals(x, subtract_mean=False)
    M = design_with_offset(cm, x)
    Ndiag = jnp.square(cm.scaled_sigma(x))
    T, phi = cm.noise_basis_or_empty(x)
    dx, (covn, nrm), chi2, nbad = step(
        r, M, Ndiag, T, phi, normalized_cov=True
    )
    return x + dx[no:], (covn, nrm), chi2, nbad


def gls_step_full_cov(r, M, Ndiag, T, phi, method=None,
                      normalized_cov=False):
    """Dense-covariance path: C = diag(N) + T phi T^T, explicit n x n
    factorization (reference full_cov=True) — the O(n^3) wall the TPU
    build attacks.

    method='f64' (CPU default): explicit f64 Cholesky.
    method='mixed' (accelerator default): equilibrated f32 MXU Cholesky
    + iterative refinement with the TRUE operator applied through its
    Woodbury structure (ops/ffgram.py::woodbury_chol_solve_ir) — the
    dense f64 covariance is never materialized, so n=16384 fits a
    16 GB chip (~2 n^2 f32 bytes vs the ~6x dense-f64 route that
    OOMed at 27 GB); an emulated-f64 n x n Cholesky is ~300x slower
    than f32 on TPU.  Same validated tolerance class as the
    reduced-rank mixed paths (_woodbury_mixed_tail)."""
    from pint_tpu.models.noise import dense_noise_cov

    if method is None:
        method = "f64" if jax.default_backend() == "cpu" else "mixed"
    if method == "mixed" and T is not None:
        from pint_tpu.ops import solve_policy
        from pint_tpu.ops.ffgram import (
            matmul_split32, woodbury_chol_solve_ir,
        )

        norm = _column_norms(M)
        Mn = M / norm[None, :]
        X = jnp.concatenate([Mn, r[:, None]], axis=1)
        # Factorization choice (r5, VERDICT r4 weak 2): at large n the
        # f32 preconditioner factorization is parallel/dense.py::
        # fast_cholesky32 — blocked, 3-pass-bf16 trailing GEMM, b=512
        # panels, per-block ridge: 22.5 TF/s vs the native custom
        # call's 19.5 at n=16384 (profiling/cholesky_sweep.py, r5
        # chain=16 numbers).  At the production refine=2 its refined
        # step is indistinguishable from the native factor's (probed
        # on-chip at n=8192: dx deltas match to 2 digits at the
        # comparison's own ~0.05-sigma emulated-f64 noise floor) — the
        # IR residual applies the true f64 operator through the
        # Woodbury structure either way.  Small n keeps XLA's native
        # call: the unrolled blocked kernel only adds compile time
        # where the factorization isn't the bottleneck.  Above 16384
        # the native call ALSO stays: the bare blocked kernel at
        # n=32768 compiles in ~42 s, but embedded in the full jitted
        # step the remote-compile service never returned (>45 min
        # with ~zero CPU, measured r5) — the unrolled trailing-update
        # HLO inside the step graph is past what the compile
        # transport handles in useful time.
        if 8192 <= Ndiag.shape[0] <= 16384:
            from pint_tpu.parallel.dense import fast_cholesky32

            CiX = woodbury_chol_solve_ir(
                Ndiag, T, phi, X, cholesky=fast_cholesky32,
                check_rtol=solve_policy.check_rtol(),
            )
        else:
            CiX = woodbury_chol_solve_ir(
                Ndiag, T, phi, X,
                check_rtol=solve_policy.check_rtol(),
            )
        # X^T C^-1 X on the MXU (an n x (p+1) emulated-f64 matmul
        # would cost more than the factorization on TPU)
        G = matmul_split32(X.T, CiX)
        return _finish_normal_eqs(
            G[:-1, :-1], -G[:-1, -1], G[-1, -1], norm, normalized_cov,
            ir=True,
        )
    C = dense_noise_cov(Ndiag, T, phi)
    if method == "mixed":  # pure-white C: small/diagonal, dense is fine
        from pint_tpu.ops import solve_policy
        from pint_tpu.ops.ffgram import chol_solve_ir, matmul_split32

        norm = _column_norms(M)
        Mn = M / norm[None, :]
        X = jnp.concatenate([Mn, r[:, None]], axis=1)
        CiX = chol_solve_ir(
            C, X, cholesky=solve_policy.ir_cholesky(C.shape[0]),
            check_rtol=solve_policy.check_rtol(),
        )
        G = matmul_split32(X.T, CiX)
        return _finish_normal_eqs(
            G[:-1, :-1], -G[:-1, -1], G[-1, -1], norm, normalized_cov,
            ir=True,
        )
    L = jnp.linalg.cholesky(C)

    def cinv_mult(X):
        Y = jax.scipy.linalg.solve_triangular(L, X, lower=True)
        return jax.scipy.linalg.solve_triangular(L.T, Y, lower=False)

    return _solve_normal_eqs(cinv_mult, r, M, normalized_cov)


# ---------------------------------------------------------------------- #
# O(append) streaming state (ISSUE 14)
# ---------------------------------------------------------------------- #
#
# A long-lived timing stream maintains the GLS normal equations as an
# ADDITIVE Gram-block state so that appending j TOAs costs
# O(j k^2 + k^2 p + p^3) — independent of the n TOAs already absorbed:
#
#   G    (q, q)  X^T N^-1 X for X = [Mn | r], q = p1 + 1   (A_white,
#                b_white, r^T N^-1 r all live here — the same layout
#                as gram32's G_XX)
#   twx  (k, q)  T^T N^-1 X
#   stt  (k, k)  T^T N^-1 T              (Sigma = diag(1/phi) + stt)
#   sig_L (k,k)  maintained Cholesky factor of the EQUILIBRATED Sigma
#                (frozen Jacobi diagonal sig_d from the last refresh),
#                advanced per append by ops/cholupdate.py::chol_update
#                in solve_policy.stream_factor_dtype()
#   norm (p1,)   FROZEN column norms — normalization must not move
#                between appends or the Gram blocks stop being additive
#   x    (nfree,) current solution; r-dependent state entries always
#                refer to residuals at this x
#
# Appended rows enter with their exact per-row weight, pad rows with
# EXACTLY zero weight (stronger than the batch PAD_ERROR_US
# convention: streaming state accumulates forever, so pads must be
# perfectly neutral).  After each solve the r-dependent blocks are
# advanced under the LINEARIZATION r(x+dx) = r(x) + Mn dxn — exact in
# the state's own model, drifting from the true nonlinear residuals
# only at second order; the periodic refresh (PINT_TPU_STREAM_REFRESH)
# re-anchors everything, and both solves carry the poison-to-NaN drift
# check (solve_policy.stream_drift_rtol) so numerical decay can never
# go unnoticed (ops/cholupdate.py documents the convention).


def stream_state_init(r, M, Ninv, T, phi, x):
    """Build the streaming Gram state from full arrays at solution x
    (runs once per stream open/refresh — the only O(n) solver work in
    a stream's steady state).  ``Ninv`` is the per-row INVERSE white
    variance — exact zeros on pad rows (same convention as
    stream_state_append).  Returns the state dict above plus
    ``phi_inv``/``sig_d``."""
    from pint_tpu.ops import solve_policy

    norm = _column_norms(M * jnp.sqrt(Ninv)[:, None])
    Mn = M / norm[None, :]
    X = jnp.concatenate([Mn, r[:, None]], axis=1)
    XN = X * Ninv[:, None]
    G = X.T @ XN
    twx = T.T @ XN
    stt = (T * Ninv[:, None]).T @ T
    phi_inv = 1.0 / phi
    Sigma = jnp.diag(phi_inv) + stt
    k = T.shape[1]
    if k:
        sig_d = jnp.diagonal(Sigma)
        dinv = 1.0 / jnp.sqrt(sig_d)
        Seq = Sigma * jnp.outer(dinv, dinv)
        sig_L = jnp.linalg.cholesky(
            Seq.astype(solve_policy.stream_factor_dtype())
        )
    else:
        sig_d = jnp.ones((0,))
        sig_L = jnp.zeros((0, 0), solve_policy.stream_factor_dtype())
    return {
        "G": G, "twx": twx, "stt": stt, "sig_L": sig_L,
        "sig_d": sig_d, "phi_inv": phi_inv, "norm": norm,
        "x": jnp.asarray(x, jnp.float64),
    }


def stream_state_append(state, r_j, M_j, Ninv_j, T_j):
    """Absorb j appended rows: additive Gram updates + the rank-j
    Cholesky update of the maintained equilibrated Sigma factor.
    ``Ninv_j`` must already carry exact zeros on pad rows."""
    from pint_tpu.ops.cholupdate import chol_update

    Mn_j = M_j / state["norm"][None, :]
    X_j = jnp.concatenate([Mn_j, r_j[:, None]], axis=1)
    XN_j = X_j * Ninv_j[:, None]
    out = dict(state)
    out["G"] = state["G"] + X_j.T @ XN_j
    out["twx"] = state["twx"] + T_j.T @ XN_j
    out["stt"] = state["stt"] + (T_j * Ninv_j[:, None]).T @ T_j
    if state["sig_L"].shape[0]:
        V = T_j.T * jnp.sqrt(Ninv_j)[None, :]
        Veq = V / jnp.sqrt(state["sig_d"])[:, None]
        out["sig_L"] = chol_update(state["sig_L"], Veq)
    return out


def stream_state_solve(state, noffset_: int, check_rtol=None):
    """One exact GLS solve of the current state (the state is a linear
    least-squares problem, so one solve IS the converged answer) and
    the linearized advance of the r-dependent blocks to the new x.

    Returns ``(state', dx (p1,), (covn, norm), chi2)`` with the
    normalized-covariance convention of _finish_normal_eqs.  Both the
    maintained-factor Sigma solve and the p x p normal-equation solve
    carry the ``check_rtol`` poison-to-NaN drift check; on a failed
    check the returned state is the UNCHANGED input state (callers
    re-serve via a warm full refit — the poisoned dx/chi2 never feed
    anything downstream)."""
    from pint_tpu.ops.cholupdate import factor_solve_ir
    from pint_tpu.ops.ffgram import chol_solve_ir

    G, twx = state["G"], state["twx"]
    k = twx.shape[0]
    if k:
        dinv = 1.0 / jnp.sqrt(state["sig_d"])
        Sigma_eq = (jnp.diag(state["phi_inv"]) + state["stt"]) \
            * jnp.outer(dinv, dinv)
        corr = dinv[:, None] * factor_solve_ir(
            state["sig_L"], Sigma_eq, dinv[:, None] * twx,
            check_rtol=check_rtol,
        )
        A = G[:-1, :-1] - twx[:, :-1].T @ corr[:, :-1]
        b = -(G[:-1, -1] - twx[:, :-1].T @ corr[:, -1])
        r_cinv_r = G[-1, -1] - jnp.dot(twx[:, -1], corr[:, -1])
    else:
        A = G[:-1, :-1]
        b = -G[:-1, -1]
        r_cinv_r = G[-1, -1]
    p = A.shape[0]
    X = chol_solve_ir(
        A, jnp.concatenate([b[:, None], jnp.eye(p)], axis=1),
        check_rtol=check_rtol,
    )
    dxn = X[:, 0]
    covn = 0.5 * (X[:, 1:] + X[:, 1:].T)
    chi2 = r_cinv_r - jnp.dot(dxn, b)
    # linearized advance r -> r + Mn dxa of every r-dependent block
    # (exact in the state's model; OLD blocks on the right-hand
    # sides).  The OFFSET components of the step are ZEROED first:
    # the fitter never commits them (gauss_newton_step returns
    # x + dx[no:]) — residuals at any x carry the model's own phase
    # convention, and appended rows are evaluated exactly there, so
    # folding the profiled offset into the stored r-column would make
    # old and new rows inconsistent by a constant the next solve's
    # global offset column cannot absorb.  The offset is re-profiled
    # by every solve instead, mirroring the iterated fitter.
    dxa = dxn.at[:noffset_].set(0.0)
    Gmm = G[:-1, :-1]
    gmr = G[:-1, -1]
    Gd = Gmm @ dxa
    G2 = G.at[:-1, -1].set(gmr + Gd).at[-1, :-1].set(gmr + Gd)
    G2 = G2.at[-1, -1].set(
        G[-1, -1] + 2.0 * jnp.dot(dxa, gmr) + jnp.dot(dxa, Gd)
    )
    out = dict(state)
    out["G"] = G2
    if k:
        out["twx"] = twx.at[:, -1].set(
            twx[:, -1] + twx[:, :-1] @ dxa
        )
    out["x"] = state["x"] + (dxn / state["norm"])[noffset_:]
    # drift poison: a failed check must leave the STATE untouched so
    # the retry/fallback path re-runs from a clean anchor (scalar
    # jnp.where — never lax.cond, these solves run vmapped in serve)
    ok = jnp.isfinite(chi2) & jnp.all(jnp.isfinite(dxn))
    out = {
        kk: jnp.where(ok, v, state[kk]) for kk, v in out.items()
    }
    return out, dxn / state["norm"], (covn, state["norm"]), chi2


class GLSFitter(Fitter):
    """Iterated GLS fit; also correct (equals WLS) with no correlated
    noise in the model.

    fused='auto' (default) picks, on accelerators, the general-basis
    mixed-precision MXU path for correlated-noise models (see
    _woodbury_mixed_tail for the validated accuracy bounds; the
    Fourier basis is a compile-time host-precomputed constant);
    fused=False forces the all-f64 path (always used on CPU),
    fused=True opts into the Pallas streaming-basis path (see
    gls_step_woodbury_fourier's accuracy note), fused='mixed' forces
    the mixed path on any backend (used by cross-path tests).

    fit_toas dispatches the compiled scan loop through the runtime
    degradation ladder (runtime/fallback.py: native mode -> all-f64 ->
    CPU re-dispatch); ``self.guard_report`` records which rung served
    the result and what tripped on the way down.
    """

    def __init__(self, toas: TOAs, model: TimingModel,
                 full_cov: bool = False, fused="auto"):
        super().__init__(toas, model)
        self.full_cov = full_cov
        self.fused = fused

    def _step_inputs(self, x):
        """(residuals, design-with-offset, Ndiag) for one GLS step;
        wideband overrides with the stacked [TOA; DM] blocks."""
        r = self.cm.time_residuals(x, subtract_mean=False)
        M = self._design_with_offset(x)
        Ndiag = jnp.square(self.cm.scaled_sigma(x))
        return r, M, Ndiag

    def _step_noise(self, x):
        """(T, phi) reduced-rank basis matching _step_inputs' rows."""
        return self.cm.noise_basis_or_empty(x)

    def _fourier_available(self) -> bool:
        """Whether the Pallas pure-Fourier fused path applies; wideband
        overrides to False (its rows are [TOA; DM]-stacked)."""
        # eval_shape: trace-only structure query, no device work
        return (
            jax.eval_shape(self.cm.noise_fourier_spec, self.cm.x0())
            is not None
        )

    def _step_mode(self) -> str:
        """'fourier' (Pallas fused Gram), 'mixed' (general-basis f32
        MXU), 'f64' (all-f64 XLA), or 'full_cov' (dense n x n)."""
        if self.full_cov and self.fused in (True, "mixed"):
            from pint_tpu.exceptions import PintTpuError

            raise PintTpuError(
                f"fused={self.fused!r} and full_cov=True are mutually "
                "exclusive (the fused/mixed paths are reduced-rank by "
                "construction)"
            )
        if self.full_cov:
            return "full_cov"
        if self.fused is False:
            return "f64"
        if self.fused == "mixed":
            return "mixed"
        if self.fused is True:
            if not self._fourier_available():
                from pint_tpu.exceptions import PintTpuError

                raise PintTpuError(
                    "fused=True needs a single pure-Fourier correlated-"
                    "noise basis (PL red noise)"
                )
            return "fourier"
        # 'auto': the general mixed path on accelerators — with the
        # compile-time precomputed Fourier basis (models/noise.py::
        # fourier_basis) it is both faster than the Pallas streaming
        # path (30.5M vs 28.4M TOAs/s at the 1e5-TOA bench) and far
        # more accurate (the in-kernel f32 phases cost ~0.2 sigma on
        # stiff spin parameters; see gls_step_woodbury_fourier).
        # fused=True opts into the streaming kernel (it never
        # materializes the (n, 2k) basis — useful at very large n*k).
        return default_accel_mode(self.cm)

    def _make_step(self, mode: str):
        """Step closure returning (dx, (covn, norm), chi2, nbad) — the
        covariance stays normalized on device (see _finish_normal_eqs)
        and is unnormalized on the host by _finish_scan_fit."""
        def step(x):
            r, M, Ndiag = self._step_inputs(x)
            if mode == "fourier":
                t_sec, freqs, phi = self.cm.noise_fourier_spec(x)
                return gls_step_woodbury_fourier(
                    r, M, Ndiag, t_sec, freqs, phi, normalized_cov=True
                )
            # pure white: Woodbury with the empty basis degenerates to
            # WLS normal equations
            T, phi = self._step_noise(x)
            if mode == "full_cov":
                return gls_step_full_cov(
                    r, M, Ndiag, T, phi,
                    method="f64" if self.fused is False else None,
                    normalized_cov=True,
                )
            if mode == "mixed":
                return gls_step_woodbury_mixed(
                    r, M, Ndiag, T, phi, normalized_cov=True
                )
            return gls_step_woodbury(
                r, M, Ndiag, T, phi, normalized_cov=True
            )

        return step

    def _make_fit_loop(self, mode: str, maxiter: int, tol_chi2: float):
        """The whole Gauss-Newton iteration as one device program —
        the shared scan harness (base.make_scan_fit_loop) around this
        fitter's step; chi2 here is the step's whitened chi2 at the
        pre-step state (reference semantics:
        src/pint/fitter.py::GLSFitter.fit_toas)."""
        step = self._make_step(mode)
        no = self._noffset
        p = len(self.cm.free_names) + no

        def live_step(x):
            dx, cov, chi2, nbad = step(x)
            return x + dx[no:], cov, chi2, nbad.astype(jnp.int32)

        return make_scan_fit_loop(
            live_step, p, maxiter, tol_chi2,
            lambda x0: jnp.asarray(jnp.inf), cm=self.cm,
        )

    @record_fit
    def fit_toas(self, maxiter: int = 4, tol_chi2: float | None = None) -> float:
        mode = self._step_mode()
        if tol_chi2 is None:
            # the mixed-precision modes carry ~1e-6 relative f32 noise
            # in chi2 between iterations; demanding the f64 tolerance
            # there would spin to maxiter and report converged=False.
            # full_cov is only exact when its method resolves to f64
            # (CPU backend or fused=False) — on accelerators it takes
            # the f32-Cholesky mixed method.
            exact = mode == "f64" or (
                mode == "full_cov"
                and (self.fused is False
                     or jax.default_backend() == "cpu")
            )
            tol_chi2 = 1e-10 if exact else 3e-6
        from pint_tpu.runtime.fallback import run_fit_ladder

        def make_loop(rung_mode):
            # rung modes: the native mode first, then the all-f64
            # reduced-rank Woodbury path ('f64' — also the f64 rung for
            # full_cov fits: algebraically the same C = N + T phi T^T
            # model through a hazard-free factorization), then the
            # 'cpu' rung re-dispatching the f64 loop under the
            # ladder-device pin (runtime/fallback.py).
            key = (rung_mode, maxiter, tol_chi2)
            if key not in self._fit_loops:
                self._fit_loops[key] = self._make_fit_loop(*key)
            return self._fit_loops[key]

        result, self.guard_report = run_fit_ladder(
            self.cm, mode, make_loop,
            site=f"fit:{type(self).__name__}",
            fail_msg="non-finite chi2 during GLS fit",
        )
        return self._finish_scan_fit(
            result,
            "degenerate normal-equation directions zeroed in GLS solve",
            "non-finite chi2 during GLS fit",
        )
