"""Fitter statistics helpers.

Reference parity: src/pint/utils.py::FTest and fitter.py::Fitter.ftest —
significance of adding parameters to a nested timing model.
"""

from __future__ import annotations

import numpy as np
from scipy.stats import f as f_dist


def ftest(chi2_1: float, dof_1: int, chi2_2: float, dof_2: int) -> float:
    """F-test probability that the chi2 improvement of the larger model
    (2, with dof_2 < dof_1) arises by chance.

    Returns the p-value (small = the extra parameters are significant);
    NaN when the inputs are not a valid nested comparison.
    """
    delta_chi2 = chi2_1 - chi2_2
    delta_dof = dof_1 - dof_2
    if delta_dof <= 0 or dof_2 <= 0 or delta_chi2 < 0:
        return float("nan")
    F = (delta_chi2 / delta_dof) / (chi2_2 / dof_2)
    return float(f_dist.sf(F, delta_dof, dof_2))


def akaike_information_criterion(chi2: float, nfree: int) -> float:
    """AIC = chi2 + 2 k (up to a model-independent constant)."""
    return float(chi2 + 2 * nfree)


def parameter_correlation_matrix(cov: np.ndarray) -> np.ndarray:
    """Normalize a parameter covariance matrix to correlations."""
    s = np.sqrt(np.diag(cov))
    s = np.where(s == 0, 1.0, s)
    return cov / np.outer(s, s)
