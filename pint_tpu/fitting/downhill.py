"""Downhill (step-halving) fitters.

Reference parity: src/pint/fitter.py::DownhillFitter / DownhillWLSFitter /
DownhillGLSFitter — propose a full Gauss-Newton step, evaluate chi2, and
halve the step length (lambda) until chi2 stops increasing; warn (keep
the best-known solution) when no acceptable step exists and raise
InvalidModelParameters on non-finite starts.

TPU-first differences: the proposal and the chi2 evaluation are the same
compiled kernels the plain fitters use (pure functions of the delta
vector x), so the lambda line-search costs one kernel call per trial —
no model rebuilds, no recompiles.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import (
    ConvergenceWarning,
    DegeneracyWarning,
    GuardTripWarning,
    InvalidModelParameters,
    PintTpuNumericsError,
)
from pint_tpu.fitting.base import Fitter, record_fit
from pint_tpu.fitting.gls import (
    default_accel_mode,
    gls_step_full_cov,
    gls_step_woodbury,
    gls_step_woodbury_mixed,
    make_cinv_mult,
)
from pint_tpu.fitting.wls import _wls_step
from pint_tpu.runtime.guard import validate_finite


class DownhillFitter(Fitter):
    """Base downhill fitter: subclasses provide _proposal
    (dx, cov, nbad, predicted_decrease) and _chi2 (offset-profiled
    objective) kernels."""

    method = "downhill"

    # subclasses override ------------------------------------------------
    def _make_proposal(self, force_f64: bool = False):
        """Proposal kernel; ``force_f64=True`` is the guard's fallback
        rung — the all-f64 step path on subclasses whose native
        proposal is mixed-precision (a no-op re-dispatch otherwise)."""
        raise NotImplementedError

    def _make_chi2(self):
        raise NotImplementedError

    def _guarded_proposal(self, proposal, x, fell_back: bool):
        """Dispatch + validate one proposal (runtime/guard.py shared
        validator).  A non-finite proposal falls back ONCE to the
        all-f64 step (the downhill sibling of the fit-loop ladder in
        runtime/fallback.py — the chi2 acceptance ladder downstream
        still gates every step, so no injected or real fault can slip
        a wrong step through silently).  Returns
        (dx, cov, nbad, pred, proposal, fell_back)."""
        site = f"downhill:{type(self).__name__}/proposal"
        dx, cov, nbad, pred = proposal(x)
        try:
            validate_finite({"dx": dx, "pred": pred}, site=site,
                            what="downhill proposal")
        except PintTpuNumericsError:
            if fell_back:
                raise
            warnings.warn(
                "downhill proposal produced non-finite values; "
                "falling back to the all-f64 proposal step",
                GuardTripWarning,
            )
            proposal = self._make_proposal(force_f64=True)
            fell_back = True
            dx, cov, nbad, pred = proposal(x)
            validate_finite({"dx": dx, "pred": pred},
                            site=site + "/rung:f64",
                            what="downhill proposal")
        return dx, cov, nbad, pred, proposal, fell_back

    # --------------------------------------------------------------------
    @staticmethod
    def _chi2_noise_floor(lams, c_tries) -> float:
        """MEASURED per-trial chi2 noise floor at the current state.

        Dedicated probe lambdas (<= 5e-4, plus the lambda=0 baseline)
        ride along in the single-dispatch ladder, short enough that
        the true chi2 change is linear in lambda to high accuracy
        (curvature enters at O(pred*lambda^2)); their deviation from a
        fitted straight line in lambda measures the backend's chi2
        evaluation scatter directly at the scale the accept/reject
        decisions operate on.  Measured on the axon chip (r4,
        golden1): within-program DIFFERENTIAL scatter ~3e-7 chi2
        units — the emulated-f64 error is smooth in x, so nearby
        evaluations in one program are far more consistent than the
        ~1e-7 s ABSOLUTE residual-noise model suggests (that model
        put the floor at ~5.8 chi2 units, silently loosening the r3
        acceptance tolerance by 7 orders; cross-PROGRAM offsets are
        the absolute-scale effect, handled by the ladder's lambda=0
        baseline).  Accept/reject decisions below this floor are coin
        flips — the r1/r2 spurious-ConvergenceWarning failure mode.
        Measuring per iteration removes r3's hard-coded delta_r=1e-7
        constant AND tracks the shrinking residuals as the fit
        converges (VERDICT r3 weak 4 + ADVICE r3)."""
        lams = np.asarray(lams, dtype=float)
        c = np.asarray(c_tries, dtype=float)
        ok = np.isfinite(c)
        if int(np.sum(ok)) < 4:
            return 0.0
        ls, cs = lams[ok], c[ok]
        coef = np.polyfit(ls, cs, 1)
        resid = cs - np.polyval(coef, ls)
        return 6.0 * float(np.sqrt(np.sum(resid**2) / (len(ls) - 2)))

    @record_fit
    def fit_toas(
        self,
        maxiter: int = 20,
        required_chi2_decrease: float = 1e-2,
        max_chi2_increase: float = 1e-2,
        min_lambda: float = 1e-3,
    ) -> float:
        proposal = self._make_proposal()
        chi2_of = self._make_chi2()
        # the lambda ladder is static, so the whole line search is ONE
        # vmapped device call per iteration (the reference's host loop
        # evaluates trial steps one by one — up to 11 dispatches here,
        # ~85 ms each through the axon tunnel); the acceptance rule
        # below picks the LARGEST acceptable lambda, exactly matching
        # the sequential first-accept semantics.
        lams = []
        lam = 1.0
        while lam >= min_lambda:
            lams.append(lam)
            lam *= 0.5
        # measurement-only probe lambdas BELOW min_lambda (never
        # accepted as steps): short enough that the true chi2 change
        # is linear in lambda, so together with the small ladder
        # trials they feed the per-iteration noise-floor line fit.
        # The trailing lambda=0 entry is the BASELINE: measured on
        # chip (r4), chi2 evaluated through a different XLA program
        # (scalar vs vmapped) carries a program-decorrelated absolute
        # offset (~1e-5 chi2 units on golden1) while values within ONE
        # program at nearby x are differentially accurate — so every
        # accept/reject comparison below uses the ladder's own
        # same-program baseline, never a scalar evaluation.
        # fixed small values, NOT min_lambda-scaled: the line-fit
        # measurement needs lambdas deep in the linear regime even
        # when a caller raises min_lambda (with e.g. min_lambda=0.5,
        # scaled probes would sit where curvature ~pred*lambda^2
        # masquerades as noise)
        probe_lams = [
            s for s in (5e-4, 2.5e-4, 1.25e-4, 6.25e-5)
            if s < min_lambda
        ]
        if len(probe_lams) < 4:
            # a PARTIALLY-surviving fixed list (min_lambda in
            # (6.25e-5, 5e-4]) would leave the line fit under-
            # determined and _chi2_noise_floor silently 0 — scale the
            # whole probe set down instead
            probe_lams = [min_lambda * f
                          for f in (0.5, 0.25, 0.125, 0.0625)]
        # measure from the dedicated probes + the lambda=0 baseline
        # ONLY: ladder trials up to ~8e-3 carry a true quadratic term
        # ~pred*lambda^2 whose deviation from the fitted line would
        # scale the "noise" floor with the predicted decrease on
        # far-from-converged fits (r4 review)
        probe_sel = np.asarray(
            [False] * len(lams) + [True] * len(probe_lams) + [True]
        )
        all_lams = np.asarray(lams + probe_lams + [0.0])
        lams_arr = jnp.asarray(all_lams)
        # O(10)-float ladder constant — baking it is intended (way
        # below any transport/413 threshold, and constant-folds)
        chi2_ladder = self.cm.jit(
            lambda x, dx: jax.vmap(chi2_of)(
                x[None, :] + lams_arr[:, None] * dx[None, :]  # lint: ok(transport)
            )
        )

        x = self.cm.x0()
        chi2 = float(chi2_of(x))
        if not np.isfinite(chi2):
            raise InvalidModelParameters(
                "initial model produces non-finite chi2"
            )
        cov = None
        self.converged = False
        self.last_noise_floor = 0.0
        step_problem = False
        fell_back = False
        for it in range(maxiter):
            dx, cov, nbad, pred, proposal, fell_back = (
                self._guarded_proposal(proposal, x, fell_back)
            )
            if int(nbad):
                warnings.warn(
                    f"{int(nbad)} degenerate directions zeroed in downhill "
                    "proposal",
                    DegeneracyWarning,
                )
            c_all = np.asarray(chi2_ladder(x, dx))
            c_tries = c_all[: len(lams)]
            # same-program baseline at the current x (see ladder note)
            chi2 = float(c_all[-1])
            if not np.isfinite(chi2):
                # trial lambdas may legally overshoot into NaN, but a
                # non-finite BASELINE means the accepted state itself
                # is poisoned — refuse with the shared diagnosis
                validate_finite(
                    {"chi2_baseline": chi2},
                    site=f"downhill:{type(self).__name__}/baseline",
                    what="downhill chi2 baseline",
                )
            # floor re-measured from THIS ladder at THIS x, so the
            # tolerance tracks the shrinking residuals (ADVICE r3)
            noise_floor = self._chi2_noise_floor(
                all_lams[probe_sel], c_all[probe_sel]
            )
            self.last_noise_floor = noise_floor
            accepted = None
            for lam, c_try in zip(lams, c_tries):
                if np.isfinite(c_try) and c_try < (
                    chi2 + max_chi2_increase + noise_floor
                ):
                    accepted = (x + lam * dx, float(c_try))
                    break
            if accepted is None:
                # No acceptable step.  Noise-immune verdict: the
                # Gauss-Newton solve's own quadratic model predicts the
                # attainable decrease (dx.b); when that prediction sits
                # below the tolerance / backend chi2-noise floor the
                # model was already converged and the ladder's failure
                # is pure measurement noise — silent convergence.  A
                # LARGE predicted decrease that no trial realizes is a
                # genuine step problem (reference: StepProblem): warn,
                # keep the best-known parameters, and leave .converged
                # False so callers don't mistake a demonstrably failed
                # step for a successful fit (ADVICE r3).
                if float(pred) > max(required_chi2_decrease, noise_floor):
                    warnings.warn(
                        "downhill fit: no step length decreased chi2 "
                        f"(chi2={chi2:.6g}) despite a predicted "
                        f"decrease of {float(pred):.3g}; keeping the "
                        "best-known parameters",
                        ConvergenceWarning,
                    )
                    step_problem = True
                else:
                    self.converged = True
                break
            x_new, chi2_new = accepted
            decrease = chi2 - chi2_new
            x, chi2 = x_new, chi2_new
            if abs(decrease) < max(required_chi2_decrease, noise_floor):
                self.converged = True
                break
        if not self.converged and not step_problem:
            warnings.warn(
                f"downhill fit did not meet tolerance in {maxiter} "
                "iterations",
                ConvergenceWarning,
            )

        # covariance at the FINAL accepted state (the loop's cov is one
        # Gauss-Newton step stale for x-dependent sigmas/designs)
        _, cov, _, _ = proposal(x)
        from pint_tpu.runtime.fallback import GuardReport

        self.guard_report = GuardReport(
            site=f"downhill:{type(self).__name__}",
            rung="f64-fallback" if fell_back else "native",
            rung_index=1 if fell_back else 0,
        )
        return self._finalize(x, cov, float(chi2))


class DownhillWLSFitter(DownhillFitter):
    """Downhill WLS (reference: DownhillWLSFitter)."""

    def __init__(self, toas, model):
        super().__init__(toas, model)
        if self.cm.has_correlated_errors:
            from pint_tpu.exceptions import CorrelatedErrors

            raise CorrelatedErrors(model)

    def _make_proposal(self, force_f64: bool = False):
        # force_f64 is a no-op here: the WLS QR/SVD step is already the
        # f64 path, so the guard's fallback is a clean re-dispatch
        cm, noffset = self.cm, self._noffset

        @cm.jit
        def proposal(x):
            r = cm.time_residuals(x, subtract_mean=False)
            M = self._design_with_offset(x)
            w = 1.0 / jnp.square(cm.scaled_sigma(x))
            dx, cov, nbad = _wls_step(r, M, w, normalized_cov=True)
            # quadratic-model predicted chi2 decrease: dx . (-M^T W r)
            pred = -jnp.dot(dx, M.T @ (w * r))
            return dx[noffset:], cov, nbad, pred

        return proposal

    def _make_chi2(self):
        # cm.chi2 profiles the offset exactly via weighted-mean subtraction
        return self.cm.jit(self.cm.chi2)


class DownhillGLSFitter(DownhillFitter):
    """Downhill GLS (reference: DownhillGLSFitter).  The acceptance
    objective is the GLS chi2 r^T C^-1 r with the implicit offset
    profiled out analytically: chi2 - (1^T C^-1 r)^2 / (1^T C^-1 1)."""

    def __init__(self, toas, model, full_cov: bool = False):
        super().__init__(toas, model)
        self.full_cov = full_cov

    def _noise(self, x):
        Ndiag = jnp.square(self.cm.scaled_sigma(x))
        T, phi = self.cm.noise_basis_or_empty(x)
        return Ndiag, T, phi

    def _make_proposal(self, force_f64: bool = False):
        cm, noffset, full_cov = self.cm, self._noffset, self.full_cov
        # proposal DIRECTION quality is all that matters here (the
        # vmapped chi2 ladder still gates acceptance), so the
        # accelerator mixed path applies (GLSFitter's policy);
        # force_f64 is the guard's fallback rung — the all-f64
        # reduced-rank Woodbury step
        if force_f64:
            step = gls_step_woodbury
        elif full_cov:
            step = gls_step_full_cov
        elif default_accel_mode(cm) == "mixed":
            step = gls_step_woodbury_mixed
        else:
            step = gls_step_woodbury

        @cm.jit
        def proposal(x):
            r = cm.time_residuals(x, subtract_mean=False)
            M = self._design_with_offset(x)
            Ndiag, T, phi = self._noise(x)
            dx, cov, _, nbad = step(r, M, Ndiag, T, phi,
                                    normalized_cov=True)
            # quadratic-model predicted decrease: dx . (-M^T C^-1 r)
            Cir = make_cinv_mult(Ndiag, T, phi)(r[:, None])[:, 0]
            pred = -jnp.dot(dx, M.T @ Cir)
            return dx[noffset:], cov, nbad, pred

        return proposal

    def _make_chi2(self):
        cm = self.cm

        @cm.jit
        def chi2(x):
            r = cm.time_residuals(x, subtract_mean=False)
            Ndiag, T, phi = self._noise(x)
            cinv_mult = make_cinv_mult(Ndiag, T, phi)
            u = jnp.ones_like(r)
            Cir = cinv_mult(r[:, None])[:, 0]
            Ciu = cinv_mult(u[:, None])[:, 0]
            c2 = jnp.dot(r, Cir)
            if self._noffset:
                c2 = c2 - jnp.dot(u, Cir) ** 2 / jnp.dot(u, Ciu)
            return c2

        return chi2
