"""Downhill (step-halving) fitters.

Reference parity: src/pint/fitter.py::DownhillFitter / DownhillWLSFitter /
DownhillGLSFitter — propose a full Gauss-Newton step, evaluate chi2, and
halve the step length (lambda) until chi2 stops increasing; warn (keep
the best-known solution) when no acceptable step exists and raise
InvalidModelParameters on non-finite starts.

TPU-first differences: the proposal and the chi2 evaluation are the same
compiled kernels the plain fitters use (pure functions of the delta
vector x), so the lambda line-search costs one kernel call per trial —
no model rebuilds, no recompiles.  Since r9 the WHOLE trajectory —
proposal, lambda ladder, noise-floor measurement, accept/reject, and
stop/freeze control — runs as ONE ``lax.scan`` device program
(``_fused_loop``), so a steady-state downhill fit costs a single
guarded dispatch instead of ~maxiter host round-trips (~85 ms each
through the axon tunnel; profiling/dispatch_floor.py measures the
floor).  The reference host loop survives as ``_fit_toas_host`` — the
fault ladder's last rung and the ``PINT_TPU_DOWNHILL_FUSED=0`` escape
hatch — and ``.converged`` / ConvergenceWarning / DegeneracyWarning
behavior is reconstructed on the host from the program's returned
flags, so both paths are observably identical.
"""

from __future__ import annotations

import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import (
    ConvergenceWarning,
    DegeneracyWarning,
    GuardTripWarning,
    InvalidModelParameters,
    PintTpuNumericsError,
)
from pint_tpu.fitting.base import Fitter, device_noise_floor, record_fit
from pint_tpu.fitting.gls import (
    default_accel_mode,
    gls_step_full_cov,
    gls_step_woodbury,
    gls_step_woodbury_mixed,
    make_cinv_mult,
)
from pint_tpu.fitting.wls import _wls_step
from pint_tpu.runtime.guard import fence_owned, validate_finite


def _ladder_lams(min_lambda: float):
    """The static lambda ladder + measurement probes shared by the
    fused trajectory and the host loop.

    The ladder is static, so the whole line search is ONE vmapped
    device call per iteration (the reference's host loop evaluates
    trial steps one by one — up to 11 dispatches, ~85 ms each through
    the axon tunnel); the acceptance rule downstream picks the LARGEST
    acceptable lambda, exactly matching the sequential first-accept
    semantics.

    The probe lambdas are measurement-only values BELOW min_lambda
    (never accepted as steps): short enough that the true chi2 change
    is linear in lambda, so together they feed the per-iteration
    noise-floor line fit.  They are fixed small values, NOT
    min_lambda-scaled: the line-fit measurement needs lambdas deep in
    the linear regime even when a caller raises min_lambda (with e.g.
    min_lambda=0.5, scaled probes would sit where curvature
    ~pred*lambda^2 masquerades as noise) — except when the fixed list
    would only PARTIALLY survive (min_lambda in (6.25e-5, 5e-4]),
    which would leave the line fit under-determined and the floor
    silently 0; then the whole probe set scales down instead.

    The trailing lambda=0 entry is the BASELINE: measured on chip
    (r4), chi2 evaluated through a different XLA program (scalar vs
    vmapped) carries a program-decorrelated absolute offset (~1e-5
    chi2 units on golden1) while values within ONE program at nearby x
    are differentially accurate — so every accept/reject comparison
    uses the ladder's own same-program baseline, never a scalar
    evaluation.

    Returns (lams, probe_lams, all_lams) with
    all_lams = lams + probe_lams + [0.0] as a host array."""
    lams = []
    lam = 1.0
    while lam >= min_lambda:
        lams.append(lam)
        lam *= 0.5
    probe_lams = [
        s for s in (5e-4, 2.5e-4, 1.25e-4, 6.25e-5) if s < min_lambda
    ]
    if len(probe_lams) < 4:
        probe_lams = [min_lambda * f for f in (0.5, 0.25, 0.125, 0.0625)]
    all_lams = np.asarray(lams + probe_lams + [0.0])
    return lams, probe_lams, all_lams


class DownhillFitter(Fitter):
    """Base downhill fitter: subclasses provide _proposal
    (dx, cov, nbad, predicted_decrease) and _chi2 (offset-profiled
    objective) RAW traceable bodies — callers wrap them in
    ``self.cm.jit`` for a host-loop dispatch, or trace them directly
    inside the fused trajectory program."""

    method = "downhill"

    # subclasses override ------------------------------------------------
    def _make_proposal(self, force_f64: bool = False):
        """RAW proposal body ``x -> (dx, cov, nbad, pred)`` (no cm.jit
        wrap — the fused trajectory traces it inside its scan, nested
        guarded wrappers would re-dispatch per leg); ``force_f64=True``
        is the guard's fallback rung — the all-f64 step path on
        subclasses whose native proposal is mixed-precision (a no-op
        re-dispatch otherwise)."""
        raise NotImplementedError

    def _make_chi2(self):
        """RAW offset-profiled objective body ``x -> chi2``."""
        raise NotImplementedError

    def _guarded_proposal(self, proposal, x, fell_back: bool):
        """Dispatch + validate one HOST-LOOP proposal (runtime/guard.py
        shared validator).  A non-finite proposal falls back ONCE to the
        all-f64 step (the downhill sibling of the fit-loop ladder in
        runtime/fallback.py — the chi2 acceptance ladder downstream
        still gates every step, so no injected or real fault can slip
        a wrong step through silently).  Returns
        (dx, cov, nbad, pred, proposal, fell_back)."""
        site = f"downhill:{type(self).__name__}/proposal"
        dx, cov, nbad, pred = proposal(x)
        try:
            validate_finite({"dx": dx, "pred": pred}, site=site,
                            what="downhill proposal")
        except PintTpuNumericsError:
            if fell_back:
                raise
            warnings.warn(
                "downhill proposal produced non-finite values; "
                "falling back to the all-f64 proposal step",
                GuardTripWarning,
            )
            proposal = self.cm.jit(self._make_proposal(force_f64=True))
            fell_back = True
            dx, cov, nbad, pred = proposal(x)
            validate_finite({"dx": dx, "pred": pred},
                            site=site + "/rung:f64",
                            what="downhill proposal")
        return dx, cov, nbad, pred, proposal, fell_back

    # --------------------------------------------------------------------
    @staticmethod
    def _chi2_noise_floor(lams, c_tries) -> float:
        """MEASURED per-trial chi2 noise floor at the current state.

        Dedicated probe lambdas (<= 5e-4, plus the lambda=0 baseline)
        ride along in the single-dispatch ladder, short enough that
        the true chi2 change is linear in lambda to high accuracy
        (curvature enters at O(pred*lambda^2)); their deviation from a
        fitted straight line in lambda measures the backend's chi2
        evaluation scatter directly at the scale the accept/reject
        decisions operate on.  Measured on the axon chip (r4,
        golden1): within-program DIFFERENTIAL scatter ~3e-7 chi2
        units — the emulated-f64 error is smooth in x, so nearby
        evaluations in one program are far more consistent than the
        ~1e-7 s ABSOLUTE residual-noise model suggests (that model
        put the floor at ~5.8 chi2 units, silently loosening the r3
        acceptance tolerance by 7 orders; cross-PROGRAM offsets are
        the absolute-scale effect, handled by the ladder's lambda=0
        baseline).  Accept/reject decisions below this floor are coin
        flips — the r1/r2 spurious-ConvergenceWarning failure mode.
        Measuring per iteration removes r3's hard-coded delta_r=1e-7
        constant AND tracks the shrinking residuals as the fit
        converges (VERDICT r3 weak 4 + ADVICE r3).  The fused
        trajectory computes the same fit in-program
        (fitting/base.py::device_noise_floor)."""
        lams = np.asarray(lams, dtype=float)
        c = np.asarray(c_tries, dtype=float)
        ok = np.isfinite(c)
        if int(np.sum(ok)) < 4:
            return 0.0
        ls, cs = lams[ok], c[ok]
        coef = np.polyfit(ls, cs, 1)
        resid = cs - np.polyval(coef, ls)
        return 6.0 * float(np.sqrt(np.sum(resid**2) / (len(ls) - 2)))

    # -- the fused trajectory (r9) ----------------------------------------
    def _fused_loop(
        self,
        force_f64: bool,
        maxiter: int,
        required_chi2_decrease: float,
        max_chi2_increase: float,
        min_lambda: float,
    ):
        """The WHOLE downhill trajectory as ONE device program: a
        ``lax.scan`` over iterations whose live leg runs the
        Gauss-Newton proposal, the vmapped lambda ladder (trials +
        noise-floor probes + same-program baseline), the in-program
        noise-floor line fit, and the accept/reject + stop/freeze
        control; dead legs after convergence are O(1) pass-throughs.
        A steady-state fit is a single guarded dispatch through
        ``cm.jit`` instead of ~maxiter×(1+n_lams) tunnel round-trips.

        Semantics mirror ``_fit_toas_host`` decision-for-decision; the
        host cannot raise from inside the program, so hazards freeze
        the carry and return FLAGS (bad_prop/bad_base) that the fit
        ladder's validator converts back into the host loop's
        refusals.  Returns the compiled loop
        ``x0 -> (x, chi2, cov, init_chi2, done, conv, step_problem,
        pred, floor, bad_prop, bad_base, executed, nbads, floors)``,
        cached per (force_f64, maxiter, tolerances)."""
        key = (
            "downhill-fused", bool(force_f64), int(maxiter),
            float(required_chi2_decrease), float(max_chi2_increase),
            float(min_lambda),
        )
        loop = self._fit_loops.get(key)
        if loop is not None:
            return loop
        # no-arg call on the native rung: the proposal body is the
        # overridable surface (tests monkeypatch zero-arg makers)
        proposal = (
            self._make_proposal(force_f64=True) if force_f64
            else self._make_proposal()
        )
        chi2_fn = self._make_chi2()
        lams, _probe_lams, all_lams = _ladder_lams(min_lambda)
        nlam = len(lams)
        # O(10)-float ladder constants — baking them is intended (way
        # below any transport/413 threshold, and they constant-fold)
        lams_arr = jnp.asarray(all_lams)
        probe_arr = jnp.asarray(all_lams[nlam:])
        req = float(required_chi2_decrease)
        max_inc = float(max_chi2_increase)

        def body(carry, _):
            x, chi2c, done, conv, sp, pred_c, floor_c, badp, badb = carry

            def live(_op):
                dx, _cov, nbad, pred = proposal(x)
                prop_ok = jnp.all(jnp.isfinite(dx)) & jnp.isfinite(pred)
                c_all = jax.vmap(chi2_fn)(
                    x[None, :] + lams_arr[:, None] * dx[None, :]  # lint: ok(transport)
                )
                # same-program baseline at the current x (ladder note)
                base = c_all[-1]
                base_ok = jnp.isfinite(base)
                # floor re-measured from THIS ladder at THIS x, so the
                # tolerance tracks the shrinking residuals (ADVICE r3)
                floor = device_noise_floor(probe_arr, c_all[nlam:])  # lint: ok(transport)
                c_tries = c_all[:nlam]
                okm = jnp.isfinite(c_tries) & (
                    c_tries < base + max_inc + floor
                )
                # first True = LARGEST acceptable lambda (host order)
                idx = jnp.argmax(okm)
                any_ok = jnp.any(okm) & prop_ok & base_ok
                c_new = c_tries[idx]
                tol = jnp.maximum(req, floor)
                small = jnp.abs(base - c_new) < tol
                hazard = (~prop_ok) | (~base_ok)
                # no-accept verdicts (host-loop comment block applies):
                # a LARGE unrealized predicted decrease is a genuine
                # step problem; a sub-floor one is silent convergence
                sp_now = (~any_ok) & (~hazard) & (pred > tol)
                conv_now = jnp.where(
                    any_ok, small, (~sp_now) & (~hazard)
                )
                stop = (~any_ok) | small
                x_n = jnp.where(any_ok, x + lams_arr[idx] * dx, x)
                chi2_n = jnp.where(
                    any_ok, c_new, jnp.where(hazard, chi2c, base)
                )
                return (
                    x_n, chi2_n, stop, conv_now, sp_now, pred, floor,
                    badp | ~prop_ok, badb | (prop_ok & ~base_ok),
                    jnp.asarray(True), jnp.asarray(nbad, jnp.int32),
                    floor,
                )

            def dead(_op):
                return (
                    x, chi2c, done, conv, sp, pred_c, floor_c, badp,
                    badb, jnp.asarray(False),
                    jnp.asarray(0, jnp.int32), jnp.zeros_like(floor_c),
                )

            (
                x_n, chi2_n, done_n, conv_n, sp_n, pred_n, floor_n,
                badp_n, badb_n, executed, nbad, floor_y,
            ) = jax.lax.cond(done, dead, live, None)
            return (
                (x_n, chi2_n, done_n, conv_n, sp_n, pred_n, floor_n,
                 badp_n, badb_n),
                (executed, nbad, floor_y),
            )

        def downhill_traj(x0):
            init_chi2 = chi2_fn(x0)
            bad0 = ~jnp.isfinite(init_chi2)
            init = (
                x0, init_chi2, bad0, jnp.asarray(False),
                jnp.asarray(False), jnp.asarray(0.0), jnp.asarray(0.0),
                jnp.asarray(False), jnp.asarray(False),
            )
            carry, ys = jax.lax.scan(body, init, None, length=maxiter)
            x, chi2, done, conv, sp, pred, floor, badp, badb = carry
            # covariance at the FINAL accepted state (the in-loop cov
            # is one Gauss-Newton step stale for x-dependent designs)
            _, cov, _, _ = proposal(x)
            executed, nbads, floors = ys
            return (
                x, chi2, cov, init_chi2, done, conv, sp, pred, floor,
                badp, badb, executed, nbads, floors,
            )

        # the scan state is donated (ISSUE 12): x0 is freshly built
        # per fit_toas call (cm.x0()), the trajectory's x output
        # aliases it in place, and the guard snapshots it before any
        # replayable attempt — never reuse a loop argument after the
        # call (pintlint rule perf1)
        loop = self.cm.jit(downhill_traj, donate=True)
        self._fit_loops[key] = loop
        return loop

    def _finish_fused(self, out, maxiter: int) -> float:
        """Host tail of a fused-trajectory run: reconstruct the host
        loop's observable behavior — DegeneracyWarning per degenerate
        executed iteration, the step-problem / tolerance
        ConvergenceWarnings, ``.converged``, ``.niter``,
        ``.last_noise_floor`` — from the program's returned flags,
        then finalize exactly like the host loop."""
        (
            x, chi2, cov, _init_chi2, _done, conv, sp, pred, floor,
            _badp, _badb, executed, nbads, floors,
        ) = out
        executed = np.asarray(executed)
        nbads = np.asarray(nbads)
        for nb in nbads[executed & (nbads > 0)]:
            warnings.warn(
                f"{int(nb)} degenerate directions zeroed in downhill "
                "proposal",
                DegeneracyWarning,
            )
        self.niter = int(executed.sum())
        self.converged = bool(np.asarray(conv))
        self.last_noise_floor = float(np.asarray(floor))
        chi2 = float(np.asarray(chi2))
        if bool(np.asarray(sp)):
            warnings.warn(
                "downhill fit: no step length decreased chi2 "
                f"(chi2={chi2:.6g}) despite a predicted "
                f"decrease of {float(np.asarray(pred)):.3g}; keeping "
                "the best-known parameters",
                ConvergenceWarning,
            )
        elif not self.converged:
            warnings.warn(
                f"downhill fit did not meet tolerance in {maxiter} "
                "iterations",
                ConvergenceWarning,
            )
        return self._finalize(np.asarray(x), cov, chi2)

    def _start_x(self, x0):
        """Starting delta vector for a trajectory: ``cm.x0()`` (zeros =
        the par-file model) or a caller-supplied WARM START (ISSUE 14
        streaming refits: x0 = the previous converged solution, so the
        trajectory lands in 1-2 iterations).  The warm vector is
        round-tripped through host numpy into a FRESH device buffer:
        the fused loop donates its operand (perf1), and donating a
        buffer the caller still holds would poison their copy."""
        if x0 is None:
            return self.cm.x0()
        x0 = np.asarray(x0, dtype=np.float64)
        if x0.shape != (len(self.cm.free_names),):
            raise ValueError(
                f"warm-start x0 has shape {x0.shape}; expected "
                f"({len(self.cm.free_names)},)"
            )
        return jnp.asarray(x0)

    @record_fit
    def fit_toas(
        self,
        maxiter: int = 20,
        required_chi2_decrease: float = 1e-2,
        max_chi2_increase: float = 1e-2,
        min_lambda: float = 1e-3,
        x0=None,
    ) -> float:
        """One guarded dispatch at steady state: the fused trajectory
        runs down the fault ladder native -> all-f64 -> reference host
        loop (runtime/fallback.py::run_ladder), with the shared finite
        validator gating each rung — an injected or real non-finite
        fused result degrades instead of committing garbage.
        ``PINT_TPU_DOWNHILL_FUSED=0`` restores the host loop
        outright.

        ``x0`` (optional) WARM-STARTS the trajectory from a previous
        solution: the start vector is already a runtime argument of the
        cached fused-loop kernel, so a warm refit reuses the SAME
        compiled program as a cold fit — zero retraces — and the lambda
        ladder + convergence control are unchanged (a warm start near
        the optimum simply converges on the first or second iteration;
        a bad warm start walks downhill exactly like a cold fit)."""
        if os.environ.get("PINT_TPU_DOWNHILL_FUSED", "1") == "0":
            return self._fit_toas_host(
                maxiter, required_chi2_decrease, max_chi2_increase,
                min_lambda, x0=x0,
            )
        from pint_tpu.runtime.fallback import run_ladder

        site = f"downhill:{type(self).__name__}"

        def fused_thunk(force_f64):
            def thunk(_rung_site):
                loop = self._fused_loop(
                    force_f64, maxiter, required_chi2_decrease,
                    max_chi2_increase, min_lambda,
                )
                # the loop donates its operands, so its outputs may
                # alias recyclable buffers: materialize host-owned
                # copies before anything downstream keeps a view
                # (runtime/guard.py::fence_owned)
                return ("fused", fence_owned(loop(self._start_x(x0))))

            return thunk

        def host_thunk(_rung_site):
            return ("host", self._fit_toas_host(
                maxiter, required_chi2_decrease, max_chi2_increase,
                min_lambda, x0=x0,
            ))

        def validate(tagged, rung_site):
            kind, out = tagged
            if kind != "fused":
                return  # the host rung validates per-iteration itself
            x, chi2, _cov, init_chi2 = out[0], out[1], out[2], out[3]
            badp, badb = out[9], out[10]
            if not np.isfinite(float(np.asarray(init_chi2))):
                # reference semantics: a non-finite STARTING chi2 is a
                # caller error, never a backend fault — refuse without
                # laddering (InvalidModelParameters is not a trip)
                raise InvalidModelParameters(
                    "initial model produces non-finite chi2"
                )
            validate_finite(
                {"x": x, "chi2": chi2}, site=rung_site,
                what="fused downhill trajectory",
            )
            if bool(np.asarray(badp)) or bool(np.asarray(badb)):
                what = (
                    "proposal" if bool(np.asarray(badp))
                    else "chi2 baseline"
                )
                raise PintTpuNumericsError(
                    "fused downhill trajectory froze on a non-finite "
                    f"{what} at {rung_site}"
                )

        rungs = [
            ("native", fused_thunk(False)),
            ("f64-fallback", fused_thunk(True)),
            ("host-loop", host_thunk),
        ]
        (kind, out), report = run_ladder(rungs, site, validate=validate)
        self.guard_report = report
        if kind == "host":
            return out
        return self._finish_fused(out, maxiter)

    def _fit_toas_host(
        self,
        maxiter: int,
        required_chi2_decrease: float,
        max_chi2_increase: float,
        min_lambda: float,
        x0=None,
    ) -> float:
        """The reference host loop (~one guarded dispatch per leg):
        the fused trajectory's last ladder rung, and the
        ``PINT_TPU_DOWNHILL_FUSED=0`` escape hatch.  Sets
        ``guard_report`` itself for direct callers; the fused
        dispatcher overwrites it with the full ladder report."""
        proposal = self.cm.jit(self._make_proposal())
        chi2_raw = self._make_chi2()
        chi2_of = self.cm.jit(chi2_raw)
        lams, probe_lams, all_lams = _ladder_lams(min_lambda)
        # measure from the dedicated probes + the lambda=0 baseline
        # ONLY: ladder trials up to ~8e-3 carry a true quadratic term
        # ~pred*lambda^2 whose deviation from the fitted line would
        # scale the "noise" floor with the predicted decrease on
        # far-from-converged fits (r4 review)
        probe_sel = np.asarray(
            [False] * len(lams) + [True] * len(probe_lams) + [True]
        )
        lams_arr = jnp.asarray(all_lams)
        # O(10)-float ladder constant — baking it is intended (way
        # below any transport/413 threshold, and constant-folds)
        chi2_ladder = self.cm.jit(
            lambda x, dx: jax.vmap(chi2_raw)(
                x[None, :] + lams_arr[:, None] * dx[None, :]  # lint: ok(transport)
            )
        )

        x = self._start_x(x0)
        chi2 = float(chi2_of(x))
        if not np.isfinite(chi2):
            raise InvalidModelParameters(
                "initial model produces non-finite chi2"
            )
        cov = None
        self.converged = False
        self.last_noise_floor = 0.0
        self.niter = 0
        step_problem = False
        fell_back = False
        for it in range(maxiter):
            self.niter = it + 1
            dx, cov, nbad, pred, proposal, fell_back = (
                self._guarded_proposal(proposal, x, fell_back)
            )
            if int(nbad):
                warnings.warn(
                    f"{int(nbad)} degenerate directions zeroed in downhill "
                    "proposal",
                    DegeneracyWarning,
                )
            c_all = np.asarray(chi2_ladder(x, dx))
            c_tries = c_all[: len(lams)]
            # same-program baseline at the current x (see ladder note)
            chi2 = float(c_all[-1])
            if not np.isfinite(chi2):
                # trial lambdas may legally overshoot into NaN, but a
                # non-finite BASELINE means the accepted state itself
                # is poisoned — refuse with the shared diagnosis
                validate_finite(
                    {"chi2_baseline": chi2},
                    site=f"downhill:{type(self).__name__}/baseline",
                    what="downhill chi2 baseline",
                )
            # floor re-measured from THIS ladder at THIS x, so the
            # tolerance tracks the shrinking residuals (ADVICE r3)
            noise_floor = self._chi2_noise_floor(
                all_lams[probe_sel], c_all[probe_sel]
            )
            self.last_noise_floor = noise_floor
            accepted = None
            for lam, c_try in zip(lams, c_tries):
                if np.isfinite(c_try) and c_try < (
                    chi2 + max_chi2_increase + noise_floor
                ):
                    accepted = (x + lam * dx, float(c_try))
                    break
            if accepted is None:
                # No acceptable step.  Noise-immune verdict: the
                # Gauss-Newton solve's own quadratic model predicts the
                # attainable decrease (dx.b); when that prediction sits
                # below the tolerance / backend chi2-noise floor the
                # model was already converged and the ladder's failure
                # is pure measurement noise — silent convergence.  A
                # LARGE predicted decrease that no trial realizes is a
                # genuine step problem (reference: StepProblem): warn,
                # keep the best-known parameters, and leave .converged
                # False so callers don't mistake a demonstrably failed
                # step for a successful fit (ADVICE r3).
                if float(pred) > max(required_chi2_decrease, noise_floor):
                    warnings.warn(
                        "downhill fit: no step length decreased chi2 "
                        f"(chi2={chi2:.6g}) despite a predicted "
                        f"decrease of {float(pred):.3g}; keeping the "
                        "best-known parameters",
                        ConvergenceWarning,
                    )
                    step_problem = True
                else:
                    self.converged = True
                break
            x_new, chi2_new = accepted
            decrease = chi2 - chi2_new
            x, chi2 = x_new, chi2_new
            if abs(decrease) < max(required_chi2_decrease, noise_floor):
                self.converged = True
                break
        if not self.converged and not step_problem:
            warnings.warn(
                f"downhill fit did not meet tolerance in {maxiter} "
                "iterations",
                ConvergenceWarning,
            )

        # covariance at the FINAL accepted state (the loop's cov is one
        # Gauss-Newton step stale for x-dependent sigmas/designs)
        _, cov, _, _ = proposal(x)
        from pint_tpu.runtime.fallback import GuardReport

        self.guard_report = GuardReport(
            site=f"downhill:{type(self).__name__}",
            rung="f64-fallback" if fell_back else "native",
            rung_index=1 if fell_back else 0,
        )
        return self._finalize(x, cov, float(chi2))


class DownhillWLSFitter(DownhillFitter):
    """Downhill WLS (reference: DownhillWLSFitter)."""

    def __init__(self, toas, model):
        super().__init__(toas, model)
        if self.cm.has_correlated_errors:
            from pint_tpu.exceptions import CorrelatedErrors

            raise CorrelatedErrors(model)

    def _make_proposal(self, force_f64: bool = False):
        # force_f64 is a no-op here: the WLS QR/SVD step is already the
        # f64 path, so the guard's fallback is a clean re-dispatch
        cm, noffset = self.cm, self._noffset

        def proposal(x):
            r = cm.time_residuals(x, subtract_mean=False)
            M = self._design_with_offset(x)
            w = 1.0 / jnp.square(cm.scaled_sigma(x))
            dx, cov, nbad = _wls_step(r, M, w, normalized_cov=True)
            # quadratic-model predicted chi2 decrease: dx . (-M^T W r)
            pred = -jnp.dot(dx, M.T @ (w * r))
            return dx[noffset:], cov, nbad, pred

        return proposal

    def _make_chi2(self):
        # cm.chi2 profiles the offset exactly via weighted-mean subtraction
        return self.cm.chi2


class DownhillGLSFitter(DownhillFitter):
    """Downhill GLS (reference: DownhillGLSFitter).  The acceptance
    objective is the GLS chi2 r^T C^-1 r with the implicit offset
    profiled out analytically: chi2 - (1^T C^-1 r)^2 / (1^T C^-1 1)."""

    def __init__(self, toas, model, full_cov: bool = False):
        super().__init__(toas, model)
        self.full_cov = full_cov

    def _noise(self, x):
        Ndiag = jnp.square(self.cm.scaled_sigma(x))
        T, phi = self.cm.noise_basis_or_empty(x)
        return Ndiag, T, phi

    def _make_proposal(self, force_f64: bool = False):
        cm, noffset, full_cov = self.cm, self._noffset, self.full_cov
        # proposal DIRECTION quality is all that matters here (the
        # vmapped chi2 ladder still gates acceptance), so the
        # accelerator mixed path applies (GLSFitter's policy);
        # force_f64 is the guard's fallback rung — the all-f64
        # reduced-rank Woodbury step
        if force_f64:
            step = gls_step_woodbury
        elif full_cov:
            step = gls_step_full_cov
        elif default_accel_mode(cm) == "mixed":
            step = gls_step_woodbury_mixed
        else:
            step = gls_step_woodbury

        def proposal(x):
            r = cm.time_residuals(x, subtract_mean=False)
            M = self._design_with_offset(x)
            Ndiag, T, phi = self._noise(x)
            dx, cov, _, nbad = step(r, M, Ndiag, T, phi,
                                    normalized_cov=True)
            # quadratic-model predicted decrease: dx . (-M^T C^-1 r)
            Cir = make_cinv_mult(Ndiag, T, phi)(r[:, None])[:, 0]
            pred = -jnp.dot(dx, M.T @ Cir)
            return dx[noffset:], cov, nbad, pred

        return proposal

    def _make_chi2(self):
        cm = self.cm

        def chi2(x):
            r = cm.time_residuals(x, subtract_mean=False)
            Ndiag, T, phi = self._noise(x)
            cinv_mult = make_cinv_mult(Ndiag, T, phi)
            u = jnp.ones_like(r)
            Cir = cinv_mult(r[:, None])[:, 0]
            Ciu = cinv_mult(u[:, None])[:, 0]
            c2 = jnp.dot(r, Cir)
            if self._noffset:
                c2 = c2 - jnp.dot(u, Cir) ** 2 / jnp.dot(u, Ciu)
            return c2

        return chi2
