"""Downhill (step-halving) fitters.

Reference parity: src/pint/fitter.py::DownhillFitter / DownhillWLSFitter /
DownhillGLSFitter — propose a full Gauss-Newton step, evaluate chi2, and
halve the step length (lambda) until chi2 stops increasing; warn (keep
the best-known solution) when no acceptable step exists and raise
InvalidModelParameters on non-finite starts.

TPU-first differences: the proposal and the chi2 evaluation are the same
compiled kernels the plain fitters use (pure functions of the delta
vector x), so the lambda line-search costs one kernel call per trial —
no model rebuilds, no recompiles.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import (
    ConvergenceWarning,
    DegeneracyWarning,
    InvalidModelParameters,
)
from pint_tpu.fitting.base import Fitter
from pint_tpu.fitting.gls import (
    default_accel_mode,
    gls_step_full_cov,
    gls_step_woodbury,
    gls_step_woodbury_mixed,
    make_cinv_mult,
)
from pint_tpu.fitting.wls import _wls_step


class DownhillFitter(Fitter):
    """Base downhill fitter: subclasses provide _proposal
    (dx, cov, nbad, predicted_decrease) and _chi2 (offset-profiled
    objective) kernels."""

    method = "downhill"

    # subclasses override ------------------------------------------------
    def _make_proposal(self):
        raise NotImplementedError

    def _make_chi2(self):
        raise NotImplementedError

    # --------------------------------------------------------------------
    def _chi2_noise_floor(self, x) -> float:
        """Per-trial chi2 noise scale of the backend: 0 on IEEE-f64
        CPU; on accelerators with f32-pair emulated f64 (axon TPU) the
        residual kernels carry ~1e-7 s of deterministic-but-x-dependent
        noise (docs/precision.md), which scatters the lambda ladder's
        chi2 values by ~ delta_chi2 = 2 sqrt(sum (r_i w_i)^2) delta_r.
        Accept/reject decisions below 3x this floor are coin flips —
        the r1/r2 spurious-ConvergenceWarning failure mode (VERDICT r2
        weak 4)."""
        import jax

        if jax.default_backend() == "cpu":
            return 0.0
        delta_r = 1e-7  # documented emulated-f64 residual noise (s)
        r = np.asarray(self.cm.time_residuals(x))
        w = 1.0 / np.square(np.asarray(self.cm.scaled_sigma(x)))
        return 6.0 * delta_r * float(np.sqrt(np.sum((r * w) ** 2)))

    def fit_toas(
        self,
        maxiter: int = 20,
        required_chi2_decrease: float = 1e-2,
        max_chi2_increase: float = 1e-2,
        min_lambda: float = 1e-3,
    ) -> float:
        proposal = self._make_proposal()
        chi2_of = self._make_chi2()
        # the lambda ladder is static, so the whole line search is ONE
        # vmapped device call per iteration (the reference's host loop
        # evaluates trial steps one by one — up to 11 dispatches here,
        # ~85 ms each through the axon tunnel); the acceptance rule
        # below picks the LARGEST acceptable lambda, exactly matching
        # the sequential first-accept semantics.
        lams = []
        lam = 1.0
        while lam >= min_lambda:
            lams.append(lam)
            lam *= 0.5
        lams_arr = jnp.asarray(lams)
        chi2_ladder = jax.jit(
            lambda x, dx: jax.vmap(chi2_of)(
                x[None, :] + lams_arr[:, None] * dx[None, :]
            )
        )

        x = self.cm.x0()
        chi2 = float(chi2_of(x))
        if not np.isfinite(chi2):
            raise InvalidModelParameters(
                "initial model produces non-finite chi2"
            )
        noise_floor = self._chi2_noise_floor(x)
        cov = None
        self.converged = False
        for it in range(maxiter):
            dx, cov, nbad, pred = proposal(x)
            if int(nbad):
                warnings.warn(
                    f"{int(nbad)} degenerate directions zeroed in downhill "
                    "proposal",
                    DegeneracyWarning,
                )
            c_tries = np.asarray(chi2_ladder(x, dx))
            accepted = None
            for lam, c_try in zip(lams, c_tries):
                if np.isfinite(c_try) and c_try < (
                    chi2 + max_chi2_increase + noise_floor
                ):
                    accepted = (x + lam * dx, float(c_try))
                    break
            if accepted is None:
                # No acceptable step.  Noise-immune verdict: the
                # Gauss-Newton solve's own quadratic model predicts the
                # attainable decrease (dx.b); when that prediction sits
                # below the tolerance / backend chi2-noise floor the
                # model was already converged and the ladder's failure
                # is pure measurement noise — silent convergence.  A
                # LARGE predicted decrease that no trial realizes is a
                # genuine step problem (reference: StepProblem) and
                # still warns.
                if float(pred) > max(required_chi2_decrease, noise_floor):
                    warnings.warn(
                        "downhill fit: no step length decreased chi2 "
                        f"(chi2={chi2:.6g}) despite a predicted "
                        f"decrease of {float(pred):.3g}; keeping the "
                        "best-known parameters",
                        ConvergenceWarning,
                    )
                self.converged = True
                break
            x_new, chi2_new = accepted
            decrease = chi2 - chi2_new
            x, chi2 = x_new, chi2_new
            if abs(decrease) < max(required_chi2_decrease, noise_floor):
                self.converged = True
                break
        if not self.converged:
            warnings.warn(
                f"downhill fit did not meet tolerance in {maxiter} "
                "iterations",
                ConvergenceWarning,
            )

        # covariance at the FINAL accepted state (the loop's cov is one
        # Gauss-Newton step stale for x-dependent sigmas/designs)
        _, cov, _, _ = proposal(x)
        return self._finalize(x, cov, float(chi2))


class DownhillWLSFitter(DownhillFitter):
    """Downhill WLS (reference: DownhillWLSFitter)."""

    def __init__(self, toas, model):
        super().__init__(toas, model)
        if self.cm.has_correlated_errors:
            from pint_tpu.exceptions import CorrelatedErrors

            raise CorrelatedErrors(model)

    def _make_proposal(self):
        cm, noffset = self.cm, self._noffset

        @jax.jit
        def proposal(x):
            r = cm.time_residuals(x, subtract_mean=False)
            M = self._design_with_offset(x)
            w = 1.0 / jnp.square(cm.scaled_sigma(x))
            dx, cov, nbad = _wls_step(r, M, w, normalized_cov=True)
            # quadratic-model predicted chi2 decrease: dx . (-M^T W r)
            pred = -jnp.dot(dx, M.T @ (w * r))
            return dx[noffset:], cov, nbad, pred

        return proposal

    def _make_chi2(self):
        # cm.chi2 profiles the offset exactly via weighted-mean subtraction
        return jax.jit(self.cm.chi2)


class DownhillGLSFitter(DownhillFitter):
    """Downhill GLS (reference: DownhillGLSFitter).  The acceptance
    objective is the GLS chi2 r^T C^-1 r with the implicit offset
    profiled out analytically: chi2 - (1^T C^-1 r)^2 / (1^T C^-1 1)."""

    def __init__(self, toas, model, full_cov: bool = False):
        super().__init__(toas, model)
        self.full_cov = full_cov

    def _noise(self, x):
        Ndiag = jnp.square(self.cm.scaled_sigma(x))
        T, phi = self.cm.noise_basis_or_empty(x)
        return Ndiag, T, phi

    def _make_proposal(self):
        cm, noffset, full_cov = self.cm, self._noffset, self.full_cov
        # proposal DIRECTION quality is all that matters here (the
        # vmapped chi2 ladder still gates acceptance), so the
        # accelerator mixed path applies (GLSFitter's policy)
        if full_cov:
            step = gls_step_full_cov
        elif default_accel_mode(cm) == "mixed":
            step = gls_step_woodbury_mixed
        else:
            step = gls_step_woodbury

        @jax.jit
        def proposal(x):
            r = cm.time_residuals(x, subtract_mean=False)
            M = self._design_with_offset(x)
            Ndiag, T, phi = self._noise(x)
            dx, cov, _, nbad = step(r, M, Ndiag, T, phi,
                                    normalized_cov=True)
            # quadratic-model predicted decrease: dx . (-M^T C^-1 r)
            Cir = make_cinv_mult(Ndiag, T, phi)(r[:, None])[:, 0]
            pred = -jnp.dot(dx, M.T @ Cir)
            return dx[noffset:], cov, nbad, pred

        return proposal

    def _make_chi2(self):
        cm = self.cm

        @jax.jit
        def chi2(x):
            r = cm.time_residuals(x, subtract_mean=False)
            Ndiag, T, phi = self._noise(x)
            cinv_mult = make_cinv_mult(Ndiag, T, phi)
            u = jnp.ones_like(r)
            Cir = cinv_mult(r[:, None])[:, 0]
            Ciu = cinv_mult(u[:, None])[:, 0]
            c2 = jnp.dot(r, Cir)
            if self._noffset:
                c2 = c2 - jnp.dot(u, Cir) ** 2 / jnp.dot(u, Ciu)
            return c2

        return chi2
