"""Weighted-least-squares fitter (uncorrelated errors).

Reference parity: src/pint/fitter.py::WLSFitter.fit_toas — iterate:
residuals, design matrix (plus implicit offset column), column-normalized
SVD solve, step, chi2.  Differences by design:
- the kernel is exact in the delta vector x, so iterations never
  recompile and 'downhill' step-halving operates on the same kernels;
- the SVD runs on device (jnp.linalg), sharded when the TOA axis is
  sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import ConvergenceFailure, DegeneracyWarning
from pint_tpu.fitting.base import Fitter
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.toas.toas import TOAs


def _wls_step(r, M, w, threshold=None):
    """One WLS normal-equation solve via column-scaled SVD.

    r (n,), M (n,p) = d resid/d x, w (n,) weights -> (delta_x (p,),
    covariance (p,p)).  Mirrors the reference's conditioning trick:
    scale columns to unit norm before SVD (fitter.py::WLSFitter).
    """
    sw = jnp.sqrt(w)
    A = M * sw[:, None]
    b = -r * sw
    norm = jnp.sqrt(jnp.sum(A * A, axis=0))
    norm = jnp.where(norm == 0, 1.0, norm)
    A = A / norm[None, :]
    U, s, Vt = jnp.linalg.svd(A, full_matrices=False)
    if threshold is None:
        threshold = jnp.finfo(jnp.float64).eps * max(A.shape)
    bad = s < threshold * s[0]
    s_inv = jnp.where(bad, 0.0, 1.0 / jnp.where(bad, 1.0, s))
    dx = (Vt.T * s_inv[None, :]) @ (U.T @ b) / norm
    cov = (Vt.T * s_inv[None, :] ** 2) @ Vt / jnp.outer(norm, norm)
    return dx, cov, jnp.sum(bad)


class WLSFitter(Fitter):
    # residuals WITHOUT mean subtraction; the offset column absorbs the
    # mean exactly as the reference's "Offset" design-matrix column does.
    def _r(self, x):
        return self.cm.time_residuals(x, subtract_mean=False)

    def fit_toas(self, maxiter: int = 4, tol_chi2: float = 1e-10) -> float:
        if self.cm.has_correlated_errors:
            from pint_tpu.exceptions import CorrelatedErrors

            raise CorrelatedErrors(self.model)

        @jax.jit
        def step(x):
            r = self._r(x)
            M = self._design_with_offset(x)
            w = 1.0 / jnp.square(self.cm.scaled_sigma(x))
            dx, cov, nbad = _wls_step(r, M, w)
            return dx, cov, nbad

        @jax.jit
        def chi2_of(x):
            return self.cm.chi2(x)

        x = self.cm.x0()
        chi2 = float(chi2_of(x))
        cov = None
        for it in range(maxiter):
            dx, cov, nbad = step(x)
            if int(nbad):
                import warnings

                warnings.warn(
                    f"{int(nbad)} degenerate design-matrix directions "
                    "zeroed in SVD solve",
                    DegeneracyWarning,
                )
            x_new = x + dx[self._noffset:]  # dx[0] is the offset column
            chi2_new = float(chi2_of(x_new))
            if not np.isfinite(chi2_new):
                raise ConvergenceFailure("non-finite chi2 during WLS fit")
            x, last_chi2, chi2 = x_new, chi2, chi2_new
            if abs(last_chi2 - chi2) < tol_chi2 * max(chi2, 1.0):
                self.converged = True
                break

        # parameter covariance in free_names order (offset row/col
        # dropped, matching the reference's parameter_covariance_matrix
        # without Offset)
        return self._finalize(x, cov, chi2)
