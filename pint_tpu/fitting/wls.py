"""Weighted-least-squares fitter (uncorrelated errors).

Reference parity: src/pint/fitter.py::WLSFitter.fit_toas — iterate:
residuals, design matrix (plus implicit offset column), column-normalized
SVD solve, step, chi2.  Differences by design:
- the kernel is exact in the delta vector x, so iterations never
  recompile and 'downhill' step-halving operates on the same kernels;
- the SVD runs on device (jnp.linalg), sharded when the TOA axis is
  sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pint_tpu.fitting.base import Fitter, make_scan_fit_loop, record_fit
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.toas.toas import TOAs


#: QR acceptance: min|R_ii|/max|R_ii| above this takes the QR answer;
#: below it (near-exact degeneracy) the thresholded-eigh gram path
#: zeroes the bad directions and reports them.  Measured r5 cliff
#: placement, docs/precision.md: on-chip QR holds ~cond * 1e-13 out to
#: cond ~1e10, while the gram route silently loses ALL accuracy past
#: cond ~1e3-1e4 (emulated-f64 eigh is only ~f32-grade and the Gram
#: squares the condition number).
_QR_DIAG_RTOL = 1e-8

#: Ceiling on the cheap triangular condition ESTIMATE of R (ADVICE
#: r5): |R_ii| ratios alone under-reveal rank for unpivoted QR (a
#: matrix can be numerically singular with benign diagonals — the
#: classic Kahan example), so the gate is backed by a LINPACK-style
#: one-solve estimate (growth of R^-1 @ 1).  Set well PAST the
#: cond ~1e10 ladder QR is validated on
#: (tests/test_onchip_accuracy.py::test_onchip_wls_conditioning_*):
#: at cond >= 1e13 the QR answer's relerr (~cond * 1e-13) reaches
#: O(1), so routing to the gram fallback — which ZEROES the
#: degenerate directions and reports them, the reference SVD-cut
#: semantics — loses nothing and regains a bounded answer.  Between
#: ~1e10 and this ceiling QR still beats gram by orders of magnitude,
#: so a mid-band handoff would be a net accuracy LOSS.
_QR_COND_MAX = 1e13


def default_wls_method() -> str:
    """The backend-dependent WLS solve policy: the reference's
    column-scaled 'svd' lstsq on CPU; on accelerators (axon's
    emulated-f64 SVD NaNs) 'qr' — a Householder-QR least squares with
    a thresholded-eigh gram FALLBACK for near-exact degeneracy.
    Single source of truth for _wls_step and every fitter that names
    the method in a DegeneracyWarning."""
    return "svd" if jax.default_backend() == "cpu" else "qr"


def _wls_step(r, M, w, threshold=None, method=None,
              normalized_cov=False):
    """One WLS least-squares solve with degenerate-direction zeroing.

    r (n,), M (n,p) = d resid/d x, w (n,) weights -> (delta_x (p,),
    covariance (p,p), n_degenerate).  Mirrors the reference's
    conditioning trick: scale columns to unit norm first
    (fitter.py::WLSFitter).

    method='svd' (CPU default) is the reference's column-scaled SVD
    lstsq.  method='qr' (accelerator default, r5) factorizes the
    column-normalized weighted design directly: on-chip QR +
    triangular solve measure near-IEEE accuracy (relerr ~ cond *
    1e-13 on a synthetic ladder out to cond 1e10 —
    tests/test_onchip_accuracy.py::test_onchip_wls_conditioning_*),
    because Householder reflections never square the condition
    number.  The step takes the 'gram' answer instead — which zeroes
    the degenerate directions and counts them (the reference's
    SVD-cut semantics) — when the factor looks rank-deficient:
    diag(R) ratio below _QR_DIAG_RTOL, OR a cheap
    one-triangular-solve condition estimate above _QR_COND_MAX (r6;
    unpivoted QR's diagonal is NOT a reliable rank revealer on its
    own — Kahan-type matrices keep benign |R_ii| while R^-1
    explodes, which the solve-growth estimate catches).  The
    fallback rides a jax.lax.cond, so the full-rank common case
    never executes the O(n p^2) Gram product + eigh at runtime.
    UNDERDETERMINED systems (n < p: R is non-square, no triangular
    solve exists) route to 'gram' statically — shapes are known at
    trace time (r6; previously a shape error deep inside
    solve_triangular).

    method='gram' solves the p x p normal equations by thresholded
    eigh (the r2-r4 accelerator default, kept for the fallback and for
    comparison): the Gram SQUARES the condition number and axon's
    emulated-f64 eigh is only ~f32-grade, so this route silently
    degrades from cond ~1e3 — the r5 measurement that made 'qr' the
    default.  Its eigenvalue cut is eps*max(n,p)*lam_max — the Gram's
    own roundoff floor (the GLS-tail convention,
    gls.py::_finish_normal_eqs): it zeroes directions with s/s0 below
    sqrt(eps*max(n,p)) — ~4e-7 at n=600, ~4.7e-6 at n=1e5.
    """
    from pint_tpu.fitting.gls import _column_norms, _eigh_threshold_solve

    if method is None:
        method = default_wls_method()
    sw = jnp.sqrt(w)
    b = -r * sw
    # _column_norms is the overflow-safe (|max|-prescaled) column norm:
    # weighted design columns reach ~1e21 (the F1 column is
    # dt^2/2 * 1/sigma) and naive squares overflow the f32 EXPONENT
    # range of f32-pair emulated f64 (axon TPU)
    norm = _column_norms(M * sw[:, None])
    A = (M / norm[None, :]) * sw[:, None]
    if threshold is None:
        threshold = jnp.finfo(jnp.float64).eps * max(A.shape)
    if method == "qr" and A.shape[0] < A.shape[1]:
        # underdetermined: reduced QR yields R (n, p) non-square —
        # there is no triangular solve; the thresholded-eigh gram
        # path handles the rank-deficient normal equations (ADVICE r5)
        method = "gram"
    if method == "gram":
        dx, covn, nbad = _eigh_threshold_solve(A.T @ A, A.T @ b, threshold)
    elif method == "qr":
        Q, R = jnp.linalg.qr(A)
        diag = jnp.abs(jnp.diagonal(R))
        diag_ok = diag.min() > _QR_DIAG_RTOL * diag.max()
        # cheap condition estimate (one triangular solve): the growth
        # of z = R^-1 @ 1 lower-bounds ||R^-1||; with unit-norm
        # columns ||R|| <= sqrt(p), so max|z| * max|R_ii| ~ cond(R).
        # Non-finite growth (exact singularity overflowed the solve)
        # also fails the gate.
        z = jax.scipy.linalg.solve_triangular(
            R, jnp.ones((A.shape[1],), dtype=A.dtype), lower=False
        )
        cond_est = jnp.max(jnp.abs(z)) * diag.max()
        rank_ok = (
            diag_ok
            & jnp.isfinite(cond_est)
            & (cond_est < _QR_COND_MAX)
        )

        def qr_solve(_):
            Rinv = jax.scipy.linalg.solve_triangular(
                R, jnp.eye(A.shape[1], dtype=A.dtype), lower=False
            )
            dx = Rinv @ (Q.T @ b)
            return dx, Rinv @ Rinv.T, jnp.asarray(0, jnp.int64)

        def gram_fallback(_):
            dx, covn, nbad = _eigh_threshold_solve(
                A.T @ A, A.T @ b, threshold
            )
            return dx, covn, nbad.astype(jnp.int64)

        dx, covn, nbad = jax.lax.cond(
            rank_ok, qr_solve, gram_fallback, None
        )
    else:
        # CPU-pinned path: 'svd' is only ever the default on the CPU
        # backend (default_wls_method routes accelerators to 'qr'
        # because this very SVD NaNs under axon's emulated f64)
        U, s, Vt = jnp.linalg.svd(A, full_matrices=False)  # lint: ok(f64-emu)
        bad = s < threshold * s[0]
        s_inv = jnp.where(bad, 0.0, 1.0 / jnp.where(bad, 1.0, s))
        dx = (Vt.T * s_inv[None, :]) @ (U.T @ b)
        covn = (Vt.T * s_inv[None, :] ** 2) @ Vt
        nbad = jnp.sum(bad)
    if normalized_cov:  # see gls.py::_finish_normal_eqs on why
        return dx / norm, (covn, norm), nbad
    return dx / norm, covn / jnp.outer(norm, norm), nbad


class WLSFitter(Fitter):
    """Iterated WLS fit, run — like GLSFitter — as ONE device program
    (the whole Gauss-Newton iteration in a lax.scan, one dispatch per
    fit instead of 2·maxiter host round-trips)."""

    # residuals WITHOUT mean subtraction; the offset column absorbs the
    # mean exactly as the reference's "Offset" design-matrix column does.
    def _r(self, x):
        return self.cm.time_residuals(x, subtract_mean=False)

    def _make_fit_loop(self, maxiter: int, tol_chi2: float):
        """Shared scan harness (base.make_scan_fit_loop) around the WLS
        step; chi2 is cm.chi2 at the post-step state and the
        comparison seed is chi2(x0) (reference semantics:
        src/pint/fitter.py::WLSFitter.fit_toas)."""
        no = self._noffset
        p = len(self.cm.free_names) + no
        # resolve the solve method here so DegeneracyWarning can name it
        # (the 'gram' eigenvalue cut zeroes directions ~1e-6 that 'svd'
        # keeps — backend-dependent min-norm answers, docs/precision.md)
        self._wls_method = default_wls_method()

        def live_step(x):
            r = self._r(x)
            M = self._design_with_offset(x)
            w = 1.0 / jnp.square(self.cm.scaled_sigma(x))
            dx, cov, nbad = _wls_step(
                r, M, w, method=self._wls_method, normalized_cov=True
            )
            x_new = x + dx[no:]  # dx[0] is the offset column
            return x_new, cov, self.cm.chi2(x_new), nbad.astype(jnp.int32)

        return make_scan_fit_loop(
            live_step, p, maxiter, tol_chi2, self.cm.chi2, cm=self.cm
        )

    @record_fit
    def fit_toas(self, maxiter: int = 4, tol_chi2: float = 1e-10) -> float:
        if self.cm.has_correlated_errors:
            from pint_tpu.exceptions import CorrelatedErrors

            raise CorrelatedErrors(self.model)
        from pint_tpu.runtime.fallback import run_fit_ladder

        def make_loop(rung_mode):
            # the WLS solve method is resolved inside _make_fit_loop
            # (QR on accelerators, SVD on CPU) and IS already the f64
            # path, so every rung reuses the same loop; the final
            # 'cpu' rung re-dispatches it under the ladder-device pin
            # (IEEE f64 on accelerator backends; a clean re-dispatch
            # on CPU ones).
            key = (maxiter, tol_chi2)
            if key not in self._fit_loops:
                self._fit_loops[key] = self._make_fit_loop(*key)
            return self._fit_loops[key]

        result, self.guard_report = run_fit_ladder(
            self.cm, default_wls_method(), make_loop,
            site=f"fit:{type(self).__name__}",
            fail_msg="non-finite chi2 during WLS fit",
            f64_rung=False,
        )
        # parameter covariance comes back in free_names order (offset
        # row/col dropped in _finalize, matching the reference's
        # parameter_covariance_matrix without Offset)
        return self._finish_scan_fit(
            result,
            "degenerate design-matrix directions zeroed in WLS solve "
            f"(method={self._wls_method}; threshold is backend-dependent"
            " — see docs/precision.md)",
            "non-finite chi2 during WLS fit",
        )
