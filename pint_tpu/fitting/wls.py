"""Weighted-least-squares fitter (uncorrelated errors).

Reference parity: src/pint/fitter.py::WLSFitter.fit_toas — iterate:
residuals, design matrix (plus implicit offset column), column-normalized
SVD solve, step, chi2.  Differences by design:
- the kernel is exact in the delta vector x, so iterations never
  recompile and 'downhill' step-halving operates on the same kernels;
- the SVD runs on device (jnp.linalg), sharded when the TOA axis is
  sharded.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import ConvergenceFailure, DegeneracyWarning
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.residuals import Residuals
from pint_tpu.toas.toas import TOAs


def _wls_step(r, M, w, threshold=None):
    """One WLS normal-equation solve via column-scaled SVD.

    r (n,), M (n,p) = d resid/d x, w (n,) weights -> (delta_x (p,),
    covariance (p,p)).  Mirrors the reference's conditioning trick:
    scale columns to unit norm before SVD (fitter.py::WLSFitter).
    """
    sw = jnp.sqrt(w)
    A = M * sw[:, None]
    b = -r * sw
    norm = jnp.sqrt(jnp.sum(A * A, axis=0))
    norm = jnp.where(norm == 0, 1.0, norm)
    A = A / norm[None, :]
    U, s, Vt = jnp.linalg.svd(A, full_matrices=False)
    if threshold is None:
        threshold = jnp.finfo(jnp.float64).eps * max(A.shape)
    bad = s < threshold * s[0]
    s_inv = jnp.where(bad, 0.0, 1.0 / jnp.where(bad, 1.0, s))
    dx = (Vt.T * s_inv[None, :]) @ (U.T @ b) / norm
    cov = (Vt.T * s_inv[None, :] ** 2) @ Vt / jnp.outer(norm, norm)
    return dx, cov, jnp.sum(bad)


class WLSFitter:
    def __init__(self, toas: TOAs, model: TimingModel):
        self.toas = toas
        self.model = model
        self.cm = model.compile(toas)
        self.resids_init = Residuals(toas, model, compiled=self.cm)
        self.resids: Residuals = self.resids_init
        self.converged = False
        self.parameter_covariance_matrix: np.ndarray | None = None

    # residuals WITHOUT mean subtraction; the offset column absorbs the
    # mean exactly as the reference's "Offset" design-matrix column does.
    def _r(self, x):
        return self.cm.time_residuals(x, subtract_mean=False)

    @property
    def _noffset(self):
        # PHOFF (explicit fitted phase offset) replaces the implicit
        # offset column; both together are exactly degenerate
        return 0 if "PHOFF" in self.cm.free_names else 1

    def _design_with_offset(self, x):
        M = self.cm.design_matrix(x)
        if not self._noffset:
            return M
        ones = jnp.ones((self.cm.bundle.ntoa, 1))
        return jnp.concatenate([ones, M], axis=1)

    def fit_toas(self, maxiter: int = 4, tol_chi2: float = 1e-10) -> float:
        if self.cm.has_correlated_errors:
            from pint_tpu.exceptions import CorrelatedErrors

            raise CorrelatedErrors(self.model)

        @jax.jit
        def step(x):
            r = self._r(x)
            M = self._design_with_offset(x)
            w = 1.0 / jnp.square(self.cm.scaled_sigma(x))
            dx, cov, nbad = _wls_step(r, M, w)
            return dx, cov, nbad

        @jax.jit
        def chi2_of(x):
            return self.cm.chi2(x)

        x = self.cm.x0()
        chi2 = float(chi2_of(x))
        cov = None
        for it in range(maxiter):
            dx, cov, nbad = step(x)
            if int(nbad):
                import warnings

                warnings.warn(
                    f"{int(nbad)} degenerate design-matrix directions "
                    "zeroed in SVD solve",
                    DegeneracyWarning,
                )
            x_new = x + dx[self._noffset:]  # dx[0] is the offset column
            chi2_new = float(chi2_of(x_new))
            if not np.isfinite(chi2_new):
                raise ConvergenceFailure("non-finite chi2 during WLS fit")
            x, last_chi2, chi2 = x_new, chi2, chi2_new
            if abs(last_chi2 - chi2) < tol_chi2 * max(chi2, 1.0):
                self.converged = True
                break

        # parameter covariance in free_names order (offset row/col
        # dropped, matching the reference's parameter_covariance_matrix
        # without Offset)
        no = self._noffset
        cov = np.asarray(cov)[no:, no:]
        sigmas = np.sqrt(np.diag(cov))
        self.parameter_covariance_matrix = cov
        self.cm.commit(np.asarray(x), uncertainties=sigmas)
        self.resids = Residuals(
            self.toas, self.model, compiled=self.cm
        )
        self.model.top_params["CHI2"].value = chi2
        return chi2

    def print_summary(self) -> str:
        lines = [
            f"Fitted model using WLS with {len(self.cm.free_names)} free "
            f"parameters, {len(self.toas)} TOAs",
            f"chi2 = {self.resids.chi2:.4f}  dof = {self.resids.dof}  "
            f"reduced chi2 = {self.resids.reduced_chi2:.4f}",
            f"weighted RMS = {self.resids.rms_weighted() * 1e6:.4f} us",
            "",
            f"{'PARAM':<12}{'VALUE':>25}{'UNCERTAINTY':>15}",
        ]
        for n in self.cm.free_names:
            p = self.model.params[n]
            lines.append(
                f"{n:<12}{p._format_value():>25}"
                f"{p.uncertainty if p.uncertainty is not None else float('nan'):>15.3e}"
            )
        out = "\n".join(lines)
        print(out)
        return out
