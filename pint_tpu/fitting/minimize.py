"""Derivative-free / quasi-Newton chi2 minimization.

Reference parity: src/pint/fitter.py::PowellFitter — scipy
minimization over the model chi2 for problems where the Gauss-Newton
step misbehaves (strong nonlinearity, near-degenerate geometry).
TPU-first: the objective is the jitted chi2 kernel of x, and for the
gradient-based methods jax.grad supplies exact derivatives (the
reference's Powell is derivative-free only).
"""

from __future__ import annotations

import jax
import numpy as np
from scipy.optimize import minimize

from pint_tpu.fitting.base import Fitter, record_fit


class MinimizeFitter(Fitter):
    """scipy.optimize.minimize over the chi2 kernel (method='Powell'
    reproduces the reference's PowellFitter; 'L-BFGS-B'/'BFGS' use jax
    gradients)."""

    def __init__(self, toas, model, method: str = "Powell"):
        super().__init__(toas, model)
        if self.cm.has_correlated_errors:
            from pint_tpu.exceptions import CorrelatedErrors

            raise CorrelatedErrors(model)
        self.method = method

    @record_fit
    def fit_toas(self, maxiter: int = 2000) -> float:
        chi2 = self.cm.jit(self.cm.chi2)
        kw = {}
        if self.method not in ("Powell", "Nelder-Mead"):
            grad = self.cm.jit(jax.grad(self.cm.chi2))
            kw["jac"] = lambda v: np.asarray(grad(np.asarray(v)))
        res = minimize(
            lambda v: float(chi2(np.asarray(v))),
            np.zeros(self.cm.nfree),
            method=self.method,
            options={"maxiter": maxiter},
            **kw,
        )
        self.converged = bool(res.success)
        # uncertainties from the Gauss-Newton covariance at the optimum
        from pint_tpu.fitting.wls import _wls_step
        import jax.numpy as jnp

        x = jnp.asarray(res.x)
        M = self._design_with_offset(x)
        w = 1.0 / jnp.square(self.cm.scaled_sigma(x))
        # normalized covariance (device outer(norm, norm) overflows
        # f32-range emulated f64); _finalize unnormalizes on the host
        _, cov, _ = _wls_step(
            jnp.zeros(self.cm.bundle.ntoa), M, w, normalized_cov=True
        )
        return self._finalize(res.x, cov, float(res.fun))


class PowellFitter(MinimizeFitter):
    """Name-compatible alias (reference: fitter.PowellFitter)."""

    def __init__(self, toas, model):
        super().__init__(toas, model, method="Powell")
