"""Shared fitter machinery.

Reference parity: src/pint/fitter.py::Fitter (the common state held by
WLS/GLS/downhill variants: compiled model, residuals, covariance,
offset-column handling, post-fit commit, summary printing).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.timing_model import TimingModel
from pint_tpu.residuals import Residuals
from pint_tpu.toas.toas import TOAs


def noffset(cm) -> int:
    """1 when the implicit offset column is in use; 0 when a free PHOFF
    replaces it (both together are exactly degenerate)."""
    return 0 if "PHOFF" in cm.free_names else 1


def design_with_offset(cm, x):
    """Design matrix with the implicit offset column prepended (when
    applicable) — shared by fitters, gridutils, and the MCMC seeder."""
    M = cm.design_matrix(x)
    if not noffset(cm):
        return M
    ones = jnp.ones((cm.bundle.ntoa, 1))
    return jnp.concatenate([ones, M], axis=1)


class Fitter:
    """Common base: compiled kernels + offset column + post-fit commit."""

    def __init__(self, toas: TOAs, model: TimingModel):
        self.toas = toas
        self.model = model
        self.cm = model.compile(toas)
        self.resids_init = Residuals(toas, model, compiled=self.cm)
        self.resids: Residuals = self.resids_init
        self.converged = False
        self.parameter_covariance_matrix: np.ndarray | None = None
        self.chi2: float | None = None

    @property
    def _noffset(self):
        return noffset(self.cm)

    def _design_with_offset(self, x):
        return design_with_offset(self.cm, x)

    def _make_resids(self):
        """Residuals object for the current compiled state; wideband
        fitters override to return WidebandResiduals."""
        return Residuals(self.toas, self.model, compiled=self.cm)

    def _finalize(self, x, cov, chi2: float):
        """Drop the offset row/col, commit fitted deltas + uncertainties
        back into host Parameters, refresh residuals."""
        no = self._noffset
        cov = np.asarray(cov)[no:, no:]
        sigmas = np.sqrt(np.diag(cov))
        self.parameter_covariance_matrix = cov
        self.cm.commit(np.asarray(x), uncertainties=sigmas)
        self.resids = self._make_resids()
        self.model.top_params["CHI2"].value = float(chi2)
        self.chi2 = float(chi2)
        return float(chi2)

    def print_summary(self) -> str:
        chi2 = self.chi2 if self.chi2 is not None else self.resids.chi2
        lines = [
            f"Fitted model using {type(self).__name__} with "
            f"{len(self.cm.free_names)} free parameters, "
            f"{len(self.toas)} TOAs; converged={self.converged}",
            f"chi2 = {chi2:.4f}",
        ]
        dof = getattr(self.resids, "dof", None)
        if dof is not None:
            lines.append(
                f"dof = {dof}  reduced chi2 = {chi2 / dof:.4f}"
            )
        if hasattr(self.resids, "rms_weighted"):
            lines.append(
                f"weighted RMS = {self.resids.rms_weighted() * 1e6:.4f} us"
            )
        lines.append(
            f"{'PARAM':<12}{'VALUE':>25}{'UNCERTAINTY':>15}"
        )
        for n in self.cm.free_names:
            p = self.model.params[n]
            unc = p.uncertainty if p.uncertainty is not None else float("nan")
            lines.append(f"{n:<12}{p._format_value():>25}{unc:>15.3e}")
        out = "\n".join(lines)
        print(out)
        return out
