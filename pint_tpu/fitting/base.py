"""Shared fitter machinery.

Reference parity: src/pint/fitter.py::Fitter (the common state held by
WLS/GLS/downhill variants: compiled model, residuals, covariance,
offset-column handling, post-fit commit, summary printing) — plus the
TPU-first single-dispatch scan harness (make_scan_fit_loop) that runs a
whole Gauss-Newton iteration as ONE device program.
"""

from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import DegeneracyWarning
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.obs.trace import TRACER
from pint_tpu.residuals import Residuals
from pint_tpu.runtime.guard import ensure_scan_finite
from pint_tpu.toas.toas import TOAs


def record_fit(fit_fn):
    """Decorator for every fitter's ``fit_toas``: runs the body under
    the fit-level flight-recorder span (Fitter._fit_obs_span) so one
    fit produces a complete fit > rung > compile/dispatch > fence span
    tree, plus the always-on fit counter and per-fit log-dedup
    reset."""
    import functools

    @functools.wraps(fit_fn)
    def wrapped(self, *args, **kwargs):
        with self._fit_obs_span():
            return fit_fn(self, *args, **kwargs)

    return wrapped


def noffset(cm) -> int:
    """1 when the implicit offset column is in use; 0 when a free PHOFF
    replaces it (both together are exactly degenerate)."""
    return 0 if "PHOFF" in cm.free_names else 1


def design_with_offset(cm, x):
    """Design matrix with the implicit offset column prepended (when
    applicable) — shared by fitters, gridutils, and the MCMC seeder."""
    M = cm.design_matrix(x)
    if not noffset(cm):
        return M
    ones = jnp.ones((cm.bundle.ntoa, 1))
    return jnp.concatenate([ones, M], axis=1)


def device_noise_floor(lams, c):
    """Traced twin of ``DownhillFitter._chi2_noise_floor``: the
    measured per-trial chi2 scatter at the current state, computed
    IN-PROGRAM so the fused downhill trajectory re-measures the
    backend's chi2 evaluation noise every iteration without a host
    round-trip.

    ``lams`` is the static probe-lambda vector (probes + the lambda=0
    baseline); ``c`` the matching chi2 trials.  Degree-1 least squares
    in closed form, with non-finite trials masked out of EVERY sum (a
    poisoned probe must not poison the floor), and fewer than 4 finite
    points yielding 0.0 — exactly the host staticmethod's semantics
    (np.polyfit solves the same normal equations; the two agree to
    rounding, which is far below the 6-sigma inflation the floor
    carries)."""
    m = jnp.isfinite(c)
    w = m.astype(lams.dtype)
    cs = jnp.where(m, c, 0.0)
    n = jnp.sum(w)
    n_safe = jnp.maximum(n, 1.0)
    xm = jnp.sum(w * lams) / n_safe
    ym = jnp.sum(cs) / n_safe
    dxl = lams - xm
    sxx = jnp.sum(w * dxl * dxl)
    sxy = jnp.sum(w * dxl * (cs - ym))
    slope = sxy / jnp.where(sxx > 0, sxx, 1.0)
    resid = w * ((cs - ym) - slope * dxl)
    # operands are O(chi2-scatter) deviations from the fitted line —
    # provably O(1), no |max|-prescale needed
    ss = jnp.sum(resid * resid)  # lint: ok(f64-emu)
    dof = jnp.maximum(n - 2.0, 1.0)
    return jnp.where(n >= 4.0, 6.0 * jnp.sqrt(ss / dof), 0.0)


def make_scan_fit_loop(live_step, p, maxiter, tol_chi2, init_chi2,
                       cm=None):
    """The whole Gauss-Newton iteration as ONE device program
    (lax.scan), so a fit costs a single dispatch instead of `maxiter`
    host round-trips (~85 ms each through the axon tunnel).  Semantics
    match the reference host loops (src/pint/fitter.py::*Fitter
    .fit_toas): apply the step, stop when chi2 stops moving, freeze on
    non-finite chi2 (the host raises ConvergenceFailure from the
    reported flags afterwards — Fitter._finish_scan_fit).

    live_step(x) -> (x_new, cov, chi2, nbad int32) where cov is the
    NORMALIZED covariance pytree (covn (p,p), norm (p,)) — kept in
    O(1) device units because raw variances of stiff columns underflow
    f32-range emulated f64 (gls.py::_finish_normal_eqs); chi2 may be
    evaluated pre-step (GLS: the whitened chi2 of the solve) or
    post-step (WLS: cm.chi2 at x_new) — convergence compares
    successive values either way.  init_chi2(x0) seeds the comparison
    (inf when the first step must always run).
    """
    cov_init = (jnp.zeros((p, p)), jnp.ones((p,)))

    def dead_step(x):
        return (
            x,
            cov_init,
            jnp.asarray(jnp.inf),
            jnp.asarray(0, jnp.int32),
        )

    def body(carry, _):
        x, chi2_prev, cov_prev, done, conv = carry
        x_new, cov, chi2, nbad = jax.lax.cond(
            done, dead_step, live_step, x
        )
        bad = ~jnp.isfinite(chi2)
        x_keep = jnp.where(done | bad, x, x_new)
        converged = jnp.abs(chi2_prev - chi2) < tol_chi2 * jnp.maximum(
            chi2, 1.0
        )
        chi2_keep = jnp.where(done | bad, chi2_prev, chi2)
        cov_keep = jax.tree_util.tree_map(
            lambda a, b: jnp.where(done | bad, a, b), cov_prev, cov
        )
        new_done = done | bad | converged
        new_conv = conv | (converged & ~done)
        return (
            (x_keep, chi2_keep, cov_keep, new_done, new_conv),
            (nbad, bad & ~done),
        )

    def fit_loop(x0):
        init = (
            x0,
            init_chi2(x0),
            cov_init,
            jnp.asarray(False),
            jnp.asarray(False),
        )
        (x, chi2, cov, _done, conv), (nbads, bads) = jax.lax.scan(
            body, init, None, length=maxiter
        )
        return x, chi2, cov, conv, nbads, bads

    # with a CompiledModel in hand, the TOA bundle rides as a runtime
    # argument (cm.jit) so the lowered module is O(1) in ntoa — a plain
    # jit would bake ~240 HLO bytes/TOA of bundle literals
    # the cm=None branch serves harness-level unit tests only (no
    # CompiledModel, no device data to meter)
    if cm is not None:
        return cm.jit(fit_loop)
    return jax.jit(fit_loop)  # lint: obs-ok (test-only, no cm)


class Fitter:
    """Common base: compiled kernels + offset column + post-fit commit."""

    def __init__(self, toas: TOAs, model: TimingModel):
        self.toas = toas
        self.model = model
        self.cm = model.compile(toas)
        self.resids_init = self._make_resids()  # wideband overrides
        self.resids = self.resids_init
        self.converged = False
        self.parameter_covariance_matrix: np.ndarray | None = None
        self.chi2: float | None = None
        # compiled scan fit loops, keyed per-fitter (mode/maxiter/tol);
        # here so _finish_scan_fit is self-contained for any subclass
        self._fit_loops: dict = {}
        # which fallback-ladder rung served the last fit
        # (runtime/fallback.py::GuardReport; None before any fit)
        self.guard_report = None

    def _fit_obs_span(self):
        """Open the fit-level flight-recorder span (every fit_toas
        wraps its body in this — the 'fit' root the dispatch/compile/
        fence spans nest under), bump the fit counter, and reset the
        log-dedup filter so each fit's warnings print once per FIT,
        not once per process."""
        from pint_tpu import logging as plog
        from pint_tpu.obs import metrics as obs_metrics

        plog.reset_dedup()
        obs_metrics.counter("fit.count", help="fit_toas calls").inc()
        return TRACER.span(
            f"fit:{type(self).__name__}", "fit",
            free_params=len(self.cm.free_names),
            ntoa=self.cm.bundle.ntoa,
        )

    def flight_report(self) -> str:
        """Human post-mortem of the recorded flight (sibling of
        ``guard_report``): top spans, recompiles, bytes to device,
        rung history.  Metrics are always on; span detail appears when
        the recorder is enabled (obs.trace.enable() /
        $PINT_TPU_TRACE=1).  See docs/observability.md."""
        from pint_tpu.obs.export import flight_report

        return flight_report(guard_report=self.guard_report)

    @property
    def _noffset(self):
        return noffset(self.cm)

    def _design_with_offset(self, x):
        return design_with_offset(self.cm, x)

    def _make_resids(self):
        """Residuals object for the current compiled state; wideband
        fitters override to return WidebandResiduals."""
        return Residuals(self.toas, self.model, compiled=self.cm)

    @staticmethod
    def _unnorm_cov(cov):
        """(covn, norm) -> covn/outer(norm, norm) in HOST IEEE f64
        (device f64 on axon keeps only the f32 exponent range, where
        variances of stiff columns like F1 underflow to zero); plain
        arrays pass through."""
        if isinstance(cov, tuple):
            covn, norm = (np.asarray(c) for c in cov)
            return covn / np.outer(norm, norm)
        return np.asarray(cov)

    def _finish_scan_fit(self, result, warn_msg: str, fail_msg: str):
        """Shared host tail of a make_scan_fit_loop run: emit one
        DegeneracyWarning per degenerate iteration, raise on non-finite
        chi2, record convergence, commit.  The compiled loops SURVIVE
        the commit (r5): cm.commit() rebases only the numeric
        references, which ride every cm.jit call as runtime arguments
        — a refit costs one dispatch, not a ~30 s recompile
        (profiling/profile_fit_wall.py)."""
        # explicit device fence: the scan result is an async pytree —
        # without this, host code below could time/commit values that
        # do not exist yet (the fence is a recorded span when tracing)
        result = TRACER.fence(result, name="fit-result")
        x, chi2, cov, conv, nbads, bads = result
        nbads = np.asarray(nbads)
        for nb in nbads[nbads > 0]:
            warnings.warn(f"{int(nb)} {warn_msg}", DegeneracyWarning)
        # the SHARED non-finite refusal (runtime/guard.py): a NaN fit
        # raises a diagnosed PintTpuNumericsError (a ConvergenceFailure
        # subclass) instead of committing garbage.  When the fit came
        # through the fallback ladder this has already passed once per
        # rung; here it is the safety net for direct callers.
        ensure_scan_finite(result, fail_msg,
                           site=f"fit:{type(self).__name__}")
        self.converged = bool(conv)
        chi2 = self._finalize(x, cov, float(chi2))
        return chi2

    def _finalize(self, x, cov, chi2: float):
        """Drop the offset row/col, commit fitted deltas + uncertainties
        back into host Parameters, refresh residuals."""
        no = self._noffset
        cov = self._unnorm_cov(cov)[no:, no:]
        sigmas = np.sqrt(np.diag(cov))
        self.parameter_covariance_matrix = cov
        self.cm.commit(np.asarray(x), uncertainties=sigmas)
        self.resids = self._make_resids()
        self.model.top_params["CHI2"].value = float(chi2)
        self.chi2 = float(chi2)
        return float(chi2)

    def get_derived_params(self) -> str:
        """Derived quantities from the (fitted) model — spin period,
        characteristic age, surface B field, spin-down luminosity, and
        binary mass function when applicable (reference:
        src/pint/fitter.py::Fitter.get_derived_params)."""
        from pint_tpu import derived_quantities as dq

        m = self.model
        lines = []

        def _val(name):
            p = m.params.get(name)
            if p is None or p.value is None:
                return None
            v = p.value
            return float(v.to_float()) if hasattr(v, "to_float") else float(v)

        f0, f1 = _val("F0"), _val("F1")
        if f0:
            p0, p1 = dq.p_to_f(f0, f1 or 0.0)  # involution: f->p too
            lines.append(f"P0 = {p0:.15g} s")
            if f1:
                lines.append(f"P1 = {p1:.6g}")
                lines.append(
                    f"tau_c = {dq.pulsar_age(f0, f1):.4g} yr"
                )
                lines.append(f"B_surf = {dq.pulsar_B(f0, f1):.4g} G")
                lines.append(
                    f"Edot = {dq.pulsar_edot(f0, f1):.4g} erg/s"
                )
        pb, a1 = _val("PB"), _val("A1")
        if pb is None and _val("FB0"):
            pb = 1.0 / _val("FB0") / 86400.0
        if pb and a1:
            mf = dq.mass_funct(pb * 86400.0, a1)
            lines.append(f"mass function = {mf:.6g} Msun")
            lines.append(
                "companion mass (i=60deg, mp=1.4) = "
                f"{dq.companion_mass(pb * 86400.0, a1):.4g} Msun"
            )
        # returns the string and leaves printing to the caller, like
        # the reference Fitter.get_derived_params; print_summary
        # appends it to its (printed) report
        return "\n".join(lines)

    def print_summary(self) -> str:
        chi2 = self.chi2 if self.chi2 is not None else self.resids.chi2
        lines = [
            f"Fitted model using {type(self).__name__} with "
            f"{len(self.cm.free_names)} free parameters, "
            f"{len(self.toas)} TOAs; converged={self.converged}",
            f"chi2 = {chi2:.4f}",
        ]
        dof = getattr(self.resids, "dof", None)
        if dof is not None:
            lines.append(
                f"dof = {dof}  reduced chi2 = {chi2 / dof:.4f}"
            )
        if hasattr(self.resids, "rms_weighted"):
            lines.append(
                f"weighted RMS = {self.resids.rms_weighted() * 1e6:.4f} us"
            )
        lines.append(
            f"{'PARAM':<12}{'VALUE':>25}{'UNCERTAINTY':>15}"
        )
        for n in self.cm.free_names:
            p = self.model.params[n]
            unc = p.uncertainty if p.uncertainty is not None else float("nan")
            lines.append(f"{n:<12}{p._format_value():>25}{unc:>15.3e}")
        derived = self.get_derived_params()
        if derived:
            lines.append("Derived Parameters:")
            lines.append(derived)
        out = "\n".join(lines)
        print(out)
        return out
