"""Wideband fitters: joint TOA + DM-measurement fitting.

Reference parity: src/pint/fitter.py::WidebandTOAFitter /
WidebandDownhillFitter with the labeled-matrix stacking of
src/pint/pint_matrix.py — wideband TOAs carry per-TOA DM measurements
(-pp_dm / -pp_dme flags); the fit minimizes the joint chi2 of

    r = [ time residuals (n,) ; DM residuals (n,) ]

with block covariance C = blockdiag(C_toa, D_dm): C_toa the usual
N + T phi T^T (white rescaling + correlated bases), D_dm the diagonal
DMEFAC/DMEQUAD-scaled DM variances.  DM-affecting parameters (DM, DMX_*,
DMJUMP*) get design-matrix rows in both blocks automatically — the
combined residual vector is one pure function of x and the design matrix
is its jacfwd, so the cross-block bookkeeping the reference does with
labeled-axis matrix combiners reduces to an array concatenation here.

TPU-first: WidebandTOAFitter subclasses GLSFitter, so the whole
Gauss-Newton iteration runs as ONE device program (lax.scan) and the
general-basis mixed-precision MXU path applies to the stacked system on
accelerators (the Pallas pure-Fourier path does not — its streamed basis
rows are TOA-indexed, while the stacked system has 2n rows).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import PintTpuError
from pint_tpu.fitting.downhill import DownhillFitter
from pint_tpu.fitting.gls import (
    GLSFitter,
    default_accel_mode,
    gls_step_full_cov,
    gls_step_woodbury,
    gls_step_woodbury_mixed,
    make_cinv_mult,
)
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.residuals import Residuals
from pint_tpu.toas.toas import TOAs


class WidebandResiduals:
    """Paired TOA + DM residuals (reference:
    residuals.py::WidebandTOAResiduals with .toa and .dm members)."""

    def __init__(self, toas: TOAs, model: TimingModel, compiled=None):
        self.toas = toas
        self.model = model
        self.cm = compiled or model.compile(toas)
        self.toa = Residuals(toas, model, compiled=self.cm)
        self._x = self.cm.x0()

    @property
    def dm_resids(self) -> np.ndarray:
        """DM residuals (measured - model), pc/cm^3."""
        return np.asarray(self.cm.dm_residuals(self._x))

    @property
    def dm_chi2(self) -> float:
        r = self.cm.dm_residuals(self._x)
        s = self.cm.scaled_dm_sigma(self._x)
        return float(jnp.sum(jnp.square(r / s)))

    @property
    def chi2(self) -> float:
        return self.toa.chi2 + self.dm_chi2


def _validate_wideband(toas: TOAs) -> None:
    if not toas.is_wideband():
        raise PintTpuError(
            "wideband fitter requires -pp_dm flags on every TOA"
        )
    _, dme = toas.get_dm_measurements()
    bad = ~np.isfinite(dme) | (dme <= 0)
    if bad.any():
        raise PintTpuError(
            f"{int(bad.sum())} TOAs have missing/invalid -pp_dme DM "
            "uncertainties (first at index "
            f"{int(np.flatnonzero(bad)[0])})"
        )


class _WidebandKernels:
    """Shared wideband kernel builders (combined residuals / design /
    noise over the stacked [TOA; DM] rows).  Mixin over a Fitter
    subclass providing self.cm / self._noffset."""

    def _make_resids(self):
        return WidebandResiduals(self.toas, self.model, compiled=self.cm)

    def _combined_residuals(self, x):
        return jnp.concatenate(
            [
                self.cm.time_residuals(x, subtract_mean=False),
                self.cm.dm_residuals(x),
            ]
        )

    def _combined_design(self, x):
        """(2n, p[+1]) jacfwd design matrix; offset column is 1 on TOA
        rows, 0 on DM rows (a phase offset does not move DM)."""
        M = jax.jacfwd(self._combined_residuals)(x)
        if not self._noffset:
            return M
        n = self.cm.bundle.ntoa
        ones = jnp.concatenate([jnp.ones(n), jnp.zeros(n)])[:, None]
        return jnp.concatenate([ones, M], axis=1)

    def _combined_ndiag(self, x):
        """(2n,) stacked diagonal variances [white TOA; DM]."""
        return jnp.concatenate(
            [
                jnp.square(self.cm.scaled_sigma(x)),
                jnp.square(self.cm.scaled_dm_sigma(x)),
            ]
        )

    def _combined_basis(self, x):
        """(2n, k) basis + (k,) weights: correlated bases act on the TOA
        block only; the DM block is diagonal."""
        n = self.cm.bundle.ntoa
        Tt, phi = self.cm.noise_basis_or_empty(x)
        T = jnp.concatenate([Tt, jnp.zeros((n, Tt.shape[1]))], axis=0)
        return T, phi

    def _combined_noise(self, x):
        """(Ndiag (2n,), T (2n,k), phi (k,))."""
        T, phi = self._combined_basis(x)
        return self._combined_ndiag(x), T, phi


class WidebandTOAFitter(_WidebandKernels, GLSFitter):
    """Iterated joint GLS over [TOA; DM] residual blocks, run as one
    lax.scan device program with GLSFitter's mode selection ('auto'
    picks the mixed-precision MXU path on accelerators)."""

    def __init__(self, toas: TOAs, model: TimingModel,
                 full_cov: bool = False, fused="auto"):
        _validate_wideband(toas)
        if fused is True:
            # fail fast with the real reason: the Pallas fourier kernel
            # streams TOA-indexed basis rows, but the wideband system
            # has stacked [TOA; DM] rows — regardless of noise content
            raise PintTpuError(
                "the Pallas pure-Fourier path (fused=True) does not "
                "apply to wideband's stacked [TOA; DM] system; use "
                "fused='mixed' to force the mixed-precision MXU path"
            )
        super().__init__(toas, model, full_cov=full_cov, fused=fused)

    def _fourier_available(self) -> bool:
        return False

    def _step_inputs(self, x):
        return (
            self._combined_residuals(x),
            self._combined_design(x),
            self._combined_ndiag(x),
        )

    def _step_noise(self, x):
        return self._combined_basis(x)


class WidebandDownhillFitter(_WidebandKernels, DownhillFitter):
    """Step-halving wideband fitter (reference: WidebandDownhillFitter)."""

    def __init__(self, toas: TOAs, model: TimingModel,
                 full_cov: bool = False):
        _validate_wideband(toas)
        super().__init__(toas, model)
        self.full_cov = full_cov

    def _make_proposal(self, force_f64: bool = False):
        noffset, full_cov = self._noffset, self.full_cov
        # accelerator mixed proposals, as in DownhillGLSFitter (the
        # chi2 ladder still gates acceptance); force_f64 is the
        # guard's fallback rung (all-f64 Woodbury over the stacked
        # [TOA; DM] system).  RAW body (downhill.py contract): the
        # fused trajectory traces it inside its scan; host-loop
        # callers wrap it in cm.jit at the use site.
        if force_f64:
            fn = gls_step_woodbury
        elif full_cov:
            fn = gls_step_full_cov
        elif default_accel_mode(self.cm) == "mixed":
            fn = gls_step_woodbury_mixed
        else:
            fn = gls_step_woodbury

        def proposal(x):
            r = self._combined_residuals(x)
            M = self._combined_design(x)
            Ndiag, T, phi = self._combined_noise(x)
            dx, cov, _, nbad = fn(r, M, Ndiag, T, phi,
                                  normalized_cov=True)
            # predicted quadratic decrease (downhill.py convention)
            Cir = make_cinv_mult(Ndiag, T, phi)(r[:, None])[:, 0]
            pred = -jnp.dot(dx, M.T @ Cir)
            return dx[noffset:], cov, nbad, pred

        return proposal

    def _make_chi2(self):
        n = self.cm.bundle.ntoa

        def chi2(x):
            r = self._combined_residuals(x)
            Ndiag, T, phi = self._combined_noise(x)
            cinv_mult = make_cinv_mult(Ndiag, T, phi)
            Cir = cinv_mult(r[:, None])[:, 0]
            c2 = jnp.dot(r, Cir)
            if self._noffset:
                u = jnp.concatenate([jnp.ones(n), jnp.zeros(n)])
                Ciu = cinv_mult(u[:, None])[:, 0]
                c2 = c2 - jnp.dot(u, Cir) ** 2 / jnp.dot(u, Ciu)
            return c2

        return chi2
