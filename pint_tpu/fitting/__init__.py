"""Fitters: WLS / GLS / downhill / wideband over compiled kernels.

Reference parity: src/pint/fitter.py class hierarchy (SURVEY.md §3.3).
"""

from pint_tpu.fitting.gls import GLSFitter  # noqa: F401
from pint_tpu.fitting.wls import WLSFitter  # noqa: F401


def auto_fitter(toas, model, **kw):
    """Pick a fitter by model content (reference: Fitter.auto)."""
    if any(
        c.introduces_correlated_errors for c in model.noise_components
    ):
        return GLSFitter(toas, model, **kw)
    return WLSFitter(toas, model, **kw)
