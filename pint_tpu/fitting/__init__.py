"""Fitters: WLS / GLS / downhill / wideband over compiled kernels.

Reference parity: src/pint/fitter.py class hierarchy (SURVEY.md §3.3).
"""

from pint_tpu.fitting.downhill import (  # noqa: F401
    DownhillFitter,
    DownhillGLSFitter,
    DownhillWLSFitter,
)
from pint_tpu.fitting.gls import GLSFitter  # noqa: F401
from pint_tpu.fitting.utils import ftest  # noqa: F401
from pint_tpu.fitting.wideband import (  # noqa: F401
    WidebandDownhillFitter,
    WidebandResiduals,
    WidebandTOAFitter,
)
from pint_tpu.fitting.wls import WLSFitter  # noqa: F401


def auto_fitter(toas, model, downhill: bool = True, **kw):
    """Pick a fitter by model content (reference: Fitter.auto):
    wideband data -> Wideband fitter; correlated noise -> GLS; else WLS;
    downhill variants by default."""
    if toas.is_wideband():
        cls = WidebandDownhillFitter if downhill else WidebandTOAFitter
        return cls(toas, model, **kw)
    correlated = any(
        c.introduces_correlated_errors for c in model.noise_components
    )
    if correlated:
        cls = DownhillGLSFitter if downhill else GLSFitter
        return cls(toas, model, **kw)
    cls = DownhillWLSFitter if downhill else WLSFitter
    return cls(toas, model, **kw)
