"""Bayesian timing: log-likelihood / log-prior / prior-transform.

Reference parity: src/pint/bayesian.py::BayesianTiming — likelihood
over the compiled residual kernels, per-parameter priors, prior
transform for nested samplers.  TPU-first: lnpost is one jitted pure
function of the delta vector x, so it vmaps across walkers — the
ensemble sampler in pint_tpu.sampler runs every walker in parallel on
device (the reference hands single-point callables to emcee).

Correlated noise (PL red / ECORR / ...) is marginalized analytically
with the same Woodbury identity the GLS fitter factorizes through
(fitting/gls.py): lnL = -1/2 [r^T C^-1 r + ln det C + n ln 2pi] with
C = N + T phi T^T evaluated via a k x k Cholesky — never an n x n
array, so the per-walker cost is O(n k) and the whole ensemble still
vmaps.  Because phi/N come from the pdict, noise HYPER-parameters
(TNREDAMP/TNREDGAM, EFAC/EQUAD) marked free in the par file are
sampled too — the enterprise-class marginalized likelihood.

The priors act on x (delta from the par-file reference values, internal
units), matching the fitters' parameterization.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.models.priors import (
    NormalRV,
    Prior,
    UniformBoundedRV,
    default_prior,
)


def lnlikelihood_cm(cm, x):
    """Gaussian likelihood of the timing residuals for one compiled
    model (jit/vmap-safe; the BayesianTiming.lnlikelihood interior,
    factored out so the background-job kernels — serve/jobs/kernels.py
    — evaluate the IDENTICAL expression over a serve-session cm with
    the bundle swapped in as a runtime argument).

    White noise: diagonal.  Correlated noise: Woodbury-marginalized —
    rCr = r N^-1 r - z^T z with z the k-vector whitened through the
    Cholesky of Sigma = phi^-1 + T^T N^-1 T, and ln det C = ln det N +
    ln det phi + ln det Sigma (matrix determinant lemma).  Sigma comes
    from the fitters' shared assembly (fitting/gls.py::woodbury_sigma)
    so sampler and fitter can never disagree on the marginalization.
    """
    from pint_tpu.fitting.gls import woodbury_sigma

    r = cm.time_residuals(x)
    sig = cm.scaled_sigma(x)
    n = r.shape[-1]
    if not cm.has_correlated_errors:
        return (
            -0.5 * jnp.sum(jnp.square(r / sig))
            - jnp.sum(jnp.log(sig))
            - 0.5 * n * jnp.log(2.0 * jnp.pi)
        )
    T, phi = cm.noise_basis_or_empty(x)
    Ninv, _TN, Sigma = woodbury_sigma(jnp.square(sig), T, phi)
    Ninv_r = r * Ninv
    L = jnp.linalg.cholesky(Sigma)
    z = jax.scipy.linalg.solve_triangular(
        L, T.T @ Ninv_r, lower=True
    )
    rCr = jnp.dot(r, Ninv_r) - jnp.dot(z, z)
    logdet_C = (
        2.0 * jnp.sum(jnp.log(sig))
        + jnp.sum(jnp.log(phi))
        + 2.0 * jnp.sum(jnp.log(jnp.diagonal(L)))
    )
    return -0.5 * (rCr + logdet_C + n * jnp.log(2.0 * jnp.pi))


def make_lnprior(priors: dict, param_names):
    """-> lnprior(x): sum of per-parameter log-priors over the x-space
    deltas, jax-traceable for the analytic prior types (uniform bounds
    / normal; improper uniform contributes 0).  Shared by
    BayesianTiming and the job kernels — the prior constants bake into
    the traced program, which is why job kernel identity includes a
    par/prior tag (serve/jobs/kernels.py)."""
    names = list(param_names)

    def lnprior(x):
        out = 0.0
        for i, n in enumerate(names):
            p = priors[n]
            xi = x[..., i]
            if isinstance(p, NormalRV):
                z = (xi - p.mean) / p.sigma
                out = out - 0.5 * z * z - jnp.log(
                    p.sigma * jnp.sqrt(2.0 * jnp.pi)
                )
            elif isinstance(p, UniformBoundedRV):
                out = out + jnp.where(
                    (xi >= p.lower) & (xi <= p.upper), p._logw, -jnp.inf
                )
            # improper uniform contributes 0
        return out

    return lnprior


def default_priors_for(model, param_names) -> dict:
    """name -> default_prior(param) for every free name; the shared
    default the engine's job admission uses so kernel prior tags match
    between BayesianTiming and serve/jobs."""
    return {n: default_prior(model.params[n]) for n in param_names}


class BayesianTiming:
    def __init__(self, model, toas, priors: Optional[dict] = None):
        """priors: param-name -> Prior over the x-space delta; defaults
        per models.priors.default_prior."""
        self.model = model
        self.toas = toas
        self.cm = model.compile(toas)
        self.param_names = list(self.cm.free_names)
        self.nparams = len(self.param_names)
        self.priors: dict[str, Prior] = {}
        for n in self.param_names:
            if priors and n in priors:
                self.priors[n] = priors[n]
            else:
                self.priors[n] = default_prior(model.params[n])

    # -- pieces -----------------------------------------------------------
    def lnlikelihood(self, x):
        """Gaussian likelihood of the timing residuals (jit/vmap-safe);
        delegates to the module-level lnlikelihood_cm — one expression
        shared with the background-job kernels."""
        return lnlikelihood_cm(self.cm, x)

    def lnprior(self, x):
        """Sum of per-parameter log-priors; jax-traceable for the
        analytic prior types (uniform bounds / normal)."""
        return make_lnprior(self.priors, self.param_names)(x)

    def lnposterior(self, x):
        return self.lnprior(x) + self.lnlikelihood(x)

    def prior_transform(self, cube):
        """Unit hypercube -> x (for nested samplers); host-side numpy."""
        cube = np.atleast_1d(np.asarray(cube, dtype=np.float64))
        return np.array([
            self.priors[n].ppf(cube[i])
            for i, n in enumerate(self.param_names)
        ])

    def lnposterior_jit(self):
        # bundle rides as a runtime argument (CompiledModel.jit): the
        # lowered module stays O(1) in ntoa for event-scale datasets
        return self.cm.jit(self.lnposterior)

    def sample_nested(self, nlive: int = 200, dlogz: float = 0.1,
                      seed: int = 0, **kw):
        """Nested sampling of the timing posterior: prior_transform +
        the jitted vmapped lnlikelihood through the native sampler
        (pint_tpu.nested; the reference feeds exactly these two
        callables to nestle.sample).  Every prior must be proper
        (improper uniforms have no prior transform)."""
        from pint_tpu.nested import nested_sample

        ll = self.cm.jit(jax.vmap(self.lnlikelihood))

        def loglike_batch(X):
            return np.asarray(ll(jnp.asarray(X)))

        return nested_sample(
            loglike_batch, self.prior_transform, self.nparams,
            nlive=nlive, dlogz=dlogz, seed=seed, **kw,
        )
