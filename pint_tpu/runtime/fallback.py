"""Degradation ladder: TPU-mixed -> TPU-f64 -> CPU.

When the guard trips on a dispatch (watchdog timeout, transport
rejection, retries exhausted, or a diagnosed non-finite result), the
ladder re-dispatches the SAME step on the next rung and records which
rung finally served the result:

1. the production accelerator mode (mixed-precision f32 MXU Grams —
   fitting/gls.py::default_accel_mode),
2. the all-f64 XLA path on the same backend (slower — emulated f64 —
   but avoids every f32-Gram/eigh hazard and most transport weight),
3. a CPU re-dispatch pinned via the guard's ladder-device context
   (IEEE f64: the rung of last resort; on accelerator backends this
   recompiles the same program for host CPU — uncommitted operands
   follow the pin, explicitly device-committed bundles keep their
   placement).

On a CPU backend the ladder degenerates to [cpu-<mode>, cpu]: the
final rung is a clean re-dispatch of the same IEEE-f64 program on an
explicitly pinned device — still worth one rung (a transient fault or
an injected one clears), and it is what lets the CPU test suite
exercise the full fall-through deterministically
(tests/test_runtime_guard.py).

No rung ever returns a silently-wrong result: every rung's output goes
through the shared finite validator before it is accepted, and an
exhausted ladder raises :class:`LadderExhausted` carrying the full
(rung, error) history.
"""

from __future__ import annotations

import dataclasses
import warnings

import jax

from pint_tpu.exceptions import (
    GuardTimeout,
    GuardTripWarning,
    LadderExhausted,
    PintTpuNumericsError,
    RetriesExhausted,
    TransportRejection,
)
from pint_tpu.obs import metrics as obs_metrics
from pint_tpu.obs.trace import TRACER
from pint_tpu.runtime import guard

#: guard trips that drop a rung; anything else (shape errors, user
#: bugs) propagates immediately — degrading can't fix a wrong program.
TRIP_ERRORS = (
    GuardTimeout,
    TransportRejection,
    RetriesExhausted,
    PintTpuNumericsError,
)


@dataclasses.dataclass(frozen=True)
class GuardReport:
    """Which rung served a laddered computation, and what tripped on
    the way down.  ``history`` is ((rung_name, 'ExcType: msg'), ...)
    for the rungs that failed before ``rung`` succeeded."""

    site: str
    rung: str
    rung_index: int
    history: tuple = ()

    @property
    def fell_back(self) -> bool:
        return self.rung_index > 0

    def to_dict(self) -> dict:
        return {
            "site": self.site,
            "rung": self.rung,
            "rung_index": self.rung_index,
            "history": [list(h) for h in self.history],
        }


def run_ladder(rungs, site: str, validate=None):
    """Try ``rungs`` = [(name, thunk), ...] in order.

    ``thunk(rung_site)`` performs the dispatch (its inner cm.jit /
    jax.jit wrapper carries the watchdog+retry guard — the ladder adds
    no second supervision layer); ``validate(result, rung_site)``
    raises PintTpuNumericsError to reject a rung's output.  Returns
    (result, GuardReport).  Raises LadderExhausted when every rung
    trips."""
    history = []
    for i, (name, thunk) in enumerate(rungs):
        rung_site = f"{site}/rung:{name}"
        try:
            # each rung is a span: a trace of a degraded run shows the
            # failed rungs' wall time alongside the serving rung's
            with TRACER.span(
                f"rung:{name}", "rung", site=site, rung_index=i
            ):
                out = thunk(rung_site)
                if validate is not None:
                    validate(out, rung_site)
            obs_metrics.gauge(
                "fallback.rung",
                help="rung index that served the last ladder",
            ).set(i)
            return out, GuardReport(
                site=site, rung=name, rung_index=i,
                history=tuple(history),
            )
        except TRIP_ERRORS as e:
            history.append((name, f"{type(e).__name__}: {e}"))
            guard.STATS.bump("fallbacks")
            TRACER.event(
                "fallback", "guard", site=site, rung=name,
                error=f"{type(e).__name__}: {e}",
                next_rung=(
                    rungs[i + 1][0] if i + 1 < len(rungs) else None
                ),
            )
            if i + 1 < len(rungs):
                warnings.warn(
                    f"guard tripped on rung {name!r} at {site} "
                    f"({type(e).__name__}); falling back to rung "
                    f"{rungs[i + 1][0]!r}",
                    GuardTripWarning,
                )
    raise LadderExhausted(site, history)


def fit_rungs(mode: str, backend: str | None = None,
              f64_rung: bool = True):
    """The rung sequence [(name, rung_mode, pin_cpu), ...] for a fit of
    the given native mode.  ``f64_rung=False`` skips the intermediate
    all-f64 rung (WLS: its one solve method IS already the f64 path)."""
    backend = backend or jax.default_backend()
    seq = [(f"{backend}-{mode}", mode, False)]
    if f64_rung and mode != "f64":
        seq.append((f"{backend}-f64", "f64", False))
    seq.append(("cpu", "f64" if f64_rung else mode, True))
    return seq


def run_fit_ladder(cm, mode: str, make_loop, site: str, fail_msg: str,
                   f64_rung: bool = True):
    """Run a compiled scan fit loop down the degradation ladder.

    ``make_loop(rung_mode)`` returns the compiled loop for a rung's
    mode (fitters cache these per (mode, maxiter, tol)); the CPU rung
    reuses the f64 loop under the guard's ladder-device pin, which
    recompiles it for host CPU (jax's default_device is part of the
    jit key).  Validation is the shared scan-result check — the same
    refusal production fit_toas applies — so a rung that froze on
    non-finite chi2, or whose final state is NaN, drops through."""

    def build(rmode, pin):
        def thunk(rung_site):
            loop = make_loop(rmode)
            if pin:
                with guard.ladder_device(jax.devices("cpu")[0]):
                    return loop(cm.x0())
            return loop(cm.x0())

        return thunk

    rungs = [
        (name, build(rmode, pin))
        for name, rmode, pin in fit_rungs(mode, f64_rung=f64_rung)
    ]
    return run_ladder(
        rungs, site,
        validate=lambda res, s: guard.ensure_scan_finite(
            res, fail_msg, s
        ),
    )
