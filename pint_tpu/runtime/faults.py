"""Deterministic fault injection for the device-execution guard.

None of the axon failure modes the guard defends against — wedged
remote compiles, HTTP 413 transport rejections, transient tunnel
errors, emulated-f64 NaN outputs — occur naturally on the CPU test
mesh, so the watchdog/retry/fallback ladder would otherwise ship
untested.  This module simulates them deterministically at the guard's
own hook points (tests/test_runtime_guard.py exercises the whole
ladder on CPU with it).

Activation
----------
- Env var ``PINT_TPU_FAULTS`` (read per guarded call, so test runners
  can set it per-process), or
- the :func:`inject` context manager (the test API — scoped, and
  leftover un-fired counts are discarded on exit).

Spec grammar (documented in docs/robustness.md)::

    spec    := entry ("," entry)*
    entry   := kind [":" count] ["@" site_substring]
    kind    := "hang" | "413" | "transient" | "nan"

Each entry arms ``count`` firings (default 1; ``inf`` = unlimited) of
one fault kind, optionally restricted to guard sites whose name
contains ``site_substring``.  Examples::

    PINT_TPU_FAULTS="hang:1"            # first compile/dispatch wedges
    PINT_TPU_FAULTS="transient:2@cm.jit"  # two tunnel errors, then clean
    PINT_TPU_FAULTS="nan:inf@rung:tpu-mixed"  # the mixed rung always NaNs

Fault semantics (each maps to one real axon failure mode):

- ``hang``      — sleep ``hang_seconds`` inside the guarded attempt
                  (simulated wedged remote compile; the watchdog must
                  trip, CLAUDE.md's >40 min n=32768 case).
- ``413``       — raise :class:`TransportRejection` before the dispatch
                  (simulated oversized compile request; deterministic,
                  so the guard must NOT retry — it falls back instead).
- ``transient`` — raise :class:`TransientDispatchError` before the
                  dispatch (simulated connection reset; retried with
                  backoff on the same rung).
- ``nan``       — poison the values passing through the shared finite
                  validator with NaN (simulated emulated-f64 NaN step).
                  Corruption only ever produces NaN — loud by
                  construction — never a silently-wrong finite value.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from pint_tpu.exceptions import (
    PintTpuError,
    TransientDispatchError,
    TransportRejection,
)

KINDS = ("hang", "413", "transient", "nan")

_DEFAULT_HANG_SECONDS = 30.0

_lock = threading.Lock()


@dataclass
class _Entry:
    kind: str
    remaining: float  # inf = unlimited
    site: str | None = None  # substring filter on the guard site name

    def matches(self, kind: str, site: str) -> bool:
        return (
            self.kind == kind
            and self.remaining > 0
            and (self.site is None or self.site in site)
        )


@dataclass
class FaultPlan:
    """A parsed fault spec: an ordered list of armed fault entries."""

    entries: list = field(default_factory=list)
    hang_seconds: float = _DEFAULT_HANG_SECONDS
    fired: list = field(default_factory=list)  # (kind, site) log

    @classmethod
    def parse(cls, spec: str, hang_seconds: float | None = None):
        if hang_seconds is None:
            hang_seconds = float(
                os.environ.get(
                    "PINT_TPU_FAULT_HANG_SECONDS", _DEFAULT_HANG_SECONDS
                )
            )
        entries = []
        for raw in spec.split(","):
            raw = raw.strip()
            if not raw:
                continue
            body, _, site = raw.partition("@")
            kind, _, count = body.partition(":")
            kind = kind.strip()
            if kind not in KINDS:
                raise PintTpuError(
                    f"unknown fault kind {kind!r} in PINT_TPU_FAULTS "
                    f"spec {spec!r} (known: {', '.join(KINDS)})"
                )
            n = 1.0 if not count else (
                float("inf") if count.strip() == "inf"
                else float(int(count))
            )
            entries.append(_Entry(kind, n, site.strip() or None))
        return cls(entries=entries, hang_seconds=hang_seconds)

    def take(self, kind: str, site: str) -> bool:
        """Consume one firing of ``kind`` at ``site`` if armed."""
        for e in self.entries:
            if e.matches(kind, site):
                e.remaining -= 1
                self.fired.append((kind, site))
                return True
        return False

    def remaining(self, kind: str | None = None) -> float:
        return sum(
            e.remaining for e in self.entries
            if kind is None or e.kind == kind
        )


# context-manager plans (test API); the env plan is cached separately
_plans: list[FaultPlan] = []
_env_cache: tuple[str, FaultPlan | None] = ("", None)


def _env_plan() -> FaultPlan | None:
    """The plan armed by $PINT_TPU_FAULTS, re-parsed when the env var
    changes (so monkeypatched specs take effect mid-process)."""
    global _env_cache
    spec = os.environ.get("PINT_TPU_FAULTS", "")
    if spec != _env_cache[0]:
        _env_cache = (spec, FaultPlan.parse(spec) if spec else None)
    return _env_cache[1]


def _all_plans():
    env = _env_plan()
    return (_plans + [env]) if env is not None else list(_plans)


def active() -> bool:
    """True when any fault is still armed (guards use this to decide
    whether the fault hooks need consulting at all)."""
    return any(p.remaining() > 0 for p in _all_plans())


def _take(kind: str, site: str) -> FaultPlan | None:
    """Consume one firing of ``kind``; innermost context plan wins."""
    with _lock:
        for plan in reversed(_all_plans()):
            if plan.take(kind, site):
                return plan
    return None


@contextlib.contextmanager
def inject(spec: str, hang_seconds: float | None = None):
    """Arm a fault plan for the duration of the with-block (test API).

    >>> with faults.inject("nan:1"):
    ...     fitter.fit_toas()   # first rung NaNs, ladder recovers
    """
    plan = FaultPlan.parse(spec, hang_seconds=hang_seconds)
    _plans.append(plan)
    try:
        yield plan
    finally:
        _plans.remove(plan)


# -- hook points (called by runtime/guard.py) ----------------------------
def maybe_hang(site: str) -> None:
    """Simulated wedged compile: block inside the guarded attempt for
    ``hang_seconds`` (long past any test watchdog), then continue."""
    plan = _take("hang", site)
    if plan is not None:
        time.sleep(plan.hang_seconds)


def maybe_raise(site: str) -> None:
    """Simulated transport failures, raised before the dispatch runs."""
    if _take("413", site) is not None:
        raise TransportRejection(
            f"injected fault at {site}: HTTP 413 request entity too "
            "large (simulated oversized compile payload)"
        )
    if _take("transient", site) is not None:
        raise TransientDispatchError(
            f"injected fault at {site}: connection reset by peer "
            "(simulated transient tunnel error)"
        )


def corrupt(mats: dict, site: str) -> dict:
    """Simulated emulated-f64 NaN step: replace the validator's view of
    the results with NaN (the originals are untouched — the validator
    refuses the poisoned copy loudly, never returning it)."""
    if _take("nan", site) is None:
        return mats
    return {
        name: np.full(np.shape(a), np.nan, dtype=np.float64)
        for name, a in mats.items()
    }
