"""Persistent XLA compilation cache: warm process starts skip the
first-fit compile.

The cold-path profile (profiling/profile_fit_wall.py) shows
``first_fit_compile_s`` of ~32-43 s through the remote-compile tunnel —
paid again by EVERY process start even though the lowered module is
byte-identical run to run (the cm.jit argument-fed split makes it O(1)
in the data, so the cache key is stable across datasets of one shape).
jax ships a persistent on-disk executable cache; this module turns it
on for the framework with safe defaults and an escape hatch.

Env contract (documented in docs/performance.md):
  * ``PINT_TPU_COMPILE_CACHE=0``       — disable entirely.
  * ``PINT_TPU_COMPILE_CACHE_DIR``     — cache directory (default
    ``~/.cache/pint_tpu/xla-cache``).
  * ``PINT_TPU_COMPILE_CACHE_MIN_S``   — minimum compile seconds for an
    executable to be persisted (default 0.2; the axon tunnel makes
    every real kernel cost far more, while trivial test kernels stay
    out of the cache).

Enabling is best-effort: a read-only filesystem, an unknown jax flag,
or a PJRT backend that cannot serialize executables must never break a
fit — failures downgrade to a one-time warning and the in-memory-only
behavior jax always had.  Cache-dir writes are keyed by jax/jaxlib
version and backend internally (jax's own cache-key machinery), so one
directory serves CPU test meshes and the TPU tunnel side by side.
"""

from __future__ import annotations

import os
import warnings
from pathlib import Path
from typing import Optional

_state = {"enabled": False, "dir": None, "tried": False}


def cache_dir() -> Optional[str]:
    """The active persistent-cache directory, or None when disabled."""
    return _state["dir"] if _state["enabled"] else None


def enable(directory: Optional[str] = None) -> Optional[str]:
    """Turn on jax's persistent compilation cache (idempotent).

    Returns the cache directory in use, or None when disabled by env /
    unsupported.  Called once at ``import pint_tpu`` — early, so every
    backend client created afterwards sees the config."""
    if _state["tried"] and directory is None:
        return cache_dir()
    _state["tried"] = True
    if os.environ.get("PINT_TPU_COMPILE_CACHE", "1") == "0":
        return None
    d = (
        directory
        or os.environ.get("PINT_TPU_COMPILE_CACHE_DIR")
        or os.path.join(
            os.path.expanduser("~"), ".cache", "pint_tpu", "xla-cache"
        )
    )
    try:
        Path(d).mkdir(parents=True, exist_ok=True)
        probe = Path(d) / ".writable"
        probe.touch()
        probe.unlink()
    except OSError as e:
        warnings.warn(
            f"persistent compile cache disabled: {d!r} not writable "
            f"({e})"
        )
        return None
    import jax

    min_s = float(os.environ.get("PINT_TPU_COMPILE_CACHE_MIN_S", "0.2"))
    try:
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_s
        )
        # cache every size: the axon tunnel round-trip dwarfs any
        # deserialization cost, and small modules are the common case
        # below the bake threshold
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # unknown flag on this jax: stay in-memory
        warnings.warn(f"persistent compile cache unavailable: {e}")
        return None
    # jax pins its cache singleton to the directory of the FIRST
    # cached compile; after a config update the singleton must reset
    # or a mid-process redirect (tests) keeps writing to the old
    # directory.  Private API, so strictly best-effort; a no-op when
    # nothing has compiled yet (the import-time call).
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass
    _state["enabled"] = True
    _state["dir"] = d
    return d


def entry_count() -> int:
    """Number of persisted executables in the active cache directory
    (0 when disabled) — the observability hook bench/tests use to
    assert writes and hits without reaching into jax internals."""
    d = cache_dir()
    if d is None:
        return 0
    try:
        return sum(
            1 for p in os.scandir(d)
            if p.is_file() and not p.name.startswith(".")
        )
    except OSError:
        return 0
