"""Device-execution guard for the axon dispatch path.

Every documented failure mode of the remote TPU tunnel — wedged
compiles, HTTP 413 transport rejections, transient dispatch errors,
emulated-f64 NaN/flush hazards — is detected, retried, and degraded
here instead of by per-call-site workarounds:

- :mod:`pint_tpu.runtime.guard` — the ``guarded_call`` supervisor
  (thread-based watchdog, bounded retries with backoff+jitter) and the
  SHARED finite-state validator with a structured emulated-f64 hazard
  diagnosis; ``CompiledModel.jit`` wraps every dispatch in it.
- :mod:`pint_tpu.runtime.fallback` — the TPU-mixed -> TPU-f64 -> CPU
  degradation ladder; fitters run their compiled scan loops through it
  and record which rung served the result (``fitter.guard_report``).
- :mod:`pint_tpu.runtime.faults` — deterministic fault injection
  (``$PINT_TPU_FAULTS`` / ``faults.inject``) so the whole ladder is
  testable on the CPU mesh where none of these faults occur naturally.

Design notes and the failure taxonomy live in docs/robustness.md.

Observability (PR 2): every guard action is recorded by the dispatch
flight recorder — spans for dispatches/attempts/rungs, events for
retries/timeouts/fallbacks, and the counters now live in the obs
metrics registry (``pint_tpu.obs.metrics.snapshot()`` is the canonical
read; ``STATS`` is a compatibility adapter over it).  See
docs/observability.md.
"""

from pint_tpu.runtime import faults  # noqa: F401
from pint_tpu.runtime.fallback import (  # noqa: F401
    GuardReport,
    fit_rungs,
    run_fit_ladder,
    run_ladder,
)
from pint_tpu.runtime.guard import (  # noqa: F401
    STATS,
    GuardConfig,
    NumericsDiagnosis,
    configured,
    diagnose_nonfinite,
    disabled,
    dispatch_guard,
    ensure_scan_finite,
    guarded_call,
    validate_finite,
)
