"""Device-execution guard: watchdog, bounded retries, finite-state
validation with a structured emulated-f64 hazard diagnosis.

Every jitted dispatch in the framework funnels through
``CompiledModel.jit`` (models/timing_model.py), which wraps its
host-callable in :func:`dispatch_guard`.  The guard supervises each
compile/dispatch with:

- a **thread-based watchdog** — the axon remote-compile tunnel can
  wedge silently (>40 min with ~zero CPU on the n=32768 dense step,
  r5), so the attempt runs in a worker thread and is abandoned when the
  timeout passes.  The first attempt per (wrapper, ladder device) uses
  the compile timeout; warm dispatches use the (shorter) dispatch
  timeout.
- **bounded retries with exponential backoff + jitter** for transient
  transport errors (connection resets, 5xx).  Deterministic transport
  rejections (HTTP 413 payload-too-large) are never retried with the
  same payload — they propagate so the fallback ladder
  (runtime/fallback.py) can re-lower instead.
- **post-step finite validation** (:func:`validate_finite`) — the
  shared non-finite refusal that profiling/run_benchmarks.py::_timeit
  pioneered in r4, promoted here so production ``fit_toas`` gets it
  too: a NaN/Inf result raises a diagnosed
  :class:`PintTpuNumericsError` mapping the symptom onto the known
  emulated-f64 hazard taxonomy (docs/precision.md, docs/robustness.md)
  instead of returning garbage.

Fault injection (runtime/faults.py) hooks into the attempt and the
validator so the whole ladder is exercised deterministically on the
CPU test mesh, where none of these faults occur naturally.

Defaults keep the guard essentially free on CPU backends (no watchdog
thread; the inline path costs ~1 us per dispatch) and arm the watchdog
on accelerators; ``$PINT_TPU_GUARD=off`` disables everything, and
``$PINT_TPU_GUARD_{COMPILE_TIMEOUT,DISPATCH_TIMEOUT,RETRIES}``
override individual knobs.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import os
import random
import threading
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import (
    GuardTimeout,
    PintTpuError,
    PintTpuNumericsError,
    RetriesExhausted,
    TransientDispatchError,
    TransportRejection,
)
from pint_tpu.obs import metrics as obs_metrics
from pint_tpu.obs.trace import TRACER
from pint_tpu.runtime import faults

_UNSET = object()


# -- configuration -------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class GuardConfig:
    """Watchdog/retry policy for guarded compiles and dispatches.

    ``None`` timeouts disable the watchdog (the attempt runs inline on
    the calling thread — the CPU default, where the tunnel failure
    modes don't exist and a per-dispatch thread would be pure
    overhead)."""

    compile_timeout: float | None = None  # first call per device
    dispatch_timeout: float | None = None  # warm calls
    max_retries: int = 2  # RE-tries of transient failures/timeouts
    backoff_base: float = 0.25  # seconds; doubles per retry
    backoff_max: float = 8.0
    jitter: float = 0.5  # uniform [0, jitter) fraction added

    @classmethod
    def from_env(cls) -> "GuardConfig":
        """Backend-dependent defaults + $PINT_TPU_GUARD_* overrides.

        Accelerator defaults: compile watchdog 2400 s (the observed
        axon wedge class sat past 40 min; a legit n=32768 kernel
        compile is ~42 s), dispatch watchdog 900 s."""
        env = os.environ.get

        def _t(name, default):
            v = env(name)
            if v is None:
                return default
            v = float(v)
            return None if v <= 0 else v

        accel = jax.default_backend() != "cpu"
        return cls(
            compile_timeout=_t(
                "PINT_TPU_GUARD_COMPILE_TIMEOUT", 2400.0 if accel else None
            ),
            dispatch_timeout=_t(
                "PINT_TPU_GUARD_DISPATCH_TIMEOUT", 900.0 if accel else None
            ),
            max_retries=int(env("PINT_TPU_GUARD_RETRIES", "2")),
        )


_config_cache: GuardConfig | None = None
_override: GuardConfig | None = None
_disabled_depth = 0
_ladder_dev = None  # device pin set by the fallback ladder's CPU rung


def current_config() -> GuardConfig:
    global _config_cache
    if _override is not None:
        return _override
    if _config_cache is None:
        _config_cache = GuardConfig.from_env()
    return _config_cache


@contextlib.contextmanager
def configured(**kw):
    """Override guard-config fields for the with-block (test/bench API):
    ``with guard.configured(dispatch_timeout=0.1, max_retries=0): ...``"""
    global _override
    prev = _override
    _override = dataclasses.replace(prev or current_config(), **kw)
    try:
        yield _override
    finally:
        _override = prev


@contextlib.contextmanager
def disabled():
    """Bypass the guard entirely (used by bench.py's overhead probe)."""
    global _disabled_depth
    _disabled_depth += 1
    try:
        yield
    finally:
        _disabled_depth -= 1


@contextlib.contextmanager
def ladder_device(device):
    """Pin guarded dispatches to ``device`` for the with-block.

    jax's ``default_device`` context is THREAD-LOCAL (and part of the
    jit cache key), so the fallback ladder cannot simply wrap a rung in
    ``jax.default_device(...)`` — the watchdog runs the dispatch in a
    worker thread that would not see it.  The guard instead re-enters
    the context inside whichever thread executes the attempt."""
    global _ladder_dev
    prev = _ladder_dev
    _ladder_dev = device
    try:
        yield
    finally:
        _ladder_dev = prev


def _device_ctx():
    return (
        jax.default_device(_ladder_dev)
        if _ladder_dev is not None
        else contextlib.nullcontext()
    )


# -- stats (bench.py's guard block reads these) --------------------------
class GuardStats:
    """DEPRECATED adapter: the guard counters now live in the obs
    metrics registry (pint_tpu/obs/metrics.py — PR 2's flight
    recorder), where ``obs.metrics.snapshot()`` is the canonical
    telemetry read.  This shim keeps every existing consumer working
    (bench.py's guard block, tests/test_runtime_guard.py, the attr
    reads like ``STATS.retries``) by delegating to the SAME registry
    counters, so the two surfaces can never disagree."""

    #: legacy attribute -> canonical metric name
    _MAP = {
        "dispatches": "dispatch.count",
        "guarded": "dispatch.guarded",
        "retries": "guard.retries",
        "timeouts": "guard.timeouts",
        "transport_rejections": "guard.transport_rejections",
        "numerics_errors": "guard.numerics_errors",
        "fallbacks": "guard.fallbacks",
    }
    _MARGIN_S = "guard.watchdog_margin_s"
    _MARGIN_FRAC = "guard.watchdog_margin_frac_min"

    def __init__(self):
        # pre-resolve the counters off the hot path (bump() runs per
        # dispatch inside the <2% guard budget)
        self._counters = {
            attr: obs_metrics.counter(name)
            for attr, name in self._MAP.items()
        }
        self._margin_s = obs_metrics.gauge(self._MARGIN_S, unit="s")
        self._margin_frac = obs_metrics.gauge(self._MARGIN_FRAC)

    def reset(self):
        for c in self._counters.values():
            c.reset()
        self._margin_s.reset()
        self._margin_frac.reset()

    def bump(self, name, n=1):
        self._counters[name].inc(n)

    def note_margin(self, margin_s, timeout_s):
        self._margin_s.set(float(margin_s))
        self._margin_frac.set_min(float(margin_s) / float(timeout_s))

    def __getattr__(self, name):
        # legacy counter/gauge attribute reads (STATS.retries, ...)
        counters = object.__getattribute__(self, "_counters")
        if name in counters:
            return counters[name].value
        if name == "last_watchdog_margin_s":
            return object.__getattribute__(self, "_margin_s").value
        if name == "min_watchdog_margin_frac":
            return object.__getattribute__(self, "_margin_frac").value
        raise AttributeError(name)

    def snapshot(self) -> dict:
        """DEPRECATED: prefer pint_tpu.obs.metrics.snapshot() (the
        superset).  Kept byte-compatible for existing consumers."""
        out = {attr: c.value for attr, c in self._counters.items()}
        out["watchdog_margin_s"] = self._margin_s.value
        out["watchdog_margin_frac"] = self._margin_frac.value
        return out


STATS = GuardStats()


# -- error classification ------------------------------------------------
_TRANSIENT_MARKERS = (
    "connection reset", "connection refused", "connection aborted",
    "broken pipe", "temporarily unavailable", "deadline exceeded",
    "unavailable", "timed out", "timeout", "transient",
    "502", "503", "504",
)
_REJECTION_MARKERS = (
    "413", "payload too large", "request entity too large",
    "message length", "exceeds maximum",
)


def classify_error(e: BaseException) -> str:
    """'rejection' (deterministic transport refusal — fall back, never
    retry), 'transient' (retry with backoff), or 'fatal' (propagate).
    Real tunnel errors arrive as foreign exception types, so beyond our
    own types this is marker-based on the message text."""
    if isinstance(e, TransportRejection):
        return "rejection"
    if isinstance(e, TransientDispatchError):
        return "transient"
    if isinstance(e, PintTpuError):
        return "fatal"  # our own semantics, not transport weather
    text = f"{type(e).__name__} {e}".lower()
    if any(m in text for m in _REJECTION_MARKERS):
        return "rejection"
    if isinstance(e, (ConnectionError, TimeoutError)) or any(
        m in text for m in _TRANSIENT_MARKERS
    ):
        return "transient"
    return "fatal"


# -- buffer donation (ISSUE 12) ------------------------------------------
def donation_enabled() -> bool:
    """The ``PINT_TPU_DONATE`` hatch, read at wrapper BUILD time:
    ``cm.jit(fn, donate=True)`` and serve's ``traced_jit`` donate
    their large per-dispatch operands (XLA aliases input buffers into
    outputs and frees the non-aliasable ones at dispatch) only while
    this is on.  ``=0`` restores copy-in semantics everywhere."""
    return os.environ.get("PINT_TPU_DONATE", "1") != "0"


_donation_warning_quieted = [False]


def quiet_unusable_donation() -> None:
    """Narrowly silence jax's once-per-lowering "Some donated buffers
    were not usable" UserWarning: a donated operand with no
    same-shaped output cannot alias, but donation still frees it at
    dispatch — exactly the peak-memory win we want for the stacked
    bundle operands — so the warning is expected, not actionable.
    Installed only when a donating wrapper is actually built."""
    if not _donation_warning_quieted[0]:
        warnings.filterwarnings(
            "ignore", message="Some donated buffers were not usable"
        )
        _donation_warning_quieted[0] = True


def _copy_donated_leaf(leaf):
    # jnp.copy follows the operand's committed placement/sharding
    # (computation-follows-data), so replica- and gang-committed
    # operands snapshot onto their own device(s), never the default
    if isinstance(leaf, jax.Array):
        return jnp.copy(leaf)
    return leaf


def snapshot_donated(args, donate):
    """Replay snapshot of the donated argument positions: device-side
    copies of every ``jax.Array`` leaf, taken BEFORE the dispatch —
    a failed attempt may already have consumed the donated buffers
    (jax invalidates them at call time regardless of how the attempt
    ends), so a retry must substitute these copies.  Host-numpy leaves
    pass through untouched: jit stages host operands through a fresh
    device buffer, so donation can never invalidate them.  ``donate``
    is ``True`` (every position) or an iterable of positions — the
    ``_donate_argnums`` marker a donating wrapper carries."""
    if donate is True:
        posns = range(len(args))
    else:
        posns = [int(i) for i in donate if 0 <= int(i) < len(args)]
    out = list(args)
    for i in posns:
        out[i] = jax.tree_util.tree_map(_copy_donated_leaf, out[i])
    return tuple(out)


def fence_owned(out):
    """Materialize a DONATING dispatch's outputs as host-OWNED numpy.

    On CPU, ``np.asarray`` of a jax Array is a zero-copy view of the
    XLA buffer — safe while nothing recycles it, which donation
    breaks: an output buffer aliased onto a donated input returns to
    the allocator the moment its jax Array drops, and a long-lived
    response view silently goes garbage when LATER dispatches reuse
    the memory (caught by the serve parity gate).  So every fence
    downstream of a donating kernel must own its bytes: one host
    memcpy on CPU, no change on accelerators (their fence is a real
    device-to-host transfer either way).  Passes through ``np.asarray``
    views when donation is off — today's semantics."""
    if donation_enabled():
        return jax.tree_util.tree_map(
            lambda leaf: np.array(leaf, copy=True), out
        )
    return jax.tree_util.tree_map(np.asarray, out)


# -- the supervisor ------------------------------------------------------
def _attempt(fn, args, site, timeout, obs_span=None):
    """One supervised attempt: fault hooks + optional watchdog thread.

    With a timeout, the attempt runs in a daemon worker (join with
    timeout; a wedged attempt is abandoned, not killed — Python cannot
    interrupt a thread blocked in a C extension).  The ladder-device
    pin is re-entered inside the executing thread (see ladder_device).
    ``obs_span`` is the caller's attempt span: spans opened inside the
    worker thread re-parent beneath it (TRACER.under), and the
    watchdog margin is attached to it on success.
    """
    if not timeout:
        with _device_ctx():
            faults.maybe_hang(site)
            faults.maybe_raise(site)
            return fn(*args)

    cell = {}

    def work():
        try:
            with TRACER.under(obs_span), _device_ctx():
                faults.maybe_hang(site)
                faults.maybe_raise(site)
                cell["ok"] = fn(*args)
        except BaseException as e:  # re-raised on the caller thread
            cell["err"] = e

    t = threading.Thread(
        target=work, daemon=True, name=f"pint-tpu-guard {site}"
    )
    t0 = time.monotonic()
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise GuardTimeout(site=site, timeout=timeout)
    margin = timeout - (time.monotonic() - t0)
    STATS.note_margin(margin, timeout)
    if obs_span is not None:
        obs_span.set(watchdog_margin_s=round(margin, 4))
    if "err" in cell:
        raise cell["err"]
    return cell["ok"]


def guarded_call(fn, args=(), site="", config=None, timeout=_UNSET,
                 is_compile=False, donate_argnums=None):
    """Run ``fn(*args)`` under the guard: watchdog + bounded retries.

    Raises GuardTimeout (watchdog exhausted), TransportRejection
    (deterministic — immediately), RetriesExhausted (transient failures
    past max_retries), or the original error (fatal class).  The
    fallback ladder catches exactly these to drop a rung.

    ``donate_argnums`` (True = every position, or a position tuple —
    the wrapper's ``_donate_argnums`` marker) declares that ``fn``
    DONATES those operands: jax invalidates the donated device buffers
    at call time whether or not the attempt succeeds, so a retry with
    the original ``args`` would read freed buffers.  Before any
    attempt that could be retried the guard snapshots the donated
    positions (:func:`snapshot_donated`) and substitutes the snapshot
    on the retry path — re-snapshotting each round so every retry is
    itself replayable.  The snapshot is skipped when no retry can
    plausibly happen (no watchdog armed AND no faults injected — the
    CPU steady state), keeping donation free where transient transport
    failures don't exist."""
    cfg = config or current_config()
    if timeout is _UNSET:
        timeout = cfg.compile_timeout if is_compile else cfg.dispatch_timeout
    attempts = max(0, int(cfg.max_retries)) + 1
    delay = cfg.backoff_base
    for attempt in range(1, attempts + 1):
        snap = None
        if (donate_argnums and attempt < attempts
                and (timeout is not None or faults.active())):
            # taken BEFORE the dispatch: a transient failure arrives
            # AFTER the donated buffers are already gone
            snap = snapshot_donated(args, donate_argnums)
            obs_metrics.counter("guard.donation_snapshots").inc()
        # span per attempt (recorder off: shared no-op handle), so the
        # trace shows each retry's wall time and watchdog margin
        h = TRACER.span(
            "attempt", "attempt", site=site, n=attempt,
            timeout_s=timeout, is_compile=bool(is_compile),
        )
        try:
            with h:
                return _attempt(fn, args, site, timeout, obs_span=h)
        except GuardTimeout:
            STATS.bump("timeouts")
            TRACER.event(
                "watchdog-timeout", "guard", site=site,
                timeout_s=timeout, attempt=attempt,
            )
            if attempt == attempts:
                raise
        except Exception as e:
            kind = classify_error(e)
            if kind == "rejection":
                STATS.bump("transport_rejections")
                TRACER.event(
                    "transport-rejection", "guard", site=site,
                    error=f"{type(e).__name__}: {e}",
                )
                if isinstance(e, TransportRejection):
                    raise
                raise TransportRejection(str(e)) from e
            if kind != "transient":
                raise
            if attempt == attempts:
                raise RetriesExhausted(site, attempt, e) from e
        if snap is not None:
            # replay against the pre-dispatch copies, never the
            # (possibly freed) donated originals
            args = snap
        STATS.bump("retries")
        TRACER.event("retry", "guard", site=site, attempt=attempt)
        time.sleep(
            min(delay, cfg.backoff_max)
            * (1.0 + cfg.jitter * random.random())
        )
        delay *= 2.0


def _host_side(args) -> bool:
    """False inside a jax trace (vmap/jit/grad) — the guard must never
    interpose there: threads break thread-local trace state, and inner
    cm.jit calls under an outer trace simply inline."""
    try:
        if not jax.core.trace_state_clean():
            return False
    except Exception:
        pass
    return not any(
        isinstance(leaf, jax.core.Tracer)
        for leaf in jax.tree_util.tree_leaves(args)
    )


def dispatch_guard(fn, site: str):
    """Wrap a host-callable jitted function so every call runs under
    guarded_call.  The compile-vs-dispatch timeout choice tracks the
    first call per (wrapper, ladder device) — a rung falling to the CPU
    device pays a fresh compile and gets the compile watchdog again.
    Preserves the ``.lower`` AOT hook (profiling/bench), and honors
    the wrapper's ``_donate_argnums`` marker (ISSUE 12): a donating
    wrapper's retries replay guard-side snapshots instead of the freed
    donated buffers (see guarded_call)."""
    compiled_for: set = set()
    donate = getattr(fn, "_donate_argnums", None)

    @functools.wraps(fn)
    def guarded(*args):
        if not _host_side(args):
            return fn(*args)  # inlining under an outer trace
        STATS.bump("dispatches")
        devkey = None if _ladder_dev is None else str(_ladder_dev)
        first = devkey not in compiled_for
        # flight-recorder span: 'compile' on the wrapper's first call
        # per ladder device (trace + XLA compile + run), 'dispatch' on
        # warm calls — the distinct-category contract tests/bench and
        # docs/observability.md rely on.  Off path: one attr check.
        h = TRACER.span(
            site, "compile" if first else "dispatch", site=site
        )
        with h:
            if (_disabled_depth > 0
                    or os.environ.get("PINT_TPU_GUARD") == "off"):
                h.set(guarded=False)
                with _device_ctx():  # the ladder pin still applies
                    return fn(*args)
            STATS.bump("guarded")
            out = guarded_call(
                fn, args, site=site, is_compile=first,
                donate_argnums=donate,
            )
            compiled_for.add(devkey)
            return out

    if hasattr(fn, "lower"):
        guarded.lower = fn.lower
    return guarded


# -- the shared finite-state validator (satellite: promoted from
# profiling/run_benchmarks.py::_timeit's r4 refusal) ---------------------
@dataclasses.dataclass(frozen=True)
class NumericsDiagnosis:
    """Structured mapping of a non-finite symptom onto the emulated-f64
    hazard taxonomy (docs/precision.md; full table in
    docs/robustness.md)."""

    hazard: str  # taxonomy key
    evidence: str  # what the values showed
    hint: str  # which known fix applies
    backend: str

    @property
    def summary(self) -> str:
        return (
            f"hazard={self.hazard} [{self.evidence}] — {self.hint} "
            f"(backend={self.backend}; taxonomy: docs/robustness.md)"
        )


#: f32 exponent-range limits that axon's f32-pair emulated f64 inherits
#: (docs/precision.md): squaring past ~1.8e19 overflows, products below
#: ~1.2e-38 flush to zero, 1/x of x < ~1e-38 overflows.
F32_RANGE_MAX = 3.4e38
F32_SQUARE_CEILING = 1.8e19
F32_FLUSH_FLOOR = 1.2e-38


def diagnose_nonfinite(mats: dict) -> NumericsDiagnosis:
    """Best-effort hazard classification from the materialized values.

    Heuristic by construction — the NaN has already destroyed most of
    the evidence — but each branch names the one known failure class
    whose signature matches, so the operator starts at the right
    gotcha instead of bisecting device code."""
    backend = jax.default_backend()
    finite_abs = []
    n_inf = n_nan = 0
    bad_all_scalar = True
    for a in mats.values():
        a = np.asarray(a, dtype=np.float64)
        n_inf += int(np.sum(np.isinf(a)))
        n_nan += int(np.sum(np.isnan(a)))
        if not np.all(np.isfinite(a)) and a.ndim > 0:
            bad_all_scalar = False
        f = np.abs(a[np.isfinite(a)])
        if f.size:
            finite_abs.append(f)
    fmax = max((float(f.max()) for f in finite_abs), default=0.0)
    nonzero_min = min(
        (float(f[f > 0].min()) for f in finite_abs if np.any(f > 0)),
        default=np.inf,
    )
    cpu_note = (
        "NOTE: this backend is CPU (IEEE f64) — the emulated-f64 "
        "hazards below do not apply there; suspect a genuine "
        "model/data problem (zero TOA errors, singular system) or an "
        "injected fault.  "
        if backend == "cpu" else ""
    )
    if n_inf or fmax > F32_SQUARE_CEILING:
        return NumericsDiagnosis(
            "exponent-range-overflow",
            f"{n_inf} inf, max finite |value| {fmax:.3g} "
            f"(f32-range square ceiling ~{F32_SQUARE_CEILING:.1e})",
            cpu_note + "emulated f64 keeps the f32 EXPONENT range: "
            "|max|-prescale before sums of squares "
            "(fitting/gls.py::_column_norms) and keep weighted design "
            f"columns |M*sqrt(w)| under ~{F32_RANGE_MAX:.1e} "
            "(docs/precision.md weighted-design ceiling)",
            backend,
        )
    if nonzero_min < 1e-30:
        return NumericsDiagnosis(
            "subnormal-flush",
            f"smallest nonzero finite |value| {nonzero_min:.3g} "
            f"(flush floor ~{F32_FLUSH_FLOOR:.1e})",
            cpu_note + "products of tiny factors flush to ZERO below "
            "~1.2e-38 and 1/x of x<~1e-38 overflows: form such "
            "products in log space (models/noise.py::powerlaw_phi) "
            "and keep degenerate weights >= 1e-30 "
            "(noise_basis_or_empty)",
            backend,
        )
    if bad_all_scalar and n_nan:
        return NumericsDiagnosis(
            "scalar-transcendental-path",
            f"{n_nan} NaN confined to 0-d values",
            cpu_note + "0-d transcendentals take axon's f32-accurate "
            "scalar path (usually ~2e-8 error, not NaN, but domain "
            "edges differ): route scalar parameters through "
            "ops/scalarmath.py (sin_p/cos_p/...; "
            "pintlint rule scalarmath catches this statically)",
            backend,
        )
    return NumericsDiagnosis(
        "unknown",
        f"{n_nan} NaN / {n_inf} inf with unremarkable finite values",
        cpu_note + "no known emulated-f64 signature matches; check "
        "the model inputs (zero/negative uncertainties, empty mask "
        "selections) and docs/robustness.md",
        backend,
    )


def validate_finite(values: dict, site: str = "",
                    what: str = "device step") -> dict:
    """The SHARED non-finite refusal: materialize ``values`` (a dict of
    name -> array-like; None entries skipped), refuse NaN/Inf with a
    diagnosed PintTpuNumericsError.  Every consumer — production
    fit_toas (fitting/base.py::Fitter._finish_scan_fit), the fallback
    ladder, bench.py, profiling/run_benchmarks.py::_timeit — calls this
    one function, so a NaN can never be timed, committed, or published
    from any of them.  Fault injection poisons a COPY here (nan kind);
    the poisoned copy is refused, never returned."""
    # materialization IS the device fence here (np.asarray blocks on
    # the value) — recorded as a validate-category span so the wait
    # shows up in the flight trace
    with TRACER.span("validate", "validate", site=site, what=what):
        mats = {
            name: np.asarray(v)
            for name, v in values.items()
            if v is not None
        }
        mats = faults.corrupt(mats, site)
        bad = [n for n, a in mats.items() if not np.all(np.isfinite(a))]
        if bad:
            diag = diagnose_nonfinite(mats)
            STATS.bump("numerics_errors")
            TRACER.event(
                "numerics-error", "guard", site=site,
                hazard=diag.hazard, what=what,
            )
            raise PintTpuNumericsError(
                f"{what} produced non-finite values ({', '.join(bad)}) "
                f"at {site or 'unknown site'}: {diag.summary}",
                diagnosis=diag,
            )
        return mats


def ensure_scan_finite(result, fail_msg: str, site: str = ""):
    """Validate a make_scan_fit_loop result tuple: the scan freezes on
    a non-finite chi2 and reports per-iteration flags, so a flagged
    iteration is refused here with the shared diagnosis, and the final
    state/chi2 get the plain finite check."""
    x, chi2, cov, conv, nbads, bads = result
    bads = np.asarray(bads)
    if bads.any():
        first = int(np.flatnonzero(bads)[0])
        # the scan kept the last-good state, so the poisoned values are
        # gone — diagnose from what survived, flagging the iteration
        diag = diagnose_nonfinite({"x": np.asarray(x)})
        STATS.bump("numerics_errors")
        TRACER.event(
            "numerics-error", "guard", site=site, hazard=diag.hazard,
            what="fit loop (frozen scan)",
        )
        raise PintTpuNumericsError(
            f"{fail_msg} (chi2 went non-finite at iteration {first}; "
            f"the scan froze on the last finite state) at "
            f"{site or 'unknown site'}: {diag.summary}",
            diagnosis=diag,
        )
    validate_finite({"x": x, "chi2": chi2}, site=site, what="fit loop")
    return result
