"""Runtime lock-witness sanitizer for the serving stack (ISSUE 15).

The static ``lockorder`` / ``blocking`` rules (tools/lint) prove what
the program *structure* shows; they cannot see dynamic composition —
``Future`` done-callbacks running inline under the finisher lock,
closures dispatched onto executor threads, the id-sorted multi-
``trace_lock`` protocol in ``Replica._fused_kernel_for`` whose loop
variable no static resolver follows.  This module is the dynamic half:
a drop-in wrapper registry for the serve-stack locks that records
per-thread acquisition stacks and detects, while real traffic (or the
chaos harness) runs:

- **order inversions**: the first time ``A`` is held while ``B`` is
  acquired, the edge ``A -> B`` is recorded with its acquisition
  stack; a later acquisition in the reverse order is a violation
  carrying *both* witness stacks;
- **same-identity nesting**: two instances under one name (the fused
  cross-key dispatch taking several ``Session.trace_lock``s) must be
  acquired in ascending ``id()`` order — the deterministic global
  order that makes the protocol deadlock-free; a descending
  acquisition is a violation;
- **blocking-under-lock**: ``Condition.wait()`` with no timeout while
  other witnessed locks are held.

Cost model (the ``PINT_TPU_TRACE`` pattern — ~free when off):
``wrap()`` returns the *raw* lock unless the witness is installed
(``PINT_TPU_LOCK_WITNESS=1`` at import, or programmatic
:func:`enable` / :func:`armed` before the locks are created), so
production pays literally nothing; installed-but-disabled proxies pay
one module-global flag check per acquire.  Violations land in
:func:`violations`, the ``lockwitness.violations`` obs counter, and a
``TRACER`` event.  ``tools/chaos.py`` arms the witness for every leg
and asserts zero violations (docs/robustness.md).

Semaphores and queues are deliberately NOT witnessed: their ownership
is handed across threads (``Replica._sem`` acquires on the dispatcher
and releases on the fencer), which a per-thread held-stack model would
misread as a leak.  The static ``blocking`` rule covers their
untimed-acquire hazards instead.
"""

from __future__ import annotations

import os
import threading
import traceback
from contextlib import contextmanager

__all__ = [
    "wrap", "enable", "disable", "armed", "enabled", "installed",
    "violations", "violation_count", "reset", "lock_id",
]

_env_on = os.environ.get("PINT_TPU_LOCK_WITNESS", "") not in ("", "0")
_installed = _env_on   # wrap() returns proxies iff True at creation
_enabled = _env_on     # recording on/off (cheap flag on the hot path)

_tls = threading.local()
_graph_lock = threading.Lock()
_edges: dict = {}        # (outer, inner) -> first-witness record
_violations: list = []
_reported: set = set()   # dedupe key per violation class/pair


def lock_id(obj) -> int:
    """Canonical identity of a possibly-witnessed lock: the RAW lock's
    id().  The witness records and compares ``id(self._lock)`` — the
    underlying lock — so any ascending-id acquisition protocol over
    same-identity locks (Replica._fused_kernel_for) MUST sort by this,
    not ``id(obj)``: when wrap() returned proxies, proxy-id order and
    raw-id order disagree nondeterministically and an id(obj) sort
    intermittently acquires in what the witness sees as DESCENDING
    order."""
    if isinstance(obj, WitnessLock):
        return id(obj._lock)
    return id(obj)


def installed() -> bool:
    return _installed


def enabled() -> bool:
    return _enabled


def enable():
    """Install + enable.  Locks created after this point get proxies;
    locks created before (while not installed) stay raw."""
    global _installed, _enabled
    _installed = True
    _enabled = True


def disable():
    global _enabled
    _enabled = False


@contextmanager
def armed():
    """Enable for the duration of the block (the chaos-harness hook:
    engines built inside get witnessed locks)."""
    global _enabled
    prev = _enabled
    enable()
    try:
        yield
    finally:
        _enabled = prev


def violations() -> list:
    with _graph_lock:
        return [dict(v) for v in _violations]


def violation_count() -> int:
    with _graph_lock:
        return len(_violations)


def reset():
    """Clear the order graph and recorded violations (between chaos
    legs / tests).  Per-thread held stacks are left alone — they
    drain naturally as the owning threads release."""
    with _graph_lock:
        _edges.clear()
        _violations.clear()
        _reported.clear()


# -- recording -------------------------------------------------------------
def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _capture(limit: int = 10) -> list:
    frames = traceback.extract_stack()
    # drop the witness's own frames (tail) and cap depth
    frames = [
        f for f in frames[:-2]
        if "lockwitness" not in (f.filename or "")
    ][-limit:]
    return [f"{f.filename}:{f.lineno} in {f.name}" for f in frames]


def _emit(kind: str, name: str, detail: str, stacks: dict):
    key = (kind, detail.split(" — ")[0])
    with _graph_lock:
        if key in _reported:
            return
        _reported.add(key)
        _violations.append({
            "kind": kind,
            "lock": name,
            "thread": threading.current_thread().name,
            "detail": detail,
            "stacks": stacks,
        })
    try:  # the obs layer is optional at this depth — never raise
        from pint_tpu.obs import metrics as obs_metrics
        from pint_tpu.obs.trace import TRACER

        obs_metrics.counter("lockwitness.violations").inc()
        TRACER.event("lockwitness", "runtime", kind=kind, lock=name)
    except Exception:
        pass


def _check_order(name: str, obj) -> None:
    """Edge/violation bookkeeping at an acquisition ATTEMPT (before
    blocking on the real lock, so a would-be deadlock still gets
    recorded)."""
    held = _held()
    if not held:
        return
    me = threading.current_thread().name
    for hname, hid, hstack in held:
        if hname == name:
            if hid == id(obj):
                continue  # re-entrant same instance (RLock/Condition)
            if id(obj) < hid:
                _emit(
                    "same-identity-order", name,
                    f"same-identity-order {name} — nested acquisition "
                    "of a second instance with DESCENDING id(); the "
                    "deadlock-free protocol is ascending-id order "
                    "(Replica._fused_kernel_for)",
                    {"outer": hstack, "inner": _capture()},
                )
            continue
        edge = (hname, name)
        rev = (name, hname)
        with _graph_lock:
            prior = _edges.get(rev)
            if edge not in _edges:
                _edges[edge] = {
                    "thread": me, "stack": _capture(),
                    "under": hstack,
                }
        if prior is not None:
            _emit(
                "inversion", name,
                f"inversion {hname}<->{name} — this thread holds "
                f"{hname} and acquires {name}; thread "
                f"{prior['thread']} previously held {name} while "
                f"acquiring {hname} (both witness stacks attached)",
                {
                    "this": _capture(),
                    "this_under": hstack,
                    "prior": prior["stack"],
                    "prior_under": prior["under"],
                },
            )


def _push(name: str, obj) -> None:
    _held().append((name, id(obj), _capture()))


def _pop(name: str, obj) -> None:
    held = getattr(_tls, "held", None)
    if not held:
        return
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] == name and held[i][1] == id(obj):
            del held[i]
            return


# -- proxies ---------------------------------------------------------------
class WitnessLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper.  Disabled cost:
    one module-global flag check per acquire/release."""

    __slots__ = ("_lock", "_name")

    def __init__(self, lock, name: str):
        self._lock = lock
        self._name = name

    def acquire(self, *a, **k):
        if _enabled:
            _check_order(self._name, self._lock)
        got = self._lock.acquire(*a, **k)
        if got and _enabled:
            _push(self._name, self._lock)
        return got

    def release(self):
        _pop(self._name, self._lock)
        self._lock.release()

    def locked(self):
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<WitnessLock {self._name} of {self._lock!r}>"


class WitnessCondition(WitnessLock):
    """``threading.Condition`` wrapper: same ordering model on the
    underlying (re-entrant) lock, plus the dynamic blocking check —
    an untimed ``wait()`` while OTHER witnessed locks are held is the
    drain-never-hangs hazard at runtime."""

    __slots__ = ()

    def wait(self, timeout=None):
        if _enabled:
            if timeout is None:
                others = [
                    e for e in _held() if e[1] != id(self._lock)
                ]
                if others:
                    _emit(
                        "blocking-under-lock", self._name,
                        f"blocking-under-lock {self._name}.wait() — "
                        "untimed Condition.wait while holding "
                        + ", ".join(
                            dict.fromkeys(e[0] for e in others)
                        ),
                        {
                            "wait": _capture(),
                            "held": [e[2] for e in others],
                        },
                    )
            # wait releases the condition for its duration
            _pop(self._name, self._lock)
            try:
                return self._lock.wait(timeout)
            finally:
                _push(self._name, self._lock)
        return self._lock.wait(timeout)

    def wait_for(self, predicate, timeout=None):
        # composed of timed waits internally; check only the untimed
        # form, mirroring wait()
        if _enabled and timeout is None:
            others = [e for e in _held() if e[1] != id(self._lock)]
            if others:
                _emit(
                    "blocking-under-lock", self._name,
                    f"blocking-under-lock {self._name}.wait_for() — "
                    "untimed Condition.wait_for while holding "
                    + ", ".join(dict.fromkeys(e[0] for e in others)),
                    {
                        "wait": _capture(),
                        "held": [e[2] for e in others],
                    },
                )
        return self._lock.wait_for(predicate, timeout)

    def notify(self, n=1):
        return self._lock.notify(n)

    def notify_all(self):
        return self._lock.notify_all()

    def locked(self):  # Condition has no locked(); mirror its lock
        return self._lock._lock.locked()


def wrap(obj, name: str):
    """Register a serve-stack lock with the witness.  Returns the raw
    object when the witness is not installed (zero production cost);
    a proxy when it is.  Semaphores/queues pass through untouched
    (cross-thread handoff semantics — module docstring)."""
    if not _installed:
        return obj
    if isinstance(obj, threading.Condition):
        return WitnessCondition(obj, name)
    if isinstance(obj, (
        threading.Semaphore, threading.BoundedSemaphore
    )):
        return obj
    return WitnessLock(obj, name)
