"""Photon-event TOA loading from mission FITS files.

Reference parity: src/pint/event_toas.py / fermi_toas.py
(load_event_TOAs, load_Fermi_TOAs, mission lookup tables) — read an
event table's TIME column, convert mission elapsed time (MET) to MJD
via MJDREFI/MJDREFF/TIMEZERO, and build a TOAs object.

Supported event frames:
- barycentered events (TIMESYS='TDB', e.g. barycorr/axBary output):
  site '@' — the full precision path;
- geocentered or spacecraft events in UTC/TT at the geocenter (site
  '0'): spacecraft orbit-file interpolation (the reference's FT2/orbit
  readers) can refine this when an orbit product is supplied
  [verify: orbit-file support lands with satellite_obs].

Event TOAs get zero measurement uncertainty by convention (the
reference uses error=0 for photons) and a -photon flag.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.exceptions import PintTpuError
from pint_tpu.io.fits import get_bintable
from pint_tpu.timebase.times import TimeArray
from pint_tpu.toas.toas import TOAs

# mission defaults (reference: event_toas mission lookup tables)
MISSIONS = {
    "nicer": dict(extname="EVENTS", timecol="TIME"),
    "nustar": dict(extname="EVENTS", timecol="TIME"),
    "xmm": dict(extname="EVENTS", timecol="TIME"),
    "rxte": dict(extname="EVENTS", timecol="TIME"),
    "swift": dict(extname="EVENTS", timecol="TIME"),
    "fermi": dict(extname="EVENTS", timecol="TIME"),
    "generic": dict(extname=None, timecol="TIME"),
}


def _mjdref(hdr) -> float:
    if "MJDREFI" in hdr:
        return float(hdr["MJDREFI"]) + float(hdr.get("MJDREFF", 0.0))
    if "MJDREF" in hdr:
        return float(hdr["MJDREF"])
    raise PintTpuError("event file has no MJDREF/MJDREFI keyword")


def load_event_TOAs(
    path,
    mission: str = "generic",
    energy_range=None,
    errors_us: float = 0.0,
    weightcol: str = None,
    site: str = None,
    energycol: str = None,
) -> TOAs:
    """Event FITS -> TOAs (one per photon).

    weightcol: photon-weight column; weights ride in each TOA's flags
    (key 'weight') so they stay aligned through the time sort and any
    later subsetting.
    energycol: photon-energy column (e.g. Fermi 'ENERGY', MeV); stored
    in the 'energy' flag the same way — consumed by energy-dependent
    templates (templates/lceprimitives.py).
    site: observatory code override — pass the name registered via
    observatory.satellite.register_satellite to place the photons at
    the spacecraft (orbit-table geometry) instead of the defaults
    ('@' for barycentered TIMESYS=TDB files, '0' geocenter otherwise).
    """
    cfg = MISSIONS.get(mission.lower())
    if cfg is None:
        raise PintTpuError(
            f"unknown mission {mission!r}; known {sorted(MISSIONS)}"
        )
    hdu = get_bintable(path, cfg["extname"])
    hdr = hdu.header
    met = np.asarray(hdu.column(cfg["timecol"]), dtype=np.float64)
    weights = (
        np.asarray(hdu.column(weightcol), dtype=np.float64)
        if weightcol else None
    )
    energies = (
        np.asarray(hdu.column(energycol), dtype=np.float64)
        if energycol else None
    )
    if energy_range is not None and "PI" in [
        c.upper() for c in hdu.columns()
    ]:
        pi = np.asarray(hdu.column("PI"), dtype=np.float64)
        lo, hi = energy_range
        keep = (pi >= lo) & (pi <= hi)
        met = met[keep]
        if weights is not None:
            weights = weights[keep]
        if energies is not None:
            energies = energies[keep]
    mjdref = _mjdref(hdr)
    timezero = float(hdr.get("TIMEZERO", 0.0))
    timesys = str(hdr.get("TIMESYS", "TT")).upper()
    # exact split: integer reference day + (fractional day + MET) seconds
    ref_day = int(np.floor(mjdref))
    ref_sec = (mjdref - ref_day) * 86400.0
    sec = ref_sec + met + timezero

    if timesys == "TDB":
        default_site = "@"
        scale = "tdb"
    elif timesys in ("TT", "UTC"):
        default_site = "0"  # geocenter
        scale = timesys.lower()
    else:
        raise PintTpuError(f"unsupported event TIMESYS {timesys!r}")
    if site is not None and timesys == "TDB":
        raise PintTpuError(
            "site override is for unbarycentered events; this file is "
            "TIMESYS=TDB (already at the SSB)"
        )
    site = site if site is not None else default_site
    t = TimeArray(np.full(len(sec), ref_day, dtype=np.int64), 0.0, scale)
    t = t.add_seconds(sec)
    if scale == "tt":
        # TOAs store UTC for topocentric sites; convert once here
        t = t.to_scale("utc")
    n = len(sec)
    flags = [{"photon": "1", "mission": mission} for _ in range(n)]
    if weights is not None:
        for f, w in zip(flags, weights):
            f["weight"] = repr(float(w))
    if energies is not None:
        for f, e in zip(flags, energies):
            f["energy"] = repr(float(e))
    toas = TOAs(
        t,
        np.full(n, np.inf),  # photons: infinite frequency (no DM)
        np.full(n, errors_us),
        [site] * n,
        flags,
    )
    toas.sort()
    return toas


def get_event_weights(toas: TOAs):
    """Per-photon weights from the 'weight' flags, or None."""
    vals = toas.get_flag_value("weight", None)
    if any(v is None for v in vals):
        return None
    return np.array([float(v) for v in vals])


def get_event_energies(toas: TOAs):
    """Per-photon energies (MeV) from the 'energy' flags, or None."""
    vals = toas.get_flag_value("energy", None)
    if any(v is None for v in vals):
        return None
    return np.array([float(v) for v in vals])


def load_fermi_TOAs(path, **kw) -> TOAs:
    """Fermi photon events (reference: fermi_toas.load_Fermi_TOAs)."""
    return load_event_TOAs(path, mission="fermi", **kw)
