"""pintpublish: publication-style parameter table from a par/tim pair.

Reference parity: src/pint/scripts/pintpublish.py — fit and emit a
LaTeX (or plain-text) table of measured and derived quantities.
"""

from __future__ import annotations

import argparse

import numpy as np

import pint_tpu.logging as plog


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Generate a publication parameter table"
    )
    ap.add_argument("parfile")
    ap.add_argument("timfile")
    ap.add_argument("--latex", action="store_true")
    ap.add_argument("--log-level", default="WARNING")
    args = ap.parse_args(argv)
    plog.setup(args.log_level)

    from pint_tpu.fitting import auto_fitter
    from pint_tpu.models.builder import get_model_and_toas

    model, toas = get_model_and_toas(args.parfile, args.timfile)
    fitter = auto_fitter(toas, model)
    fitter.fit_toas()
    rr = fitter.resids
    r = rr.toa if hasattr(rr, "toa") else rr

    rows = [
        ("Pulsar name", model.top_params["PSR"].value or "", ""),
        ("MJD range", f"{toas.first_mjd():.1f}-{toas.last_mjd():.1f}", ""),
        ("Number of TOAs", str(len(toas)), ""),
        ("Weighted RMS residual (us)",
         f"{r.rms_weighted() * 1e6:.3f}", ""),
        ("Reduced chi2", f"{r.reduced_chi2:.3f}", ""),
    ]
    for n in fitter.cm.free_names:
        p = model.params[n]
        unc = (
            f"{p.uncertainty:.2e}" if p.uncertainty is not None else ""
        )
        rows.append((n, p._format_value(), unc))
    # derived quantities when the spin parameters allow
    try:
        from pint_tpu import derived_quantities as dq

        f0 = float(model.params["F0"].value.to_float())
        f1 = float(model.params["F1"].value)
        rows.append(
            ("Characteristic age (yr)", f"{dq.pulsar_age(f0, f1):.3e}", "")
        )
        rows.append(
            ("Surface B field (G)", f"{dq.pulsar_B(f0, f1):.3e}", "")
        )
    except (KeyError, AttributeError, TypeError):
        pass

    if args.latex:
        print("\\begin{tabular}{lll}")
        print("\\hline Parameter & Value & Uncertainty \\\\ \\hline")
        for name, val, unc in rows:
            print(f"{name} & {val} & {unc} \\\\")
        print("\\hline \\end{tabular}")
    else:
        width = max(len(r[0]) for r in rows) + 2
        for name, val, unc in rows:
            print(f"{name:<{width}}{val:>28}  {unc}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
