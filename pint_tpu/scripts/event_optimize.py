"""event_optimize: MCMC fit of a timing model to photon phases.

Reference parity: src/pint/scripts/event_optimize.py — maximize the
unbinned template likelihood sum(log f(phi_i(x))) over the model's free
parameters with an ensemble sampler.  TPU-first: the per-photon phase
kernel and the template density are one jitted pure function of the
delta vector x, vmapped across walkers by pint_tpu.sampler.
"""

from __future__ import annotations

import argparse

import numpy as np

import pint_tpu.logging as plog


def build_lnpost(cm, template, weights=None):
    """Photon-template log-posterior over parameter deltas x."""
    import jax.numpy as jnp

    tpar = jnp.asarray(template.get_parameters())
    w = None if weights is None else jnp.asarray(weights)

    def lnpost(x):
        # TZR-anchored absolute phase: the template was fit to
        # absolute phases, so the likelihood must score the same
        # anchor or AbsPhase models bias the walk by the TZR fraction
        phases = jnp.mod(cm.absolute_phase(x).frac, 1.0)
        f = template(phases, params=tpar)
        if w is None:
            return jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
        return jnp.sum(
            jnp.log(jnp.maximum(w * f + (1.0 - w), 1e-300))
        )

    return lnpost


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="MCMC-fit a timing model to photon phases"
    )
    ap.add_argument("eventfile")
    ap.add_argument("parfile")
    ap.add_argument(
        "gaussianfile",
        help="template: a .gauss component file (itemplate "
        "convention), a binned .prof profile, or the plain "
        "'weight:width:loc' one-peak-per-line format",
    )
    ap.add_argument(
        "--fit-template", action="store_true",
        help="ML-refit the template to the starting phases (with "
        "Hessian errors) before sampling, and write it back out as "
        "<outfile>.gauss when it is a Gaussian template",
    )
    ap.add_argument("--mission", default="generic")
    ap.add_argument("--weightcol", default=None)
    ap.add_argument(
        "--energycol", default=None,
        help="photon-energy column (MeV; e.g. Fermi ENERGY) — feeds "
        "energy-dependent template primitives during --fit-template",
    )
    ap.add_argument("--nwalkers", type=int, default=32)
    ap.add_argument("--nsteps", type=int, default=500)
    ap.add_argument("--burnin", type=float, default=0.25)
    ap.add_argument("--outfile", default="event_optimize_post.par")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--log-level", default="INFO")
    args = ap.parse_args(argv)
    log = plog.setup(args.log_level)

    from pint_tpu.event_toas import get_event_weights, load_event_TOAs
    from pint_tpu.models.builder import get_model
    from pint_tpu.sampler import run_ensemble
    from pint_tpu.templates import LCGaussian
    from pint_tpu.toas.ingest import ingest_for_model

    model = get_model(args.parfile)
    toas = load_event_TOAs(
        args.eventfile, mission=args.mission, weightcol=args.weightcol,
        energycol=args.energycol,
    )
    ingest_for_model(toas, model)
    cm = model.compile(toas, subtract_mean=False)
    log.info(
        "loaded %d photons; free params %s", len(toas), cm.free_names
    )

    from pint_tpu.templates import read_template

    template, _errs = read_template(args.gaussianfile)
    weights = get_event_weights(toas)

    if args.fit_template:
        from pint_tpu.event_toas import get_event_energies
        from pint_tpu.templates import LCFitter, write_gauss

        phases = np.asarray(cm.absolute_phase(cm.x0()).frac) % 1.0
        log10_ens = None
        if template.is_energy_dependent:
            en = get_event_energies(toas)
            if en is None:
                raise SystemExit(
                    "energy-dependent template needs --energycol"
                )
            log10_ens = np.log10(en / 1000.0)  # MeV -> log10(E/GeV)
        lcf = LCFitter(template, phases, weights=weights,
                       log10_ens=log10_ens)
        ll = lcf.fit()
        errs = lcf.errors()
        log.info("template refit: loglike %.2f", ll)
        if all(isinstance(p, LCGaussian) for p in template.primitives):
            write_gauss(template, args.outfile + ".gauss", errors=errs)
            log.info("wrote %s.gauss", args.outfile)

    lnpost = build_lnpost(cm, template, weights)
    # seed the walker ball at the scale where each parameter shifts the
    # mean photon phase by ~0.05 cycles
    import jax

    g = np.asarray(
        jax.grad(lambda x: cm.absolute_phase(x).frac.mean())(cm.x0())
    )
    scales = 0.05 / np.maximum(np.abs(g), 1e-30)
    chain, lnp, acc = run_ensemble(
        lnpost, np.zeros(cm.nfree), nwalkers=args.nwalkers,
        nsteps=args.nsteps, seed=args.seed, init_scale=scales,
    )
    log.info("acceptance %.3f", acc)
    nburn = int(args.burnin * len(chain))
    flat = chain[nburn:].reshape(-1, cm.nfree)
    med = np.median(flat, axis=0)
    std = np.std(flat, axis=0)
    cm.commit(med, uncertainties=std)
    i, j = np.unravel_index(np.argmax(lnp), lnp.shape)
    print(f"max log-likelihood: {float(lnp[i, j]):.2f}  "
          f"acceptance {acc:.3f}")
    for n in cm.free_names:
        p = model.params[n]
        print(f"  {n:<10} {p._format_value()} +- {p.uncertainty:.3e}")
    with open(args.outfile, "w") as f:
        f.write(model.as_parfile())
    log.info("wrote %s", args.outfile)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
