"""Console scripts (reference parity: src/pint/scripts/).

Each module exposes main(argv=None); entry points are declared in
pyproject.toml.  All scripts force x64 and accept --log-level.
"""

import contextlib as _contextlib
import signal as _signal

import jax as _jax

_jax.config.update("jax_enable_x64", True)

# die quietly when stdout is a closed pipe (e.g. `pintempo ... | head`)
with _contextlib.suppress(AttributeError, ValueError):
    _signal.signal(_signal.SIGPIPE, _signal.SIG_DFL)
