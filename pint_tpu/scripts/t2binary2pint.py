"""t2binary2pint: normalize tempo2 T2-model par files.

Reference parity: src/pint/scripts/t2binary2pint.py — tempo2's
catch-all 'BINARY T2' model is a parameter-dependent union; map it to
the concrete model family this framework implements: ELL1 variants when
EPS1/EPS2/TASC are present (ELL1H with H3), DD variants otherwise
(DDH with H3/STIGMA, DDK with KIN/KOM, DDS with SHAPMAX, else DD).
"""

from __future__ import annotations

import argparse

import pint_tpu.logging as plog


def t2_binary_target(params: set) -> str:
    if "EPS1" in params or "TASC" in params:
        return "ELL1H" if "H3" in params else "ELL1"
    if "KIN" in params and "KOM" in params:
        return "DDK"
    if "SHAPMAX" in params:
        return "DDS"
    if "H3" in params and ("STIG" in params or "STIGMA" in params):
        return "DDH"
    return "DD"


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Convert a tempo2 BINARY T2 par file"
    )
    ap.add_argument("input_par")
    ap.add_argument("output_par")
    ap.add_argument("--log-level", default="INFO")
    args = ap.parse_args(argv)
    log = plog.setup(args.log_level)

    from pint_tpu.io.par import parse_parfile
    from pint_tpu.models.builder import get_model

    pardict = parse_parfile(args.input_par)
    binary = pardict.get("BINARY", [["none"]])[0][0].upper()
    if binary != "T2":
        log.info("BINARY %s needs no conversion; validating only", binary)
        out_text = get_model(args.input_par).as_parfile()
    else:
        target = t2_binary_target(set(pardict))
        log.info("BINARY T2 -> %s", target)
        lines = []
        with open(args.input_par) as f:
            for line in f:
                toks = line.split()
                if toks and toks[0].upper() == "BINARY":
                    line = f"BINARY {target}\n"
                lines.append(line)
        out_text = get_model("".join(lines)).as_parfile()
    with open(args.output_par, "w") as f:
        f.write(out_text)
    log.info("wrote %s", args.output_par)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
