"""photonphase: assign model phases to photon events.

Reference parity: src/pint/scripts/photonphase.py — load event FITS,
compute per-photon pulse phase (needs AbsPhase/TZR* for absolute
phase), run the H-test, optionally write a PULSE_PHASE column.
"""

from __future__ import annotations

import argparse

import numpy as np

import pint_tpu.logging as plog


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Assign pulse phases to photon events"
    )
    ap.add_argument("eventfile")
    ap.add_argument("parfile")
    ap.add_argument("--mission", default="generic")
    ap.add_argument("--outfile", default=None,
                    help="write events + PULSE_PHASE to this FITS file")
    ap.add_argument("--plot", action="store_true")
    ap.add_argument("--plotfile", default=None)
    ap.add_argument("--log-level", default="INFO")
    args = ap.parse_args(argv)
    log = plog.setup(args.log_level)

    from pint_tpu.event_toas import load_event_TOAs
    from pint_tpu.eventstats import h2sig, hm
    from pint_tpu.models.builder import get_model
    from pint_tpu.toas.ingest import ingest_for_model

    model = get_model(args.parfile)
    toas = load_event_TOAs(args.eventfile, mission=args.mission)
    log.info("loaded %d photons", len(toas))
    ingest_for_model(toas, model)
    cm = model.compile(toas, subtract_mean=False)
    # TZR-anchored absolute phase (reference: photonphase uses
    # model.phase(abs_phase=True) so PULSE_PHASE has the TZR zero)
    ph = cm.absolute_phase(cm.x0())
    phases = np.mod(np.asarray(ph.frac), 1.0)
    h = hm(phases)
    print(f"Htest : {h:.2f}  ({h2sig(h):.2f} sigma)")
    if args.outfile:
        from pint_tpu.io.fits import add_column

        add_column(args.eventfile, args.outfile, "PULSE_PHASE", phases)
        log.info("wrote %s", args.outfile)
    if args.plotfile or args.plot:
        import matplotlib

        matplotlib.use("Agg" if args.plotfile else matplotlib.get_backend())
        import matplotlib.pyplot as plt

        plt.hist(phases, bins=32)
        plt.xlabel("pulse phase")
        plt.ylabel("photons")
        if args.plotfile:
            plt.savefig(args.plotfile)
        else:
            plt.show()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
