"""compare_parfiles: parameter-level diff of two models.

Reference parity: src/pint/scripts/compare_parfiles.py (wraps
TimingModel.compare).
"""

from __future__ import annotations

import argparse

import pint_tpu.logging as plog


def main(argv=None):
    ap = argparse.ArgumentParser(description="Compare two par files")
    ap.add_argument("par1")
    ap.add_argument("par2")
    ap.add_argument("--log-level", default="WARNING")
    args = ap.parse_args(argv)
    plog.setup(args.log_level)

    from pint_tpu.models.builder import get_model

    m1 = get_model(args.par1)
    m2 = get_model(args.par2)
    print(f"{'PARAM':<12} {args.par1:>25} {args.par2:>25}")
    print(m1.compare(m2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
