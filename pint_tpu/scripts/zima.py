"""zima: simulate fake TOAs from a timing model.

Reference parity: src/pint/scripts/zima.py (wraps simulation.py).
"""

from __future__ import annotations

import argparse

import numpy as np

import pint_tpu.logging as plog


def main(argv=None):
    ap = argparse.ArgumentParser(description="Simulate TOAs (zima)")
    ap.add_argument("parfile")
    ap.add_argument("timfile", help="output tim file")
    ap.add_argument("--ntoa", type=int, default=100)
    ap.add_argument("--startMJD", type=float, default=56000.0)
    ap.add_argument("--duration", type=float, default=400.0, help="days")
    ap.add_argument("--error", type=float, default=1.0, help="TOA sigma, us")
    ap.add_argument("--freq", type=float, nargs="+", default=[1400.0],
                    help="observing frequencies (MHz), cycled over TOAs")
    ap.add_argument("--obs", default="@")
    ap.add_argument("--addnoise", action="store_true")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--log-level", default="INFO")
    args = ap.parse_args(argv)
    log = plog.setup(args.log_level)

    from pint_tpu.io.tim import write_tim_file
    from pint_tpu.models.builder import get_model
    from pint_tpu.simulation import make_fake_toas_uniform

    model = get_model(args.parfile)
    freqs = np.resize(np.asarray(args.freq, dtype=np.float64), args.ntoa)
    rng = (
        np.random.default_rng(args.seed) if args.seed is not None else None
    )
    toas = make_fake_toas_uniform(
        args.startMJD, args.startMJD + args.duration, args.ntoa, model,
        error_us=args.error, freq_mhz=freqs, obs=args.obs,
        add_noise=args.addnoise, rng=rng,
    )
    write_tim_file(args.timfile, toas)
    log.info("wrote %d TOAs to %s", len(toas), args.timfile)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
