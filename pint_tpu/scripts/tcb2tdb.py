"""tcb2tdb: convert a TCB par file to TDB.

Reference parity: src/pint/scripts/tcb2tdb.py (wraps
models/tcb_conversion.py).
"""

from __future__ import annotations

import argparse

import pint_tpu.logging as plog


def main(argv=None):
    ap = argparse.ArgumentParser(description="Convert TCB par to TDB")
    ap.add_argument("input_par")
    ap.add_argument("output_par")
    ap.add_argument("--log-level", default="INFO")
    args = ap.parse_args(argv)
    log = plog.setup(args.log_level)

    # get_model applies the TCB->TDB conversion when UNITS is TCB
    from pint_tpu.models.builder import get_model

    model = get_model(args.input_par)
    with open(args.output_par, "w") as f:
        f.write(model.as_parfile())
    log.info("wrote %s (UNITS %s)", args.output_par,
             model.top_params["UNITS"].value)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
