"""pintbary: quick barycentering of times.

Reference parity: src/pint/scripts/pintbary.py — convert topocentric
UTC MJDs to barycentric arrival times (TDB at SSB) for a sky position.
"""

from __future__ import annotations

import argparse

import numpy as np

import pint_tpu.logging as plog


def main(argv=None):
    ap = argparse.ArgumentParser(description="Barycenter times (pintbary)")
    ap.add_argument("mjds", nargs="+", type=float, help="UTC MJD(s)")
    ap.add_argument("--obs", default="geocenter")
    ap.add_argument("--ra", required=True, help="RAJ (hh:mm:ss.s)")
    ap.add_argument("--dec", required=True, help="DECJ (dd:mm:ss.s)")
    ap.add_argument("--ephem", default="builtin")
    ap.add_argument("--freq", type=float, default=np.inf)
    ap.add_argument("--dm", type=float, default=0.0)
    ap.add_argument("--log-level", default="WARNING")
    args = ap.parse_args(argv)
    plog.setup(args.log_level)

    from pint_tpu.models.builder import get_model
    from pint_tpu.timebase.times import TimeArray
    from pint_tpu.toas.ingest import ingest
    from pint_tpu.toas.toas import TOAs

    par = (
        f"PSR BARY\nRAJ {args.ra}\nDECJ {args.dec}\nF0 1.0\n"
        f"PEPOCH {args.mjds[0]}\nDM {args.dm}\n"
    )
    model = get_model(par)
    n = len(args.mjds)
    toas = TOAs(
        TimeArray.from_mjd_float(np.asarray(args.mjds), scale="utc"),
        np.full(n, args.freq), np.ones(n), [args.obs] * n,
        [dict() for _ in range(n)],
    )
    ingest(toas, ephem=args.ephem, model=model)
    cm = model.compile(toas)
    delay = np.asarray(cm.delay(cm.x0()))
    t_bary = toas.t_tdb.add_seconds(-delay)
    for s in t_bary.to_mjd_strings(15):
        print(s)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
