"""pintempo: tempo2-style command-line fit.

Reference parity: src/pint/scripts/pintempo.py — load par+tim, pick a
fitter, fit, print summary, optionally plot residuals and write the
fitted par file.
"""

from __future__ import annotations

import argparse

import numpy as np

import pint_tpu.logging as plog


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Fit a timing model to TOAs (pintempo)"
    )
    ap.add_argument("parfile")
    ap.add_argument("timfile")
    ap.add_argument("--fitter", default="auto",
                    choices=["auto", "wls", "gls", "downhill", "wideband"])
    ap.add_argument("--full-cov", action="store_true",
                    help="dense covariance GLS path")
    ap.add_argument("--maxiter", type=int, default=None)
    ap.add_argument("--outfile", default=None,
                    help="write the fitted model to this par file")
    ap.add_argument("--plot", action="store_true")
    ap.add_argument("--plotfile", default=None)
    ap.add_argument("--log-level", default="INFO")
    args = ap.parse_args(argv)
    log = plog.setup(args.log_level)

    from pint_tpu.fitting import (
        GLSFitter,
        WLSFitter,
        WidebandTOAFitter,
        auto_fitter,
    )
    from pint_tpu.models.builder import get_model_and_toas

    model, toas = get_model_and_toas(args.parfile, args.timfile)
    log.info("loaded %d TOAs, model %s", len(toas), model.name)

    kw = {}
    if args.fitter == "auto":
        fitter = auto_fitter(toas, model)
    elif args.fitter == "wls":
        fitter = WLSFitter(toas, model)
    elif args.fitter == "gls":
        fitter = GLSFitter(toas, model, full_cov=args.full_cov)
    elif args.fitter == "wideband":
        fitter = WidebandTOAFitter(toas, model, full_cov=args.full_cov)
    else:
        fitter = auto_fitter(toas, model)
    if args.maxiter:
        kw["maxiter"] = args.maxiter
    pre_rms = fitter.resids_init.toa.rms_weighted() if hasattr(
        fitter.resids_init, "toa"
    ) else fitter.resids_init.rms_weighted()
    chi2 = fitter.fit_toas(**kw)
    log.info("chi2 = %.4f", chi2)
    print(f"Pre-fit weighted RMS:  {pre_rms * 1e6:.4f} us")
    fitter.print_summary()
    if args.outfile:
        with open(args.outfile, "w") as f:
            f.write(model.as_parfile())
        log.info("wrote %s", args.outfile)
    if args.plot or args.plotfile:
        import matplotlib

        matplotlib.use("Agg" if args.plotfile else matplotlib.get_backend())
        import matplotlib.pyplot as plt

        r = fitter.resids
        rr = r.toa if hasattr(r, "toa") else r
        mjd = toas.mjd_float()
        err = np.asarray(toas.error_us)
        plt.errorbar(mjd, rr.time_resids * 1e6, yerr=err, fmt=".")
        plt.xlabel("MJD")
        plt.ylabel("residual (us)")
        plt.title(f"{model.name} post-fit")
        if args.plotfile:
            plt.savefig(args.plotfile)
        else:
            plt.show()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
