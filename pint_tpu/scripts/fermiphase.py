"""fermiphase: Fermi-LAT photon phase assignment.

Reference parity: src/pint/scripts/fermiphase.py — the Fermi-specific
front end over the photonphase machinery (mission defaults + weight
column support for the H-test).
"""

from __future__ import annotations

import argparse

import numpy as np

import pint_tpu.logging as plog


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Compute phases for Fermi-LAT photons"
    )
    ap.add_argument("eventfile")
    ap.add_argument("parfile")
    ap.add_argument("--weightcol", default=None,
                    help="photon-weight column name (e.g. MODEL_WEIGHT)")
    ap.add_argument("--outfile", default=None)
    ap.add_argument("--plotfile", default=None)
    ap.add_argument("--log-level", default="INFO")
    args = ap.parse_args(argv)
    log = plog.setup(args.log_level)

    from pint_tpu.event_toas import get_event_weights, load_event_TOAs
    from pint_tpu.eventstats import h2sig, hm
    from pint_tpu.models.builder import get_model
    from pint_tpu.toas.ingest import ingest_for_model

    model = get_model(args.parfile)
    # weights ride in the TOA flags so they survive the time sort
    toas = load_event_TOAs(
        args.eventfile, mission="fermi", weightcol=args.weightcol
    )
    weights = get_event_weights(toas)
    log.info("loaded %d Fermi photons", len(toas))
    ingest_for_model(toas, model)
    cm = model.compile(toas, subtract_mean=False)
    phases = np.mod(np.asarray(cm.absolute_phase(cm.x0()).frac), 1.0)
    h = hm(phases, weights=weights)
    print(f"Htest : {h:.2f}  ({h2sig(h):.2f} sigma)")
    if args.outfile:
        from pint_tpu.io.fits import add_column

        add_column(args.eventfile, args.outfile, "PULSE_PHASE", phases)
        log.info("wrote %s", args.outfile)
    if args.plotfile:
        from pint_tpu.plot_utils import phaseogram

        phaseogram(
            toas.mjd_float(), phases, weights=weights,
            plotfile=args.plotfile,
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
