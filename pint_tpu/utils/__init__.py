"""Host-side utilities (unit policy, angles, misc helpers)."""

from pint_tpu.utils.angles import (  # noqa: F401
    parse_angle_hms,
    parse_angle_dms,
    format_angle_hms,
    format_angle_dms,
)
from pint_tpu.utils.misc import (  # noqa: F401
    compute_hash,
    dmx_ranges_from_toas,
    dmxparse,
    lines_of,
    open_or_use,
    split_intervals,
    taylor_horner,
    taylor_horner_deriv,
    weighted_mean,
)
