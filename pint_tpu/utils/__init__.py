"""Host-side utilities (unit policy, angles, misc helpers)."""

from pint_tpu.utils.angles import (  # noqa: F401
    parse_angle_hms,
    parse_angle_dms,
    format_angle_hms,
    format_angle_dms,
)
