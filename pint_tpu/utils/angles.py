"""Sexagesimal angle parsing/formatting (par-file RAJ/DECJ convention).

Reference parity: astropy Angle parsing used by AngleParameter
(src/pint/models/parameter.py::AngleParameter).  RAJ is hours:min:sec,
DECJ is deg:min:sec; internal representation is radians (f64 — 1e-16 rad
~ 0.6 m projected, far below timing noise; sub-ulp sky positions are not
physically meaningful).
"""

from __future__ import annotations

import math

from pint_tpu.constants import DEG_TO_RAD, HOUR_TO_RAD
from pint_tpu.exceptions import PintTpuError


def _parse_sexagesimal(s: str) -> tuple[float, int]:
    """-> (value in leading units, sign)."""
    s = s.strip()
    sign = 1
    if s.startswith("-"):
        sign, s = -1, s[1:]
    elif s.startswith("+"):
        s = s[1:]
    parts = s.split(":")
    if len(parts) > 3:
        raise PintTpuError(f"bad sexagesimal angle {s!r}")
    val = 0.0
    for i, p in enumerate(parts):
        if p == "":
            raise PintTpuError(f"bad sexagesimal angle {s!r}")
        val += float(p) / 60.0**i
    return val, sign


def parse_angle_hms(s: str) -> float:
    """'HH:MM:SS.sss' (or decimal hours) -> radians."""
    val, sign = _parse_sexagesimal(s)
    return sign * val * HOUR_TO_RAD


def parse_angle_dms(s: str) -> float:
    """'+DD:MM:SS.sss' (or decimal degrees) -> radians."""
    val, sign = _parse_sexagesimal(s)
    return sign * val * DEG_TO_RAD


def _format_sexagesimal(val: float, ndp: int) -> str:
    sign = "-" if val < 0 else ""
    val = abs(val)
    d = int(val)
    rem = (val - d) * 60.0
    m = int(rem)
    s = (rem - m) * 60.0
    # guard against 59.99999 -> 60 rollover
    s_str = f"{s:0{3 + ndp}.{ndp}f}"
    if float(s_str) >= 60.0:
        s_str = f"{0.0:0{3 + ndp}.{ndp}f}"
        m += 1
    if m >= 60:
        m -= 60
        d += 1
    return f"{sign}{d:02d}:{m:02d}:{s_str}"


def format_angle_hms(rad: float, ndp: int = 11) -> str:
    return _format_sexagesimal(rad / HOUR_TO_RAD, ndp)


def format_angle_dms(rad: float, ndp: int = 10) -> str:
    out = _format_sexagesimal(rad / DEG_TO_RAD, ndp)
    return out if out.startswith("-") else "+" + out
