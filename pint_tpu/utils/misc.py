"""Utility grab-bag (reference parity: src/pint/utils.py).

The reference's utils.py is ~3000 LoC; the numerics pieces
(taylor_horner, PosVel algebra) live in pint_tpu.ops / the geometry
columns here, so this module carries the host-side helpers: weighted
statistics, DMX summaries (dmxparse), observing-epoch interval
splitting, content hashing for caches, and file-or-object opening.
"""

from __future__ import annotations

import hashlib
import io
import os
from typing import Optional

import numpy as np

from pint_tpu.ops.taylor import taylor_horner, taylor_horner_deriv  # noqa: F401


def weighted_mean(values, errors, dof: bool = False):
    """Weighted mean and its uncertainty; optionally the reduced chi2
    about the mean (reference: utils.weighted_mean)."""
    v = np.asarray(values, dtype=np.float64)
    w = 1.0 / np.square(np.asarray(errors, dtype=np.float64))
    mean = np.sum(w * v) / np.sum(w)
    err = 1.0 / np.sqrt(np.sum(w))
    if not dof:
        return mean, err
    chi2 = np.sum(w * (v - mean) ** 2) / max(len(v) - 1, 1)
    return mean, err, chi2


def split_intervals(mjds, gap_days: float = 0.5):
    """Split sorted MJDs into observing-epoch groups at gaps
    (reference: the interval splitters backing DMX range suggestions).
    Returns a list of (start_idx, end_idx) half-open index pairs."""
    mjds = np.asarray(mjds, dtype=np.float64)
    order = np.argsort(mjds)
    s = mjds[order]
    breaks = np.flatnonzero(np.diff(s) > gap_days) + 1
    bounds = np.concatenate([[0], breaks, [len(s)]])
    return [
        (int(bounds[i]), int(bounds[i + 1]))
        for i in range(len(bounds) - 1)
    ]


def dmx_ranges_from_toas(toas, gap_days: float = 15.0, pad_days=0.1):
    """Suggest (DMXR1, DMXR2) MJD ranges covering the TOAs (reference:
    utils.dmx_ranges / dmx_setup workflows)."""
    mjd = toas.mjd_float()
    out = []
    for i0, i1 in split_intervals(np.sort(mjd), gap_days):
        s = np.sort(mjd)
        out.append((s[i0] - pad_days, s[i1 - 1] + pad_days))
    return out


def dmxparse(model) -> dict:
    """Summarize a fitted DMX model (reference: utils.dmxparse):
    -> dict with per-range epochs, values, uncertainties, bounds."""
    comp = model.components.get("DispersionDMX")
    if comp is None:
        raise ValueError("model has no DispersionDMX component")
    idx = comp.dmx_indices
    r1 = np.array([comp.params[f"DMXR1_{i:04d}"].value for i in idx])
    r2 = np.array([comp.params[f"DMXR2_{i:04d}"].value for i in idx])
    val = np.array(
        [float(comp.params[f"DMX_{i:04d}"].value) for i in idx]
    )
    unc = np.array([
        comp.params[f"DMX_{i:04d}"].uncertainty or np.nan for i in idx
    ])
    return {
        "dmx_index": np.asarray(idx),
        "dmx_epochs": (r1 + r2) / 2.0,
        "dmx_r1": r1,
        "dmx_r2": r2,
        "dmxs": val,
        "dmx_verrs": unc,
        "mean_dmx": float(np.nanmean(val)) if len(val) else np.nan,
    }


def compute_hash(*items) -> str:
    """Stable content hash for cache keys: file paths hash their bytes;
    other values hash their repr (reference: utils.compute_hash backing
    the TOA pickle cache)."""
    h = hashlib.sha256()
    for it in items:
        if isinstance(it, (str, os.PathLike)) and os.path.isfile(it):
            with open(it, "rb") as f:
                for block in iter(lambda: f.read(1 << 20), b""):
                    h.update(block)
        else:
            h.update(repr(it).encode())
        h.update(b"\x00")
    return h.hexdigest()


def open_or_use(obj, mode: str = "r"):
    """Context manager: open(path) or pass a file object through
    (reference: utils.open_or_use)."""
    import contextlib

    if isinstance(obj, (str, os.PathLike)):
        return open(obj, mode)
    return contextlib.nullcontext(obj)


def lines_of(obj):
    """Iterate lines of a path, file object, or multi-line string."""
    if isinstance(obj, str) and "\n" in obj:
        return io.StringIO(obj)
    return open_or_use(obj)
