"""Binary-model parameter conversion (ELL1 <-> DD/BT families).

Reference parity: src/pint/binaryconvert.py::convert_binary — rewrite a
TimingModel's binary component in another parameterization.  The
load-bearing conversions:

  ELL1 -> DD/BT:  ECC = sqrt(EPS1^2+EPS2^2), OM = atan2(EPS1, EPS2),
                  T0 = TASC + OM/2pi * PB
  DD/BT -> ELL1:  EPS1 = ECC sin OM, EPS2 = ECC cos OM,
                  TASC = T0 - OM/2pi * PB
  DDS <-> DD:     SINI = 1 - exp(-SHAPMAX)
  ELL1H -> ELL1:  M2 = H3/STIGMA^3/Tsun, SINI = 2 STIGMA/(1+STIGMA^2)
"""

from __future__ import annotations

import math

import numpy as np

from pint_tpu.constants import SECS_PER_DAY, SECS_PER_JULIAN_YEAR, TSUN
from pint_tpu.exceptions import TimingModelError
from pint_tpu.models.timing_model import TimingModel

_TWO_PI = 2.0 * math.pi


def _get(model, name, default=None):
    p = model.params.get(name)
    if p is None or p.value is None:
        return default
    v = p.value
    if hasattr(v, "mjd_float"):  # MJDParameter holds a TimeArray
        return float(v.mjd_float()[0])
    if hasattr(v, "to_float"):
        return float(v.to_float())
    return float(v)


def _binary_component(model):
    from pint_tpu.models.pulsar_binary import PulsarBinary

    for c in model.components.values():
        if isinstance(c, PulsarBinary):
            return c
    raise TimingModelError("model has no binary component")


def convert_binary(model: TimingModel, target: str) -> TimingModel:
    """Return a NEW TimingModel with the binary rewritten as `target`
    ('ELL1', 'DD', 'BT', 'DDS', ...).  Non-binary components are reused
    (shared host Parameter objects are copied via parfile round-trip)."""
    from pint_tpu.models.builder import get_model

    cur = _binary_component(model)
    cur_name = cur.binary_model_name.upper()
    target = target.upper()
    if target == cur_name:
        return get_model(model.as_parfile())

    par_lines = []
    skip = set()
    _DEG_YR_TO_RAD_S = math.pi / 180.0 / SECS_PER_JULIAN_YEAR
    if cur_name.startswith("ELL1") and target in ("DD", "BT", "DDS", "DDH"):
        eps1 = _get(model, "EPS1", 0.0)
        eps2 = _get(model, "EPS2", 0.0)
        ecc = math.hypot(eps1, eps2)
        om = math.atan2(eps1, eps2) % _TWO_PI
        pb_d = _get(model, "PB")
        if pb_d is None:
            fb0 = _get(model, "FB0")
            pb_d = 1.0 / fb0 / SECS_PER_DAY
        tasc = _get(model, "TASC")
        t0 = tasc + om / _TWO_PI * pb_d
        par_lines += [
            f"ECC {ecc:.15e}", f"OM {math.degrees(om):.15f}",
            f"T0 {t0:.15f}",
        ]
        # EPS1 = e sin w, EPS2 = e cos w  =>  invert the rates:
        # edot = E1D sin w + E2D cos w; wdot = (E1D cos w - E2D sin w)/e
        e1d = _get(model, "EPS1DOT", 0.0)
        e2d = _get(model, "EPS2DOT", 0.0)
        if e1d or e2d:
            if ecc == 0.0:
                raise TimingModelError(
                    "cannot convert EPS1DOT/EPS2DOT with zero eccentricity"
                )
            edot = e1d * math.sin(om) + e2d * math.cos(om)
            omdot_rad_s = (e1d * math.cos(om) - e2d * math.sin(om)) / ecc
            par_lines += [
                f"EDOT {edot:.15e}",
                f"OMDOT {omdot_rad_s / _DEG_YR_TO_RAD_S:.15e}",
            ]
        skip |= {"EPS1", "EPS2", "TASC", "EPS1DOT", "EPS2DOT"}
    elif cur_name in ("DD", "BT", "DDS", "DDGR", "DDK", "BT_PIECEWISE") \
            and target.startswith("ELL1"):
        ecc = _get(model, "ECC", 0.0)
        if ecc > 0.05:
            raise TimingModelError(
                f"ECC={ecc}: the ELL1 expansion needs e << 1"
            )
        if _get(model, "GAMMA", 0.0):
            raise TimingModelError(
                "ELL1 cannot represent GAMMA (Einstein delay); remove it "
                "or keep a DD-family model"
            )
        om = math.radians(_get(model, "OM", 0.0))
        pb_d = _get(model, "PB")
        t0 = _get(model, "T0")
        par_lines += [
            f"EPS1 {ecc * math.sin(om):.15e}",
            f"EPS2 {ecc * math.cos(om):.15e}",
            f"TASC {t0 - om / _TWO_PI * pb_d:.15f}",
        ]
        edot = _get(model, "EDOT", 0.0)
        omdot_rad_s = _get(model, "OMDOT", 0.0) * _DEG_YR_TO_RAD_S
        if edot or omdot_rad_s:
            par_lines += [
                f"EPS1DOT {edot * math.sin(om) + ecc * omdot_rad_s * math.cos(om):.15e}",
                f"EPS2DOT {edot * math.cos(om) - ecc * omdot_rad_s * math.sin(om):.15e}",
            ]
        skip |= {"ECC", "OM", "T0", "EDOT", "OMDOT", "GAMMA"}
    elif cur_name == "DDS" and target == "DD":
        par_lines.append(
            f"SINI {1.0 - math.exp(-_get(model, 'SHAPMAX')):.15f}"
        )
        skip |= {"SHAPMAX"}
    elif cur_name == "DD" and target == "DDS":
        sini = _get(model, "SINI")
        if sini is None or not 0 < sini < 1:
            raise TimingModelError("DD->DDS needs 0 < SINI < 1")
        par_lines.append(f"SHAPMAX {-math.log(1.0 - sini):.15f}")
        skip |= {"SINI"}
    else:
        raise TimingModelError(
            f"conversion {cur_name} -> {target} not supported"
        )

    # orthometric -> physical Shapiro when leaving the H3 families
    if cur_name in ("ELL1H", "DDH") and target in ("DD", "BT", "DDS", "ELL1"):
        h3 = _get(model, "H3")
        stig = _get(model, "STIGMA")
        if h3 is not None and stig:
            par_lines += [
                f"M2 {h3 / stig**3 / TSUN:.15e}",
                f"SINI {2.0 * stig / (1.0 + stig * stig):.15f}",
            ]
        skip |= {"H3", "H4", "STIGMA", "NHARM"}

    out_lines = [f"BINARY {target}"]
    for line in model.as_parfile().splitlines():
        toks = line.split()
        if not toks:
            continue
        if toks[0] == "BINARY" or toks[0] in skip:
            continue
        out_lines.append(line)
    out_lines += par_lines
    return get_model("\n".join(out_lines) + "\n")
