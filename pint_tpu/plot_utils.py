"""Plotting helpers (reference parity: src/pint/plot_utils.py and the
pintk residual views; the Tk GUI itself is out of scope per SURVEY.md
§7 — these utilities are its replacement surface).
"""

from __future__ import annotations

import numpy as np


def phaseogram(
    mjds, phases, weights=None, bins: int = 64, rotate: float = 0.0,
    ax=None, plotfile=None,
):
    """Two-panel phaseogram: pulse profile + phase vs time (reference:
    plot_utils.phaseogram for photon data)."""
    import matplotlib

    if plotfile:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    ph = np.mod(np.asarray(phases) + rotate, 1.0)
    mjds = np.asarray(mjds)
    if ax is None:
        fig, (ax0, ax1) = plt.subplots(
            2, 1, sharex=True, figsize=(6, 8),
            gridspec_kw={"height_ratios": [1, 3]},
        )
    else:
        ax0, ax1 = ax
        fig = ax0.figure
    # doubled phase axis, standard pulsar convention
    ph2 = np.concatenate([ph, ph + 1.0])
    w2 = None if weights is None else np.concatenate([weights, weights])
    ax0.hist(ph2, bins=2 * bins, range=(0, 2), weights=w2,
             histtype="step", color="k")
    ax0.set_ylabel("photons")
    ax1.scatter(
        ph2, np.concatenate([mjds, mjds]), s=1.0,
        c="k" if weights is None else np.concatenate([weights, weights]),
        cmap=None if weights is None else "viridis",
    )
    ax1.set_xlim(0, 2)
    ax1.set_xlabel("pulse phase")
    ax1.set_ylabel("MJD")
    if plotfile:
        fig.savefig(plotfile)
        plt.close(fig)
    return fig


def plot_residuals(
    toas, resids, ax=None, plotfile=None, label=None, in_us=True,
):
    """Residuals vs MJD with error bars (the pintk plk-view
    equivalent)."""
    import matplotlib

    if plotfile:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if ax is None:
        fig, ax = plt.subplots(figsize=(8, 4))
    else:
        fig = ax.figure
    r = np.asarray(resids.time_resids if hasattr(resids, "time_resids")
                   else resids)
    scale = 1e6 if in_us else 1.0
    ax.errorbar(
        toas.mjd_float(), r * scale, yerr=np.asarray(toas.error_us)
        * (1.0 if in_us else 1e-6),
        fmt=".", ms=3, label=label,
    )
    ax.set_xlabel("MJD")
    ax.set_ylabel(f"residual ({'us' if in_us else 's'})")
    if label:
        ax.legend()
    if plotfile:
        fig.savefig(plotfile)
        plt.close(fig)
    return fig


def plot_random_models(fitter, n_models=30, ax=None, plotfile=None):
    """Overlay residual curves drawn from the fit covariance
    (reference: pintk random-models view / calculate_random_models)."""
    import matplotlib

    if plotfile:
        matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from pint_tpu.simulation import calculate_random_models

    if ax is None:
        fig, ax = plt.subplots(figsize=(8, 4))
    else:
        fig = ax.figure
    curves = calculate_random_models(fitter, n_models=n_models)
    mjd = fitter.toas.mjd_float()
    order = np.argsort(mjd)
    for c in curves:
        ax.plot(mjd[order], c[order] * 1e6, alpha=0.2, color="C0")
    rr = fitter.resids
    r = rr.toa.time_resids if hasattr(rr, "toa") else rr.time_resids
    ax.errorbar(
        mjd, np.asarray(r) * 1e6,
        yerr=np.asarray(fitter.toas.error_us), fmt=".k", ms=3,
    )
    ax.set_xlabel("MJD")
    ax.set_ylabel("residual (us)")
    if plotfile:
        fig.savefig(plotfile)
        plt.close(fig)
    return fig
