"""Sinusoidal whitening terms: Wave (tempo heritage) and the modern
WaveX / DMWaveX / CMWaveX families.

Reference parity: src/pint/models/wave.py::Wave (WAVE_OM + WAVEn
sin/cos pairs, applied as a time delay folded into phase via F0),
src/pint/models/wavex.py::WaveX (WXFREQ_/WXSIN_/WXCOS_ explicit-
frequency delay sinusoids), dmwavex.py::DMWaveX (DM-unit amplitudes,
nu^-2 chromatic), cmwavex.py::CMWaveX (nu^-CMIDX chromatic).
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.constants import DM_CONST
from pint_tpu.models.component import DelayComponent, PhaseComponent
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    pairParameter,
    prefix_index,
)
from pint_tpu.ops.dd import DD

TWOPI = 2.0 * jnp.pi


def _days_since(bundle, epoch_pair):
    day, sec = epoch_pair
    return (bundle.tdb_day - day) + (bundle.tdb_sec - sec).to_float() / 86400.0


class Wave(PhaseComponent):
    """Fundamental WAVE_OM (rad/day) + harmonics WAVEn = (sin, cos)
    amplitudes in seconds; positive amplitude = extra delay."""

    register = True
    category = "wave"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("WAVE_OM", units="rad/d"))
        self.add_param(MJDParameter("WAVEEPOCH", time_scale="tdb"))
        self.prefix_patterns = ["WAVE"]
        self.wave_indices: list[int] = []

    def new_prefix_param(self, name):
        k = prefix_index(name, "WAVE")
        if k is None or k < 1:
            return None
        p = self.add_param(pairParameter(f"WAVE{k}", units="s"))
        return p

    def setup(self, model):
        self.wave_indices = sorted(
            int(n[4:]) for n in self.params
            if n.startswith("WAVE") and n[4:].isdigit()
            and self.params[n].value is not None
        )
        if self.params["WAVEEPOCH"].value is None and "Spindown" in getattr(
            model, "components", {}
        ):
            pep = model.components["Spindown"].params["PEPOCH"].value
            if pep is not None:
                self.params["WAVEEPOCH"].value = pep

    def validate(self, model):
        if self.wave_indices:
            self.require("WAVE_OM", "WAVEEPOCH")

    def phase_term(self, pdict, bundle, delay):
        if not self.wave_indices:
            return DD.zeros((bundle.ntoa,))
        td = _days_since(bundle, pdict["WAVEEPOCH"])
        om = pdict["WAVE_OM"]
        f0 = pdict["F0"]
        f0 = f0.to_float() if isinstance(f0, DD) else f0
        wave = jnp.zeros(bundle.ntoa)
        for k in self.wave_indices:
            a, b = pdict[f"WAVE{k}"]
            arg = k * om * td
            wave = wave + a * jnp.sin(arg) + b * jnp.cos(arg)
        # positive wave seconds = delay => phase decreases
        return DD.from_float(-wave * f0)


class WaveXBase(DelayComponent):
    """Shared machinery for explicit-frequency sinusoid delays."""

    prefixes = ("WXFREQ_", "WXSIN_", "WXCOS_")
    epoch_name = "WXEPOCH"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter(self.epoch_name, time_scale="tdb"))
        self.prefix_patterns = list(self.prefixes)
        self.indices: list[int] = []

    def _add_index(self, idx: int):
        fr, sn, cs = self.prefixes
        self.add_param(floatParameter(f"{fr}{idx:04d}", units="1/d"))
        self.add_param(floatParameter(f"{sn}{idx:04d}", units="s", value=0.0))
        self.add_param(floatParameter(f"{cs}{idx:04d}", units="s", value=0.0))
        self.indices.append(idx)

    def new_prefix_param(self, name):
        for pref in self.prefixes:
            idx = prefix_index(name, pref)
            if idx is not None:
                if f"{self.prefixes[0]}{idx:04d}" not in self.params:
                    self._add_index(idx)
                return self.params[f"{pref}{idx:04d}"]
        return None

    def setup(self, model):
        fr = self.prefixes[0]
        self.indices = sorted(
            int(n[len(fr):]) for n in self.params
            if n.startswith(fr) and self.params[n].value is not None
        )
        if self.params[self.epoch_name].value is None and hasattr(
            model, "components"
        ) and "Spindown" in model.components:
            pep = model.components["Spindown"].params["PEPOCH"].value
            if pep is not None:
                self.params[self.epoch_name].value = pep

    def _chromatic_factor(self, pdict, bundle):
        return 1.0

    def delay_term(self, pdict, bundle, acc_delay):
        if not self.indices:
            return jnp.zeros(bundle.ntoa)
        td = _days_since(bundle, pdict[self.epoch_name])
        fr, sn, cs = self.prefixes
        d = jnp.zeros(bundle.ntoa)
        for i in self.indices:
            arg = TWOPI * pdict[f"{fr}{i:04d}"] * td
            d = d + pdict[f"{sn}{i:04d}"] * jnp.sin(arg) + pdict[
                f"{cs}{i:04d}"
            ] * jnp.cos(arg)
        return d * self._chromatic_factor(pdict, bundle)


class WaveX(WaveXBase):
    register = True
    category = "wave"


class DMWaveX(WaveXBase):
    """Amplitudes in pc/cm^3; delay scales as DM_CONST/f^2."""

    register = True
    category = "dispersion_dmx"
    prefixes = ("DMWXFREQ_", "DMWXSIN_", "DMWXCOS_")
    epoch_name = "DMWXEPOCH"

    def _chromatic_factor(self, pdict, bundle):
        return DM_CONST / jnp.square(bundle.freq_mhz)


class CMWaveX(WaveXBase):
    """Chromatic (nu^-CMIDX) sinusoids; CMIDX is owned by ChromaticCM
    when present (default 4, scattering-like)."""

    register = True
    category = "chromatic"
    prefixes = ("CMWXFREQ_", "CMWXSIN_", "CMWXCOS_")
    epoch_name = "CMWXEPOCH"

    def _chromatic_factor(self, pdict, bundle):
        alpha = pdict.get("CMIDX", 4.0)
        return DM_CONST / bundle.freq_mhz**alpha
