"""Frequency-dependent profile-evolution delays: FD and FDJUMP.

Reference parity: src/pint/models/frequency_dependent.py::FD — delay =
sum_i FDi * log(nu/1 GHz)^i; src/pint/models/fdjump.py::FDJump —
per-selection FD-like terms (FD1JUMP.. mask families).
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import (
    floatParameter,
    maskParameter,
    prefix_index,
)


class FD(DelayComponent):
    register = True
    category = "frequency_dependent"

    def __init__(self, max_terms: int = 9):
        super().__init__()
        for k in range(1, max_terms + 1):
            self.add_param(floatParameter(f"FD{k}", units="s"))
        self.prefix_patterns = ["FD"]

    def new_prefix_param(self, name):
        k = prefix_index(name, "FD")
        if k is None or k < 1:
            return None
        if f"FD{k}" not in self.params:
            self.add_param(floatParameter(f"FD{k}", units="s"))
        return self.params[f"FD{k}"]

    def _terms(self):
        return sorted(
            int(n[2:]) for n in self.params
            if n[2:].isdigit() and self.params[n].value is not None
        )

    def delay_term(self, pdict, bundle, acc_delay):
        lf = jnp.log(bundle.freq_mhz / 1000.0)
        d = jnp.zeros(bundle.ntoa)
        for k in self._terms():
            d = d + pdict[f"FD{k}"] * lf**k
        return d


class FDJump(DelayComponent):
    """FDnJUMP mask families: FD-like log-frequency terms applied to TOA
    subsets (per receiver)."""

    register = True
    category = "frequency_dependent"

    MAX_ORDER = 4

    def __init__(self):
        super().__init__()
        self.fdjump_params: list[tuple[str, int]] = []

    def _add_fdjump_order(self, order):
        def add(idx: int):
            name = f"FD{order}JUMP{idx}"
            p = self.add_param(maskParameter(name, index=idx, units="s"))
            self.fdjump_params.append((name, order))
            return p

        return add

    def mask_families(self):
        return {
            f"FD{k}JUMP": self._add_fdjump_order(k)
            for k in range(1, self.MAX_ORDER + 1)
        }

    def delay_term(self, pdict, bundle, acc_delay):
        lf = jnp.log(bundle.freq_mhz / 1000.0)
        d = jnp.zeros(bundle.ntoa)
        for name, order in self.fdjump_params:
            d = d + pdict[name] * lf**order * bundle.masks[name]
        return d
