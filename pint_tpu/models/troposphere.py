"""Tropospheric propagation delay (hydrostatic + wet, Niell mapping).

Reference parity: src/pint/models/troposphere_delay.py::TroposphereDelay
— zenith hydrostatic delay from standard pressure at the observatory
altitude (Davis et al. 1985), a nominal zenith wet delay, both mapped to
the line-of-sight elevation with the Niell (1996) mapping functions
(seasonally-varying hydrostatic coefficients, latitude-interpolated).

Geometry inputs (per-TOA source elevation, observatory latitude /
altitude) are static host-side products of topocentric ingest; they ride
in ``bundle.masks`` like the other compile-time selections:

  TROPO:sin_elev  (n,)  sine of source elevation
  TROPO:lat       (n,)  observatory geodetic latitude (rad)
  TROPO:alt       (n,)  observatory altitude (m)
  TROPO:doy       (n,)  day-of-year (for the seasonal term)

For data without topocentric geometry (site '@'), the delay is zero.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import boolParameter

# Niell 1996 hydrostatic mapping coefficients at |lat| = 15,30,45,60,75 deg
_LAT_GRID = np.array([15.0, 30.0, 45.0, 60.0, 75.0]) * np.pi / 180.0
_HYD_AVG = np.array([
    [1.2769934e-3, 2.9153695e-3, 62.610505e-3],
    [1.2683230e-3, 2.9152299e-3, 62.837393e-3],
    [1.2465397e-3, 2.9288445e-3, 63.721774e-3],
    [1.2196049e-3, 2.9022565e-3, 63.824265e-3],
    [1.2045996e-3, 2.9024912e-3, 64.258455e-3],
])
_HYD_AMP = np.array([
    [0.0, 0.0, 0.0],
    [1.2709626e-5, 2.1414979e-5, 9.0128400e-5],
    [2.6523662e-5, 3.0160779e-5, 4.3497037e-5],
    [3.4000452e-5, 7.2562722e-5, 84.795348e-5],
    [4.1202191e-5, 11.723375e-5, 170.37206e-5],
])
_WET = np.array([
    [5.8021897e-4, 1.4275268e-3, 4.3472961e-2],
    [5.6794847e-4, 1.5138625e-3, 4.6729510e-2],
    [5.8118019e-4, 1.4572752e-3, 4.3908931e-2],
    [5.9727542e-4, 1.5007428e-3, 4.4626982e-2],
    [6.1641693e-4, 1.7599082e-3, 5.4736038e-2],
])
# height-correction coefficients (Niell 1996)
_A_HT, _B_HT, _C_HT = 2.53e-5, 5.49e-3, 1.14e-3

_C_M_S = 299792458.0
# nominal zenith wet delay, metres (the reference uses a fixed estimate;
# real wet delays are 0.05-0.3 m and unmodelable without weather data)
_ZWD_M = 0.1


def _herring(sin_e, a, b, c):
    """Herring continued-fraction mapping function."""
    top = 1.0 + a / (1.0 + b / (1.0 + c))
    bot = sin_e + a / (sin_e + b / (sin_e + c))
    return top / bot


def _interp_coeffs(table, lat):
    """Piecewise-linear latitude interpolation of (5,3) Niell tables."""
    out = []
    for j in range(3):
        out.append(jnp.interp(jnp.abs(lat), _LAT_GRID, table[:, j]))
    return out


class TroposphereDelay(DelayComponent):
    register = True
    category = "troposphere"

    def __init__(self):
        super().__init__()
        self.add_param(boolParameter("CORRECT_TROPOSPHERE", value=True))

    def extra_masks(self, toas) -> dict:
        n = len(toas)
        elev = getattr(toas, "obs_elevation_rad", None)
        if elev is None:
            z = np.zeros(n)
            return {
                "TROPO:sin_elev": z, "TROPO:lat": z,
                "TROPO:alt": z, "TROPO:doy": z,
            }
        return {
            "TROPO:sin_elev": np.sin(elev),
            "TROPO:lat": np.asarray(toas.obs_lat_rad),
            "TROPO:alt": np.asarray(toas.obs_alt_m),
            # MJD 51544 = 2000-01-01; day-of-year mod 365.25 is plenty
            # for the ~1e-5 seasonal term
            "TROPO:doy": np.mod(toas.mjd_float() - 51544.0, 365.25),
        }

    def zenith_hydrostatic_m(self, lat, alt_m):
        """Davis et al. 1985 ZHD from standard-atmosphere pressure."""
        p_hpa = 1013.25 * (1.0 - 2.2557e-5 * alt_m) ** 5.2568
        return (
            0.0022768 * p_hpa
            / (1.0 - 0.00266 * jnp.cos(2.0 * lat) - 2.8e-7 * alt_m)
        )

    def delay_term(self, pdict, bundle, acc_delay):
        if not self.params["CORRECT_TROPOSPHERE"].value:
            return jnp.zeros(bundle.ntoa)
        sin_e = bundle.masks["TROPO:sin_elev"]
        lat = bundle.masks["TROPO:lat"]
        alt = bundle.masks["TROPO:alt"]
        doy = bundle.masks["TROPO:doy"]
        valid = sin_e > 0.0
        s = jnp.where(valid, sin_e, 1.0)

        # hydrostatic: seasonally-varying coefficients
        a0, b0, c0 = _interp_coeffs(_HYD_AVG, lat)
        a1, b1, c1 = _interp_coeffs(_HYD_AMP, lat)
        # Niell phase convention: DOY 28 (northern); southern shifted 1/2 yr
        season = jnp.cos(
            2.0 * jnp.pi * (doy - 28.0) / 365.25
            + jnp.where(lat < 0, jnp.pi, 0.0)
        )
        mh = _herring(s, a0 - a1 * season, b0 - b1 * season, c0 - c1 * season)
        # height correction
        mh = mh + (1.0 / s - _herring(s, _A_HT, _B_HT, _C_HT)) * (
            alt / 1000.0
        )

        aw, bw, cw = _interp_coeffs(_WET, lat)
        mw = _herring(s, aw, bw, cw)

        path_m = self.zenith_hydrostatic_m(lat, alt) * mh + _ZWD_M * mw
        return jnp.where(valid, path_m / _C_M_S, 0.0)
