"""Timing-model layer: parameters, components, composition, builder.

Reference parity: src/pint/models/ (SURVEY.md §2b).
"""
