"""Timing-model layer: parameters, components, composition, builder.

Reference parity: src/pint/models/ (SURVEY.md §2b).
"""

from pint_tpu.models.astrometry import (  # noqa: F401
    AstrometryEcliptic,
    AstrometryEquatorial,
)
from pint_tpu.models.builder import get_model, get_model_and_toas  # noqa: F401
from pint_tpu.models.component import (  # noqa: F401
    Component,
    DelayComponent,
    NoiseComponent,
    PhaseComponent,
)
from pint_tpu.models.dispersion import (  # noqa: F401
    DispersionDM,
    DispersionDMX,
    DMJump,
)
from pint_tpu.models.jump import DelayJump, PhaseJump  # noqa: F401
from pint_tpu.models.piecewise import PiecewiseSpindown  # noqa: F401
from pint_tpu.models.pulsar_binary import (  # noqa: F401
    BinaryBT,
    BinaryBTPiecewise,
    BinaryDD,
    BinaryDDGR,
    BinaryDDH,
    BinaryDDK,
    BinaryDDS,
    BinaryELL1,
    BinaryELL1H,
    BinaryELL1k,
    PulsarBinary,
)
from pint_tpu.models.troposphere import TroposphereDelay  # noqa: F401
from pint_tpu.models.absolute_phase import AbsPhase  # noqa: F401
from pint_tpu.models.chromatic import ChromaticCM  # noqa: F401
from pint_tpu.models.frequency_dependent import FD, FDJump  # noqa: F401
from pint_tpu.models.glitch import Glitch  # noqa: F401
from pint_tpu.models.ifunc import IFunc  # noqa: F401
from pint_tpu.models.noise import (  # noqa: F401
    EcorrNoise,
    PLChromNoise,
    PLDMNoise,
    PLRedNoise,
    ScaleDmError,
    ScaleToaError,
)
from pint_tpu.models.phase_offset import PhaseOffset  # noqa: F401
from pint_tpu.models.solar_system_shapiro import SolarSystemShapiro  # noqa: F401
from pint_tpu.models.solar_wind import (  # noqa: F401
    SolarWindDispersion,
    SolarWindDispersionX,
)
from pint_tpu.models.wave import CMWaveX, DMWaveX, Wave, WaveX  # noqa: F401
from pint_tpu.models.spindown import Spindown  # noqa: F401
from pint_tpu.models.timing_model import CompiledModel, TimingModel  # noqa: F401
