"""TimingModel: ordered component composition -> compiled JAX kernels.

Reference parity: src/pint/models/timing_model.py::TimingModel (.delay,
.phase, .designmatrix, .d_phase_d_param, component add/remove, validate,
as_parfile) — re-designed for XLA:

- A TimingModel is still an ordered bag of Components (delay components
  folded in category order, each seeing the accumulated delay; phase
  components summed at the delayed time — §3.2 of SURVEY.md).
- ``compile(toas)`` freezes the composition: mask parameters become
  static 0/1 arrays, reference parameter values become trace constants
  (DD for precision-critical ones), and the result is a CompiledModel
  whose kernels are pure functions of ``x`` — the f64 vector of *deltas*
  of the free parameters from their reference values (internal units).
  x = 0 reproduces the reference model exactly; fitters iterate x without
  recompiling; ``commit(x)`` folds deltas back into host Parameters.
- Derivatives (the design matrix) are jax.jacfwd of the phase-residual
  kernel — replacing the reference's ~100 hand-written d_*_d_param
  methods and its finite-difference fallback in one stroke.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import TimingModelError
from pint_tpu.models.component import (
    DEFAULT_ORDER,
    Component,
    DelayComponent,
    NoiseComponent,
    PhaseComponent,
)
from pint_tpu.models.parameter import (
    MJDParameter,
    Parameter,
    floatParameter,
    maskParameter,
    strParameter,
)
from pint_tpu.ops.dd import DD
from pint_tpu.ops.phase import Phase
from pint_tpu.timebase.hostdd import HostDD
from pint_tpu.toas.bundle import TOABundle, make_bundle


class TimingModel:
    """Host-side model: components + top-level metadata parameters."""

    def __init__(self, components=(), name: str = ""):
        self.name = name
        self.components: dict[str, Component] = {}
        # top-level params (reference: TimingModel.top_level_params)
        self.top_params: dict[str, Parameter] = {}
        for p in (
            strParameter("PSR", aliases=("PSRJ", "PSRB")),
            strParameter("EPHEM"),
            strParameter("CLOCK", aliases=("CLK",)),
            strParameter("UNITS"),
            strParameter("TIMEEPH"),
            strParameter("T2CMETHOD"),
            strParameter("DILATEFREQ"),
            MJDParameter("START", time_scale="tdb"),
            MJDParameter("FINISH", time_scale="tdb"),
            floatParameter("NTOA"),
            floatParameter("TRES"),
            strParameter("INFO"),
            strParameter("BINARY"),
            floatParameter("CHI2"),
            floatParameter("CHI2R"),
            floatParameter("DMDATA"),
        ):
            self.top_params[p.name] = p
        for c in components:
            self.add_component(c, setup=False)
        self.setup()

    # -- composition -----------------------------------------------------
    def add_component(self, comp: Component, setup: bool = True):
        name = type(comp).__name__
        if name in self.components:
            raise TimingModelError(f"duplicate component {name}")
        self.components[name] = comp
        if setup:
            self.setup()

    def remove_component(self, name: str):
        self.components.pop(name)

    def setup(self):
        for c in self._ordered_components():
            c.setup(self)

    def validate(self):
        for c in self._ordered_components():
            c.validate(self)

    def _ordered_components(self) -> list[Component]:
        def key(c):
            try:
                return DEFAULT_ORDER.index(c.category)
            except ValueError:
                return len(DEFAULT_ORDER)

        return sorted(self.components.values(), key=key)

    @property
    def delay_components(self) -> list[DelayComponent]:
        return [
            c for c in self._ordered_components()
            if isinstance(c, DelayComponent)
        ]

    @property
    def phase_components(self) -> list[PhaseComponent]:
        return [
            c for c in self._ordered_components()
            if isinstance(c, PhaseComponent)
        ]

    @property
    def noise_components(self) -> list[NoiseComponent]:
        return [
            c for c in self._ordered_components()
            if isinstance(c, NoiseComponent)
        ]

    # -- parameter access -------------------------------------------------
    @property
    def params(self) -> dict[str, Parameter]:
        out = dict(self.top_params)
        for c in self._ordered_components():
            out.update(c.params)
        return out

    def __getattr__(self, name):
        d = object.__getattribute__(self, "__dict__")
        for c in d.get("components", {}).values():
            if name in c.params:
                return c.params[name]
        if name in d.get("top_params", {}):
            return d["top_params"][name]
        raise AttributeError(f"TimingModel has no parameter {name!r}")

    def __getitem__(self, name):
        p = self.params.get(name)
        if p is None:
            raise KeyError(name)
        return p

    @property
    def free_params(self) -> list[str]:
        out = []
        for c in self._ordered_components():
            out.extend(c.free_params)
        return out

    @property
    def fittable_params(self) -> list[str]:
        """Continuous set parameters (epochs included: they fit via a
        seconds-delta, see CompiledModel._pdict / commit)."""
        out = []
        for c in self._ordered_components():
            for n, p in c.params.items():
                if p.continuous and p.value is not None:
                    out.append(n)
        return out

    def free_params_component(self) -> list[tuple[str, Component]]:
        out = []
        for c in self._ordered_components():
            out.extend((n, c) for n in c.free_params)
        return out

    # -- compile ----------------------------------------------------------
    def _build_masks(self, toas) -> dict:
        masks = {}
        for c in self._ordered_components():
            for n in c.mask_params:
                masks[n] = c.params[n].select(toas).astype(np.float64)
            # component-specific static selections (DMX ranges, SWX, ...)
            if hasattr(c, "extra_masks"):
                masks.update(c.extra_masks(toas))
        return masks

    def has_tzr_anchor(self) -> bool:
        """True when the model carries an AbsPhase TZR anchor — such a
        model's compiled kernels embed the TZR bundle as trace
        scaffolding, so serving sessions never stack it with other
        pars (serve/session.py::composition_key folds the par hash
        in)."""
        absph = self.components.get("AbsPhase")
        return (
            absph is not None
            and absph.params["TZRMJD"].value is not None
        )

    def compile(self, toas, subtract_mean: bool = True) -> "CompiledModel":
        bundle = make_bundle(toas, self._build_masks(toas))
        tzr_bundle = None
        absph = self.components.get("AbsPhase")
        if absph is not None and absph.params["TZRMJD"].value is not None:
            # ingested through the SAME ephemeris/options as the data
            # TOAs, eagerly at build time and memoized on the component
            # (absolute_phase.py::ingested_tzr_toas)
            tzr_toas = absph.ingested_tzr_toas(self)
            tzr_bundle = make_bundle(tzr_toas, self._build_masks(tzr_toas))
        return CompiledModel(
            self, bundle, subtract_mean=subtract_mean, tzr_bundle=tzr_bundle
        )

    # -- host-facing conveniences (reference: TimingModel.delay/.phase/
    # .designmatrix; one-shot evaluations that compile under the hood —
    # fitters hold a CompiledModel instead of re-calling these) ---------
    def delay(self, toas) -> np.ndarray:
        """Total delay (s) at each TOA for the current parameters."""
        cm = self.compile(toas)
        return np.asarray(cm.delay(cm.x0()))

    def phase(self, toas):
        """(int_cycles, frac) model phase arrays at each TOA."""
        cm = self.compile(toas, subtract_mean=False)
        ph = cm.phase(cm.x0())
        return np.asarray(ph.int_), np.asarray(ph.frac)

    def designmatrix(self, toas):
        """(M (n, p) seconds-per-internal-unit, free-param names) —
        reference signature minus the astropy units column."""
        cm = self.compile(toas)
        return np.asarray(cm.design_matrix(cm.x0())), list(cm.free_names)

    def d_phase_d_param(self, toas, param: str) -> np.ndarray:
        """Phase derivative (cycles per internal unit) for one free
        parameter (reference: TimingModel.d_phase_d_param) — a single
        jvp with a unit tangent, not a full-Jacobian column."""
        cm = self.compile(toas)
        if param not in cm.free_names:
            raise TimingModelError(
                f"{param} is not a free parameter of this model"
            )
        tangent = jnp.zeros(cm.nfree).at[
            cm.free_names.index(param)
        ].set(1.0)
        _, col = jax.jvp(cm.phase_residuals, (cm.x0(),), (tangent,))
        return np.asarray(col)

    # -- parfile ----------------------------------------------------------
    def as_parfile(self) -> str:
        lines = []
        for p in self.top_params.values():
            line = p.as_parfile_line()
            if line:
                lines.append(line)
        for c in self._ordered_components():
            for p in c.params.values():
                line = p.as_parfile_line()
                if line:
                    lines.append(line)
        return "".join(lines)

    def clone(self) -> "TimingModel":
        """Independent copy sharing NOTHING mutable with this model:
        fresh component instances carrying deep-copied Parameters plus
        deep-copied top-level params, re-``setup()``.  The serving
        layer's per-response model materialization
        (serve/session.py::ParRecord.commit_clone) uses this instead
        of re-parsing the par text per fit response — a clone is pure
        param-state copying, no tokenizing/validate/TZR ingest (the
        ROADMAP item-2 leftover; one host parse per par ADMISSION, not
        per response).  Heavy derived state memoized on components
        (AbsPhase._tzr_memo's ingested TZR TOAs) is deliberately NOT
        copied — a later compile() of the clone re-ingests lazily.
        Every OTHER instance attribute rides along: components keep
        builder-populated registries of their dynamically-added
        params (EcorrNoise.ecorr_params, PhaseJump.jump_params,
        DispersionDMX.dmx_indices, ...) outside ``params``, and a
        clone that dropped them silently lost those terms from the
        noise basis / design matrix (the ISSUE 9 parse-cache bringup
        caught ECORR vanishing from cloned GLS fits)."""
        import copy

        comps = []
        for c in self._ordered_components():
            c2 = type(c)()
            for k, v in vars(c).items():
                if k in ("params", "_tzr_memo"):
                    continue
                setattr(c2, k, copy.deepcopy(v))
            c2.params = {
                n: copy.deepcopy(p) for n, p in c.params.items()
            }
            comps.append(c2)
        m = TimingModel(comps, name=self.name)
        m.top_params = copy.deepcopy(self.top_params)
        m.setup()
        return m

    def compare(self, other: "TimingModel") -> str:
        """Human-readable parameter comparison (reference:
        TimingModel.compare)."""
        rows = []
        names = list(self.params) + [
            n for n in other.params if n not in self.params
        ]
        for n in names:
            a = self.params.get(n)
            b = other.params.get(n)
            av = None if a is None else a.value
            bv = None if b is None else b.value
            if av is None and bv is None:
                continue
            mark = "" if repr(av) == repr(bv) else "  *"
            rows.append(f"{n:<12} {av!r:>25} {bv!r:>25}{mark}")
        return "\n".join(rows)

    def __repr__(self):
        return (
            f"TimingModel({self.name or self.top_params['PSR'].value}, "
            f"components=[{', '.join(self.components)}])"
        )


def reference_values(model: "TimingModel") -> dict:
    """Reference (internal-unit) values for every set parameter of the
    model — the ``x = 0`` anchor of a CompiledModel's delta vector.
    Extracted from CompiledModel.__init__ so the serving layer's
    per-par records (serve/session.py::ParRecord) can derive a fresh
    par's runtime references WITHOUT building a prototype
    CompiledModel: the values depend only on the host model, never on
    a TOA set."""
    ref: dict[str, object] = {}
    for c in model._ordered_components():
        for n, p in c.params.items():
            if p.value is None:
                continue
            if isinstance(p, MJDParameter):
                day, sec = p.internal()
                ref[n] = (day, sec)
            else:
                ref[n] = p.internal()
    return ref


def split_ref_runtime(ref: dict, device: bool = True):
    """Split a reference dict into (numeric device pytree, static host
    dict).  The numeric leaves are what commit() rebases and what the
    PTA batch stacks per pulsar; strings/bools stay static (they shape
    the trace).  Shared by CompiledModel.jit (single model — the
    numeric part rides every call as runtime arguments) and
    parallel/pta.py::_device_ref (vmapped per-pulsar stacks).

    ``device=False`` keeps the numeric leaves HOST numpy f64 scalars
    (identical values and pytree structure — DD still flattens to
    (hi, lo)): the serving batcher np.stack's per-par reference
    pytrees on a leading pulsar axis before anything crosses to the
    device, and jnp leaf placement here would cost one axon transfer
    per leaf per admitted par instead of one bulk transfer per
    dispatched batch (the make_bundle ``as_numpy`` rationale).

    CONTRACT (ADVICE r5): every numeric ref must be VALUE-like — a
    quantity kernels consume through ``_pdict`` as an f64 operand.
    Anything that shapes the trace (harmonic counts, basis sizes, mask
    selections, array indices) must NOT live in the ref dict's numeric
    leaves: after the coercion below it arrives in kernels as an f64
    TRACER, and ``int(tracer)`` / shape use raises deep inside jax with
    no hint of which parameter leaked.  Components therefore read
    shape-like parameters straight from the host Parameter (the
    TNREDC pattern: ``self.params["TNREDC"].value`` in
    models/noise.py), which never enters this split.  The assert
    rejects the tell-tale case — a bare Python/numpy integer ref —
    loudly at split time instead.
    """
    f64 = jnp.float64 if device else np.float64
    num, static = {}, {}
    for n, v in ref.items():
        if isinstance(v, (int, np.integer)) and not isinstance(v, bool):
            raise TimingModelError(
                f"reference value for {n!r} is a bare integer ({v!r}): "
                "numeric refs must be value-like f64 quantities, never "
                "static counts/indices/shapes (those must stay host "
                "Parameters — see split_ref_runtime's contract)"
            )
        if isinstance(v, HostDD):
            num[n] = DD(f64(float(v.hi)), f64(float(v.lo)))
        elif (
            isinstance(v, tuple) and len(v) == 2
            and isinstance(v[1], HostDD)
        ):
            day, sec = v
            num[n] = (
                f64(float(day)),
                DD(f64(float(sec.hi)), f64(float(sec.lo))),
            )
        elif isinstance(v, tuple):
            num[n] = tuple(f64(float(e)) for e in v)
        elif isinstance(v, (float, int)) and not isinstance(v, bool):
            num[n] = f64(v)
        else:
            static[n] = v
    return num, static


class CompiledModel:
    """A TimingModel frozen against a TOA set: pure kernels of x.

    x layout: one f64 entry per free parameter, in ``self.free_names``
    order, holding the *delta* from the reference value in internal units.
    """

    def __init__(
        self,
        model: TimingModel,
        bundle: TOABundle,
        subtract_mean=True,
        tzr_bundle: Optional[TOABundle] = None,
    ):
        self.model = model
        self.bundle = bundle
        self.tzr_bundle = tzr_bundle
        self.subtract_mean = subtract_mean
        self.free_names = model.free_params
        self._index = {n: i for i, n in enumerate(self.free_names)}
        # reference (internal-unit) values for every set parameter
        self.ref: dict[str, object] = reference_values(model)
        self.track_mode = (
            "use_pulse_numbers"
            if not np.all(np.isnan(np.asarray(bundle.pulse_number)))
            else "nearest"
        )
        self._jit_cache: dict = {}
        self._ref_runtime_cache = None
        self._cleared_for = None  # bundle whose swap last cleared jax

    @property
    def nfree(self):
        return len(self.free_names)

    def x0(self) -> jnp.ndarray:
        return jnp.zeros(self.nfree, dtype=jnp.float64)

    def _ref_runtime(self):
        """Numeric-device pytree of the CURRENT reference values,
        cached until the next commit().  These ride every cm.jit call
        as runtime arguments (see jit below), so a post-fit commit —
        which rebases them — invalidates NO compiled code."""
        if self._ref_runtime_cache is None:
            self._ref_runtime_cache = split_ref_runtime(self.ref)[0]
        return self._ref_runtime_cache

    def _ref_swap_call(self, fn, refnum, args):
        """Run fn with the numeric reference entries swapped for
        ``refnum`` (tracers during a jit trace) — the single-model
        sibling of parallel/pta.py::PTABatch._with_state."""
        saved = self.ref
        self.ref = {**saved, **refnum}
        try:
            return fn(*args)
        finally:
            self.ref = saved

    def jit(self, fn, donate=False):
        """jax.jit(fn) with this model's TOA bundles AND numeric
        reference values passed as RUNTIME arguments instead of
        closure constants.

        ``donate=True`` (ISSUE 12) marks the CALLER-VISIBLE operands —
        the per-dispatch ``args``, e.g. the fused-downhill scan state —
        as ``donate_argnums``: XLA aliases them into same-shaped
        outputs (the x-in/x-out fit loop) and frees the rest at
        dispatch instead of holding both copies live.  The cached
        bundle/reference operands are NEVER donated — they ride every
        call.  Donation is a per-call-fresh-operand contract: the
        caller must not reuse an argument after the call (pintlint
        rule perf1 flags use-after-donate statically), which is why it
        is opt-in.  The guard snapshots donated operands it may need
        to replay (runtime/guard.py::snapshot_donated), so the retry
        ladder never reads a freed buffer.  ``PINT_TPU_DONATE=0``
        disables donation everywhere at wrapper build time.

        A plain ``jax.jit`` over a CompiledModel method bakes every
        bundle column (and the precomputed Fourier basis riding in
        bundle.masks) into the lowered module as dense literals —
        ~240 bytes of HLO text per TOA, i.e. a ~240 MB module at 1e6
        TOAs, which chokes remote-compile transports and recompiles
        whenever the data changes.  Here the bundles are swapped for
        tracers during the single trace, so the module is O(1) in ntoa
        and the same executable serves any same-shape dataset
        (the XLA-idiomatic split of static program vs runtime data).

        The numeric references ride as arguments in BOTH branches
        (r5, VERDICT r4 weak 4): they are the ONLY thing commit()
        rebases, so with them as runtime values a refit after commit
        reuses every compiled loop — previously each fit_toas paid a
        full recompile of the scan loop + residual kernels (~30 s
        through the remote-compile tunnel at 1e5 TOAs; measured
        profiling/profile_fit_wall.py).  Safety precedent: the PTA
        batch has always vmapped these same leaves as tracers
        (parallel/pta.py::_device_ref), so the whole kernel surface is
        known to trace correctly with runtime references.

        SMALL datasets keep the baked-constant lowering for the
        BUNDLE: XLA's LICM does not reliably hoist argument-derived
        loop invariants out of scan bodies, so argument-fed bundles
        re-execute per-step work that constant folding eliminates
        (+22% on the 1e5 north star, measured r4); below the threshold
        the module is small enough that baking is strictly better.
        ``$PINT_TPU_BAKE_THRESHOLD`` overrides the cutover (TOA
        count): remote-compile transports can choke on mid-size baked
        modules long before the 200k default — the n=32768 dense step
        (~16 MB of baked literals) stopped compiling in useful time on
        the axon tunnel in r5 while its argument-fed form compiles in
        seconds.

        Every wrapper returned here is the framework's dispatch
        chokepoint, so it carries the device-execution guard
        (runtime/guard.py::dispatch_guard): watchdog timeouts for
        wedged remote compiles, bounded retries for transient
        transport errors, and the fault-injection hooks — one wrap
        covers every fitter, bench, and profiling dispatch.  Calls
        made inside another trace (vmap/jit) bypass the guard and
        inline as before."""
        import functools
        import os

        from pint_tpu import obs as _obs
        from pint_tpu.runtime.guard import (
            dispatch_guard,
            donation_enabled,
            quiet_unusable_donation,
        )

        site = f"cm.jit:{getattr(fn, '__name__', 'fn')}"
        donating = bool(donate) and donation_enabled()
        if donating:
            quiet_unusable_donation()

        # flight-recorder hooks (pint_tpu/obs): `noted` replaces fn in
        # the traced position, so its host side effect fires exactly
        # once per XLA (re)trace — an exact compile/recompile counter
        # (jax executes the Python body only on jit cache miss).  A
        # retrace past the wrapper's first is a RECOMPILE: bundle
        # swap, ladder-device pin, or a shape change — and must never
        # happen on a commit()-then-refit (the r5 one-dispatch
        # invariant bench.py's obs block asserts).
        ntraces = [0]

        def noted(*a):
            _obs.note_trace(site, retrace=ntraces[0] > 0)
            ntraces[0] += 1
            return fn(*a)

        _const_bytes = [None]  # operand bytes constant per wrapper

        threshold = int(
            os.environ.get("PINT_TPU_BAKE_THRESHOLD", "200000")
        )

        def _inner(bundles, refnum, args):
            old = (self.bundle, self.tzr_bundle)
            self.bundle, self.tzr_bundle = bundles
            try:
                return self._ref_swap_call(noted, refnum, args)
            finally:
                self.bundle, self.tzr_bundle = old

        # donation covers ONLY position 2 — the caller's per-dispatch
        # args; the bundle/reference pytrees (0, 1) are cached and
        # reused across every call, so donating them would free the
        # model out from under the next dispatch
        inner = (
            jax.jit(_inner, donate_argnums=(2,)) if donating
            else jax.jit(_inner)
        )

        _arg_bytes = [None]

        def argfed_call(args):
            """Argument-fed dispatch: bundles + refs ride as runtime
            operands, so any same-shape dataset reuses the compiled
            module (the >threshold default, and the adaptive swap
            target below it)."""
            if _arg_bytes[0] is None:
                # the bundle/ref operands ride EVERY call; their byte
                # total is shape-constant per wrapper (the same-shape
                # data-swap contract), so one tree walk amortizes over
                # all dispatches
                _arg_bytes[0] = _obs.trace.nbytes_of(
                    ((self.bundle, self.tzr_bundle),
                     self._ref_runtime())
                )
            _obs.note_transfer(site, _arg_bytes[0], args)
            return inner(
                (self.bundle, self.tzr_bundle), self._ref_runtime(),
                args,
            )

        if self.bundle.ntoa <= threshold:
            # baked-constant lowering — but pinned to the bundle
            # OBJECTS, so an in-place bundle swap never silently
            # serves the old dataset from jit's shape-keyed cache
            # (the same-shape data-swap contract of
            # docs/parallelism.md).  The cache holds STRONG
            # references and compares with `is` — bare id() keys can
            # false-hit after GC address reuse.
            #
            # ADAPTIVE CUTOVER (r6): the FIRST same-shape bundle swap
            # switches this wrapper permanently to the argument-fed
            # path instead of re-baking.  A re-bake pays a full
            # remote recompile of a literal-heavy module PER SWAP
            # (~35 s at 1e5 TOAs, profiling/profile_fit_wall.py);
            # once data starts swapping, baking's per-step advantage
            # (+22% via scan-LICM constant folding, r4) can never
            # amortize that, while the argument-fed module compiles
            # once — often straight from the persistent compile cache
            # (runtime/compile_cache.py) — and then serves every
            # subsequent swap for pure transfer+dispatch, like the
            # >threshold path always has.  A DIFFERENT-shape swap
            # re-bakes as before (an argument-fed module would also
            # recompile, and below the threshold baked is faster).
            # PINT_TPU_ADAPTIVE_SWAP=0 restores unconditional
            # re-bake.
            baked: list = []  # [bundle, tzr_bundle, jitted, shape_sig]
            mode = ["baked"]
            adaptive = (
                os.environ.get("PINT_TPU_ADAPTIVE_SWAP", "1") != "0"
            )

            def _shape_sig(pair):
                return (
                    jax.tree_util.tree_structure(pair),
                    tuple(
                        (getattr(l, "shape", ()), getattr(l, "dtype", None))
                        for l in jax.tree_util.tree_leaves(pair)
                    ),
                )

            def _clear_for_retrace():
                # jax's initial-style jaxpr caches (lax.scan bodies
                # etc.) key on the CLOSURE IDENTITY of fn's inner
                # functions + avals, and their cached entries hold
                # the PREVIOUS trace's ref tracers as consts —
                # re-tracing the same closures would resurrect them
                # (UnexpectedTracerError; r5, found converting refs
                # to runtime args).  The clear is process-global (jax
                # offers nothing finer); _cleared_for dedups it per
                # swapped bundle so this model's OWN lazily
                # re-tracing wrappers don't cascade-discard each
                # other's fresh compiles.
                if baked and self._cleared_for is not self.bundle:
                    jax.clear_caches()
                    self._cleared_for = self.bundle
                    _obs.TRACER.event(
                        "cache-clear", "compile", site=site
                    )

            def _jitted():
                if (not baked or baked[0] is not self.bundle
                        or baked[1] is not self.tzr_bundle):
                    _clear_for_retrace()
                    # fresh closure each re-bake: jax's trace cache
                    # keys on function identity, so jit(fn) again
                    # would serve the OLD bundle's baked trace.  The
                    # donating variant takes the caller args as ONE
                    # tuple operand so the donated position is static
                    # regardless of arity.
                    baked[:] = [
                        self.bundle, self.tzr_bundle,
                        (
                            jax.jit(
                                lambda refnum, a: self._ref_swap_call(
                                    noted, refnum, a
                                ),
                                donate_argnums=(1,),
                            )
                            if donating
                            else jax.jit(
                                lambda refnum, *a:
                                self._ref_swap_call(noted, refnum, a)
                            )
                        ),
                        _shape_sig((self.bundle, self.tzr_bundle)),
                    ]
                    # baked-literal transport pressure (near-413
                    # early warning; pint_tpu/obs/__init__.py)
                    _obs.note_baked_module(
                        site, self.bundle.ntoa,
                        (self.bundle, self.tzr_bundle),
                    )
                return baked[2]

            @functools.wraps(fn)
            def rebaking(*args):
                if (
                    mode[0] == "baked" and adaptive and baked
                    and (baked[0] is not self.bundle
                         or baked[1] is not self.tzr_bundle)
                    and _shape_sig((self.bundle, self.tzr_bundle))
                    == baked[3]
                ):
                    _clear_for_retrace()
                    mode[0] = "args"
                    _obs.TRACER.event(
                        "swap-to-args", "compile", site=site,
                        ntoa=self.bundle.ntoa,
                    )
                if mode[0] == "args":
                    return argfed_call(args)
                if _const_bytes[0] is None:
                    _const_bytes[0] = _obs.trace.nbytes_of(
                        self._ref_runtime()
                    )
                _obs.note_transfer(site, _const_bytes[0], args)
                if donating:
                    return _jitted()(self._ref_runtime(), args)
                return _jitted()(self._ref_runtime(), *args)

            # AOT hook: lower against the CURRENT bundles/refs + mode
            rebaking.lower = lambda *args: (
                inner.lower(
                    (self.bundle, self.tzr_bundle),
                    self._ref_runtime(), args,
                )
                if mode[0] == "args"
                else (
                    _jitted().lower(self._ref_runtime(), args)
                    if donating
                    else _jitted().lower(self._ref_runtime(), *args)
                )
            )
            if donating:
                # every caller-visible position is donated (they all
                # land in the donated inner operand) — the guard's
                # retry snapshot marker (runtime/guard.py)
                rebaking._donate_argnums = True
            return dispatch_guard(rebaking, site)

        @functools.wraps(fn)
        def wrapped(*args):
            return argfed_call(args)

        # AOT hooks (profiling/bench): lower with the CURRENT state
        wrapped.lower = lambda *args: inner.lower(
            (self.bundle, self.tzr_bundle), self._ref_runtime(), args
        )
        if donating:
            wrapped._donate_argnums = True
        return dispatch_guard(wrapped, site)

    # -- pdict construction (inside trace) --------------------------------
    def _pdict(self, x):
        pd = {}
        for n, v in self.ref.items():
            if isinstance(v, DD):
                # device-typed reference (PTA batching swaps per-pulsar
                # refs in as traced values)
                if n in self._index:
                    pd[n] = (v + x[self._index[n]]).normalize()
                else:
                    pd[n] = v
            elif (
                isinstance(v, tuple) and len(v) == 2
                and isinstance(v[1], DD)
            ):
                day, sec = v  # device-typed epoch (day, DD seconds)
                if n in self._index:
                    sec = (sec + x[self._index[n]]).normalize()
                pd[n] = (day, sec)
            elif isinstance(v, HostDD):
                const = DD(jnp.float64(float(v.hi)), jnp.float64(float(v.lo)))
                if n in self._index:
                    pd[n] = (const + x[self._index[n]]).normalize()
                else:
                    pd[n] = const
            elif isinstance(v, tuple) and len(v) == 2 and isinstance(
                v[1], HostDD
            ):
                # epoch (day, HostDD sec); if free, x[i] is a seconds delta
                day, sec = v
                sec_dd = DD(
                    jnp.float64(float(sec.hi)), jnp.float64(float(sec.lo))
                )
                if n in self._index:
                    sec_dd = (sec_dd + x[self._index[n]]).normalize()
                pd[n] = (float(day), sec_dd)
            elif isinstance(v, tuple):
                # pairParameter (sin, cos amplitudes): static floats
                pd[n] = v
            elif isinstance(v, (float, int)) or (
                hasattr(v, "dtype") and getattr(v, "ndim", None) == 0
            ):
                # host float OR a traced/device f64 scalar (PTA batch)
                if n in self._index:
                    pd[n] = jnp.float64(v) + x[self._index[n]]
                else:
                    pd[n] = jnp.float64(v)
            else:
                pd[n] = v  # strings, bools: static
        return pd

    # -- kernels ----------------------------------------------------------
    def delay(self, x):
        """Total delay in seconds (f64) at each TOA."""
        pd = self._pdict(x)
        d = jnp.zeros(self.bundle.ntoa)
        for c in self.model.delay_components:
            d = d + c.delay_term(pd, self.bundle, d)
        return d

    def phase(self, x, bundle: Optional[TOABundle] = None) -> Phase:
        bundle = self.bundle if bundle is None else bundle
        pd = self._pdict(x)
        d = jnp.zeros(bundle.ntoa)
        for c in self.model.delay_components:
            d = d + c.delay_term(pd, bundle, d)
        total = DD.zeros(bundle.ntoa)
        for c in self.model.phase_components:
            total = total + c.phase_term(pd, bundle, d)
        return Phase.from_dd(total)

    def spin_frequency(self, x):
        """Instantaneous spin frequency at each TOA (for time residuals)."""
        pd = self._pdict(x)
        for c in self.model.phase_components:
            if hasattr(c, "spin_frequency"):
                return c.spin_frequency(pd, self.bundle)
        raise TimingModelError("no spindown component in model")

    def absolute_phase(self, x, bundle: Optional[TOABundle] = None) -> Phase:
        """Model phase with the TZR anchor subtracted when the model
        carries AbsPhase (reference: TimingModel.phase(abs_phase=True))
        — the phase photonphase/fermiphase/event_optimize/polycos
        publish.  Without AbsPhase this is the raw model phase."""
        ph = self.phase(x, bundle=bundle)
        if self.tzr_bundle is not None:
            tz = self.phase(x, bundle=self.tzr_bundle)
            ph = ph - tz[0]  # Phase carry-normalized subtraction
        return ph

    def phase_residuals(self, x):
        """Phase residuals in cycles (f64), no mean subtraction.

        -padd flags / tim PHASE commands add (integer) turns to the
        model phase before pulse-number subtraction (reference:
        Residuals.calc_phase_resids); with 'nearest' tracking integer
        adds cancel by construction.
        """
        ph = self.absolute_phase(x)
        if self.track_mode == "use_pulse_numbers":
            pn = self.bundle.pulse_number
            return (ph.int_ - pn + self.bundle.padd) + ph.frac
        return ph.frac

    # -- wideband DM interfaces (reference: dispersion components'
    # dm_value/d_dm_d_param consumed by WidebandTOAResiduals) ------------
    def dm_model(self, x):
        """Model DM at each TOA in pc/cm^3, including DMJUMP offsets to
        the measurement scale."""
        pd = self._pdict(x)
        dm = jnp.zeros(self.bundle.ntoa)
        for c in self.model.delay_components:
            if hasattr(c, "dm_value"):
                dm = dm + c.dm_value(pd, self.bundle)
            if hasattr(c, "dm_offset"):
                dm = dm + c.dm_offset(pd, self.bundle)
        return dm

    def dm_residuals(self, x):
        """Wideband DM residuals: measured - model (pc/cm^3)."""
        if self.bundle.dm_meas is None:
            raise TimingModelError(
                "no wideband DM measurements (-pp_dm flags) in these TOAs"
            )
        return self.bundle.dm_meas - self.dm_model(x)

    def scaled_dm_sigma(self, x):
        """Per-TOA wideband DM uncertainty (pc/cm^3) after DMEFAC/DMEQUAD
        rescaling (reference: TimingModel.scaled_dm_sigma)."""
        pd = self._pdict(x)
        sig = self.bundle.dm_err
        for c in self.model.noise_components:
            if hasattr(c, "scaled_dm_sigma"):
                sig = c.scaled_dm_sigma(pd, self.bundle, sig)
        return sig

    def scaled_sigma(self, x):
        """Per-TOA white uncertainty in seconds after noise-model
        rescaling (reference: TimingModel.scaled_toa_sigma)."""
        pd = self._pdict(x)
        sig = self.bundle.error_us * 1e-6
        for c in self.model.noise_components:
            sig = c.scaled_sigma(pd, self.bundle, sig)
        return sig

    def noise_basis(self, x):
        """Stacked correlated-noise basis/weights: (T (n,k), phi (k,)) or
        None (reference: noise_model_designmatrix/basis_weight)."""
        pd = self._pdict(x)
        bases, weights = [], []
        for c in self.model.noise_components:
            bw = c.basis_weight(pd, self.bundle)
            if bw is not None:
                bases.append(bw[0])
                weights.append(bw[1])
        if not bases:
            return None
        return (
            jnp.concatenate(bases, axis=1), jnp.concatenate(weights)
        )

    def noise_basis_or_empty(self, x):
        """Like noise_basis but never None: models without correlated
        noise get a zero basis column with ~zero weight, so GLS /
        downhill / wideband consumers share one degenerate-basis
        convention."""
        bw = self.noise_basis(x)
        if bw is not None:
            return bw
        # weight 1e-30, NOT smaller: the Woodbury inner solve forms
        # 1/phi, and axon's f32-pair emulated f64 keeps the f32
        # EXPONENT range — 1e40 overflows to inf and NaNs the whole
        # fit (the basis column is zero, so any finite weight is
        # exact; caught by the on-TPU smoke suite, docs/precision.md)
        return (
            jnp.zeros((self.bundle.ntoa, 1)),
            jnp.ones(1) * 1e-30,
        )

    def noise_covariance(self, x):
        """Dense (n, n) noise covariance C = diag(N) + T phi T^T
        (reference: TimingModel.covariance_matrix / the full_cov GLS
        input).  O(n^2) memory — diagnostics and small-n use only."""
        from pint_tpu.models.noise import dense_noise_cov

        Ndiag = jnp.square(self.scaled_sigma(x))
        bw = self.noise_basis(x)
        T, phi = bw if bw is not None else (None, None)
        return dense_noise_cov(Ndiag, T, phi)

    def noise_fourier_spec(self, x):
        """(t_seconds, freqs, phi) when the model's correlated noise is
        exactly one pure-Fourier basis (PL red noise) — the shape the
        Pallas fused-Gram GLS path accepts; None otherwise."""
        pd = self._pdict(x)
        specs = [
            c.fourier_spec(pd, self.bundle)
            for c in self.model.noise_components
            if hasattr(c, "fourier_spec")
        ]
        n_corr = sum(
            c.introduces_correlated_errors
            for c in self.model.noise_components
        )
        if len(specs) == 1 and n_corr == 1:
            return specs[0]
        return None

    @property
    def has_correlated_errors(self):
        return any(
            c.introduces_correlated_errors
            for c in self.model.noise_components
        )

    def _weights(self, x):
        return 1.0 / jnp.square(self.scaled_sigma(x))

    def time_residuals(self, x, subtract_mean: Optional[bool] = None):
        """Time residuals in seconds; weighted-mean-subtracted by default
        (reference: Residuals.calc_time_resids)."""
        sm = self.subtract_mean if subtract_mean is None else subtract_mean
        pr = self.phase_residuals(x)
        f = self.spin_frequency(x)
        r = pr / f
        if sm:
            w = self._weights(x)
            r = r - jnp.sum(w * r) / jnp.sum(w)
        return r

    def chi2(self, x):
        r = self.time_residuals(x)
        w = self._weights(x)
        return jnp.sum(w * r * r)

    def design_matrix(self, x):
        """(n_toa, n_free) d(time-resid)/d(param delta), seconds per
        internal unit; reference: TimingModel.designmatrix = d_phase/d_par
        / F0 — here jacfwd of the phase residual over the spin frequency."""
        jac = jax.jacfwd(self.phase_residuals)(x)
        f = self.spin_frequency(x)
        return jac / f[:, None]

    # -- jitted conveniences ----------------------------------------------
    def _jitted(self, name):
        if name not in self._jit_cache:
            fn = getattr(self, name)
            # self.jit, not jax.jit: bundles re-bake on data swap and
            # references ride as runtime args, so these survive
            # commit() (r5 — a post-fit residual refresh used to
            # recompile the whole residual kernel)
            self._jit_cache[name] = self.jit(fn)
        return self._jit_cache[name]

    def time_residuals_jit(self, x):
        return self._jitted("time_residuals")(x)

    def chi2_jit(self, x):
        return self._jitted("chi2")(x)

    def design_matrix_jit(self, x):
        return self._jitted("design_matrix")(x)

    # -- commit fitted deltas back to host parameters ---------------------
    def commit(self, x, uncertainties=None):
        x = np.asarray(x)
        params = self.model.params
        for n, i in self._index.items():
            p = params[n]
            ref = self.ref[n]
            if isinstance(ref, tuple):
                p.add_internal_delta(float(x[i]))
            elif isinstance(ref, HostDD):
                p.set_internal(ref + float(x[i]))
            else:
                p.set_internal(float(ref) + float(x[i]))
            if uncertainties is not None:
                p.set_internal_uncertainty(float(uncertainties[i]))
        # refresh references so x=0 is the new model.  Compiled code
        # survives this: the numeric references ride every cm.jit call
        # as runtime arguments (see jit/_ref_runtime), so only the
        # cached argument pytree needs rebuilding.
        for n in self._index:
            p = params[n]
            self.ref[n] = p.internal()
        self._ref_runtime_cache = None
