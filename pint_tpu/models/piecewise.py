"""Piecewise spindown: independent spin solutions over MJD intervals.

Reference parity: src/pint/models/piecewise.py::PiecewiseSpindown — per
piece i, for TOAs with PWSTART_i <= MJD < PWSTOP_i, add

  phase_i = PWPH_i + PWF0_i dt + PWF1_i dt^2/2 + PWF2_i dt^3/6,
  dt = t - PWEP_i (seconds, delay-corrected)

on top of the global Spindown phase.  Range membership is static per
TOA -> 0/1 masks at compile time; the piece terms are small (offsets
from the global solution), so f64 accumulation into DD phase is exact.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.exceptions import MissingParameter, TimingModelError
from pint_tpu.models.component import PhaseComponent
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    prefix_index,
)
from pint_tpu.ops.dd import DD

_FAMS = ("PWEP_", "PWPH_", "PWF0_", "PWF1_", "PWF2_", "PWSTART_", "PWSTOP_")


class PiecewiseSpindown(PhaseComponent):
    register = True
    category = "piecewise_spindown"

    def __init__(self):
        super().__init__()
        self.prefix_patterns = list(_FAMS)
        self.piece_indices: list[int] = []

    def add_piece(self, idx: int):
        self.add_param(MJDParameter(f"PWEP_{idx}", time_scale="tdb"))
        self.add_param(
            floatParameter(f"PWPH_{idx}", units="cycles", value=0.0)
        )
        self.add_param(floatParameter(f"PWF0_{idx}", units="Hz", value=0.0))
        self.add_param(floatParameter(f"PWF1_{idx}", units="Hz/s", value=0.0))
        self.add_param(
            floatParameter(f"PWF2_{idx}", units="Hz/s^2", value=0.0)
        )
        self.add_param(floatParameter(f"PWSTART_{idx}", units="MJD"))
        self.add_param(floatParameter(f"PWSTOP_{idx}", units="MJD"))
        self.piece_indices.append(idx)

    def new_prefix_param(self, name):
        for pref in _FAMS:
            idx = prefix_index(name, pref)
            if idx is not None:
                if f"PWEP_{idx}" not in self.params:
                    self.add_piece(idx)
                return self.params[f"{pref}{idx}"]
        return None

    def setup(self, model):
        # a piece exists if ANY of its params is set, so validate can
        # report a missing PWEP/PWSTART/PWSTOP instead of silently
        # dropping the piece
        idx = set()
        for n, p in self.params.items():
            if p.value is None:
                continue
            for pref in _FAMS:
                if n.startswith(pref) and n[len(pref):].isdigit():
                    idx.add(int(n[len(pref):]))
        self.piece_indices = sorted(idx)

    def validate(self, model):
        for i in self.piece_indices:
            if self.params[f"PWEP_{i}"].value is None:
                raise MissingParameter("PiecewiseSpindown", f"PWEP_{i}")
            if (
                self.params[f"PWSTART_{i}"].value is None
                or self.params[f"PWSTOP_{i}"].value is None
            ):
                raise TimingModelError(
                    f"piecewise-spindown piece {i} missing PWSTART/PWSTOP"
                )

    def extra_masks(self, toas) -> dict:
        mjd = toas.mjd_float()
        out = {}
        for i in self.piece_indices:
            r1 = self.params[f"PWSTART_{i}"].value
            r2 = self.params[f"PWSTOP_{i}"].value
            out[f"PW_{i}"] = ((mjd >= r1) & (mjd < r2)).astype(np.float64)
        return out

    def phase_term(self, pdict, bundle, delay):
        total = jnp.zeros(bundle.ntoa)
        for i in self.piece_indices:
            day, sec = pdict[f"PWEP_{i}"]
            dt = bundle.dt_seconds(day, sec).to_float() - delay
            ph = (
                pdict[f"PWPH_{i}"]
                + pdict[f"PWF0_{i}"] * dt
                + pdict[f"PWF1_{i}"] * dt * dt / 2.0
                + pdict[f"PWF2_{i}"] * dt**3 / 6.0
            )
            total = total + bundle.masks[f"PW_{i}"] * ph
        return DD.from_float(total)
