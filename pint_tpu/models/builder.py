"""Model builder: par file -> instantiated, validated TimingModel.

Reference parity: src/pint/models/model_builder.py::ModelBuilder,
get_model, get_model_and_toas — component selection from the parameter
-> component reverse map, BINARY-line binary-wrapper choice, alias and
prefix/mask-parameter routing, UNITS check.

Selection rule: a registered component is included iff the par file
contains a parameter name that *only* that component accepts (its
"trigger" params); shared names (PX, POSEPOCH, ...) never trigger but
route fine once a component is in.  Binary wrappers are chosen solely by
the BINARY line.  SolarSystemShapiro is a default component whenever an
astrometry component is present (matching the reference's default list).
"""

from __future__ import annotations

import hashlib
import os
import threading
import warnings
from collections import OrderedDict
from typing import Union

from pint_tpu.exceptions import PintTpuError

# import the component zoo so the registry is populated
import pint_tpu.models.absolute_phase  # noqa: F401
import pint_tpu.models.astrometry  # noqa: F401
import pint_tpu.models.chromatic  # noqa: F401
import pint_tpu.models.dispersion  # noqa: F401
import pint_tpu.models.frequency_dependent  # noqa: F401
import pint_tpu.models.glitch  # noqa: F401
import pint_tpu.models.ifunc  # noqa: F401
import pint_tpu.models.jump  # noqa: F401
import pint_tpu.models.noise  # noqa: F401
import pint_tpu.models.phase_offset  # noqa: F401
import pint_tpu.models.piecewise  # noqa: F401
import pint_tpu.models.solar_wind  # noqa: F401
import pint_tpu.models.troposphere  # noqa: F401
import pint_tpu.models.wave  # noqa: F401
import pint_tpu.models.pulsar_binary  # noqa: F401
import pint_tpu.models.solar_system_shapiro  # noqa: F401
import pint_tpu.models.spindown  # noqa: F401
from pint_tpu.exceptions import TimingModelError, UnknownParameter
from pint_tpu.io.par import parse_parfile
from pint_tpu.models.astrometry import Astrometry
from pint_tpu.models.component import Component
from pint_tpu.models.pulsar_binary import PulsarBinary
from pint_tpu.models.solar_system_shapiro import SolarSystemShapiro
from pint_tpu.models.timing_model import TimingModel

# par-file lines that are not parameters
_IGNORE = {"MODE", "EPHVER", "END", "NITS", "IBOOT"}


class ModelBuilder:
    def __init__(self):
        self.registry = dict(Component.component_types)

    # -- selection --------------------------------------------------------
    def _binary_class(self, name: str):
        for cls in self.registry.values():
            if (
                issubclass(cls, PulsarBinary)
                and cls.binary_model_name.upper() == name.upper()
            ):
                return cls
        raise TimingModelError(f"unknown binary model {name!r}")

    def choose_components(self, pardict) -> list[Component]:
        nonbinary = {
            n: cls for n, cls in self.registry.items()
            if not issubclass(cls, PulsarBinary)
        }
        self._protos: dict = {}

        def acceptors(par_name):
            out = []
            for n, cls in nonbinary.items():
                proto = self._protos.setdefault(n, cls())
                if (
                    par_name in proto.mask_families()
                    or proto.ensure_param(par_name) is not None
                ):
                    out.append(n)
            return out

        chosen: set[str] = set()
        for par_name in pardict:
            if par_name in _IGNORE:
                continue
            hits = acceptors(par_name)
            if len(hits) == 1:
                chosen.add(hits[0])
        comps = [self.registry[n]() for n in sorted(chosen)]
        n_astrom = sum(isinstance(c, Astrometry) for c in comps)
        if n_astrom > 1:
            raise TimingModelError(
                "par file mixes equatorial (RAJ/DECJ) and ecliptic "
                "(ELONG/ELAT) astrometry"
            )
        if "BINARY" in pardict:
            comps.append(self._binary_class(pardict["BINARY"][0][0])())
        if n_astrom and not any(
            isinstance(c, SolarSystemShapiro) for c in comps
        ):
            comps.append(SolarSystemShapiro())
        return comps

    # -- routing ----------------------------------------------------------
    def __call__(self, par) -> TimingModel:
        pardict = parse_parfile(par)
        units = pardict.get("UNITS", [["TDB"]])[0][0].upper()
        comps = self.choose_components(pardict)
        model = TimingModel(components=comps)
        mask_counters: dict[tuple[int, str], int] = {}
        unknown = {}
        for name, entries in pardict.items():
            if name in _IGNORE:
                continue
            if self._route_top(model, name, entries):
                continue
            routed = False
            for c in model.components.values():
                fams = c.mask_families()
                if name in fams:
                    key = (id(c), name)
                    for tokens in entries:
                        mask_counters[key] = mask_counters.get(key, 0) + 1
                        p = fams[name](mask_counters[key])
                        p.set_from_tokens(tokens)
                    routed = True
                    break
                p = c.ensure_param(name)
                if p is not None:
                    if len(entries) > 1:
                        warnings.warn(
                            f"repeated par-file line {name}; using the first",
                            UserWarning,
                        )
                    p.set_from_tokens(entries[0])
                    routed = True
                    break
            if not routed:
                unknown[name] = entries
        if unknown:
            warnings.warn(
                f"unrecognized par-file parameters {sorted(unknown)}",
                UnknownParameterWarning,
            )
        model.unrecognized = unknown
        model.name = model.top_params["PSR"].value or ""
        if units == "TCB":
            from pint_tpu.models.tcb_conversion import convert_tcb_tdb

            warnings.warn(
                "UNITS TCB parfile: converting parameters to TDB "
                "(reference: tcb_conversion.convert_tcb_tdb)",
                UserWarning,
            )
            convert_tcb_tdb(model)
        model.setup()
        model.validate()
        _ingest_tzr_eagerly(model)
        return model

    @staticmethod
    def _route_top(model, name, entries) -> bool:
        for p in model.top_params.values():
            if p.name_matches(name):
                p.set_from_tokens(entries[0])
                return True
        return False


class UnknownParameterWarning(UserWarning):
    """Par-file lines no component understands (reference raises/warns via
    UnknownParameter; here the model still builds)."""


class TZRDeferredWarning(UserWarning):
    """TZR reference arrival could not be ingested at model build;
    anchoring deferred to compile() (a dedicated class so the parse
    cache can tell this ENVIRONMENT-scoped warning apart from the
    content-scoped parse warnings it replays on a hit)."""


def _ingest_tzr_eagerly(model: TimingModel) -> None:
    """Eager TZR ingest: the clock/EOP/ephemeris environment in scope
    NOW (model build or parse-cache hit) is the one the reference
    arrival must use; a later compile() elsewhere would silently
    anchor through a different chain (golden22 oracle set).  A failure
    (unresolvable TZRSITE, orbit dir unset) must NOT break parse-only
    workflows (par read-modify-write, tcb2tdb): warn and let compile()
    raise if it still can't ingest then."""
    absph = model.components.get("AbsPhase")
    if absph is None or absph.params["TZRMJD"].value is None:
        return
    try:
        absph.ingested_tzr_toas(model)
    except (PintTpuError, OSError) as e:
        # only ENVIRONMENT-resolution failures defer: unknown site,
        # missing files, malformed/incomplete data files (the SPK
        # reader raises EphemerisFormat/SegmentError, both
        # PintTpuError subclasses).  Anything else is a real ingest
        # bug and must propagate — a swallowed one would let
        # compile() anchor the phase through a different chain, the
        # golden22 bug class
        warnings.warn(
            f"TZR reference arrival could not be ingested at "
            f"model build ({e}); phase anchoring is deferred "
            "to compile() under the environment in scope then",
            TZRDeferredWarning,
        )


# -- par-text parse cache (ISSUE 9) ---------------------------------------
# get_model's ~2 ms host parse is the cold-par admission ceiling (~260
# pars/s, ROADMAP item 2 leftover).  Identical par TEXT re-admitted
# (population churn past the serving layer's ParRecords LRU, repeated
# loads in analysis scripts) hits a content-hash cache instead: the
# cache holds a pristine CLONE of the built model plus the parse-time
# warnings; a hit replays the warnings and returns a fresh clone (pure
# param-state copying, no tokenize/validate), then re-runs the eager
# TZR ingest so environment anchoring keeps build-time semantics.
# Only multi-line STRINGS cache (a path's content can change on disk;
# a file object is consumed).  The clock/EOP/ephemeris env vars join
# the key because TCB conversion and TZR deferral are env-sensitive.
_PARSE_CACHE: OrderedDict = OrderedDict()  # lint: guarded-by(_PARSE_CACHE_LOCK)
_PARSE_CACHE_LOCK = threading.Lock()
_PARSE_ENV_KEYS = (
    "PINT_TPU_CLOCK_DIR", "PINT_TPU_EOP", "PINT_TPU_EPHEM_DIR",
)


def _parse_cache_size() -> int:
    if os.environ.get("PINT_TPU_PARSE_CACHE", "1") == "0":
        return 0
    try:
        return max(
            0, int(os.environ.get("PINT_TPU_PARSE_CACHE_SIZE", "256"))
        )
    except ValueError:
        return 256


def _parse_cache_key(par):
    if not isinstance(par, str) or "\n" not in par:
        return None
    env = tuple(os.environ.get(k, "") for k in _PARSE_ENV_KEYS)
    return (hashlib.sha256(par.encode()).hexdigest(), env)


def clear_parse_cache() -> None:
    """Drop every cached parse (test isolation; env-reset hooks)."""
    with _PARSE_CACHE_LOCK:
        _PARSE_CACHE.clear()


def get_model(par) -> TimingModel:
    """par file (path, text, or file object) -> TimingModel."""
    from pint_tpu.obs import metrics as _metrics

    size = _parse_cache_size()
    key = _parse_cache_key(par) if size else None
    if key is not None:
        with _PARSE_CACHE_LOCK:
            hit = _PARSE_CACHE.get(key)
            if hit is not None:
                _PARSE_CACHE.move_to_end(key)
        if hit is not None:
            proto, unrec, caught = hit
            for w in caught:
                # replay the content-scoped parse warnings (repeated
                # lines, unknown params, TCB conversion) through the
                # caller's live filters
                warnings.warn_explicit(
                    w.message, w.category, w.filename, w.lineno
                )
            _metrics.counter("model.parse_cache_hits").inc()
            model = proto.clone()
            # clone() carries param state only: restore the builder
            # -set extras (unrecognized lines), then re-anchor TZR
            # under the environment in scope NOW, like a real build
            # (clone deliberately drops the TZR memo)
            model.unrecognized = {
                k: [list(t) for t in v] for k, v in unrec.items()
            }
            _ingest_tzr_eagerly(model)
            return model
    # exact host-parse ledger: the serving population gate pins that
    # steady-state traffic costs ZERO parses (admission is the only
    # parser; fit responses clone — tests/test_serve_population.py)
    _metrics.counter("model.parses").inc()
    if key is None:
        return ModelBuilder()(par)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        model = ModelBuilder()(par)
    kept = []
    for w in caught:
        # the deferral is ENVIRONMENT state, not par content — the hit
        # path re-runs the ingest and re-decides it fresh
        if not issubclass(w.category, TZRDeferredWarning):
            kept.append(w)
        warnings.warn_explicit(
            w.message, w.category, w.filename, w.lineno
        )
    with _PARSE_CACHE_LOCK:
        _PARSE_CACHE[key] = (
            model.clone(),
            {
                k: [list(t) for t in v]
                for k, v in model.unrecognized.items()
            },
            tuple(kept),
        )
        _PARSE_CACHE.move_to_end(key)
        while len(_PARSE_CACHE) > size:
            _PARSE_CACHE.popitem(last=False)
    return model


def get_model_and_toas(
    par, tim, ephem: str = None, planets: bool = None, **ingest_kw
):
    """Load a par/tim pair and run the full ingest pipeline (§3.1)."""
    from pint_tpu.io.tim import get_TOAs_from_tim
    from pint_tpu.toas.ingest import ingest_for_model

    model = get_model(par)
    toas = get_TOAs_from_tim(tim)
    if ephem is not None:
        ingest_kw["ephem"] = ephem
    if planets is not None:
        ingest_kw["planets"] = planets
    ingest_for_model(toas, model, **ingest_kw)
    return model, toas
