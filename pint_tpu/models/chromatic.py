"""Chromatic (nu^-alpha) delay: ChromaticCM Taylor model.

Reference parity: src/pint/models/chromatic_model.py::ChromaticCM —
delay = DM_CONST * CM(t) / f^CMIDX with f in MHz and CM in
pc cm^-3 MHz^(CMIDX-2); CM(t) a Taylor series in (t - CMEPOCH).
CMIDX=2 reduces exactly to DM dispersion; 4 models scattering.
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.constants import DM_CONST, SECS_PER_JULIAN_YEAR
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    prefix_index,
)
from pint_tpu.ops.taylor import taylor_horner


class ChromaticCM(DelayComponent):
    register = True
    category = "chromatic"

    def __init__(self, max_terms: int = 6):
        super().__init__()
        self.add_param(floatParameter("CM", units="pc/cm^3 MHz^(a-2)"))
        # the chromatic index: the reference spells it TNCHROMIDX in par
        # files (chromatic_model.py); the noise component PLChromNoise
        # reads it from here
        self.add_param(
            floatParameter("CMIDX", units="", value=4.0,
                           aliases=("TNCHROMIDX", "TNChromIdx"))
        )
        for k in range(1, max_terms + 1):
            self.add_param(
                floatParameter(
                    f"CM{k}", units=f"pc/cm^3 MHz^(a-2)/yr^{k}",
                    scale_to_internal=SECS_PER_JULIAN_YEAR ** (-k),
                )
            )
        self.add_param(MJDParameter("CMEPOCH", time_scale="tdb"))
        self.prefix_patterns = ["CM"]

    def new_prefix_param(self, name):
        k = prefix_index(name, "CM")
        if k is None or k < 1:
            return None
        if f"CM{k}" not in self.params:
            self.add_param(
                floatParameter(
                    f"CM{k}", units=f"pc/cm^3 MHz^(a-2)/yr^{k}",
                    scale_to_internal=SECS_PER_JULIAN_YEAR ** (-k),
                )
            )
        return self.params[f"CM{k}"]

    def _deriv_ks(self):
        return sorted(
            int(n[2:]) for n in self.params
            if n[2:].isdigit() and n.startswith("CM")
            and self.params[n].value is not None
        )

    def validate(self, model):
        ks = self._deriv_ks()
        if ks:
            from pint_tpu.exceptions import MissingParameter, TimingModelError

            if ks != list(range(1, ks[-1] + 1)):
                raise TimingModelError(
                    f"non-contiguous chromatic derivatives CM{ks}"
                )
            if self.params["CMEPOCH"].value is None:
                raise MissingParameter("ChromaticCM", "CMEPOCH")

    def cm_value(self, pdict, bundle):
        coeffs = [pdict["CM"]] + [pdict[f"CM{k}"] for k in self._deriv_ks()]
        if len(coeffs) == 1:
            return coeffs[0] * jnp.ones(bundle.ntoa)
        day, sec = pdict["CMEPOCH"]
        dt = bundle.dt_seconds(day, sec).to_float()
        return taylor_horner(dt, coeffs)

    def delay_term(self, pdict, bundle, acc_delay):
        if self.params["CM"].value is None:
            return jnp.zeros(bundle.ntoa)
        alpha = pdict.get("CMIDX", 4.0)
        return DM_CONST * self.cm_value(pdict, bundle) / bundle.freq_mhz**alpha
