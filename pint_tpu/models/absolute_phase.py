"""Absolute phase anchor (TZR reference TOA).

Reference parity: src/pint/models/absolute_phase.py::AbsPhase — TZRMJD/
TZRSITE/TZRFRQ define a fiducial arrival at which the model phase is
zero; photon-folding (photonphase) and polycos need this.  The TZR
"TOA" goes through the same ingest pipeline as data TOAs, then the
compiled kernel subtracts phase(TZR) from every TOA's phase.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.models.component import Component
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    strParameter,
)


class AbsPhase(Component):
    register = True
    category = "absolute_phase"

    def __init__(self):
        super().__init__()
        # TZRMJD is in the timescale of the site clock (TDB for '@')
        self.add_param(MJDParameter("TZRMJD", time_scale="utc"))
        self.add_param(strParameter("TZRSITE", value="@"))
        self.add_param(floatParameter("TZRFRQ", units="MHz"))

    def validate(self, model):
        self.require("TZRMJD")

    def make_tzr_toas(self):
        """Single-TOA TOAs object for the TZR arrival (host-side)."""
        from pint_tpu.timebase.times import TimeArray
        from pint_tpu.toas.toas import TOAs

        site = (self.params["TZRSITE"].value or "@").lower()
        frq = self.params["TZRFRQ"].value
        if frq is None:
            frq = np.inf
        t = self.params["TZRMJD"].value
        scale = "tdb" if site in ("@", "bat", "ssb", "barycenter") else "utc"
        t = TimeArray(t.mjd_int, t.sec, scale)
        return TOAs(
            t, np.array([float(frq)]), np.array([1.0]), [site], [dict()]
        )

    def _tzr_config_key(self, model):
        t = self.params["TZRMJD"].value
        ps = model.params.get("PLANET_SHAPIRO")
        return (
            int(np.asarray(t.mjd_int).ravel()[0]),
            float(np.asarray(t.sec.to_float()).ravel()[0]),
            (self.params["TZRSITE"].value or "@").lower(),
            self.params["TZRFRQ"].value,
            model.top_params["EPHEM"].value,
            (model.top_params.get("CLOCK").value
             if model.top_params.get("CLOCK") else None),
            bool(ps.value) if ps is not None else False,
        )

    def ingested_tzr_toas(self, model):
        """TZR TOAs ingested through the model's chain, memoized by the
        TZR/chain configuration (reference: get_TZR_toa's cache).
        Built EAGERLY at model construction (models/builder.py) so the
        clock/EOP/ephemeris environment in scope at build time is the
        one the reference TOA uses — a later compile() in a different
        environment would otherwise silently anchor the phase through
        a different chain (caught by the golden22 oracle set)."""
        from pint_tpu.toas.ingest import ingest_for_model

        key = self._tzr_config_key(model)
        memo = getattr(self, "_tzr_memo", None)
        if memo is None or memo[0] != key:
            toas = self.make_tzr_toas()
            ingest_for_model(toas, model)
            self._tzr_memo = (key, toas)
        return self._tzr_memo[1]
