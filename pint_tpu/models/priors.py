"""Per-parameter priors for Bayesian inference.

Reference parity: src/pint/models/priors.py::Prior + RV wrappers —
uniform/normal/bounded distributions attached to Parameters, consumed
by BayesianTiming (lnprior, prior_transform).
"""

from __future__ import annotations

import math

import numpy as np


class Prior:
    """Base prior: logpdf(x) and ppf(q) (inverse CDF for nested-sampling
    prior transforms)."""

    def logpdf(self, x):
        raise NotImplementedError

    def ppf(self, q):
        raise NotImplementedError


class UniformUnboundedRV(Prior):
    """Improper flat prior (the reference's default for fit params)."""

    def logpdf(self, x):
        return np.zeros_like(np.asarray(x, dtype=np.float64))

    def ppf(self, q):
        raise ValueError(
            "improper uniform prior has no prior transform; give the "
            "parameter bounds for nested sampling"
        )


class UniformBoundedRV(Prior):
    def __init__(self, lower: float, upper: float):
        if not upper > lower:
            raise ValueError("need upper > lower")
        self.lower, self.upper = float(lower), float(upper)
        self._logw = -math.log(upper - lower)

    def logpdf(self, x):
        x = np.asarray(x, dtype=np.float64)
        inside = (x >= self.lower) & (x <= self.upper)
        return np.where(inside, self._logw, -np.inf)

    def ppf(self, q):
        return self.lower + (self.upper - self.lower) * np.asarray(q)


class NormalRV(Prior):
    def __init__(self, mean: float, sigma: float):
        self.mean, self.sigma = float(mean), float(sigma)

    def logpdf(self, x):
        z = (np.asarray(x, dtype=np.float64) - self.mean) / self.sigma
        return -0.5 * z * z - math.log(
            self.sigma * math.sqrt(2.0 * math.pi)
        )

    def ppf(self, q):
        from scipy.stats import norm

        return self.mean + self.sigma * norm.ppf(np.asarray(q))


def default_prior(param) -> Prior:
    """Reference behavior: normal around the par-file value when an
    uncertainty exists (scaled wide), else improper uniform."""
    if param.uncertainty:
        return NormalRV(0.0, 10.0 * abs(param.internal_uncertainty()))
    return UniformUnboundedRV()
