"""Phase and delay jumps over TOA subsets.

Reference parity: src/pint/models/jump.py::PhaseJump (JUMP maskParameter
family; a JUMP of J seconds advances the emission time, i.e. subtracts
J * F0 cycles of phase for selected TOAs) and DelayJump (JUMP applied as
seconds of delay; tempo1 heritage, rarely used).  Selections become
static 0/1 mask arrays at compile time (SURVEY.md §7 hard-part #2).
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.models.component import DelayComponent, PhaseComponent
from pint_tpu.models.parameter import maskParameter
from pint_tpu.ops.dd import DD


class PhaseJump(PhaseComponent):
    register = True
    category = "phase_jump"

    def __init__(self):
        super().__init__()
        self.jump_params: list[str] = []

    def add_jump(self, idx: int) -> maskParameter:
        name = f"JUMP{idx}"
        p = self.add_param(maskParameter(name, index=idx, units="s"))
        self.jump_params.append(name)
        return p

    def mask_families(self):
        return {"JUMP": self.add_jump}

    def phase_term(self, pdict, bundle, delay):
        f0 = pdict["F0"]
        f0 = f0.to_float() if isinstance(f0, DD) else f0
        jump_s = jnp.zeros(bundle.ntoa)
        for n in self.jump_params:
            jump_s = jump_s + pdict[n] * bundle.masks[n]
        # J seconds of jump = -J*F0 cycles (delay-equivalent convention)
        return DD.from_float(-jump_s * f0)


class DelayJump(DelayComponent):
    """JUMP applied as seconds of delay (tempo1 MODE 1 convention).

    Not selected by the builder (PhaseJump takes JUMP lines, matching the
    reference default); available for explicit construction.
    """

    category = "jump_delay"

    def __init__(self):
        super().__init__()
        self.jump_params: list[str] = []

    def add_jump(self, idx: int) -> maskParameter:
        name = f"JUMP{idx}"
        p = self.add_param(maskParameter(name, index=idx, units="s"))
        self.jump_params.append(name)
        return p

    def mask_families(self):
        return {"JUMP": self.add_jump}

    def delay_term(self, pdict, bundle, acc_delay):
        d = jnp.zeros(bundle.ntoa)
        for n in self.jump_params:
            d = d + pdict[n] * bundle.masks[n]
        return d
