"""Solar-wind dispersion delay.

Reference parity: src/pint/models/solar_wind_dispersion.py::
SolarWindDispersion — spherically-symmetric 1/r^2 electron density
n(r) = NE_SW (AU/r)^2; the column density along the line of sight from
the observer through the heliosphere is

  DM_sw = NE_SW * AU^2 * (pi - theta) / (d sin(theta))

with d = |obs->Sun| and theta the Sun-observer-pulsar elongation angle
(Edwards et al. 2006 eq. 20).  Delay = DM_CONST * DM_sw / f^2.
NE_SW1.. Taylor terms in time mirror the reference's SWM extension.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.constants import AU, DM_CONST, PC, SECS_PER_JULIAN_YEAR, C
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    prefix_index,
)
from pint_tpu.ops.taylor import taylor_horner

# AU^2/pc in light-seconds: geometry arrives in light-seconds, so
# column = n0[cm^-3] * (AU_ls^2/d_ls) * angle_factor, converted to pc cm^-3
_AU_LS = AU / C
_PC_LS = PC / C


def _find_astrometry(model):
    from pint_tpu.models.astrometry import Astrometry

    for c in model.components.values():
        if isinstance(c, Astrometry):
            return c
    return None


def elongation_geometry(astrometry, pdict, bundle):
    """Sun-observer-pulsar geometry shared by NE_SW and SWX:
    -> (d, safe_d, theta, sin_t): obs-Sun distance (light-seconds; d is
    the raw value for zero-geometry guards, safe_d is clamped for
    division), elongation angle (rad), and its clamped sine."""
    psr_dir = astrometry.ssb_to_psr_xyz(pdict, bundle)
    r = bundle.obs_sun_pos_ls  # obs -> Sun, light-seconds
    d = jnp.sqrt(jnp.sum(r * r, axis=-1))
    safe_d = jnp.maximum(d, 1e-30)
    cos_e = jnp.sum(r * psr_dir, axis=-1) / safe_d
    theta = jnp.arccos(jnp.clip(cos_e, -1.0, 1.0))
    sin_t = jnp.maximum(jnp.sin(theta), 1e-9)
    return d, safe_d, theta, sin_t


class SolarWindDispersion(DelayComponent):
    register = True
    category = "solar_wind"

    def __init__(self, max_terms: int = 5):
        super().__init__()
        self.add_param(floatParameter("NE_SW", units="cm^-3", aliases=("NE1AU", "SOLARN0")))
        for k in range(1, max_terms + 1):
            self.add_param(
                floatParameter(
                    f"NE_SW{k}", units=f"cm^-3/yr^{k}",
                    scale_to_internal=SECS_PER_JULIAN_YEAR ** (-k),
                )
            )
        self.add_param(MJDParameter("SWEPOCH", time_scale="tdb"))
        self.prefix_patterns = ["NE_SW"]

    def new_prefix_param(self, name):
        k = prefix_index(name, "NE_SW")
        if k is None or k < 1:
            return None
        if f"NE_SW{k}" not in self.params:
            self.add_param(
                floatParameter(
                    f"NE_SW{k}", units=f"cm^-3/yr^{k}",
                    scale_to_internal=SECS_PER_JULIAN_YEAR ** (-k),
                )
            )
        return self.params[f"NE_SW{k}"]

    def setup(self, model):
        self._astrometry_ref = _find_astrometry(model)

    def _deriv_ks(self):
        ks = sorted(
            int(n[5:]) for n in self.params
            if n.startswith("NE_SW") and n[5:].isdigit()
            and self.params[n].value is not None
        )
        return ks

    def validate(self, model):
        from pint_tpu.exceptions import TimingModelError

        if self.params["NE_SW"].value is not None and self._astrometry_ref is None:
            raise TimingModelError(
                "SolarWindDispersion needs an astrometry component"
            )
        ks = self._deriv_ks()
        if ks:
            if ks != list(range(1, ks[-1] + 1)):
                raise TimingModelError(
                    f"non-contiguous solar-wind derivatives NE_SW{ks}"
                )
            if self.params["SWEPOCH"].value is None:
                from pint_tpu.exceptions import MissingParameter

                raise MissingParameter("SolarWindDispersion", "SWEPOCH")

    def _ne_sw(self, pdict, bundle):
        coeffs = [pdict["NE_SW"]] + [
            pdict[f"NE_SW{k}"] for k in self._deriv_ks()
        ]
        if len(coeffs) == 1:
            return coeffs[0]
        day, sec = pdict["SWEPOCH"]
        dt = bundle.dt_seconds(day, sec).to_float()
        return taylor_horner(dt, coeffs)

    def solar_wind_dm(self, pdict, bundle):
        """DM_sw at each TOA (pc/cm^3)."""
        d, safe_d, theta, sin_t = elongation_geometry(
            self._astrometry_ref, pdict, bundle
        )
        n0 = self._ne_sw(pdict, bundle)
        # column in cm^-3 * ls -> pc cm^-3 via /PC_ls
        col = n0 * _AU_LS * _AU_LS * (np.pi - theta) / (safe_d * sin_t)
        dm = col / _PC_LS
        return jnp.where(d > 0, dm, 0.0)

    def dm_value(self, pdict, bundle):
        """Wideband interface: solar-wind DM counts toward the model DM
        at each TOA (reference: SolarWindDispersion is a 'dispersion
        type' component in the wideband DM model)."""
        if self.params["NE_SW"].value is None:
            return jnp.zeros(bundle.ntoa)
        return self.solar_wind_dm(pdict, bundle)

    def delay_term(self, pdict, bundle, acc_delay):
        if self.params["NE_SW"].value is None:
            return jnp.zeros(bundle.ntoa)
        dm = self.solar_wind_dm(pdict, bundle)
        return DM_CONST * dm / jnp.square(bundle.freq_mhz)


class SolarWindDispersionX(DelayComponent):
    """Piecewise solar-wind DM amplitudes over MJD ranges (SWX).

    Reference: src/pint/models/solar_wind_dispersion.py::
    SolarWindDispersionX — per segment i with SWXR1_/SWXR2_ bounds,
    SWXDM_#### scales the normalized spherical solar-wind geometry
    profile; the fitted quantity is the segment's DM amplitude.  Here
    the profile is the n0=1 column normalized at 90-degree elongation /
    1 AU, so SWXDM is the DM the segment would produce at quadrature
    [verify normalization convention against the reference mount].
    """

    register = True
    category = "solar_wind"

    def __init__(self):
        super().__init__()
        self.swx_indices: list[int] = []
        self.prefix_patterns = ["SWXDM_", "SWXR1_", "SWXR2_"]

    def add_swx_range(self, idx: int):
        self.add_param(
            floatParameter(f"SWXDM_{idx:04d}", units="pc/cm^3", value=0.0)
        )
        self.add_param(floatParameter(f"SWXR1_{idx:04d}", units="MJD"))
        self.add_param(floatParameter(f"SWXR2_{idx:04d}", units="MJD"))
        self.swx_indices.append(idx)

    def new_prefix_param(self, name):
        for pref in ("SWXDM_", "SWXR1_", "SWXR2_"):
            idx = prefix_index(name, pref)
            if idx is not None:
                if f"SWXDM_{idx:04d}" not in self.params:
                    self.add_swx_range(idx)
                return self.params[f"{pref}{idx:04d}"]
        return None

    def setup(self, model):
        self._astrometry_ref = _find_astrometry(model)
        self.swx_indices = sorted(
            int(n[6:]) for n in self.params
            if n.startswith("SWXDM_") and self.params[n].value is not None
        )

    def validate(self, model):
        from pint_tpu.exceptions import MissingParameter, TimingModelError

        if self.swx_indices and self._astrometry_ref is None:
            raise TimingModelError("SWX needs an astrometry component")
        for i in self.swx_indices:
            if (
                self.params[f"SWXR1_{i:04d}"].value is None
                or self.params[f"SWXR2_{i:04d}"].value is None
            ):
                raise MissingParameter(
                    "SolarWindDispersionX", f"SWXR1_{i:04d}/SWXR2_{i:04d}"
                )

    def extra_masks(self, toas) -> dict:
        mjd = toas.mjd_float()
        out = {}
        for i in self.swx_indices:
            r1 = self.params[f"SWXR1_{i:04d}"].value
            r2 = self.params[f"SWXR2_{i:04d}"].value
            out[f"SWX_{i:04d}"] = ((mjd >= r1) & (mjd < r2)).astype(
                np.float64
            )
        return out

    def _profile(self, pdict, bundle):
        """Normalized geometry: 1 at 90-deg elongation, 1 AU."""
        d, safe_d, theta, sin_t = elongation_geometry(
            self._astrometry_ref, pdict, bundle
        )
        prof = (
            _AU_LS * (np.pi - theta) / (safe_d * sin_t)
        ) / (np.pi / 2.0)
        return jnp.where(d > 0, prof, 0.0)

    def dm_value(self, pdict, bundle):
        if not self.swx_indices:
            return jnp.zeros(bundle.ntoa)
        prof = self._profile(pdict, bundle)
        dm = jnp.zeros(bundle.ntoa)
        for i in self.swx_indices:
            dm = dm + (
                pdict[f"SWXDM_{i:04d}"]
                * bundle.masks[f"SWX_{i:04d}"]
                * prof
            )
        return dm

    def delay_term(self, pdict, bundle, acc_delay):
        if not self.swx_indices:
            return jnp.zeros(bundle.ntoa)
        return DM_CONST * self.dm_value(pdict, bundle) / jnp.square(
            bundle.freq_mhz
        )
