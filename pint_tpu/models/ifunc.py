"""IFunc: tabulated interpolated phase corrections.

Reference parity: src/pint/models/ifunc.py::IFunc — SIFUNC selects the
interpolation mode (0: constant/sinc [approximated as nearest], 1:
nearest, 2: linear — the common case), IFUNC1..n are (MJD, seconds)
pairs; the tabulated seconds are applied as phase via F0 like Wave.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.models.component import PhaseComponent
from pint_tpu.models.parameter import (
    floatParameter,
    pairParameter,
    prefix_index,
)
from pint_tpu.ops.dd import DD


class IFunc(PhaseComponent):
    register = True
    category = "ifunc"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("SIFUNC", value=2.0))
        self.prefix_patterns = ["IFUNC"]
        self.ifunc_indices: list[int] = []

    def new_prefix_param(self, name):
        k = prefix_index(name, "IFUNC")
        if k is None or k < 1:
            return None
        p = self.add_param(pairParameter(f"IFUNC{k}", units="MJD,s"))
        return p

    def setup(self, model):
        self.ifunc_indices = sorted(
            int(n[5:]) for n in self.params
            if n.startswith("IFUNC") and n[5:].isdigit()
            and self.params[n].value is not None
        )

    def phase_term(self, pdict, bundle, delay):
        if not self.ifunc_indices:
            return DD.zeros((bundle.ntoa,))
        nodes = np.array(
            [self.params[f"IFUNC{i}"].value for i in self.ifunc_indices]
        )
        order = np.argsort(nodes[:, 0])
        xs = jnp.asarray(nodes[order, 0])
        ys = jnp.asarray(nodes[order, 1])
        t = bundle.tdb_day + bundle.tdb_sec.to_float() / 86400.0
        mode = int(self.params["SIFUNC"].value)
        if mode == 2:
            val = jnp.interp(t, xs, ys)
        else:  # nearest (modes 0/1)
            idx = jnp.clip(
                jnp.searchsorted(xs, t), 0, xs.shape[0] - 1
            )
            left = jnp.clip(idx - 1, 0, xs.shape[0] - 1)
            use_left = jnp.abs(t - xs[left]) < jnp.abs(t - xs[idx])
            val = jnp.where(use_left, ys[left], ys[idx])
        f0 = pdict["F0"]
        f0 = f0.to_float() if isinstance(f0, DD) else f0
        return DD.from_float(-val * f0)
