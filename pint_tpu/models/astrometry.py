"""Astrometry: Roemer + parallax delays from sky position & proper motion.

Reference parity: src/pint/models/astrometry.py::AstrometryEquatorial /
AstrometryEcliptic — SSB->pulsar unit vector vs epoch (linear proper
motion), Roemer delay -r_obs.n/c, parallax delay px*(|r|^2-(r.n)^2)/2.

Internal units: angles rad, proper motions rad/s, PX rad (parallax
angle); positions arrive in the bundle in light-seconds, so delays are
plain f64 dot products (sub-ps precision at AU scales).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from pint_tpu.ops.scalarmath import cos_p, sin_p

from pint_tpu.constants import (
    AU_LIGHT_SEC,
    MAS_TO_RAD,
    OBL_J2000,
    SECS_PER_JULIAN_YEAR,
)
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import (
    AngleParameter,
    MJDParameter,
    floatParameter,
)

_MAS_YR = MAS_TO_RAD / SECS_PER_JULIAN_YEAR


class Astrometry(DelayComponent):
    category = "astrometry"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("POSEPOCH", time_scale="tdb"))
        self.add_param(
            floatParameter(
                "PX", units="mas", scale_to_internal=MAS_TO_RAD,
                description="parallax",
            )
        )

    def _dt_pos(self, pdict, bundle):
        """Seconds from POSEPOCH (f64 is ample for PM terms)."""
        if self.params["POSEPOCH"].value is not None:
            day, sec = pdict["POSEPOCH"]
        elif self.params.get("PEPOCH_FALLBACK") is not None:  # pragma: no cover
            day, sec = pdict["PEPOCH"]
        else:
            # first-TOA fallback epoch; keep traceable (the bundle may
            # be a tracer under vmap / bundle-as-argument callers)
            day, sec = bundle.tdb_day[0], 0.0
        return bundle.dt_seconds(day, sec).to_float()

    def ssb_to_psr_xyz(self, pdict, bundle):
        """Unit vector(s) SSB->pulsar at each TOA, (n,3)."""
        raise NotImplementedError

    def sky_basis(self, pdict):
        """(east, north) unit vectors on the sky at the reference position
        in ICRS xyz — the (I0, J0) basis of Kopeikin 1995 used by DDK."""
        raise NotImplementedError

    def proper_motion(self, pdict):
        """(pm_long, pm_lat) in rad/s in this component's frame
        (PMRA/PMDEC or PMELONG/PMELAT)."""
        raise NotImplementedError

    def px_rad(self, pdict):
        """Parallax in radians (0.0 if unset)."""
        if self.params["PX"].value is None:
            return 0.0
        return pdict["PX"]

    def delay_term(self, pdict, bundle, acc_delay):
        n = self.ssb_to_psr_xyz(pdict, bundle)
        r = bundle.ssb_obs_pos_ls  # light-seconds
        rn = jnp.sum(r * n, axis=-1)
        roemer = -rn
        px = pdict.get("PX")
        if px is None or self.params["PX"].value is None:
            return roemer
        r2 = jnp.sum(r * r, axis=-1)
        # parallax delay: px/(2 AU) * (|r|^2 - (r.n)^2)  [px in rad]
        plx = px / (2.0 * AU_LIGHT_SEC) * (r2 - rn * rn)
        return roemer + plx


class AstrometryEquatorial(Astrometry):
    register = True

    def __init__(self):
        super().__init__()
        self.add_param(
            AngleParameter("RAJ", units="H:M:S", aliases=("RA",), frozen=False)
        )
        self.add_param(
            AngleParameter("DECJ", units="D:M:S", aliases=("DEC",), frozen=False)
        )
        self.add_param(
            floatParameter(
                "PMRA", units="mas/yr", scale_to_internal=_MAS_YR,
                description="proper motion in RA (mu_alpha*cos(dec))",
            )
        )
        self.add_param(
            floatParameter(
                "PMDEC", units="mas/yr", scale_to_internal=_MAS_YR,
            )
        )

    def validate(self, model):
        self.require("RAJ", "DECJ")

    def ssb_to_psr_xyz(self, pdict, bundle):
        # sin_p/cos_p, NOT jnp trig: ra/dec are 0-d scalars without PM,
        # and axon's scalar transcendental path is f32-accurate — a
        # 3e-8 direction error is ~15 us of Roemer delay
        # (ops/scalarmath.py; tests/test_onchip_accuracy.py)
        dt = self._dt_pos(pdict, bundle)
        ra0, dec0 = pdict["RAJ"], pdict["DECJ"]
        pmra = pdict.get("PMRA")
        pmdec = pdict.get("PMDEC")
        dec = dec0 if pmdec is None else dec0 + pmdec * dt
        cosd = cos_p(dec)
        ra = ra0 if pmra is None else ra0 + pmra * dt / cos_p(dec0)
        return jnp.stack(
            [cos_p(ra) * cosd, sin_p(ra) * cosd, sin_p(dec)], axis=-1
        )

    def sky_basis(self, pdict):
        ra, dec = pdict["RAJ"], pdict["DECJ"]
        sr, cr = sin_p(ra), cos_p(ra)
        sd, cd = sin_p(dec), cos_p(dec)
        east = jnp.stack(
            [-sr, cr, jnp.zeros_like(cr)], axis=-1
        )
        north = jnp.stack([-cr * sd, -sr * sd, cd], axis=-1)
        return east, north

    def proper_motion(self, pdict):
        pml = pdict.get("PMRA")
        pmb = pdict.get("PMDEC")
        return (
            0.0 if pml is None else pml,
            0.0 if pmb is None else pmb,
        )


class AstrometryEcliptic(Astrometry):
    register = True

    def __init__(self):
        super().__init__()
        self.add_param(
            AngleParameter("ELONG", units="deg", aliases=("LAMBDA",), frozen=False)
        )
        self.add_param(
            AngleParameter("ELAT", units="deg", aliases=("BETA",), frozen=False)
        )
        self.add_param(
            floatParameter(
                "PMELONG", units="mas/yr", scale_to_internal=_MAS_YR,
                aliases=("PMLAMBDA",),
            )
        )
        self.add_param(
            floatParameter(
                "PMELAT", units="mas/yr", scale_to_internal=_MAS_YR,
                aliases=("PMBETA",),
            )
        )
        from pint_tpu.models.parameter import strParameter

        self.add_param(strParameter("ECL", value="IERS2010"))

    def validate(self, model):
        self.require("ELONG", "ELAT")

    def _obliquity(self):
        # IERS2010 mean obliquity at J2000 (constants.OBL_J2000);
        # reference reads data/runtime ecliptic.dat keyed by ECL
        return OBL_J2000

    def _ecl_to_equ(self, v):
        eps = self._obliquity()
        # static python-float obliquity: rotate with HOST trig (device
        # 0-d trig is f32-accurate on axon, ops/scalarmath.py)
        ce, se = math.cos(eps), math.sin(eps)
        # rotate ecliptic -> equatorial (x axis shared)
        x = v[..., 0]
        y = ce * v[..., 1] - se * v[..., 2]
        z = se * v[..., 1] + ce * v[..., 2]
        return jnp.stack([x, y, z], axis=-1)

    def ssb_to_psr_xyz(self, pdict, bundle):
        # scalar-safe trig: see AstrometryEquatorial.ssb_to_psr_xyz
        dt = self._dt_pos(pdict, bundle)
        lam0, bet0 = pdict["ELONG"], pdict["ELAT"]
        pml = pdict.get("PMELONG")
        pmb = pdict.get("PMELAT")
        bet = bet0 if pmb is None else bet0 + pmb * dt
        lam = lam0 if pml is None else lam0 + pml * dt / cos_p(bet0)
        cb = cos_p(bet)
        x_ecl = jnp.stack(
            [cos_p(lam) * cb, sin_p(lam) * cb, sin_p(bet)], axis=-1
        )
        return self._ecl_to_equ(x_ecl)

    def sky_basis(self, pdict):
        lam, bet = pdict["ELONG"], pdict["ELAT"]
        sl, cl = sin_p(lam), cos_p(lam)
        sb, cb = sin_p(bet), cos_p(bet)
        east = jnp.stack([-sl, cl, jnp.zeros_like(cl)], axis=-1)
        north = jnp.stack([-cl * sb, -sl * sb, cb], axis=-1)
        return self._ecl_to_equ(east), self._ecl_to_equ(north)

    def proper_motion(self, pdict):
        pml = pdict.get("PMELONG")
        pmb = pdict.get("PMELAT")
        return (
            0.0 if pml is None else pml,
            0.0 if pmb is None else pmb,
        )
