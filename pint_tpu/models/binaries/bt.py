"""Blandford & Teukolsky (1976) binary delay.

Reference parity: src/pint/models/stand_alone_psr_binaries/BT_model.py
(BTmodel) / tempo bnrybt.f — Keplerian Roemer + Einstein delay with the
first-order emission-time correction Delta(t-Delta) ~= Delta (1 - dDelta/dt).
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.models.binaries.kepler import kepler_solve


def bt_delay(M, nb, a1, ecc, omega, gamma):
    """BT timing delay (seconds).

    M: mean anomaly in [-pi, pi) (from DD orbit counting); nb: angular
    orbital frequency (rad/s); omega: longitude of periastron (rad);
    all inputs per-TOA f64 arrays or scalars.
    """
    u = kepler_solve(M, ecc)
    su, cu = jnp.sin(u), jnp.cos(u)
    sw, cw = jnp.sin(omega), jnp.cos(omega)
    alpha = a1 * sw
    beta = a1 * jnp.sqrt(jnp.maximum(1.0 - ecc * ecc, 0.0)) * cw
    d = alpha * (cu - ecc) + (beta + gamma) * su
    # dDelta/dt = nb (-alpha sin u + (beta+gamma) cos u)/(1 - e cos u)
    ddot = nb * (-alpha * su + (beta + gamma) * cu) / (1.0 - ecc * cu)
    return d * (1.0 - ddot)
