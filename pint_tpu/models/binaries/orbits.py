"""Orbital longitude from PB/PBDOT or FBn — DD-precise orbit counting.

Reference parity: src/pint/models/stand_alone_psr_binaries/binary_orbits.py
(OrbitPB, OrbitFBX) — the number of elapsed orbits since the epoch, its
fractional part (orbital phase), and the instantaneous orbital angular
frequency.  Precision: dt spans ~1e9 s and PB ~1e4-1e6 s, so the orbit
count reaches ~1e5; computing it in DD keeps the *fractional* orbit exact
to ~1e-16, i.e. sub-ps in the Roemer delay.  The trig arguments that
kernels actually consume are the small fractional phase — TPU-friendly
f64 after the DD split.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from pint_tpu.ops.dd import DD
from pint_tpu.ops.taylor import (
    taylor_horner_deriv,
    taylor_horner_dd,
)

TWOPI = 2.0 * math.pi


def orbits_pb(dt: DD, pb, pbdot=0.0, xpbdot=0.0) -> DD:
    """Elapsed orbits since epoch, PB parameterization.

    orbits = dt/PB - (PBDOT+XPBDOT)/2 * (dt/PB)^2; pb may be DD.
    """
    nbdt = dt / pb
    corr = pbdot + xpbdot
    if isinstance(corr, DD):
        corr = corr.to_float()
    return nbdt - (nbdt * nbdt) * (0.5 * corr)


def orbits_fb(dt: DD, fbs) -> DD:
    """Elapsed orbits from orbital-frequency Taylor series FB0, FB1, ...

    orbits = sum_i FBi dt^{i+1} / (i+1)!  (factorial convention matching
    the reference's taylor_horner use in OrbitFBX).
    """
    return taylor_horner_dd(dt, [0.0, *fbs])


def phase_from_orbits(orbits: DD):
    """-> (phi, norbit): orbital longitude phi = 2*pi*frac in [-pi, pi)
    and the integer orbit count (f64)."""
    norbit, frac = orbits.split_int_frac()
    return TWOPI * frac, norbit


def nb_pb(dt_f, pb, pbdot=0.0, xpbdot=0.0):
    """Instantaneous orbital angular frequency 2*pi*d(orbits)/dt, f64."""
    pb = pb.to_float() if isinstance(pb, DD) else pb
    corr = pbdot + xpbdot
    if isinstance(corr, DD):
        corr = corr.to_float()
    return TWOPI * (1.0 / pb - corr * dt_f / (pb * pb))


def nb_fb(dt_f, fbs):
    fbs = [f.to_float() if isinstance(f, DD) else f for f in fbs]
    return TWOPI * taylor_horner_deriv(dt_f, [0.0, *fbs], 1)
