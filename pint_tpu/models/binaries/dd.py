"""Damour & Deruelle (1986) binary delays.

Reference parity: src/pint/models/stand_alone_psr_binaries/DD_model.py
(DDmodel) / tempo2 DDmodel — Roemer with per-orbit periastron advance
omega = OM + k*Ae(u) (k = OMDOT/n), relativistic deformations er/eth,
Einstein gamma sin(u) folded into the inverse-timing expansion,
Shapiro log delay, and aberration A0/B0 terms.

The inverse timing formula (DD paper eq. 46-52 as implemented by the
reference's delayInverse):

  D = Dre (1 - nhat Drep + (nhat Drep)^2 + 1/2 nhat^2 Dre Drepp
           - 1/2 e sin(u)/(1-e cos(u)) nhat^2 Dre Drep)
  nhat = nb/(1 - e cos u)
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from pint_tpu.models.binaries.kepler import kepler_solve, true_anomaly

TWOPI = 2.0 * math.pi


def dd_delay(
    M, norbit, nb, a1, ecc, om0, k,
    gamma=0.0, m2r=0.0, sini=0.0, dr=0.0, dth=0.0, a0=0.0, b0=0.0,
    use_shapiro=True,
):
    """DD timing delay (seconds).

    M: mean anomaly in [-pi, pi); norbit: integer orbit count since T0
    (for the cumulative true anomaly feeding the periastron advance);
    nb: angular orbital frequency; k = OMDOT/n (dimensionless periastron
    advance per radian of true anomaly); m2r = TSUN*M2 (sec).
    """
    u = kepler_solve(M, ecc)
    su, cu = jnp.sin(u), jnp.cos(u)
    nu = true_anomaly(u, ecc)
    nu_cum = nu + TWOPI * norbit
    omega = om0 + k * nu_cum
    sw, cw = jnp.sin(omega), jnp.cos(omega)
    er = ecc * (1.0 + dr)
    eth = ecc * (1.0 + dth)
    alpha = a1 * sw
    beta = a1 * jnp.sqrt(jnp.maximum(1.0 - eth * eth, 0.0)) * cw
    dre = alpha * (cu - er) + (beta + gamma) * su
    drep = -alpha * su + (beta + gamma) * cu
    drepp = -alpha * cu - (beta + gamma) * su
    onemecu = 1.0 - ecc * cu
    anhat = nb / onemecu
    nd = anhat * drep
    d = dre * (
        1.0 - nd + nd * nd
        + 0.5 * anhat * anhat * dre * drepp
        - 0.5 * ecc * su / onemecu * anhat * anhat * dre * drep
    )
    if use_shapiro:
        brace = onemecu - sini * (
            sw * (cu - ecc)
            + jnp.sqrt(jnp.maximum(1.0 - ecc * ecc, 0.0)) * cw * su
        )
        d = d - 2.0 * m2r * jnp.log(jnp.maximum(brace, 1e-30))
    # aberration (A0/B0, almost always zero)
    d = d + a0 * (jnp.sin(omega + nu) + ecc * sw) + b0 * (
        jnp.cos(omega + nu) + ecc * cw
    )
    return d


def gr_pk_params(pb_s, ecc, a1, mtot_s, m2_s):
    """GR post-Keplerian parameters from masses (DDGR).

    Reference parity: stand_alone_psr_binaries/DDGR_model.py — all mass
    quantities in seconds (GM/c^3); returns dict of omdot_k, gamma,
    pbdot, dr, dth, sini.
    """
    n = TWOPI / pb_s
    m1 = mtot_s - m2_s
    mn23 = (mtot_s * n) ** (2.0 / 3.0)
    e2 = ecc * ecc
    k = 3.0 * mn23 / (1.0 - e2)  # dimensionless: omdot = k*n
    gamma = ecc / n * mn23 * m2_s * (m1 + 2.0 * m2_s) / (mtot_s * mtot_s)
    pbdot = (
        -192.0 * math.pi / 5.0
        * (n * mtot_s) ** (5.0 / 3.0)
        * (m1 * m2_s / (mtot_s * mtot_s))
        * (1.0 + (73.0 / 24.0) * e2 + (37.0 / 96.0) * e2 * e2)
        * (1.0 - e2) ** (-3.5)
    )
    dr = (3.0 * m1 * m1 + 6.0 * m1 * m2_s + 2.0 * m2_s * m2_s) / (
        mtot_s * mtot_s
    ) * mn23
    dth = (3.5 * m1 * m1 + 6.0 * m1 * m2_s + 2.0 * m2_s * m2_s) / (
        mtot_s * mtot_s
    ) * mn23
    # x = (m2/M) (M/n^2)^(1/3) sin i  =>  sin i = x n^(2/3) M^(2/3) / m2
    sini = a1 * n ** (2.0 / 3.0) * mtot_s ** (2.0 / 3.0) / m2_s
    return {
        "k": k, "gamma": gamma, "pbdot": pbdot,
        "dr": dr, "dth": dth, "sini": sini,
    }
