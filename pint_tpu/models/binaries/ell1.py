"""ELL1-family binary delays (small-eccentricity expansion).

Reference parity: src/pint/models/stand_alone_psr_binaries/ELL1_model.py
(ELL1model, ELL1Hmodel) and ELL1k_model.py — Lange et al. 2001 expansion
of the Roemer delay to first order in eccentricity, the tempo2-style
emission-time (inverse-timing) correction, and Shapiro delay in either
(M2, SINI) or orthometric (H3, H4/STIG; Freire & Wex 2010)
parameterization.

All functions are pure f64 jnp kernels of the orbital longitude
``phi`` (already DD-extracted, see binaries/orbits.py) and scalar
parameters in internal units (seconds, radians, dimensionless).
"""

from __future__ import annotations

import jax.numpy as jnp


def roemer_terms(phi, a1, eps1, eps2):
    """ELL1 Roemer delay and its first two phi-derivatives.

    Dre  = a1 [ sin(phi) + (eps2/2) sin(2 phi) - (eps1/2) cos(2 phi) ]
    (first order in e; constant -3/2 eps1 term absorbed into TASC,
    matching tempo2/reference convention).
    """
    s, c = jnp.sin(phi), jnp.cos(phi)
    s2, c2 = jnp.sin(2.0 * phi), jnp.cos(2.0 * phi)
    dre = a1 * (s + 0.5 * (eps2 * s2 - eps1 * c2))
    drep = a1 * (c + eps2 * c2 + eps1 * s2)
    drepp = a1 * (-s + 2.0 * (eps1 * c2 - eps2 * s2))
    return dre, drep, drepp


def inverse_timing(dre, drep, drepp, nb):
    """Emission-time correction: the delay must be evaluated at
    t_em = t - Delta; expanding Delta(t - Delta) to second order
    (reference: ELL1model.delayR / tempo2 ELL1model.C):

      Dre' = Dre (1 - nb Drep + (nb Drep)^2 + 1/2 nb^2 Dre Drepp)
    """
    nbdrep = nb * drep
    return dre * (1.0 - nbdrep + nbdrep * nbdrep + 0.5 * nb * nb * dre * drepp)


def shapiro_ms(phi, m2_tsun, sini):
    """Shapiro delay -2 r ln(1 - s sin phi); r = TSUN*M2 passed in
    seconds (m2_tsun)."""
    arg = 1.0 - sini * jnp.sin(phi)
    return -2.0 * m2_tsun * jnp.log(jnp.maximum(arg, 1e-30))


def shapiro_h3_stig(phi, h3, stig):
    """Orthometric Shapiro (Freire & Wex 2010): exact resummation with
    r = h3/stig^3, s = 2 stig/(1+stig^2)."""
    r = h3 / (stig * stig * stig)
    s = 2.0 * stig / (1.0 + stig * stig)
    return shapiro_ms(phi, r, s)


def shapiro_h3_only(phi, h3):
    """H3-only approximation: keep just the third harmonic,
    Delta_S ~= -(4/3) h3 sin(3 phi)  (Freire & Wex 2010 eq. 19)."""
    return -(4.0 / 3.0) * h3 * jnp.sin(3.0 * phi)


def eps_at_t(dt_f, eps1, eps2, eps1dot=0.0, eps2dot=0.0):
    """Linear-in-time Laplace-Lagrange parameters (ELL1)."""
    return eps1 + eps1dot * dt_f, eps2 + eps2dot * dt_f


def eps_at_t_k(dt_f, eps1_0, eps2_0, omdot=0.0, lnedot=0.0):
    """ELL1k variant (Susobhanan et al. 2018): explicit periastron
    advance OMDOT (rad/s) and fractional eccentricity-rate LNEDOT (1/s):

      e(t) = e0 (1 + lnedot dt);  omega(t) = omega0 + omdot dt
    """
    from pint_tpu.ops.scalarmath import arctan2_p

    # 0-d arctan2 is f32-accurate on axon (ops/scalarmath.py)
    om0 = arctan2_p(eps1_0, eps2_0)
    e0 = jnp.sqrt(eps1_0 * eps1_0 + eps2_0 * eps2_0)
    e = e0 * (1.0 + lnedot * dt_f)
    om = om0 + omdot * dt_f
    return e * jnp.sin(om), e * jnp.cos(om)
