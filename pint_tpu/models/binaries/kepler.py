"""Kepler-equation solve and anomaly conversions — jit/vmap-safe.

Reference parity: the Newton iteration in
src/pint/models/stand_alone_psr_binaries/BT_model.py / DD_model.py
(compute_eccentric_anomaly).  Here the iteration count is FIXED
(SURVEY.md §7 hard-part #5): Newton converges quadratically from
E0 = M + e sin M, so 8 iterations reach f64 machine precision for any
e < 0.97 — no data-dependent control flow, so XLA unrolls straight-line
code that fuses and vmaps.
"""

from __future__ import annotations

import jax.numpy as jnp


def kepler_solve(M, ecc, iters: int = 8):
    """Eccentric anomaly u solving u - e sin(u) = M (M in [-pi, pi))."""
    u = M + ecc * jnp.sin(M)
    for _ in range(iters):
        u = u - (u - ecc * jnp.sin(u) - M) / (1.0 - ecc * jnp.cos(u))
    return u


def true_anomaly(u, ecc):
    """True anomaly nu from eccentric anomaly u (same branch as u)."""
    return 2.0 * jnp.arctan2(
        jnp.sqrt(1.0 + ecc) * jnp.sin(0.5 * u),
        jnp.sqrt(1.0 - ecc) * jnp.cos(0.5 * u),
    )
