"""Binary orbital-delay kernels (the stand-alone-model analogue).

Reference parity: src/pint/models/stand_alone_psr_binaries/ — the
unit-free orbital math, separated from the parameter-marshalling wrapper
components in pint_tpu.models.pulsar_binary.  Everything here is pure
jnp/DD kernel code: trace-safe, vmap-safe, differentiable (the design
matrix is jax.jacfwd of these kernels; no hand-written d_X_d_par chain).
"""
