"""Binary wrapper components: parameters -> stand-alone delay kernels.

Reference parity: src/pint/models/pulsar_binary.py::PulsarBinary plus the
per-model wrappers (binary_ell1.py, binary_bt.py, binary_dd.py, ...).
The wrapper owns the Parameter zoo (units, aliases, tempo scaling
conventions) and marshals internal-unit scalars into the pure kernels in
pint_tpu.models.binaries; derivatives come from jax.jacfwd of the whole
phase kernel, so no per-parameter derivative plumbing exists here.

Internal units: seconds (PB, GAMMA, H3/H4), light-seconds (A1),
radians (OM), rad/s (OMDOT), dimensionless (ECC, EPS1/2, SINI, PBDOT).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from pint_tpu.constants import SECS_PER_DAY, SECS_PER_JULIAN_YEAR, TSUN
from pint_tpu.exceptions import TimingModelError
from pint_tpu.models.binaries import ell1 as _ell1
from pint_tpu.models.binaries.orbits import (
    nb_fb,
    nb_pb,
    orbits_fb,
    orbits_pb,
    phase_from_orbits,
)
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
)
from pint_tpu.ops.dd import DD

_DEG = math.pi / 180.0
_DEG_PER_YEAR = _DEG / SECS_PER_JULIAN_YEAR


class PulsarBinary(DelayComponent):
    """Base class: Keplerian + common post-Keplerian parameters."""

    category = "pulsar_system"
    binary_model_name: str = ""
    epoch_param = "T0"

    def __init__(self, max_fb: int = 12):
        super().__init__()
        self.add_param(
            floatParameter(
                "PB", units="d", long_double=True,
                scale_to_internal=SECS_PER_DAY,
                description="orbital period",
            )
        )
        self.add_param(
            floatParameter("PBDOT", units="s/s", unit_scale=True)
        )
        self.add_param(
            floatParameter("XPBDOT", units="s/s", unit_scale=True)
        )
        self.add_param(
            floatParameter(
                "A1", units="ls", aliases=("X",),
                description="projected semi-major axis",
            )
        )
        self.add_param(
            floatParameter(
                "A1DOT", units="ls/s", aliases=("XDOT",), unit_scale=True
            )
        )
        self.add_param(MJDParameter("T0", time_scale="tdb"))
        self.add_param(floatParameter("ECC", units="", aliases=("E",)))
        self.add_param(floatParameter("EDOT", units="1/s", unit_scale=True))
        self.add_param(
            floatParameter("OM", units="deg", scale_to_internal=_DEG)
        )
        self.add_param(
            floatParameter(
                "OMDOT", units="deg/yr", scale_to_internal=_DEG_PER_YEAR
            )
        )
        self.add_param(floatParameter("M2", units="Msun"))
        self.add_param(floatParameter("SINI", units=""))
        self.add_param(floatParameter("GAMMA", units="s"))
        # FBn: orbital-frequency Taylor series alternative to PB
        self.add_param(
            floatParameter("FB0", units="1/s", long_double=True,
                           aliases=("FB",))
        )
        for k in range(1, max_fb + 1):
            self.add_param(floatParameter(f"FB{k}", units=f"1/s^{k + 1}"))
        self.prefix_patterns = ["FB"]

    def new_prefix_param(self, name):
        from pint_tpu.models.parameter import prefix_index

        k = prefix_index(name, "FB")
        if k is None:
            return None
        return self.add_param(floatParameter(f"FB{k}", units=f"1/s^{k + 1}"))

    # -- shared marshalling ----------------------------------------------
    def val(self, pdict, name, default=0.0):
        v = pdict.get(name)
        if v is None:
            return default
        return v.to_float() if isinstance(v, DD) else v

    def _use_fb(self):
        return self.params["FB0"].value is not None

    def _fb_list(self, pdict):
        out = [pdict["FB0"]]
        k = 1
        while self.params.get(f"FB{k}") is not None and \
                self.params[f"FB{k}"].value is not None:
            out.append(pdict[f"FB{k}"])
            k += 1
        return out

    def _dt(self, pdict, bundle, acc_delay) -> DD:
        day, sec = pdict[self.epoch_param]
        return bundle.dt_seconds(day, sec) - acc_delay

    def _orbits(self, pdict, dt: DD) -> DD:
        if self._use_fb():
            return orbits_fb(dt, self._fb_list(pdict))
        return orbits_pb(
            dt, pdict["PB"], self.val(pdict, "PBDOT"),
            self.val(pdict, "XPBDOT"),
        )

    def _nb(self, pdict, dt_f):
        if self._use_fb():
            return nb_fb(dt_f, self._fb_list(pdict))
        return nb_pb(
            dt_f, pdict["PB"], self.val(pdict, "PBDOT"),
            self.val(pdict, "XPBDOT"),
        )

    def _a1(self, pdict, dt_f):
        return self.val(pdict, "A1") + self.val(pdict, "A1DOT") * dt_f

    def validate(self, model):
        if not self._use_fb():
            self.require("PB")
        self.require("A1", self.epoch_param)

    def delay_term(self, pdict, bundle, acc_delay):
        dt = self._dt(pdict, bundle, acc_delay)
        return self._binary_delay(pdict, dt)

    def _binary_delay(self, pdict, dt: DD):
        raise NotImplementedError


class BinaryELL1(PulsarBinary):
    """Lange et al. 2001 small-eccentricity model.

    Reference: models/binary_ell1.py::BinaryELL1 +
    stand_alone_psr_binaries/ELL1_model.py.
    """

    register = True
    binary_model_name = "ELL1"
    epoch_param = "TASC"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("TASC", time_scale="tdb"))
        self.add_param(floatParameter("EPS1", units="", value=None))
        self.add_param(floatParameter("EPS2", units="", value=None))
        self.add_param(floatParameter("EPS1DOT", units="1/s", unit_scale=True))
        self.add_param(floatParameter("EPS2DOT", units="1/s", unit_scale=True))
        # ELL1 does not use T0/ECC/OM directly
        for n in ("T0", "ECC", "EDOT", "OM", "OMDOT", "GAMMA"):
            self.remove_param(n)

    def validate(self, model):
        super().validate(model)
        self.require("EPS1", "EPS2")

    def _eps(self, pdict, dt_f):
        return _ell1.eps_at_t(
            dt_f, self.val(pdict, "EPS1"), self.val(pdict, "EPS2"),
            self.val(pdict, "EPS1DOT"), self.val(pdict, "EPS2DOT"),
        )

    def _shapiro(self, pdict, phi):
        if (
            self.params["M2"].value is not None
            and self.params["SINI"].value is not None
        ):
            return _ell1.shapiro_ms(
                phi, TSUN * self.val(pdict, "M2"), self.val(pdict, "SINI")
            )
        return 0.0

    def _binary_delay(self, pdict, dt: DD):
        dt_f = dt.to_float()
        phi, _ = phase_from_orbits(self._orbits(pdict, dt))
        nb = self._nb(pdict, dt_f)
        eps1, eps2 = self._eps(pdict, dt_f)
        a1 = self._a1(pdict, dt_f)
        dre, drep, drepp = _ell1.roemer_terms(phi, a1, eps1, eps2)
        d = _ell1.inverse_timing(dre, drep, drepp, nb)
        return d + self._shapiro(pdict, phi)


class BinaryELL1H(BinaryELL1):
    """ELL1 with orthometric Shapiro parameters (Freire & Wex 2010).

    Reference: models/binary_ell1.py::BinaryELL1H /
    ELL1H_model.ELL1Hmodel.
    """

    register = True
    binary_model_name = "ELL1H"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("H3", units="s"))
        self.add_param(floatParameter("H4", units="s"))
        self.add_param(floatParameter("STIGMA", units="", aliases=("STIG", "VARSIGMA")))
        self.add_param(floatParameter("NHARM", units=""))
        for n in ("M2", "SINI"):
            self.remove_param(n)

    def validate(self, model):
        super().validate(model)
        self.require("H3")

    def _shapiro(self, pdict, phi):
        h3 = self.val(pdict, "H3")
        if self.params["STIGMA"].value is not None:
            return _ell1.shapiro_h3_stig(phi, h3, self.val(pdict, "STIGMA"))
        if self.params["H4"].value is not None:
            stig = self.val(pdict, "H4") / h3
            return _ell1.shapiro_h3_stig(phi, h3, stig)
        return _ell1.shapiro_h3_only(phi, h3)


class BinaryELL1k(BinaryELL1):
    """ELL1 variant with explicit OMDOT/LNEDOT (Susobhanan et al. 2018).

    Reference: models/binary_ell1.py::BinaryELL1k / ELL1k_model.py.
    """

    register = True
    binary_model_name = "ELL1K"

    def __init__(self):
        super().__init__()
        self.add_param(
            floatParameter(
                "OMDOT", units="deg/yr", scale_to_internal=_DEG_PER_YEAR
            )
        )
        self.add_param(floatParameter("LNEDOT", units="1/s", unit_scale=True))
        for n in ("EPS1DOT", "EPS2DOT"):
            self.remove_param(n)

    def _eps(self, pdict, dt_f):
        return _ell1.eps_at_t_k(
            dt_f, self.val(pdict, "EPS1"), self.val(pdict, "EPS2"),
            self.val(pdict, "OMDOT"), self.val(pdict, "LNEDOT"),
        )
