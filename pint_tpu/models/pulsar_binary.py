"""Binary wrapper components: parameters -> stand-alone delay kernels.

Reference parity: src/pint/models/pulsar_binary.py::PulsarBinary plus the
per-model wrappers (binary_ell1.py, binary_bt.py, binary_dd.py, ...).
The wrapper owns the Parameter zoo (units, aliases, tempo scaling
conventions) and marshals internal-unit scalars into the pure kernels in
pint_tpu.models.binaries; derivatives come from jax.jacfwd of the whole
phase kernel, so no per-parameter derivative plumbing exists here.

Internal units: seconds (PB, GAMMA, H3/H4), light-seconds (A1),
radians (OM), rad/s (OMDOT), dimensionless (ECC, EPS1/2, SINI, PBDOT).
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from pint_tpu.constants import SECS_PER_DAY, SECS_PER_JULIAN_YEAR, TSUN
from pint_tpu.exceptions import TimingModelError
from pint_tpu.models.binaries import ell1 as _ell1
from pint_tpu.models.binaries.orbits import (
    nb_fb,
    nb_pb,
    orbits_fb,
    orbits_pb,
    phase_from_orbits,
)
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
)
from pint_tpu.ops.dd import DD
from pint_tpu.ops.scalarmath import cos_p, exp_p, sin_p

_DEG = math.pi / 180.0
_DEG_PER_YEAR = _DEG / SECS_PER_JULIAN_YEAR


class PulsarBinary(DelayComponent):
    """Base class: Keplerian + common post-Keplerian parameters."""

    category = "pulsar_system"
    binary_model_name: str = ""
    epoch_param = "T0"

    def __init__(self, max_fb: int = 12):
        super().__init__()
        self.add_param(
            floatParameter(
                "PB", units="d", long_double=True,
                scale_to_internal=SECS_PER_DAY,
                description="orbital period",
            )
        )
        self.add_param(
            floatParameter("PBDOT", units="s/s", unit_scale=True)
        )
        self.add_param(
            floatParameter("XPBDOT", units="s/s", unit_scale=True)
        )
        self.add_param(
            floatParameter(
                "A1", units="ls", aliases=("X",),
                description="projected semi-major axis",
            )
        )
        self.add_param(
            floatParameter(
                "A1DOT", units="ls/s", aliases=("XDOT",), unit_scale=True
            )
        )
        self.add_param(MJDParameter("T0", time_scale="tdb"))
        self.add_param(floatParameter("ECC", units="", aliases=("E",)))
        self.add_param(floatParameter("EDOT", units="1/s", unit_scale=True))
        self.add_param(
            floatParameter("OM", units="deg", scale_to_internal=_DEG)
        )
        self.add_param(
            floatParameter(
                "OMDOT", units="deg/yr", scale_to_internal=_DEG_PER_YEAR
            )
        )
        self.add_param(floatParameter("M2", units="Msun"))
        self.add_param(floatParameter("SINI", units=""))
        self.add_param(floatParameter("GAMMA", units="s"))
        # FBn: orbital-frequency Taylor series alternative to PB
        self.add_param(
            floatParameter("FB0", units="1/s", long_double=True,
                           aliases=("FB",))
        )
        for k in range(1, max_fb + 1):
            self.add_param(floatParameter(f"FB{k}", units=f"1/s^{k + 1}"))
        self.prefix_patterns = ["FB"]

    def new_prefix_param(self, name):
        from pint_tpu.models.parameter import prefix_index

        k = prefix_index(name, "FB")
        if k is None:
            return None
        return self.add_param(floatParameter(f"FB{k}", units=f"1/s^{k + 1}"))

    # -- shared marshalling ----------------------------------------------
    def val(self, pdict, name, default=0.0):
        v = pdict.get(name)
        if v is None:
            return default
        return v.to_float() if isinstance(v, DD) else v

    def _use_fb(self):
        return self.params["FB0"].value is not None

    def _fb_list(self, pdict):
        out = [pdict["FB0"]]
        k = 1
        while self.params.get(f"FB{k}") is not None and \
                self.params[f"FB{k}"].value is not None:
            out.append(pdict[f"FB{k}"])
            k += 1
        return out

    def _dt(self, pdict, bundle, acc_delay) -> DD:
        day, sec = pdict[self.epoch_param]
        return bundle.dt_seconds(day, sec) - acc_delay

    def _orbits(self, pdict, dt: DD) -> DD:
        if self._use_fb():
            return orbits_fb(dt, self._fb_list(pdict))
        return orbits_pb(
            dt, pdict["PB"], self.val(pdict, "PBDOT"),
            self.val(pdict, "XPBDOT"),
        )

    def _nb(self, pdict, dt_f):
        if self._use_fb():
            return nb_fb(dt_f, self._fb_list(pdict))
        return nb_pb(
            dt_f, pdict["PB"], self.val(pdict, "PBDOT"),
            self.val(pdict, "XPBDOT"),
        )

    def _a1(self, pdict, dt_f):
        return self.val(pdict, "A1") + self.val(pdict, "A1DOT") * dt_f

    def validate(self, model):
        if not self._use_fb():
            self.require("PB")
        self.require("A1", self.epoch_param)

    def delay_term(self, pdict, bundle, acc_delay):
        dt = self._dt(pdict, bundle, acc_delay)
        return self._binary_delay(pdict, bundle, dt)

    def _binary_delay(self, pdict, bundle, dt: DD):
        raise NotImplementedError


class BinaryBT(PulsarBinary):
    """Blandford & Teukolsky (1976) model.

    Reference: models/binary_bt.py::BinaryBT / BT_model.py.
    """

    register = True
    binary_model_name = "BT"

    def _ecc(self, pdict, dt_f):
        return self.val(pdict, "ECC") + self.val(pdict, "EDOT") * dt_f

    def _om(self, pdict, dt_f):
        # BT: linear-in-time periastron advance
        return self.val(pdict, "OM") + self.val(pdict, "OMDOT") * dt_f

    def _binary_delay(self, pdict, bundle, dt: DD):
        from pint_tpu.models.binaries.bt import bt_delay

        dt_f = dt.to_float()
        M, _ = phase_from_orbits(self._orbits(pdict, dt))
        nb = self._nb(pdict, dt_f)
        return bt_delay(
            M, nb, self._a1(pdict, dt_f), self._ecc(pdict, dt_f),
            self._om(pdict, dt_f), self.val(pdict, "GAMMA"),
        )


class BinaryDD(PulsarBinary):
    """Damour & Deruelle (1986) quasi-relativistic model.

    Reference: models/binary_dd.py::BinaryDD / DD_model.py.
    """

    register = True
    binary_model_name = "DD"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("DR", units=""))
        self.add_param(floatParameter("DTH", units="", aliases=("DTHETA",)))
        self.add_param(floatParameter("A0", units="s"))
        self.add_param(floatParameter("B0", units="s"))

    def _nb0(self, pdict):
        """Reference orbital angular frequency n (rad/s) for k=OMDOT/n."""
        if self._use_fb():
            fb0 = pdict["FB0"]
            return 2.0 * math.pi * (
                fb0.to_float() if isinstance(fb0, DD) else fb0
            )
        pb = pdict["PB"]
        return 2.0 * math.pi / (pb.to_float() if isinstance(pb, DD) else pb)

    def _ecc(self, pdict, dt_f):
        return self.val(pdict, "ECC") + self.val(pdict, "EDOT") * dt_f

    def _pk(self, pdict, dt_f):
        """Post-Keplerian ingredients (overridden by DDS/DDGR)."""
        return {
            "k": self.val(pdict, "OMDOT") / self._nb0(pdict),
            "gamma": self.val(pdict, "GAMMA"),
            "m2r": TSUN * self.val(pdict, "M2"),
            "sini": self.val(pdict, "SINI"),
            "dr": self.val(pdict, "DR"),
            "dth": self.val(pdict, "DTH"),
        }

    def _binary_delay(self, pdict, bundle, dt: DD):
        from pint_tpu.models.binaries.dd import dd_delay

        dt_f = dt.to_float()
        M, norb = phase_from_orbits(self._orbits(pdict, dt))
        nb = self._nb(pdict, dt_f)
        pk = self._pk(pdict, dt_f)
        return dd_delay(
            M, norb, nb, self._a1(pdict, dt_f), self._ecc(pdict, dt_f),
            self.val(pdict, "OM"), pk["k"], gamma=pk["gamma"],
            m2r=pk["m2r"], sini=pk["sini"], dr=pk["dr"], dth=pk["dth"],
            a0=self.val(pdict, "A0"), b0=self.val(pdict, "B0"),
        )


class BinaryDDH(BinaryDD):
    """DD with orthometric Shapiro parameters (H3, STIGMA) per
    Freire & Wex 2010 — for systems where M2/SINI are strongly
    covariant.

    Reference: models/binary_dd.py::BinaryDDH / DDH_model.py:
    r = H3/STIGMA^3, s = 2 STIGMA/(1 + STIGMA^2).
    """

    register = True
    binary_model_name = "DDH"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("H3", units="s"))
        self.add_param(
            floatParameter("STIGMA", units="", aliases=("STIG", "VARSIGMA"))
        )
        for n in ("M2", "SINI"):
            self.remove_param(n)

    def validate(self, model):
        super().validate(model)
        self.require("H3", "STIGMA")
        stig = float(self.params["STIGMA"].value)
        if not 0.0 < stig < 1.0:
            raise TimingModelError(
                f"DDH needs 0 < STIGMA < 1 (got {stig}): "
                "stigma = sini/(1+cosi) and m2r = H3/STIGMA^3"
            )

    def _pk(self, pdict, dt_f):
        pk = super()._pk(pdict, dt_f)
        h3 = self.val(pdict, "H3")
        stig = self.val(pdict, "STIGMA")
        pk["m2r"] = h3 / (stig * stig * stig)
        pk["sini"] = 2.0 * stig / (1.0 + stig * stig)
        return pk


class BinaryBTPiecewise(BinaryBT):
    """BT with piecewise-constant T0 / A1 over MJD ranges.

    Reference: models/binary_bt_piecewise.py::BinaryBTPiecewise /
    BT_piecewise.py — per range i, T0X_#### and/or A1X_#### replace the
    global T0/A1 for TOAs with XR1_#### <= MJD < XR2_####.  Range
    membership is static per TOA (depends only on TOA epochs), so the
    pieces become 0/1 mask arrays at compile time.
    """

    register = True
    binary_model_name = "BT_PIECEWISE"

    def __init__(self):
        super().__init__()
        self.piece_indices: list[int] = []
        self.prefix_patterns = list(self.prefix_patterns) + [
            "T0X_", "A1X_", "XR1_", "XR2_"
        ]

    def add_piece(self, idx: int):
        self.add_param(MJDParameter(f"T0X_{idx:04d}", time_scale="tdb"))
        self.add_param(floatParameter(f"A1X_{idx:04d}", units="ls"))
        self.add_param(floatParameter(f"XR1_{idx:04d}", units="MJD"))
        self.add_param(floatParameter(f"XR2_{idx:04d}", units="MJD"))
        self.piece_indices.append(idx)

    def new_prefix_param(self, name):
        from pint_tpu.models.parameter import prefix_index

        for pref in ("T0X_", "A1X_", "XR1_", "XR2_"):
            idx = prefix_index(name, pref)
            if idx is not None:
                if f"XR1_{idx:04d}" not in self.params:
                    self.add_piece(idx)
                return self.params[f"{pref}{idx:04d}"]
        return super().new_prefix_param(name)

    def setup(self, model):
        super().setup(model)
        # a piece exists if ANY of its parameters is set, so validate can
        # catch missing range bounds instead of silently dropping pieces
        idx = set()
        for n, p in self.params.items():
            if p.value is None:
                continue
            for pref in ("T0X_", "A1X_", "XR1_", "XR2_"):
                if n.startswith(pref) and n[len(pref):].isdigit():
                    idx.add(int(n[len(pref):]))
        self.piece_indices = sorted(idx)

    def validate(self, model):
        super().validate(model)
        spans = []
        for i in self.piece_indices:
            r1 = self.params[f"XR1_{i:04d}"].value
            r2 = self.params[f"XR2_{i:04d}"].value
            if r1 is None or r2 is None:
                raise TimingModelError(
                    f"BT piecewise range {i} missing XR1/XR2 bounds"
                )
            spans.append((r1, r2, i))
        spans.sort()
        for (a1, a2, i), (b1, b2, j) in zip(spans, spans[1:]):
            if b1 < a2:
                raise TimingModelError(
                    f"BT piecewise ranges {i} and {j} overlap "
                    f"([{a1}, {a2}) vs [{b1}, {b2}))"
                )

    def extra_masks(self, toas) -> dict:
        import numpy as np

        mjd = toas.mjd_float()
        out = {}
        for i in self.piece_indices:
            r1 = self.params[f"XR1_{i:04d}"].value
            r2 = self.params[f"XR2_{i:04d}"].value
            out[f"BTX_{i:04d}"] = ((mjd >= r1) & (mjd < r2)).astype(
                np.float64
            )
        return out

    def _binary_delay(self, pdict, bundle, dt: DD):
        from pint_tpu.models.binaries.bt import bt_delay

        # piecewise T0: subtract (T0X - T0) seconds inside each range
        t0_day, t0_sec = pdict["T0"]
        shift = jnp.zeros(bundle.ntoa)
        a1_extra = jnp.zeros(bundle.ntoa)
        for i in self.piece_indices:
            m = bundle.masks[f"BTX_{i:04d}"]
            t0x = pdict.get(f"T0X_{i:04d}")
            if t0x is not None:
                xd, xs = t0x
                dsec = (xd - t0_day) * 86400.0 + (
                    (xs - t0_sec).to_float()
                    if isinstance(xs, DD) else xs - t0_sec
                )
                shift = shift + m * dsec
            a1x = pdict.get(f"A1X_{i:04d}")
            if a1x is not None:
                a1_extra = a1_extra + m * (a1x - self.val(pdict, "A1"))
        dt = dt - shift
        dt_f = dt.to_float()
        M, _ = phase_from_orbits(self._orbits(pdict, dt))
        nb = self._nb(pdict, dt_f)
        a1 = self._a1(pdict, dt_f) + a1_extra
        return bt_delay(
            M, nb, a1, self._ecc(pdict, dt_f),
            self._om(pdict, dt_f), self.val(pdict, "GAMMA"),
        )


class BinaryDDS(BinaryDD):
    """DD with SHAPMAX parameterization of the Shapiro shape,
    s = 1 - exp(-SHAPMAX) (high-inclination systems).

    Reference: models/binary_dd.py::BinaryDDS / DDS_model.py.
    """

    register = True
    binary_model_name = "DDS"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("SHAPMAX", units=""))
        self.remove_param("SINI")

    def _pk(self, pdict, dt_f):
        pk = super()._pk(pdict, dt_f)
        # exp_p: 0-d transcendentals are f32-accurate on axon
        # (ops/scalarmath.py)
        pk["sini"] = 1.0 - exp_p(-self.val(pdict, "SHAPMAX"))
        return pk


class BinaryDDGR(BinaryDD):
    """DD with all post-Keplerian parameters fixed by GR from
    (MTOT, M2) — reference: models/binary_dd.py::BinaryDDGR /
    DDGR_model.py.  XOMDOT/XPBDOT are excess terms beyond GR.
    """

    register = True
    binary_model_name = "DDGR"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("MTOT", units="Msun"))
        self.add_param(
            floatParameter(
                "XOMDOT", units="deg/yr", scale_to_internal=_DEG_PER_YEAR
            )
        )
        for n in ("SINI", "GAMMA", "OMDOT", "PBDOT", "DR", "DTH"):
            self.remove_param(n)

    def validate(self, model):
        super().validate(model)
        self.require("MTOT", "M2")

    def _gr(self, pdict, dt_f):
        from pint_tpu.models.binaries.dd import gr_pk_params

        pb = pdict.get("PB")
        if pb is None:
            fb0 = pdict["FB0"]
            pb_s = 1.0 / (fb0.to_float() if isinstance(fb0, DD) else fb0)
        else:
            pb_s = pb.to_float() if isinstance(pb, DD) else pb
        return gr_pk_params(
            pb_s, self._ecc(pdict, dt_f), self.val(pdict, "A1"),
            TSUN * self.val(pdict, "MTOT"), TSUN * self.val(pdict, "M2"),
        )

    def _orbits(self, pdict, dt: DD):
        # PBDOT is the GR value (plus any XPBDOT excess)
        gr = self._gr(pdict, 0.0)
        if self._use_fb():
            return orbits_fb(dt, self._fb_list(pdict))
        return orbits_pb(
            dt, pdict["PB"], gr["pbdot"], self.val(pdict, "XPBDOT")
        )

    def _nb(self, pdict, dt_f):
        gr = self._gr(pdict, 0.0)
        if self._use_fb():
            return nb_fb(dt_f, self._fb_list(pdict))
        return nb_pb(
            dt_f, pdict["PB"], gr["pbdot"], self.val(pdict, "XPBDOT")
        )

    def _pk(self, pdict, dt_f):
        gr = self._gr(pdict, dt_f)
        return {
            "k": gr["k"] + self.val(pdict, "XOMDOT") / self._nb0(pdict),
            "gamma": gr["gamma"],
            "m2r": TSUN * self.val(pdict, "M2"),
            "sini": gr["sini"],
            "dr": gr["dr"],
            "dth": gr["dth"],
        }


class BinaryDDK(BinaryDD):
    """DD with Kopeikin (1995, 1996) annual-orbital-parallax and
    proper-motion coupling to astrometry.

    Reference: models/binary_ddk.py::BinaryDDK / DDK_model.py.  KIN/KOM
    orient the orbit on the sky (KOM from celestial North through East);
    proper motion secularly drifts the apparent inclination and
    periastron longitude, and the observer's SSB offset adds annual
    terms scaled by 1/distance (needs PX).  Sign conventions follow
    Kopeikin 1996 eqs. (10)-(11) and Kopeikin 1995 eq. (18)
    [verify against reference mount when available].
    """

    register = True
    binary_model_name = "DDK"

    def __init__(self):
        super().__init__()
        self.add_param(
            floatParameter("KIN", units="deg", scale_to_internal=_DEG)
        )
        self.add_param(
            floatParameter("KOM", units="deg", scale_to_internal=_DEG)
        )
        from pint_tpu.models.parameter import boolParameter

        self.add_param(boolParameter("K96", value=True))
        self.remove_param("SINI")

    def setup(self, model):
        from pint_tpu.models.astrometry import Astrometry

        self._astrometry_ref = None
        for c in model.components.values():
            if isinstance(c, Astrometry):
                self._astrometry_ref = c

    def validate(self, model):
        super().validate(model)
        self.require("KIN", "KOM")
        if self._astrometry_ref is None:
            raise TimingModelError(
                "DDK requires an astrometry component (KIN/KOM couple the "
                "orbit orientation to sky position)"
            )

    def _kopeikin(self, pdict, bundle, dt_f):
        """-> (a1_eff, om_eff, kin) per TOA."""
        ast = self._astrometry_ref
        kin0 = pdict["KIN"]
        kom = pdict["KOM"]
        # scalar-safe trig: KIN/KOM are 0-d parameters and axon's
        # scalar transcendental path is f32-accurate (ops/scalarmath.py)
        sk, ck = sin_p(kom), cos_p(kom)
        sin_kin0 = sin_p(kin0)
        cot_kin0 = cos_p(kin0) / sin_kin0
        pml, pmb = ast.proper_motion(pdict)
        # Kopeikin 1996: secular drift from proper motion
        dkin_pm = (-pml * sk + pmb * ck) * dt_f
        dom_pm = (pml * ck + pmb * sk) / sin_kin0 * dt_f
        a1 = self._a1(pdict, dt_f)
        a1_eff = a1 * (1.0 + cot_kin0 * dkin_pm)
        om_eff = self.val(pdict, "OM") + dom_pm
        kin = kin0 + dkin_pm
        # Kopeikin 1995: annual orbital parallax (K96)
        px = ast.px_rad(pdict)
        if self.params["K96"].value and ast.params["PX"].value is not None:
            from pint_tpu.constants import AU_LIGHT_SEC

            d_ls = AU_LIGHT_SEC / px  # distance in light-seconds
            east, north = ast.sky_basis(pdict)
            r = bundle.ssb_obs_pos_ls
            delta_i0 = jnp.sum(r * east, axis=-1)
            delta_j0 = jnp.sum(r * north, axis=-1)
            a1_eff = a1_eff + a1 / d_ls * cot_kin0 * (
                delta_i0 * sk - delta_j0 * ck
            )
            om_eff = om_eff - (delta_i0 * ck + delta_j0 * sk) / (
                d_ls * sin_kin0
            )
        return a1_eff, om_eff, kin

    def _binary_delay(self, pdict, bundle, dt: DD):
        from pint_tpu.models.binaries.dd import dd_delay

        dt_f = dt.to_float()
        M, norb = phase_from_orbits(self._orbits(pdict, dt))
        nb = self._nb(pdict, dt_f)
        a1_eff, om_eff, kin = self._kopeikin(pdict, bundle, dt_f)
        pk = self._pk(pdict, dt_f)
        return dd_delay(
            M, norb, nb, a1_eff, self._ecc(pdict, dt_f),
            om_eff, pk["k"], gamma=pk["gamma"],
            m2r=pk["m2r"], sini=jnp.sin(kin), dr=pk["dr"], dth=pk["dth"],
            a0=self.val(pdict, "A0"), b0=self.val(pdict, "B0"),
        )

    def _pk(self, pdict, dt_f):
        pk = super()._pk(pdict, dt_f)
        pk["sini"] = None  # replaced by sin(KIN) in _binary_delay
        return pk


class BinaryELL1(PulsarBinary):
    """Lange et al. 2001 small-eccentricity model.

    Reference: models/binary_ell1.py::BinaryELL1 +
    stand_alone_psr_binaries/ELL1_model.py.
    """

    register = True
    binary_model_name = "ELL1"
    epoch_param = "TASC"

    def __init__(self):
        super().__init__()
        self.add_param(MJDParameter("TASC", time_scale="tdb"))
        self.add_param(floatParameter("EPS1", units="", value=None))
        self.add_param(floatParameter("EPS2", units="", value=None))
        self.add_param(floatParameter("EPS1DOT", units="1/s", unit_scale=True))
        self.add_param(floatParameter("EPS2DOT", units="1/s", unit_scale=True))
        # ELL1 does not use T0/ECC/OM directly
        for n in ("T0", "ECC", "EDOT", "OM", "OMDOT", "GAMMA"):
            self.remove_param(n)

    def validate(self, model):
        super().validate(model)
        self.require("EPS1", "EPS2")

    def _eps(self, pdict, dt_f):
        return _ell1.eps_at_t(
            dt_f, self.val(pdict, "EPS1"), self.val(pdict, "EPS2"),
            self.val(pdict, "EPS1DOT"), self.val(pdict, "EPS2DOT"),
        )

    def _shapiro(self, pdict, phi):
        if (
            self.params["M2"].value is not None
            and self.params["SINI"].value is not None
        ):
            return _ell1.shapiro_ms(
                phi, TSUN * self.val(pdict, "M2"), self.val(pdict, "SINI")
            )
        return 0.0

    def _binary_delay(self, pdict, bundle, dt: DD):
        dt_f = dt.to_float()
        phi, _ = phase_from_orbits(self._orbits(pdict, dt))
        nb = self._nb(pdict, dt_f)
        eps1, eps2 = self._eps(pdict, dt_f)
        a1 = self._a1(pdict, dt_f)
        dre, drep, drepp = _ell1.roemer_terms(phi, a1, eps1, eps2)
        d = _ell1.inverse_timing(dre, drep, drepp, nb)
        return d + self._shapiro(pdict, phi)


class BinaryELL1H(BinaryELL1):
    """ELL1 with orthometric Shapiro parameters (Freire & Wex 2010).

    Reference: models/binary_ell1.py::BinaryELL1H /
    ELL1H_model.ELL1Hmodel.
    """

    register = True
    binary_model_name = "ELL1H"

    def __init__(self):
        super().__init__()
        self.add_param(floatParameter("H3", units="s"))
        self.add_param(floatParameter("H4", units="s"))
        self.add_param(floatParameter("STIGMA", units="", aliases=("STIG", "VARSIGMA")))
        self.add_param(floatParameter("NHARM", units=""))
        for n in ("M2", "SINI"):
            self.remove_param(n)

    def validate(self, model):
        super().validate(model)
        self.require("H3")

    def _shapiro(self, pdict, phi):
        h3 = self.val(pdict, "H3")
        if self.params["STIGMA"].value is not None:
            return _ell1.shapiro_h3_stig(phi, h3, self.val(pdict, "STIGMA"))
        if self.params["H4"].value is not None:
            stig = self.val(pdict, "H4") / h3
            return _ell1.shapiro_h3_stig(phi, h3, stig)
        return _ell1.shapiro_h3_only(phi, h3)


class BinaryELL1k(BinaryELL1):
    """ELL1 variant with explicit OMDOT/LNEDOT (Susobhanan et al. 2018).

    Reference: models/binary_ell1.py::BinaryELL1k / ELL1k_model.py.
    """

    register = True
    binary_model_name = "ELL1K"

    def __init__(self):
        super().__init__()
        self.add_param(
            floatParameter(
                "OMDOT", units="deg/yr", scale_to_internal=_DEG_PER_YEAR
            )
        )
        self.add_param(floatParameter("LNEDOT", units="1/s", unit_scale=True))
        for n in ("EPS1DOT", "EPS2DOT"):
            self.remove_param(n)

    def _eps(self, pdict, dt_f):
        return _ell1.eps_at_t_k(
            dt_f, self.val(pdict, "EPS1"), self.val(pdict, "EPS2"),
            self.val(pdict, "OMDOT"), self.val(pdict, "LNEDOT"),
        )
