"""Glitch: step + decaying-exponential phase terms per glitch epoch.

Reference parity: src/pint/models/glitch.py::Glitch — for each glitch i
with epoch GLEP_i, for t > GLEP:

  phase_i = GLPH_i + GLF0_i dt + GLF1_i dt^2/2 + GLF2_i dt^3/6
            + GLF0D_i * TD_i * (1 - exp(-dt/TD_i))

Glitch terms are small (<<1e9 cycles), so plain f64 accumulation into a
DD phase is exact to well below a nanosecond.
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.exceptions import MissingParameter
from pint_tpu.models.component import PhaseComponent
from pint_tpu.models.parameter import floatParameter, prefix_index
from pint_tpu.ops.dd import DD

_FAMS = ("GLEP_", "GLPH_", "GLF0_", "GLF1_", "GLF2_", "GLF0D_", "GLTD_")


class Glitch(PhaseComponent):
    register = True
    category = "glitch"

    def __init__(self):
        super().__init__()
        self.prefix_patterns = list(_FAMS)
        self.glitch_indices: list[int] = []

    def add_glitch(self, idx: int):
        self.add_param(floatParameter(f"GLEP_{idx}", units="MJD"))
        self.add_param(floatParameter(f"GLPH_{idx}", units="cycles", value=0.0))
        self.add_param(floatParameter(f"GLF0_{idx}", units="Hz", value=0.0))
        self.add_param(floatParameter(f"GLF1_{idx}", units="Hz/s", value=0.0))
        self.add_param(floatParameter(f"GLF2_{idx}", units="Hz/s^2", value=0.0))
        self.add_param(floatParameter(f"GLF0D_{idx}", units="Hz", value=0.0))
        self.add_param(
            floatParameter(f"GLTD_{idx}", units="d", scale_to_internal=86400.0)
        )
        self.glitch_indices.append(idx)

    def new_prefix_param(self, name):
        for pref in _FAMS:
            idx = prefix_index(name, pref)
            if idx is not None:
                if f"GLEP_{idx}" not in self.params:
                    self.add_glitch(idx)
                return self.params[f"{pref}{idx}"]
        return None

    def setup(self, model):
        self.glitch_indices = sorted(
            int(n[5:]) for n in self.params
            if n.startswith("GLEP_") and self.params[n].value is not None
        )

    def validate(self, model):
        for i in self.glitch_indices:
            if self.params[f"GLEP_{i}"].value is None:
                raise MissingParameter("Glitch", f"GLEP_{i}")

    def phase_term(self, pdict, bundle, delay):
        total = jnp.zeros(bundle.ntoa)
        for i in self.glitch_indices:
            glep = pdict[f"GLEP_{i}"]
            dt = (bundle.tdb_day - glep) * 86400.0 + bundle.tdb_sec.to_float()
            dt = dt - delay
            on = dt > 0.0
            dtp = jnp.where(on, dt, 0.0)
            ph = (
                self._v(pdict, f"GLPH_{i}")
                + self._v(pdict, f"GLF0_{i}") * dtp
                + self._v(pdict, f"GLF1_{i}") * dtp * dtp / 2.0
                + self._v(pdict, f"GLF2_{i}") * dtp**3 / 6.0
            )
            # GLTD 0 (tempo/PINT convention for no decay) must not divide
            td_host = self.params[f"GLTD_{i}"].value
            if td_host is not None and float(td_host) != 0.0:
                td = pdict[f"GLTD_{i}"]
                f0d = self._v(pdict, f"GLF0D_{i}")
                ph = ph + f0d * td * (1.0 - jnp.exp(-dtp / td))
            total = total + jnp.where(on, ph, 0.0)
        return DD.from_float(total)

    @staticmethod
    def _v(pdict, name, default=0.0):
        v = pdict.get(name)
        return default if v is None else v
