"""Explicit overall phase offset.

Reference parity: src/pint/models/phase_offset.py::PhaseOffset — PHOFF
in pulse cycles, subtracted from the model phase; the fittable
alternative to implicit weighted-mean subtraction.
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.models.component import PhaseComponent
from pint_tpu.models.parameter import floatParameter
from pint_tpu.ops.dd import DD


class PhaseOffset(PhaseComponent):
    register = True
    category = "phase_offset"

    def __init__(self):
        super().__init__()
        self.add_param(
            floatParameter("PHOFF", units="cycles", description="phase offset")
        )

    def phase_term(self, pdict, bundle, delay):
        return DD.from_float(
            -pdict["PHOFF"] * jnp.ones(bundle.ntoa)
        )
