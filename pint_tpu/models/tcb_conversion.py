"""TCB <-> TDB parameter conversion.

Reference parity: src/pint/models/tcb_conversion.py::convert_tcb_tdb —
tempo2-style parfiles can be in TCB units (UNITS TCB).  IAU 2006 B3:
dTDB/dTCB = 1 - L_B, i.e. a fixed physical interval spans FEWER TDB
seconds, so a parameter with effective time dimensionality d (value ~
s^d) scales as

    value_tdb = value_tcb * (1 - L_B)^d

F0 [s^-1, d=-1] becomes LARGER: F0_tdb = F0_tcb / (1-L_B) = F0_tcb * K
with tempo2's IFTE_K = 1/(1-L_B); PB [s, d=+1] becomes smaller.  DM has
effective d = -1 (the dispersion constant is held fixed while delay
scales with d=+1 and freq^2 with d=-2).  Epochs transform through the
full TCB->TDB time-scale conversion.
"""

from __future__ import annotations

from pint_tpu.constants import L_B

# parameter name -> effective time dimensionality d (value ~ s^d).
# Generated families handled by prefix below.
_DIMENSIONS = {
    "F0": -1, "F1": -2, "F2": -3, "F3": -4, "F4": -5, "F5": -6,
    "PB": 1, "A1": 1, "FB0": -1, "FB1": -2, "FB2": -3,
    "GAMMA": 1, "M2": 0, "MTOT": 0,
    "DM": -1, "NE_SW": -1,
    "PX": 0, "OM": 0, "ECC": 0, "SINI": 0,
    "OMDOT": -1, "PBDOT": 0, "EDOT": -1, "A1DOT": 0,
}

_PREFIX_DIMS = [
    ("F", lambda k: -(k + 1)),  # F0..Fn
    ("DMX_", lambda k: -1),
    ("GLF0_", lambda k: -1),
    ("GLF1_", lambda k: -2),
    ("GLF2_", lambda k: -3),
]


def _dimension(name: str):
    if name in _DIMENSIONS:
        return _DIMENSIONS[name]
    for pref, fn in _PREFIX_DIMS:
        rest = name[len(pref):]
        if name.startswith(pref) and rest.isdigit():
            return fn(int(rest))
    return None


def convert_tcb_tdb(model, backwards: bool = False):
    """Convert a model's parameters in place TCB->TDB (or TDB->TCB when
    backwards).  Epoch parameters route through TimeArray scale
    conversion; dimensioned parameters scale by (1-L_B)^(-d).

    The scale is computed and applied in double-double: the plain-f64
    product (1-L_B)**d carries ~1e-16 relative rounding, which on F0
    is a ~6 ns phase error over a 1300-day span — caught by the
    golden23 TCB oracle set (tests/test_independent_oracle.py)."""
    from pint_tpu.models.parameter import MJDParameter
    from pint_tpu.timebase.hostdd import HostDD

    one_minus = HostDD(1.0) - L_B
    for name, p in model.params.items():
        if p.value is None:
            continue
        if isinstance(p, MJDParameter):
            t = p.value  # TimeArray in tdb scale tag
            # reinterpret the stored epoch in the source scale and convert
            from pint_tpu.timebase.times import TimeArray

            src = "tcb" if not backwards else "tdb"
            dst = "tdb" if not backwards else "tcb"
            t2 = TimeArray(t.mjd_int, t.sec, src).to_scale(dst)
            p.value = TimeArray(t2.mjd_int, t2.sec, "tdb")
            continue
        d = _dimension(name)
        if not d:
            continue
        dd = d if not backwards else -d
        scale = HostDD(1.0)
        for _ in range(abs(dd)):
            scale = scale * one_minus if dd > 0 else scale / one_minus
        iv = p.internal()
        if hasattr(iv, "to_float"):
            p.set_internal(iv * scale)
        else:
            p.set_internal(float(HostDD(float(iv)) * scale))
    units = model.top_params["UNITS"]
    units.value = "TDB" if not backwards else "TCB"
    return model
