"""Solar-system Shapiro delay (Sun + optionally planets).

Reference parity: src/pint/models/solar_system_shapiro.py — delay
-(2 GM_b / c^3) ln(r - r.n) summed over bodies; the log's constant
offset is degenerate with overall phase and irrelevant to fitting.
PLANET_SHAPIRO enables Jupiter..Neptune terms (requires planet position
columns from ingest with planets=True).
"""

from __future__ import annotations

import jax.numpy as jnp

from pint_tpu.constants import (
    AU_LIGHT_SEC,
    C,
    GM_JUPITER,
    GM_NEPTUNE,
    GM_SATURN,
    GM_SUN,
    GM_URANUS,
    GM_VENUS,
)
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import boolParameter

_T2 = 2.0 / C**3  # 2/c^3; times GM gives seconds

_PLANET_GM = {
    "venus": GM_VENUS,
    "jupiter": GM_JUPITER,
    "saturn": GM_SATURN,
    "uranus": GM_URANUS,
    "neptune": GM_NEPTUNE,
}


class SolarSystemShapiro(DelayComponent):
    register = True
    category = "solar_system_shapiro"

    def __init__(self):
        super().__init__()
        self.add_param(
            boolParameter("PLANET_SHAPIRO", value=False)
        )

    @staticmethod
    def _body_delay(gm, obs_body_pos_ls, psr_dir):
        """-(2GM/c^3) * ln((r - r.n)/AU_ls); r = obs->body light-sec."""
        r = jnp.sqrt(jnp.sum(obs_body_pos_ls**2, axis=-1))
        rn = jnp.sum(obs_body_pos_ls * psr_dir, axis=-1)
        # guard: at r==0 (barycentric fake data) the term is 0
        arg = jnp.maximum((r - rn) / AU_LIGHT_SEC, 1e-30)
        return jnp.where(
            r > 0, -(gm * _T2) * jnp.log(arg), 0.0
        )

    def delay_term(self, pdict, bundle, acc_delay):
        # pulsar direction from the astrometry component via bundle cache:
        # the TimingModel guarantees astrometry runs first (DEFAULT_ORDER);
        # we recompute the unit vector here to stay functional.
        psr_dir = self._psr_dir(pdict, bundle)
        d = self._body_delay(GM_SUN, bundle.obs_sun_pos_ls, psr_dir)
        if self.params["PLANET_SHAPIRO"].value:
            for body, gm in _PLANET_GM.items():
                if body in bundle.obs_planet_pos_ls:
                    d = d + self._body_delay(
                        gm, bundle.obs_planet_pos_ls[body], psr_dir
                    )
        return d

    def _psr_dir(self, pdict, bundle):
        self._astrometry_ref = getattr(self, "_astrometry_ref", None)
        if self._astrometry_ref is None:
            raise RuntimeError(
                "SolarSystemShapiro needs an astrometry component "
                "(set by TimingModel.setup)"
            )
        return self._astrometry_ref.ssb_to_psr_xyz(pdict, bundle)

    def setup(self, model):
        from pint_tpu.models.astrometry import Astrometry

        self._astrometry_ref = None
        for c in model.components.values():
            if isinstance(c, Astrometry):
                self._astrometry_ref = c
