"""Dispersion delay components: DM polynomial + DMX piecewise.

Reference parity: src/pint/models/dispersion_model.py::DispersionDM,
DispersionDMX, DMJump — delay = K * DM(t) / f^2 with K the Tempo
dispersion constant (constants.DM_CONST), DM(t) a Taylor series in
(t - DMEPOCH), DMX piecewise offsets over MJD ranges via mask arrays.

Wideband DM-measurement interfaces (dm_value/dm_designmatrix) live here
too, consumed by WidebandTOAFitter.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.constants import DM_CONST, SECS_PER_JULIAN_YEAR
from pint_tpu.models.component import DelayComponent
from pint_tpu.models.parameter import (
    MJDParameter,
    floatParameter,
    maskParameter,
)
from pint_tpu.ops.taylor import taylor_horner


class DispersionDM(DelayComponent):
    register = True
    category = "dispersion_constant"

    def __init__(self, max_terms: int = 10):
        super().__init__()
        self.add_param(
            floatParameter("DM", units="pc/cm^3", frozen=False)
        )
        for k in range(1, max_terms + 1):
            # DMk in pc cm^-3 / yr^k -> internal per-second^k
            self.add_param(
                floatParameter(
                    f"DM{k}",
                    units=f"pc/cm^3/yr^{k}",
                    scale_to_internal=SECS_PER_JULIAN_YEAR ** (-k),
                )
            )
        self.add_param(MJDParameter("DMEPOCH", time_scale="tdb"))
        self.prefix_patterns = ["DM"]

    def new_prefix_param(self, name):
        from pint_tpu.models.parameter import prefix_index

        k = prefix_index(name, "DM")
        if k is None or k < 1:  # DM0 is not a valid derivative
            return None
        return self.add_param(
            floatParameter(
                f"DM{k}",
                units=f"pc/cm^3/yr^{k}",
                scale_to_internal=SECS_PER_JULIAN_YEAR ** (-k),
            )
        )

    def validate(self, model):
        from pint_tpu.exceptions import TimingModelError

        if (
            self.params["DM1"].value is not None
            and self.params["DMEPOCH"].value is None
        ):
            raise TimingModelError("DMEPOCH required when DM1 is set")
        set_ks = [
            int(n[2:]) for n in self.params
            if n.startswith("DM") and n[2:].isdigit()
            and self.params[n].value is not None
        ]
        if set_ks and sorted(set_ks) != list(range(1, max(set_ks) + 1)):
            raise TimingModelError(
                f"non-contiguous DM derivatives: DM{sorted(set_ks)}"
            )

    def _coeffs(self, pdict):
        out = [pdict["DM"]]
        k = 1
        while f"DM{k}" in pdict and self.params[f"DM{k}"].value is not None:
            out.append(pdict[f"DM{k}"])
            k += 1
        return out

    def dm_value(self, pdict, bundle):
        """DM at each TOA (pc/cm^3)."""
        coeffs = self._coeffs(pdict)
        if len(coeffs) == 1:
            return coeffs[0] * jnp.ones(bundle.ntoa)
        day, sec = pdict["DMEPOCH"]
        dt = bundle.dt_seconds(day, sec).to_float()
        # note: reference uses plain Taylor (not /k!) for DM derivatives?
        # No: PINT uses taylor_horner with factorial convention; we match.
        return taylor_horner(dt, coeffs)

    def delay_term(self, pdict, bundle, acc_delay):
        dm = self.dm_value(pdict, bundle)
        return DM_CONST * dm / jnp.square(bundle.freq_mhz)


class DispersionDMX(DelayComponent):
    """Piecewise-constant DM offsets over MJD ranges (DMX_####)."""

    register = True
    category = "dispersion_dmx"

    def __init__(self, n_ranges: int = 0):
        super().__init__()
        self.dmx_indices: list[int] = []
        for i in range(1, n_ranges + 1):
            self.add_dmx_range(i)
        self.prefix_patterns = ["DMX_", "DMXR1_", "DMXR2_"]

    def add_dmx_range(self, idx: int):
        self.add_param(
            floatParameter(f"DMX_{idx:04d}", units="pc/cm^3", value=0.0)
        )
        self.add_param(floatParameter(f"DMXR1_{idx:04d}", units="MJD"))
        self.add_param(floatParameter(f"DMXR2_{idx:04d}", units="MJD"))
        self.dmx_indices.append(idx)

    def new_prefix_param(self, name):
        from pint_tpu.models.parameter import prefix_index

        for pref in ("DMX_", "DMXR1_", "DMXR2_"):
            idx = prefix_index(name, pref)
            if idx is not None:
                if f"DMX_{idx:04d}" not in self.params:
                    self.add_dmx_range(idx)
                return self.params[f"{pref}{idx:04d}"]
        return None

    def validate(self, model):
        for i in self.dmx_indices:
            if (
                self.params[f"DMXR1_{i:04d}"].value is None
                or self.params[f"DMXR2_{i:04d}"].value is None
            ):
                from pint_tpu.exceptions import MissingParameter

                raise MissingParameter(
                    "DispersionDMX", f"DMXR1_{i:04d}/DMXR2_{i:04d}",
                    f"DMX_{i:04d} is set but its MJD range bounds are not",
                )

    def setup(self, model):
        self.dmx_indices = sorted(
            int(n[4:]) for n in self.params
            if n.startswith("DMX_") and self.params[n].value is not None
        )

    def extra_masks(self, toas) -> dict[str, np.ndarray]:
        return self.dmx_masks(toas)

    def dmx_masks(self, toas) -> dict[str, np.ndarray]:
        """Host-side: per-range 0/1 masks from DMXR1/DMXR2."""
        mjd = toas.mjd_float()
        out = {}
        for i in self.dmx_indices:
            r1 = self.params[f"DMXR1_{i:04d}"].value
            r2 = self.params[f"DMXR2_{i:04d}"].value
            out[f"DMX_{i:04d}"] = (
                (mjd >= r1) & (mjd <= r2)
            ).astype(np.float64)
        return out

    def dm_value(self, pdict, bundle):
        dm = jnp.zeros(bundle.ntoa)
        for i in self.dmx_indices:
            name = f"DMX_{i:04d}"
            dm = dm + pdict[name] * bundle.masks[name]
        return dm

    def delay_term(self, pdict, bundle, acc_delay):
        return DM_CONST * self.dm_value(pdict, bundle) / jnp.square(
            bundle.freq_mhz
        )


class DMJump(DelayComponent):
    """Wideband DM jumps: shift DM *measurements*, not the delay.

    Reference: dispersion_model.py::DMJump — the delay term is zero; the
    jump applies to wideband DM residuals (fitting/wideband.py).
    """

    register = True
    category = "dispersion_jump"

    def __init__(self):
        super().__init__()
        self.dmjump_params: list[str] = []

    def add_dmjump(self, idx: int) -> maskParameter:
        name = f"DMJUMP{idx}"
        p = self.add_param(maskParameter(name, index=idx, units="pc/cm^3"))
        self.dmjump_params.append(name)
        return p

    def mask_families(self):
        return {"DMJUMP": self.add_dmjump}

    def delay_term(self, pdict, bundle, acc_delay):
        return jnp.zeros(bundle.ntoa)

    def dm_offset(self, pdict, bundle):
        off = jnp.zeros(bundle.ntoa)
        for n in self.dmjump_params:
            off = off - pdict[n] * bundle.masks[n]
        return off
