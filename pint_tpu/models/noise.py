"""Noise-model components: white rescaling + reduced-rank correlated bases.

Reference parity: src/pint/models/noise_model.py — ScaleToaError
(EFAC/EQUAD/TNEQ), ScaleDmError (DMEFAC/DMEQUAD), EcorrNoise (ECORR),
PLRedNoise (TNRED*), PLDMNoise (TNDM*).  Two consumer interfaces,
matching the reference's scaled_toa_sigma and
noise_model_designmatrix/basis_weight pair:

  scaled_sigma(pdict, bundle, sigma_s) -> per-TOA white sigma (seconds)
  basis_weight(pdict, bundle) -> (basis (n,k), weight (k,)) or None

The covariance never materializes as N x N unless a fitter explicitly
asks (full_cov): correlated noise enters as C = N + T phi T^T with
k << n (SURVEY.md §5 long-context strategy — the Woodbury/reduced-rank
trick is the blockwise-attention analogue and we keep it).

Epoch quantization for ECORR and the selection masks are computed
host-side at compile time and shipped as static arrays in the bundle
(SURVEY.md §7 hard-part #2).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from pint_tpu.constants import SECS_PER_JULIAN_YEAR
from pint_tpu.models.component import NoiseComponent
from pint_tpu.models.parameter import floatParameter, maskParameter
from pint_tpu.ops.scalarmath import power_p

F_YR = 1.0 / SECS_PER_JULIAN_YEAR
#: log10 of the static power-law constant f_yr^-3 / (12 pi^2), folded
#: into the amplitude exponent so no tiny intermediate is ever formed
_LOG10_PL_K = math.log10(F_YR ** -3.0 / (12.0 * math.pi * math.pi))

# TOAs closer than this are one observing epoch for ECORR quantization
ECORR_EPOCH_GAP_S = 10.0


class ScaleToaError(NoiseComponent):
    """sigma' = EFAC * sqrt(sigma^2 + EQUAD^2) over mask selections
    (tempo2 convention, matching the reference's ScaleToaError)."""

    register = True
    category = "scale_toa_error"

    def __init__(self):
        super().__init__()
        self.efac_params: list[str] = []
        self.equad_params: list[str] = []
        self.tneq_params: list[str] = []

    def add_efac(self, idx: int):
        name = f"EFAC{idx}"
        p = self.add_param(
            maskParameter(name, index=idx, units="", aliases=("T2EFAC",))
        )
        self.efac_params.append(name)
        return p

    def add_equad(self, idx: int):
        name = f"EQUAD{idx}"
        p = self.add_param(
            maskParameter(
                name, index=idx, units="us", aliases=("T2EQUAD",),
                scale_to_internal=1e-6,
            )
        )
        self.equad_params.append(name)
        return p

    def add_tneq(self, idx: int):
        """TNEQ: log10(EQUAD/s)."""
        name = f"TNEQ{idx}"
        p = self.add_param(maskParameter(name, index=idx, units="log10(s)"))
        self.tneq_params.append(name)
        return p

    def mask_families(self):
        return {
            "EFAC": self.add_efac,
            "T2EFAC": self.add_efac,
            "EQUAD": self.add_equad,
            "T2EQUAD": self.add_equad,
            "TNEQ": self.add_tneq,
        }

    def scaled_sigma(self, pdict, bundle, sigma_s):
        equad2 = jnp.zeros_like(sigma_s)
        for n in self.equad_params:
            equad2 = equad2 + jnp.square(pdict[n]) * bundle.masks[n]
        for n in self.tneq_params:
            # power_p: 0-d pow is f32-accurate on axon (ops/scalarmath)
            equad2 = equad2 + jnp.square(
                power_p(10.0, pdict[n])
            ) * bundle.masks[n]
        efac = jnp.ones_like(sigma_s)
        for n in self.efac_params:
            # masked multiplicative: efac where selected, 1 elsewhere
            efac = efac * (1.0 + (pdict[n] - 1.0) * bundle.masks[n])
        return efac * jnp.sqrt(jnp.square(sigma_s) + equad2)


class ScaleDmError(NoiseComponent):
    """DMEFAC/DMEQUAD: rescale wideband DM-measurement errors (consumed
    by the wideband fitter, not the TOA sigma chain)."""

    register = True
    category = "scale_dm_error"

    def __init__(self):
        super().__init__()
        self.dmefac_params: list[str] = []
        self.dmequad_params: list[str] = []

    def add_dmefac(self, idx: int):
        name = f"DMEFAC{idx}"
        p = self.add_param(maskParameter(name, index=idx, units=""))
        self.dmefac_params.append(name)
        return p

    def add_dmequad(self, idx: int):
        name = f"DMEQUAD{idx}"
        p = self.add_param(maskParameter(name, index=idx, units="pc/cm^3"))
        self.dmequad_params.append(name)
        return p

    def mask_families(self):
        return {"DMEFAC": self.add_dmefac, "DMEQUAD": self.add_dmequad}

    def scaled_dm_sigma(self, pdict, bundle, sigma_dm):
        equad2 = jnp.zeros_like(sigma_dm)
        for n in self.dmequad_params:
            equad2 = equad2 + jnp.square(pdict[n]) * bundle.masks[n]
        efac = jnp.ones_like(sigma_dm)
        for n in self.dmefac_params:
            efac = efac * (1.0 + (pdict[n] - 1.0) * bundle.masks[n])
        return efac * jnp.sqrt(jnp.square(sigma_dm) + equad2)


def dense_noise_cov(Ndiag, T, phi):
    """Dense (n, n) noise covariance C = diag(Ndiag) + T diag(phi) T^T
    — the single assembly shared by CompiledModel.noise_covariance and
    the full_cov GLS path (reference: the full_cov=True input of
    src/pint/fitter.py::GLSFitter.fit_toas)."""
    import jax.numpy as jnp

    C = jnp.diag(Ndiag)
    if T is not None:
        C = C + (T * phi[None, :]) @ T.T
    return C


def quantize_epochs(mjd: np.ndarray, select: np.ndarray,
                    gap_s: float = ECORR_EPOCH_GAP_S) -> np.ndarray:
    """Host-side: (n, n_epoch) 0/1 quantization matrix U grouping
    selected TOAs into observing epochs (gap-based, like the
    reference/enterprise create_quantization_matrix)."""
    n = len(mjd)
    idx = np.flatnonzero(select)
    if idx.size == 0:
        return np.zeros((n, 0))
    order = idx[np.argsort(mjd[idx])]
    cols = []
    current = [order[0]]
    for i in order[1:]:
        if (mjd[i] - mjd[current[-1]]) * 86400.0 > gap_s:
            cols.append(current)
            current = [i]
        else:
            current.append(i)
    cols.append(current)
    U = np.zeros((n, len(cols)))
    for j, members in enumerate(cols):
        U[members, j] = 1.0
    return U


class EcorrNoise(NoiseComponent):
    """Per-epoch fully-correlated white noise (ECORR): basis = epoch
    quantization matrix U, weight = ECORR^2 per epoch."""

    register = True
    category = "ecorr_noise"
    introduces_correlated_errors = True

    def __init__(self):
        super().__init__()
        self.ecorr_params: list[str] = []

    def add_ecorr(self, idx: int):
        name = f"ECORR{idx}"
        p = self.add_param(
            maskParameter(
                name, index=idx, units="us", aliases=("T2ECORR",),
                scale_to_internal=1e-6,
            )
        )
        self.ecorr_params.append(name)
        return p

    def mask_families(self):
        return {"ECORR": self.add_ecorr, "T2ECORR": self.add_ecorr}

    def extra_masks(self, toas) -> dict:
        """Quantization matrices, computed once at compile time."""
        out = {}
        mjd = toas.mjd_float()
        for n in self.ecorr_params:
            sel = self.params[n].select(toas)
            out[f"{n}:U"] = quantize_epochs(mjd, sel)
        return out

    def basis_weight(self, pdict, bundle):
        bases, weights = [], []
        for n in self.ecorr_params:
            U = bundle.masks[f"{n}:U"]
            if U.shape[1] == 0:
                continue
            bases.append(U)
            weights.append(
                jnp.square(pdict[n]) * jnp.ones(U.shape[1])
            )
        if not bases:
            return None
        return jnp.concatenate(bases, axis=1), jnp.concatenate(weights)


def _toa_seconds(bundle) -> jnp.ndarray:
    """Per-TOA time in seconds relative to the first TOA's day (f64;
    harmonic phases need only ~1e-9 relative precision)."""
    day0 = bundle.tdb_day[0]
    return (bundle.tdb_day - day0) * 86400.0 + bundle.tdb_sec.to_float()


def fourier_freqs(bundle, nharm: int):
    """Harmonic layout shared by the materialized basis and the Pallas
    fused-Gram path: (t_seconds (n,), freqs (nharm,), tspan)."""
    t = _toa_seconds(bundle)
    tspan = jnp.max(t) - jnp.min(t)
    j = jnp.arange(1, nharm + 1, dtype=jnp.float64)
    return t, j / tspan, tspan


def fourier_basis(bundle, nharm: int, mask_key: str | None = None):
    """(n, 2*nharm) sin/cos design matrix and the frequencies (Hz).

    The basis depends only on static TOA times, so components
    precompute it host-side in IEEE f64 at compile time (extra_masks)
    and pass its bundle.masks key: that makes every fit-loop step read
    a constant instead of re-evaluating n*k emulated-f64 sin/cos on
    device (~1 ms/step at 1e5 TOAs x 30 harmonics on TPU), and is also
    MORE accurate on axon (emulated f64 is non-IEEE).  The traced
    fallback serves hand-built bundles without the mask."""
    t, f, tspan = fourier_freqs(bundle, nharm)
    F = bundle.masks.get(mask_key) if mask_key else None
    if F is None:
        arg = 2.0 * math.pi * t[:, None] * f[None, :]
        F = jnp.concatenate([jnp.sin(arg), jnp.cos(arg)], axis=1)
    return F, jnp.concatenate([f, f]), tspan


def fourier_basis_rows(bundle, freqs, day0):
    """Rows of a FROZEN-frequency Fourier basis for newly appended
    TOAs (ISSUE 14 basis slicing): the streaming solver extends its
    noise basis by evaluating only the new rows, against the BASE
    span's harmonic layout — ``freqs`` (nharm,) and epoch ``day0``
    are the stream state's frozen values from the last refresh, NOT
    recomputed from this (tail) bundle, so appended rows land in
    exactly the columns the absorbed Gram state already spans.
    Returns (j, 2*nharm) [sin | cos] matching fourier_basis's layout.
    Device-side f64 sin/cos on rank-1 arrays (~1e-14 on axon — the
    scalar-transcendental hazard does not apply; CLAUDE.md)."""
    t = (bundle.tdb_day - day0) * 86400.0 + bundle.tdb_sec.to_float()
    arg = 2.0 * math.pi * t[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.sin(arg), jnp.cos(arg)], axis=1)


def host_fourier_basis(toas, nharm: int) -> np.ndarray:
    """Host-side (IEEE f64 numpy) twin of fourier_basis's sin/cos
    matrix, from the same TDB columns bundle.py packs — computed once
    per dataset at compile time."""
    day = np.asarray(toas.t_tdb.mjd_int, dtype=np.float64)
    sec = np.asarray(toas.t_tdb.sec.to_float(), dtype=np.float64)
    t = (day - day[0]) * 86400.0 + sec
    tspan = t.max() - t.min()
    f = np.arange(1, nharm + 1, dtype=np.float64) / tspan
    arg = 2.0 * np.pi * t[:, None] * f[None, :]
    return np.concatenate([np.sin(arg), np.cos(arg)], axis=1)


def powerlaw_phi(f, tspan, log10_amp, gamma):
    """Power-law PSD weights phi_j (s^2), enterprise convention:
    phi_j = A^2/(12 pi^2) f_yr^(gamma-3) f_j^(-gamma) / Tspan.

    Evaluation order matters on accelerators whose emulated f64 keeps
    only the f32 EXPONENT range (axon): the naive grouping
    A^2 * f_yr^(gamma-3) hits ~4e-38 for PTA-class parameters
    (A=10^-13.8, gamma=4.3) and flushes to ZERO, NaN-ing the whole
    Woodbury solve through 1/phi — silently fine on CPU, where this
    used to be constant-folded in IEEE f64 before bundles became jit
    arguments (r4).  The amplitude factor is therefore formed in LOG
    space with the large static constant f_yr^-3/(12 pi^2) folded in
    (A^2 alone underflows at log10_amp <= -19, within sampler prior
    ranges), and the result is floored at 1e-30 s^2 — physically inert
    ((1e-15 s)^2 vs ns-scale residuals) but keeps 1/phi finite."""
    # power_p on the scalar parameters (0-d pow takes axon's f32 scalar
    # path, ops/scalarmath.py); f is per-harmonic, so plain ** is fine
    amp2_k = power_p(10.0, 2.0 * log10_amp + _LOG10_PL_K)
    return jnp.maximum(
        amp2_k * (f / F_YR) ** (-gamma) / tspan, 1e-30
    )


class _FourierBasisNoise(NoiseComponent):
    """Base for PL Fourier-basis noise components (reference:
    src/pint/models/noise_model.py — the pl_rn_basis_weight_pair /
    create_fourier_design_matrix machinery shared by PLRedNoise /
    PLDMNoise / PLChromNoise).  TPU-first deviation: the sin/cos basis
    is precomputed host-side at compile time into bundle.masks (see
    fourier_basis) instead of being rebuilt per fit iteration."""

    def _basis_key(self) -> str:
        return f"{self.category}:F"

    def extra_masks(self, toas) -> dict:
        return {self._basis_key(): host_fourier_basis(toas, self._nharm())}


class PLRedNoise(_FourierBasisNoise):
    """Power-law achromatic red noise (TNREDAMP/TNREDGAM/TNREDC)."""

    register = True
    category = "pl_red_noise"
    introduces_correlated_errors = True

    def __init__(self):
        super().__init__()
        self.add_param(
            floatParameter(
                "TNREDAMP", units="log10(strain)", aliases=("TNRedAmp",)
            )
        )
        self.add_param(
            floatParameter("TNREDGAM", units="", aliases=("TNRedGam",))
        )
        self.add_param(
            floatParameter("TNREDC", units="", aliases=("TNRedC",), value=None)
        )

    def validate(self, model):
        self.require("TNREDAMP", "TNREDGAM")

    def _nharm(self):
        v = self.params["TNREDC"].value
        return int(v) if v is not None else 30

    def basis_weight(self, pdict, bundle):
        F, f, tspan = fourier_basis(bundle, self._nharm(),
                                    self._basis_key())
        phi = powerlaw_phi(
            f, tspan, pdict["TNREDAMP"], pdict["TNREDGAM"]
        )
        return F, phi

    def fourier_spec(self, pdict, bundle):
        """(t_seconds, harmonic freqs (k,), phi (2k,)) — the pure
        sin/cos structure consumed by the Pallas fused-Gram GLS path
        (ops/pallas_kernels.py); only achromatic PL noise has it.
        Shares fourier_freqs with basis_weight so the two paths can
        never disagree on the harmonic layout."""
        t, f, tspan = fourier_freqs(bundle, self._nharm())
        phi = powerlaw_phi(
            jnp.concatenate([f, f]), tspan,
            pdict["TNREDAMP"], pdict["TNREDGAM"],
        )
        return t, f, phi


class PLChromNoise(_FourierBasisNoise):
    """Power-law chromatic noise (reference: noise_model.py::
    PLChromNoise) — basis columns scaled by (1400 MHz / f)^index.  The
    chromatic index is the ChromaticCM component's CMIDX/TNCHROMIDX (the
    reference reads it from the CM model too); 4.0 when no ChromaticCM
    is in the model."""

    register = True
    category = "pl_chrom_noise"
    introduces_correlated_errors = True

    def __init__(self):
        super().__init__()
        self.add_param(
            floatParameter("TNCHROMAMP", units="log10", aliases=("TNChromAmp",))
        )
        self.add_param(
            floatParameter("TNCHROMGAM", units="", aliases=("TNChromGam",))
        )
        self.add_param(floatParameter("TNCHROMC", units="", value=None))

    def validate(self, model):
        self.require("TNCHROMAMP", "TNCHROMGAM")

    def _nharm(self):
        v = self.params["TNCHROMC"].value
        return int(v) if v is not None else 30

    def basis_weight(self, pdict, bundle):
        F, f, tspan = fourier_basis(bundle, self._nharm(),
                                    self._basis_key())
        idx = pdict.get("CMIDX")
        if idx is None:
            idx = 4.0
        chrom = (1400.0 / bundle.freq_mhz) ** idx
        F = F * chrom[:, None]
        phi = powerlaw_phi(
            f, tspan, pdict["TNCHROMAMP"], pdict["TNCHROMGAM"]
        )
        return F, phi


class PLDMNoise(_FourierBasisNoise):
    """Power-law DM (chromatic nu^-2) noise; basis columns scaled by
    (1400 MHz / f)^2 so amplitudes share the red-noise convention."""

    register = True
    category = "pl_dm_noise"
    introduces_correlated_errors = True

    def __init__(self):
        super().__init__()
        self.add_param(
            floatParameter("TNDMAMP", units="log10", aliases=("TNDMAmp",))
        )
        self.add_param(floatParameter("TNDMGAM", units="", aliases=("TNDMGam",)))
        self.add_param(floatParameter("TNDMC", units="", value=None))

    def validate(self, model):
        self.require("TNDMAMP", "TNDMGAM")

    def _nharm(self):
        v = self.params["TNDMC"].value
        return int(v) if v is not None else 30

    def basis_weight(self, pdict, bundle):
        F, f, tspan = fourier_basis(bundle, self._nharm(),
                                    self._basis_key())
        chrom = jnp.square(1400.0 / bundle.freq_mhz)
        F = F * chrom[:, None]
        phi = powerlaw_phi(f, tspan, pdict["TNDMAMP"], pdict["TNDMGAM"])
        return F, phi
