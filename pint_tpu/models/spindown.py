"""Spindown: rotational phase as a Taylor series in F0..Fn.

Reference parity: src/pint/models/spindown.py::Spindown — phase =
taylor_horner(dt, [0, F0, F1, ...]) with dt = TDB - PEPOCH - delay in
(long double) seconds.  Here dt is DD and F0 is a DD parameter (an f64 F0
alone would alias ~100 ns of phase over 20 yr; see models/parameter.py).
"""

from __future__ import annotations

from pint_tpu.exceptions import TimingModelError
from pint_tpu.models.component import PhaseComponent
from pint_tpu.models.parameter import MJDParameter, floatParameter
from pint_tpu.ops.taylor import taylor_horner_dd, taylor_horner_deriv_dd


class Spindown(PhaseComponent):
    register = True
    category = "spindown"

    def __init__(self, max_fterms: int = 12):
        super().__init__()
        self.add_param(
            floatParameter(
                "F0", units="Hz", long_double=True,
                description="spin frequency", frozen=False,
            )
        )
        self.add_param(
            floatParameter("F1", units="Hz/s", description="spin-down rate")
        )
        for k in range(2, max_fterms + 1):
            self.add_param(
                floatParameter(f"F{k}", units=f"Hz/s^{k}")
            )
        self.add_param(MJDParameter("PEPOCH", time_scale="tdb"))
        self.prefix_patterns = ["F"]

    def new_prefix_param(self, name):
        from pint_tpu.models.parameter import prefix_index

        k = prefix_index(name, "F")
        if k is None:
            return None
        return self.add_param(floatParameter(f"F{k}", units=f"Hz/s^{k}"))

    def validate(self, model):
        self.require("F0")
        set_ks = sorted(
            int(n[1:]) for n in self.params
            if n.startswith("F") and n[1:].isdigit()
            and self.params[n].value is not None
        )
        if set_ks and set_ks != list(range(0, set_ks[-1] + 1)):
            raise TimingModelError(
                f"non-contiguous spin terms: F{set_ks} (gaps not allowed)"
            )
        if len(set_ks) > 1 and self.params["PEPOCH"].value is None:
            raise TimingModelError("PEPOCH required when F1.. are set")

    def _max_k(self):
        ks = [
            int(n[1:]) for n in self.params
            if n.startswith("F") and n[1:].isdigit()
        ]
        return max(ks)

    def _coeff_names(self):
        """Contiguous F-terms F0..Fn actually set."""
        names = []
        for k in range(0, self._max_k() + 1):
            n = f"F{k}"
            if n in self.params and self.params[n].value is not None:
                names.append(n)
            else:
                break
        return names

    def _dt(self, pdict, bundle, delay):
        if self.params["PEPOCH"].value is not None:
            day, sec = pdict["PEPOCH"]
        else:
            day, sec = float(bundle.tdb_day[0]), 0.0
        return bundle.dt_seconds(day, sec) - delay

    def phase_term(self, pdict, bundle, delay):
        dt = self._dt(pdict, bundle, delay)
        coeffs = [0.0] + [pdict[n] for n in self._coeff_names()]
        return taylor_horner_dd(dt, coeffs)

    def spin_frequency(self, pdict, bundle):
        """f(t) at each TOA (no delay correction; matches reference use of
        per-TOA barycentric frequency for time residuals)."""
        dt = self._dt(pdict, bundle, 0.0)
        coeffs = [0.0] + [pdict[n] for n in self._coeff_names()]
        return taylor_horner_deriv_dd(dt, coeffs, 1).to_float()
