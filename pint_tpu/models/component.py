"""Component base classes + registry.

Reference parity: src/pint/models/timing_model.py::Component (metaclass
registry ``component_types``), DelayComponent, PhaseComponent, and the
NoiseComponent split in src/pint/models/noise_model.py.

Design: a Component instance is a *host-side* bag of Parameters plus pure
kernel functions.  Kernel methods receive
  pdict   dict param-name -> jnp scalar (f64) or DD scalar
  bundle  TOABundle (device arrays)
and return arrays; they must be trace-safe (no Python control flow on
traced values).  Mask parameters become static 0/1 arrays in the bundle,
computed host-side at compile time (SURVEY.md §7 hard-part #2).
"""

from __future__ import annotations

from typing import Optional

from pint_tpu.exceptions import MissingParameter, TimingModelError
from pint_tpu.models.parameter import Parameter, maskParameter

# category evaluation order for delays/phases; mirrors the reference's
# DEFAULT_ORDER (timing_model.py::DEFAULT_ORDER)
DEFAULT_ORDER = [
    "astrometry",
    "jump_delay",
    "troposphere",
    "solar_system_shapiro",
    "solar_wind",
    "dispersion_constant",
    "dispersion_dmx",
    "chromatic",
    "frequency_dependent",
    "pulsar_system",
    "spindown",
    "phase_jump",
    "wave",
    "ifunc",
    "glitch",
    "piecewise_spindown",
    "absolute_phase",
    "phase_offset",
]


class Component:
    """Base: ordered parameter container with a class registry."""

    register = False
    category: Optional[str] = None
    component_types: dict = {}

    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        if cls.__dict__.get("register", cls.register):
            Component.component_types[cls.__name__] = cls

    def __init__(self):
        self.params: dict[str, Parameter] = {}

    # -- parameter plumbing ---------------------------------------------
    def add_param(self, par: Parameter) -> Parameter:
        self.params[par.name] = par
        return par

    def remove_param(self, name: str):
        self.params.pop(name, None)

    def __getattr__(self, name):
        params = object.__getattribute__(self, "__dict__").get("params", {})
        if name in params:
            return params[name]
        raise AttributeError(
            f"{type(self).__name__} has no attribute/parameter {name!r}"
        )

    def has_param(self, name: str) -> bool:
        return name in self.params

    def match_param_alias(self, name: str) -> Optional[str]:
        """Resolve an alias to this component's canonical param name."""
        for p in self.params.values():
            if p.name_matches(name):
                return p.name
        return None

    @property
    def free_params(self) -> list[str]:
        return [
            n for n, p in self.params.items()
            if not p.frozen and p.value is not None
        ]

    @property
    def mask_params(self) -> list[str]:
        return [
            n for n, p in self.params.items() if isinstance(p, maskParameter)
        ]

    # -- lifecycle -------------------------------------------------------
    def setup(self, model):
        """Called once after all parameters are set (derive indexed
        families, caches)."""

    def validate(self, model):
        """Raise TimingModelError / MissingParameter on ill-formed input."""

    def require(self, *names):
        for n in names:
            p = self.params.get(n)
            if p is None or p.value is None:
                raise MissingParameter(type(self).__name__, n)

    # -- builder support -------------------------------------------------
    def mask_families(self) -> dict:
        """prefix -> factory(index)->maskParameter for repeated par lines
        (JUMP, EFAC, ...); overridden by components with mask families."""
        return {}

    def new_prefix_param(self, name: str):
        """Create a Parameter for an indexed-family name not yet
        instantiated (F13, DMX_0017, ...); None if unrecognized."""
        return None

    def ensure_param(self, name: str):
        """Existing/alias/freshly-created Parameter for ``name``; None if
        this component does not understand it (builder routing hook)."""
        canon = self.match_param_alias(name)
        if canon is not None:
            return self.params[canon]
        return self.new_prefix_param(name)

    @classmethod
    def accepted_param_names(cls) -> set[str]:
        """All par-file names (incl. aliases, excl. prefix indices) this
        component understands; used by the model builder's reverse map."""
        proto = cls()
        names = set()
        for p in proto.params.values():
            names.add(p.name.upper())
            names.update(a.upper() for a in p.aliases)
        for pref in getattr(proto, "prefix_patterns", []):
            names.add(pref.upper() + "#")
        names.update(k.upper() for k in proto.mask_families())
        return names

    def __repr__(self):
        ps = ", ".join(
            f"{n}={p.value}" for n, p in self.params.items()
            if p.value is not None
        )
        return f"{type(self).__name__}({ps})"


class DelayComponent(Component):
    """Contributes seconds of delay; evaluated in category order, each
    seeing the delay accumulated so far (progressive barycentering)."""

    def delay_term(self, pdict, bundle, acc_delay):
        """-> f64 seconds (n,); acc_delay is the sum of earlier terms."""
        raise NotImplementedError


class PhaseComponent(Component):
    """Contributes pulse phase (DD cycles), evaluated at t - total_delay."""

    def phase_term(self, pdict, bundle, delay):
        """-> DD cycles (n,); delay is the total delay in seconds."""
        raise NotImplementedError


class NoiseComponent(Component):
    """Modifies TOA uncertainties / contributes covariance bases.

    Two interfaces, mirroring the reference (noise_model.py):
      scaled_sigma(pdict, bundle, sigma_us) -> rescaled white sigma
      basis_weight(pdict, bundle) -> (basis (n,k), weight (k,)) or None
    """

    introduces_correlated_errors = False

    def scaled_sigma(self, pdict, bundle, sigma_s):
        return sigma_s

    def basis_weight(self, pdict, bundle):
        return None
