"""Parameter zoo.

Reference parity: src/pint/models/parameter.py — floatParameter,
MJDParameter, AngleParameter, strParameter, boolParameter, intParameter,
prefixParameter, maskParameter, pairParameter, funcParameter.

Design differences from the reference:
- no astropy Quantities: every parameter declares ``units`` (par-file
  units, for IO and display) and a ``scale_to_internal`` factor mapping
  the par value to the unit-free internal convention its component's
  kernel expects (seconds / radians / Hz / ...).
- parameters whose f64 rounding would corrupt sub-ns phase (F0, PEPOCH,
  binary T0/TASC/PB...) are tagged ``precision="dd"`` and carried as
  HostDD, parsed exactly from the par-file string.  Kernels receive them
  as DD pytrees (or as deltas from a DD reference, see docs).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional

import numpy as np

from pint_tpu.exceptions import PintTpuError, PrefixError
from pint_tpu.timebase.hostdd import HostDD
from pint_tpu.timebase.times import TimeArray
from pint_tpu.utils.angles import (
    format_angle_dms,
    format_angle_hms,
    parse_angle_dms,
    parse_angle_hms,
)

_FORTRAN_EXP = re.compile(r"[dD]")


def _parse_float_str(s: str) -> float:
    return float(_FORTRAN_EXP.sub("e", s))


def _fortran_to_e(s: str) -> str:
    return _FORTRAN_EXP.sub("e", s)


class Parameter:
    """Base parameter: value + units + uncertainty + frozen + aliases."""

    param_type = "base"

    def __init__(
        self,
        name: str,
        value: Any = None,
        units: str = "",
        description: str = "",
        aliases: tuple = (),
        frozen: bool = True,
        uncertainty: Optional[float] = None,
        continuous: bool = True,
        scale_to_internal: float = 1.0,
    ):
        self.name = name
        self.units = units
        self.description = description
        self.aliases = tuple(aliases)
        self.frozen = frozen
        self.uncertainty = uncertainty
        self.continuous = continuous
        self.scale_to_internal = scale_to_internal
        self._value = None
        if value is not None:
            self.value = value

    # -- value handling --------------------------------------------------
    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, v):
        self._value = self._coerce(v)

    def _coerce(self, v):
        return v

    @property
    def quantity(self):  # reference-API compatibility
        return self._value

    def internal(self):
        """Value in internal (kernel) units."""
        if self._value is None:
            return None
        return self._value * self.scale_to_internal

    def set_internal(self, v):
        """Update from an internal-units value (after fitting)."""
        self.value = v / self.scale_to_internal

    def internal_uncertainty(self):
        if self.uncertainty is None:
            return None
        return self.uncertainty * self.scale_to_internal

    def set_internal_uncertainty(self, u):
        self.uncertainty = u / self.scale_to_internal

    # -- par-file IO -----------------------------------------------------
    def set_from_tokens(self, tokens: list[str]):
        """tokens: [value] or [value fit] or [value fit unc].

        Tempo convention (matching the reference's par reading): a
        parameter READ FROM A PAR FILE is frozen unless its fit flag
        is '1' — component-constructor frozen defaults only apply to
        programmatically built models.  (Caught by an event_optimize
        run where a flagless 'DM' line was sampled with zero gradient
        at infinite photon frequency and walked to 1e34.)"""
        self.value = self._parse_value_str(tokens[0])
        self.frozen = True
        if len(tokens) >= 2:
            # fit flags are exactly '0'/'1' (tempo convention); any other
            # numeric second token is a tempo2-style bare uncertainty
            if tokens[1] in ("0", "1"):
                self.frozen = tokens[1] == "0"
                if len(tokens) >= 3:
                    self.uncertainty = _parse_float_str(tokens[2])
            else:
                self.uncertainty = _parse_float_str(tokens[1])

    def _parse_value_str(self, s: str):
        return s

    def _format_value(self) -> str:
        return str(self._value)

    def as_parfile_line(self) -> str:
        if self._value is None:
            return ""
        line = f"{self.name:<15} {self._format_value():>25}"
        if not self.frozen:
            line += " 1"
        if self.uncertainty is not None:
            if self.frozen:
                line += " 0"
            line += f" {self.uncertainty:.8g}"
        return line + "\n"

    def name_matches(self, name: str) -> bool:
        name = name.upper()
        return name == self.name.upper() or name in (
            a.upper() for a in self.aliases
        )

    def __repr__(self):
        fit = "" if self.frozen else " FIT"
        return f"<{type(self).__name__} {self.name}={self._value}{fit}>"


class floatParameter(Parameter):
    param_type = "float"

    def __init__(
        self,
        name,
        value=None,
        long_double=False,
        unit_scale=False,
        scale_factor=1e-12,
        scale_threshold=1e-7,
        **kw,
    ):
        # long_double (reference naming) => DD precision here
        self.precision = "dd" if long_double else "f64"
        # tempo convention: PBDOT/XDOT/EDOT values larger than threshold
        # are taken to be in units of scale_factor (reference:
        # parameter.py::floatParameter unit_scale)
        self.unit_scale = unit_scale
        self.scale_factor = scale_factor
        self.scale_threshold = scale_threshold
        self._applied_scale = False
        super().__init__(name, value=value, **kw)

    def _coerce(self, v):
        if isinstance(v, HostDD):
            return v if self.precision == "dd" else float(v.to_float())
        if isinstance(v, str):
            return self._parse_value_str(v)
        if self.precision == "dd":
            return HostDD(float(v))
        return float(v)

    def _parse_value_str(self, s):
        if self.precision == "dd":
            v = HostDD.from_string(_fortran_to_e(s))
            if self.unit_scale and abs(float(v.to_float())) > self.scale_threshold:
                self._applied_scale = True
                return v * self.scale_factor
            return v
        v = _parse_float_str(s)
        if self.unit_scale and abs(v) > self.scale_threshold:
            self._applied_scale = True
            return v * self.scale_factor
        return v

    def set_from_tokens(self, tokens):
        self._applied_scale = False
        super().set_from_tokens(tokens)
        # tempo scaling applies to an uncertainty parsed from THESE tokens
        # only (never to a pre-existing uncertainty)
        has_unc_token = len(tokens) >= 3 or (
            len(tokens) == 2 and tokens[1] not in ("0", "1")
        )
        if self._applied_scale and has_unc_token and self.uncertainty is not None:
            self.uncertainty *= self.scale_factor

    def set_internal(self, v):
        if self.precision == "dd" and not isinstance(v, HostDD):
            v = HostDD(np.float64(v))
        self.value = v / self.scale_to_internal

    def _format_value(self):
        v = self._value
        if isinstance(v, HostDD):
            from decimal import Decimal, localcontext

            with localcontext() as ctx:
                ctx.prec = 40
                d = Decimal(float(v.hi)) + Decimal(float(v.lo))
                return f"{d:.25g}"
        return f"{v:.17g}"


class intParameter(Parameter):
    param_type = "int"

    def _coerce(self, v):
        return int(v)

    def _parse_value_str(self, s):
        return int(float(s))


class boolParameter(Parameter):
    param_type = "bool"

    def _coerce(self, v):
        if isinstance(v, str):
            return s_to_bool(v)
        return bool(v)

    def _parse_value_str(self, s):
        return s_to_bool(s)

    def internal(self):
        return self._value

    def _format_value(self):
        return "Y" if self._value else "N"

    def set_from_tokens(self, tokens):
        self.value = tokens[0] if tokens else True


def s_to_bool(s: str) -> bool:
    s = s.strip().upper()
    if s in ("Y", "YES", "T", "TRUE", "1"):
        return True
    if s in ("N", "NO", "F", "FALSE", "0"):
        return False
    raise PintTpuError(f"cannot parse bool {s!r}")


class strParameter(Parameter):
    param_type = "str"

    def _coerce(self, v):
        return str(v)

    def internal(self):
        return self._value


class MJDParameter(Parameter):
    """Epoch parameter (PEPOCH, POSEPOCH, T0, TASC, ...), exact two-part."""

    param_type = "mjd"

    def __init__(self, name, value=None, time_scale="tdb", **kw):
        self.time_scale = time_scale
        kw.setdefault("units", "MJD")
        super().__init__(name, value=value, **kw)

    def _coerce(self, v):
        if isinstance(v, TimeArray):
            return v
        if isinstance(v, str):
            return self._parse_value_str(v)
        return TimeArray.from_mjd_float(float(v), scale=self.time_scale)

    def _parse_value_str(self, s):
        return TimeArray.from_mjd_strings(
            [_fortran_to_e(s)], scale=self.time_scale
        )

    def _format_value(self):
        return self._value.to_mjd_strings(ndigits=15)[0]

    def internal(self):
        """-> (mjd_int, sec HostDD scalar) pair."""
        if self._value is None:
            return None
        return (int(self._value.mjd_int[0]), self._value.sec[0])

    def add_internal_delta(self, dsec: float):
        """Shift the epoch by dsec seconds (fitting epochs operates on a
        seconds-delta from the reference value)."""
        self._value = self._value.add_seconds(dsec)

    def set_internal(self, v):
        raise PintTpuError(
            "epoch parameters update via add_internal_delta, not set_internal"
        )

    def internal_uncertainty(self):
        """Uncertainty in seconds (par-file convention is days)."""
        if self.uncertainty is None:
            return None
        return self.uncertainty * 86400.0

    def set_internal_uncertainty(self, u):
        self.uncertainty = u / 86400.0


class AngleParameter(Parameter):
    """RAJ/DECJ/ELONG/ELAT etc.; internal radians."""

    param_type = "angle"

    def __init__(self, name, value=None, units="rad", **kw):
        # units: 'H:M:S', 'D:M:S', 'deg', 'rad'
        kw["units"] = units
        super().__init__(name, value=value, **kw)

    def _parse_value_str(self, s):
        u = self.units
        if u == "H:M:S":
            return parse_angle_hms(s)
        if u == "D:M:S":
            return parse_angle_dms(s)
        if u == "deg":
            return _parse_float_str(s) * np.pi / 180.0
        return _parse_float_str(s)

    def _coerce(self, v):
        if isinstance(v, str):
            return self._parse_value_str(v)
        return float(v)  # already radians

    def _format_value(self):
        u = self.units
        if u == "H:M:S":
            return format_angle_hms(self._value)
        if u == "D:M:S":
            return format_angle_dms(self._value)
        if u == "deg":
            return f"{self._value * 180.0 / np.pi:.17g}"
        return f"{self._value:.17g}"

    def internal(self):
        return self._value  # radians

    def set_internal(self, v):
        self._value = float(v)

    def internal_uncertainty(self):
        """Uncertainty in radians: par-file uncertainties for H:M:S are in
        seconds of time; for D:M:S in arcseconds (tempo convention)."""
        if self.uncertainty is None:
            return None
        if self.units == "H:M:S":
            return self.uncertainty * np.pi / (12.0 * 3600.0)
        if self.units == "D:M:S":
            return self.uncertainty * np.pi / (180.0 * 3600.0)
        if self.units == "deg":
            return self.uncertainty * np.pi / 180.0
        return self.uncertainty

    def set_internal_uncertainty(self, u):
        if self.units == "H:M:S":
            self.uncertainty = u * (12.0 * 3600.0) / np.pi
        elif self.units == "D:M:S":
            self.uncertainty = u * (180.0 * 3600.0) / np.pi
        elif self.units == "deg":
            self.uncertainty = u * 180.0 / np.pi
        else:
            self.uncertainty = u


class prefixParameter:
    """Factory for indexed families (F2.., DMX_0001, WXSIN_0001, ...).

    Reference parity: prefixParameter wraps a template parameter type and
    stamps out indexed instances on demand (model_builder routes unknown
    names like ``DMX_0007`` here).
    """

    def __init__(
        self,
        prefix: str,
        index_format: str = "d",
        template: Callable[[str], Parameter] = None,
        start_index: int = 0,
    ):
        self.prefix = prefix
        self.index_format = index_format
        self.template = template
        self.start_index = start_index

    def match(self, name: str) -> Optional[int]:
        name = name.upper()
        p = self.prefix.upper()
        if not name.startswith(p):
            return None
        tail = name[len(p):]
        if not tail.isdigit():
            return None
        return int(tail)

    def instance(self, index: int) -> Parameter:
        name = f"{self.prefix}{index:{self.index_format}}"
        par = self.template(name)
        par.index = index
        return par


def prefix_index(name: str, prefix: str) -> Optional[int]:
    """Index of a prefixed-family name: ('F12','F')->12; None if ``name``
    is not ``prefix`` + digits.  Shared by component new_prefix_param
    hooks so naming edge cases live in one place."""
    name = name.upper()
    p = prefix.upper()
    if not name.startswith(p):
        return None
    tail = name[len(p):]
    return int(tail) if tail.isdigit() else None


def split_prefixed_name(name: str) -> tuple[str, str, int]:
    """'DMX_0017' -> ('DMX_', '0017', 17); 'F12' -> ('F', '12', 12)."""
    m = re.match(r"^([A-Za-z0-9_]*?[A-Za-z_])(\d+)$", name)
    if m is None:
        raise PrefixError(f"{name!r} is not a prefixed parameter name")
    return m.group(1), m.group(2), int(m.group(2))


class maskParameter(floatParameter):
    """Parameter applying only to a TOA subset (JUMP, EFAC, EQUAD, ...).

    Selection criteria (one per instance, tempo par syntax):
      ``JUMP -f L-wide 0.5``   flag -f == L-wide
      ``JUMP mjd 55000 56000`` mjd range
      ``JUMP freq 1000 2000``  freq range (MHz)
      ``JUMP tel gbt``         observatory
    ``select(toas)`` -> boolean mask over TOAs; masks are computed
    host-side at model-build time and become static arrays in the compiled
    kernel (SURVEY.md §7 hard-part #2).
    """

    param_type = "mask"

    def __init__(self, name, index=1, key=None, key_value=(), **kw):
        self.index = index
        self.key = key  # '-flag', 'mjd', 'freq', 'tel'
        self.key_value = list(key_value)
        base = re.sub(r"\d+$", "", name)
        self.prefix = base
        super().__init__(name, **kw)

    def set_from_tokens(self, tokens):
        # tokens: key key_values... value [fit] [unc]
        key = tokens[0]
        if key.lower() in ("mjd", "freq"):
            self.key = key.lower()
            self.key_value = [float(tokens[1]), float(tokens[2])]
            rest = tokens[3:]
        elif key.lower() in ("tel", "name"):
            self.key = key.lower()
            self.key_value = [tokens[1]]
            rest = tokens[2:]
        elif key.startswith("-"):
            self.key = key
            self.key_value = [tokens[1]]
            rest = tokens[2:]
        else:
            raise PintTpuError(
                f"cannot parse mask parameter {self.name} key {key!r}"
            )
        if rest:
            super().set_from_tokens(rest)
        else:
            self.value = 0.0

    def select(self, toas) -> np.ndarray:
        """Boolean mask over a TOAs table (host-side)."""
        n = len(toas)
        if self.key is None:
            return np.ones(n, dtype=bool)
        if self.key == "mjd":
            m = toas.mjd_float()
            return (m >= self.key_value[0]) & (m <= self.key_value[1])
        if self.key == "freq":
            return (toas.freq >= self.key_value[0]) & (
                toas.freq <= self.key_value[1]
            )
        if self.key == "tel":
            try:
                from pint_tpu.observatories import get_observatory

                want = get_observatory(self.key_value[0]).name
                return np.array(
                    [get_observatory(o).name == want for o in toas.obs]
                )
            except ImportError:
                # registry not yet available: literal (case-insensitive)
                # site-code comparison
                want = self.key_value[0].lower()
                return np.array([o.lower() == want for o in toas.obs])
        # flag key
        flag = self.key.lstrip("-")
        want = str(self.key_value[0])
        return np.array(
            [str(f.get(flag, "")) == want for f in toas.flags]
        )

    def as_parfile_line(self):
        if self._value is None:
            return ""
        if self.key is None:
            key_str = ""
        elif self.key in ("mjd", "freq"):
            key_str = f"{self.key} {self.key_value[0]:.8f} {self.key_value[1]:.8f} "
        else:
            key_str = f"{self.key} {self.key_value[0]} "
        line = f"{self.name_no_index:<8} {key_str}{self._format_value()}"
        if not self.frozen:
            line += " 1"
        if self.uncertainty is not None:
            if self.frozen:
                line += " 0"
            line += f" {self.uncertainty:.8g}"
        return line + "\n"

    @property
    def name_no_index(self):
        return self.prefix


class pairParameter(Parameter):
    """Two-component parameter (WAVE1 = sin cos amplitudes)."""

    param_type = "pair"

    def _coerce(self, v):
        a, b = v
        return (float(a), float(b))

    def set_from_tokens(self, tokens):
        self.value = (_parse_float_str(tokens[0]), _parse_float_str(tokens[1]))

    def _format_value(self):
        return f"{self._value[0]:.17g} {self._value[1]:.17g}"

    def internal(self):
        return (
            self._value[0] * self.scale_to_internal,
            self._value[1] * self.scale_to_internal,
        )


class funcParameter(Parameter):
    """Read-only derived parameter computed from others (reference parity:
    funcParameter)."""

    param_type = "func"

    def __init__(self, name, func=None, params=(), **kw):
        self._func = func
        self._params = params
        super().__init__(name, **kw)

    def evaluate(self, model):
        vals = [getattr(model, p).value for p in self._params]
        if any(v is None for v in vals):
            return None
        return self._func(*vals)

    def as_parfile_line(self):
        return ""  # derived, never written
