"""Nested sampling over the jitted timing likelihood.

Reference parity: bayesian.py::BayesianTiming.prior_transform is the
reference's nestle/dynesty integration surface (its docs feed exactly
this callable to ``nestle.sample``).  nestle is unavailable here by
design, so this module is the native consumer: an ellipsoid-rejection
nested sampler (Skilling 2004) with device-batched likelihood
evaluation — candidates are proposed in the unit cube, mapped through
prior_transform, and scored in vmapped batches so each iteration costs
one device dispatch at most; accepted-but-unused candidates above the
current likelihood threshold are pooled and reused while the
threshold allows.

method='multi' (default; nestle's 'multi' class, VERDICT r4 missing
4) recursively splits the live set with 2-means and keeps the split
when the child bounding ellipsoids' total volume is clearly below the
parent's — a separated multimodal posterior gets one ellipsoid per
mode, where the 'single' method's lone ellipsoid spans the void
between modes and the rejection loop starves (the SINGLE method is
kept for comparison and regression; tests/test_nested.py pins a
bimodal case where it provably fails).  Multi-ellipsoid proposals draw
an ellipsoid by volume and accept with probability 1/q (q = number of
ellipsoids containing the candidate) so the proposal density stays
uniform over the union.

Returns evidence (logz ± logzerr from the information H), the dead
points with importance weights, and equal-weight posterior samples.
"""

from __future__ import annotations

import numpy as np


def _bounding_ellipsoid(cubes, enlarge):
    """(mean, L) with L the Cholesky factor of the covariance scaled to
    contain every live point, inflated by ``enlarge``."""
    d = cubes.shape[1]
    mean = cubes.mean(axis=0)
    dx = cubes - mean
    cov = dx.T @ dx / max(1, len(cubes) - 1) + 1e-14 * np.eye(d)
    cinv = np.linalg.inv(cov)
    d2 = np.einsum("ij,jk,ik->i", dx, cinv, dx).max()
    return mean, np.linalg.cholesky(cov * d2) * enlarge


def _sample_ellipsoid(rng, mean, L, m):
    d = len(mean)
    z = rng.normal(size=(m, d))
    z /= np.linalg.norm(z, axis=1, keepdims=True)
    r = rng.uniform(size=(m, 1)) ** (1.0 / d)
    return mean + (z * r) @ L.T


def _logvol(L):
    """log volume of the ellipsoid with Cholesky factor L, up to the
    (constant) unit-ball volume — only ratios are ever compared."""
    return float(np.sum(np.log(np.abs(np.diagonal(L)))))


def _kmeans2(pts, iters: int = 12):
    """Deterministic 2-means: seeded by the extremes of the first
    principal axis (the split direction a separated pair of modes
    actually has)."""
    dx = pts - pts.mean(axis=0)
    # leading principal axis via the thin SVD of the centered cloud
    # (nlive x d is small; SVD also behaves on degenerate clouds where
    # a covariance eig could return noise directions)
    _, _, vt = np.linalg.svd(dx, full_matrices=False)
    proj = dx @ vt[0]
    c = np.stack([pts[int(np.argmin(proj))], pts[int(np.argmax(proj))]])
    for _ in range(iters):
        d0 = np.linalg.norm(pts - c[0], axis=1)
        d1 = np.linalg.norm(pts - c[1], axis=1)
        lab = (d1 < d0)
        if lab.all() or (~lab).all():
            break
        c = np.stack([pts[~lab].mean(axis=0), pts[lab].mean(axis=0)])
    return pts[~lab], pts[lab]


def _build_ellipsoids(cubes, enlarge, min_pts, max_depth: int = 6,
                      split_factor: float = 0.5):
    """Recursive multi-ellipsoid decomposition of the live set.  A
    2-means split is kept only when the children's total volume is
    below ``split_factor`` of the parent's — a unimodal cloud splits
    into two halves of roughly the parent volume and is NOT split,
    while separated modes shrink the total by orders of magnitude."""
    ells = []

    def recurse(pts, depth):
        mean, L = _bounding_ellipsoid(pts, enlarge)
        if depth < max_depth and len(pts) >= 2 * min_pts:
            a, b = _kmeans2(pts)
            if min(len(a), len(b)) >= min_pts:
                la = _bounding_ellipsoid(a, enlarge)
                lb = _bounding_ellipsoid(b, enlarge)
                tot = np.logaddexp(_logvol(la[1]), _logvol(lb[1]))
                if tot < _logvol(L) + np.log(split_factor):
                    recurse(a, depth + 1)
                    recurse(b, depth + 1)
                    return
        ells.append((mean, L))

    recurse(np.asarray(cubes), 0)
    return ells


def _sample_multi(rng, ells, m):
    """m candidates uniform over the ellipsoid UNION: draw an
    ellipsoid by volume, sample it, accept with probability 1/q where
    q counts the ellipsoids containing the draw."""
    logv = np.array([_logvol(L) for _, L in ells])
    p = np.exp(logv - logv.max())
    p /= p.sum()
    which = rng.choice(len(ells), size=m, p=p)
    out = np.empty((m, len(ells[0][0])))
    for e, (mean, L) in enumerate(ells):
        sel = which == e
        if sel.any():
            out[sel] = _sample_ellipsoid(rng, mean, L, int(sel.sum()))
    if len(ells) == 1:
        return out
    # multiplicity correction
    q = np.zeros(m)
    for mean, L in ells:
        y = np.linalg.solve(L, (out - mean).T).T
        q += (np.einsum("ij,ij->i", y, y) <= 1.0 + 1e-12)
    keep = rng.uniform(size=m) < 1.0 / np.maximum(q, 1.0)
    return out[keep]


def nested_init(
    loglike_batch,
    prior_transform,
    ndim: int,
    nlive: int = 200,
    batch: int = 128,
    dlogz: float = 0.1,
    max_iter: int = 200000,
    enlarge: float = 1.25,
    seed: int = 0,
    method: str = "multi",
) -> dict:
    """Draw the initial live set and return the full sampler state.

    The state dict is everything nested_iterate/nested_result need —
    live points, dead lists, evidence accumulators, the candidate
    pool, the host RNG, and the run configuration — so a run can be
    segmented at iteration granularity (the background-job quantum,
    serve/jobs/runner.py) and checkpointed between segments
    (nested_checkpoint_state / nested_restore_state) without changing
    a single RNG draw relative to the uninterrupted nested_sample."""
    if method not in ("multi", "single"):
        raise ValueError(f"unknown method {method!r}")
    rng = np.random.default_rng(seed)
    cubes = rng.uniform(size=(nlive, ndim))
    X = np.stack([prior_transform(c) for c in cubes])
    logl = np.array(loglike_batch(X), dtype=np.float64)  # writable copy
    # NaN likelihoods (overflowed residuals at extreme prior draws)
    # are treated as impossible, exactly like -inf; they then die
    # first and carry zero weight (see the logwt guard below)
    logl[np.isnan(logl)] = -np.inf
    return dict(
        rng=rng, cubes=cubes, X=X, logl=logl, ncall=nlive,
        logz=-np.inf, h=0.0, nells_max=0,
        dead_x=[], dead_logl=[], dead_logwt=[],
        pool_c=np.empty((0, ndim)), pool_x=np.empty((0, ndim)),
        pool_l=np.empty(0), it=0, done=False,
        ndim=ndim, nlive=nlive, batch=batch, dlogz=dlogz,
        max_iter=max_iter, enlarge=enlarge, method=method,
    )


def nested_iterate(st: dict, loglike_batch, prior_transform,
                   n_iter: int) -> bool:
    """Advance the sampler by up to ``n_iter`` dead points (in place).
    Returns True when the run has terminated (dlogz criterion or
    max_iter) — call nested_result exactly once after that.  The loop
    body is the former nested_sample interior verbatim, so a chunked
    run is draw-for-draw identical to the monolithic one."""
    rng, nlive, batch = st["rng"], st["nlive"], st["batch"]
    dlogz, enlarge, method = st["dlogz"], st["enlarge"], st["method"]
    ndim = st["ndim"]
    cubes, X, logl = st["cubes"], st["X"], st["logl"]
    pool_c, pool_x, pool_l = st["pool_c"], st["pool_x"], st["pool_l"]
    logz, h, it = st["logz"], st["h"], st["it"]
    end = it + max(0, int(n_iter))
    while it < end and it < st["max_iter"]:
        # termination BEFORE recording the worst point: the remaining
        # evidence is bounded by the max live logl over the current
        # volume; checking here keeps the dead and live sets disjoint
        # (recording then breaking would count the worst point twice —
        # once with its shell weight, once in the live flush below)
        logz_remain = float(logl.max()) - it / nlive
        if (
            np.isfinite(logz)
            and np.logaddexp(logz, logz_remain) - logz < dlogz
        ):
            st["done"] = True
            break
        i_min = int(np.argmin(logl))
        l_min = float(logl[i_min])
        # shell volume between successive prior-volume shrinkages
        lv0, lv1 = -it / nlive, -(it + 1) / nlive
        logdvol = lv1 + np.log(np.expm1(lv0 - lv1))
        logwt = l_min + logdvol
        if np.isfinite(logwt):
            logz_new = np.logaddexp(logz, logwt)
            prev = (
                np.exp(logz - logz_new) * (h + logz)
                if np.isfinite(logz) else 0.0
            )
            h = np.exp(logwt - logz_new) * l_min + prev - logz_new
            logz = logz_new
        # else: an impossible point (l_min = -inf) carries zero
        # weight — updating H through it would make logzerr NaN
        st["dead_x"].append(X[i_min].copy())
        st["dead_logl"].append(l_min)
        st["dead_logwt"].append(logwt)

        # replacement: pool first (threshold only rises), else propose
        keep = pool_l > l_min
        pool_c, pool_x, pool_l = pool_c[keep], pool_x[keep], pool_l[keep]
        rounds = 0
        ell = None  # live set is invariant until a replacement lands
        while len(pool_l) == 0:
            rounds += 1
            if rounds > 1000:
                # likelihood plateau (or an all-impossible start, or a
                # separated multimodal set under method='single'): no
                # candidate can exceed l_min, so the rejection loop
                # would spin forever — fail loudly with the state
                raise RuntimeError(
                    f"nested_sample: no candidate exceeded logl="
                    f"{l_min!r} after {rounds - 1} proposal rounds "
                    f"({(rounds - 1) * batch} draws) at iteration "
                    f"{it}; the likelihood is flat (or -inf) over "
                    "the sampled region"
                )
            if ell is None:
                if method == "multi":
                    ell = _build_ellipsoids(
                        cubes, enlarge, min_pts=max(2 * ndim, 5)
                    )
                    st["nells_max"] = max(st["nells_max"], len(ell))
                else:
                    ell = [_bounding_ellipsoid(cubes, enlarge)]
                    st["nells_max"] = max(st["nells_max"], 1)
            cand = (
                _sample_multi(rng, ell, batch) if len(ell) > 1
                else _sample_ellipsoid(rng, *ell[0], batch)
            )
            ok = np.all((cand >= 0.0) & (cand < 1.0), axis=1)
            cand = cand[ok]
            if len(cand) == 0:
                continue
            cx = np.stack([prior_transform(c) for c in cand])
            # pad to the fixed batch length so a jitted vectorized
            # likelihood compiles ONCE (varying survivor counts would
            # otherwise recompile per shape — r4 review)
            npad = batch - len(cx)
            cx_pad = (
                np.concatenate([cx, np.repeat(cx[:1], npad, axis=0)])
                if npad else cx
            )
            cl = np.asarray(
                loglike_batch(cx_pad), dtype=np.float64
            )[: len(cx)]
            st["ncall"] += len(cx_pad)  # padded rows are evaluated too
            good = cl > l_min
            pool_c, pool_x, pool_l = cand[good], cx[good], cl[good]
        cubes[i_min] = pool_c[0]
        X[i_min] = pool_x[0]
        logl[i_min] = pool_l[0]
        pool_c, pool_x, pool_l = pool_c[1:], pool_x[1:], pool_l[1:]
        it += 1
    else:
        if it >= st["max_iter"]:
            st["done"] = True
    st["pool_c"], st["pool_x"], st["pool_l"] = pool_c, pool_x, pool_l
    st["logz"], st["h"], st["it"] = logz, h, it
    return st["done"]


def nested_result(st: dict) -> dict:
    """Flush the final live points into the dead set and build the
    result dict (the former nested_sample epilogue; consumes the state
    RNG for the equal-weight resampling — call once)."""
    nlive, it = st["nlive"], st["it"]
    X, logl = st["X"], st["logl"]
    logz, h = st["logz"], st["h"]
    dead_x = list(st["dead_x"])
    dead_logl = list(st["dead_logl"])
    dead_logwt = list(st["dead_logwt"])
    # final live points: each carries 1/nlive of the remaining volume
    logdvol = -it / nlive - np.log(nlive)
    for j in range(nlive):
        logwt = float(logl[j]) + logdvol
        if np.isfinite(logwt):
            logz_new = np.logaddexp(logz, logwt)
            prev = (
                np.exp(logz - logz_new) * (h + logz)
                if np.isfinite(logz) else 0.0
            )
            h = (np.exp(logwt - logz_new) * float(logl[j])
                 + prev - logz_new)
            logz = logz_new
        dead_x.append(X[j].copy())
        dead_logl.append(float(logl[j]))
        dead_logwt.append(logwt)

    dead_x = np.stack(dead_x)
    dead_logl = np.asarray(dead_logl)
    dead_logwt = np.asarray(dead_logwt)
    logzerr = float(np.sqrt(max(h, 0.0) / nlive))
    # equal-weight posterior resampling
    p = np.exp(dead_logwt - dead_logwt.max())
    p /= p.sum()
    neff = int(1.0 / np.sum(p * p))
    idx = st["rng"].choice(len(p), size=max(neff, 1), p=p)
    return dict(
        logz=float(logz), logzerr=logzerr, h=float(h), niter=it,
        ncall=int(st["ncall"]), samples=dead_x[idx], samples_raw=dead_x,
        logwt=dead_logwt, logl=dead_logl,
        nells=max(st["nells_max"], 1),
    )


_NESTED_SCALARS = (
    "ncall", "logz", "h", "nells_max", "it", "done", "ndim", "nlive",
    "batch", "dlogz", "max_iter", "enlarge", "method",
)
_NESTED_ARRAYS = ("cubes", "X", "logl", "pool_c", "pool_x", "pool_l")
_NESTED_LISTS = ("dead_logl", "dead_logwt")


def nested_checkpoint_state(st: dict) -> dict:
    """State -> a flat npz-able payload (checkpoint.save_job).  The
    host Generator serializes via its bit_generator state dict (rides
    as a pickled object array); dead_x keeps its per-point list
    structure as a stacked array + count."""
    out = {"rng_state": st["rng"].bit_generator.state}
    for k in _NESTED_SCALARS:
        out[k] = st[k]
    for k in _NESTED_ARRAYS:
        out[k] = np.asarray(st[k])
    for k in _NESTED_LISTS:
        out[k] = np.asarray(st[k], dtype=np.float64)
    out["n_dead"] = len(st["dead_x"])
    out["dead_x"] = (
        np.stack(st["dead_x"]) if st["dead_x"]
        else np.empty((0, st["ndim"]))
    )
    return out


def nested_restore_state(payload: dict) -> dict:
    """Inverse of nested_checkpoint_state — the restored state resumes
    draw-for-draw where the checkpoint left off."""
    st = {}
    for k in _NESTED_SCALARS:
        v = payload[k]
        v = v.item() if hasattr(v, "item") else v
        st[k] = str(v) if k == "method" else v
    st["it"] = int(st["it"])
    st["done"] = bool(st["done"])
    for k in ("ndim", "nlive", "batch", "max_iter", "ncall",
              "nells_max"):
        st[k] = int(st[k])
    for k in _NESTED_ARRAYS:
        st[k] = np.array(payload[k], dtype=np.float64)
    for k in _NESTED_LISTS:
        st[k] = [float(v) for v in np.asarray(payload[k])]
    st["dead_x"] = [
        row.copy() for row in np.asarray(payload["dead_x"],
                                         dtype=np.float64)
    ]
    rng = np.random.default_rng(0)
    rng.bit_generator.state = payload["rng_state"]
    st["rng"] = rng
    return st


def nested_sample(
    loglike_batch,
    prior_transform,
    ndim: int,
    nlive: int = 200,
    batch: int = 128,
    dlogz: float = 0.1,
    max_iter: int = 200000,
    enlarge: float = 1.25,
    seed: int = 0,
    method: str = "multi",
):
    """Run ellipsoid-rejection nested sampling.

    loglike_batch: (m, ndim) parameter array -> (m,) log-likelihoods
      (wrap a jitted vmapped likelihood; called with full parameter
      vectors from prior_transform).
    prior_transform: unit-cube vector -> parameter vector (the
      BayesianTiming.prior_transform contract).
    method: 'multi' (default; recursive 2-means ellipsoid
      decomposition, handles separated multimodal posteriors) or
      'single' (one bounding ellipsoid — nestle's 'single').

    Composed of nested_init / nested_iterate / nested_result so the
    background-job runner can execute the identical computation in
    preemptible segments; this monolithic driver is draw-for-draw the
    same run.

    Returns a dict with logz, logzerr, niter, ncall, samples
    (equal-weight posterior), samples_raw, logwt, logl, and nells
    (max simultaneous ellipsoid count seen — 1 for unimodal runs).
    """
    st = nested_init(
        loglike_batch, prior_transform, ndim, nlive=nlive, batch=batch,
        dlogz=dlogz, max_iter=max_iter, enlarge=enlarge, seed=seed,
        method=method,
    )
    while not nested_iterate(st, loglike_batch, prior_transform,
                             max_iter):
        pass
    return nested_result(st)
