"""Nested sampling over the jitted timing likelihood.

Reference parity: bayesian.py::BayesianTiming.prior_transform is the
reference's nestle/dynesty integration surface (its docs feed exactly
this callable to ``nestle.sample``).  nestle is unavailable here by
design, so this module is the native consumer: a single-bounding-
ellipsoid rejection nested sampler (Skilling 2004; the 'single' method
of nestle) with device-batched likelihood evaluation — candidates are
proposed in the unit cube, mapped through prior_transform, and scored
in vmapped batches so each iteration costs one device dispatch at
most; accepted-but-unused candidates above the current likelihood
threshold are pooled and reused while the threshold allows.

Returns evidence (logz ± logzerr from the information H), the dead
points with importance weights, and equal-weight posterior samples.
"""

from __future__ import annotations

import numpy as np


def _bounding_ellipsoid(cubes, enlarge):
    """(mean, L) with L the Cholesky factor of the covariance scaled to
    contain every live point, inflated by ``enlarge``."""
    d = cubes.shape[1]
    mean = cubes.mean(axis=0)
    dx = cubes - mean
    cov = dx.T @ dx / max(1, len(cubes) - 1) + 1e-14 * np.eye(d)
    cinv = np.linalg.inv(cov)
    d2 = np.einsum("ij,jk,ik->i", dx, cinv, dx).max()
    return mean, np.linalg.cholesky(cov * d2) * enlarge


def _sample_ellipsoid(rng, mean, L, m):
    d = len(mean)
    z = rng.normal(size=(m, d))
    z /= np.linalg.norm(z, axis=1, keepdims=True)
    r = rng.uniform(size=(m, 1)) ** (1.0 / d)
    return mean + (z * r) @ L.T


def nested_sample(
    loglike_batch,
    prior_transform,
    ndim: int,
    nlive: int = 200,
    batch: int = 128,
    dlogz: float = 0.1,
    max_iter: int = 200000,
    enlarge: float = 1.25,
    seed: int = 0,
):
    """Run single-ellipsoid nested sampling.

    loglike_batch: (m, ndim) parameter array -> (m,) log-likelihoods
      (wrap a jitted vmapped likelihood; called with full parameter
      vectors from prior_transform).
    prior_transform: unit-cube vector -> parameter vector (the
      BayesianTiming.prior_transform contract).

    Returns a dict with logz, logzerr, niter, ncall, samples
    (equal-weight posterior), samples_raw, logwt, logl.
    """
    rng = np.random.default_rng(seed)
    cubes = rng.uniform(size=(nlive, ndim))
    X = np.stack([prior_transform(c) for c in cubes])
    logl = np.array(loglike_batch(X), dtype=np.float64)  # writable copy
    # NaN likelihoods (overflowed residuals at extreme prior draws)
    # are treated as impossible, exactly like -inf; they then die
    # first and carry zero weight (see the logwt guard below)
    logl[np.isnan(logl)] = -np.inf
    ncall = nlive

    logz = -np.inf
    h = 0.0
    dead_x, dead_logl, dead_logwt = [], [], []
    pool_c, pool_x, pool_l = (
        np.empty((0, ndim)), np.empty((0, ndim)), np.empty(0)
    )

    it = 0
    while it < max_iter:
        # termination BEFORE recording the worst point: the remaining
        # evidence is bounded by the max live logl over the current
        # volume; checking here keeps the dead and live sets disjoint
        # (recording then breaking would count the worst point twice —
        # once with its shell weight, once in the live flush below)
        logz_remain = float(logl.max()) - it / nlive
        if (
            np.isfinite(logz)
            and np.logaddexp(logz, logz_remain) - logz < dlogz
        ):
            break
        i_min = int(np.argmin(logl))
        l_min = float(logl[i_min])
        # shell volume between successive prior-volume shrinkages
        lv0, lv1 = -it / nlive, -(it + 1) / nlive
        logdvol = lv1 + np.log(np.expm1(lv0 - lv1))
        logwt = l_min + logdvol
        if np.isfinite(logwt):
            logz_new = np.logaddexp(logz, logwt)
            prev = (
                np.exp(logz - logz_new) * (h + logz)
                if np.isfinite(logz) else 0.0
            )
            h = np.exp(logwt - logz_new) * l_min + prev - logz_new
            logz = logz_new
        # else: an impossible point (l_min = -inf) carries zero
        # weight — updating H through it would make logzerr NaN
        dead_x.append(X[i_min].copy())
        dead_logl.append(l_min)
        dead_logwt.append(logwt)

        # replacement: pool first (threshold only rises), else propose
        keep = pool_l > l_min
        pool_c, pool_x, pool_l = pool_c[keep], pool_x[keep], pool_l[keep]
        rounds = 0
        ell = None  # live set is invariant until a replacement lands
        while len(pool_l) == 0:
            rounds += 1
            if rounds > 1000:
                # likelihood plateau (or an all-impossible start): no
                # candidate can exceed l_min, so the rejection loop
                # would spin forever — fail loudly with the state
                raise RuntimeError(
                    f"nested_sample: no candidate exceeded logl="
                    f"{l_min!r} after {rounds - 1} proposal rounds "
                    f"({(rounds - 1) * batch} draws) at iteration "
                    f"{it}; the likelihood is flat (or -inf) over "
                    "the sampled region"
                )
            if ell is None:
                ell = _bounding_ellipsoid(cubes, enlarge)
            cand = _sample_ellipsoid(rng, *ell, batch)
            ok = np.all((cand >= 0.0) & (cand < 1.0), axis=1)
            cand = cand[ok]
            if len(cand) == 0:
                continue
            cx = np.stack([prior_transform(c) for c in cand])
            # pad to the fixed batch length so a jitted vectorized
            # likelihood compiles ONCE (varying survivor counts would
            # otherwise recompile per shape — r4 review)
            npad = batch - len(cx)
            cx_pad = (
                np.concatenate([cx, np.repeat(cx[:1], npad, axis=0)])
                if npad else cx
            )
            cl = np.asarray(
                loglike_batch(cx_pad), dtype=np.float64
            )[: len(cx)]
            ncall += len(cx_pad)  # padded rows are evaluated too
            good = cl > l_min
            pool_c, pool_x, pool_l = cand[good], cx[good], cl[good]
        cubes[i_min] = pool_c[0]
        X[i_min] = pool_x[0]
        logl[i_min] = pool_l[0]
        pool_c, pool_x, pool_l = pool_c[1:], pool_x[1:], pool_l[1:]
        it += 1

    # final live points: each carries 1/nlive of the remaining volume
    logdvol = -it / nlive - np.log(nlive)
    for j in range(nlive):
        logwt = float(logl[j]) + logdvol
        if np.isfinite(logwt):
            logz_new = np.logaddexp(logz, logwt)
            prev = (
                np.exp(logz - logz_new) * (h + logz)
                if np.isfinite(logz) else 0.0
            )
            h = (np.exp(logwt - logz_new) * float(logl[j])
                 + prev - logz_new)
            logz = logz_new
        dead_x.append(X[j].copy())
        dead_logl.append(float(logl[j]))
        dead_logwt.append(logwt)

    dead_x = np.stack(dead_x)
    dead_logl = np.asarray(dead_logl)
    dead_logwt = np.asarray(dead_logwt)
    logzerr = float(np.sqrt(max(h, 0.0) / nlive))
    # equal-weight posterior resampling
    p = np.exp(dead_logwt - dead_logwt.max())
    p /= p.sum()
    neff = int(1.0 / np.sum(p * p))
    idx = rng.choice(len(p), size=max(neff, 1), p=p)
    return dict(
        logz=float(logz), logzerr=logzerr, h=float(h), niter=it,
        ncall=int(ncall), samples=dead_x[idx], samples_raw=dead_x,
        logwt=dead_logwt, logl=dead_logl,
    )
