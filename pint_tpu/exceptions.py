"""Central exception types (reference parity: src/pint/exceptions.py)."""


class PintTpuError(Exception):
    """Base class for all pint_tpu errors."""


class MissingParameter(PintTpuError):
    """A required timing-model parameter is absent or unset."""

    def __init__(self, module="", param="", msg=None):
        self.module = module
        self.param = param
        super().__init__(msg or f"{module} is missing parameter {param}")


class MissingTOAs(PintTpuError):
    """A mask parameter selects no TOAs."""

    def __init__(self, parameter_names=()):
        if isinstance(parameter_names, str):
            parameter_names = [parameter_names]
        self.parameter_names = list(parameter_names)
        super().__init__(f"Parameters {self.parameter_names} select no TOAs")


class MissingClockCorrection(PintTpuError):
    """No clock correction available for an observatory/epoch."""


class ClockCorrectionOutOfRange(PintTpuError):
    """A TOA falls outside the span of the observatory clock file."""


class DataFileError(PintTpuError, ValueError):
    """Malformed runtime data file (EOP tables, clock files, ...).
    Also a ValueError so pre-r4 except clauses keep working; being a
    PintTpuError lets environment-sensitive consumers (the TZR
    build-time ingest) classify it as deferrable."""


class EphemerisError(PintTpuError):
    """Ephemeris file/segment problems (reference: jplephem errors)."""


class EphemerisFormatError(EphemerisError, ValueError):
    """Malformed/unsupported SPK/DAF file.  Also a ValueError so
    pre-r4 callers' except clauses keep working."""


class EphemerisSegmentError(EphemerisError, KeyError):
    """Missing target/center segment or chain to the SSB.  Also a
    KeyError: the ephemeris fallback policy
    (ephemeris/time_ephemeris.py::_posvel) catches KeyError to retry
    with NAIF ids / the builtin theory."""

    # KeyError.__str__ repr-quotes the message; keep plain formatting
    __str__ = Exception.__str__


class UnknownObservatory(PintTpuError):
    """Observatory name not found in the registry."""


class UnknownParameter(PintTpuError):
    """Par-file line not understood by any component."""


class TimingModelError(PintTpuError):
    """Ill-formed timing model (validation failure)."""


class PrefixError(PintTpuError):
    """Malformed prefix-parameter name."""


class ConvergenceFailure(PintTpuError):
    """A fitter failed to converge."""


class MaxiterReached(ConvergenceFailure):
    """Downhill fitter hit the iteration limit without meeting tolerance."""


class StepProblem(ConvergenceFailure):
    """Downhill fitter could not find a chi2-decreasing step."""


class InvalidModelParameters(PintTpuError):
    """A proposed step produced non-finite / unphysical parameters."""


class CorrelatedErrors(PintTpuError):
    """Model has correlated noise but the fitter cannot handle it."""

    def __init__(self, model):
        trouble = [c.__class__.__name__ for c in model.noise_components if c.introduces_correlated_errors]
        super().__init__(
            f"Model has correlated errors ({trouble}); use a GLS fitter"
        )


class DegeneracyWarning(UserWarning):
    """Design matrix is degenerate; some parameters are unconstrained."""


class ConvergenceWarning(UserWarning):
    """A fitter stopped without meeting its convergence tolerance."""


class PropertyAttributeError(PintTpuError):
    """Error raised inside a property getter (reference parity)."""
