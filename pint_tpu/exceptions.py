"""Central exception types (reference parity: src/pint/exceptions.py)."""


class PintTpuError(Exception):
    """Base class for all pint_tpu errors."""


class MissingParameter(PintTpuError):
    """A required timing-model parameter is absent or unset."""

    def __init__(self, module="", param="", msg=None):
        self.module = module
        self.param = param
        super().__init__(msg or f"{module} is missing parameter {param}")


class MissingTOAs(PintTpuError):
    """A mask parameter selects no TOAs."""

    def __init__(self, parameter_names=()):
        if isinstance(parameter_names, str):
            parameter_names = [parameter_names]
        self.parameter_names = list(parameter_names)
        super().__init__(f"Parameters {self.parameter_names} select no TOAs")


class MissingClockCorrection(PintTpuError):
    """No clock correction available for an observatory/epoch."""


class ClockCorrectionOutOfRange(PintTpuError):
    """A TOA falls outside the span of the observatory clock file."""


class DataFileError(PintTpuError, ValueError):
    """Malformed runtime data file (EOP tables, clock files, ...).
    Also a ValueError so pre-r4 except clauses keep working; being a
    PintTpuError lets environment-sensitive consumers (the TZR
    build-time ingest) classify it as deferrable."""


class EphemerisError(PintTpuError):
    """Ephemeris file/segment problems (reference: jplephem errors)."""


class EphemerisFormatError(EphemerisError, ValueError):
    """Malformed/unsupported SPK/DAF file.  Also a ValueError so
    pre-r4 callers' except clauses keep working."""


class EphemerisSegmentError(EphemerisError, KeyError):
    """Missing target/center segment or chain to the SSB.  Also a
    KeyError: the ephemeris fallback policy
    (ephemeris/time_ephemeris.py::_posvel) catches KeyError to retry
    with NAIF ids / the builtin theory."""

    # KeyError.__str__ repr-quotes the message; keep plain formatting
    __str__ = Exception.__str__


class UnknownObservatory(PintTpuError):
    """Observatory name not found in the registry."""


class UnknownParameter(PintTpuError):
    """Par-file line not understood by any component."""


class TimingModelError(PintTpuError):
    """Ill-formed timing model (validation failure)."""


class PrefixError(PintTpuError):
    """Malformed prefix-parameter name."""


class ConvergenceFailure(PintTpuError):
    """A fitter failed to converge."""


class MaxiterReached(ConvergenceFailure):
    """Downhill fitter hit the iteration limit without meeting tolerance."""


class StepProblem(ConvergenceFailure):
    """Downhill fitter could not find a chi2-decreasing step."""


class InvalidModelParameters(PintTpuError):
    """A proposed step produced non-finite / unphysical parameters."""


class PintTpuNumericsError(ConvergenceFailure):
    """A device computation produced non-finite values (NaN/Inf).

    Raised by the shared finite-state validator
    (runtime/guard.py::validate_finite) with a ``diagnosis`` mapping
    the symptom onto the known emulated-f64 hazard taxonomy
    (docs/precision.md / docs/robustness.md): exponent-range overflow,
    subnormal flush, scalar-transcendental path.  Subclasses
    ConvergenceFailure so pre-existing except clauses around fitters
    keep working."""

    def __init__(self, msg, diagnosis=None):
        self.diagnosis = diagnosis
        super().__init__(msg)


class GuardTimeout(PintTpuError):
    """A guarded compile/dispatch exceeded its watchdog timeout
    (runtime/guard.py) — the axon tunnel can wedge silently, so this is
    detected by a host-side watchdog thread, not by the transport."""

    def __init__(self, site="", timeout=None, msg=None):
        self.site = site
        self.timeout = timeout
        super().__init__(
            msg
            or f"guarded call at {site or 'unknown site'} exceeded its "
            f"{timeout}s watchdog (wedged compile/dispatch?)"
        )


class TransportRejection(PintTpuError):
    """The remote-compile/dispatch transport rejected the request
    deterministically (HTTP 413 class: payload too large).  Never
    retried with the same payload — the fallback ladder re-lowers
    instead (argument-fed operands / next rung)."""


class TransientDispatchError(PintTpuError):
    """A transient transport failure (injected by runtime/faults.py;
    real tunnel errors arrive as foreign exception types and are
    classified by runtime/guard.py::classify_error)."""


class RetriesExhausted(PintTpuError):
    """Bounded retries of a transient failure were exhausted."""

    def __init__(self, site="", attempts=0, last=None):
        self.site = site
        self.attempts = attempts
        self.last = last
        super().__init__(
            f"guarded call at {site or 'unknown site'} still failing "
            f"after {attempts} attempts (last: {last!r})"
        )


class LadderExhausted(ConvergenceFailure):
    """Every rung of the degradation ladder (runtime/fallback.py)
    tripped the guard.  ``history`` records (rung_name, error) pairs in
    the order attempted — no rung ever returned a silently-wrong
    result; they all failed loudly."""

    def __init__(self, site="", history=()):
        self.site = site
        self.history = tuple(history)
        rungs = "; ".join(f"{n}: {e}" for n, e in self.history)
        super().__init__(
            f"fallback ladder exhausted at {site or 'unknown site'} "
            f"({len(self.history)} rungs tried: {rungs})"
        )


class CorrelatedErrors(PintTpuError):
    """Model has correlated noise but the fitter cannot handle it."""

    def __init__(self, model):
        trouble = [c.__class__.__name__ for c in model.noise_components if c.introduces_correlated_errors]
        super().__init__(
            f"Model has correlated errors ({trouble}); use a GLS fitter"
        )


class CheckpointError(PintTpuError):
    """A checkpoint file (pint_tpu/checkpoint.py) could not be read:
    truncated (a pre-atomic-write torn file, or disk-full), corrupt,
    the wrong kind, or written by a newer build.  Always raised
    TYPED at load time — a torn checkpoint degrades to an explicit
    error the caller (or the background-job resume ladder,
    serve/jobs/) can act on, never a bare zipfile/KeyError crash and
    never a silently-partial resume."""


class RequestRejected(PintTpuError):
    """Typed load-shed rejection from the serving engine
    (serve/engine.py).  The backpressure contract of docs/serving.md:
    an overloaded engine REFUSES work loudly — a bounded-queue
    rejection, a missed per-request deadline, or a shutdown — and
    never hangs, OOMs, or silently drops a request.  ``reason`` is one
    of ``'queue-full'``, ``'deadline'``, ``'quota'`` (the request's
    composition is at its per-composition in-flight quota —
    ``PINT_TPU_SERVE_QUOTA``; admission fairness, ISSUE 11),
    ``'shutdown'``, ``'no-replica'`` (the serving fabric had no
    live replica left to take the batch — every candidate quarantined
    or drained), ``'jobs-disabled'`` (background class off:
    ``PINT_TPU_SERVE_JOBS=0``), or ``'jobs-queue-full'`` (the bounded
    background-job queue is at ``PINT_TPU_SERVE_JOBS_QUEUE``).  The
    full reason table clients can switch on lives in docs/serving.md
    and is pinned by tests/test_serve_slo.py."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(
            f"request rejected ({reason})"
            + (f": {detail}" if detail else "")
        )


class DegeneracyWarning(UserWarning):
    """Design matrix is degenerate; some parameters are unconstrained."""


class ConvergenceWarning(UserWarning):
    """A fitter stopped without meeting its convergence tolerance."""


class GuardTripWarning(UserWarning):
    """The device-execution guard tripped on a fallback-ladder rung and
    the computation was re-dispatched on the next rung."""


class PropertyAttributeError(PintTpuError):
    """Error raised inside a property getter (reference parity)."""
