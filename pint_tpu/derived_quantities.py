"""Closed-form astrophysical quantities from timing parameters.

Reference parity: src/pint/derived_quantities.py — mass functions,
companion/pulsar masses, characteristic age, magnetic fields, P<->F
conversions, GR post-Keplerian predictions.  Internal units: SI seconds
/ Hz / solar masses; angles in radians unless noted.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.constants import C, SECS_PER_DAY, SECS_PER_JULIAN_YEAR, TSUN

_TWO_PI = 2.0 * np.pi


def p_to_f(p, pd=None, pdd=None):
    """Period (s) [, derivatives] -> frequency (Hz) [, derivatives]."""
    f = 1.0 / p
    if pd is None:
        return f
    fd = -pd / (p * p)
    if pdd is None:
        return f, fd
    fdd = 2.0 * pd * pd / p**3 - pdd / (p * p)
    return f, fd, fdd


def pferrs(p, p_err, pd=None, pd_err=None):
    """(P, Perr[, Pdot, Pdoterr]) -> (F, Ferr[, Fdot, Fdoterr]);
    first-order error propagation (reference: utils.pferrs)."""
    f = 1.0 / p
    f_err = p_err / (p * p)
    if pd is None:
        return f, f_err
    fd = -pd / (p * p)
    fd_err = np.sqrt(
        (pd_err / p**2) ** 2 + (2.0 * pd * p_err / p**3) ** 2
    )
    return f, f_err, fd, fd_err


def pulsar_age(f0, f1, n=3.0, fo=1e99):
    """Characteristic age (yr): tau = -f/((n-1) fdot) (1-(f/fo)^(n-1))."""
    tau_s = -f0 / ((n - 1.0) * f1) * (1.0 - (f0 / fo) ** (n - 1.0))
    return tau_s / SECS_PER_JULIAN_YEAR


def pulsar_B(f0, f1):
    """Surface dipole field (Gauss): 3.2e19 sqrt(-Pdot P)."""
    p, pd = 1.0 / f0, -f1 / (f0 * f0)
    return 3.2e19 * np.sqrt(np.maximum(pd, 0.0) * p)


def pulsar_B_lightcyl(f0, f1):
    """Field at the light cylinder (Gauss); reference formula
    2.9e8 Pdot^0.5 P^-5/2."""
    p, pd = 1.0 / f0, -f1 / (f0 * f0)
    return 2.9e8 * np.sqrt(np.maximum(pd, 0.0)) * p ** (-2.5)


def pulsar_edot(f0, f1, I=1e45):
    """Spin-down luminosity (erg/s): -4 pi^2 I f fdot."""
    return -4.0 * np.pi**2 * I * f0 * f1


def mass_funct(pb_s, a1_ls):
    """Mass function (Msun): 4 pi^2 x^3 / (G Pb^2), with x in
    light-seconds and Tsun = G Msun / c^3."""
    return _TWO_PI**2 * a1_ls**3 / (pb_s**2) / TSUN


def mass_funct2(mp, mc, inc_rad):
    """(mc sin i)^3 / (mp+mc)^2 in Msun."""
    return (mc * np.sin(inc_rad)) ** 3 / (mp + mc) ** 2


def companion_mass(pb_s, a1_ls, inc_rad=np.pi / 3, mp=1.4):
    """Solve the mass function for mc (Newton iteration)."""
    mf = mass_funct(pb_s, a1_ls)
    sini = np.sin(inc_rad)
    mc = np.maximum(mf, 0.05) ** (1.0 / 3.0) * (mp + 0.5) ** (2.0 / 3.0) / sini
    for _ in range(50):
        g = (mc * sini) ** 3 / (mp + mc) ** 2 - mf
        dg = (
            3.0 * sini**3 * mc**2 / (mp + mc) ** 2
            - 2.0 * (mc * sini) ** 3 / (mp + mc) ** 3
        )
        mc = mc - g / dg
    return mc


def pulsar_mass(pb_s, a1_ls, mc, inc_rad):
    """Solve the mass function for mp given mc."""
    mf = mass_funct(pb_s, a1_ls)
    return (mc * np.sin(inc_rad)) ** 1.5 / np.sqrt(mf) - mc


def omdot(mp, mc, pb_s, ecc):
    """GR periastron advance (deg/yr)."""
    nb = _TWO_PI / pb_s
    w = (
        3.0 * nb ** (5.0 / 3.0)
        * (TSUN * (mp + mc)) ** (2.0 / 3.0)
        / (1.0 - ecc**2)
    )  # rad/s
    return np.rad2deg(w) * SECS_PER_JULIAN_YEAR


def gamma(mp, mc, pb_s, ecc):
    """GR Einstein-delay amplitude (s)."""
    nb = _TWO_PI / pb_s
    return (
        ecc * nb ** (-1.0 / 3.0) * TSUN ** (2.0 / 3.0)
        * (mp + mc) ** (-4.0 / 3.0) * mc * (mp + 2.0 * mc)
    )


def pbdot(mp, mc, pb_s, ecc):
    """GR orbital decay (s/s)."""
    nb = _TWO_PI / pb_s
    e2 = ecc * ecc
    fe = (1.0 + 73.0 / 24.0 * e2 + 37.0 / 96.0 * e2 * e2) / (
        1.0 - e2
    ) ** 3.5
    return (
        -192.0 * np.pi / 5.0 * nb ** (5.0 / 3.0) * fe
        * TSUN ** (5.0 / 3.0) * mp * mc * (mp + mc) ** (-1.0 / 3.0)
    )


def sini_gr(mp, mc, pb_s, a1_ls):
    """GR Shapiro shape: s = x nb^(2/3) (Tsun)^(-1/3) (mp+mc)^(2/3)/mc."""
    nb = _TWO_PI / pb_s
    return (
        a1_ls * nb ** (2.0 / 3.0) * TSUN ** (-1.0 / 3.0)
        * (mp + mc) ** (2.0 / 3.0) / mc
    )


def shklovskii_factor(pmtot_rad_s, d_kpc):
    """Apparent Pdot/P from transverse motion: mu^2 d / c (1/s)."""
    d_m = d_kpc * 3.0856775814913673e19
    return pmtot_rad_s**2 * d_m / C


def dispersion_slope(dm):
    """DM (pc/cm^3) -> slope in s MHz^2 (tempo convention K*DM)."""
    from pint_tpu.constants import DM_CONST

    return DM_CONST * dm


def pb_from_fb0(fb0):
    """FB0 (1/s) -> PB (days)."""
    return 1.0 / fb0 / SECS_PER_DAY
