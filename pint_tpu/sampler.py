"""Ensemble MCMC sampler, JAX-native (Goodman & Weare stretch moves).

Reference parity: src/pint/sampler.py::EmceeSampler +
mcmc_fitter.py::MCMCFitter — the reference delegates to emcee (host
Python, one likelihood call per walker per step).  Here the whole
ensemble advances inside one jitted lax.scan: the posterior is vmapped
over walkers, so every step evaluates all walkers as one batched device
computation — the natural TPU shape (SURVEY.md §7: vmap is the batch
axis).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def ensemble_init(
    x0,
    nwalkers: int = 64,
    seed: int = 0,
    init_scale=1e-8,
    init_cov=None,
    init_walkers=None,
):
    """Initial ensemble + the post-init RNG key, factored out of
    run_ensemble so the background-job runner (serve/jobs/runner.py)
    starts from the EXACT same walker positions and key state as an
    uninterrupted run (walker-count rules and RNG call order are part
    of the bitwise-resume contract).  Returns (walkers (nwalkers,
    ndim), key)."""
    ndim = int(np.asarray(x0).shape[-1])
    if init_walkers is not None:
        walkers = jnp.asarray(init_walkers)
        nwalkers = int(walkers.shape[0])
        if nwalkers % 2:
            raise ValueError("init_walkers needs an even walker count")
    else:
        if nwalkers < 2 * ndim:
            nwalkers = 2 * ndim
        if nwalkers % 2:
            nwalkers += 1
    key = jax.random.PRNGKey(seed)
    # k0 is consumed even on the init_walkers path so the step-key
    # schedule below is a function of (seed, nsteps_total) only —
    # never of HOW the ensemble was initialized
    key, k0 = jax.random.split(key)
    if init_walkers is None:
        ball = jax.random.normal(k0, (nwalkers, ndim))
        if init_cov is not None:
            L = jnp.linalg.cholesky(
                jnp.asarray(init_cov)
                + 1e-30 * jnp.eye(ndim) * jnp.max(jnp.diag(init_cov))
            )
            offs = ball @ L.T
        else:
            offs = ball * jnp.asarray(init_scale)
        walkers = jnp.asarray(x0) + offs
    return walkers, key


def ensemble_keys(key, nsteps: int, nsteps_total=None, start: int = 0):
    """Per-step key slice [start, start+nsteps) of a PLANNED schedule.

    jax.random.split(key, n) yields different keys for different n, so
    a resumable run must fix the schedule length up front: the full
    plan is split(key, nsteps_total) and every segment slices it.  A
    run segmented this way is bitwise-identical to the uninterrupted
    split(key, nsteps_total) run — the contract the preemption path
    (serve/jobs/) and checkpoint.resume_mcmc rely on.  With no plan
    (nsteps_total None) and start > 0, the plan defaults to
    start + nsteps (deterministic continuation past a completed run).
    """
    if nsteps_total is None and start == 0:
        return jax.random.split(key, nsteps)
    total = int(nsteps_total) if nsteps_total is not None else start + nsteps
    if start + nsteps > total:
        raise ValueError(
            f"segment [{start}, {start + nsteps}) exceeds the planned "
            f"schedule of {total} steps"
        )
    return jax.random.split(key, total)[start:start + nsteps]


def make_stretch_step(lnpost_v: Callable, ndim: int, nwalkers: int,
                      a: float = 2.0):
    """One Goodman-Weare ensemble step as a lax.scan body:
    (walkers, lp), key -> ((walkers, lp), (walkers, lp, n_accepted)).
    Shared verbatim between run_ensemble and the background-job
    quantum kernel (serve/jobs/kernels.py) — one source of truth for
    the proposal math is what makes job-path chains bitwise-comparable
    to host-path chains."""
    half = nwalkers // 2

    def half_step(carry, keys, first_half: bool):
        walkers, lp = carry
        k_z, k_pick, k_acc = keys
        if first_half:
            movers = walkers[:half]
            lp_m = lp[:half]
            others = walkers[half:]
        else:
            movers = walkers[half:]
            lp_m = lp[half:]
            others = walkers[:half]
        # stretch move: z ~ g(z) = 1/sqrt(z) on [1/a, a]
        u = jax.random.uniform(k_z, (half,))
        z = jnp.square((a - 1.0) * u + 1.0) / a
        j = jax.random.randint(k_pick, (half,), 0, half)
        proposal = others[j] + z[:, None] * (movers - others[j])
        lp_prop = lnpost_v(proposal)
        ln_accept = (ndim - 1.0) * jnp.log(z) + lp_prop - lp_m
        accept = jnp.log(
            jax.random.uniform(k_acc, (half,))
        ) < ln_accept
        new_m = jnp.where(accept[:, None], proposal, movers)
        new_lp_m = jnp.where(accept, lp_prop, lp_m)
        if first_half:
            walkers = jnp.concatenate([new_m, walkers[half:]])
            lp = jnp.concatenate([new_lp_m, lp[half:]])
        else:
            walkers = jnp.concatenate([walkers[:half], new_m])
            lp = jnp.concatenate([lp[:half], new_lp_m])
        return (walkers, lp), jnp.sum(accept)

    def step(carry, key):
        keys = jax.random.split(key, 6)
        carry, acc1 = half_step(carry, keys[:3], True)
        carry, acc2 = half_step(carry, keys[3:], False)
        (walkers, lp) = carry
        return carry, (walkers, lp, acc1 + acc2)

    return step


def run_ensemble(
    lnpost: Callable,
    x0: np.ndarray,
    nwalkers: int = 64,
    nsteps: int = 1000,
    a: float = 2.0,
    seed: int = 0,
    init_scale=1e-8,
    init_cov=None,
    init_walkers=None,
    init_lp=None,
    nsteps_total=None,
    start: int = 0,
):
    """Sample lnpost with stretch moves.

    x0 (ndim,): starting point.  Walkers start at init_walkers
    (nwalkers, ndim) when given — the exact-resume path used by
    checkpoint.resume_mcmc — else in a ball shaped by init_cov
    (ndim, ndim), else isotropic init_scale (scalar or per-dim vector).
    Stretch moves are affine-invariant, but a well-shaped initial
    ensemble is what makes them mix immediately when parameter scales
    span many decades.

    Resume contract (see ensemble_keys): a run planned as
    nsteps_total steps may execute as segments — pass start (steps
    already done), init_walkers and init_lp (the carried ensemble and
    its log-posteriors; passing init_lp skips the re-evaluation so the
    continuation is bitwise, not merely numerically, identical) — and
    the concatenated segments equal the uninterrupted run exactly.

    Returns (chain (nsteps, nwalkers, ndim), lnp (nsteps, nwalkers),
    acceptance_fraction).
    """
    walkers, key = ensemble_init(
        x0, nwalkers=nwalkers, seed=seed, init_scale=init_scale,
        init_cov=init_cov, init_walkers=init_walkers,
    )
    nwalkers, ndim = int(walkers.shape[0]), int(walkers.shape[1])
    lnpost_v = jax.vmap(lnpost)
    lp = lnpost_v(walkers) if init_lp is None else jnp.asarray(init_lp)
    step = make_stretch_step(lnpost_v, ndim, nwalkers, a)
    keys = ensemble_keys(key, nsteps, nsteps_total, start)
    (_, _), (chain, lnp, acc) = jax.lax.scan(step, (walkers, lp), keys)
    return (
        np.asarray(chain),
        np.asarray(lnp),
        float(jnp.sum(acc)) / (nsteps * nwalkers),
    )


def integrated_autocorr_time(chain, c: float = 5.0):
    """Per-parameter integrated autocorrelation time of an ensemble
    chain (nsteps, nwalkers, ndim) — the statistic the reference's
    emcee exposes as ``get_autocorr_time`` and gates results on
    (VERDICT r4 missing 4): FFT autocorrelation per walker, averaged
    over the ensemble, summed under Sokal's adaptive window
    (M = min m with m >= c * tau(m))."""
    x = np.asarray(chain, dtype=np.float64)
    n, w, d = x.shape
    nfft = 1 << (2 * n - 1).bit_length()
    taus = np.empty(d)
    for j in range(d):
        xm = x[:, :, j] - x[:, :, j].mean(axis=0, keepdims=True)
        f = np.fft.rfft(xm, n=nfft, axis=0)
        acf = np.fft.irfft(f * np.conjugate(f), n=nfft, axis=0)[:n].real
        var0 = acf[0].copy()
        var0[var0 == 0.0] = 1.0  # frozen walker column: rho := 0
        rho = (acf / var0[None, :]).mean(axis=1)
        tau_m = 2.0 * np.cumsum(rho) - 1.0
        m = np.arange(len(tau_m))
        win = np.argmax(m >= c * tau_m)
        if m[win] < c * tau_m[win]:  # window never satisfied
            win = len(tau_m) - 1
        taus[j] = max(tau_m[win], 1.0)
    return taus


def effective_sample_size(chain, c: float = 5.0):
    """Per-parameter ESS = nsteps * nwalkers / tau."""
    x = np.asarray(chain)
    return x.shape[0] * x.shape[1] / integrated_autocorr_time(x, c)


def gelman_rubin(chain):
    """Per-parameter split-R-hat over the ensemble: each walker chain
    is split in half, giving 2*nwalkers sequences; R-hat compares
    between- and within-sequence variances (Gelman et al.; the
    convergence gate the reference community applies to emcee runs).
    Values near 1 indicate mixing; > ~1.05 means unconverged."""
    x = np.asarray(chain, dtype=np.float64)
    n2 = (x.shape[0] // 2) * 2
    # (n/2, 2*nwalkers, d) split sequences
    seq = np.concatenate([x[: n2 // 2], x[n2 // 2: n2]], axis=1)
    n, m, d = seq.shape
    means = seq.mean(axis=0)            # (m, d)
    varis = seq.var(axis=0, ddof=1)     # (m, d)
    W = varis.mean(axis=0)
    B = n * means.var(axis=0, ddof=1)
    W = np.where(W == 0.0, 1e-300, W)
    return np.sqrt((n - 1) / n + B / (n * W))


class MCMCFitter:
    """Posterior sampling over a compiled timing model (reference:
    mcmc_fitter.MCMCFitter, emcee-backed there, lax.scan here).

    Convergence health (VERDICT r4 missing 4/weak 5): after fit_toas,
    ``convergence_diagnostics()`` reports per-parameter integrated
    autocorrelation time, ESS, and split-R-hat; get_posterior_samples
    WARNS when the chain is shorter than 50x the longest
    autocorrelation time (emcee's reliability rule) or split-R-hat
    exceeds 1.05 — an unconverged chain no longer passes silently."""

    def __init__(self, toas, model, priors: Optional[dict] = None):
        from pint_tpu.bayesian import BayesianTiming

        self.bt = BayesianTiming(model, toas, priors=priors)
        self.model = model
        self.toas = toas
        self.chain = None
        self.lnp = None
        self.acceptance = None

    def _init_cov(self):
        """Gauss-Newton covariance at x=0 shapes the initial ensemble
        (parameter scales span ~15 decades; an isotropic ball would
        take the sampler thousands of steps to burn in).  Offset-column
        handling is the fitters' shared logic (a second ones column
        next to a free PHOFF would make the design singular)."""
        import jax.numpy as jnp

        from pint_tpu.fitting.base import design_with_offset, noffset
        from pint_tpu.fitting.wls import _wls_step

        cm = self.bt.cm
        x = cm.x0()
        M = design_with_offset(cm, x)
        w = 1.0 / jnp.square(cm.scaled_sigma(x))
        # normalized covariance + host unnormalization: device
        # outer(norm, norm) overflows f32-range emulated f64 for stiff
        # columns (F1) and would zero the walker spread there
        # (fitting/gls.py::_finish_normal_eqs)
        _, (covn, norm), _ = _wls_step(
            jnp.zeros(cm.bundle.ntoa), M, w, normalized_cov=True
        )
        covn, norm = np.asarray(covn), np.asarray(norm)
        cov = covn / np.outer(norm, norm)
        no = noffset(cm)
        return cov[no:, no:]

    def fit_toas(
        self, nsteps: int = 1000, nwalkers: int = 64, burn: float = 0.25,
        seed: int = 0,
    ) -> float:
        lnpost = self.bt.lnposterior
        chain, lnp, acc = run_ensemble(
            lnpost, np.zeros(self.bt.nparams), nwalkers=nwalkers,
            nsteps=nsteps, seed=seed,
            init_cov=self._init_cov(),
        )
        self.chain, self.lnp, self.acceptance = chain, lnp, acc
        # RNG-cursor record for checkpoint.save_mcmc: where in the
        # planned key schedule this chain ends (the resume contract —
        # sampler.ensemble_keys)
        self.run_meta = dict(
            seed=seed, nsteps_done=nsteps, nsteps_total=nsteps,
        )
        nburn = int(burn * len(chain))
        flat = chain[nburn:].reshape(-1, self.bt.nparams)
        med = np.median(flat, axis=0)
        std = np.std(flat, axis=0)
        self.bt.cm.commit(med, uncertainties=std)
        i, j = np.unravel_index(np.argmax(lnp), lnp.shape)
        self.maxpost = float(lnp[i, j])
        return self.maxpost

    def convergence_diagnostics(self, burn: float = 0.25) -> dict:
        """{tau, ess, rhat, acceptance, n_post} for the post-burn
        chain, per free parameter in cm.free_names order."""
        if self.chain is None:
            raise ValueError("run fit_toas first")
        nburn = int(burn * len(self.chain))
        post = self.chain[nburn:]
        return dict(
            tau=integrated_autocorr_time(post),
            ess=effective_sample_size(post),
            rhat=gelman_rubin(post),
            acceptance=self.acceptance,
            n_post=post.shape[0],
        )

    def get_posterior_samples(self, burn: float = 0.25):
        import warnings

        nburn = int(burn * len(self.chain))
        diag = self.convergence_diagnostics(burn)
        names = list(self.bt.cm.free_names)
        short = diag["n_post"] < 50.0 * diag["tau"]
        if short.any():
            bad = [f"{names[i]} (tau={diag['tau'][i]:.0f})"
                   for i in np.nonzero(short)[0]]
            warnings.warn(
                "MCMC chain shorter than 50x the integrated "
                f"autocorrelation time for {', '.join(bad)}; "
                f"posterior summaries are unreliable — run more steps "
                f"(n_post={diag['n_post']})"
            )
        mixed_bad = diag["rhat"] > 1.05
        if mixed_bad.any():
            bad = [f"{names[i]} (R-hat={diag['rhat'][i]:.3f})"
                   for i in np.nonzero(mixed_bad)[0]]
            warnings.warn(
                f"MCMC split-R-hat above 1.05 for {', '.join(bad)}; "
                "walkers have not mixed — run more steps"
            )
        return self.chain[nburn:].reshape(-1, self.bt.nparams)
