"""Interactive fitting layer (pintk replacement).

Reference parity: src/pint/pintk/ — a ~4000-LoC Tk GUI (plk residual
canvas, par/tim editors).  Per SURVEY.md §7 the Tk GUI is out of scope;
what IS in scope is its testable core, `pintk/pulsar.py::Pulsar` — the
stateful wrapper the GUI drives: load par/tim, fit, delete/restore
TOAs, add/remove jumps, random-model draws, undo — plus the
paredit/timedit EDITING surface (src/pint/pintk/paredit.py /
timedit.py): get_par_text/edit_par and get_tim_text/edit_tim
round-trip the session through user-edited text, re-ingesting when an
edit changes the ingest options (EPHEM / CLOCK / PLANET_SHAPIRO).
Headless here, plus a minimal matplotlib front end (``plk()``) for
interactive use.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.fitting import auto_fitter
from pint_tpu.models.builder import get_model
from pint_tpu.residuals import Residuals


class Pulsar:
    """Stateful par/tim session driving fits and TOA edits
    (reference: pintk/pulsar.py::Pulsar)."""

    def __init__(self, parfile, timfile=None, toas=None):
        self.parfile = parfile
        self.model = get_model(parfile)
        self._par_backup = self.model.as_parfile()
        if toas is not None:
            self.all_toas = toas
        else:
            from pint_tpu.toas.cache import get_TOAs

            self.all_toas = get_TOAs(timfile, model=self.model)
        self.deleted = np.zeros(len(self.all_toas), dtype=bool)
        self.fitter = None
        self._fit_history: list[str] = []

    # -- selection -------------------------------------------------------
    @property
    def selected_toas(self):
        return self.all_toas[~self.deleted]

    def delete_toas(self, indices):
        self.deleted[np.asarray(indices, dtype=int)] = True

    def restore_toas(self, indices=None):
        if indices is None:
            self.deleted[:] = False
        else:
            self.deleted[np.asarray(indices, dtype=int)] = False

    # -- fitting ---------------------------------------------------------
    def residuals(self) -> Residuals:
        return Residuals(self.selected_toas, self.model)

    def fit(self, **kw) -> float:
        """Fit the non-deleted TOAs; history enables undo.  The undo
        entry is recorded only after the fit succeeds, so a raising fit
        leaves the history consistent."""
        pre_fit = self.model.as_parfile()
        fitter = auto_fitter(self.selected_toas, self.model, **kw)
        chi2 = fitter.fit_toas()
        self._fit_history.append(pre_fit)
        self.fitter = fitter
        return chi2

    def undo_fit(self):
        """Undo the last fit OR par edit.  If the undone edit had
        changed an ingest-relevant card, the TOAs are re-ingested
        under the restored model so model and geometry columns never
        diverge."""
        if not self._fit_history:
            raise ValueError("nothing to undo")
        old = self.model
        self.model = get_model(self._fit_history.pop())
        self.fitter = None
        if any(
            self._card(old, c) != self._card(self.model, c)
            for c in self._INGEST_CARDS
        ):
            from pint_tpu.toas.ingest import ingest_for_model

            ingest_for_model(self.all_toas, self.model)

    def reset_model(self):
        self.model = get_model(self._par_backup)
        self.fitter = None
        self._fit_history.clear()

    # -- par/tim editing (paredit/timedit capability) --------------------
    _INGEST_CARDS = ("EPHEM", "CLOCK", "PLANET_SHAPIRO")

    @staticmethod
    def _card(model, name):
        p = model.top_params.get(name) or model.params.get(name)
        return None if p is None else p.value

    def get_par_text(self) -> str:
        """Current model as par-file text (the paredit buffer)."""
        return self.model.as_parfile()

    def edit_par(self, text: str):
        """Apply edited par text: rebuild the model (undo-able like a
        fit) and recompute residuals.  If the edit changes an
        ingest-relevant card (EPHEM/CLOCK/PLANET_SHAPIRO) the TOAs are
        re-ingested under the new options — matching get_TOAs'
        model-driven chain (reference: pintk/paredit.py apply)."""
        from pint_tpu.toas.ingest import ingest_for_model

        old_model = self.model
        pre = old_model.as_parfile()
        new_model = get_model(text)
        reingest = any(
            self._card(old_model, c) != self._card(new_model, c)
            for c in self._INGEST_CARDS
        )
        self.model = new_model
        self._fit_history.append(pre)
        self.fitter = None
        if reingest:
            ingest_for_model(self.all_toas, new_model)
        return self.model

    def get_tim_text(self) -> str:
        """Current (non-deleted flags preserved) TOAs as tim text."""
        import io as _io

        from pint_tpu.io.tim import write_tim_file

        buf = _io.StringIO()
        write_tim_file(buf, self.all_toas)
        return buf.getvalue()

    def edit_tim(self, text: str):
        """Apply edited tim text: reparse + re-ingest under the
        current model; the deletion mask resets (TOA identity is not
        preserved across an edit), matching pintk/timedit.py apply."""
        import io as _io

        from pint_tpu.io.tim import get_TOAs_from_tim
        from pint_tpu.toas.ingest import ingest_for_model

        toas = get_TOAs_from_tim(_io.StringIO(text))
        ingest_for_model(toas, self.model)
        self.all_toas = toas
        self.deleted = np.zeros(len(toas), dtype=bool)
        self.fitter = None
        return toas

    # -- jumps -----------------------------------------------------------
    def add_jump(self, indices) -> str:
        """JUMP the given TOA indices via a -gui_jump flag selection
        (reference: pintk jump workflow)."""
        from pint_tpu.models.jump import PhaseJump

        comp = self.model.components.get("PhaseJump")
        if comp is None:
            comp = PhaseJump()
            self.model.add_component(comp)
        n_existing = len(comp.jump_params)
        tag = str(n_existing + 1)
        for i in np.asarray(indices):
            self.all_toas.flags[int(i)]["gui_jump"] = tag
        p = comp.mask_families()["JUMP"](n_existing + 1)
        p.set_from_tokens(["-gui_jump", tag, "0", "1"])
        self.model.setup()
        return p.name

    # -- random models ---------------------------------------------------
    def random_models(self, n_models: int = 20):
        if self.fitter is None:
            raise ValueError("fit first")
        from pint_tpu.simulation import calculate_random_models

        return calculate_random_models(self.fitter, n_models=n_models)

    def write_fit_par(self, path):
        with open(path, "w") as f:
            f.write(self.model.as_parfile())

    def __repr__(self):
        return (
            f"Pulsar({self.model.name!r}, {len(self.all_toas)} TOAs, "
            f"{int(self.deleted.sum())} deleted)"
        )


def plk(parfile, timfile, block: bool = True):
    """Minimal interactive residual viewer/fitter (matplotlib):
    'f' = fit, 'u' = undo fit, 'd' = delete nearest TOA, 'r' = restore
    all, 'q' = close.  Returns the Pulsar session."""
    import matplotlib.pyplot as plt

    psr = Pulsar(parfile, timfile)
    fig, ax = plt.subplots(figsize=(9, 5))

    def redraw():
        from pint_tpu.plot_utils import plot_residuals

        ax.clear()
        r = psr.residuals()
        plot_residuals(psr.selected_toas, r, ax=ax)
        ax.set_title(
            f"{psr.model.name}  chi2={r.chi2:.2f} dof={r.dof}"
        )
        fig.canvas.draw_idle()

    def on_key(event):
        try:
            if event.key == "f":
                psr.fit()
                redraw()
            elif event.key == "u":
                psr.undo_fit()
                redraw()
            elif event.key == "r":
                psr.restore_toas()
                redraw()
            elif event.key == "d" and event.xdata is not None:
                live = np.flatnonzero(~psr.deleted)
                mjd = psr.all_toas.mjd_float()[live]
                psr.delete_toas(
                    [live[np.argmin(np.abs(mjd - event.xdata))]]
                )
                redraw()
            elif event.key == "q":
                plt.close(fig)
        except Exception as e:  # viewer must survive bad keypresses
            ax.set_title(f"{type(e).__name__}: {e}")
            fig.canvas.draw_idle()

    fig.canvas.mpl_connect("key_press_event", on_key)
    redraw()
    if block:
        plt.show()
    return psr
