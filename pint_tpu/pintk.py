"""Interactive fitting layer (pintk replacement).

Reference parity: src/pint/pintk/ — a ~4000-LoC Tk GUI (plk residual
canvas, par/tim editors).  Per SURVEY.md §7 the Tk GUI is out of scope;
what IS in scope is its testable core, `pintk/pulsar.py::Pulsar` — the
stateful wrapper the GUI drives: load par/tim, fit, delete/restore
TOAs, add/remove jumps, random-model draws, undo.  That layer is here,
headless, plus a minimal matplotlib front end (``plk()``) for
interactive use.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.fitting import auto_fitter
from pint_tpu.models.builder import get_model
from pint_tpu.residuals import Residuals


class Pulsar:
    """Stateful par/tim session driving fits and TOA edits
    (reference: pintk/pulsar.py::Pulsar)."""

    def __init__(self, parfile, timfile=None, toas=None):
        self.parfile = parfile
        self.model = get_model(parfile)
        self._par_backup = self.model.as_parfile()
        if toas is not None:
            self.all_toas = toas
        else:
            from pint_tpu.toas.cache import get_TOAs

            self.all_toas = get_TOAs(timfile, model=self.model)
        self.deleted = np.zeros(len(self.all_toas), dtype=bool)
        self.fitter = None
        self._fit_history: list[str] = []

    # -- selection -------------------------------------------------------
    @property
    def selected_toas(self):
        return self.all_toas[~self.deleted]

    def delete_toas(self, indices):
        self.deleted[np.asarray(indices, dtype=int)] = True

    def restore_toas(self, indices=None):
        if indices is None:
            self.deleted[:] = False
        else:
            self.deleted[np.asarray(indices, dtype=int)] = False

    # -- fitting ---------------------------------------------------------
    def residuals(self) -> Residuals:
        return Residuals(self.selected_toas, self.model)

    def fit(self, **kw) -> float:
        """Fit the non-deleted TOAs; history enables undo.  The undo
        entry is recorded only after the fit succeeds, so a raising fit
        leaves the history consistent."""
        pre_fit = self.model.as_parfile()
        fitter = auto_fitter(self.selected_toas, self.model, **kw)
        chi2 = fitter.fit_toas()
        self._fit_history.append(pre_fit)
        self.fitter = fitter
        return chi2

    def undo_fit(self):
        if not self._fit_history:
            raise ValueError("nothing to undo")
        self.model = get_model(self._fit_history.pop())
        self.fitter = None

    def reset_model(self):
        self.model = get_model(self._par_backup)
        self.fitter = None
        self._fit_history.clear()

    # -- jumps -----------------------------------------------------------
    def add_jump(self, indices) -> str:
        """JUMP the given TOA indices via a -gui_jump flag selection
        (reference: pintk jump workflow)."""
        from pint_tpu.models.jump import PhaseJump

        comp = self.model.components.get("PhaseJump")
        if comp is None:
            comp = PhaseJump()
            self.model.add_component(comp)
        n_existing = len(comp.jump_params)
        tag = str(n_existing + 1)
        for i in np.asarray(indices):
            self.all_toas.flags[int(i)]["gui_jump"] = tag
        p = comp.mask_families()["JUMP"](n_existing + 1)
        p.set_from_tokens(["-gui_jump", tag, "0", "1"])
        self.model.setup()
        return p.name

    # -- random models ---------------------------------------------------
    def random_models(self, n_models: int = 20):
        if self.fitter is None:
            raise ValueError("fit first")
        from pint_tpu.simulation import calculate_random_models

        return calculate_random_models(self.fitter, n_models=n_models)

    def write_fit_par(self, path):
        with open(path, "w") as f:
            f.write(self.model.as_parfile())

    def __repr__(self):
        return (
            f"Pulsar({self.model.name!r}, {len(self.all_toas)} TOAs, "
            f"{int(self.deleted.sum())} deleted)"
        )


def plk(parfile, timfile, block: bool = True):
    """Minimal interactive residual viewer/fitter (matplotlib):
    'f' = fit, 'u' = undo fit, 'd' = delete nearest TOA, 'r' = restore
    all, 'q' = close.  Returns the Pulsar session."""
    import matplotlib.pyplot as plt

    psr = Pulsar(parfile, timfile)
    fig, ax = plt.subplots(figsize=(9, 5))

    def redraw():
        from pint_tpu.plot_utils import plot_residuals

        ax.clear()
        r = psr.residuals()
        plot_residuals(psr.selected_toas, r, ax=ax)
        ax.set_title(
            f"{psr.model.name}  chi2={r.chi2:.2f} dof={r.dof}"
        )
        fig.canvas.draw_idle()

    def on_key(event):
        try:
            if event.key == "f":
                psr.fit()
                redraw()
            elif event.key == "u":
                psr.undo_fit()
                redraw()
            elif event.key == "r":
                psr.restore_toas()
                redraw()
            elif event.key == "d" and event.xdata is not None:
                live = np.flatnonzero(~psr.deleted)
                mjd = psr.all_toas.mjd_float()[live]
                psr.delete_toas(
                    [live[np.argmin(np.abs(mjd - event.xdata))]]
                )
                redraw()
            elif event.key == "q":
                plt.close(fig)
        except Exception as e:  # viewer must survive bad keypresses
            ax.set_title(f"{type(e).__name__}: {e}")
            fig.canvas.draw_idle()

    fig.canvas.mpl_connect("key_press_event", on_key)
    redraw()
    if block:
        plt.show()
    return psr
