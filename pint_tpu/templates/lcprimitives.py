"""Light-curve primitives: periodic unit-normalized peak shapes.

Reference parity: src/pint/templates/lcprimitives.py::LCGaussian,
LCVonMises — each primitive is a density on phase [0, 1) with
parameters (width, location); jax-traceable __call__.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class LCPrimitive:
    """Base: params [width, loc]; density integrates to 1 over a cycle."""

    n_params = 2

    def __init__(self, width: float = 0.03, loc: float = 0.5):
        self.params = np.array([width, loc], dtype=np.float64)

    def __call__(self, phases, params=None):
        raise NotImplementedError

    @property
    def loc(self):
        return self.params[1]

    @property
    def width(self):
        return self.params[0]

    def __repr__(self):
        return (
            f"{type(self).__name__}(width={self.params[0]:.4f}, "
            f"loc={self.params[1]:.4f})"
        )


class LCGaussian(LCPrimitive):
    """Wrapped Gaussian peak (summed over +-2 neighbor cycles — ample
    for widths < 0.2 cycles)."""

    def __call__(self, phases, params=None):
        w, loc = (
            (self.params[0], self.params[1]) if params is None
            else (params[0], params[1])
        )
        dphi = phases - loc
        out = 0.0
        for k in (-2, -1, 0, 1, 2):
            z = (dphi + k) / w
            out = out + jnp.exp(-0.5 * z * z)
        return out / (w * jnp.sqrt(2.0 * jnp.pi))


class LCVonMises(LCPrimitive):
    """Von Mises peak; width parameter = 1/sqrt(kappa) (sigma-like)."""

    def __call__(self, phases, params=None):
        w, loc = (
            (self.params[0], self.params[1]) if params is None
            else (params[0], params[1])
        )
        kappa = 1.0 / (w * w)
        from jax.scipy.special import i0e

        z = 2.0 * jnp.pi * (phases - loc)
        # exp(kappa cos z)/(2 pi I0(kappa)), computed overflow-safe
        return jnp.exp(kappa * (jnp.cos(z) - 1.0)) / (
            2.0 * jnp.pi * i0e(kappa)
        )
