"""Light-curve primitives: periodic unit-normalized peak shapes.

Reference parity: src/pint/templates/lcprimitives.py::LCGaussian,
LCVonMises — each primitive is a density on phase [0, 1) with
parameters (width, location); jax-traceable __call__.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class LCPrimitive:
    """Base: params [width, loc]; density integrates to 1 over a cycle."""

    n_params = 2

    def __init__(self, width: float = 0.03, loc: float = 0.5):
        self.params = np.array([width, loc], dtype=np.float64)

    def __call__(self, phases, params=None):
        raise NotImplementedError

    @property
    def loc(self):
        return self.params[1]

    @property
    def width(self):
        return self.params[0]

    def fit_bounds(self):
        """L-BFGS-B bounds per parameter: positive width, free loc."""
        return [(1e-4, 0.5), (None, None)]

    is_energy_dependent = False

    def wrap_loc(self):
        """Fold the fitted location into [0, 1) (the loc slot is the
        LAST parameter for every base primitive; energy-dependent
        wrappers override)."""
        self.params[-1] = self.params[-1] % 1.0

    def __repr__(self):
        return (
            f"{type(self).__name__}(width={self.params[0]:.4f}, "
            f"loc={self.params[1]:.4f})"
        )


class LCGaussian(LCPrimitive):
    """Wrapped Gaussian peak (summed over +-2 neighbor cycles — ample
    for widths < 0.2 cycles)."""

    def __call__(self, phases, params=None):
        w, loc = (
            (self.params[0], self.params[1]) if params is None
            else (params[0], params[1])
        )
        dphi = phases - loc
        out = 0.0
        for k in (-2, -1, 0, 1, 2):
            z = (dphi + k) / w
            out = out + jnp.exp(-0.5 * z * z)
        return out / (w * jnp.sqrt(2.0 * jnp.pi))


class LCVonMises(LCPrimitive):
    """Von Mises peak; width parameter = 1/sqrt(kappa) (sigma-like)."""

    def __call__(self, phases, params=None):
        w, loc = (
            (self.params[0], self.params[1]) if params is None
            else (params[0], params[1])
        )
        kappa = 1.0 / (w * w)
        from jax.scipy.special import i0e

        z = 2.0 * jnp.pi * (phases - loc)
        # angle density exp(kappa cos z)/(2 pi I0(kappa)) times the
        # dtheta/dphi = 2 pi Jacobian -> per-CYCLE density (a 1/2pi
        # normalization bug here was caught by
        # test_templates.py::test_primitive_normalization)
        return jnp.exp(kappa * (jnp.cos(z) - 1.0)) / i0e(kappa)


class LCLorentzian(LCPrimitive):
    """Wrapped Cauchy (Lorentzian) peak — closed-form wrap (reference:
    lcprimitives.py::LCLorentzian).  width = HWHM gamma in cycles;
    density in phase: (1-rho^2)/(1+rho^2-2 rho cos(2 pi dphi)) with
    rho = exp(-2 pi gamma)."""

    def __call__(self, phases, params=None):
        w, loc = (
            (self.params[0], self.params[1]) if params is None
            else (params[0], params[1])
        )
        rho = jnp.exp(-2.0 * jnp.pi * w)
        z = 2.0 * jnp.pi * (phases - loc)
        return (1.0 - rho * rho) / (
            1.0 + rho * rho - 2.0 * rho * jnp.cos(z)
        )


class LCGaussian2(LCPrimitive):
    """Two-sided (asymmetric) Gaussian peak (reference:
    lcprimitives.py::LCGaussian2): width1 on the leading (dphi < 0)
    side, width2 trailing, continuous at the peak; params
    [width1, width2, loc]."""

    n_params = 3

    def __init__(self, width: float = 0.03, width2: float = 0.03,
                 loc: float = 0.5):
        self.params = np.array([width, width2, loc], dtype=np.float64)

    @property
    def loc(self):
        return self.params[2]

    def fit_bounds(self):
        return [(1e-4, 0.5), (1e-4, 0.5), (None, None)]

    def __call__(self, phases, params=None):
        w1, w2, loc = (
            tuple(self.params) if params is None
            else (params[0], params[1], params[2])
        )
        norm = 2.0 / (jnp.sqrt(2.0 * jnp.pi) * (w1 + w2))
        dphi = phases - loc
        out = 0.0
        for k in (-2, -1, 0, 1, 2):
            d = dphi + k
            w = jnp.where(d < 0, w1, w2)
            z = d / w
            out = out + jnp.exp(-0.5 * z * z)
        return norm * out

    def __repr__(self):
        return (
            f"LCGaussian2(width={self.params[0]:.4f}, "
            f"width2={self.params[1]:.4f}, loc={self.params[2]:.4f})"
        )


class LCBinnedProfile(LCPrimitive):
    """Empirical binned profile (a .prof file) as a primitive: periodic
    linear interpolation of a normalized histogram; the only live
    parameter is the phase shift (params [scale(unused), loc] to keep
    the (width, loc) layout).  Reference capability:
    lcprimitives-style empirical templates consumed by event_optimize.
    """

    def __init__(self, values, loc: float = 0.0):
        vals = np.asarray(values, dtype=np.float64)
        if vals.ndim != 1 or len(vals) < 4:
            raise ValueError("binned profile needs a 1-D array (>=4 bins)")
        if np.any(vals < 0):
            vals = vals - vals.min()  # raw profiles may ride a baseline
        if not np.isfinite(vals).all() or vals.mean() <= 0:
            # mirrors read_prof's 'profile is constant' guard for
            # directly constructed profiles (ADVICE r2): an all-zero /
            # constant-after-baseline profile would yield NaN/inf
            raise ValueError(
                "binned profile is empty or constant (zero mean after "
                "baseline subtraction)"
            )
        self.values = vals / vals.mean()  # unit mean = unit integral
        self.params = np.array([1.0, loc], dtype=np.float64)

    def fit_bounds(self):
        # the scale slot is structural, not a shape parameter: pin it
        return [(1.0, 1.0), (None, None)]

    def __call__(self, phases, params=None):
        loc = self.params[1] if params is None else params[1]
        nb = len(self.values)
        # bin centers at (i + 0.5)/nb; wrap by padding one bin each side
        grid = (jnp.arange(nb + 2) - 0.5) / nb
        vals = jnp.concatenate([
            self.values[-1:], jnp.asarray(self.values), self.values[:1]
        ])
        x = jnp.mod(phases - loc, 1.0)
        return jnp.interp(x, grid, vals)
