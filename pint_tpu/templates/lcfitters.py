"""Maximum-likelihood template fitting of photon phases.

Reference parity: src/pint/templates/lcfitters.py::LCFitter — unbinned
Poisson/weighted log-likelihood, here as a jitted jax objective with
analytic gradients fed to scipy L-BFGS-B.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from scipy.optimize import minimize

from pint_tpu.templates.lctemplate import LCTemplate


class LCFitter:
    def __init__(self, template: LCTemplate, phases, weights=None,
                 log10_ens=None):
        self.template = template
        self.phases = jnp.asarray(np.asarray(phases, dtype=np.float64))
        self.weights = (
            None if weights is None
            else jnp.asarray(np.asarray(weights, dtype=np.float64))
        )
        self.log10_ens = (
            None if log10_ens is None
            else jnp.asarray(np.asarray(log10_ens, dtype=np.float64))
        )
        if self.log10_ens is None and getattr(
            template, "is_energy_dependent", False
        ):
            # without energies the slope parameters have exactly zero
            # gradient: the fit would silently equal the energy-blind
            # one and errors() would invert a singular Hessian
            raise ValueError(
                "template has energy-dependent primitives; pass "
                "log10_ens (per-photon log10(E/GeV))"
            )

    def loglikelihood(self, params=None):
        """Unbinned log-likelihood (weighted form: Kerr 2011 eq. 2)."""
        f = self.template(
            self.phases, params=params, log10_ens=self.log10_ens
        )
        if self.weights is None:
            return jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
        return jnp.sum(
            jnp.log(jnp.maximum(self.weights * f + (1.0 - self.weights),
                                1e-300))
        )

    def fit(self, maxiter: int = 200):
        """L-BFGS-B with jax gradients; bounds keep weights in [0,1]
        and widths positive.  Returns the optimized log-likelihood."""
        x0 = self.template.get_parameters()
        n = len(self.template.primitives)

        obj = jax.jit(lambda v: -self.loglikelihood(params=v))
        grad = jax.jit(jax.grad(lambda v: -self.loglikelihood(params=v)))

        bounds = [(1e-6, 1.0)] * n
        for p in self.template.primitives:
            bounds += p.fit_bounds()

        res = minimize(
            lambda v: float(obj(jnp.asarray(v))),
            x0,
            jac=lambda v: np.asarray(grad(jnp.asarray(v))),
            method="L-BFGS-B",
            bounds=bounds,
            options={"maxiter": maxiter},
        )
        self.template.set_parameters(res.x)
        # wrap fitted locations into [0, 1)
        for p in self.template.primitives:
            p.wrap_loc()
        self.result = res
        return -float(res.fun)

    def errors(self):
        """Parameter uncertainties from the observed information: the
        jax Hessian of -loglikelihood at the fitted parameters,
        pseudo-inverted (weight parameters pinned at a bound get a 0
        eigenvalue rather than a spurious tiny error).  Reference:
        LCFitter's hess_errors.  Stored on the template as
        .param_errors (get_parameters() layout) and returned."""
        v0 = jnp.asarray(self.template.get_parameters())
        H = np.asarray(
            jax.hessian(lambda v: -self.loglikelihood(params=v))(v0)
        )
        cov = np.linalg.pinv(H, rcond=1e-12)
        err = np.sqrt(np.maximum(np.diag(cov), 0.0))
        self.template.param_errors = err
        return err

    def __repr__(self):
        return f"LCFitter({self.template!r}, n={len(self.phases)})"
