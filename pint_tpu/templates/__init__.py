"""Pulse-profile templates + photon-phase ML fitting.

Reference parity: src/pint/templates/ (lctemplate.py, lcprimitives.py,
lcfitters.py — heritage Fermi pointlike): analytic profile templates as
weighted sums of periodic primitives plus an unpulsed background,
fitted to photon phases by maximum likelihood.  The log-likelihood is a
pure jax function of the parameter vector; the fitter uses scipy
L-BFGS-B with jax gradients (host driver, device math).
"""

from pint_tpu.templates.lcprimitives import (  # noqa: F401
    LCBinnedProfile,
    LCGaussian,
    LCGaussian2,
    LCLorentzian,
    LCVonMises,
)
from pint_tpu.templates.lceprimitives import LCEPrimitive  # noqa: F401
from pint_tpu.templates.lctemplate import LCTemplate  # noqa: F401
from pint_tpu.templates.lcfitters import LCFitter  # noqa: F401
from pint_tpu.templates.lcio import (  # noqa: F401
    read_gauss,
    read_prof,
    read_template,
    write_gauss,
    write_prof,
)
