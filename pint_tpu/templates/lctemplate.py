"""LCTemplate: weighted sum of primitives + unpulsed background.

Reference parity: src/pint/templates/lctemplate.py::LCTemplate —
f(phi) = sum_i w_i g_i(phi) + (1 - sum_i w_i), with g_i normalized
primitives; parameter vector layout [w_1..w_n, p_1..: per-primitive
(width, loc)].
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class LCTemplate:
    def __init__(self, primitives, weights=None):
        self.primitives = list(primitives)
        n = len(self.primitives)
        if weights is None:
            weights = np.full(n, 0.5 / n)
        self.weights = np.asarray(weights, dtype=np.float64)
        if self.weights.sum() > 1.0 + 1e-9:
            raise ValueError("primitive weights must sum to <= 1")

    # -- parameter vector -------------------------------------------------
    def get_parameters(self) -> np.ndarray:
        parts = [self.weights]
        for p in self.primitives:
            parts.append(p.params)
        return np.concatenate(parts)

    def set_parameters(self, vec):
        vec = np.asarray(vec, dtype=np.float64)
        n = len(self.primitives)
        self.weights = vec[:n].copy()
        off = n
        for p in self.primitives:
            p.params = vec[off:off + p.n_params].copy()
            off += p.n_params

    @property
    def is_energy_dependent(self):
        return any(
            getattr(p, "is_energy_dependent", False)
            for p in self.primitives
        )

    def __call__(self, phases, params=None, log10_ens=None):
        """Density at phases; jax-traceable when params is a jnp vector
        in get_parameters() layout.  log10_ens (per-photon
        log10(E/GeV)) feeds energy-dependent primitives
        (lceprimitives.LCEPrimitive); others ignore it."""
        n = len(self.primitives)
        if params is None:
            params = self.get_parameters()
        w = params[:n]
        out = 1.0 - jnp.sum(w)
        off = n
        for i, p in enumerate(self.primitives):
            kw = (
                {"log10_ens": log10_ens}
                if getattr(p, "is_energy_dependent", False) else {}
            )
            out = out + w[i] * p(
                phases, params=params[off:off + p.n_params], **kw
            )
            off += p.n_params
        return out

    def random(self, n, rng=None, log10_ens=None):
        """Draw photon phases from the template (for tests/simulation);
        with log10_ens (length n), each photon is drawn from its own
        energy's density."""
        rng = rng or np.random.default_rng()
        params = self.get_parameters()
        if log10_ens is None:
            fmax = float(
                np.max(np.asarray(self(np.linspace(0, 1, 2048), params)))
            )
            out = []
            while len(out) < n:
                cand = rng.uniform(size=2 * n)
                f = np.asarray(self(cand, params))
                keep = rng.uniform(size=2 * n) * fmax < f
                out.extend(cand[keep].tolist())
            return np.asarray(out[:n])
        u = np.asarray(log10_ens, dtype=np.float64)
        if u.shape != (n,):
            raise ValueError("log10_ens must have length n")
        grid = np.linspace(0, 1, 512)
        # density envelope at EVERY photon's energy (chunked so the
        # working array stays (1024, 512)): an interior-energy
        # superposition of drifting peaks can exceed any coarse-grid
        # maximum (ADVICE r3 + r4 review); the phase grid plus the
        # 1.1 margin and the in-loop rescale below cover what 512
        # phase samples could still miss
        fmax = 0.0
        for lo in range(0, n, 1024):
            u_chunk = u[lo:lo + 1024]
            fmax = max(fmax, float(np.max(np.asarray(
                self(grid[None, :], params, log10_ens=u_chunk[:, None])
            ))))
        fmax *= 1.1
        phases = np.empty(n)
        todo = np.ones(n, dtype=bool)
        while todo.any():
            idx = np.flatnonzero(todo)
            cand = rng.uniform(size=len(idx))
            f = np.asarray(self(cand, params, log10_ens=u[idx]))
            f_hi = float(np.max(f, initial=0.0))
            if f_hi > fmax:
                # grid missed a sharper interior superposition: raise
                # the envelope and restart (already-accepted draws
                # under a too-low envelope would be biased)
                fmax = 1.1 * f_hi
                todo[:] = True
                continue
            keep = rng.uniform(size=len(idx)) * fmax < f
            phases[idx[keep]] = cand[keep]
            todo[idx[keep]] = False
        return phases

    def __repr__(self):
        inner = ", ".join(
            f"{w:.3f}*{p!r}" for w, p in zip(self.weights, self.primitives)
        )
        return f"LCTemplate({inner})"
