"""LCTemplate: weighted sum of primitives + unpulsed background.

Reference parity: src/pint/templates/lctemplate.py::LCTemplate —
f(phi) = sum_i w_i g_i(phi) + (1 - sum_i w_i), with g_i normalized
primitives; parameter vector layout [w_1..w_n, p_1..: per-primitive
(width, loc)].
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


class LCTemplate:
    def __init__(self, primitives, weights=None):
        self.primitives = list(primitives)
        n = len(self.primitives)
        if weights is None:
            weights = np.full(n, 0.5 / n)
        self.weights = np.asarray(weights, dtype=np.float64)
        if self.weights.sum() > 1.0 + 1e-9:
            raise ValueError("primitive weights must sum to <= 1")

    # -- parameter vector -------------------------------------------------
    def get_parameters(self) -> np.ndarray:
        parts = [self.weights]
        for p in self.primitives:
            parts.append(p.params)
        return np.concatenate(parts)

    def set_parameters(self, vec):
        vec = np.asarray(vec, dtype=np.float64)
        n = len(self.primitives)
        self.weights = vec[:n].copy()
        off = n
        for p in self.primitives:
            p.params = vec[off:off + p.n_params].copy()
            off += p.n_params

    @property
    def is_energy_dependent(self):
        return any(
            getattr(p, "is_energy_dependent", False)
            for p in self.primitives
        )

    def __call__(self, phases, params=None, log10_ens=None):
        """Density at phases; jax-traceable when params is a jnp vector
        in get_parameters() layout.  log10_ens (per-photon
        log10(E/GeV)) feeds energy-dependent primitives
        (lceprimitives.LCEPrimitive); others ignore it."""
        n = len(self.primitives)
        if params is None:
            params = self.get_parameters()
        w = params[:n]
        out = 1.0 - jnp.sum(w)
        off = n
        for i, p in enumerate(self.primitives):
            kw = (
                {"log10_ens": log10_ens}
                if getattr(p, "is_energy_dependent", False) else {}
            )
            out = out + w[i] * p(
                phases, params=params[off:off + p.n_params], **kw
            )
            off += p.n_params
        return out

    def _rand_jitted(self, branch, fn):
        """Per-instance cache of the sampler's jitted callables:
        random() is called per-realization in simulation sweeps, and
        re-jitting a fresh lambda each call would recompile the
        template density every time (jit's own cache keys on function
        identity).  One wrapper per branch suffices — jax.jit caches
        per input shape internally.  Parameters ride as ARGUMENTS so
        the cached executable stays valid after a fit moves them, and
        the key carries the primitive STRUCTURE — type, param layout,
        any wrapped base primitive's type, and a digest of non-param
        state like a binned profile's table — so a same-shape
        primitive swap (or an in-place base/table change) re-traces
        instead of silently serving the old template's density."""
        def psig(p):
            parts = [type(p).__name__, len(p.params)]
            base = getattr(p, "base", None)
            if base is not None:
                parts.append(psig(base))
            vals = getattr(p, "values", None)
            if vals is not None:  # binned-profile table = traced const
                parts.append(hash(np.asarray(vals).tobytes()))
            return tuple(parts)

        sig = tuple(psig(p) for p in self.primitives)
        key = (branch, sig)
        cache = getattr(self, "_rand_jit_cache", None)
        if cache is None:
            cache = self._rand_jit_cache = {}
        if key not in cache:
            import jax

            cache[key] = jax.jit(fn)
        return cache[key]

    def random(self, n, rng=None, log10_ens=None):
        """Draw photon phases from the template (for tests/simulation);
        with log10_ens (length n), each photon is drawn from its own
        energy's density.

        The per-round density evaluation is JITTED at a fixed shape
        (r5): a rejection sampler makes dozens of rounds, and an eager
        template call is a chain of hundreds of small dispatches —
        ~0.9 s/round for a 6000-photon energy-dependent draw, ~55 s
        total where the jitted version takes under a second.  Both
        branches share the envelope contract: a 1.1 margin over a
        grid-estimated maximum, plus an in-loop rescale-and-RESTART
        when any computed density exceeds it (draws accepted under a
        too-low envelope are biased and must be discarded)."""
        rng = rng or np.random.default_rng()
        if log10_ens is not None:
            u = np.asarray(log10_ens, dtype=np.float64)
            if u.shape != (n,):
                raise ValueError("log10_ens must have length n")
        if n == 0:
            return np.empty(0)
        # candidate batches are padded to a 1024 multiple so sweeps
        # with varying photon counts reuse ONE compiled density per
        # branch instead of retracing at every distinct n
        n_pad = -(-n // 1024) * 1024
        params = jnp.asarray(self.get_parameters())
        if log10_ens is None:
            density = self._rand_jitted("noe", lambda c, p: self(c, p))
            fmax = 1.1 * float(np.max(np.asarray(
                density(jnp.linspace(0.0, 1.0, 2048), params)
            )))
            out = []
            while len(out) < n:
                cand = rng.uniform(size=2 * n_pad)
                f = np.asarray(density(jnp.asarray(cand), params))
                f_hi = float(np.max(f, initial=0.0))
                if f_hi > fmax:
                    # a peak narrower than the 2048-point grid spacing
                    # slipped the estimate: raise and restart
                    fmax = 1.1 * f_hi
                    out = []
                    continue
                keep = rng.uniform(size=2 * n_pad) * fmax < f
                out.extend(cand[keep].tolist())
            return np.asarray(out[:n])
        grid = np.linspace(0, 1, 512)
        # density envelope at EVERY photon's energy (chunked so the
        # working array stays (1024, 512)): an interior-energy
        # superposition of drifting peaks can exceed any coarse-grid
        # maximum (ADVICE r3 + r4 review); the phase grid plus the
        # 1.1 margin and the in-loop rescale below cover what 512
        # phase samples could still miss
        env = self._rand_jitted(
            "env", lambda uu, p: jnp.max(
                self(grid[None, :], p, log10_ens=uu[:, None])
            )
        )
        # device-scalar accumulation: a float() per chunk would force
        # ceil(n/1024) serialized dispatch round-trips (~85 ms each on
        # the tunnel); one conversion at the end lets them pipeline
        chunk_maxes = []
        for lo in range(0, n, 1024):
            u_chunk = u[lo:lo + 1024]
            if len(u_chunk) < 1024:  # pad: one compiled shape
                u_chunk = np.concatenate(
                    [u_chunk, np.full(1024 - len(u_chunk), u_chunk[-1])]
                )
            chunk_maxes.append(env(jnp.asarray(u_chunk), params))
        fmax = 1.1 * float(jnp.max(jnp.stack(chunk_maxes)))
        # fixed-shape rounds: evaluate ALL n candidates each round and
        # fill only the still-pending slots — one compiled density
        # serves every round (a per-round shape would recompile)
        density = self._rand_jitted(
            "en", lambda c, uu, p: self(c, p, log10_ens=uu)
        )
        u_dev = jnp.asarray(
            np.concatenate([u, np.full(n_pad - n, u[-1])])
        )
        phases = np.empty(n)
        todo = np.ones(n, dtype=bool)
        while todo.any():
            cand = rng.uniform(size=n_pad)
            f = np.asarray(
                density(jnp.asarray(cand), u_dev, params)
            )[:n]
            cand = cand[:n]
            # envelope check over ALL slots: a completed slot whose
            # fresh density exceeds fmax is evidence its earlier
            # acceptance ran under a too-low envelope — restart
            f_hi = float(np.max(f, initial=0.0))
            if f_hi > fmax:
                fmax = 1.1 * f_hi
                todo[:] = True
                continue
            keep = todo & (rng.uniform(size=n) * fmax < f)
            phases[keep] = cand[keep]
            todo[keep] = False
        return phases

    def __repr__(self):
        inner = ", ".join(
            f"{w:.3f}*{p!r}" for w, p in zip(self.weights, self.primitives)
        )
        return f"LCTemplate({inner})"
