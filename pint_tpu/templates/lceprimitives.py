"""Energy-dependent light-curve primitives.

Reference parity: src/pint/templates/lceprimitives.py (LCEGaussian
and friends) — peak location and width drift with photon energy, the
capability behind Fermi weighted-photon template fits where the pulse
shape sharpens/moves across the band.

Design here: ONE wrapper, ``LCEPrimitive``, makes any 2-parameter
base primitive energy-dependent with linear drifts in
``u = log10(E / 1 GeV)`` (the pivot the reference uses):

    width(u) = clip(width0 + width_slope * u, 1e-4, 0.5)
    loc(u)   = loc0 + loc_slope * u

The base primitive's jax formula is reused unchanged — its (width,
loc) scalars simply become per-photon arrays, which every elementwise
primitive broadcasts over, so the whole energy-dependent template
stays one traceable function of (phases, log10_ens, params).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.templates.lcprimitives import LCPrimitive


class LCEPrimitive:
    """Energy-dependent wrapper: params [width0, loc0, width_slope,
    loc_slope]; evaluation needs per-photon log10(E/GeV)."""

    n_params = 4
    is_energy_dependent = True

    def __init__(self, base: LCPrimitive, width_slope: float = 0.0,
                 loc_slope: float = 0.0):
        if base.n_params != 2:
            raise ValueError(
                "LCEPrimitive wraps 2-parameter (width, loc) "
                f"primitives; {type(base).__name__} has "
                f"{base.n_params}"
            )
        self.base = base
        self.params = np.array(
            [base.params[0], base.params[1], width_slope, loc_slope],
            dtype=np.float64,
        )

    @property
    def width(self):
        return self.params[0]

    @property
    def loc(self):
        return self.params[1]

    def __call__(self, phases, params=None, log10_ens=None):
        p = self.params if params is None else params
        w0, l0, ws, ls = p[0], p[1], p[2], p[3]
        u = 0.0 if log10_ens is None else log10_ens
        w = jnp.clip(w0 + ws * u, 1e-4, 0.5)
        loc = l0 + ls * u
        return self.base(phases, params=(w, loc))

    def fit_bounds(self):
        base = self.base.fit_bounds()
        # slopes unbounded; width positivity is enforced by the clip
        return base + [(None, None), (None, None)]

    def wrap_loc(self):
        self.params[1] = self.params[1] % 1.0

    def __repr__(self):
        return (
            f"LCEPrimitive({type(self.base).__name__}, "
            f"width={self.params[0]:.4f}+{self.params[2]:.4f}u, "
            f"loc={self.params[1]:.4f}+{self.params[3]:.4f}u)"
        )
