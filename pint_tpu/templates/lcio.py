"""Template file IO: .gauss component files and .prof binned profiles.

Reference parity: src/pint/templates/lctemplate.py (prim_io /
gauss_template round-trip) and the scripts/event_optimize.py template
loading path — the two on-disk template formats the photon pipeline
exchanges with tempo/itemplate tooling:

.gauss — itemplate/pointlike Gaussian-component text:

    # comments
    const = 0.400 +/- 0.0100
    phas1 = 0.1000 +/- 0.0010
    fwhm1 = 0.0400 +/- 0.0020
    ampl1 = 0.3500 +/- 0.0100
    phas2 = ...

  const is the unpulsed fraction (1 - sum of ampl); fwhm is in cycles
  (width sigma = fwhm / (2 sqrt(2 ln 2))).  Errors are optional on
  read and written when the template carries them.

.prof — a binned intensity profile: one value per line (or two
  columns, bin index + value); becomes an LCBinnedProfile primitive
  with weight 1 - const (const estimated from the profile minimum).
"""

from __future__ import annotations

import math
import re

import numpy as np

from pint_tpu.templates.lcprimitives import LCBinnedProfile, LCGaussian
from pint_tpu.templates.lctemplate import LCTemplate

_FWHM = 2.0 * math.sqrt(2.0 * math.log(2.0))
_LINE = re.compile(
    r"^\s*(const|phas|fwhm|ampl)\s*(\d*)\s*=\s*([-+0-9.eE]+)"
    r"(?:\s*\+/-\s*([-+0-9.eE]+))?"
)


def read_gauss(path):
    """-> (LCTemplate of LCGaussians, errors vector in
    get_parameters() layout or None)."""
    fields: dict[tuple[str, int], tuple[float, float | None]] = {}
    for line in open(path):
        m = _LINE.match(line)
        if not m:
            continue
        key, idx, val, err = m.groups()
        fields[(key, int(idx or 0))] = (
            float(val), None if err is None else float(err)
        )
    ncomp = max((i for (k, i) in fields if k == "ampl"), default=0)
    if ncomp == 0:
        raise ValueError(f"{path}: no ampl# components found")
    prims, weights = [], []
    for i in range(1, ncomp + 1):
        try:
            phas = fields[("phas", i)]
            fwhm = fields[("fwhm", i)]
            ampl = fields[("ampl", i)]
        except KeyError as e:
            raise ValueError(
                f"{path}: incomplete component {i} ({e})"
            ) from None
        prims.append(LCGaussian(width=fwhm[0] / _FWHM, loc=phas[0]))
        weights.append(ampl[0])
    tmpl = LCTemplate(prims, weights=weights)
    # errors, if every field carried one
    errs = []
    have_all = all(v[1] is not None for v in fields.values())
    if have_all:
        errs = [fields[("ampl", i)][1] for i in range(1, ncomp + 1)]
        for i in range(1, ncomp + 1):
            errs.append(fields[("fwhm", i)][1] / _FWHM)
            errs.append(fields[("phas", i)][1])
    return tmpl, (np.asarray(errs) if have_all else None)


def write_gauss(template: LCTemplate, path, errors=None):
    """Write an all-Gaussian template (+ optional errors in
    get_parameters() layout)."""
    n = len(template.primitives)
    if not all(isinstance(p, LCGaussian) for p in template.primitives):
        raise ValueError(".gauss files hold LCGaussian components only")

    def fmt(val, err):
        # %.8f for values: a high-statistics phase fit localizes to
        # few-1e-7, finer than %.6f quantization; %g for the error
        # (%.6f would floor a few-1e-7 error to a claimed-exact 0)
        if err is None:
            return f"{val:.8f}"
        return f"{val:.8f} +/- {err:.4g}"

    e = None if errors is None else np.asarray(errors)
    lines = ["# pint_tpu template (itemplate .gauss convention)"]
    const = 1.0 - float(np.sum(template.weights))
    lines.append(f"const = {fmt(const, None if e is None else 0.0)}")
    for i, (w, p) in enumerate(
        zip(template.weights, template.primitives), start=1
    ):
        we = None if e is None else e[i - 1]
        k = n + 2 * (i - 1)
        fe = None if e is None else e[k] * _FWHM
        pe = None if e is None else e[k + 1]
        lines.append(f"phas{i} = {fmt(p.params[1] % 1.0, pe)}")
        lines.append(f"fwhm{i} = {fmt(p.params[0] * _FWHM, fe)}")
        lines.append(f"ampl{i} = {fmt(float(w), we)}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def read_prof(path):
    """Binned profile -> LCTemplate([LCBinnedProfile], [1 - const]);
    const (unpulsed fraction) is estimated from the profile minimum.
    Baseline-subtracted profiles (values straddling zero) are handled
    by splitting on the SHIFTED profile: the pulsed fraction is
    pulsed_sum / (pulsed_sum + nbins * baseline-above-zero), never a
    negative or blown-up weight."""
    raw = np.loadtxt(path)
    vals = raw[:, -1] if raw.ndim == 2 else raw
    base = float(vals.min())
    pulsed = vals - base
    ps = float(pulsed.sum())
    if ps <= 0:
        raise ValueError(f"{path}: profile is constant (no pulse)")
    w = ps / (ps + len(vals) * max(base, 0.0))
    return LCTemplate([LCBinnedProfile(pulsed + 1e-12)], weights=[w])


def write_prof(template: LCTemplate, path, nbins: int = 256):
    """Sample any template onto nbins and write one value per line."""
    phases = (np.arange(nbins) + 0.5) / nbins
    vals = np.asarray(template(phases))
    np.savetxt(path, vals, fmt="%.8f")
    return path


def read_template(path):
    """The one template-format dispatch (used by event_optimize):
    .gauss -> component template; the legacy one-peak-per-line
    'weight:width:loc' text -> Gaussian template; anything else ->
    binned .prof profile.  Returns (template, errors-or-None)."""
    path = str(path)
    first = ""
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                first = line
                break
    # content sniffing, not extension: 'const = ...' lines mean the
    # itemplate convention whatever the file is called
    if path.endswith(".gauss") or "=" in first:
        return read_gauss(path)
    if ":" in first:
        prims, wts = [], []
        for line in open(path):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            wt, width, loc = (float(v) for v in line.split(":"))
            prims.append(LCGaussian(width=width, loc=loc))
            wts.append(wt)
        return LCTemplate(prims, weights=wts), None
    return read_prof(path), None
