"""Fake-TOA simulation (zima backend).

Reference parity: src/pint/simulation.py::make_fake_toas_uniform /
make_fake_toas_fromtim — choose arrival times so the model phase is an
integer (iterative inversion), then optionally add white noise draws.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.models.timing_model import TimingModel
from pint_tpu.timebase.times import TimeArray
from pint_tpu.toas.ingest import ingest_barycentric
from pint_tpu.toas.toas import TOAs


def make_fake_toas_uniform(
    start_mjd: float,
    end_mjd: float,
    ntoa: int,
    model: TimingModel,
    error_us: float = 1.0,
    freq_mhz=1400.0,
    obs="@",
    add_noise: bool = False,
    rng: Optional[np.random.Generator] = None,
    iterations: int = 3,
    mjds=None,
) -> TOAs:
    """Uniformly spaced TOAs whose model phase is (near-)integer.

    For obs='@' the times are barycentric TDB (no ingest chain).  The
    inversion iterates: evaluate phase residual, shift each TOA by
    -resid/f; three passes land at machine-level integer phase.
    obs may be a single code, a full per-TOA sequence (paired with the
    given mjds, permuted together if they need sorting), or a short
    pattern that cycles over the time-sorted grid; mjds (optional)
    overrides the uniform grid with explicit epochs (e.g. to pin a TOA
    onto a leap-second day).
    """
    obs_list = None if isinstance(obs, str) else list(obs)
    if mjds is None:
        mjds = np.linspace(start_mjd, end_mjd, ntoa)
    else:
        mjds = np.asarray(mjds, dtype=np.float64)
        ntoa = len(mjds)
        order = np.argsort(mjds, kind="stable")
        if obs_list is not None and len(obs_list) == ntoa:
            obs_list = [obs_list[i] for i in order]  # keep the pairing
        mjds = mjds[order]
    freq = np.broadcast_to(np.asarray(freq_mhz, dtype=np.float64), (ntoa,))
    t = TimeArray.from_mjd_float(mjds, scale="utc")
    if obs_list is None:
        obs_list = [obs] * ntoa
    elif len(obs_list) != ntoa:
        if len(obs_list) > ntoa:
            raise ValueError(
                f"obs sequence ({len(obs_list)}) longer than ntoa "
                f"({ntoa}); pass exactly ntoa codes or a short pattern"
            )
        obs_list = [obs_list[i % len(obs_list)] for i in range(ntoa)]
    toas = TOAs(
        t,
        freq,
        np.full(ntoa, error_us),
        obs_list,
        [dict() for _ in range(ntoa)],
    )
    _ingest(toas, model)
    _invert_to_integer_phase(toas, model, iterations)
    if add_noise:
        _add_white_noise(toas, model, rng)
    return toas


def _sim_cpu_device():
    """Device pin for the simulation's eager residual sweeps.

    The phase inversion below evaluates ``cm.time_residuals`` EAGERLY
    (op by op, no jit).  On the axon tunnel every eager op is a ~85 ms
    round-trip, so the sweep cost ~70 s of pure dispatch latency
    REGARDLESS of ntoa — the fixed `build_ingest_s` floor the r6
    cold-path profile flagged (profiling/profile_fit_wall.py).  Host
    scaffolding belongs on the host: pinned to CPU the same sweep is
    numpy-speed AND exact IEEE f64 (the tunnel's f32-pair emulation is
    not), so simulated TOAs can only get more accurate.  Device fits of
    the simulated data still run on the default backend — only this
    host-side construction is pinned.
    """
    import jax

    return jax.default_device(jax.devices("cpu")[0])


def _invert_to_integer_phase(toas: TOAs, model: TimingModel, iterations):
    """Shift arrival times until the model phase is (near-)integer."""
    with _sim_cpu_device():
        for _ in range(iterations):
            cm = model.compile(toas, subtract_mean=False)
            cm.track_mode = "nearest"
            resid = np.asarray(
                cm.time_residuals(cm.x0(), subtract_mean=False)
            )
            toas.t = toas.t.add_seconds(-resid)
            _ingest(toas, model)


def _add_white_noise(toas: TOAs, model: TimingModel, rng):
    rng = rng or np.random.default_rng()
    toas.t = toas.t.add_seconds(
        rng.normal(0.0, toas.error_us * 1e-6)
    )
    _ingest(toas, model)


def _ingest(toas: TOAs, model: TimingModel):
    if all(o.lower() in ("@", "bat", "ssb", "barycenter") for o in toas.obs):
        ingest_barycentric(toas)
    else:
        from pint_tpu.toas.ingest import ingest_for_model

        ingest_for_model(toas, model)


def make_fake_toas_fromtim(
    tim, model: TimingModel, add_noise: bool = False,
    rng: Optional[np.random.Generator] = None, iterations: int = 3,
) -> TOAs:
    """Replace the TOAs of an existing tim file (path or TOAs object)
    with model-perfect ones at the same epochs/frequencies/errors/sites
    (reference: simulation.make_fake_toas_fromtim).  A passed-in TOAs
    object is copied, never mutated."""
    import os

    from pint_tpu.io.tim import get_TOAs_from_tim

    if isinstance(tim, (str, bytes, os.PathLike)):
        toas = get_TOAs_from_tim(tim)
    else:
        toas = tim[:]  # slice-copy: the caller's object stays intact
    _ingest(toas, model)
    _invert_to_integer_phase(toas, model, iterations)
    if add_noise:
        _add_white_noise(toas, model, rng)
    return toas


def make_test_pulsar(
    par: str,
    ntoa: int = 64,
    start_mjd: float = 54000.0,
    end_mjd: float = 56000.0,
    seed: int = 0,
    jitter_us: float = 1.0,
    freqs=(1400.0, 800.0),
    flags=("L-wide", "S-wide"),
    obs="@",
    error_us: float = 1.0,
    iterations: int = 3,
    mjds=None,
):
    """Simulated pulsar scaffold shared by benches, smoke runs, and
    tests: build the model, simulate TOAs cycling over observing
    frequencies, tag alternating receiver flags (for mask params), add
    white jitter, ingest.  Returns (model, toas).  obs/mjds pass
    through to make_fake_toas_uniform (per-TOA sites, explicit epochs)."""
    from pint_tpu.models.builder import get_model

    rng = np.random.default_rng(seed)
    model = get_model(par)
    if mjds is not None:
        ntoa = len(mjds)
    toas = make_fake_toas_uniform(
        start_mjd, end_mjd, ntoa, model, error_us=error_us,
        freq_mhz=np.resize(np.asarray(freqs, dtype=np.float64), ntoa),
        obs=obs, iterations=iterations, mjds=mjds,
    )
    for i, f in enumerate(toas.flags):
        f["f"] = flags[i % len(flags)]
    if jitter_us:
        toas.t = toas.t.add_seconds(
            rng.normal(0.0, jitter_us * 1e-6, ntoa)
        )
    _ingest(toas, model)
    return model, toas


def make_population(
    par: str,
    npsr: int,
    ntoa: int = 64,
    seed: int = 0,
    spread: float = 1e-9,
    **make_test_pulsar_kw,
):
    """Population scaffold for composition-keyed serving benches and
    tests (ISSUE 6): ``npsr`` par-parameter variants of ONE
    composition sharing ONE simulated TOA set — so population runs pay
    the host ingest path once, not N times.

    Builds the base pulsar via :func:`make_test_pulsar`, then emits
    par texts whose free float/HostDD parameters (spin, astrometry,
    dispersion — whatever the composition frees) are perturbed by
    ``spread`` relative (absolute for zero-valued references) draws.
    The component stack, free-parameter layout, and mask structure are
    untouched, so every variant lands in the same serving composition
    (serve/session.py::composition_key) and stacks into one vmapped
    dispatch.  Epoch (MJD) parameters stay fixed: perturbing them
    would only re-anchor the internal delta, not change composition,
    and tiny-spread epoch shifts are invisible at f64 anyway.

    Returns ``(pars, toas)`` where ``pars[0]`` is the base model's own
    parfile and ``toas`` is the shared (already ingested) TOA set.
    """
    from pint_tpu.models.builder import get_model
    from pint_tpu.timebase.hostdd import HostDD

    if npsr < 1:
        raise ValueError(f"make_population needs npsr >= 1, got {npsr}")
    model, toas = make_test_pulsar(
        par, ntoa=ntoa, seed=seed, **make_test_pulsar_kw
    )
    base = model.as_parfile()
    rng = np.random.default_rng(seed + 0x5EED)
    pars = [base]
    for _ in range(1, npsr):
        m = get_model(base)
        for name in m.free_params:
            p = m.params[name]
            ref = p.internal()
            if isinstance(ref, HostDD):
                scale = abs(float(ref.hi)) or 1.0
                p.set_internal(
                    ref + spread * scale * rng.standard_normal()
                )
            elif isinstance(ref, float):
                scale = abs(ref) or 1.0
                p.set_internal(
                    ref + spread * scale * rng.standard_normal()
                )
            # tuples (epochs / pair parameters) stay at the base value
        pars.append(m.as_parfile())
    return pars, toas


def calculate_random_models(
    fitter, n_models: int = 100, rng: Optional[np.random.Generator] = None
):
    """Draw parameter vectors from the fit covariance and return per-draw
    residual curves (reference: simulation.calculate_random_models)."""
    rng = rng or np.random.default_rng()
    cov = fitter.parameter_covariance_matrix  # free_names order
    if cov is None:
        raise ValueError("fit first")
    L = np.linalg.cholesky(cov + 1e-30 * np.eye(len(cov)))
    draws = rng.normal(size=(n_models, len(cov))) @ L.T
    out = []
    for d in draws:
        out.append(
            np.asarray(fitter.cm.time_residuals(np.asarray(d)))
        )
    return np.stack(out)
