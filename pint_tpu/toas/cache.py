"""Persistent TOA ingest cache: skip the clock/TDB/posvel pipeline on
reload, and re-ingest only the appended tail when a tim file grows.

Reference parity: src/pint/toa.py get_TOAs(usepickle=True) — the
reference writes <tim>.pickle.gz keyed by a content hash.  Here the
ingested columns are saved as a .npz next to the tim file (or in
$PINT_TPU_CACHE_DIR), double-double columns round-tripping exactly
(hi/lo pairs).

Cache key (r6): three independent components, each invalidating on its
own axis —
  * ``content_hash``  — sha256 of the tim file bytes (data changed);
  * ``options_key``   — ingest options incl. the model's par-file text
    (ephemeris/BIPM/planets choices changed);
  * baked into ``options_key``: the npz ``_FORMAT_VERSION`` and
    ingest_topo.INGEST_CODE_VERSION (the ingest numerics changed —
    bumping either orphan-invalidates every existing cache file).

Append-incremental reuse: observation runs APPEND TOAs — the common
"new day of data" reload shares every earlier row bit-for-bit.  When
the content hash misses but the options key matches and the cached
rows are exactly a prefix of the new tim rows (arrival times, freqs,
errors, sites, flags all equal), only the tail is ingested and the
columns are stitched.  This is exact because the ingest chain is a
pure per-TOA map (see ingest_topo's chunking contract) — proven
bit-identical in tests/test_ingest_parallel.py.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

import numpy as np

from pint_tpu.timebase.hostdd import HostDD
from pint_tpu.timebase.times import TimeArray
from pint_tpu.toas.toas import TOAs

_FORMAT_VERSION = 2

#: per-TOA derived columns persisted alongside the raw rows
_DERIVED_COLS = (
    "clock_corr_s", "ssb_obs_pos", "ssb_obs_vel", "obs_sun_pos",
    "obs_lat_rad", "obs_alt_m", "obs_elevation_rad",
)


def _cache_path(tim_path) -> Path:
    cdir = os.environ.get("PINT_TPU_CACHE_DIR")
    p = Path(tim_path)
    if cdir:
        return Path(cdir) / (p.name + ".ingest.npz")
    return p.with_name(p.name + ".ingest.npz")


def _options_key(**options) -> str:
    """Hash of everything except the tim content: ingest options + the
    npz format version + the ingest-chain code version."""
    from pint_tpu.toas.ingest_topo import INGEST_CODE_VERSION
    from pint_tpu.utils import compute_hash

    return compute_hash(
        _FORMAT_VERSION, INGEST_CODE_VERSION, sorted(options.items())
    )


def _content_hash(tim_path) -> str:
    from pint_tpu.utils import compute_hash

    return compute_hash(tim_path)


def _flag_reprs(toas: TOAs) -> np.ndarray:
    return np.array([repr(sorted(f.items())) for f in toas.flags])


def save_cache(toas: TOAs, tim_path, **options):
    """Write the ingested TOA columns keyed on tim content + options +
    code version."""
    arrs = {
        "options_key": np.array(_options_key(**options)),
        "content_hash": np.array(_content_hash(tim_path)),
        "t_day": toas.t.mjd_int, "t_hi": toas.t.sec.hi,
        "t_lo": toas.t.sec.lo, "t_scale": np.array(toas.t.scale),
        "freq": toas.freq, "error_us": toas.error_us,
        "obs": np.array(toas.obs),
        "flags": _flag_reprs(toas),
    }
    if toas.ephem is not None:
        arrs["ephem"] = np.array(toas.ephem)
    if toas.t_tdb is not None:
        arrs.update(
            tdb_day=toas.t_tdb.mjd_int, tdb_hi=toas.t_tdb.sec.hi,
            tdb_lo=toas.t_tdb.sec.lo,
        )
    for col in _DERIVED_COLS:
        v = getattr(toas, col)
        if v is not None:
            arrs[col] = v
    for body, v in toas.obs_planet_pos.items():
        arrs[f"planet:{body}"] = v
    np.savez_compressed(_cache_path(tim_path), **arrs)


def _toas_from_npz(z) -> TOAs:
    import ast

    flags = [
        dict(ast.literal_eval(s)) for s in z["flags"].tolist()
    ]
    t = TimeArray(
        z["t_day"], HostDD(z["t_hi"], z["t_lo"]), str(z["t_scale"])
    )
    toas = TOAs(t, z["freq"], z["error_us"], z["obs"].tolist(), flags)
    if "tdb_day" in z:
        toas.t_tdb = TimeArray(
            z["tdb_day"], HostDD(z["tdb_hi"], z["tdb_lo"]), "tdb"
        )
    for col in _DERIVED_COLS:
        if col in z:
            setattr(toas, col, z[col])
    for name in z.files:
        if name.startswith("planet:"):
            toas.obs_planet_pos[name.split(":", 1)[1]] = z[name]
    if "ephem" in z:
        toas.ephem = str(z["ephem"])
    return toas


def _load_npz(tim_path, **options):
    """The cache npz when it exists and its options/version key
    matches; None otherwise (content hash NOT checked here)."""
    path = _cache_path(tim_path)
    if not path.exists():
        return None
    try:
        z = np.load(path, allow_pickle=False)
    except (OSError, ValueError):
        return None
    key = "options_key" if "options_key" in z.files else "key"
    if str(z[key]) != _options_key(**options):
        return None
    return z


def load_cache(tim_path, **options) -> Optional[TOAs]:
    """Ingested TOAs from cache, or None on miss/stale key (content,
    options, or code version changed)."""
    z = _load_npz(tim_path, **options)
    if z is None or "content_hash" not in z.files:
        return None
    if str(z["content_hash"]) != _content_hash(tim_path):
        return None
    return _toas_from_npz(z)


def _prefix_rows_match(cached: TOAs, new: TOAs) -> bool:
    """True when the cached rows are exactly the first len(cached) raw
    rows of the new tim parse (times, freqs, errors, sites, flags)."""
    nc = len(cached)
    if nc == 0 or nc > len(new):
        return False
    head = new[:nc]
    return (
        cached.t.scale == head.t.scale
        and np.array_equal(cached.t.mjd_int, head.t.mjd_int)
        and np.array_equal(cached.t.sec.hi, head.t.sec.hi)
        and np.array_equal(cached.t.sec.lo, head.t.sec.lo)
        and np.array_equal(cached.freq, head.freq)
        and np.array_equal(cached.error_us, head.error_us)
        and cached.obs == head.obs
        and cached.flags == head.flags
    )


def _stitch_columns(full: TOAs, prefix: TOAs, tail: TOAs):
    """Copy ingested columns onto ``full`` by concatenating the cached
    prefix with the freshly-ingested tail, preserving ROW ORDER (no
    re-sort: the stitched table must be bitwise the full-ingest one)."""
    full.t_tdb = TimeArray(
        np.concatenate([prefix.t_tdb.mjd_int, tail.t_tdb.mjd_int]),
        HostDD(
            np.concatenate([prefix.t_tdb.sec.hi, tail.t_tdb.sec.hi]),
            np.concatenate([prefix.t_tdb.sec.lo, tail.t_tdb.sec.lo]),
        ),
        "tdb",
    )
    for col in _DERIVED_COLS:
        a, b = getattr(prefix, col), getattr(tail, col)
        if a is not None and b is not None:
            setattr(full, col, np.concatenate([a, b]))
    for body in tail.obs_planet_pos:
        if body in prefix.obs_planet_pos:
            full.obs_planet_pos[body] = np.concatenate(
                [prefix.obs_planet_pos[body], tail.obs_planet_pos[body]]
            )
    full.ephem = tail.ephem if tail.ephem is not None else prefix.ephem


def append_ingested(base: TOAs, tail: TOAs, model=None,
                    **ingest_kw) -> TOAs:
    """In-memory sibling of :func:`get_TOAs`'s append-incremental
    path — the streaming ObserveSession's TOA-set extension (ISSUE
    14): ingest ONLY the appended ``tail`` (the base's computed
    columns are reused as-is, zero re-ingest of absorbed rows) and
    merge.  The merge time-sorts and refuses inconsistent ephemerides
    (toas/toas.py::merge_TOAs); accounting lands on the same
    ``ingest.cache.incremental``/``rows_reused`` counters as the
    file-path tail ingest, so the O(append) claim is observable."""
    from pint_tpu.obs import metrics as obs_metrics
    from pint_tpu.obs.trace import TRACER
    from pint_tpu.toas.ingest import ingest, ingest_for_model
    from pint_tpu.toas.toas import merge_TOAs

    if base.t_tdb is None:
        raise ValueError(
            "append_ingested needs an already-ingested base TOA set"
        )
    with TRACER.span(
        "ingest:append", "ingest", base=len(base), tail=len(tail),
    ):
        if tail.t_tdb is None:
            if model is not None:
                ingest_for_model(tail, model, **ingest_kw)
            else:
                ingest(tail, **ingest_kw)
        merged = merge_TOAs([base, tail])
    obs_metrics.counter(
        "ingest.cache.incremental",
        help="ingest-cache prefix reuses (tail-only ingest)",
    ).inc()
    obs_metrics.counter(
        "ingest.cache.rows_reused", unit="TOAs",
        help="TOA rows served from the ingest cache",
    ).inc(len(base))
    return merged


def get_TOAs(
    tim_path,
    model=None,
    usepickle: bool = False,
    **ingest_kw,
) -> TOAs:
    """tim file -> ingested TOAs, with optional caching (the
    reference's get_TOAs(usepickle=...) surface).

    With ``usepickle=True``: an exact cache hit (content + options +
    code version) skips ingest entirely; a grown tim file whose old
    rows are an unchanged prefix re-ingests ONLY the appended tail;
    anything else re-ingests in full and refreshes the cache.
    Outcomes land on the metrics registry (``ingest.cache.*``) and the
    flight recorder."""
    from pint_tpu.obs import metrics as obs_metrics
    from pint_tpu.obs.trace import TRACER
    from pint_tpu.toas.ingest import ingest, ingest_for_model

    opts = dict(ingest_kw)
    if model is not None:
        opts["model_par"] = model.as_parfile()

    def _ingest(t):
        if model is not None:
            return ingest_for_model(t, model, **ingest_kw)
        return ingest(t, **ingest_kw)

    cached_prefix = None
    if usepickle:
        with TRACER.span("ingest:cache-load", "ingest"):
            cached = load_cache(tim_path, **opts)
        if cached is not None:
            obs_metrics.counter(
                "ingest.cache.hits", help="full ingest-cache hits"
            ).inc()
            return cached
        z = _load_npz(tim_path, **opts)
        if z is not None and "content_hash" in z.files:
            cached_prefix = _toas_from_npz(z)

    from pint_tpu.io.tim import get_TOAs_from_tim

    toas = get_TOAs_from_tim(tim_path)
    if (
        cached_prefix is not None
        and cached_prefix.t_tdb is not None
        and _prefix_rows_match(cached_prefix, toas)
    ):
        nc = len(cached_prefix)
        with TRACER.span(
            "ingest:incremental", "ingest",
            ntoa=len(toas), cached=nc, tail=len(toas) - nc,
        ):
            tail = _ingest(toas[nc:])
            _stitch_columns(toas, cached_prefix, tail)
        obs_metrics.counter(
            "ingest.cache.incremental",
            help="ingest-cache prefix reuses (tail-only ingest)",
        ).inc()
        obs_metrics.counter(
            "ingest.cache.rows_reused", unit="TOAs",
            help="TOA rows served from the ingest cache",
        ).inc(nc)
        save_cache(toas, tim_path, **opts)
        return toas

    if usepickle:
        obs_metrics.counter(
            "ingest.cache.misses", help="ingest-cache misses"
        ).inc()
    toas = _ingest(toas)
    if usepickle:
        save_cache(toas, tim_path, **opts)
    return toas
