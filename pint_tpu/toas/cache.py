"""TOA ingest cache: skip the clock/TDB/posvel pipeline on reload.

Reference parity: src/pint/toa.py get_TOAs(usepickle=True) — the
reference writes <tim>.pickle.gz keyed by a content hash.  Here the
ingested columns are saved as a .npz next to the tim file (or in
$PINT_TPU_CACHE_DIR), keyed on the tim bytes + ingest options hash;
double-double columns round-trip exactly (hi/lo pairs).
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

import numpy as np

from pint_tpu.timebase.hostdd import HostDD
from pint_tpu.timebase.times import TimeArray
from pint_tpu.toas.toas import TOAs
from pint_tpu.utils import compute_hash

_FORMAT_VERSION = 1


def _cache_path(tim_path) -> Path:
    cdir = os.environ.get("PINT_TPU_CACHE_DIR")
    p = Path(tim_path)
    if cdir:
        return Path(cdir) / (p.name + ".ingest.npz")
    return p.with_name(p.name + ".ingest.npz")


def _options_key(tim_path, **options) -> str:
    return compute_hash(
        tim_path, _FORMAT_VERSION, sorted(options.items())
    )


def save_cache(toas: TOAs, tim_path, **options):
    """Write the ingested TOA columns keyed on tim content + options."""
    arrs = {
        "key": np.array(_options_key(tim_path, **options)),
        "t_day": toas.t.mjd_int, "t_hi": toas.t.sec.hi,
        "t_lo": toas.t.sec.lo, "t_scale": np.array(toas.t.scale),
        "freq": toas.freq, "error_us": toas.error_us,
        "obs": np.array(toas.obs),
        "flags": np.array(
            [repr(sorted(f.items())) for f in toas.flags]
        ),
    }
    if toas.t_tdb is not None:
        arrs.update(
            tdb_day=toas.t_tdb.mjd_int, tdb_hi=toas.t_tdb.sec.hi,
            tdb_lo=toas.t_tdb.sec.lo,
        )
    for col in (
        "clock_corr_s", "ssb_obs_pos", "ssb_obs_vel", "obs_sun_pos",
        "obs_lat_rad", "obs_alt_m", "obs_elevation_rad",
    ):
        v = getattr(toas, col)
        if v is not None:
            arrs[col] = v
    for body, v in toas.obs_planet_pos.items():
        arrs[f"planet:{body}"] = v
    np.savez_compressed(_cache_path(tim_path), **arrs)


def load_cache(tim_path, **options) -> Optional[TOAs]:
    """Ingested TOAs from cache, or None on miss/stale key."""
    path = _cache_path(tim_path)
    if not path.exists():
        return None
    try:
        z = np.load(path, allow_pickle=False)
    except (OSError, ValueError):
        return None
    if str(z["key"]) != _options_key(tim_path, **options):
        return None
    import ast

    flags = [
        dict(ast.literal_eval(s)) for s in z["flags"].tolist()
    ]
    t = TimeArray(
        z["t_day"], HostDD(z["t_hi"], z["t_lo"]), str(z["t_scale"])
    )
    toas = TOAs(t, z["freq"], z["error_us"], z["obs"].tolist(), flags)
    if "tdb_day" in z:
        toas.t_tdb = TimeArray(
            z["tdb_day"], HostDD(z["tdb_hi"], z["tdb_lo"]), "tdb"
        )
    for col in (
        "clock_corr_s", "ssb_obs_pos", "ssb_obs_vel", "obs_sun_pos",
        "obs_lat_rad", "obs_alt_m", "obs_elevation_rad",
    ):
        if col in z:
            setattr(toas, col, z[col])
    for name in z.files:
        if name.startswith("planet:"):
            toas.obs_planet_pos[name.split(":", 1)[1]] = z[name]
    return toas


def get_TOAs(
    tim_path,
    model=None,
    usepickle: bool = False,
    **ingest_kw,
) -> TOAs:
    """tim file -> ingested TOAs, with optional caching (the
    reference's get_TOAs(usepickle=...) surface)."""
    from pint_tpu.io.tim import get_TOAs_from_tim
    from pint_tpu.toas.ingest import ingest, ingest_for_model

    opts = dict(ingest_kw)
    if model is not None:
        opts["model_par"] = model.as_parfile()
    if usepickle:
        cached = load_cache(tim_path, **opts)
        if cached is not None:
            return cached
    toas = get_TOAs_from_tim(tim_path)
    if model is not None:
        ingest_for_model(toas, model, **ingest_kw)
    else:
        ingest(toas, **ingest_kw)
    if usepickle:
        save_cache(toas, tim_path, **opts)
    return toas
