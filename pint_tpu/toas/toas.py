"""TOAs container: the host-side table of arrival times + metadata.

Reference parity: src/pint/toa.py::TOAs (astropy-Table-backed; columns
mjd, mjd_float, error, freq, obs, flags, clkcorr, tdb, tdbld,
ssb_obs_pos/vel, obs_sun_pos...).  Here: plain numpy arrays + a
``TimeArray`` for arrival times, with the ingest pipeline
(pint_tpu.toas.ingest) filling the computed columns; ``to_bundle()``
exports the device-resident array bundle consumed by compiled kernels.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pint_tpu.timebase.hostdd import HostDD
from pint_tpu.timebase.times import TimeArray


class TOAs:
    """Table of TOAs.

    Core columns (always present):
      t         TimeArray (UTC at observatory, unless site '@'/bary)
      freq      observing frequency, MHz (np.inf for infinite-frequency)
      error_us  TOA uncertainty in microseconds
      obs       observatory codes (list[str])
      flags     list[dict] per-TOA tim flags
    Computed columns (after ingest):
      clock_corr_s   applied clock correction (seconds)
      t_tdb          TimeArray in TDB at the observatory (time scale only)
      ssb_obs_pos/vel   m, m/s GCRS->SSB observatory state (n,3)
      obs_sun_pos       m, obs->Sun vector (n,3)
      obs_planet_pos    dict body -> (n,3) m
    """

    def __init__(self, t: TimeArray, freq, error_us, obs, flags=None):
        n = len(t)
        self.t = t
        self.freq = np.asarray(freq, dtype=np.float64)
        self.error_us = np.asarray(error_us, dtype=np.float64)
        self.obs = list(obs)
        self.flags = flags if flags is not None else [dict() for _ in range(n)]
        assert len(self.freq) == n and len(self.error_us) == n
        assert len(self.obs) == n and len(self.flags) == n
        # computed columns
        self.clock_corr_s: Optional[np.ndarray] = None
        self.t_tdb: Optional[TimeArray] = None
        self.ssb_obs_pos: Optional[np.ndarray] = None
        self.ssb_obs_vel: Optional[np.ndarray] = None
        self.obs_sun_pos: Optional[np.ndarray] = None
        self.obs_planet_pos: dict = {}
        self.obs_lat_rad: Optional[np.ndarray] = None
        self.obs_alt_m: Optional[np.ndarray] = None
        self.obs_elevation_rad: Optional[np.ndarray] = None
        self.ephem: Optional[str] = None
        self.clock_info: dict = {}

    # per-TOA computed columns that slice/sort alongside the core ones
    _COMPUTED_COLS = (
        "clock_corr_s", "ssb_obs_pos", "ssb_obs_vel", "obs_sun_pos",
        "obs_lat_rad", "obs_alt_m", "obs_elevation_rad",
    )

    # ------------------------------------------------------------------ #
    def __len__(self):
        return len(self.t)

    def __getitem__(self, idx) -> "TOAs":
        if isinstance(idx, (int, np.integer)):
            idx = slice(idx, idx + 1)
        sel = np.arange(len(self))[idx]
        out = TOAs(
            self.t[sel],
            self.freq[sel],
            self.error_us[sel],
            [self.obs[i] for i in sel],
            [self.flags[i] for i in sel],
        )
        for col in self._COMPUTED_COLS:
            v = getattr(self, col)
            if v is not None:
                setattr(out, col, v[sel])
        if self.t_tdb is not None:
            out.t_tdb = self.t_tdb[sel]
        out.obs_planet_pos = {k: v[sel] for k, v in self.obs_planet_pos.items()}
        out.ephem = self.ephem
        return out

    def mjd_float(self) -> np.ndarray:
        return self.t.mjd_float()

    def sort(self) -> np.ndarray:
        """Sort in place by time; returns the permutation applied."""
        order = self.t.sort_index()
        self.t = self.t[order]
        self.freq = self.freq[order]
        self.error_us = self.error_us[order]
        self.obs = [self.obs[i] for i in order]
        self.flags = [self.flags[i] for i in order]
        for col in self._COMPUTED_COLS:
            v = getattr(self, col)
            if v is not None:
                setattr(self, col, v[order])
        if self.t_tdb is not None:
            self.t_tdb = self.t_tdb[order]
        self.obs_planet_pos = {
            k: v[order] for k, v in self.obs_planet_pos.items()
        }
        return order

    def get_flag_value(self, flag: str, default="") -> list:
        return [f.get(flag, default) for f in self.flags]

    def is_wideband(self) -> bool:
        """True when every TOA carries a wideband DM measurement
        (-pp_dm flag; reference: toa.py::TOAs.is_wideband)."""
        return len(self) > 0 and all("pp_dm" in f for f in self.flags)

    def get_dm_measurements(self) -> tuple[np.ndarray, np.ndarray]:
        """Wideband DM measurements + errors (pc/cm^3) from -pp_dm /
        -pp_dme flags; NaN where absent."""
        dm = np.array(
            [float(f.get("pp_dm", np.nan)) for f in self.flags]
        )
        dme = np.array(
            [float(f.get("pp_dme", np.nan)) for f in self.flags]
        )
        return dm, dme

    def get_pulse_numbers(self) -> Optional[np.ndarray]:
        """Per-TOA pulse numbers from -pn flags, if all present."""
        pns = self.get_flag_value("pn", None)
        if any(p is None for p in pns):
            return None
        return np.array([float(p) for p in pns])

    @property
    def ntoas(self):
        return len(self)

    def first_mjd(self) -> float:
        return float(np.min(self.mjd_float()))

    def last_mjd(self) -> float:
        return float(np.max(self.mjd_float()))

    def __repr__(self):
        return (
            f"TOAs(n={len(self)}, mjd {self.first_mjd():.1f}-"
            f"{self.last_mjd():.1f}, obs {sorted(set(self.obs))})"
        )


def merge_TOAs(toas_list) -> TOAs:
    """Concatenate TOA sets (reference: toa.merge_TOAs).  Computed
    columns merge only when present on every member (else they reset to
    None and a re-ingest is needed); the result is time-sorted.
    Members ingested with different ephemerides refuse to merge (their
    geometry columns would be inconsistent)."""
    if not toas_list:
        raise ValueError("nothing to merge")
    t0 = toas_list[0]
    # geometry consistency: members whose SSB geometry columns are
    # populated must agree on the ephemeris that produced them —
    # including ephem=None members (barycentric ingest), whose columns
    # would otherwise silently concatenate under another member's tag
    geom_ephems = {t.ephem for t in toas_list if t.ssb_obs_pos is not None}
    if len(geom_ephems) > 1:
        raise ValueError(
            "cannot merge TOAs with geometry computed under different "
            f"ephemerides: {sorted(str(e) for e in geom_ephems)}"
        )
    ephems = {t.ephem for t in toas_list}
    out = TOAs(
        TimeArray(
            np.concatenate([t.t.mjd_int for t in toas_list]),
            HostDD(
                np.concatenate([t.t.sec.hi for t in toas_list]),
                np.concatenate([t.t.sec.lo for t in toas_list]),
            ),
            t0.t.scale,
        ),
        np.concatenate([t.freq for t in toas_list]),
        np.concatenate([t.error_us for t in toas_list]),
        sum((t.obs for t in toas_list), []),
        sum(([dict(f) for f in t.flags] for t in toas_list), []),
    )
    if any(t.t.scale != t0.t.scale for t in toas_list):
        raise ValueError("cannot merge TOAs with different time scales")
    for col in TOAs._COMPUTED_COLS:
        vals = [getattr(t, col) for t in toas_list]
        if all(v is not None for v in vals):
            setattr(out, col, np.concatenate(vals))
    if all(t.t_tdb is not None for t in toas_list):
        out.t_tdb = TimeArray(
            np.concatenate([t.t_tdb.mjd_int for t in toas_list]),
            HostDD(
                np.concatenate([t.t_tdb.sec.hi for t in toas_list]),
                np.concatenate([t.t_tdb.sec.lo for t in toas_list]),
            ),
            "tdb",
        )
    bodies = set().union(*(t.obs_planet_pos for t in toas_list))
    for b in bodies:
        if all(b in t.obs_planet_pos for t in toas_list):
            out.obs_planet_pos[b] = np.concatenate(
                [t.obs_planet_pos[b] for t in toas_list]
            )
    # single shared tag propagates; a mix (e.g. tagged + never-
    # ingested) leaves the merged set untagged
    out.ephem = ephems.pop() if len(ephems) == 1 else None
    for t in toas_list:
        out.clock_info.update(t.clock_info)
    out.sort()
    return out
