"""TOA layer: container, ingest pipeline, selection."""

from pint_tpu.toas.toas import TOAs  # noqa: F401
