"""Topocentric TOA ingest: clock chain -> TDB -> solar-system geometry.

Reference parity: the §3.1 load-time stack (SURVEY.md) —
TOAs.apply_clock_corrections (observatory/__init__.py clock chains),
TOAs.compute_TDBs (astropy/ERFA time scales), TOAs.compute_posvels
(solar_system_ephemerides + erfautils.gcrs_posvel_from_itrf) — all
host-side numpy/HostDD; the products become TOABundle device columns.

Chain per TOA:
  1. site clock (+ GPS->UTC)            [observatory registry + .clk files]
  2. UTC -> TAI -> TT(TAI) [+ TT(BIPM)] [timebase.TimeArray + leap seconds]
  3. TT -> TDB (geocentric series) + topocentric (v_earth . r_obs)/c^2
  4. observatory ITRF -> GCRS posvel    [earth.rotation, EOP table]
  5. Earth/Sun/planet SSB posvels       [ephemeris: SPK or builtin]
  6. source elevation (troposphere), when the model's astrometry is known

Execution model (r6 cold-path overhaul): every stage is a pure
per-TOA map — no cross-TOA reductions anywhere in the chain — so the
TOA table is CHUNKED and chunks fan out across a thread pool (numpy
releases the GIL on the large-array kernels that dominate: the
54-term nutation series, the TDB series, SPK Chebyshev evaluation).
The once-per-dataset costs (clock-file discovery/composition, EOP
table load, SPK segment-chain routing, source direction) are hoisted
into an :class:`IngestPlan` built serially up front, so workers share
read-only prepared state.  Chunked output is BIT-IDENTICAL to the
serial path (tests/test_ingest_parallel.py proves it on the golden
sets): concatenating per-element maps commutes with slicing.

``$PINT_TPU_INGEST_WORKERS`` sets the pool width (0 or 1 = serial;
unset = min(8, cpu_count)).  A worker failure degrades to one clean
serial pass (recorded on the flight recorder + metrics) so parallel
ingest can never produce an answer serial ingest would not.
"""

from __future__ import annotations

import os
import warnings
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from pint_tpu.constants import C
from pint_tpu.earth.eop import get_eop
from pint_tpu.earth.rotation import (
    OMEGA_EARTH,
    itrf_to_gcrs_matrix,
    itrf_to_geodetic,
)
from pint_tpu.ephemeris import get_ephemeris, mjd_tdb_to_et
from pint_tpu.exceptions import PintTpuError
from pint_tpu.observatory import bipm_correction, get_observatory
from pint_tpu.timebase.hostdd import HostDD
from pint_tpu.timebase.times import TimeArray
from pint_tpu.toas.toas import TOAs

# NAIF ids for the PLANET_SHAPIRO bodies
_PLANETS = {
    "jupiter": 5, "saturn": 6, "venus": 2, "uranus": 7, "neptune": 8,
}

#: Cache-key component for the persistent ingest-column cache
#: (toas/cache.py): bump whenever the numerics of this chain change so
#: stale cached columns can never masquerade as current ones.
INGEST_CODE_VERSION = "ingest-r6"

#: Below this many TOAs a thread pool costs more than it saves; the
#: chain runs as one serial chunk.
_MIN_PARALLEL_TOAS = 16384


def ingest_workers() -> int:
    """Worker-pool width for chunked ingest: $PINT_TPU_INGEST_WORKERS
    (0 or 1 = serial), default min(8, usable cores).  'Usable' is the
    scheduler AFFINITY mask where the platform exposes it — cgroup
    -pinned containers report the full machine in cpu_count(), and a
    pool wider than the mask only adds GIL convoying."""
    env = os.environ.get("PINT_TPU_INGEST_WORKERS")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            warnings.warn(
                f"ignoring non-integer PINT_TPU_INGEST_WORKERS={env!r}"
            )
    try:
        usable = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        usable = os.cpu_count() or 1
    return min(8, usable)


class IngestPlan:
    """Once-per-dataset ingest state, hoisted out of the per-TOA chain.

    Built serially BEFORE the chunk fan-out so that (a) lazy loaders —
    clock-file discovery + composition, the EOP table, the ephemeris
    kernel and its SSB segment-chain routing — run exactly once instead
    of per TOA group per chunk, and (b) their one-time warnings/errors
    (missing clock file with limits='error', missing BIPM realization,
    absent EOP table) fire in the caller's thread with serial-identical
    semantics.  Workers only READ this object.
    """

    def __init__(self, toas: TOAs, ephem, planets, include_bipm,
                 bipm_version, include_gps, limits, model):
        self.ephem_name = ephem
        self.planets = bool(planets)
        self.include_bipm = bool(include_bipm)
        self.bipm_version = bipm_version
        self.include_gps = bool(include_gps)
        self.limits = limits
        # -- observatory resolution + clock-chain composition ------------
        self.sites = {
            code: get_observatory(code) for code in sorted(set(toas.obs))
        }
        self.itrf = {}
        empty = np.empty(0)
        for code, site in self.sites.items():
            if site.is_satellite:
                continue
            # prewarm: loads + composes the site clock files (and the
            # GPS steering file) once; emits the no-clock warning or
            # MissingClockCorrection (limits='error') exactly where the
            # serial chain used to
            site.clock_corrections(
                empty, include_gps=include_gps, limits=limits
            )
            loc = site.earth_location_itrf()
            self.itrf[code] = (
                np.zeros(3) if loc is None else np.asarray(loc, float)
            )
        if self.include_bipm:
            bipm_correction(empty, bipm_version)  # prewarm + warn-once
        get_eop(empty)  # prewarm the EOP table (env load + warn-once)
        self.eph = get_ephemeris(ephem)
        # hoist the SSB segment-chain routing (SPK kernels re-walked
        # the pair graph per call before r6; ephemeris/spk.py memoizes
        # via ssb_chain) for every body this ingest will evaluate
        targets = [399, 10] + (
            [naif for naif in _PLANETS.values()] if self.planets else []
        )
        if hasattr(self.eph, "ssb_chain"):
            for t in targets:
                self.eph.ssb_chain(t)
        self.src = _source_unit_vector(model)


def ingest_topocentric(
    toas: TOAs,
    ephem: str = "builtin",
    planets: bool = False,
    include_bipm: bool = True,
    bipm_version: str = "BIPM2021",
    include_gps: bool = True,
    limits: str = "warn",
    model=None,
) -> TOAs:
    from pint_tpu.obs.trace import TRACER

    n = len(toas)
    sites = [get_observatory(o) for o in toas.obs]
    if any(s.is_barycenter for s in sites):
        if all(s.is_barycenter for s in sites):
            from pint_tpu.toas.ingest import ingest_barycentric

            return ingest_barycentric(toas)
        raise PintTpuError(
            "mixed barycentric + topocentric TOAs in one set are not "
            "supported; split the tim file"
        )
    if toas.t.scale != "utc":
        raise PintTpuError(
            f"topocentric ingest expects UTC arrival times, got "
            f"{toas.t.scale!r}"
        )

    with TRACER.span("ingest:plan", "ingest", ntoa=n):
        plan = IngestPlan(
            toas, ephem, planets, include_bipm, bipm_version,
            include_gps, limits, model,
        )

    workers = ingest_workers()
    nchunks = 1
    if workers > 1 and n >= _MIN_PARALLEL_TOAS:
        nchunks = min(workers, max(1, n // (_MIN_PARALLEL_TOAS // 2)))
    edges = np.linspace(0, n, nchunks + 1).astype(int)

    with TRACER.span(
        "ingest:chunks", "ingest", ntoa=n, nchunks=nchunks,
        workers=workers,
    ):
        if nchunks == 1:
            parts = [_compute_chunk(plan, toas.t, toas.obs, 0, n, 0)]
        else:
            parts = _run_parallel(plan, toas, edges)
    _apply_columns(toas, parts, plan)
    return toas


def _run_parallel(plan: IngestPlan, toas: TOAs, edges) -> list:
    """Fan chunks across a thread pool; any worker failure degrades to
    one clean serial pass (the parallel path must never produce an
    answer — or an error — the serial path would not)."""
    from pint_tpu.obs import metrics as obs_metrics
    from pint_tpu.obs.trace import TRACER

    nchunks = len(edges) - 1
    obs_metrics.counter(
        "ingest.parallel.chunks", help="parallel ingest chunks run"
    ).inc(nchunks)
    try:
        with ThreadPoolExecutor(max_workers=nchunks) as pool:
            futs = [
                pool.submit(
                    _compute_chunk, plan, toas.t, toas.obs,
                    int(edges[k]), int(edges[k + 1]), k,
                )
                for k in range(nchunks)
            ]
            return [f.result() for f in futs]
    except Exception as e:  # degrade: serial recompute, then re-raise
        # only if the serial chain fails too (a genuine data error)
        obs_metrics.counter(
            "ingest.parallel.degrades",
            help="parallel ingest worker failures degraded to serial",
        ).inc()
        TRACER.event(
            "ingest:parallel-degrade", "ingest", error=repr(e)
        )
        warnings.warn(
            f"parallel ingest worker failed ({e!r}); recomputing "
            "serially"
        )
        return [_compute_chunk(plan, toas.t, toas.obs, 0, len(toas), 0)]


def _compute_chunk(plan: IngestPlan, t_all: TimeArray, obs_all,
                   lo: int, hi: int, chunk: int) -> dict:
    """The per-TOA chain on rows [lo, hi): a pure function of the
    prepared plan + the raw arrival rows — returns host column arrays,
    mutates nothing.  Chunking is exact: every stage maps elementwise
    over the TOA axis (interpolation, series evaluation, Chebyshev
    records, rotation matrices), so slice-then-compute equals
    compute-then-slice bitwise."""
    from pint_tpu.obs.trace import TRACER

    t = t_all[lo:hi]
    obs = list(obs_all[lo:hi])
    n = hi - lo
    out = {}

    # -- 1. clock chain ---------------------------------------------------
    with TRACER.span("ingest:clock", "ingest", ntoa=n, chunk=chunk):
        mjd_utc = t.mjd_float()
        clock = np.zeros(n)
        itrf = np.zeros((n, 3))
        sat_groups = []  # (bool index, SatelliteObs)
        for code in sorted(set(obs)):
            idx = np.array([o == code for o in obs])
            site = plan.sites[code]
            if site.is_satellite:
                # spacecraft clocks are corrected upstream in the event
                # products; position comes from the orbit table below
                sat_groups.append((idx, site))
                continue
            clock[idx] = site.clock_corrections(
                mjd_utc[idx], include_gps=plan.include_gps,
                limits=plan.limits,
            )
            itrf[idx] = plan.itrf[code]
        out["clock_corr_s"] = clock
        t_utc = t.add_seconds(clock)

    # -- 2. UTC -> TT -----------------------------------------------------
    with TRACER.span("ingest:tt", "ingest", ntoa=n, chunk=chunk):
        t_tt = t_utc.to_scale("tt")
        if plan.include_bipm:
            bipm = bipm_correction(mjd_utc, plan.bipm_version)
            # spacecraft times are corrected upstream in the event
            # products: no BIPM realization either (reference: satellite
            # observatories default include_bipm=False)
            for idx, _sat in sat_groups:
                bipm[idx] = 0.0
            t_tt = t_tt.add_seconds(bipm)

    # -- 4. Earth rotation (needed for the TDB topocentric term) ----------
    with TRACER.span("ingest:rotation", "ingest", ntoa=n, chunk=chunk):
        dut1, xp, yp = get_eop(mjd_utc)
        mjd_ut1 = t_utc.mjd_float() + dut1 / 86400.0
        tt_cent = (
            (t_tt.mjd_int - 51544.5) + t_tt.sec.to_float() / 86400.0
        ) / 36525.0
        # one rotation-matrix build serves position, velocity, and the
        # troposphere's local-vertical below (the nutation series
        # dominates the per-TOA geometry cost)
        M = itrf_to_gcrs_matrix(mjd_ut1, tt_cent, xp, yp)
        obs_pos = (M @ itrf[..., None])[..., 0]
        omega = np.array([0.0, 0.0, OMEGA_EARTH])
        obs_vel = (
            M @ np.cross(
                np.broadcast_to(omega, itrf.shape), itrf
            )[..., None]
        )[..., 0]
        # spacecraft rows: orbit-table interpolation (already GCRS)
        if sat_groups:
            mjd_tt_f = t_tt.mjd_float()
            for idx, sat in sat_groups:
                obs_pos[idx], obs_vel[idx] = sat.posvel_gcrs(
                    mjd_tt_f[idx]
                )

    # -- 3. TT -> TDB (geocentric series + topocentric term) --------------
    with TRACER.span("ingest:tdb", "ingest", ntoa=n, chunk=chunk):
        t_tdb = t_tt.to_scale("tdb")
        eph = plan.eph
        et = mjd_tdb_to_et(t_tdb.mjd_int, t_tdb.sec.to_float())
        epos_km, evel_km = eph.ssb_posvel(399, et)
        topo_s = np.sum(evel_km * 1000.0 * obs_pos, axis=-1) / (C * C)
        t_tdb = t_tdb.add_seconds(topo_s)
        out["t_tdb"] = t_tdb

    # -- 5. geometry columns (meters, m/s) --------------------------------
    with TRACER.span("ingest:ephemeris", "ingest", ntoa=n, chunk=chunk):
        # re-evaluate at the corrected TDB (the ~us shift moves Earth
        # by ~cm)
        et = mjd_tdb_to_et(t_tdb.mjd_int, t_tdb.sec.to_float())
        epos_km, evel_km = eph.ssb_posvel(399, et)
        out["ssb_obs_pos"] = epos_km * 1000.0 + obs_pos
        out["ssb_obs_vel"] = evel_km * 1000.0 + obs_vel
        spos_km, _ = eph.ssb_posvel(10, et)
        out["obs_sun_pos"] = spos_km * 1000.0 - out["ssb_obs_pos"]
        planet_pos = {}
        if plan.planets:
            for name, naif in _PLANETS.items():
                ppos_km, _ = eph.ssb_posvel(naif, et)
                planet_pos[name] = ppos_km * 1000.0 - out["ssb_obs_pos"]
        out["planet_pos"] = planet_pos

    # -- 6. troposphere geometry ------------------------------------------
    with TRACER.span("ingest:troposphere", "ingest", ntoa=n, chunk=chunk):
        on_ground = np.linalg.norm(itrf, axis=-1) > 1e6  # geocenter: no air
        lat, lon, height = itrf_to_geodetic(
            np.where(on_ground[:, None], itrf, [6378137.0, 0.0, 0.0])
        )
        lat = np.where(on_ground, lat, 0.0)
        height = np.where(on_ground, height, 0.0)
        out["obs_lat_rad"] = lat
        out["obs_alt_m"] = height
        if plan.src is not None:
            # geodetic normal in ITRF, rotated to GCRS with the same
            # matrix chain used for the position
            normal_itrf = np.stack(
                [np.cos(lat) * np.cos(lon), np.cos(lat) * np.sin(lon),
                 np.sin(lat)], axis=-1
            )
            normal_gcrs = (M @ normal_itrf[..., None])[..., 0]
            elev = np.arcsin(
                np.clip(np.sum(normal_gcrs * plan.src, axis=-1),
                        -1.0, 1.0)
            )
            # no troposphere for geocentric/space sites: elevation <= 0
            # makes TroposphereDelay's validity mask false
            out["obs_elevation_rad"] = np.where(
                on_ground, elev, -np.pi / 2
            )
    return out


def _apply_columns(toas: TOAs, parts: list, plan: IngestPlan):
    """Concatenate per-chunk column dicts back onto the TOAs table."""
    def cat(key):
        if len(parts) == 1:
            return parts[0][key]
        return np.concatenate([p[key] for p in parts])

    tdbs = [p["t_tdb"] for p in parts]
    if len(tdbs) == 1:
        toas.t_tdb = tdbs[0]
    else:
        toas.t_tdb = TimeArray(
            np.concatenate([x.mjd_int for x in tdbs]),
            HostDD(
                np.concatenate([x.sec.hi for x in tdbs]),
                np.concatenate([x.sec.lo for x in tdbs]),
            ),
            "tdb",
        )
    toas.clock_corr_s = cat("clock_corr_s")
    toas.ssb_obs_pos = cat("ssb_obs_pos")
    toas.ssb_obs_vel = cat("ssb_obs_vel")
    toas.obs_sun_pos = cat("obs_sun_pos")
    toas.obs_planet_pos = {}
    for name in parts[0]["planet_pos"]:
        if len(parts) == 1:
            toas.obs_planet_pos[name] = parts[0]["planet_pos"][name]
        else:
            toas.obs_planet_pos[name] = np.concatenate(
                [p["planet_pos"][name] for p in parts]
            )
    toas.ephem = getattr(plan.eph, "name", str(plan.ephem_name))
    toas.obs_lat_rad = cat("obs_lat_rad")
    toas.obs_alt_m = cat("obs_alt_m")
    if plan.src is not None:
        toas.obs_elevation_rad = cat("obs_elevation_rad")


def _source_unit_vector(model):
    """Host-side source direction (ICRS unit vector) from the model's
    astrometry component, or None."""
    if model is None:
        return None
    comp = None
    for name in ("AstrometryEquatorial", "AstrometryEcliptic"):
        comp = model.components.get(name) or comp
    if comp is None:
        return None
    def _f(p):
        v = p.internal()
        return float(v.to_float()) if hasattr(v, "to_float") else float(v)

    if "RAJ" in comp.params and comp.params["RAJ"].value is not None:
        ra = _f(comp.params["RAJ"])
        dec = _f(comp.params["DECJ"])
    elif (
        "ELONG" in comp.params and comp.params["ELONG"].value is not None
    ):
        lam = _f(comp.params["ELONG"])
        bet = _f(comp.params["ELAT"])
        eps = np.deg2rad(84381.406 / 3600.0)
        x = np.cos(bet) * np.cos(lam)
        y = np.cos(eps) * np.cos(bet) * np.sin(lam) - np.sin(eps) * np.sin(bet)
        z = np.sin(eps) * np.cos(bet) * np.sin(lam) + np.cos(eps) * np.sin(bet)
        return np.array([x, y, z])
    else:
        return None
    return np.array([
        np.cos(dec) * np.cos(ra), np.cos(dec) * np.sin(ra), np.sin(dec)
    ])
