"""Topocentric TOA ingest: clock chain -> TDB -> solar-system geometry.

Reference parity: the §3.1 load-time stack (SURVEY.md) —
TOAs.apply_clock_corrections (observatory/__init__.py clock chains),
TOAs.compute_TDBs (astropy/ERFA time scales), TOAs.compute_posvels
(solar_system_ephemerides + erfautils.gcrs_posvel_from_itrf) — all
host-side numpy/HostDD; the products become TOABundle device columns.

Chain per TOA:
  1. site clock (+ GPS->UTC)            [observatory registry + .clk files]
  2. UTC -> TAI -> TT(TAI) [+ TT(BIPM)] [timebase.TimeArray + leap seconds]
  3. TT -> TDB (geocentric series) + topocentric (v_earth . r_obs)/c^2
  4. observatory ITRF -> GCRS posvel    [earth.rotation, EOP table]
  5. Earth/Sun/planet SSB posvels       [ephemeris: SPK or builtin]
  6. source elevation (troposphere), when the model's astrometry is known
"""

from __future__ import annotations

import numpy as np

from pint_tpu.constants import C
from pint_tpu.earth.eop import get_eop
from pint_tpu.earth.rotation import (
    OMEGA_EARTH,
    itrf_to_gcrs_matrix,
    itrf_to_geodetic,
)
from pint_tpu.ephemeris import get_ephemeris, mjd_tdb_to_et
from pint_tpu.exceptions import PintTpuError
from pint_tpu.observatory import bipm_correction, get_observatory
from pint_tpu.timebase.times import TimeArray
from pint_tpu.toas.toas import TOAs

# NAIF ids for the PLANET_SHAPIRO bodies
_PLANETS = {
    "jupiter": 5, "saturn": 6, "venus": 2, "uranus": 7, "neptune": 8,
}


def ingest_topocentric(
    toas: TOAs,
    ephem: str = "builtin",
    planets: bool = False,
    include_bipm: bool = True,
    bipm_version: str = "BIPM2021",
    include_gps: bool = True,
    limits: str = "warn",
    model=None,
) -> TOAs:
    n = len(toas)
    sites = [get_observatory(o) for o in toas.obs]
    if any(s.is_barycenter for s in sites):
        if all(s.is_barycenter for s in sites):
            from pint_tpu.toas.ingest import ingest_barycentric

            return ingest_barycentric(toas)
        raise PintTpuError(
            "mixed barycentric + topocentric TOAs in one set are not "
            "supported; split the tim file"
        )
    if toas.t.scale != "utc":
        raise PintTpuError(
            f"topocentric ingest expects UTC arrival times, got "
            f"{toas.t.scale!r}"
        )

    # -- 1. clock chain ---------------------------------------------------
    mjd_utc = toas.t.mjd_float()
    clock = np.zeros(n)
    itrf = np.zeros((n, 3))
    sat_groups = []  # (bool index, SatelliteObs)
    for code in sorted(set(toas.obs)):
        idx = np.array([o == code for o in toas.obs])
        site = sites[int(np.flatnonzero(idx)[0])]
        if site.is_satellite:
            # spacecraft clocks are corrected upstream in the event
            # products; position comes from the orbit table below
            sat_groups.append((idx, site))
            continue
        clock[idx] = site.clock_corrections(
            mjd_utc[idx], include_gps=include_gps, limits=limits
        )
        loc = site.earth_location_itrf()
        itrf[idx] = 0.0 if loc is None else loc
    toas.clock_corr_s = clock
    t_utc = toas.t.add_seconds(clock)

    # -- 2. UTC -> TT -----------------------------------------------------
    t_tt = t_utc.to_scale("tt")
    if include_bipm:
        bipm = bipm_correction(mjd_utc, bipm_version)
        # spacecraft times are corrected upstream in the event products:
        # no BIPM realization either (reference: satellite observatories
        # default include_bipm=False)
        for idx, _sat in sat_groups:
            bipm[idx] = 0.0
        t_tt = t_tt.add_seconds(bipm)

    # -- 4. Earth rotation (needed for the TDB topocentric term) ----------
    dut1, xp, yp = get_eop(mjd_utc)
    mjd_ut1 = t_utc.mjd_float() + dut1 / 86400.0
    tt_cent = (
        (t_tt.mjd_int - 51544.5) + t_tt.sec.to_float() / 86400.0
    ) / 36525.0
    # one rotation-matrix build serves position, velocity, and the
    # troposphere's local-vertical below (the nutation series dominates
    # the per-TOA geometry cost)
    M = itrf_to_gcrs_matrix(mjd_ut1, tt_cent, xp, yp)
    obs_pos = (M @ itrf[..., None])[..., 0]
    omega = np.array([0.0, 0.0, OMEGA_EARTH])
    obs_vel = (
        M @ np.cross(np.broadcast_to(omega, itrf.shape), itrf)[..., None]
    )[..., 0]
    # spacecraft rows: orbit-table interpolation (already GCRS)
    if sat_groups:
        mjd_tt_f = t_tt.mjd_float()
        for idx, sat in sat_groups:
            obs_pos[idx], obs_vel[idx] = sat.posvel_gcrs(mjd_tt_f[idx])

    # -- 3. TT -> TDB (geocentric series + topocentric term) --------------
    t_tdb = t_tt.to_scale("tdb")
    eph = get_ephemeris(ephem)
    et = mjd_tdb_to_et(t_tdb.mjd_int, t_tdb.sec.to_float())
    epos_km, evel_km = eph.ssb_posvel(399, et)
    topo_s = np.sum(evel_km * 1000.0 * obs_pos, axis=-1) / (C * C)
    t_tdb = t_tdb.add_seconds(topo_s)
    toas.t_tdb = t_tdb

    # -- 5. geometry columns (meters, m/s) --------------------------------
    # re-evaluate at the corrected TDB (the ~us shift moves Earth by ~cm)
    et = mjd_tdb_to_et(t_tdb.mjd_int, t_tdb.sec.to_float())
    epos_km, evel_km = eph.ssb_posvel(399, et)
    toas.ssb_obs_pos = epos_km * 1000.0 + obs_pos
    toas.ssb_obs_vel = evel_km * 1000.0 + obs_vel
    spos_km, _ = eph.ssb_posvel(10, et)
    toas.obs_sun_pos = spos_km * 1000.0 - toas.ssb_obs_pos
    toas.obs_planet_pos = {}
    if planets:
        for name, naif in _PLANETS.items():
            ppos_km, _ = eph.ssb_posvel(naif, et)
            toas.obs_planet_pos[name] = (
                ppos_km * 1000.0 - toas.ssb_obs_pos
            )
    toas.ephem = getattr(eph, "name", str(ephem))

    # -- 6. troposphere geometry ------------------------------------------
    on_ground = np.linalg.norm(itrf, axis=-1) > 1e6  # geocenter: no air
    lat, lon, height = itrf_to_geodetic(
        np.where(on_ground[:, None], itrf, [6378137.0, 0.0, 0.0])
    )
    lat = np.where(on_ground, lat, 0.0)
    height = np.where(on_ground, height, 0.0)
    toas.obs_lat_rad = lat
    toas.obs_alt_m = height
    src = _source_unit_vector(model)
    if src is not None:
        # geodetic normal in ITRF, rotated to GCRS with the same matrix
        # chain used for the position
        normal_itrf = np.stack(
            [np.cos(lat) * np.cos(lon), np.cos(lat) * np.sin(lon),
             np.sin(lat)], axis=-1
        )
        normal_gcrs = (M @ normal_itrf[..., None])[..., 0]
        elev = np.arcsin(
            np.clip(np.sum(normal_gcrs * src, axis=-1), -1.0, 1.0)
        )
        # no troposphere for geocentric/space sites: elevation <= 0
        # makes TroposphereDelay's validity mask false
        toas.obs_elevation_rad = np.where(on_ground, elev, -np.pi / 2)
    return toas


def _source_unit_vector(model):
    """Host-side source direction (ICRS unit vector) from the model's
    astrometry component, or None."""
    if model is None:
        return None
    comp = None
    for name in ("AstrometryEquatorial", "AstrometryEcliptic"):
        comp = model.components.get(name) or comp
    if comp is None:
        return None
    def _f(p):
        v = p.internal()
        return float(v.to_float()) if hasattr(v, "to_float") else float(v)

    if "RAJ" in comp.params and comp.params["RAJ"].value is not None:
        ra = _f(comp.params["RAJ"])
        dec = _f(comp.params["DECJ"])
    elif (
        "ELONG" in comp.params and comp.params["ELONG"].value is not None
    ):
        lam = _f(comp.params["ELONG"])
        bet = _f(comp.params["ELAT"])
        eps = np.deg2rad(84381.406 / 3600.0)
        x = np.cos(bet) * np.cos(lam)
        y = np.cos(eps) * np.cos(bet) * np.sin(lam) - np.sin(eps) * np.sin(bet)
        z = np.sin(eps) * np.cos(bet) * np.sin(lam) + np.cos(eps) * np.sin(bet)
        return np.array([x, y, z])
    else:
        return None
    return np.array([
        np.cos(dec) * np.cos(ra), np.cos(dec) * np.sin(ra), np.sin(dec)
    ])
