"""TOABundle: the device-resident array view of a TOAs table.

This is the boundary between host ingest (numpy/HostDD, IEEE f64) and
device kernels (jnp).  Everything a compiled timing-model kernel needs is
here as jnp arrays; nothing else crosses into jit.

Precision layout (see docs/precision.md and SURVEY.md §7 step 1):
- absolute TDB epochs: exact integer day (f64) + DD seconds-of-day —
  kernels form dt against model epochs in DD, which is exact on IEEE
  backends and still ~1e-10 s on f32-pair-emulated TPU f64 (the
  delta-from-reference parameterization keeps device magnitudes small);
- geometry in light-seconds (positions) and v/c (velocities): delay
  contributions are then plain f64 dot products.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp
import numpy as np

from pint_tpu.constants import C
from pint_tpu.ops.dd import DD


class TOABundle(NamedTuple):
    tdb_day: jnp.ndarray  # (n,) f64 exact integer MJD(TDB)
    tdb_sec: DD  # (n,) seconds of TDB day
    freq_mhz: jnp.ndarray  # (n,) observing frequency, inf allowed
    error_us: jnp.ndarray  # (n,) raw TOA uncertainties
    ssb_obs_pos_ls: jnp.ndarray  # (n,3) SSB->obs, light-seconds
    ssb_obs_vel_c: jnp.ndarray  # (n,3) obs velocity / c
    obs_sun_pos_ls: jnp.ndarray  # (n,3) obs->Sun, light-seconds
    obs_planet_pos_ls: dict  # body -> (n,3) obs->planet, light-seconds
    pulse_number: jnp.ndarray  # (n,) f64; NaN where untracked
    padd: jnp.ndarray  # (n,) f64 phase adds from -padd flags / PHASE cmds
    masks: dict  # mask-param name -> (n,) f64 0/1
    # wideband DM measurements (pc/cm^3); None for narrowband data
    dm_meas: Optional[jnp.ndarray] = None
    dm_err: Optional[jnp.ndarray] = None

    @property
    def ntoa(self):
        return self.tdb_day.shape[-1]

    def dt_seconds(self, epoch_day, epoch_sec) -> DD:
        """(t_tdb - epoch) in DD seconds.

        epoch_day: exact day number — static int/float, or a traced f64
        scalar (PTA batching); epoch_sec: static float or DD scalar
        seconds-of-day.  The day-difference product is exact in f64
        (|ddays*86400| < 2^53 for any realistic span).
        """
        ddays = self.tdb_day - (
            float(epoch_day)
            if isinstance(epoch_day, (int, float)) else epoch_day
        )
        big = DD.from_prod(ddays, 86400.0)
        return big + (self.tdb_sec - epoch_sec)


def make_bundle(
    toas,
    masks: Optional[dict] = None,
    as_numpy: bool = False,
) -> TOABundle:
    """Host -> device: build the bundle from an ingested TOAs table.

    Requires toas.t_tdb (from pint_tpu.toas.ingest); position columns
    default to zeros (barycentric data, site '@').

    as_numpy=True keeps every column a HOST numpy array: the serving
    batcher (serve/batcher.py) pads and stacks many request bundles on
    a leading batch axis before anything crosses to the device, and
    per-leaf jnp placement here would cost one axon transfer per leaf
    per request instead of one bulk transfer per dispatched batch.
    """
    xp = np if as_numpy else jnp
    n = len(toas)
    if toas.t_tdb is None:
        raise ValueError(
            "TOAs not ingested: run pint_tpu.toas.ingest first "
            "(or use ingest_barycentric for site '@' data)"
        )
    zeros3 = np.zeros((n, 3))
    pos = (
        toas.ssb_obs_pos if toas.ssb_obs_pos is not None else zeros3
    )
    vel = (
        toas.ssb_obs_vel if toas.ssb_obs_vel is not None else zeros3
    )
    sun = (
        toas.obs_sun_pos if toas.obs_sun_pos is not None else zeros3
    )
    pn = toas.get_pulse_numbers()
    if pn is None:
        pn = np.full(n, np.nan)
    padd = np.array(
        [float(f.get("padd", 0.0)) for f in toas.flags], dtype=np.float64
    )
    wb = toas.is_wideband()
    dm_meas, dm_err = toas.get_dm_measurements() if wb else (None, None)
    return TOABundle(
        tdb_day=xp.asarray(toas.t_tdb.mjd_int, dtype=xp.float64),
        tdb_sec=DD(
            xp.asarray(toas.t_tdb.sec.hi), xp.asarray(toas.t_tdb.sec.lo)
        ),
        freq_mhz=xp.asarray(toas.freq),
        error_us=xp.asarray(toas.error_us),
        ssb_obs_pos_ls=xp.asarray(pos / C),
        ssb_obs_vel_c=xp.asarray(vel / C),
        obs_sun_pos_ls=xp.asarray(sun / C),
        obs_planet_pos_ls={
            k: xp.asarray(v / C) for k, v in toas.obs_planet_pos.items()
        },
        pulse_number=xp.asarray(pn),
        padd=xp.asarray(padd),
        dm_meas=None if dm_meas is None else xp.asarray(dm_meas),
        dm_err=None if dm_err is None else xp.asarray(dm_err),
        masks={k: xp.asarray(v, dtype=xp.float64) for k, v in (masks or {}).items()},
    )
