"""TOA ingest pipeline: clock chain -> TDB -> solar-system geometry.

Reference parity: the load-time stack of §3.1 (SURVEY.md) —
TOAs.apply_clock_corrections, compute_TDBs, compute_posvels.  All host-
side (numpy/HostDD); outputs are the computed columns consumed by
``make_bundle``.

Currently implemented:
- barycentric ingest (site '@' / 'bat'): arrival times are already TDB
  at the SSB (tempo2 BAT convention); geometry columns are zero.
- observatory ingest: clock chain (site clock files + GPS->UTC + BIPM),
  UTC->TDB, and observatory positions — lands with the observatory
  registry + ephemeris layers.
"""

from __future__ import annotations

import numpy as np

from pint_tpu.exceptions import PintTpuError
from pint_tpu.timebase.times import TimeArray
from pint_tpu.toas.toas import TOAs

BARY_SITES = {"@", "bat", "barycenter", "ssb"}


def ingest_barycentric(toas: TOAs) -> TOAs:
    """Site-'@' ingest: times are TDB at the barycenter; zero geometry.

    Spanned separately from :func:`ingest` because simulation
    scaffolding (make_test_pulsar) calls it directly."""
    from pint_tpu.obs.trace import TRACER

    with TRACER.span(
        "ingest:barycentric", "ingest", ntoa=len(toas)
    ):
        bad = [o for o in toas.obs if o.lower() not in BARY_SITES]
        if bad:
            raise PintTpuError(
                "ingest_barycentric: non-barycentric sites "
                f"{sorted(set(bad))}"
            )
        toas.t_tdb = TimeArray(toas.t.mjd_int, toas.t.sec, "tdb")
        n = len(toas)
        toas.clock_corr_s = np.zeros(n)
        toas.ssb_obs_pos = np.zeros((n, 3))
        toas.ssb_obs_vel = np.zeros((n, 3))
        toas.obs_sun_pos = np.zeros((n, 3))
        return toas


def ingest(toas: TOAs, ephem: str = "builtin", planets: bool = False,
           include_bipm: bool = True, bipm_version: str = "BIPM2021",
           limits: str = "warn", model=None) -> TOAs:
    """Full observatory ingest (clock chain -> TDB -> posvels).

    Runs under an ``ingest``-category flight-recorder span
    (pint_tpu/obs): host ingest is a fixed per-dataset cost that a
    trace should show next to the compile/dispatch spans it feeds."""
    from pint_tpu.obs import metrics as obs_metrics
    from pint_tpu.obs.trace import TRACER

    obs_metrics.counter("ingest.count", help="ingest calls").inc()
    obs_metrics.counter(
        "ingest.toas", unit="TOAs", help="TOAs ingested"
    ).inc(len(toas))
    with TRACER.span(
        "ingest", "ingest", ntoa=len(toas), ephem=ephem,
        planets=bool(planets),
    ):
        if all(o.lower() in BARY_SITES for o in toas.obs):
            return ingest_barycentric(toas)
        from pint_tpu.toas.ingest_topo import ingest_topocentric

        return ingest_topocentric(
            toas, ephem=ephem, planets=planets,
            include_bipm=include_bipm, bipm_version=bipm_version,
            limits=limits, model=model,
        )


def ingest_for_model(toas: TOAs, model, **kw) -> TOAs:
    """Ingest with the model's own EPHEM / PLANET_SHAPIRO options — the
    single helper every caller (builder, simulation, TZR, photonphase,
    polycos) uses so data TOAs and derived TOAs always go through
    identical chains."""
    kw.setdefault(
        "ephem", model.top_params["EPHEM"].value or "builtin"
    )
    ps = model.params.get("PLANET_SHAPIRO")
    kw.setdefault(
        "planets", bool(ps.value) if ps is not None else False
    )
    # CLOCK card (reference: toa.py::get_TOAs include_bipm/bipm_version
    # from model.CLOCK): "TT(BIPM2021)" -> that BIPM realization;
    # "TT(TAI)" / "UTC(NIST)"-style -> plain TT(TAI).
    clk = model.top_params.get("CLOCK")
    clk_val = (clk.value or "").upper().replace(" ", "") if clk else ""
    if (clk_val.startswith("TT(BIPM") and clk_val.endswith(")")
            and clk_val[7:-1].isdigit()):
        kw.setdefault("include_bipm", True)
        kw.setdefault("bipm_version", clk_val[3:-1])
    elif clk_val in ("TT(TAI)", "UTC(NIST)", "UTC"):
        kw.setdefault("include_bipm", False)
    elif clk_val:
        # 'TT(BIPM)' with no year, 'UTC(obs)' realizations, typos: do
        # not silently ignore the par file's timescale intent
        # (ADVICE r3) — say what default is taking over.
        import warnings

        warnings.warn(
            f"unrecognized CLOCK {clk.value!r} in par file; assuming "
            "the default TT(BIPM2021) realization"
        )
    return ingest(toas, model=model, **kw)
