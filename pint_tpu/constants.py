"""Physical and astronomical constants (SI), IAU 2015 / CODATA values.

The reference keeps units in astropy Quantities everywhere
(SURVEY.md §2a "Utils"); our core is unit-free SI — seconds, meters,
radians, Hz — with conversions only at the API boundary
(``pint_tpu.utils.units``).
"""

import math

# -- time -----------------------------------------------------------------
SECS_PER_DAY = 86400.0
DAYS_PER_JULIAN_YEAR = 365.25
SECS_PER_JULIAN_YEAR = SECS_PER_DAY * DAYS_PER_JULIAN_YEAR
MJD_J2000 = 51544.5  # J2000.0 epoch as MJD (TT)
JD_MINUS_MJD = 2400000.5
# TT = TAI + 32.184 s (exact, by definition)
TT_MINUS_TAI = 32.184
# TDB ~ TT at epoch 1977 Jan 1.0003725 TAI (defining relation)
# L_B and TDB0 from IAU 2006 Resolution B3 (TCB<->TDB)
L_B = 1.550519768e-8
TDB0 = -6.55e-5  # seconds
L_C = 1.48082686741e-8  # <dTCG/dTCB> - 1
L_G = 6.969290134e-10  # TCG vs TT rate (IAU 2000 Res B1.9, exact)

# -- lengths / light ------------------------------------------------------
C = 299792458.0  # m/s, exact
AU = 149597870700.0  # m, IAU 2012 exact
AU_LIGHT_SEC = AU / C  # ~499.004783836 s
PC = 3.0856775814913673e16  # m (parsec, derived from AU / arcsec)
KPC = 1e3 * PC

# -- angles ---------------------------------------------------------------
ARCSEC_TO_RAD = math.pi / (180.0 * 3600.0)
MAS_TO_RAD = ARCSEC_TO_RAD * 1e-3
DEG_TO_RAD = math.pi / 180.0
HOUR_TO_RAD = math.pi / 12.0

# -- gravity (GM values, m^3/s^2; DE440 / IAU best estimates) -------------
GM_SUN = 1.32712440041279419e20
GM_MERCURY = 2.2031868551e13
GM_VENUS = 3.24858592000e14
GM_EARTH = 3.98600435507e14
GM_MOON = 4.902800118e12
GM_MARS = 4.2828375816e13  # Mars system
GM_JUPITER = 1.26712764100000e17  # Jupiter system
GM_SATURN = 3.79405852000000e16  # Saturn system
GM_URANUS = 5.794556400000e15  # Uranus system
GM_NEPTUNE = 6.836527100580e15  # Neptune system

# Shapiro-delay coefficient 2*GM/c^3 for the Sun, seconds
T_SUN = 2.0 * GM_SUN / C**3  # ~9.8509e-6 s
# Solar mass in seconds (GM/c^3), the unit used by binary models
TSUN = GM_SUN / C**3  # ~4.92549e-6 s

# -- dispersion -----------------------------------------------------------
# DM constant: delay = DM * DM_CONST / freq_MHz^2 seconds, DM in pc/cm^3.
# The reference fixes 1/(2.41e-4) MHz^2 pc^-1 cm^3 s (Tempo convention)
# rather than the physical e^2/(2 pi m_e c); we follow for parity.
DM_CONST = 1.0 / 2.41e-4  # s MHz^2 / (pc cm^-3)

# -- solar wind -----------------------------------------------------------
# Conversion used by solar-wind dispersion: electron column in AU * cm^-3
# expressed in pc cm^-3.
AU_PC = AU / PC

# -- Earth ----------------------------------------------------------------
EARTH_EQUATORIAL_RADIUS = 6378136.6  # m (IERS 2010)
EARTH_FLATTENING = 1.0 / 298.25642
OBL_J2000 = 84381.406 * ARCSEC_TO_RAD  # IAU 2006 obliquity at J2000
