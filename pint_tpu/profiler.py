"""Profiling hooks (reference parity: SURVEY.md §5 — the reference has
manual cProfile scripts; the TPU equivalent is jax.profiler traces plus
lightweight per-phase wall timers).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a jax.profiler trace viewable in TensorBoard/Perfetto:

        with device_trace("/tmp/trace"):
            fitter.fit_toas()
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class _Phase:
    """Handle yielded by PhaseTimer: register the block's result with
    .fence(value) so EVERY device leaf is block_until_ready'd before the
    clock stops (jax dispatch is async — without a fence the timer
    records dispatch latency, not compute)."""

    def __init__(self):
        self._fences = []

    def fence(self, value):
        self._fences.append(value)
        return value

    def _wait(self):
        for v in self._fences:
            for leaf in jax.tree_util.tree_leaves(v):
                if hasattr(leaf, "block_until_ready"):
                    leaf.block_until_ready()


class PhaseTimer:
    """Named wall-clock phases with device fencing:

        timer = PhaseTimer()
        with timer("fit") as ph:
            result = ph.fence(step(x))   # all leaves synced at exit
        print(timer.report())
    """

    def __init__(self):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)

    @contextlib.contextmanager
    def __call__(self, name: str):
        ph = _Phase()
        t0 = time.perf_counter()
        try:
            yield ph
        finally:
            ph._wait()
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> str:
        lines = [f"{'phase':<24}{'calls':>7}{'total s':>12}{'mean ms':>12}"]
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            tot = self.totals[name]
            n = self.counts[name]
            lines.append(
                f"{name:<24}{n:>7}{tot:>12.3f}{tot / n * 1e3:>12.2f}"
            )
        return "\n".join(lines)
