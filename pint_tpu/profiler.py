"""Profiling hooks (reference parity: SURVEY.md §5 — the reference has
manual cProfile scripts; the TPU equivalent is jax.profiler traces plus
lightweight per-phase wall timers).

PR 2 (observability): ``PhaseTimer`` is now a veneer over the dispatch
flight recorder's span core (pint_tpu/obs/trace.py) — each phase opens
a ``phase``-category span on the global tracer (when the recorder is
enabled), so ad-hoc profiling blocks land in the same Perfetto export
as the framework's own compile/dispatch/fence spans, and the fence
uses the SHARED :func:`pint_tpu.obs.trace.fence_pytree`, which
block_until_ready's every array leaf of an arbitrary pytree (the old
``_Phase._wait`` missed leaves inside containers jax couldn't flatten
by hand — ISSUE 2 satellite fix).  The local totals/report() surface
is unchanged (tests/test_property_checkpoint.py uses it).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

from pint_tpu.obs.trace import TRACER, fence_pytree


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a jax.profiler trace viewable in TensorBoard/Perfetto:

        with device_trace("/tmp/trace"):
            fitter.fit_toas()

    Complements pint_tpu.obs.export's host-side span trace: this is
    the XLA-internal view (per-op device timelines), which often
    cannot run through the axon tunnel — the obs spans always can.
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class _Phase:
    """Handle yielded by PhaseTimer: register the block's result with
    .fence(value) so EVERY device leaf is block_until_ready'd before
    the clock stops (jax dispatch is async — without a fence the timer
    records dispatch latency, not compute).  Arbitrary pytrees fence
    correctly (shared obs.trace.fence_pytree)."""

    def __init__(self):
        self._fences = []

    def fence(self, value):
        self._fences.append(value)
        return value

    def _wait(self):
        fence_pytree(self._fences)


class PhaseTimer:
    """Named wall-clock phases with device fencing:

        timer = PhaseTimer()
        with timer("fit") as ph:
            result = ph.fence(step(x))   # all leaves synced at exit
        print(timer.report())

    Built on the flight-recorder span core: when the recorder is on
    (obs.trace.enable() / $PINT_TPU_TRACE=1) each phase is also a
    ``phase`` span in the global trace.
    """

    def __init__(self):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)

    @contextlib.contextmanager
    def __call__(self, name: str):
        ph = _Phase()
        t0 = time.perf_counter()
        with TRACER.span(name, "phase"):
            try:
                yield ph
            finally:
                ph._wait()
                self.totals[name] += time.perf_counter() - t0
                self.counts[name] += 1

    def report(self) -> str:
        lines = [f"{'phase':<24}{'calls':>7}{'total s':>12}{'mean ms':>12}"]
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            tot = self.totals[name]
            n = self.counts[name]
            lines.append(
                f"{name:<24}{n:>7}{tot:>12.3f}{tot / n * 1e3:>12.2f}"
            )
        return "\n".join(lines)
