"""Profiling hooks (reference parity: SURVEY.md §5 — the reference has
manual cProfile scripts; the TPU equivalent is jax.profiler traces plus
lightweight per-phase wall timers).
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax


@contextlib.contextmanager
def device_trace(logdir: str):
    """Capture a jax.profiler trace viewable in TensorBoard/Perfetto:

        with device_trace("/tmp/trace"):
            fitter.fit_toas()
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class PhaseTimer:
    """Named wall-clock phases with block_until_ready fencing:

        timer = PhaseTimer()
        with timer("ingest"): ...
        with timer("fit"): ...
        print(timer.report())
    """

    def __init__(self):
        self.totals = defaultdict(float)
        self.counts = defaultdict(int)

    @contextlib.contextmanager
    def __call__(self, name: str, fence=None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if fence is not None:
                jax.tree_util.tree_leaves(fence)[0].block_until_ready()
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def report(self) -> str:
        lines = [f"{'phase':<24}{'calls':>7}{'total s':>12}{'mean ms':>12}"]
        for name in sorted(self.totals, key=self.totals.get, reverse=True):
            tot = self.totals[name]
            n = self.counts[name]
            lines.append(
                f"{name:<24}{n:>7}{tot:>12.3f}{tot / n * 1e3:>12.2f}"
            )
        return "\n".join(lines)
