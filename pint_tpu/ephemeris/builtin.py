"""Built-in analytic solar-system ephemeris (no kernel file needed).

The Earth family (earth/moon/emb) comes from the truncated VSOP87
theory (ephemeris/vsop87.py, ~0.2 arcsec for the geocenter) plus a
truncated lunar theory (Meeus ch.47 main terms, ~30 km for the Moon ->
~0.4 km for the EMB offset), both rotated from the ecliptic of date to
equatorial J2000 via the IAU1976 precession chain.  The planets use
Keplerian mean elements + rates (Standish & Williams, "Approximate
Positions of the Major Planets", valid 1800-2050, ~10-20 arcsec), and
the Sun-SSB barycenter offset is the mass-ratio-weighted sum over the
Kepler planets.

ACCURACY (documented, by design): the geocenter is arcsecond-class
(~150-700 km; dominated by VSOP87 truncation + the Kepler-grade Sun
wobble), the planets ~10-20 arcsec.  That is ample for SIMULATION,
internal round-trip consistency (fits of simulated data use the same
ephemeris and agree to sub-ns), Shapiro-delay geometry (angle errors
only), and for driving the TDB-TT defining integral to ~0.1 us
(ephemeris/time_ephemeris.py) — but NOT for absolute timing parity
with DExxx-based packages; supply a real .bsp kernel
(pint_tpu.ephemeris.spk) for that; the reference has the same split
via jplephem + astropy's 'builtin' ephemeris.
"""

from __future__ import annotations

import numpy as np

AU_KM = 149597870.7
S_PER_DAY = 86400.0
_EMRAT = 81.30056907419062  # Earth/Moon mass ratio (DE430 value)
_OBL = np.deg2rad(84381.448 / 3600.0)  # J2000 mean obliquity

# (a AU, e, I deg, L deg, varpi deg, Omega deg) + per-century rates
_ELEMENTS = {
    "mercury": ((0.38709927, 0.20563593, 7.00497902, 252.25032350,
                 77.45779628, 48.33076593),
                (0.00000037, 0.00001906, -0.00594749, 149472.67411175,
                 0.16047689, -0.12534081)),
    "venus": ((0.72333566, 0.00677672, 3.39467605, 181.97909950,
               131.60246718, 76.67984255),
              (0.00000390, -0.00004107, -0.00078890, 58517.81538729,
               0.00268329, -0.27769418)),
    "emb": ((1.00000261, 0.01671123, -0.00001531, 100.46457166,
             102.93768193, 0.0),
            (0.00000562, -0.00004392, -0.01294668, 35999.37244981,
             0.32327364, 0.0)),
    "mars": ((1.52371034, 0.09339410, 1.84969142, -4.55343205,
              -23.94362959, 49.55953891),
             (0.00001847, 0.00007882, -0.00813131, 19140.30268499,
              0.44441088, -0.29257343)),
    "jupiter": ((5.20288700, 0.04838624, 1.30439695, 34.39644051,
                 14.72847983, 100.47390909),
                (-0.00011607, -0.00013253, -0.00183714, 3034.74612775,
                 0.21252668, 0.20469106)),
    "saturn": ((9.53667594, 0.05386179, 2.48599187, 49.95424423,
                92.59887831, 113.66242448),
               (-0.00125060, -0.00050991, 0.00193609, 1222.49362201,
                -0.41897216, -0.28867794)),
    "uranus": ((19.18916464, 0.04725744, 0.77263783, 313.23810451,
                170.95427630, 74.01692503),
               (-0.00196176, -0.00004397, -0.00242939, 428.48202785,
                0.40805281, 0.04240589)),
    "neptune": ((30.06992276, 0.00859048, 1.77004347, -55.12002969,
                 44.96476227, 131.78422574),
                (0.00026291, 0.00005105, 0.00035372, 218.45945325,
                 -0.32241464, -0.00508664)),
}

# planet/Sun mass ratios (IAU/DE430); used for the SSB offset
_MASS_RATIO = {
    "mercury": 1.0 / 6023600.0,
    "venus": 1.0 / 408523.71,
    "emb": 1.0 / 328900.56,
    "mars": 1.0 / 3098708.0,
    "jupiter": 1.0 / 1047.3486,
    "saturn": 1.0 / 3497.898,
    "uranus": 1.0 / 22902.98,
    "neptune": 1.0 / 19412.24,
}


def _kepler_xyz(name, t_cent):
    """Heliocentric ecliptic-J2000 position (AU), vectorized."""
    el0, rate = _ELEMENTS[name]
    T = np.asarray(t_cent, dtype=np.float64)
    a = el0[0] + rate[0] * T
    e = el0[1] + rate[1] * T
    inc = np.deg2rad(el0[2] + rate[2] * T)
    L = np.deg2rad(el0[3] + rate[3] * T)
    varpi = np.deg2rad(el0[4] + rate[4] * T)
    Om = np.deg2rad(el0[5] + rate[5] * T)
    om = varpi - Om
    M = np.mod(L - varpi + np.pi, 2 * np.pi) - np.pi
    E = M + e * np.sin(M)
    for _ in range(8):
        E = E - (E - e * np.sin(E) - M) / (1.0 - e * np.cos(E))
    xp = a * (np.cos(E) - e)
    yp = a * np.sqrt(1.0 - e * e) * np.sin(E)
    co, so = np.cos(om), np.sin(om)
    cO, sO = np.cos(Om), np.sin(Om)
    ci, si = np.cos(inc), np.sin(inc)
    x = (co * cO - so * sO * ci) * xp + (-so * cO - co * sO * ci) * yp
    y = (co * sO + so * cO * ci) * xp + (-so * sO + co * cO * ci) * yp
    z = (so * si) * xp + (co * si) * yp
    return np.stack([x, y, z], axis=-1)


def _moon_geocentric_km(t_cent):
    """Geocentric Moon, ecliptic + mean equinox OF DATE (km); truncated
    ELP (Meeus ch.47 main terms, ~0.01 deg / ~30 km — the EMB offset
    error this induces is ~0.4 km).  Callers must rotate to J2000 via
    vsop87._ecl_of_date_to_eq_j2000 (see _pos_eq_au)."""
    T = np.asarray(t_cent, dtype=np.float64)
    d2r = np.deg2rad
    Lp = d2r(218.3164477 + 481267.88123421 * T)
    D = d2r(297.8501921 + 445267.1114034 * T)
    M = d2r(357.5291092 + 35999.0502909 * T)
    Mp = d2r(134.9633964 + 477198.8675055 * T)
    F = d2r(93.2720950 + 483202.0175233 * T)
    lon = Lp + d2r(
        6.288774 * np.sin(Mp) + 1.274027 * np.sin(2 * D - Mp)
        + 0.658314 * np.sin(2 * D) + 0.213618 * np.sin(2 * Mp)
        - 0.185116 * np.sin(M) - 0.114332 * np.sin(2 * F)
    )
    lat = d2r(
        5.128122 * np.sin(F) + 0.280602 * np.sin(Mp + F)
        + 0.277693 * np.sin(Mp - F)
    )
    r = (
        385000.56 - 20905.355 * np.cos(Mp)
        - 3699.111 * np.cos(2 * D - Mp) - 2955.968 * np.cos(2 * D)
    )
    cl, sl = np.cos(lon), np.sin(lon)
    cb, sb = np.cos(lat), np.sin(lat)
    return np.stack([r * cb * cl, r * cb * sl, r * sb], axis=-1)


def _ecl_to_eq(xyz):
    """Ecliptic J2000 -> equatorial J2000 (ICRS to ~0.02")."""
    c, s = np.cos(_OBL), np.sin(_OBL)
    x, y, z = np.moveaxis(np.asarray(xyz), -1, 0)
    return np.stack([x, c * y - s * z, s * y + c * z], axis=-1)


class BuiltinEphemeris:
    """Analytic ephemeris with the SPK-style ssb_posvel interface
    (km, km/s; NAIF ids and lowercase names accepted)."""

    name = "builtin"
    _IDS = {
        10: "sun", 399: "earth", 3: "emb", 301: "moon",
        1: "mercury", 199: "mercury", 2: "venus", 299: "venus",
        4: "mars", 499: "mars", 5: "jupiter", 599: "jupiter",
        6: "saturn", 699: "saturn", 7: "uranus", 799: "uranus",
        8: "neptune", 899: "neptune",
    }

    def _sun_ssb_au(self, t_cent):
        """Sun wrt SSB (AU, ecliptic): -sum(m_i r_i)/(1 + sum m_i).

        Memoized on the epoch array: every body evaluation routes
        through the Sun wobble, so the TDB-integrand's 9-body potential
        loop (time_ephemeris.tdb_rate) would otherwise redo the 8
        Kepler solves per body on the same grid.  A small KEYED dict
        (not a single slot, r6): the chunked parallel ingest evaluates
        several epoch grids concurrently, and a last-value slot
        thrashes across chunks — each worker's grid evicting the
        others' — costing the cross-body reuse serial ingest enjoys.
        Plain dict ops are atomic under the GIL; a lost duplicate
        insert is a benign recompute, never a wrong value."""
        t_cent = np.asarray(t_cent, dtype=np.float64)
        key = (t_cent.shape, t_cent.tobytes())
        memo = getattr(self, "_sun_memo_map", None)
        if memo is None:
            memo = self._sun_memo_map = {}
        cached = memo.get(key)
        if cached is not None:
            return cached
        num = 0.0
        msum = 0.0
        for nm, mr in _MASS_RATIO.items():
            num = num + mr * _kepler_xyz(nm, t_cent)
            msum += mr
        out = -num / (1.0 + msum)
        if len(memo) >= 32:  # one entry per live chunk grid, bounded
            memo.clear()
        memo[key] = out
        return out

    def _pos_au_ecl(self, body, t_cent):
        if body == "sun":
            return self._sun_ssb_au(t_cent)
        return self._sun_ssb_au(t_cent) + _kepler_xyz(body, t_cent)

    def _pos_eq_au(self, body, t_cent):
        """SSB-centric equatorial J2000 position (AU)."""
        if body in ("earth", "moon", "emb"):
            from pint_tpu.ephemeris import vsop87

            sun = _ecl_to_eq(self._sun_ssb_au(t_cent))
            earth = sun + vsop87.earth_heliocentric_j2000(
                np.asarray(t_cent, dtype=np.float64) / 10.0
            )
            if body == "earth":
                return earth
            # Meeus lunar theory is ecliptic+equinox OF DATE
            moon_geo = vsop87._ecl_of_date_to_eq_j2000(
                _moon_geocentric_km(t_cent) / AU_KM, t_cent
            )
            if body == "moon":
                return earth + moon_geo
            return earth + moon_geo / (1.0 + _EMRAT)  # emb
        return _ecl_to_eq(self._pos_au_ecl(body, t_cent))

    def ssb_pos(self, body, et):
        """Position-only ssb_posvel (km): skips the central-difference
        velocity (3x fewer theory evaluations — the TDB integrand's
        potential loop only needs positions)."""
        if isinstance(body, (int, np.integer)):
            body = self._IDS[int(body)]
        et = np.asarray(et, dtype=np.float64)
        return self._pos_eq_au(
            body.lower(), et / (36525.0 * S_PER_DAY)
        ) * AU_KM

    def ssb_posvel(self, body, et):
        """SSB-centric equatorial-J2000 position (km) and velocity
        (km/s) at ET seconds past J2000 (TDB); velocity by central
        difference (60 s), consistent with the position model."""
        if isinstance(body, (int, np.integer)):
            body = self._IDS[int(body)]
        body = body.lower()
        et = np.asarray(et, dtype=np.float64)
        t_cent = et / (36525.0 * S_PER_DAY)
        pos = self._pos_eq_au(body, t_cent) * AU_KM
        h = 60.0
        tp = (et + h) / (36525.0 * S_PER_DAY)
        tm = (et - h) / (36525.0 * S_PER_DAY)
        vel = (
            self._pos_eq_au(body, tp) - self._pos_eq_au(body, tm)
        ) * AU_KM / (2.0 * h)
        return pos, vel
